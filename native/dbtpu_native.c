/* dbtpu_native — C runtime primitives for the dragonboat_tpu host path.
 *
 * The reference's runtime is compiled Go; the TPU build keeps JAX/XLA for
 * the compute path and implements the host runtime's hot loops natively
 * where Python-level looping is the bottleneck:
 *
 *  - tan log replay (logdb/tan.py _replay_file): one pass over a whole
 *    log file validating [magic | len | crc32(payload)] frames — the
 *    startup-recovery hot loop over potentially GBs of WAL
 *    (reference: internal/tan/db.go replay + record.go checksums);
 *  - TCP frame validation (transport/tcp.py): header+payload CRC checks
 *    (reference: internal/transport/tcp.go requestHeader).
 *
 * Plain C + ctypes (no CPython API): the Python side passes raw buffers;
 * crc32 comes from zlib, matching Python's zlib.crc32 bit-for-bit.
 *
 * Build: cc -O2 -shared -fPIC dbtpu_native.c -lz -o dbtpu_native.so
 * (driven by dragonboat_tpu/native.py on first import, cached).
 */

#include <stddef.h>
#include <stdint.h>
#include <string.h>
#include <zlib.h>

/* one framed record: [u32 magic][u32 len][u32 crc][payload len bytes] */
typedef struct {
    uint64_t offset;        /* of the frame start */
    uint64_t payload_off;   /* of the payload within buf */
    uint32_t payload_len;
} dbtpu_rec;

/* Scan an entire log image, validating every frame.
 *
 * Returns the number of valid records written to out (capped at max_out).
 * *scan_end receives the offset one past the last valid frame.
 * *status: 0 = clean EOF, 1 = torn/corrupt frame at *scan_end,
 *          2 = out table full (call again with a larger table).     */
int dbtpu_tan_scan(const uint8_t *buf, uint64_t len, uint32_t magic,
                   dbtpu_rec *out, uint64_t max_out,
                   uint64_t *n_out, uint64_t *scan_end, int *status)
{
    uint64_t off = 0, n = 0;
    while (off + 12 <= len) {
        uint32_t m, plen, crc;
        memcpy(&m, buf + off, 4);
        memcpy(&plen, buf + off + 4, 4);
        memcpy(&crc, buf + off + 8, 4);
        if (m != magic || off + 12 + (uint64_t)plen > len) {
            *n_out = n; *scan_end = off; *status = 1;
            return 0;
        }
        uint32_t actual = (uint32_t)crc32(0L, buf + off + 12, plen);
        if (actual != crc) {
            *n_out = n; *scan_end = off; *status = 1;
            return 0;
        }
        if (n >= max_out) {
            *n_out = n; *scan_end = off; *status = 2;
            return 0;
        }
        out[n].offset = off;
        out[n].payload_off = off + 12;
        out[n].payload_len = plen;
        n++;
        off += 12 + plen;
    }
    *n_out = n;
    *scan_end = off;
    *status = (off == len) ? 0 : 1;  /* trailing partial header = torn */
    return 0;
}

/* Validate one framed TCP request: header CRC over the payload.
 * Returns 1 valid / 0 invalid. */
int dbtpu_frame_check(const uint8_t *payload, uint64_t len, uint32_t crc)
{
    return (uint32_t)crc32(0L, payload, len) == crc;
}

/* crc32 passthrough (zlib polynomial), for parity tests */
uint32_t dbtpu_crc32(const uint8_t *buf, uint64_t len, uint32_t seed)
{
    return (uint32_t)crc32(seed, buf, len);
}
