"""Device-router tests: full raft clusters with zero host routing."""

import numpy as np

from dragonboat_tpu.core import params as KP
from dragonboat_tpu.core.kstate import empty_input, empty_inbox, init_state
from dragonboat_tpu.core.router import cluster_step


def make(n_groups, replicas=3, **kw):
    kp = KP.KernelParams(
        num_peers=replicas, log_cap=256, inbox_cap=5 * (replicas - 1),
        msg_entries=4, proposal_cap=4, readindex_cap=4,
    )
    G = n_groups * replicas
    rids = np.tile(np.arange(1, replicas + 1, dtype=np.int32), n_groups)
    pids = np.arange(1, replicas + 1, dtype=np.int32)
    st = init_state(kp, G, rids, pids, **kw)
    return kp, st


def test_device_routed_election_and_commit():
    kp, st = make(4)
    box = empty_inbox(kp, st.term.shape[0])
    inp_t = empty_input(kp, st.term.shape[0])._replace(
        tick=np.ones(st.term.shape[0], bool))
    out = None
    for i in range(60):
        st, box, out = cluster_step(kp, 3, st, box, inp_t)
        role = np.asarray(st.role).reshape(4, 3)
        if (role == KP.LEADER).any(axis=1).all():
            break
    role = np.asarray(st.role).reshape(4, 3)
    assert (role == KP.LEADER).any(axis=1).all(), "not all groups elected"
    # settle: let noops commit
    inp0 = empty_input(kp, st.term.shape[0])
    for _ in range(6):
        st, box, out = cluster_step(kp, 3, st, box, inp0)
    committed = np.asarray(st.committed)
    assert (committed == 1).all()

    # propose on every leader row via input lanes
    lead_rows = np.flatnonzero(np.asarray(st.role) == KP.LEADER)
    pv = np.zeros((st.term.shape[0], kp.proposal_cap), bool)
    pv[lead_rows, :2] = True
    inp_p = inp0._replace(prop_valid=pv)
    st, box, out = cluster_step(kp, 3, st, box, inp_p)
    assert np.asarray(out.prop_accepted)[lead_rows][:, :2].all()
    for _ in range(6):
        st, box, out = cluster_step(kp, 3, st, box, inp0)
    assert (np.asarray(st.committed) == 3).all()
    # identical term rings within groups
    lt = np.asarray(st.lt).reshape(4, 3, -1)
    assert (lt == lt[:, :1]).all()


def test_device_routed_steady_state_throughput_commits():
    """Pipeline proposals every step; commits must advance steadily."""
    kp, st = make(2)
    G = st.term.shape[0]
    box = empty_inbox(kp, G)
    tick = empty_input(kp, G)._replace(tick=np.ones(G, bool))
    idle = empty_input(kp, G)
    for _ in range(40):
        st, box, _ = cluster_step(kp, 3, st, box, tick)
        if (np.asarray(st.role).reshape(2, 3) == KP.LEADER).any(axis=1).all():
            break
    for _ in range(6):
        st, box, _ = cluster_step(kp, 3, st, box, idle)
    lead = np.flatnonzero(np.asarray(st.role) == KP.LEADER)
    c0 = int(np.asarray(st.committed)[lead].sum())
    steps = 30
    for i in range(steps):
        pv = np.zeros((G, kp.proposal_cap), bool)
        pv[lead, :] = True  # 4 proposals per leader per step
        st, box, _ = cluster_step(kp, 3, st, box, idle._replace(prop_valid=pv))
    # drain
    for _ in range(6):
        st, box, _ = cluster_step(kp, 3, st, box, idle)
    c1 = int(np.asarray(st.committed)[lead].sum())
    total = c1 - c0
    assert total == 2 * steps * kp.proposal_cap, (
        f"committed {total}, want {2 * steps * kp.proposal_cap}"
    )
