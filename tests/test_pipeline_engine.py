"""Depth-1 pipelined engine behind the NodeHost client API (PR 6).

test_kernel_engine.py exercises the serial depth-0 loop (the
differential oracle); these scenarios re-drive the same client surface
with ``ExpertConfig.kernel_pipeline_depth=1`` so the overlapped path —
alternate-buffer staging, donated dispatch, one-step-late output
retirement — serves real elections, writes, reads, snapshots, eviction
and restart.  The bitwise depth-0-vs-depth-1 check lives in
test_pipeline_differential.py; here the assertions are behavioral
(linearizable results, no hung futures, pending ctx drained at idle).
"""

import time

from dragonboat_tpu.config import Config, ExpertConfig, NodeHostConfig
from dragonboat_tpu.nodehost import NodeHost

from test_kernel_engine import close_all, make_cluster, propose_retry
from test_nodehost import KVStateMachine, wait_leader


def pipelined_expert(**kw):
    kw.setdefault("kernel_log_cap", 256)
    kw.setdefault("kernel_capacity", 8)
    kw.setdefault("kernel_apply_batch", 16)
    kw.setdefault("kernel_compaction_overhead", 16)
    return ExpertConfig(kernel_pipeline_depth=1, **kw)


def make_pipelined(prefix, **kw):
    return make_cluster(prefix, expert=pipelined_expert(), **kw)


def test_pipeline_depth_plumbed_and_metrics():
    hosts = make_pipelined("pp0")
    try:
        lead = wait_leader(hosts, timeout=30)
        nh = hosts[lead]
        eng = nh.kernel_engine
        assert eng.pipeline_depth == 1
        propose_retry(nh, nh.get_noop_session(1), b"m=1")
        snap = nh.events.metrics.snapshot()
        assert snap["engine.pipeline.depth"] == 1
        assert snap["engine.pipeline.steps"] > 0
        # overlap actually happened at least once under real traffic
        assert snap["engine.pipeline.overlapped"] > 0
        assert 0 <= snap["engine.pipeline.occupancy_pct"] <= 100
    finally:
        close_all(hosts)


def test_pipeline_propose_and_read():
    hosts = make_pipelined("ppr")
    try:
        lead = wait_leader(hosts, timeout=30)
        nh = hosts[lead]
        sess = nh.get_noop_session(1)
        for i in range(10):
            propose_retry(nh, sess, f"k{i}=v{i}".encode())
        assert nh.sync_read(1, "k7", timeout_s=10) == "v7"
        deadline = time.time() + 10
        while time.time() < deadline:
            if all(h.stale_read(1, "k9") == "v9" for h in hosts.values()):
                break
            time.sleep(0.05)
        assert all(h.stale_read(1, "k9") == "v9" for h in hosts.values())
    finally:
        close_all(hosts)


def test_pipeline_pending_ctx_drains_at_idle():
    """The in-flight step retires once traffic stops: a worker loop that
    sees no new work must still consume the pending ctx (otherwise the
    last commit's futures hang one step behind forever)."""
    hosts = make_pipelined("ppd")
    try:
        lead = wait_leader(hosts, timeout=30)
        nh = hosts[lead]
        propose_retry(nh, nh.get_noop_session(1), b"drain=ok")
        assert nh.sync_read(1, "drain", timeout_s=10) == "ok"
        deadline = time.time() + 10
        while time.time() < deadline:
            engines = [h.kernel_engine for h in hosts.values()
                       if h.kernel_engine is not None]
            if all(e._pending_ctx is None for e in engines):
                break
            time.sleep(0.05)
        assert all(e._pending_ctx is None for e in engines)
    finally:
        close_all(hosts)


def test_pipeline_snapshot_and_compaction():
    hosts = make_cluster("pps", snapshot_entries=12,
                         expert=pipelined_expert())
    try:
        lead = wait_leader(hosts, timeout=30)
        nh = hosts[lead]
        sess = nh.get_noop_session(1)
        for i in range(30):
            propose_retry(nh, sess, f"s{i}=v{i}".encode())
        deadline = time.time() + 10
        node = nh.nodes[1]
        while time.time() < deadline and node.compacted_to == 0:
            time.sleep(0.05)
        assert node.compacted_to > 0
        assert nh.sync_read(1, "s29", timeout_s=10) == "v29"
    finally:
        close_all(hosts)


def test_pipeline_eviction_with_step_in_flight():
    """Evicting a lane while a donated step is in flight: the deferred
    retire must not resurrect the removed node (identity checks) and the
    shard keeps serving from the host engine."""
    hosts = make_pipelined("ppe")
    try:
        lead = wait_leader(hosts, timeout=30)
        nh = hosts[lead]
        propose_retry(nh, nh.get_noop_session(1), b"pre=evict")
        knode = nh.kernel_engine.by_shard[1]
        with nh.kernel_engine.mu:
            nh.kernel_engine._evict(knode, reason="test")
        node = nh.nodes[1]
        assert node is not knode
        assert node.peer is not None
        assert nh.stale_read(1, "pre") == "evict"
        deadline = time.time() + 30
        ok = False
        while time.time() < deadline and not ok:
            try:
                nh2 = hosts[wait_leader(hosts, timeout=10)]
                nh2.sync_propose(nh2.get_noop_session(1), b"post=evict",
                                 timeout_s=3)
                ok = nh2.sync_read(1, "post", timeout_s=3) == "evict"
            except Exception:
                time.sleep(0.2)
        assert ok
    finally:
        close_all(hosts)


def test_pipeline_restart_from_disk(tmp_path):
    """close() with a step potentially in flight, reopen at depth 1, data
    intact — exercises teardown and re-inject through the pipelined loop."""
    addrs = {1: "ppk-1"}

    def mk():
        nh = NodeHost(NodeHostConfig(
            raft_address="ppk-1", rtt_millisecond=5,
            node_host_dir=str(tmp_path),
            expert=ExpertConfig(kernel_log_cap=256, kernel_capacity=4,
                                kernel_pipeline_depth=1)))
        nh.start_replica(addrs, False, KVStateMachine, Config(
            shard_id=1, replica_id=1, election_rtt=10, heartbeat_rtt=2,
            device_resident=True))
        deadline = time.time() + 15
        while time.time() < deadline and not nh.get_leader_id(1)[1]:
            time.sleep(0.02)
        return nh

    nh = mk()
    sess = nh.get_noop_session(1)
    for i in range(15):
        propose_retry(nh, sess, f"d{i}=v{i}".encode())
    nh.close()

    nh = mk()
    try:
        deadline = time.time() + 10
        while time.time() < deadline:
            if nh.stale_read(1, "d14") == "v14":
                break
            time.sleep(0.05)
        for i in range(15):
            assert nh.stale_read(1, f"d{i}") == f"v{i}", i
        propose_retry(nh, nh.get_noop_session(1), b"dz=zz")
        assert nh.sync_read(1, "dz", timeout_s=10) == "zz"
    finally:
        nh.close()
