"""Fabric pallas kernels (round 17): the device-resident serving
path's two hot gather shapes — inbox lane staging and the quorum match
order statistic — as VMEM block kernels (parallel/fabric_pallas.py),
pinned bit-identical to their XLA lowerings in interpret mode, plus a
CPU-interpreted smoke of the scripts/tpu_pallas_ab.py ``fabric_ab``
rungs (the compiled numbers need real TPU hardware; the plumbing and
the bitwise flags do not)."""

import importlib.util
import os
import sys

import jax.numpy as jnp
import numpy as np
import pytest

from dragonboat_tpu.parallel.fabric_pallas import (
    gather_lanes_pallas,
    gather_lanes_xla,
    quorum_match_pallas,
    quorum_match_xla,
)


@pytest.mark.parametrize("G,K,M", [(8, 16, 16), (13, 32, 8), (1, 8, 8)])
def test_gather_lanes_bitwise(G, K, M):
    """Pallas lane gather == take_along_axis for in-range indexes,
    including row counts that force the pad path."""
    rng = np.random.default_rng(5)
    vals = jnp.asarray(rng.integers(-(1 << 20), 1 << 20, (G, K)),
                       jnp.int32)
    idx = jnp.asarray(rng.integers(0, K, (G, M)), jnp.int32)
    ref = gather_lanes_xla(vals, idx)
    got = gather_lanes_pallas(vals, idx, interpret=True)
    assert jnp.array_equal(ref, got)


def test_gather_lanes_sentinel_reads_zero():
    """idx == K (the router's no-lane sentinel) has no hot slot in the
    one-hot and must read 0, matching route()'s onehot_reads branch."""
    vals = jnp.asarray([[7, 8, 9, 10]], jnp.int32)
    idx = jnp.asarray([[4, 2, 4, 0]], jnp.int32)
    got = gather_lanes_pallas(vals, idx, interpret=True)
    assert got.tolist() == [[0, 9, 0, 7]]


@pytest.mark.parametrize("seed", [1, 9])
def test_quorum_match_bitwise(seed):
    """Compare-count rank select == the sort+gather reference across
    randomized matches, voting masks and quorums — duplicates, fewer
    voters than quorum, and zero-voter rows included."""
    rng = np.random.default_rng(seed)
    G, R = 64, 8
    # small value range to force duplicate matches (the tie path)
    match = jnp.asarray(rng.integers(0, 6, (G, R)), jnp.int32)
    voting = jnp.asarray(rng.random((G, R)) < 0.7)
    voting = voting.at[0].set(False)        # zero-voter row
    quorum = jnp.asarray(rng.integers(1, R + 1, G), jnp.int32)
    ref = quorum_match_xla(match, voting, quorum)
    got = quorum_match_pallas(match, voting, quorum, interpret=True)
    assert jnp.array_equal(ref, got), (
        np.argwhere(~np.asarray(ref == got)))


def _load_ab_script():
    path = os.path.join(os.path.dirname(__file__), os.pardir,
                        "scripts", "tpu_pallas_ab.py")
    spec = importlib.util.spec_from_file_location("_fabric_pallas_ab",
                                                  path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = mod
    spec.loader.exec_module(mod)
    return mod


def test_fabric_ab_rungs_smoke():
    """The kind=fabric_ab rungs run end-to-end on the forced-CPU
    multi-device mesh: the serve A/B produces both arm timings (hub
    arm slower or not — meaningless on CPU, present either way) and
    the gather A/B's bitwise flags hold."""
    mod = _load_ab_script()
    serve = mod.fabric_serve_ab(8, micro=3)
    assert "serve_error" not in serve, serve
    assert "resident_step_ms" in serve and "hub_step_ms" in serve, serve
    gather = mod.fabric_gather_ab(64, iters=2)
    assert gather.get("inbox_gather_bitwise") is True, gather
    assert gather.get("quorum_match_bitwise") is True, gather
