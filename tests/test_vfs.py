"""vfs: MemFS power-loss simulation, ErrorFS fault injection, and the
NodeHost's controlled-crash reaction to storage failures.

Reference behaviors: internal/vfs/vfs.go (IFS / strict MemFS / ErrorFS),
nodehost.go:361-367 (injected FS errors become controlled crashes),
tan durability under injected faults.
"""

import time

import pytest

from dragonboat_tpu import raftpb as pb
from dragonboat_tpu.config import Config, ExpertConfig, NodeHostConfig
from dragonboat_tpu.logdb.tan import TanLogDB
from dragonboat_tpu.nodehost import NodeHost
from dragonboat_tpu.vfs import ErrorFS, InjectedError, MemFS

from test_nodehost import KVStateMachine, wait_leader


def _update(i, term=1):
    return pb.Update(
        shard_id=1, replica_id=1,
        state=pb.State(term=term, vote=1, commit=i),
        entries_to_save=(pb.Entry(term=term, index=i, cmd=b"x" * 8),),
    )


# -- MemFS ----------------------------------------------------------------


def test_memfs_basics():
    fs = MemFS()
    fs.makedirs("/d")
    with fs.open("/d/a.txt", "w") as f:
        f.write("hello")
    assert fs.exists("/d/a.txt")
    assert fs.getsize("/d/a.txt") == 5
    with fs.open("/d/a.txt", "r") as f:
        assert f.read() == "hello"
    fs.replace("/d/a.txt", "/d/b.txt")
    assert not fs.exists("/d/a.txt")
    assert fs.listdir("/d") == ["b.txt"]
    with pytest.raises(FileNotFoundError):
        fs.open("/d/missing", "rb")


def test_memfs_crash_drops_unsynced():
    fs = MemFS()
    f = fs.open("/w.log", "ab")
    f.write(b"synced")
    fs.fsync(f)
    f.write(b"-unsynced")
    fs.crash()
    with fs.open("/w.log", "rb") as r:
        assert r.read() == b"synced"
    # a file never synced disappears entirely
    g = fs.open("/gone", "wb")
    g.write(b"data")
    fs.crash()
    assert not fs.exists("/gone")


def test_tan_on_memfs_crash_keeps_synced_records(tmp_path):
    """tan on MemFS: save_raft_state fsyncs, so a crash() immediately
    after loses nothing; unsynced appends are truncated as a torn tail."""
    fs = MemFS()
    db = TanLogDB(str(tmp_path), fs=fs)
    for i in range(1, 11):
        db.save_raft_state([_update(i)], worker_id=0)
    # append a record but crash before the fsync: write bytes directly
    db._append(1, 1, 1, b"\x01garbage-partial")
    fs.crash()

    db2 = TanLogDB(str(tmp_path), fs=fs)
    ents = db2.iterate_entries(1, 1, 1, 11, 0)
    assert [e.index for e in ents] == list(range(1, 11))
    rs = db2.read_raft_state(1, 1, 0)
    assert rs.state.commit == 10
    db2.close()


# -- ErrorFS --------------------------------------------------------------


def test_errorfs_injects_on_fsync(tmp_path):
    fs = ErrorFS.on_op(MemFS(), "fsync")
    db = TanLogDB(str(tmp_path), fs=fs)
    with pytest.raises(InjectedError):
        db.save_raft_state([_update(1)], worker_id=0)


def test_tan_survives_injected_write_failure(tmp_path):
    """Writes that fail injection never ack; everything acked (fsynced)
    before the fault is intact on reopen."""
    base = MemFS()
    fs = ErrorFS(base)
    db = TanLogDB(str(tmp_path), fs=fs)
    for i in range(1, 6):
        db.save_raft_state([_update(i)], worker_id=0)
    armed = {"on": False}
    fs.inject = lambda op, path, a=armed: a["on"] and op in ("write", "fsync")
    armed["on"] = True
    with pytest.raises(InjectedError):
        db.save_raft_state([_update(6)], worker_id=0)
    armed["on"] = False
    # power-loss on top of the fault: only fsynced state may survive
    base.crash()
    db2 = TanLogDB(str(tmp_path), fs=base)
    ents = db2.iterate_entries(1, 1, 1, 100, 0)
    assert [e.index for e in ents] == list(range(1, 6))
    db2.close()


# -- NodeHost integration -------------------------------------------------


def _mem_cfg(addr, fs, base):
    return NodeHostConfig(
        raft_address=addr, rtt_millisecond=5, node_host_dir=base,
        expert=ExpertConfig(fs=fs),
    )


def test_cluster_on_memfs_and_crash_recovery(tmp_path):
    """A 3-replica cluster entirely on MemFS: zero disk IO; a simulated
    power loss of every host preserves fsynced writes."""
    fs = MemFS()
    base = str(tmp_path)
    addrs = {i: f"mem-{i}" for i in (1, 2, 3)}
    hosts = {}
    for rid, addr in addrs.items():
        nh = NodeHost(_mem_cfg(addr, fs, base))
        assert nh.logdb.name().startswith("sharded-tan")
        nh.start_replica(addrs, False, KVStateMachine, Config(
            shard_id=1, replica_id=rid, election_rtt=10, heartbeat_rtt=1))
        hosts[rid] = nh
    lead = wait_leader(hosts)
    sess = hosts[lead].get_noop_session(1)
    for i in range(10):
        hosts[lead].sync_propose(sess, f"m{i}=v{i}".encode())
    for h in hosts.values():
        h.close()

    fs.crash()  # power loss across the fleet

    hosts2 = {}
    for rid, addr in addrs.items():
        nh = NodeHost(_mem_cfg(addr, fs, base))
        nh.start_replica({}, False, KVStateMachine, Config(
            shard_id=1, replica_id=rid, election_rtt=10, heartbeat_rtt=1))
        hosts2[rid] = nh
    try:
        lead = wait_leader(hosts2)
        deadline = time.time() + 10
        while time.time() < deadline and \
                hosts2[lead].stale_read(1, "m9") is None:
            time.sleep(0.05)
        for i in range(10):
            assert hosts2[lead].stale_read(1, f"m{i}") == f"v{i}", i
        hosts2[lead].sync_propose(
            hosts2[lead].get_noop_session(1), b"post=crash")
        assert hosts2[lead].sync_read(1, "post") == "crash"
    finally:
        for h in hosts2.values():
            h.close()


def test_storage_fault_halts_nodehost(tmp_path):
    """An injected log-write failure mid-flight is a controlled crash:
    the host records fatal_error and stops stepping instead of acking
    writes that never reached stable storage (nodehost.go:361-367)."""
    base = MemFS()
    fs = ErrorFS(base)
    nh = NodeHost(_mem_cfg("flt-1", fs, str(tmp_path)))
    nh.start_replica({1: "flt-1"}, False, KVStateMachine, Config(
        shard_id=1, replica_id=1, election_rtt=10, heartbeat_rtt=1))
    deadline = time.time() + 10
    while time.time() < deadline and not nh.get_leader_id(1)[1]:
        time.sleep(0.02)
    sess = nh.get_noop_session(1)
    nh.sync_propose(sess, b"ok=1")
    armed = {"on": False}
    fs.inject = lambda op, path, a=armed: (
        a["on"] and op in ("write", "fsync") and ".tan" in path)
    armed["on"] = True
    with pytest.raises(Exception):
        nh.sync_propose(sess, b"fails=1")
    deadline = time.time() + 10
    while time.time() < deadline and nh.fatal_error is None:
        time.sleep(0.02)
    assert isinstance(nh.fatal_error, InjectedError)
    # fail fast: later requests must not ride the full timeout
    t0 = time.time()
    with pytest.raises(Exception):
        nh.sync_propose(sess, b"again=1")
    assert time.time() - t0 < 1.0
    armed["on"] = False
    nh.close()

    # restart from the same (healthy again) FS: acked state is there
    nh2 = NodeHost(_mem_cfg("flt-1", base, str(tmp_path)))
    nh2.start_replica({}, False, KVStateMachine, Config(
        shard_id=1, replica_id=1, election_rtt=10, heartbeat_rtt=1))
    try:
        deadline = time.time() + 10
        while time.time() < deadline and nh2.stale_read(1, "ok") is None:
            time.sleep(0.05)
        assert nh2.stale_read(1, "ok") == "1"
        assert nh2.fatal_error is None
    finally:
        nh2.close()
