"""Kernel chaos tests: safety invariants under drops/dups/partitions.

The model is the reference's monkey-test strategy (docs/test.md, monkey.go):
random message loss, duplication and partitions while proposing, then assert
the raft safety properties: at most one leader per term, identical committed
prefixes across replicas, commit monotonicity.  Determinism of the kernel
(same seeds → same run) is asserted too — bitwise reproducibility is a core
TPU-design requirement (SURVEY §7 'Determinism').
"""

import random

import numpy as np

from dragonboat_tpu.core import params as KP
from kernel_harness import KernelCluster


def run_chaos(seed: int, steps: int = 400, groups: int = 4):
    rng = random.Random(seed)
    c = KernelCluster(groups, 3)
    leaders_by_term: dict[tuple[int, int], int] = {}  # (group, term) -> leader rid
    proposed = 0
    commit_watermark = np.zeros(c.G, np.int64)

    for step_i in range(steps):
        # random chaos: drop pairs, toggle isolation
        c.dropped_pairs = set()
        for g in range(c.G):
            for h in range(c.G):
                if g != h and rng.random() < 0.08:
                    c.dropped_pairs.add((g, h))
        if rng.random() < 0.02:
            c.isolated = {rng.randrange(c.G)}
        elif rng.random() < 0.05:
            c.isolated = set()
        # random duplication: re-enqueue a pending message
        for g in range(c.G):
            if c.pending[g] and rng.random() < 0.1:
                c.pending[g].append(rng.choice(c.pending[g]))

        proposals = {}
        for grp in range(groups):
            lrow = c.leader_row(grp)
            if lrow is not None and rng.random() < 0.5:
                proposals[lrow] = rng.randrange(1, 3)
                proposed += 1
        c.step(tick=True, proposals=proposals)

        # safety: at most one leader per (group, term)
        role = c.field("role")
        term = c.field("term")
        for grp in range(groups):
            for r in range(grp * 3, grp * 3 + 3):
                if role[r] == KP.LEADER:
                    key = (grp, int(term[r]))
                    rid = r % 3 + 1
                    if key in leaders_by_term:
                        assert leaders_by_term[key] == rid, (
                            f"TWO LEADERS in group {grp} term {term[r]}"
                        )
                    leaders_by_term[key] = rid
        # safety: commit never regresses
        committed = c.field("committed").astype(np.int64)
        assert (committed >= commit_watermark).all(), "commit regressed"
        commit_watermark = np.maximum(commit_watermark, committed)

    # heal and drain
    c.isolated = set()
    c.dropped_pairs = set()
    for _ in range(60):
        c.step(tick=True)
    return c, proposed


def check_log_matching(c: KernelCluster, groups: int):
    lt = c.field("lt")
    committed = c.field("committed")
    last = c.field("last")
    cap = c.kp.log_cap
    for grp in range(groups):
        rows = [grp * 3 + i for i in range(3)]
        cmin = int(min(committed[r] for r in rows))
        # committed prefix must be identical across replicas
        for i in range(1, cmin + 1):
            slot = i & (cap - 1)
            terms = {int(lt[r][slot]) for r in rows if last[r] >= i}
            assert len(terms) == 1, (
                f"log divergence group {grp} index {i}: {terms}"
            )


def test_kernel_chaos_safety():
    c, proposed = run_chaos(seed=12345)
    check_log_matching(c, 4)
    # liveness after heal: every group has a leader and converged commits
    committed = c.field("committed")
    for grp in range(4):
        assert c.leader_row(grp) is not None
        rows = [grp * 3 + i for i in range(3)]
        assert len({int(committed[r]) for r in rows}) == 1, "commit not converged"
    assert proposed > 20


def test_kernel_chaos_second_seed():
    c, _ = run_chaos(seed=999, steps=300)
    check_log_matching(c, 4)


def test_kernel_determinism():
    """Same seeds → bitwise-identical state evolution (no hidden entropy)."""
    def run(n):
        c = KernelCluster(2, 3)
        for i in range(40):
            props = {0: 1} if i % 5 == 0 else None
            c.step(tick=True, proposals=props)
        return c

    a, b = run(0), run(1)
    for f in ("term", "role", "vote", "leader", "committed", "last", "lt",
              "match", "next", "e_tick", "rand_timeout"):
        fa, fb = np.asarray(getattr(a.state, f)), np.asarray(getattr(b.state, f))
        assert (fa == fb).all(), f"nondeterminism in field {f}"
