"""Async request variants + small public-surface parity
(nodehost.go:963-1359: Request*/ProposeSession/GetLogReader/
GetNodeUser/NAReadLocalNode/RemoveData/registry accessor)."""

import time

from dragonboat_tpu.client import Session
from dragonboat_tpu.config import Config, NodeHostConfig
from dragonboat_tpu.nodehost import NodeHost
from dragonboat_tpu.request import RequestError, RequestResultCode

from test_nodehost import KVStateMachine


def _host():
    addr = f"api-{time.monotonic_ns()}"
    nh = NodeHost(NodeHostConfig(raft_address=addr, rtt_millisecond=2))
    nh.start_replica({1: addr}, False, KVStateMachine, Config(
        shard_id=1, replica_id=1, election_rtt=10, heartbeat_rtt=1,
        snapshot_entries=0, compaction_overhead=2))
    deadline = time.time() + 10
    while time.time() < deadline and not nh.get_leader_id(1)[1]:
        time.sleep(0.02)
    return nh


def test_async_request_variants_complete():
    nh = _host()
    try:
        s = nh.get_noop_session(1)
        nh.sync_propose(s, b"a=1", timeout_s=5)
        # async membership change
        rs = nh.request_add_nonvoting(1, 7, "else:1", 0, timeout_s=5)
        rs.get(5)
        assert 7 in nh.get_shard_membership(1).non_votings
        rs = nh.request_delete_replica(1, 7, 0, timeout_s=5)
        rs.get(5)
        assert 7 not in nh.get_shard_membership(1).non_votings
        # async snapshot + compaction
        rs = nh.request_snapshot(1, timeout_s=5)
        r = rs.wait(5)
        assert r.code == RequestResultCode.COMPLETED
        assert r.snapshot_index >= 3
        rs = nh.request_compaction(1, timeout_s=5)
        r = rs.wait(5)
        assert r.code == RequestResultCode.COMPLETED
    finally:
        nh.close()


def test_propose_session_async_lifecycle():
    nh = _host()
    try:
        s = Session.new_session(1)
        s.prepare_for_register()
        nh.propose_session(s, timeout_s=5).get(5)
        s.prepare_for_propose()
        r = nh.sync_propose(s, b"k=v", timeout_s=5)  # advances the series
        assert r.value == 1
        s.prepare_for_unregister()
        nh.propose_session(s, timeout_s=5).get(5)
    finally:
        nh.close()


def test_node_user_and_small_surface():
    nh = _host()
    try:
        nu = nh.get_node_user(1)
        s = nh.get_noop_session(1)
        nu.propose(s, b"x=y", timeout_s=5).get(5)
        nu.read_index(timeout_s=5).get(5)
        assert nh.na_read_local_node(1, "x") == "y"
        lr = nh.get_log_reader(1)
        assert lr.last_index() >= 1
        assert nh.raft_address.startswith("api-")
        reg, via_gossip = nh.get_node_host_registry()
        assert reg is not None and via_gossip is False
    finally:
        nh.close()


def test_remove_data_requires_stopped_shard(tmp_path):
    nh = _host()
    try:
        s = nh.get_noop_session(1)
        nh.sync_propose(s, b"a=1", timeout_s=5)
        try:
            nh.remove_data(1, 1)
            raise AssertionError("remove_data on a RUNNING shard passed")
        except RequestError:
            pass
        nh.stop_replica(1)
        nh.remove_data(1, 1)
        assert not nh.has_node_info(1, 1)
    finally:
        nh.close()
