"""Mesh at non-toy geometry (VERDICT r3 item 8): 256 groups spread over
all 8 virtual devices, witness/host/mesh shards coexisting, and
eviction + snapshot + membership change running CONCURRENTLY on mesh
residents.  Lives in its own zz module: the [1024]-row mesh step keeps
the single CI core busy, so it must sort after the real-time suites.
"""

import threading
import time

from dragonboat_tpu.config import (
    Config,
    ExpertConfig,
    MeshSpec,
    NodeHostConfig,
)
from dragonboat_tpu.nodehost import NodeHost

from test_kernel_engine import propose_retry
from test_nodehost import KVStateMachine, wait_leader

N_MESH = 256
REPLICAS = 4          # g_size 2 x replicas 4 = all 8 virtual devices


def test_mesh_256_groups_8_devices_mixed_residency_concurrent_ops():
    prefix = f"m256-{time.monotonic_ns()}"
    spec = MeshSpec(name=prefix, g_size=2, replicas=REPLICAS, n_local=128)
    addrs = {i: f"{prefix}-{i}" for i in range(1, REPLICAS + 1)}
    mesh_shards = tuple(range(1, N_MESH + 1))
    kernel_shards = (301, 302, 303)       # single-device kernel engine
    witness_shard = 310                   # witness member -> host engine
    hosts = {}
    try:
        for rid, addr in addrs.items():
            nh = NodeHost(NodeHostConfig(
                raft_address=addr, rtt_millisecond=10,
                expert=ExpertConfig(mesh=spec, kernel_log_cap=64,
                                    kernel_apply_batch=8,
                                    kernel_compaction_overhead=8,
                                    kernel_capacity=16)))
            hosts[rid] = nh
        for rid, nh in hosts.items():
            for sid in mesh_shards:
                # shard 70 will be CC-evicted to the HOST engines mid-test
                # and keeps its config there: it gets a timeout that works
                # in both regimes (see host_rtt note below) — on the mesh,
                # ticks coalesce to ~1/step so this only delays its own
                # first election by ~100 steps inside the 600 s window
                e_rtt, hb_rtt = (100, 10) if sid == 70 else (10, 2)
                nh.start_replica(addrs, False, KVStateMachine, Config(
                    shard_id=sid, replica_id=rid, election_rtt=e_rtt,
                    heartbeat_rtt=hb_rtt, mesh_resident=True))
        # Election timeouts for NON-mesh shards are sized to this box's
        # step granularity: with 256 mesh groups one worker iteration
        # takes ~1 s, but wall-clock ticks accrue every 10 ms — a 10-rtt
        # timeout delivers ~100 expired ticks per step, so every
        # replica campaigns EVERY step and elections never converge
        # (mesh lanes are immune: all replicas of a group advance in the
        # same device step, so their relative timers stay coherent).
        # 150 rtt ≈ 1.5 s spans a couple of iterations and the random
        # spread resolves the race.
        host_rtt = dict(election_rtt=150, heartbeat_rtt=15)
        # mixed residency: device-resident kernel shards on hosts 1-3
        k_addrs = {i: addrs[i] for i in (1, 2, 3)}
        for rid in (1, 2, 3):
            for sid in kernel_shards:
                hosts[rid].start_replica(k_addrs, False, KVStateMachine,
                                         Config(shard_id=sid,
                                                replica_id=rid,
                                                election_rtt=20,
                                                heartbeat_rtt=2,
                                                device_resident=True))
        # witness-bearing group: voters on hosts 1-2, witness on host 3
        w_addrs = {i: addrs[i] for i in (1, 2, 3)}
        for rid in (1, 2):
            hosts[rid].start_replica(w_addrs, False, KVStateMachine, Config(
                shard_id=witness_shard, replica_id=rid, **host_rtt))
        hosts[3].start_replica(w_addrs, False, KVStateMachine, Config(
            shard_id=witness_shard, replica_id=3, is_witness=True,
            **host_rtt))

        # -- every mesh group elects through the all_gather step --------
        deadline = time.time() + 600
        elected = 0
        while time.time() < deadline:
            elected = sum(
                1 for sid in mesh_shards
                if any(hosts[r].get_leader_id(sid)[1] for r in addrs))
            if elected == N_MESH:
                break
            time.sleep(0.5)
        assert elected == N_MESH, f"only {elected}/{N_MESH} mesh elected"
        for rid, nh in hosts.items():
            resident = sum(1 for sid in mesh_shards
                           if (sid, rid) in nh.mesh_engine.by_shard)
            assert resident == N_MESH
        # mesh step time at this geometry, for PERF.md (captured while
        # the mesh is at full residency)
        m = hosts[1].metrics()
        print(f"\nMESH_STEP_US ewma={m.get('engine.kernel_step.ewma_us', 0)}"
              f" max={m.get('engine.kernel_step.max_us', 0)}"
              f" at rows={spec.g_size * REPLICAS * spec.n_local}",
              flush=True)

        # -- concurrent: proposals + snapshot + CC-driven eviction ------
        errors = []

        def writer():
            try:
                for sid in (1, 17, 99, 200, 256):
                    lid = wait_leader(hosts, shard_id=sid, timeout=60)
                    nh = hosts[lid]
                    propose_retry(nh, nh.get_noop_session(sid),
                                  f"w{sid}=v".encode(), timeout_s=15,
                                  deadline_s=90)
            except Exception as e:            # noqa: BLE001
                errors.append(("writer", e))

        def snapshotter():
            try:
                sid = 40
                lid = wait_leader(hosts, shard_id=sid, timeout=60)
                nh = hosts[lid]
                propose_retry(nh, nh.get_noop_session(sid), b"s=1",
                              timeout_s=15, deadline_s=90)
                end = time.time() + 120
                while True:
                    try:
                        nh.sync_request_snapshot(sid, timeout_s=30)
                        break
                    except Exception:         # noqa: BLE001
                        if time.time() > end:
                            raise
                        time.sleep(0.5)
            except Exception as e:            # noqa: BLE001
                errors.append(("snapshotter", e))

        def config_changer():
            """Adding replica id 9 exceeds the mesh addressing (1..4):
            the whole group must EVICT to the host engines and keep
            serving — eviction and membership change in one motion."""
            try:
                sid = 70
                lid = wait_leader(hosts, shard_id=sid, timeout=60)
                nh = hosts[lid]
                propose_retry(nh, nh.get_noop_session(sid), b"pre=cc",
                              timeout_s=15, deadline_s=90)
                end = time.time() + 120
                while True:
                    try:
                        nh.sync_request_add_nonvoting(
                            sid, 9, f"{prefix}-x", 0, timeout_s=30)
                        break
                    except Exception:         # noqa: BLE001
                        if time.time() > end:
                            raise
                        time.sleep(0.5)
            except Exception as e:            # noqa: BLE001
                errors.append(("config_changer", e))

        threads = [threading.Thread(target=f)
                   for f in (writer, snapshotter, config_changer)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=420)
        assert not errors, errors
        assert not any(t.is_alive() for t in threads), "concurrent op hung"

        # the CC'd group left the mesh everywhere and still serves
        end = time.time() + 120
        while time.time() < end:
            off_mesh = all((70, rid) not in nh.mesh_engine.by_shard
                           for rid, nh in hosts.items())
            if off_mesh:
                break
            time.sleep(0.5)
        assert off_mesh, "shard 70 still mesh-resident after CC"
        # ONE worker thread services the [1024]-row mesh step AND every
        # host-path node on this box, so the evicted group's re-election
        # progresses one message round per ~1s engine iteration — give
        # it the time that implies
        try:
            lid = wait_leader(hosts, shard_id=70, timeout=360)
        except AssertionError:
            for rid, nh in hosts.items():
                n = nh.nodes.get(70)
                print(f"DIAG host {rid}: node={type(n).__name__ if n else None}"
                      f" leader={n.leader_id() if n else '-'}"
                      f" term={n.node_term() if n else '-'}"
                      f" inq={len(n.incoming_msgs) if n else '-'}",
                      flush=True)
            raise
        end = time.time() + 180
        while True:
            try:
                assert hosts[lid].sync_read(70, "pre", timeout_s=60) == "cc"
                break
            except AssertionError:
                raise
            except Exception:
                if time.time() > end:
                    raise
                time.sleep(1.0)

        # witness + kernel shards served throughout (wait on the hosts
        # that CARRY the shard — host 4 never reports a leader for it,
        # so a 4-host majority would demand all three carriers incl.
        # the metadata-lagged witness)
        lid = wait_leader({r: hosts[r] for r in (1, 2, 3)},
                          shard_id=witness_shard, timeout=240)
        propose_retry(hosts[lid], hosts[lid].get_noop_session(witness_shard),
                      b"wit=ok", timeout_s=15, deadline_s=90)
        lid = wait_leader({r: hosts[r] for r in (1, 2, 3)},
                          shard_id=301, timeout=240)
        propose_retry(hosts[lid], hosts[lid].get_noop_session(301),
                      b"k=ok", timeout_s=15, deadline_s=90)

    finally:
        for nh in hosts.values():
            nh.close()
