"""Proposal-lifecycle tracing (PR 7): tracer units, Chrome export,
/trace endpoint, and end-to-end spans on both engine pipeline depths.

The tracer is process-global (like flight.RECORDER), so every test
snapshots/restores its configuration and ring via the autouse fixture.
"""

import json
import time
import urllib.request

import pytest

from dragonboat_tpu import flight, lifecycle, telemetry
from dragonboat_tpu.config import Config, ExpertConfig, NodeHostConfig
from dragonboat_tpu.lifecycle import (
    LifecycleTracer,
    STAGES,
    validate_chrome_trace,
)
from dragonboat_tpu.nodehost import NodeHost
from dragonboat_tpu.request import LogicalClock, PendingProposal

from test_kernel_engine import close_all, propose_retry
from test_nodehost import KVStateMachine, wait_leader


@pytest.fixture(autouse=True)
def _isolate_global_tracer():
    """The module tracer is process-global; leave it as we found it and
    empty between tests (NodeHost construction reconfigures it)."""
    t = lifecycle.TRACER
    before = (t._every, t._slow_us)
    t.reset()
    yield
    t.configure(sample_every=before[0], slow_commit_us=before[1])
    t.reset()


def make_tracer(**kw):
    """Fully-isolated tracer: injected counting clock, private registry
    and recorder (the GLOBAL ones must not see test samples)."""
    kw.setdefault("sample_every", 1)
    kw.setdefault("clock", iter(range(0, 10_000_000, 10)).__next__)
    kw.setdefault("registry", telemetry.Registry())
    kw.setdefault("recorder", flight.FlightRecorder(capacity=16))
    return LifecycleTracer(**kw)


# -- tracer units -----------------------------------------------------------

def test_sampling_is_deterministic_one_in_n():
    t = make_tracer(sample_every=4)
    assert [k for k in range(1, 17) if t.sampled(k)] == [4, 8, 12, 16]
    # off switch: 0 disables everything
    t.configure(sample_every=0)
    assert not t.enabled
    assert not t.sampled(4)
    assert not t.begin(4)


def test_span_lifecycle_and_ring():
    t = make_tracer()
    assert t.begin(1, shard_id=7)
    assert not t.begin(1)          # duplicate key refused
    t.stamp(1, lifecycle.STAGE_STAGE)
    t.stamp(1, lifecycle.STAGE_DISPATCH)
    t.finish(1)
    t.finish(1)                    # double finish is a no-op
    traces = t.completed()
    assert len(traces) == 1
    tr = traces[0]
    assert tr["key"] == 1 and tr["shard_id"] == 7
    assert [s for s, _ in tr["stamps"]] == [
        "propose", "stage", "dispatch", "ack"]
    ts = [x for _, x in tr["stamps"]]
    assert ts == sorted(ts)
    assert tr["total_us"] == ts[-1] - ts[0]
    assert t.counts() == {"active": 0, "finished": 1,
                          "scrubbed": 0, "dropped": 0}


def test_ring_is_bounded():
    t = make_tracer(ring_size=2)
    for k in (1, 2, 3):
        t.begin(k)
        t.finish(k)
    keys = [tr["key"] for tr in t.completed()]
    assert keys == [2, 3]          # oldest evicted


def test_active_cap_refuses_not_grows():
    t = make_tracer(max_active=2)
    assert t.begin(1) and t.begin(2)
    assert not t.begin(3)          # at cap: counted, refused
    assert t.active_count() == 2
    assert t.counts()["dropped"] == 1
    t.finish(3)                    # never opened -> no trace
    assert len(t.completed()) == 0


def test_scrub_discards_without_sinking():
    t = make_tracer()
    t.begin(5)
    t.stamp(5, lifecycle.STAGE_STAGE)
    t.scrub(5)
    t.stamp(5, lifecycle.STAGE_DISPATCH)   # post-scrub stamp: no-op
    t.finish(5)                            # post-scrub finish: no-op
    assert t.completed() == []
    c = t.counts()
    assert c["scrubbed"] == 1 and c["finished"] == 0 and c["active"] == 0


def test_stage_histograms_fed_on_finish():
    reg = telemetry.Registry()
    t = make_tracer(registry=reg)
    t.begin(1)
    t.stamp(1, lifecycle.STAGE_STAGE)
    t.stamp(1, lifecycle.STAGE_DISPATCH)
    t.finish(1)
    fams = telemetry.parse_exposition(reg.exposition())
    samples = fams["commit_stage_us"]["samples"]
    by_label = {lb.get("stage"): v for nm, lb, v in samples
                if nm.endswith("_count")}
    # one observation per consecutive stamp pair, labeled by the LATER
    # stage, plus the propose->ack total
    assert by_label == {"stage": 1, "dispatch": 1, "ack": 1, "total": 1}
    sums = {lb.get("stage"): v for nm, lb, v in samples
            if nm.endswith("_sum")}
    assert sums["total"] == 30     # 3 clock ticks of 10us


def test_slow_commit_flight_event():
    rec = flight.FlightRecorder(capacity=8)
    t = make_tracer(slow_commit_us=25, recorder=rec)
    t.begin(1)                     # fast: 1 delta of 10us < 25
    t.finish(1)
    t.begin(2)
    t.stamp(2, lifecycle.STAGE_STAGE)
    t.stamp(2, lifecycle.STAGE_DISPATCH)
    t.finish(2)                    # 30us >= 25: slow
    recs = rec.tail()
    assert len(recs) == 1
    r = recs[0]
    assert r["kind"] == flight.SLOW_COMMIT
    assert r["key"] == 2 and r["total_us"] == 30 and r["slo_us"] == 25
    # full breakdown, offsets from the propose stamp, monotone
    assert [s for s, _ in r["stages"]] == [
        "propose", "stage", "dispatch", "ack"]
    offs = [o for _, o in r["stages"]]
    assert offs[0] == 0 and offs == sorted(offs)
    # the record must survive the recorder's canonical JSON dump
    json.loads(rec.dump_json())


def test_slow_commit_disabled_by_default():
    rec = flight.FlightRecorder(capacity=8)
    t = make_tracer(recorder=rec)
    t.begin(1)
    t.stamp(1, lifecycle.STAGE_DISPATCH)
    t.finish(1)
    assert rec.tail() == []


# -- Chrome-trace export + validator ---------------------------------------

def test_export_chrome_trace_round_trips_validator():
    t = make_tracer()
    for k in (1, 2):
        t.begin(k, shard_id=k)
        t.stamp(k, lifecycle.STAGE_STAGE)
        t.stamp(k, lifecycle.STAGE_DISPATCH)
        t.stamp(k, lifecycle.STAGE_RETIRE)
        t.finish(k)
    obj = json.loads(json.dumps(t.export_chrome_trace()))
    assert validate_chrome_trace(obj) == 10    # 2 spans x 5 events
    ev = obj["traceEvents"][0]
    assert ev["ph"] == "X" and ev["name"] == "propose"
    assert ev["pid"] == 1 and ev["tid"] == 1
    # dur chains: each event ends where the next begins
    span1 = [e for e in obj["traceEvents"] if e["tid"] == 1]
    for a, b in zip(span1, span1[1:]):
        assert a["ts"] + a["dur"] == b["ts"]
    # device-capture stitching names ride in args
    dispatch = next(e for e in span1 if e["name"] == "dispatch")
    assert dispatch["args"]["annotation"] == "kernel_engine.step"
    retire = next(e for e in span1 if e["name"] == "retire")
    assert retire["args"]["annotation"] == "kernel_engine.process_outputs"


def test_validator_rejections():
    ok = {"name": "propose", "ph": "X", "ts": 1, "dur": 1,
          "pid": 0, "tid": 1}
    # bare-array form accepted
    assert validate_chrome_trace([ok]) == 1
    with pytest.raises(ValueError, match="object or array"):
        validate_chrome_trace("nope")
    with pytest.raises(ValueError, match="traceEvents must be an array"):
        validate_chrome_trace({"traceEvents": 3})
    for missing in ("name", "ph", "ts", "pid", "tid"):
        bad = dict(ok)
        del bad[missing]
        with pytest.raises(ValueError, match=f"missing required key "
                                             f"'{missing}'"):
            validate_chrome_trace([bad])
    with pytest.raises(ValueError, match="non-negative"):
        validate_chrome_trace([dict(ok, ts=-1)])
    with pytest.raises(ValueError, match="non-negative"):
        validate_chrome_trace([dict(ok, dur=-2)])
    # backwards time WITHIN one (pid, tid) span
    with pytest.raises(ValueError, match="backwards"):
        validate_chrome_trace([dict(ok, ts=10), dict(ok, ts=5)])
    # different spans may interleave freely
    assert validate_chrome_trace(
        [dict(ok, ts=10), dict(ok, ts=5, tid=2)]) == 2


# -- request-book integration ----------------------------------------------

class _Session:
    client_id = 1
    series_id = 1
    responded_to = 0


def test_book_begins_finishes_and_scrubs_spans():
    t = lifecycle.TRACER
    t.configure(sample_every=1)
    book = PendingProposal(clock=LogicalClock(), shard_id=3)

    rs, entry = book.propose(_Session(), b"x", timeout_ticks=100)
    assert t.active_count() == 1
    from dragonboat_tpu.statemachine import Result

    book.applied(entry.key, 1, 1, Result(), rejected=False)
    assert rs.wait(1).completed()
    assert t.active_count() == 0
    tr = t.completed()[-1]
    assert tr["key"] == entry.key and tr["shard_id"] == 3

    # dropped -> scrub, not a trace
    _, e2 = book.propose(_Session(), b"y", timeout_ticks=100)
    book.dropped(e2.key)
    assert t.active_count() == 0
    assert all(x["key"] != e2.key for x in t.completed())

    # timeout GC -> scrub
    _, e3 = book.propose(_Session(), b"z", timeout_ticks=1)
    book.advance()
    book.advance()
    book.gc()
    assert t.active_count() == 0

    # terminate_all -> scrub
    book.propose(_Session(), b"w", timeout_ticks=100)
    book.terminate_all()
    assert t.active_count() == 0
    assert t.counts()["scrubbed"] == 3


# -- /trace endpoint --------------------------------------------------------

def test_trace_endpoint_serves_chrome_json():
    from dragonboat_tpu.server.metrics_http import MetricsServer

    t = make_tracer()
    t.begin(1)
    t.stamp(1, lifecycle.STAGE_DISPATCH)
    t.finish(1)
    srv = MetricsServer([telemetry.Registry()], tracer=t)
    try:
        with urllib.request.urlopen(
                f"http://{srv.address}/trace", timeout=5) as resp:
            assert resp.headers["Content-Type"] == "application/json"
            obj = json.loads(resp.read().decode("utf-8"))
    finally:
        srv.close()
    # the endpoint also merges compile spans from the process-wide
    # capacity tracker — any earlier live engine in this process may
    # have left some; the lifecycle spans must ride beside them
    compiles = [e for e in obj["traceEvents"] if e.get("cat") == "compile"]
    assert validate_chrome_trace(obj) == 3 + len(compiles)
    assert [e["name"] for e in obj["traceEvents"]
            if e.get("cat") != "compile"] == ["propose", "dispatch", "ack"]


# -- end-to-end: spans across the engines ----------------------------------

def _traced_expert(depth):
    return ExpertConfig(kernel_log_cap=256, kernel_capacity=8,
                        kernel_apply_batch=16,
                        kernel_compaction_overhead=16,
                        kernel_pipeline_depth=depth,
                        trace_sample_every=1)


def _make_traced_cluster(prefix, depth):
    addrs = {i: f"{prefix}-{i}" for i in range(1, 4)}
    hosts = {}
    for rid, addr in addrs.items():
        nh = NodeHost(NodeHostConfig(raft_address=addr, rtt_millisecond=5,
                                     expert=_traced_expert(depth)))
        cfg = Config(shard_id=1, replica_id=rid, election_rtt=10,
                     heartbeat_rtt=2, compaction_overhead=5,
                     device_resident=True)
        nh.start_replica(addrs, False, KVStateMachine, cfg)
        hosts[rid] = nh
    return hosts


def _wait_full_trace(min_stages, timeout=30):
    """Poll the global ring for a completed trace with >= min_stages
    DISTINCT stages; returns it."""
    deadline = time.time() + timeout
    best = None
    while time.time() < deadline:
        for tr in lifecycle.TRACER.completed():
            stages = {s for s, _ in tr["stamps"]}
            if best is None or len(stages) > len({s for s, _ in
                                                  best["stamps"]}):
                best = tr
            if len(stages) >= min_stages:
                return tr
        time.sleep(0.1)
    raise AssertionError(
        f"no trace with >= {min_stages} distinct stages; best: "
        f"{best and [s for s, _ in best['stamps']]}")


@pytest.mark.parametrize("depth", [0, 1], ids=["serial", "pipelined"])
def test_e2e_trace_spans_kernel_commit_path(depth):
    """Acceptance: a sampled proposal's completed trace crosses >= 6
    distinct stages with monotone timestamps, on both the serial and
    the pipelined (one-step-late retirement) engine loops."""
    hosts = _make_traced_cluster(f"lc{depth}", depth)
    try:
        assert lifecycle.TRACER.enabled    # NodeHost wired the config
        lead = wait_leader(hosts, timeout=30)
        nh = hosts[lead]
        sess = nh.get_noop_session(1)
        for i in range(8):
            propose_retry(nh, sess, f"t{i}=v{i}".encode())
        tr = _wait_full_trace(min_stages=6)
        names = [s for s, _ in tr["stamps"]]
        ts = [x for _, x in tr["stamps"]]
        assert names[0] == "propose" and names[-1] == "ack"
        assert len(set(names)) >= 6
        assert all(s in STAGES for s in names)
        # the kernel commit path in full
        for want in ("propose", "stage", "dispatch", "retire", "ack"):
            assert want in names, (want, names)
        assert ts == sorted(ts), "stage stamps must be monotone"
        # exported ring round-trips the strict validator
        obj = json.loads(json.dumps(
            lifecycle.TRACER.export_chrome_trace()))
        assert validate_chrome_trace(obj) > 0
        # acked sampled spans drain; nothing leaks in the span book
        deadline = time.time() + 10
        while time.time() < deadline and lifecycle.TRACER.active_count():
            time.sleep(0.1)
    finally:
        close_all(hosts)
    assert lifecycle.TRACER.active_count() == 0


def test_e2e_disabled_sampling_records_nothing():
    """trace_sample_every=0 turns every hook into a cheap no-op."""
    addrs = {1: "lcoff-1"}
    nh = NodeHost(NodeHostConfig(
        raft_address="lcoff-1", rtt_millisecond=5,
        expert=ExpertConfig(kernel_log_cap=256, kernel_capacity=8,
                            kernel_apply_batch=16,
                            kernel_compaction_overhead=16,
                            trace_sample_every=0)))
    try:
        cfg = Config(shard_id=1, replica_id=1, election_rtt=10,
                     heartbeat_rtt=2, compaction_overhead=5,
                     device_resident=True)
        nh.start_replica(addrs, False, KVStateMachine, cfg)
        assert not lifecycle.TRACER.enabled
        wait_leader({1: nh}, timeout=30)
        propose_retry(nh, nh.get_noop_session(1), b"off=1")
        assert lifecycle.TRACER.completed() == []
        assert lifecycle.TRACER.active_count() == 0
    finally:
        nh.close()
