"""Scale test: 1k device-resident shards on one kernel state.

Kept in its own module (sorting last) because the jitted [1024]-lane step
keeps the CPU busy; running it mid-suite starves the real-time E2E tests
that follow.
"""

import time

from dragonboat_tpu.config import Config, ExpertConfig, NodeHostConfig
from dragonboat_tpu.nodehost import NodeHost

from test_nodehost import KVStateMachine


def test_kernel_1k_shards_one_process():

    """1024 single-replica shards on one host's kernel state: every shard
    elects and serves writes; one jitted step advances all of them."""
    shards = tuple(range(1, 1025))
    nh = NodeHost(NodeHostConfig(
        raft_address="k1k-1", rtt_millisecond=5,
        expert=ExpertConfig(kernel_log_cap=64, kernel_capacity=1024,
                            kernel_apply_batch=8,
                            kernel_compaction_overhead=8)))
    try:
        addrs = {1: "k1k-1"}
        for sid in shards:
            nh.start_replica(addrs, False, KVStateMachine, Config(
                shard_id=sid, replica_id=1, election_rtt=10, heartbeat_rtt=2,
                device_resident=True))
        deadline = time.time() + 120
        while time.time() < deadline:
            leaders = sum(nh.get_leader_id(s)[1] for s in shards)
            if leaders == len(shards):
                break
            time.sleep(0.2)
        assert leaders == len(shards), f"only {leaders}/1024 shards elected"
        # writes on a sample of shards
        for sid in (1, 7, 512, 1024):
            sess = nh.get_noop_session(sid)
            nh.sync_propose(sess, b"big=cluster", timeout_s=20)
            assert nh.sync_read(sid, "big", timeout_s=20) == "cluster"
    finally:
        nh.close()


def test_kernel_multi_replica_shards_at_scale():
    """128 shards x 3 replicas across 3 NodeHosts, every replica a
    device-resident lane (384 lanes total, 128 per host kernel state):
    full raft rounds ride the chan transport between three batched
    kernels.  The r2 VERDICT flagged scale evidence as single-replica
    only — this is the multi-replica counterpart, sized for CI."""
    from dragonboat_tpu.request import RequestDroppedError, \
        RequestTimeoutError

    from test_kernel_engine import propose_retry
    from test_nodehost import wait_leader

    n_shards = 128
    shards = tuple(range(1, n_shards + 1))
    addrs = {1: "kmr-1", 2: "kmr-2", 3: "kmr-3"}
    hosts = {}
    ex = ExpertConfig(kernel_log_cap=64, kernel_capacity=n_shards,
                      kernel_apply_batch=8, kernel_compaction_overhead=8)
    try:
        for rid, addr in addrs.items():
            nh = NodeHost(NodeHostConfig(raft_address=addr,
                                         rtt_millisecond=5, expert=ex))
            hosts[rid] = nh   # registered before start: a mid-setup
            for sid in shards:  # failure must still close this host
                nh.start_replica(addrs, False, KVStateMachine, Config(
                    shard_id=sid, replica_id=rid, election_rtt=10,
                    heartbeat_rtt=2, device_resident=True))
        deadline = time.time() + 180
        elected = 0
        while time.time() < deadline:
            elected = sum(
                1 for sid in shards
                if any(hosts[r].get_leader_id(sid)[1] for r in addrs))
            if elected == n_shards:
                break
            time.sleep(0.25)
        assert elected == n_shards, f"only {elected}/{n_shards} elected"
        # a write on each host's leader for a sample of shards, then a
        # LINEARIZABLE read from a different host (READ_INDEX forwarded
        # cross-host to the kernel leader lane)
        for sid, read_from in ((1, 2), (64, 3), (128, 1)):
            lid = wait_leader(hosts, shard_id=sid)
            nh = hosts[lid]
            sess = nh.get_noop_session(sid)
            propose_retry(nh, sess, f"mr{sid}=ok".encode(),
                          timeout_s=10, deadline_s=30)
            other = read_from if read_from != lid else (read_from % 3) + 1
            end = time.time() + 30
            while True:
                try:
                    assert hosts[other].sync_read(
                        sid, f"mr{sid}", timeout_s=10) == "ok"
                    break
                except (RequestDroppedError, RequestTimeoutError):
                    if time.time() > end:
                        raise
                    time.sleep(0.2)
        # all three kernels still own their lanes (no mass evictions)
        for rid, nh in hosts.items():
            resident = sum(1 for sid in shards
                           if sid in nh.kernel_engine.by_shard)
            assert resident == n_shards, \
                f"host {rid}: {resident}/{n_shards} lanes resident"
    finally:
        for nh in hosts.values():
            nh.close()


def test_mesh_64_groups_across_devices():
    """Mesh scale: 64 shards x 3 replicas = 192 mesh rows over 6 virtual
    devices (g=2, r=3, n_local=32), all three NodeHosts sharing one
    MeshEngine — the r2 VERDICT noted mesh tests covered only 4-8
    groups.  Asserts every group elects through the all_gather step and
    a sample serves writes + linearizable cross-host reads."""
    from dragonboat_tpu.config import MeshSpec

    from test_kernel_engine import propose_retry

    n_shards = 64
    shards = tuple(range(1, n_shards + 1))
    prefix = f"msc-{time.monotonic_ns()}"
    spec = MeshSpec(name=prefix, g_size=2, replicas=3, n_local=32)
    addrs = {i: f"{prefix}-{i}" for i in (1, 2, 3)}
    hosts = {}
    try:
        for rid, addr in addrs.items():
            nh = NodeHost(NodeHostConfig(
                raft_address=addr, rtt_millisecond=5,
                expert=ExpertConfig(mesh=spec, kernel_log_cap=64,
                                    kernel_apply_batch=8,
                                    kernel_compaction_overhead=8)))
            hosts[rid] = nh
            for sid in shards:
                nh.start_replica(addrs, False, KVStateMachine, Config(
                    shard_id=sid, replica_id=rid, election_rtt=10,
                    heartbeat_rtt=2, mesh_resident=True))
        deadline = time.time() + 240
        elected = 0
        while time.time() < deadline:
            elected = sum(
                1 for sid in shards
                if any(hosts[r].get_leader_id(sid)[1] for r in addrs))
            if elected == n_shards:
                break
            time.sleep(0.25)
        assert elected == n_shards, f"only {elected}/{n_shards} elected"
        # every group is still mesh-resident on every host
        for rid, nh in hosts.items():
            resident = sum(1 for sid in shards
                           if (sid, rid) in nh.mesh_engine.by_shard)
            assert resident == n_shards, \
                f"host {rid}: {resident}/{n_shards} mesh-resident"
        from test_nodehost import wait_leader
        for sid in (1, 32, 64):
            lid = wait_leader(hosts, shard_id=sid)
            nh = hosts[lid]
            propose_retry(nh, nh.get_noop_session(sid),
                          f"msc{sid}=ok".encode(), timeout_s=10,
                          deadline_s=40)
            other = (lid % 3) + 1
            end = time.time() + 40
            while True:
                try:
                    assert hosts[other].sync_read(
                        sid, f"msc{sid}", timeout_s=10) == "ok"
                    break
                except Exception:
                    if time.time() > end:
                        raise
                    time.sleep(0.2)
    finally:
        for nh in hosts.values():
            nh.close()
