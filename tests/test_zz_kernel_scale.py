"""Scale test: 1k device-resident shards on one kernel state.

Kept in its own module (sorting last) because the jitted [1024]-lane step
keeps the CPU busy; running it mid-suite starves the real-time E2E tests
that follow.
"""

import time

from dragonboat_tpu.config import Config, ExpertConfig, NodeHostConfig
from dragonboat_tpu.nodehost import NodeHost

from test_nodehost import KVStateMachine


def test_kernel_1k_shards_one_process():

    """1024 single-replica shards on one host's kernel state: every shard
    elects and serves writes; one jitted step advances all of them."""
    shards = tuple(range(1, 1025))
    nh = NodeHost(NodeHostConfig(
        raft_address="k1k-1", rtt_millisecond=5,
        expert=ExpertConfig(kernel_log_cap=64, kernel_capacity=1024,
                            kernel_apply_batch=8,
                            kernel_compaction_overhead=8)))
    try:
        addrs = {1: "k1k-1"}
        for sid in shards:
            nh.start_replica(addrs, False, KVStateMachine, Config(
                shard_id=sid, replica_id=1, election_rtt=10, heartbeat_rtt=2,
                device_resident=True))
        deadline = time.time() + 120
        while time.time() < deadline:
            leaders = sum(nh.get_leader_id(s)[1] for s in shards)
            if leaders == len(shards):
                break
            time.sleep(0.2)
        assert leaders == len(shards), f"only {leaders}/1024 shards elected"
        # writes on a sample of shards
        for sid in (1, 7, 512, 1024):
            sess = nh.get_noop_session(sid)
            nh.sync_propose(sess, b"big=cluster", timeout_s=20)
            assert nh.sync_read(sid, "big", timeout_s=20) == "cluster"
    finally:
        nh.close()
