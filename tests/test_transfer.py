"""Transfer-boundary pass (analysis/transfer.py): every TB rule must
fire on a tampered fixture and stay silent on the clean one, the real
repo must be clean, the static ledger must match the live METER counts
at depth 0 and depth 1, the budget gate must catch tampering, the
dynamic-leg cache must invalidate on a jax version change, and the
runtime guard must catch an actual host round-trip through the dispatch
seam."""

from __future__ import annotations

import importlib.util
import json
import os

import pytest

from dragonboat_tpu.analysis import transfer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load(path, name):
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# A minimal transfer-clean repo: one dispatch entry pair whose every
# crossing is declared, staged through to_device builders, synced only
# at the declared SYNC_POINTS qualname, and sized through a CONTRACTS
# literal carried in the engine fixture itself.
DISPATCH_FIX = '''\
SYNC_POINTS = {
    "Engine.pending": {"tag": "pending", "why": "deferred device count"},
}
DISPATCH_ENTRIES = {
    "step": {
        "module": "core/kernel.py",
        "function": "step",
        "donated": False,
        "waiver": "depth-0 oracle must leave inputs readable",
    },
    "step_donated": {
        "module": "core/kernel.py",
        "function": "step_donated",
        "donated": True,
        "waiver": "",
    },
}
TRANSFER_LEDGER = {
    "step": {
        "resident": ("ShardState",),
        "up": (
            {"value": "Inbox", "param": "inbox",
             "site": "_InboxBuilder.to_device", "tag": "inbox_up",
             "per_step": True},
            {"value": "StepInput", "param": "inp",
             "site": "_InputBuilder.to_device", "tag": "input_up",
             "per_step": True},
        ),
        "down": (
            {"value": "[G, 8] bool", "site": "Engine._process_outputs",
             "tag": "output_flags", "per_step": True},
            {"value": "StepOutput", "site": "Engine.fetch_field",
             "tag": "lazy_out", "masked": True},
        ),
    },
    "step_donated": {
        "resident": ("ShardState",),
        "up": (
            {"value": "Inbox", "param": "inbox",
             "site": "_InboxBuilder.to_device", "tag": "inbox_up",
             "per_step": True},
            {"value": "StepInput", "param": "inp",
             "site": "_InputBuilder.to_device", "tag": "input_up",
             "per_step": True},
        ),
        "down": (
            {"value": "[G, 8] bool", "site": "Engine._process_outputs",
             "tag": "output_flags", "per_step": True},
            {"value": "StepOutput", "site": "Engine.fetch_field",
             "tag": "lazy_out", "masked": True},
        ),
    },
    "_control": (
        {"dir": "up", "value": "ShardState", "site": "Engine.inject",
         "tag": "inject_up"},
        {"dir": "down", "value": "[] i32", "site": "Engine.pending",
         "tag": "pending"},
    ),
}
'''

ENGINE_FIX = '''\
import numpy as np
import jax.numpy as jnp

CONTRACTS = {
    "ShardState": {
        "term": "[G] i32 part=G",
        "log": "[G, CAP] i32 part=G",
    },
    "Inbox": {
        "mtype": "[G, K] i32 part=G",
        "ent": "[G, K, E] i32 part=G",
    },
    "StepInput": {
        "prop_valid": "[G, B] bool part=G",
    },
    "StepOutput": {
        "resp": "[G, K] i32 part=G",
        "flags": "[G, 8] bool part=G",
    },
}


class _InboxBuilder:
    def to_device(self):
        return jnp.asarray(self.buf)


class _InputBuilder:
    def to_device(self):
        return jnp.asarray(self.buf)


class Engine:
    def inject(self, rows):
        self.state = jnp.asarray(rows)

    def pending(self):
        p = self._dispatch.dispatch(None, None, None, False)
        return int(p)

    def _process_outputs(self, out):
        return np.asarray(out)

    def fetch_field(self, out, f):
        return np.asarray(getattr(out, f))
'''

KERNEL_FIX = '''\
def step(kp, state, inbox, inp):
    return state


def step_donated(kp, state, inbox, inp):
    return state
'''


def _mini_repo(tmp_path, dispatch=DISPATCH_FIX, engine=ENGINE_FIX,
               kernel=KERNEL_FIX, budget=None):
    eng = tmp_path / "dragonboat_tpu" / "engine"
    eng.mkdir(parents=True)
    (eng / "dispatch.py").write_text(dispatch)
    (eng / "engine.py").write_text(engine)
    core = tmp_path / "core"
    core.mkdir()
    (core / "kernel.py").write_text(kernel)
    if budget is not None:
        bp = tmp_path / "dragonboat_tpu" / "analysis"
        bp.mkdir(parents=True, exist_ok=True)
        (bp / "transfer_budget.json").write_text(json.dumps(budget))
    return str(tmp_path)


def _run_fix(root):
    return transfer.run(root, files=[
        "dragonboat_tpu/engine/dispatch.py",
        "dragonboat_tpu/engine/engine.py",
    ])


def _rules(findings):
    return {f.rule for f in findings}


# ------------------------------------------------------------------ clean


def test_clean_fixture_has_no_findings(tmp_path):
    assert _run_fix(_mini_repo(tmp_path)) == []


def test_real_repo_static_is_clean():
    assert transfer.run(REPO, dynamic=False) == []


# ------------------------------------------------------------------ TB001


def test_tb001_entry_without_ledger_section(tmp_path):
    root = _mini_repo(tmp_path, dispatch=DISPATCH_FIX.replace(
        '"step_donated": {\n        "resident"', '"ghosted": {\n'
        '        "resident"'))
    fs = _run_fix(root)
    assert any(f.rule == "TB001" and "'step_donated'" in f.message
               and "no TRANSFER_LEDGER section" in f.message for f in fs)
    # ...and the renamed section matches no entry: stale declaration
    assert any(f.rule == "TB001" and "'ghosted'" in f.message
               and "stale" in f.message for f in fs)


def test_tb001_uncovered_entry_parameter(tmp_path):
    # a fourth array parameter appears on the jit entry with no
    # resident/upload declaration covering it
    root = _mini_repo(tmp_path, kernel=KERNEL_FIX.replace(
        "def step(kp, state, inbox, inp):",
        "def step(kp, state, inbox, inp, sideband):"))
    fs = _run_fix(root)
    assert any(f.rule == "TB001" and "'sideband'" in f.message
               and "undeclared host->device crossing" in f.message
               for f in fs)


def test_tb001_stale_site_qualname(tmp_path):
    root = _mini_repo(tmp_path, dispatch=DISPATCH_FIX.replace(
        '"site": "Engine.inject"', '"site": "Engine.vanished"'))
    fs = _run_fix(root)
    assert any(f.rule == "TB001" and "'Engine.vanished'" in f.message
               for f in fs)


def test_tb001_unsizable_row_value(tmp_path):
    root = _mini_repo(tmp_path, dispatch=DISPATCH_FIX.replace(
        '{"dir": "down", "value": "[] i32"',
        '{"dir": "down", "value": "[Q] i32"'))
    fs = _run_fix(root)
    assert any(f.rule == "TB001" and "cannot be sized" in f.message
               for f in fs)


def test_tb001_non_literal_ledger(tmp_path):
    root = _mini_repo(tmp_path, dispatch=(
        "SYNC_POINTS = {}\n"
        "TRANSFER_LEDGER = dict(step=1)\n"))
    fs = _run_fix(root)
    assert any(f.rule == "TB001" and "pure literal" in f.message
               for f in fs)


# ------------------------------------------------------------------ TB002


_PERMISSIVE = {
    "config": dict(transfer.DEFAULT_CONFIG),
    "budget": {
        "serial": {"up_bytes_per_step": 10**12,
                   "down_bytes_per_step": 10**12,
                   "up_crossings_per_step": 100,
                   "down_crossings_per_step": 100},
        "mesh": {"up_bytes_per_step": 10**12,
                 "down_bytes_per_step": 10**12,
                 "up_crossings_per_step": 100,
                 "down_crossings_per_step": 100},
    },
}


def test_tb002_budget_within_limits_is_clean(tmp_path):
    assert _run_fix(_mini_repo(tmp_path, budget=_PERMISSIVE)) == []


def test_tb002_tampered_byte_budget_fires(tmp_path):
    tight = json.loads(json.dumps(_PERMISSIVE))
    tight["budget"]["serial"]["up_bytes_per_step"] = 1
    fs = _run_fix(_mini_repo(tmp_path, budget=tight))
    assert any(f.rule == "TB002" and "serial" in f.message
               and "exceeds budget 1" in f.message for f in fs)


def test_tb002_missing_budget_fires_on_real_run_only(tmp_path):
    # fixture mode tolerates a missing budget; the default-mode real
    # run does not (the gate must exist to gate)
    assert "TB002" not in _rules(_run_fix(_mini_repo(tmp_path)))
    assert os.path.exists(os.path.join(REPO, transfer.BUDGET_FILE)), (
        "the seeded budget file must be checked in")


# ------------------------------------------------------------------ TB003


def test_tb003_unmasked_wide_download_row(tmp_path):
    root = _mini_repo(tmp_path, dispatch=DISPATCH_FIX.replace(
        '{"value": "StepOutput", "site": "Engine.fetch_field",\n'
        '             "tag": "lazy_out", "masked": True},',
        '{"value": "[G, CAP] i32", "site": "Engine.fetch_field",\n'
        '             "tag": "lazy_out", "per_step": True},', 1))
    fs = _run_fix(root)
    assert any(f.rule == "TB003" and "unmasked" in f.message for f in fs)


def test_tb003_eager_wide_field_fetch(tmp_path):
    root = _mini_repo(tmp_path, engine=ENGINE_FIX + '''

def sweep_everything(out):
    return np.asarray(out.resp)
''')
    fs = _run_fix(root)
    assert any(f.rule == "TB003" and ".resp" in f.message
               and "sweep_everything" in f.message for f in fs)


def test_tb003_narrow_numeric_fetch_is_clean(tmp_path):
    # the [G, 8] flags matrix pairs G with a numeric literal — that is
    # the deliberate narrow fetch, not a wide sweep
    fs = _run_fix(_mini_repo(tmp_path))
    assert "TB003" not in _rules(fs)


# ------------------------------------------------------------------ TB004


def test_tb004_upload_outside_staging_builder(tmp_path):
    root = _mini_repo(tmp_path, engine=ENGINE_FIX + '''

def sneak_upload(rows):
    return jnp.asarray(rows)
''')
    fs = _run_fix(root)
    assert any(f.rule == "TB004" and "sneak_upload" in f.message
               for f in fs)


def test_tb004_jax_numpy_spelling_is_caught(tmp_path):
    root = _mini_repo(tmp_path, engine=ENGINE_FIX + '''
import jax


def sneak_upload2(rows):
    return jax.numpy.asarray(rows)
''')
    fs = _run_fix(root)
    assert any(f.rule == "TB004" and "sneak_upload2" in f.message
               for f in fs)


def test_tb004_declared_site_and_builder_are_clean(tmp_path):
    # Engine.inject is a declared _control site and the builders are
    # *.to_device — all three upload in the clean fixture
    fs = _run_fix(_mini_repo(tmp_path))
    assert "TB004" not in _rules(fs)


# ------------------------------------------------------------------ TB005


def test_tb005_sync_outside_declared_point(tmp_path):
    root = _mini_repo(tmp_path, engine=ENGINE_FIX + '''

def eager_count(dispatch):
    p = dispatch.dispatch(None, None, None, False)
    return int(p)
''')
    fs = _run_fix(root)
    assert any(f.rule == "TB005" and "eager_count" in f.message
               and "int()" in f.message for f in fs)


def test_tb005_declared_sync_point_is_clean(tmp_path):
    # Engine.pending int()s a device value but is declared
    fs = _run_fix(_mini_repo(tmp_path))
    assert "TB005" not in _rules(fs)


def test_tb005_item_and_block_until_ready(tmp_path):
    root = _mini_repo(tmp_path, engine=ENGINE_FIX + '''

def stall(box):
    y = box.to_device()
    y.block_until_ready()
    return y.item()
''')
    fs = _run_fix(root)
    msgs = [f.message for f in fs if f.rule == "TB005"]
    assert any("block_until_ready" in m for m in msgs)
    assert any(".item()" in m for m in msgs)


# ------------------------------------------------------------------ TB006


def test_tb006_tampered_crossing_budget_fires(tmp_path):
    tight = json.loads(json.dumps(_PERMISSIVE))
    tight["budget"]["serial"]["up_crossings_per_step"] = 1
    fs = _run_fix(_mini_repo(tmp_path, budget=tight))
    assert any(f.rule == "TB006" and "transfer count grew" in f.message
               for f in fs)


# -------------------------------------------------- the seeded regression


def test_seeded_regression_host_round_trip_in_seam(tmp_path):
    """The canonical regression the pass exists to catch: a dispatch
    path that pulls a device value to the host mid-seam and re-uploads
    it.  Both legs must fire — the sync (TB005) and the re-upload
    outside any declared site (TB004) — plus TB001 when the crossing is
    'declared' at a qualname that does not exist."""
    root = _mini_repo(tmp_path, engine=ENGINE_FIX + '''

def round_trip(dispatch, state):
    out = dispatch.dispatch(state, None, None, False)
    host = float(out)          # sync outside SYNC_POINTS
    return jnp.asarray(host)   # re-upload outside any declared site
''')
    fs = _run_fix(root)
    rules = _rules(fs)
    assert "TB005" in rules and "TB004" in rules


def test_runtime_guard_catches_host_round_trip():
    """The dynamic arm of the same regression: under METER.guard() an
    unsanctioned numpy tree entering the jitted dispatch entry raises
    at the JAX level instead of silently re-staging."""
    import jax
    import numpy as np

    from dragonboat_tpu import capacity
    from dragonboat_tpu.bench_loop import bench_params, make_cluster
    from dragonboat_tpu.engine import kernel_engine as _ke
    from dragonboat_tpu.engine.dispatch import SerialDispatch

    kp = bench_params(3, platform="cpu")
    state = make_cluster(kp, 1, 3)
    G = int(state.term.shape[0])
    disp = SerialDispatch(kp)
    inbox = _ke._InboxBuilder(G, kp.inbox_cap, kp.msg_entries)
    inp = _ke._InputBuilder(G, kp.proposal_cap)
    state, _out = disp.dispatch(state, inbox, inp, donate=False)  # warm

    # the regression: state pulled to host numpy, fed straight back in
    state_np = jax.tree.map(np.array, state)
    with capacity.METER.guard():
        with pytest.raises(Exception, match="[Dd]isallow"):
            disp.dispatch(state_np, inbox, inp, donate=False)
    # sanctioned crossings still work inside the guard
    with capacity.METER.guard():
        state, _out = disp.dispatch(state, inbox, inp, donate=False)


# ------------------------------------- ledger vs live (depth 0 and 1)


def test_ledger_matches_live_counts():
    """The static TRANSFER_LEDGER and the live METER counters agree
    exactly at serial depth 0, serial depth 1 (donated) and — when the
    forced CPU mesh provides 2 devices — the 2-device mesh."""
    assert transfer.live_transfer_check(REPO, use_cache=False) == []


def test_tampered_ledger_diverges_from_live():
    """Deleting a declared per-step crossing makes the live diff fire:
    the seam still crosses, the ledger now says it must not."""
    decl, _, _ = transfer._load_decl(REPO)
    for entry in ("step", "step_donated"):
        rows = decl["TRANSFER_LEDGER"][entry]["up"]
        decl["TRANSFER_LEDGER"][entry]["up"] = tuple(
            r for r in rows if r.get("tag") != "input_up")
    fs = transfer.live_transfer_check(REPO, decl=decl, use_cache=False)
    assert any(f.rule == "TB006" and "'input_up'" in f.message
               for f in fs)


# ------------------------------------------------------ ledger artifact


def test_emit_ledger_artifact(tmp_path):
    out = str(tmp_path / "ledger.json")
    transfer.emit_ledger(REPO, out_path=out)
    with open(out, encoding="utf-8") as f:
        ledger = json.load(f)
    for entry in ("step", "step_donated", "serve_step",
                  "serve_step_donated", "fleet_stats", "fleet_health",
                  "check_invariants"):
        assert entry in ledger["entries"], entry
    for _entry, section in ledger["entries"].items():
        for dirn in ("up", "down"):
            for row in section[dirn]:
                assert isinstance(row["bytes"], int) and row["bytes"] > 0
    # the budget seed equals the sized per-step profile exactly
    with open(os.path.join(REPO, transfer.BUDGET_FILE),
              encoding="utf-8") as f:
        budget = json.load(f)["budget"]
    for profile in ("serial", "mesh"):
        for key, val in ledger["per_step"][profile].items():
            assert budget[profile][f"{key}_per_step"] == val, (
                profile, key)


# PR 17 seeded the mesh per-step budget when the serving entry still
# downloaded a device->host pending scalar every step; round 17 derives
# drain-pending from the output flags the host already fetches
_PR17_MESH_DOWN_BYTES = 8196
_PR17_MESH_DOWN_CROSSINGS = 2


def test_mesh_budget_strictly_shrank(tmp_path):
    """Round 17's device-resident fabric DELETED host crossings from the
    mesh serving step — the reseeded budget must be strictly below the
    PR 17 values, and must never regrow past them."""
    spec = transfer.reseed(REPO, budget_path=str(tmp_path / "b.json"))
    mesh = spec["budget"]["mesh"]
    assert mesh["down_bytes_per_step"] < _PR17_MESH_DOWN_BYTES, (
        "mesh per-step download budget did not shrink vs PR 17 — a "
        "per-step device->host crossing crept back into the serving "
        "entry's ledger")
    assert mesh["down_crossings_per_step"] < _PR17_MESH_DOWN_CROSSINGS, (
        "mesh per-step download crossings did not shrink vs PR 17")


def test_reseed_roundtrip(tmp_path):
    out = str(tmp_path / "budget.json")
    spec = transfer.reseed(REPO, budget_path=out)
    with open(out, encoding="utf-8") as f:
        assert json.load(f)["budget"] == spec["budget"]
    with open(os.path.join(REPO, transfer.BUDGET_FILE),
              encoding="utf-8") as f:
        assert json.load(f)["budget"] == spec["budget"], (
            "checked-in budget drifted from the declared ledger — "
            "run scripts/lint.py --reseed-transfer-budget")


# ------------------------------------------------- cache invalidation


def test_cache_invalidates_on_jax_version(tmp_path, monkeypatch):
    import jax

    key = transfer._source_key(REPO)
    cache = str(tmp_path / "cache.json")
    transfer._cache_save(cache, key, [])
    assert transfer._cache_load(cache, key) == []
    monkeypatch.setattr(jax, "__version__", "0.0.0-fake")
    assert transfer._source_key(REPO) != key
    assert transfer._cache_load(cache, transfer._source_key(REPO)) is None


def test_cache_invalidates_on_seam_source(tmp_path, monkeypatch):
    # any CACHE_SOURCES byte change shifts the key
    key = transfer._source_key(REPO)
    fake = tmp_path / "dragonboat_tpu" / "engine"
    fake.mkdir(parents=True)
    for f in transfer.CACHE_SOURCES:
        src = os.path.join(REPO, f)
        dst = tmp_path / f
        dst.parent.mkdir(parents=True, exist_ok=True)
        if os.path.exists(src):
            with open(src, "rb") as fh:
                dst.write_bytes(fh.read())
    with open(tmp_path / transfer.CACHE_SOURCES[0], "a",
              encoding="utf-8") as fh:
        fh.write("\n# tampered\n")
    assert transfer._source_key(str(tmp_path)) != key


# -------------------------------------------------- lint.py integration


def test_lint_registers_transfer_pass():
    lint = _load(os.path.join(REPO, "scripts", "lint.py"), "lint_tb")
    assert "transfer" in lint.PASSES
    assert lint.PASS_SCOPES["transfer"] == transfer.SCOPE


def test_lint_changed_only_invalidation():
    lint = _load(os.path.join(REPO, "scripts", "lint.py"), "lint_tb2")
    for changed in (["dragonboat_tpu/engine/dispatch.py"],
                    ["dragonboat_tpu/engine/kernel_engine.py"],
                    ["dragonboat_tpu/core/kernel.py"],
                    ["dragonboat_tpu/capacity.py"],
                    [transfer.BUDGET_FILE]):
        assert "transfer" in lint.select_changed(changed), changed
    assert "transfer" not in lint.select_changed(["README.md"])


def test_findings_flow_through_lint_summary(tmp_path):
    summary = _load(os.path.join(REPO, "scripts", "lint_summary.py"),
                    "lint_summary_tb")
    art = tmp_path / "findings.jsonl"
    art.write_text(json.dumps({
        "path": "dragonboat_tpu/engine/dispatch.py", "line": 1,
        "pass": "transfer", "rule": "TB001",
        "message": "undeclared crossing", "waived": False,
        "reason": None}) + "\n")
    rc = summary.main(["lint_summary.py", str(art)])
    assert rc == 1
