"""Shrunken snapshots for on-disk SMs (snapshotter.go:200 Shrink,
snapshotio.go:462 ShrinkSnapshot): after an on-disk SM recovers an
installed snapshot and syncs, the recorded file is replaced by a tiny
valid container (empty sessions, no payload); recovery recognizes the
shrunken form and never feeds it to the SM."""

import io
import json
import os
import struct
import time

import pytest

from dragonboat_tpu import raftpb as pb
from dragonboat_tpu.config import Config, NodeHostConfig
from dragonboat_tpu.nodehost import NodeHost
from dragonboat_tpu.rsm.snapshotio import (
    SnapshotFormatError,
    is_shrunk_snapshot,
    read_snapshot,
    shrink_snapshot_file,
    write_snapshot,
)
from dragonboat_tpu.rsm.statemachine import StateMachine
from dragonboat_tpu.statemachine import IOnDiskStateMachine, \
    IStateMachine, Result
from dragonboat_tpu.vfs import default_fs

from test_nodehost import wait_leader


class DurableDiskKV(IOnDiskStateMachine):
    """A REAL on-disk SM: state persists to a json file; open() recovers
    it — so a restart after shrink must come back with the data."""

    root = "/tmp/shrink-test"  # overridden per-test

    def __init__(self, shard_id=0, replica_id=0):
        self.path = os.path.join(self.root, f"sm-{shard_id}-{replica_id}.json")
        self.kv = {}
        self.applied = 0

    def open(self, stopc):
        if os.path.exists(self.path):
            with open(self.path) as f:
                d = json.load(f)
            self.kv, self.applied = d["kv"], d["applied"]
        return self.applied

    def update(self, entries):
        out = []
        for e in entries:
            k, v = e.cmd.decode().split("=", 1)
            self.kv[k] = v
            self.applied = e.index
            out.append(type(e)(index=e.index, cmd=e.cmd,
                               result=Result(value=len(self.kv))))
        self.sync()
        return out

    def lookup(self, q):
        return self.kv.get(q)

    def sync(self):
        os.makedirs(os.path.dirname(self.path), exist_ok=True)
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"kv": self.kv, "applied": self.applied}, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)

    def prepare_snapshot(self):
        return dict(self.kv), self.applied

    def save_snapshot(self, ctx, w, done):
        kv, applied = ctx
        d = json.dumps({"kv": kv, "applied": applied}).encode()
        w.write(struct.pack("<I", len(d)))
        w.write(d)

    def recover_from_snapshot(self, r, done):
        (n,) = struct.unpack("<I", r.read(4))
        d = json.loads(r.read(n).decode())
        self.kv, self.applied = d["kv"], d["applied"]
        self.sync()


class MemKV(IStateMachine):
    def __init__(self, *a):
        self.kv = {}

    def update(self, entry):
        k, v = entry.cmd.decode().split("=", 1)
        self.kv[k] = v
        return Result(value=len(self.kv))

    def lookup(self, q):
        return self.kv.get(q)

    def save_snapshot(self, w, files, done):
        d = json.dumps(self.kv).encode()
        w.write(struct.pack("<I", len(d)))
        w.write(d)

    def recover_from_snapshot(self, r, files, done):
        (n,) = struct.unpack("<I", r.read(4))
        self.kv = json.loads(r.read(n).decode())


def test_shrink_file_roundtrip(tmp_path):
    """shrink_snapshot_file replaces a full container with a valid,
    recognizably-shrunk one."""
    p = str(tmp_path / "snap.bin")
    fs = default_fs()
    with open(p, "wb") as f:
        write_snapshot(f, b"SESSIONS", lambda w: w.write(b"x" * 100_000))
    full_size = os.path.getsize(p)
    assert not is_shrunk_snapshot(p, fs)
    shrink_snapshot_file(p, fs, session_data=b"")
    assert is_shrunk_snapshot(p, fs)
    assert os.path.getsize(p) < 64 < full_size
    with open(p, "rb") as f:
        session, payload = read_snapshot(f)
        assert payload.shrunk
        assert payload.read() == b""


def test_recover_from_shrunk_skips_payload(tmp_path):
    """An on-disk SM recovering a shrunk file keeps the data its own
    storage already holds; the payload is not touched."""
    DurableDiskKV.root = str(tmp_path)
    sm = StateMachine(1, 1, DurableDiskKV(1, 1))
    for i in range(10):
        sm.handle([pb.Entry(term=1, index=i + 1, cmd=f"k{i}=v{i}".encode())])
    path = str(tmp_path / "snap.bin")
    index, term, membership = sm.save_snapshot(path)
    sm.shrink_recorded_snapshot(path)
    assert is_shrunk_snapshot(path, default_fs())

    # a fresh orchestrator around a fresh (durable) SM: open() recovers
    # the data; the shrunk snapshot recovery only restores meta/sessions
    sm2 = StateMachine(1, 1, DurableDiskKV(1, 1))
    assert sm2.get_last_applied() == 10
    ss = pb.Snapshot(index=index, term=term, membership=membership)
    sm2.recover_from_snapshot(path, ss)
    assert sm2.get_last_applied() == 10
    assert sm2.lookup("k9") == "v9"


def test_shrunk_file_rejected_for_regular_sm(tmp_path):
    p = str(tmp_path / "snap.bin")
    sm = StateMachine(1, 1, MemKV())
    sm.handle([pb.Entry(term=1, index=1, cmd=b"a=b")])
    sm.save_snapshot(p)
    shrink_snapshot_file(p, default_fs(), b"")
    sm2 = StateMachine(1, 1, MemKV())
    with pytest.raises(SnapshotFormatError):
        sm2.recover_from_snapshot(p, pb.Snapshot(index=1, term=1))


def test_shrink_noop_for_regular_sm(tmp_path):
    p = str(tmp_path / "snap.bin")
    sm = StateMachine(1, 1, MemKV())
    sm.handle([pb.Entry(term=1, index=1, cmd=b"a=b")])
    sm.save_snapshot(p)
    sm.shrink_recorded_snapshot(p)  # no-op: not on-disk
    assert not is_shrunk_snapshot(p, default_fs())


def test_installed_snapshot_shrinks_then_restart_keeps_data(tmp_path):
    """E2E: a lagging on-disk replica catches up via snapshot install;
    its recorded snapshot file ends up shrunk (node.go:871-877), and a
    full restart of that host still serves the data (the SM's own
    storage is the source of truth)."""
    DurableDiskKV.root = str(tmp_path / "sms")
    addrs = {i: f"shrink-{time.monotonic_ns()}-{i}" for i in (1, 2, 3)}
    hosts = {}
    for rid, addr in addrs.items():
        nh = NodeHost(NodeHostConfig(raft_address=addr, rtt_millisecond=5))
        nh.start_replica(addrs, False, DurableDiskKV, Config(
            shard_id=1, replica_id=rid, election_rtt=10, heartbeat_rtt=1,
            snapshot_entries=6, compaction_overhead=2))
        hosts[rid] = nh
    try:
        lid = wait_leader(hosts)
        lagger = next(r for r in hosts if r != lid)
        hosts[lagger].close()
        del hosts[lagger]
        s = hosts[lid].get_noop_session(1)
        for i in range(30):
            hosts[lid].sync_propose(s, f"d{i}=v{i}".encode())
        nh2 = NodeHost(NodeHostConfig(raft_address=addrs[lagger],
                                      rtt_millisecond=5))
        nh2.start_replica(addrs, False, DurableDiskKV, Config(
            shard_id=1, replica_id=lagger, election_rtt=10, heartbeat_rtt=1,
            snapshot_entries=6, compaction_overhead=2))
        hosts[lagger] = nh2
        deadline = time.time() + 15
        while time.time() < deadline and nh2.stale_read(1, "d29") != "v29":
            time.sleep(0.05)
        assert nh2.stale_read(1, "d29") == "v29"

        # the installed snapshot record on the lagger must be shrunk
        fs = default_fs()
        deadline = time.time() + 10
        ss = None
        while time.time() < deadline:
            ss = nh2.logdb.get_snapshot(1, lagger)
            if ss is not None and ss.filepath \
                    and os.path.exists(ss.filepath) \
                    and is_shrunk_snapshot(ss.filepath, fs):
                break
            time.sleep(0.05)
        assert ss is not None and is_shrunk_snapshot(ss.filepath, fs), \
            "installed snapshot was not shrunk"

        # restart the lagger: data must come back from the SM's own
        # storage, not the (payload-less) snapshot file
        hosts[lagger].close()
        del hosts[lagger]
        nh3 = NodeHost(NodeHostConfig(raft_address=addrs[lagger],
                                      rtt_millisecond=5))
        nh3.start_replica(addrs, False, DurableDiskKV, Config(
            shard_id=1, replica_id=lagger, election_rtt=10, heartbeat_rtt=1,
            snapshot_entries=6, compaction_overhead=2))
        hosts[lagger] = nh3
        assert nh3.stale_read(1, "d29") == "v29"
        assert nh3.stale_read(1, "d0") == "v0"
    finally:
        for h in hosts.values():
            h.close()
