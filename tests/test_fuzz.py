"""Decode-robustness fuzzing — the pytest analog of the reference's
go-fuzz entry points (raftpb/fuzz.go, internal/transport/fuzz.go).

Property: hostile bytes fed to any wire decoder must raise a controlled
ValueError/struct.error-style exception (or return a valid object) —
never crash the process, hang, or raise something uncontrolled like
MemoryError from a hostile length field.
"""

import struct
import zlib

import numpy as np
import pytest

from dragonboat_tpu import raftpb as pb
from dragonboat_tpu.logdb.tan import TanLogDB
from dragonboat_tpu.rsm.snapshotio import SnapshotFormatError, read_snapshot

OK_ERRORS = (ValueError, struct.error, IndexError, OverflowError,
             UnicodeDecodeError, EOFError)
# deliberately NOT in OK_ERRORS: MemoryError — a decoder that trusts a
# hostile length field into a giant allocation is exactly the bug class
# these tests exist to catch.


def _rng():
    return np.random.default_rng(0xDB)


def test_fuzz_message_batch_random_bytes():
    rng = _rng()
    for n in (0, 1, 3, 4, 16, 64, 256, 4096):
        for _ in range(50):
            blob = rng.integers(0, 256, size=n, dtype=np.uint8).tobytes()
            with pytest.raises(OK_ERRORS):
                pb.decode_message_batch(blob)


def test_fuzz_message_batch_bitflips():
    """Valid frame, single bit flipped anywhere -> checksum catches it
    (or the decode still yields a well-formed batch iff the flip landed
    after the CRC gate's coverage — it can't: the CRC covers the body)."""
    msgs = tuple(
        pb.Message(type=pb.MessageType.REPLICATE, from_=1, to=2, shard_id=9,
                   term=4, log_index=i,
                   entries=(pb.Entry(term=4, index=i + 1, cmd=b"pay" * 5),))
        for i in range(8)
    )
    enc = pb.encode_message_batch(pb.MessageBatch(
        requests=msgs, deployment_id=3, source_address="fz-1"))
    rng = _rng()
    for _ in range(300):
        i = int(rng.integers(0, len(enc)))
        bit = 1 << int(rng.integers(0, 8))
        mutated = bytearray(enc)
        mutated[i] ^= bit
        with pytest.raises(ValueError):
            pb.decode_message_batch(bytes(mutated))


def test_fuzz_message_batch_truncations():
    msgs = (pb.Message(type=pb.MessageType.HEARTBEAT, from_=1, to=2,
                       shard_id=1, term=1),)
    enc = pb.encode_message_batch(pb.MessageBatch(
        requests=msgs, deployment_id=0, source_address="fz-2"))
    for cut in range(len(enc)):
        with pytest.raises(OK_ERRORS):
            pb.decode_message_batch(enc[:cut])


def test_fuzz_hostile_length_fields_do_not_allocate():
    """A frame with a VALID body CRC but a hostile element count must be
    rejected by running off the buffer end — never trusted into a giant
    pre-allocation (MemoryError is not an accepted outcome)."""
    body = struct.pack("<QII", 1, 1, 4) + b"addr" + struct.pack("<I", 1 << 31)
    blob = struct.pack("<I", zlib.crc32(body)) + body
    with pytest.raises(OK_ERRORS):
        pb.decode_message_batch(blob)


def test_fuzz_tan_log_random_and_mutated(tmp_path):
    """Random garbage and bit-flipped tan logs must either replay the
    valid prefix (torn tail) or raise CorruptLogError — never crash."""
    from dragonboat_tpu.logdb.tan import CorruptLogError

    rng = _rng()
    # a valid log to mutate
    d1 = tmp_path / "base"
    db = TanLogDB(str(d1))
    for i in range(1, 20):
        db.save_raft_state([pb.Update(
            shard_id=1, replica_id=1,
            state=pb.State(term=1, vote=1, commit=i),
            entries_to_save=(pb.Entry(term=1, index=i, cmd=b"z" * 24),),
        )], 0)
    db.close()
    log_path = next(iter(sorted(d1.iterdir())))  # the single log file
    raw = log_path.read_bytes()

    for trial in range(40):
        mutated = bytearray(raw)
        for _ in range(int(rng.integers(1, 4))):
            mutated[int(rng.integers(0, len(mutated)))] ^= \
                1 << int(rng.integers(0, 8))
        d = tmp_path / f"m{trial}"
        d.mkdir()
        (d / log_path.name).write_bytes(bytes(mutated))
        try:
            db2 = TanLogDB(str(d))
            # whatever replayed must be internally consistent
            for info in db2.list_node_info():
                rs = db2.read_raft_state(info.shard_id, info.replica_id, 0)
                if rs is not None:
                    assert rs.entry_count >= 0
            db2.close()
        except CorruptLogError:
            pass  # controlled refusal is the other valid outcome

    for trial in range(20):
        d = tmp_path / f"r{trial}"
        d.mkdir()
        blob = rng.integers(0, 256, size=int(rng.integers(0, 2000)),
                            dtype=np.uint8).tobytes()
        (d / "log-00000001.tan").write_bytes(blob)
        try:
            TanLogDB(str(d)).close()
        except CorruptLogError:
            pass


def test_fuzz_snapshot_reader(tmp_path):
    rng = _rng()
    for trial in range(60):
        blob = rng.integers(0, 256, size=int(rng.integers(0, 500)),
                            dtype=np.uint8).tobytes()
        p = tmp_path / f"s{trial}.gbsnap"
        p.write_bytes(blob)
        with open(p, "rb") as f:
            with pytest.raises((SnapshotFormatError, *OK_ERRORS)):
                session, payload = read_snapshot(f)
                payload.read()


def test_fuzz_chunk_sink_hostile_chunks(tmp_path):
    """Hostile chunk sequences must never crash the sink or leak
    transfers (out-of-order ids, bogus counts, wrong deployment)."""
    from dragonboat_tpu.transport.chunks import ChunkSink

    delivered = []
    sink = ChunkSink(str(tmp_path), deployment_id=5,
                     deliver=lambda m, s: delivered.append(m))
    rng = _rng()
    for _ in range(300):
        c = pb.Chunk(
            shard_id=int(rng.integers(0, 3)),
            replica_id=int(rng.integers(0, 3)),
            from_=int(rng.integers(0, 3)),
            chunk_id=int(rng.integers(0, 5)),
            chunk_count=int(rng.integers(0, 5)),
            chunk_size=0,
            file_size=int(rng.integers(0, 100)),
            index=1, term=1,
            deployment_id=int(rng.integers(4, 7)),
            data=bytes(rng.integers(0, 256, size=int(rng.integers(0, 64)),
                                    dtype=np.uint8)),
            message=pb.Message(type=pb.MessageType.INSTALL_SNAPSHOT,
                               from_=1, to=2, shard_id=1)
            if rng.random() < 0.5 else None,
        )
        sink.add(c)  # bool result; must simply not raise
    sink.tick()
    assert sink.inflight() <= 9  # bounded by (shard, replica, from) keys
