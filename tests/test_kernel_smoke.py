"""Batched kernel smoke tests: elections, replication, reads over the
loopback router."""

import numpy as np

from dragonboat_tpu.core import params as KP
from kernel_harness import KernelCluster


def test_kernel_single_group_election():
    c = KernelCluster(1, 3)
    steps = c.run_until_leader()
    lrow = c.leader_row(0)
    assert lrow is not None
    term = c.field("term")
    leader = c.field("leader")
    lead_rid = lrow % 3 + 1
    assert (term[:3] == term[lrow]).all()
    assert (leader[:3] == lead_rid).all()
    # noop entry committed everywhere after drain
    assert (c.field("committed")[:3] == 1).all()


def test_kernel_propose_and_commit():
    c = KernelCluster(1, 3)
    c.run_until_leader()
    lrow = c.leader_row(0)
    out = c.step(proposals={lrow: 3})
    acc = np.asarray(out.prop_accepted)[lrow]
    assert acc[:3].all()
    idx = np.asarray(out.prop_index)[lrow]
    assert list(idx[:3]) == [2, 3, 4]
    c.drain(6)
    assert (c.field("committed")[:3] == 4).all()
    assert (c.field("last")[:3] == 4).all()
    # log terms identical across replicas
    lt = c.field("lt")
    assert (lt[0] == lt[1]).all() and (lt[1] == lt[2]).all()


def test_kernel_many_groups_parallel():
    c = KernelCluster(8, 3)
    for _ in range(120):
        c.step(tick=True)
        lead_rows = [c.leader_row(g) for g in range(8)]
        if all(r is not None for r in lead_rows):
            break
    c.drain(6)
    lead_rows = [c.leader_row(g) for g in range(8)]
    assert all(r is not None for r in lead_rows)
    # propose on every group's leader in ONE batched step
    out = c.step(proposals={r: 2 for r in lead_rows})
    for r in lead_rows:
        assert np.asarray(out.prop_accepted)[r][:2].all()
    c.drain(6)
    committed = c.field("committed")
    assert (committed == 3).all()  # noop + 2 on all 24 rows


def test_kernel_proposal_on_follower_dropped():
    c = KernelCluster(1, 3)
    c.run_until_leader()
    lrow = c.leader_row(0)
    frow = next(r for r in range(3) if r != lrow)
    out = c.step(proposals={frow: 1})
    assert not np.asarray(out.prop_accepted)[frow].any()
    c.drain(4)
    assert (c.field("last")[:3] == 1).all()  # only the noop


def test_kernel_read_index_quorum():
    c = KernelCluster(1, 3)
    c.run_until_leader()
    lrow = c.leader_row(0)
    out = c.step(reads={lrow: (77, 88)})
    assert not np.asarray(out.rtr_valid)[lrow].any()  # needs quorum ack
    # next steps deliver heartbeats + resps -> ready
    got = False
    for _ in range(4):
        out = c.step()
        v = np.asarray(out.rtr_valid)[lrow]
        if v.any():
            i = int(np.argmax(v))
            assert int(np.asarray(out.rtr_low)[lrow, i]) == 77
            assert int(np.asarray(out.rtr_high)[lrow, i]) == 88
            assert int(np.asarray(out.rtr_index)[lrow, i]) == 1
            got = True
            break
    assert got


def test_kernel_read_index_rejected_on_follower():
    c = KernelCluster(1, 3)
    c.run_until_leader()
    lrow = c.leader_row(0)
    frow = next(r for r in range(3) if r != lrow)
    out = c.step(reads={frow: (5, 6)})
    assert bool(np.asarray(out.ri_dropped)[frow])


def test_kernel_leader_transfer():
    c = KernelCluster(1, 3)
    c.run_until_leader()
    lrow = c.leader_row(0)
    target_rid = (lrow + 1) % 3 + 1
    c.step(transfers={lrow: target_rid})
    for _ in range(8):
        c.step()
    new_lrow = c.leader_row(0)
    assert new_lrow == c.row(0, target_rid)


def test_kernel_leader_failure_reelection():
    c = KernelCluster(1, 3)
    c.run_until_leader()
    lrow = c.leader_row(0)
    c.isolated.add(lrow)
    for _ in range(80):
        c.step(tick=True)
        alive = [r for r in range(3) if r != lrow and
                 c.field("role")[r] == KP.LEADER]
        if alive:
            break
    assert alive, "no re-election after leader isolation"
    assert c.field("term")[alive[0]] > c.field("term")[lrow]


def test_kernel_check_quorum_step_down():
    c = KernelCluster(1, 3, check_quorum=True, election=10)
    c.run_until_leader()
    lrow = c.leader_row(0)
    for r in range(3):
        if r != lrow:
            c.isolated.add(r)
    # two election timeouts of ticks: leader must step down
    for _ in range(25):
        c.step(tick=True)
    assert c.field("role")[lrow] != KP.LEADER


def test_kernel_prevote_cluster():
    c = KernelCluster(1, 3, pre_vote=True)
    c.run_until_leader()
    assert c.leader_row(0) is not None
    assert (c.field("term")[:3] == 1).all()


def test_kernel_follower_log_conflict_truncation():
    c = KernelCluster(1, 3)
    c.run_until_leader()
    lrow = c.leader_row(0)
    frows = [r for r in range(3) if r != lrow]
    # partition one follower, propose (committed via other follower)
    vic = frows[0]
    c.isolated.add(vic)
    c.step(proposals={lrow: 2})
    c.drain(6)
    assert c.field("committed")[lrow] == 3
    assert c.field("last")[vic] == 1
    # heal: victim catches up through reject/backtrack
    c.isolated.clear()
    for _ in range(10):
        c.step(tick=True)
    assert c.field("last")[vic] == c.field("last")[lrow]
    assert c.field("committed")[vic] == c.field("committed")[lrow]
    assert (c.field("lt")[vic] == c.field("lt")[lrow]).all()
