"""tools.import_snapshot — quorum-loss repair (tools/import.go:134).

Scenario: a 3-node cluster loses 2 nodes permanently.  The survivor's
exported snapshot is imported into fresh data dirs with membership
rewritten to a single node; the restarted host recovers the data and
serves writes again.
"""

import os
import time

import pytest

from dragonboat_tpu import tools
from dragonboat_tpu.config import Config, NodeHostConfig
from dragonboat_tpu.nodehost import NodeHost
from dragonboat_tpu.server.env import DirLockedError

from test_nodehost import KVStateMachine, wait_leader


def test_export_writes_metadata(tmp_path):
    nh = NodeHost(NodeHostConfig(raft_address="exp-1", rtt_millisecond=5))
    nh.start_replica({1: "exp-1"}, False, KVStateMachine, Config(
        shard_id=1, replica_id=1, election_rtt=10, heartbeat_rtt=1))
    try:
        deadline = time.time() + 10
        while time.time() < deadline and not nh.get_leader_id(1)[1]:
            time.sleep(0.02)
        sess = nh.get_noop_session(1)
        for i in range(5):
            nh.sync_propose(sess, f"e{i}=v{i}".encode())
        export = str(tmp_path / "exported.gbsnap")
        idx = nh.sync_request_snapshot(1, export_path=export)
        assert os.path.exists(export)
        meta = tools.read_export_metadata(export)
        assert meta["index"] == idx
        assert meta["shard_id"] == 1
        assert "1" in meta["membership"]["addresses"]
    finally:
        nh.close()


def test_import_snapshot_repairs_quorum_loss(tmp_path):
    data = tmp_path / "data"
    hosts, addrs = {}, {i: f"imp-{i}" for i in (1, 2, 3)}
    for rid, addr in addrs.items():
        nh = NodeHost(NodeHostConfig(raft_address=addr, rtt_millisecond=5,
                                     node_host_dir=str(data)))
        nh.start_replica(addrs, False, KVStateMachine, Config(
            shard_id=1, replica_id=rid, election_rtt=10, heartbeat_rtt=1))
        hosts[rid] = nh
    lead = wait_leader(hosts)
    nh = hosts[lead]
    sess = nh.get_noop_session(1)
    for i in range(10):
        nh.sync_propose(sess, f"q{i}=v{i}".encode())
    export = str(tmp_path / "rescue.gbsnap")
    nh.sync_request_snapshot(1, export_path=export)
    for h in hosts.values():
        h.close()

    # disaster: replicas 2 and 3 are gone forever; rebuild replica 1 as a
    # single-member shard in a FRESH data dir from the exported snapshot
    newdata = tmp_path / "rebuilt"
    cfg = NodeHostConfig(raft_address="imp-1", rtt_millisecond=5,
                         node_host_dir=str(newdata))
    tools.import_snapshot(cfg, export, {1: "imp-1"}, replica_id=1)

    nh = NodeHost(cfg)
    try:
        nh.start_replica({}, False, KVStateMachine, Config(
            shard_id=1, replica_id=1, election_rtt=10, heartbeat_rtt=1))
        deadline = time.time() + 15
        while time.time() < deadline and not nh.get_leader_id(1)[1]:
            time.sleep(0.02)
        for i in range(10):
            assert nh.stale_read(1, f"q{i}") == f"v{i}", i
        # single-member quorum serves writes again
        nh.sync_propose(nh.get_noop_session(1), b"back=online")
        assert nh.sync_read(1, "back") == "online"
        m = nh.get_shard_membership(1)
        assert dict(m.addresses) == {1: "imp-1"}
    finally:
        nh.close()


def test_import_refuses_running_host(tmp_path):
    cfg = NodeHostConfig(raft_address="run-1", rtt_millisecond=5,
                         node_host_dir=str(tmp_path / "d"))
    nh = NodeHost(cfg)
    nh.start_replica({1: "run-1"}, False, KVStateMachine, Config(
        shard_id=1, replica_id=1, election_rtt=10, heartbeat_rtt=1))
    try:
        deadline = time.time() + 10
        while time.time() < deadline and not nh.get_leader_id(1)[1]:
            time.sleep(0.02)
        export = str(tmp_path / "x.gbsnap")
        nh.sync_request_snapshot(1, export_path=export)
        with pytest.raises(DirLockedError):
            tools.import_snapshot(cfg, export, {1: "run-1"}, replica_id=1)
    finally:
        nh.close()


def test_import_requires_membership(tmp_path):
    with pytest.raises(ValueError):
        tools.import_snapshot(
            NodeHostConfig(raft_address="a-1", node_host_dir=str(tmp_path)),
            "/nonexistent", {2: "a-2"}, replica_id=1)
