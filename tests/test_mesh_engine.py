"""MeshEngine end-to-end: raft groups whose replicas span the 8-device CPU
mesh, served through the real NodeHost client API (VERDICT round-2 item 3 —
the ICI mesh promoted from bench island to serving path).

Scenarios mirror test_nodehost.py / test_kernel_engine.py with
``Config.mesh_resident=True``: every NodeHost attaches to one shared
MeshEngine, replicas of a shard live on different devices along mesh axis
'r', and intra-group raft traffic rides the all_gather inside the jitted
step instead of the chan transport (parallel/ici.py:_serve_body).
"""

import time

import pytest

from dragonboat_tpu.config import (
    Config,
    ExpertConfig,
    MeshSpec,
    NodeHostConfig,
)
from dragonboat_tpu.nodehost import NodeHost
from dragonboat_tpu.request import RequestDroppedError, RequestTimeoutError

from test_nodehost import KVStateMachine, wait_leader


def propose_retry(nh, sess, cmd, timeout_s=5, deadline_s=30):
    end = time.time() + deadline_s
    while True:
        try:
            return nh.sync_propose(sess, cmd, timeout_s=timeout_s)
        except (RequestDroppedError, RequestTimeoutError):
            if time.time() > end:
                raise
            time.sleep(0.1)


def make_cluster(prefix, n=3, snapshot_entries=0, rtt_ms=5, shards=(1,),
                 node_host_dirs=None):
    """n NodeHosts sharing one (2, 3)-mesh: 6 of the 8 virtual devices."""
    spec = MeshSpec(name=prefix, g_size=2, replicas=3, n_local=4)
    addrs = {i: f"{prefix}-{i}" for i in range(1, n + 1)}
    hosts = {}
    for rid, addr in addrs.items():
        nh = NodeHost(NodeHostConfig(
            raft_address=addr, rtt_millisecond=rtt_ms,
            node_host_dir=(node_host_dirs or {}).get(rid, ""),
            expert=ExpertConfig(mesh=spec, kernel_log_cap=256,
                                kernel_apply_batch=16,
                                kernel_compaction_overhead=16)))
        for sid in shards:
            cfg = Config(shard_id=sid, replica_id=rid, election_rtt=10,
                         heartbeat_rtt=2, snapshot_entries=snapshot_entries,
                         compaction_overhead=5, mesh_resident=True)
            nh.start_replica(addrs, False, KVStateMachine, cfg)
        hosts[rid] = nh
    return hosts


def close_all(hosts):
    for nh in hosts.values():
        nh.close()


@pytest.fixture
def cluster():
    hosts = make_cluster(f"mshA{time.monotonic_ns()}")
    yield hosts
    close_all(hosts)


def test_mesh_shard_is_mesh_resident(cluster):
    hosts = cluster
    eng = hosts[1].mesh_engine
    assert eng is not None
    # one shared engine across the attached NodeHosts
    assert eng is hosts[2].mesh_engine is hosts[3].mesh_engine
    # replicas occupy distinct rows (distinct devices along axis 'r')
    rows = [eng.by_shard[(1, r)].lane for r in (1, 2, 3)]
    assert len(set(rows)) == 3
    # protocol state lives on the mesh, not in a pycore Peer
    assert all(hosts[r].nodes[1].peer is None for r in hosts)


def test_mesh_propose_and_read(cluster):
    hosts = cluster
    lid = wait_leader(hosts, timeout=60)
    nh = hosts[lid]
    sess = nh.get_noop_session(1)
    for i in range(10):
        propose_retry(nh, sess, f"k{i}=v{i}".encode())
    assert nh.sync_read(1, "k7", timeout_s=10) == "v7"
    deadline = time.time() + 10
    while time.time() < deadline:
        if all(h.stale_read(1, "k9") == "v9" for h in hosts.values()):
            break
        time.sleep(0.05)
    assert all(h.stale_read(1, "k9") == "v9" for h in hosts.values())


def test_mesh_propose_via_follower_host(cluster):
    """Follower-host proposals forward in-engine to the leader row (the
    reference forwards MsgProp through the raft core)."""
    hosts = cluster
    lid = wait_leader(hosts, timeout=60)
    frid = next(r for r in hosts if r != lid)
    fnh = hosts[frid]
    r = propose_retry(fnh, fnh.get_noop_session(1), b"fwd=yes")
    assert r.value >= 1
    assert hosts[lid].sync_read(1, "fwd", timeout_s=10) == "yes"


def test_mesh_read_from_follower_host(cluster):
    """ReadIndex forwarded over the host transport to the leader row."""
    hosts = cluster
    lid = wait_leader(hosts, timeout=60)
    propose_retry(hosts[lid], hosts[lid].get_noop_session(1), b"fr=ok")
    frid = next(r for r in hosts if r != lid)
    deadline = time.time() + 15
    val = None
    while time.time() < deadline:
        try:
            val = hosts[frid].sync_read(1, "fr", timeout_s=3)
            if val == "ok":
                break
        except Exception:
            time.sleep(0.1)
    assert val == "ok"


def test_mesh_leader_transfer(cluster):
    hosts = cluster
    lid = wait_leader(hosts, timeout=60)
    target = next(r for r in hosts if r != lid)
    node = hosts[lid].nodes[1]
    rs = node.request_leader_transfer(target, 2000)
    hosts[lid]._work.set()
    r = rs.wait(20.0)
    assert r.code.name == "COMPLETED", r.code
    assert wait_leader(hosts, timeout=30) == target


def test_mesh_snapshot_and_compaction():
    hosts = make_cluster(f"mshS{time.monotonic_ns()}", snapshot_entries=12)
    try:
        lid = wait_leader(hosts, timeout=60)
        nh = hosts[lid]
        sess = nh.get_noop_session(1)
        for i in range(30):
            propose_retry(nh, sess, f"s{i}=v{i}".encode())
        deadline = time.time() + 15
        node = nh.nodes[1]
        while time.time() < deadline and node.compacted_to == 0:
            time.sleep(0.05)
        assert node.compacted_to > 0
        assert nh.sync_read(1, "s29", timeout_s=10) == "v29"
        idx = nh.sync_request_snapshot(1, timeout_s=10)
        assert idx > 0
    finally:
        close_all(hosts)


def test_mesh_partitioned_leader_deposed():
    """Device-side partition mask (monkey.go:170 on the mesh): cutting the
    leader's host re-elects among the remaining devices; healing rejoins."""
    hosts = make_cluster(f"mshP{time.monotonic_ns()}")
    try:
        lid = wait_leader(hosts, timeout=60)
        propose_retry(hosts[lid], hosts[lid].get_noop_session(1), b"pre=cut")
        hosts[lid].partition_node()
        others = {r: h for r, h in hosts.items() if r != lid}
        new_lid = None
        deadline = time.time() + 60
        while time.time() < deadline:
            try:
                new_lid = wait_leader(others, timeout=10)
                if new_lid != lid:
                    break
            except AssertionError:
                pass
        assert new_lid is not None and new_lid != lid
        propose_retry(others[new_lid], others[new_lid].get_noop_session(1),
                      b"during=cut")
        hosts[lid].restore_partitioned_node()
        # healed replica converges
        deadline = time.time() + 30
        while time.time() < deadline:
            if hosts[lid].stale_read(1, "during") == "cut":
                break
            time.sleep(0.05)
        assert hosts[lid].stale_read(1, "during") == "cut"
    finally:
        close_all(hosts)


def test_mesh_single_link_cut_falls_back_to_hub():
    """Round 17 per-LINK cut: severing ONE mesh link (leader <->
    follower) leaves the row serving — traffic for that link leaves the
    device fabric and rides the host hub instead, so the cut follower
    keeps replicating with zero acked loss; healing returns the link to
    the mesh and the hub gate closes behind it."""
    hosts = make_cluster(f"mshL{time.monotonic_ns()}")
    try:
        lid = wait_leader(hosts, timeout=60)
        nh = hosts[lid]
        propose_retry(nh, nh.get_noop_session(1), b"pre=cut")
        deadline = time.time() + 15
        while time.time() < deadline:
            if all(h.stale_read(1, "pre") == "cut" for h in hosts.values()):
                break
            time.sleep(0.05)
        assert all(h.stale_read(1, "pre") == "cut" for h in hosts.values())

        frid = next(r for r in hosts if r != lid)
        eng = nh.mesh_engine
        lnode = eng.by_shard[(1, lid)]
        fnode = eng.by_shard[(1, frid)]
        eng.set_link_hub_served(lnode, frid, True)
        # a link is cut at BOTH endpoints (asymmetric masks could leak
        # one direction across a link the host already re-routed)
        assert eng._dispatch.cut[lnode.lane, frid - 1]
        assert eng._dispatch.cut[fnode.lane, lid - 1]
        # the rows are NOT partitioned: only one link left the mesh
        assert not eng._dispatch.cut[lnode.lane].all()
        assert eng.link_hub_served(lnode, frid)
        assert eng.link_hub_served(fnode, lid)
        # the doctor's carrier classes track the cut: this link is now
        # hub-delivered (both directions), every other link resident
        from dragonboat_tpu import fabric as _fabric
        book = eng._link_class_book(lnode)
        la, fa = book[lid], book[frid]
        classes = _fabric.METER.snapshot()["link_classes"]
        assert classes[f"{la}->{fa}"] == "hub"
        assert classes[f"{fa}->{la}"] == "hub"

        # writes still commit, and the CUT follower still converges —
        # its replication stream now rides the host hub
        propose_retry(nh, nh.get_noop_session(1), b"during=cut")
        deadline = time.time() + 30
        while time.time() < deadline:
            if hosts[frid].stale_read(1, "during") == "cut":
                break
            time.sleep(0.05)
        assert hosts[frid].stale_read(1, "during") == "cut", (
            "cut link did not fall back to the hub")

        eng.set_link_hub_served(lnode, frid, False)
        assert not eng._dispatch.cut[lnode.lane].any()
        assert not eng._dispatch.cut[fnode.lane].any()
        classes = _fabric.METER.snapshot()["link_classes"]
        assert classes[f"{la}->{fa}"] == "resident"
        assert classes[f"{fa}->{la}"] == "resident"
        propose_retry(nh, nh.get_noop_session(1), b"post=heal")
        deadline = time.time() + 30
        while time.time() < deadline:
            if all(h.stale_read(1, "post") == "heal"
                   for h in hosts.values()):
                break
            time.sleep(0.05)
        assert all(h.stale_read(1, "post") == "heal"
                   for h in hosts.values())
    finally:
        close_all(hosts)


def test_mesh_eviction_to_host_engines():
    """Whole-group escalation: after eviction every member continues as a
    host-resident Node on its own NodeHost over the chan transport."""
    hosts = make_cluster(f"mshE{time.monotonic_ns()}")
    try:
        lid = wait_leader(hosts, timeout=60)
        nh = hosts[lid]
        propose_retry(nh, nh.get_noop_session(1), b"pre=evict")
        # wait for the write to reach every replica's SM before evicting
        deadline = time.time() + 10
        while time.time() < deadline:
            if all(h.stale_read(1, "pre") == "evict" for h in hosts.values()):
                break
            time.sleep(0.05)
        eng = nh.mesh_engine
        knode = eng.by_shard[(1, lid)]
        with eng.mu:
            eng._evict(knode, reason="test")
        assert all((1, r) not in eng.by_shard for r in (1, 2, 3))
        for h in hosts.values():
            assert h.nodes[1].peer is not None  # host-resident now
        assert nh.stale_read(1, "pre") == "evict"
        # the group keeps serving over the regular transport
        deadline = time.time() + 40
        ok = False
        while time.time() < deadline and not ok:
            try:
                nh2 = hosts[wait_leader(hosts, timeout=10)]
                nh2.sync_propose(nh2.get_noop_session(1), b"post=evict",
                                 timeout_s=3)
                ok = nh2.sync_read(1, "post", timeout_s=3) == "evict"
            except Exception:
                time.sleep(0.2)
        assert ok
    finally:
        close_all(hosts)


def test_mesh_restart_from_disk(tmp_path):
    """Durable mesh shards: close every host, reopen, rows re-inject from
    tan state with data intact."""
    dirs = {r: str(tmp_path / f"nh{r}") for r in (1, 2, 3)}
    name = f"mshR{time.monotonic_ns()}"
    hosts = make_cluster(name, node_host_dirs=dirs)
    try:
        lid = wait_leader(hosts, timeout=60)
        sess = hosts[lid].get_noop_session(1)
        for i in range(8):
            propose_retry(hosts[lid], sess, f"d{i}=v{i}".encode())
        deadline = time.time() + 10
        while time.time() < deadline:
            if all(h.stale_read(1, "d7") == "v7" for h in hosts.values()):
                break
            time.sleep(0.05)
    finally:
        close_all(hosts)

    hosts = make_cluster(name, node_host_dirs=dirs)
    try:
        lid = wait_leader(hosts, timeout=60)
        deadline = time.time() + 15
        while time.time() < deadline:
            if hosts[lid].stale_read(1, "d7") == "v7":
                break
            time.sleep(0.05)
        for i in range(8):
            assert hosts[lid].stale_read(1, f"d{i}") == f"v{i}", i
        propose_retry(hosts[lid], hosts[lid].get_noop_session(1), b"dz=zz")
        assert hosts[lid].sync_read(1, "dz", timeout_s=10) == "zz"
    finally:
        close_all(hosts)


def test_mesh_group_with_witness_member_escalates_to_host(tmp_path):
    """Witness replicas are never mesh-resident, so a mesh group that
    gains a witness member must leave the mesh (host engines serve
    witnesses); staying would blackhole all witness-bound traffic.

    The mesh is (g=2, r=4) so witness id 4 is INSIDE mesh addressing —
    only the witness-specific guard can evict.  The restart then checks
    the admission-time twin: rebuilding from the durable membership must
    refuse the mesh and fall back host-side."""
    prefix = f"mshW{time.monotonic_ns()}"
    spec = MeshSpec(name=prefix, g_size=2, replicas=4, n_local=2)
    addrs = {i: f"{prefix}-{i}" for i in (1, 2, 3)}
    dirs = {i: str(tmp_path / f"nh{i}") for i in (1, 2, 3)}
    def mk(rid):
        nh = NodeHost(NodeHostConfig(
            raft_address=addrs[rid], rtt_millisecond=5,
            node_host_dir=dirs[rid],
            expert=ExpertConfig(mesh=spec, kernel_log_cap=256,
                                kernel_apply_batch=16,
                                kernel_compaction_overhead=16)))
        nh.start_replica(addrs, False, KVStateMachine, Config(
            shard_id=1, replica_id=rid, election_rtt=10, heartbeat_rtt=2,
            mesh_resident=True))
        return nh
    hosts = {rid: mk(rid) for rid in (1, 2, 3)}
    try:
        lid = wait_leader(hosts, timeout=60)
        nh = hosts[lid]
        assert (1, lid) in nh.mesh_engine.by_shard  # really on the mesh
        propose_retry(nh, nh.get_noop_session(1), b"pre=wit")
        waddr = f"{prefix}-w"
        deadline = time.time() + 30
        while True:
            try:
                nh.sync_request_add_witness(1, 4, waddr, 0, timeout_s=5)
                break
            except (RequestDroppedError, RequestTimeoutError):
                if time.time() > deadline:
                    raise
                time.sleep(0.2)
        deadline = time.time() + 20
        while time.time() < deadline:
            if all((1, r) not in nh.mesh_engine.by_shard for r in (1, 2, 3)):
                break
            time.sleep(0.05)
        assert all((1, r) not in nh.mesh_engine.by_shard for r in (1, 2, 3)), \
            "group with witness member stayed mesh-resident"
        # and it keeps serving from the host engines
        deadline = time.time() + 40
        ok = False
        while time.time() < deadline and not ok:
            try:
                nh2 = hosts[wait_leader(hosts, timeout=10)]
                nh2.sync_propose(nh2.get_noop_session(1), b"post=wit",
                                 timeout_s=3)
                ok = nh2.sync_read(1, "post", timeout_s=3) == "wit"
            except Exception:
                time.sleep(0.2)
        assert ok
    finally:
        close_all(hosts)

    # restart: the durable membership carries the witness.  If the
    # recovered snapshot captured it, admission refuses the mesh
    # outright; otherwise the witness CC replays through the lane apply
    # within the first steps and the update-time guard evicts.  Either
    # way the shard must settle host-side, not stay a mesh blackhole.
    nh3 = mk(1)
    try:
        deadline = time.time() + 20
        while time.time() < deadline:
            if (nh3.mesh_engine is None
                    or (1, 1) not in nh3.mesh_engine.by_shard) \
                    and nh3.nodes[1].peer is not None:
                break
            time.sleep(0.05)
        assert nh3.mesh_engine is None \
            or (1, 1) not in nh3.mesh_engine.by_shard, \
            "witness-bearing group stayed mesh-resident after restart"
        assert nh3.nodes[1].peer is not None  # host-resident
    finally:
        nh3.close()
