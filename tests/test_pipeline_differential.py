"""Depth-1 pipelined loops vs the depth-0 serial oracle — bitwise.

The pipelined (double-pumped) bench loops fuse two protocol micro-steps
per fori_loop body (bench_loop.run_steps_pipelined and friends); the
engine's PipelineConfig depth-1 mode rides the same kernel.  The whole
carry is i32/bool (threefry included), so fusing the pair must be
bitwise-neutral: ``run_steps_pipelined(n)`` ≡ ``run_steps(2n)``
leaf-for-leaf.  Phase plan mirrors test_diff_onehot_reads_lockstep:
elect, drop storm, write load, mixed reads — ≥300 driven micro-steps,
every state leaf (and the final inbox) compared bitwise at each phase
end.

The comparison pass runs under ``capacity.METER.guard()``
(``jax.transfer_guard("disallow")``): a warm pass compiles every loop
entry and every scalar argument is pre-staged with ``jax.device_put``,
so the guarded drives must execute with no undeclared device<->host
crossing — a numpy scalar slipping into a jit call raises instead of
silently re-staging every invocation."""

import numpy as np
import pytest


@pytest.mark.parametrize("seed", [5, 42])
def test_diff_pipelined_lockstep(seed):
    import jax

    from dragonboat_tpu import capacity as _capacity
    from dragonboat_tpu.bench_loop import (
        bench_params,
        elect_all,
        make_cluster,
        run_steps,
        run_steps_mixed,
        run_steps_mixed_pipelined,
        run_steps_pipelined,
        run_steps_storm,
        run_steps_storm_pipelined,
    )

    kp = bench_params(3)
    state0, box0 = elect_all(kp, 3, make_cluster(kp, 64, 3))
    snap = lambda t: jax.tree_util.tree_map(np.asarray, t)  # noqa: E731
    # traced scalars staged once, outside the guard: each one passed as
    # a raw Python/numpy scalar would be a fresh host->device transfer
    # on every jit call
    t_on = jax.device_put(True)
    drop_p = jax.device_put(np.float32(0.25))
    seed_dev = jax.device_put(np.int32(seed))
    now0 = jax.device_put(np.int32(7))
    reads0 = jax.device_put(np.int32(0))
    width = max(1, kp.proposal_cap // 8)  # static argnum: never crosses

    def drive_serial():
        state, box = state0, box0
        snaps = [snap(state)]
        state, box = run_steps_storm(kp, 3, 100, drop_p, seed_dev,
                                     state, box)
        snaps.append(snap(state))
        state, box = run_steps(kp, 3, 100, t_on, t_on, state, box)
        snaps.append(snap(state))
        state, box, reads = run_steps_mixed(
            kp, 3, 100, width, now0, state, box, reads0)
        snaps.append(snap(state))
        with _capacity.METER.sanctioned("retire"):
            return snaps, snap(box), int(reads)

    def drive_pipelined():
        state, box = state0, box0
        snaps = [snap(state)]
        state, box = run_steps_storm_pipelined(
            kp, 3, 50, drop_p, seed_dev, state, box)
        snaps.append(snap(state))
        state, box = run_steps_pipelined(kp, 3, 50, t_on, t_on, state, box)
        snaps.append(snap(state))
        state, box, reads = run_steps_mixed_pipelined(
            kp, 3, 50, width, now0, state, box, reads0)
        snaps.append(snap(state))
        with _capacity.METER.sanctioned("retire"):
            return snaps, snap(box), int(reads)

    drive_serial(), drive_pipelined()  # warm: compile outside the guard
    with _capacity.METER.guard():
        a, box_a, reads_a = drive_serial()
        b, box_b, reads_b = drive_pipelined()
    phases = ("elect", "storm", "write", "mixed")
    for phase, sa, sb in zip(phases, a, b):
        for name, va, vb in zip(sa._fields, sa, sb):
            assert np.array_equal(va, vb), \
                f"phase {phase} field {name} diverged (seed {seed})"
    for name, va, vb in zip(box_a._fields, box_a, box_b):
        if va is None and vb is None:
            continue
        assert np.array_equal(va, vb), \
            f"final inbox field {name} diverged (seed {seed})"
    assert reads_a == reads_b, "completed-read counters diverged"
