"""Tracing hooks: step-latency accounting and profiler span no-ops."""

from dragonboat_tpu.events import Metrics
from dragonboat_tpu.tracing import StepTimer, annotate


def test_step_timer_feeds_metrics():
    m = Metrics()
    t = StepTimer(m, "engine.test")
    for _ in range(3):
        with t.measure():
            pass
    snap = m.snapshot()
    assert snap["engine.test.steps"] == 3
    assert snap["engine.test.total_us"] >= 0
    assert "engine.test.ewma_us" in snap
    assert snap["engine.test.max_us"] >= snap["engine.test.ewma_us"] // 2


def test_annotate_is_safe_without_capture():
    with annotate("noop-span"):
        x = 1 + 1
    assert x == 2
