"""Tracing hooks: step-latency accounting and profiler span no-ops."""

import pytest

from dragonboat_tpu import tracing
from dragonboat_tpu.events import Metrics
from dragonboat_tpu.tracing import StepTimer, annotate


def test_step_timer_feeds_metrics():
    m = Metrics()
    t = StepTimer(m, "engine.test")
    for _ in range(3):
        with t.measure():
            pass
    snap = m.snapshot()
    assert snap["engine.test.steps"] == 3
    assert snap["engine.test.total_us"] >= 0
    assert "engine.test.ewma_us" in snap
    assert snap["engine.test.max_us"] >= snap["engine.test.ewma_us"] // 2
    # the typed registry also collects per-step latency as a histogram
    assert snap["engine.test.latency_us.count"] == 3


def test_annotate_is_safe_without_capture():
    with annotate("noop-span"):
        x = 1 + 1
    assert x == 2


def test_double_start_trace_raises(tmp_path, monkeypatch):
    """A second start_trace while one is active must raise a clear
    error instead of silently clobbering _active_trace_dir (which would
    make stop_trace report the wrong capture directory)."""
    monkeypatch.setattr(tracing, "_active_trace_dir", str(tmp_path / "a"))
    with pytest.raises(RuntimeError, match="already active"):
        tracing.start_trace(str(tmp_path / "b"))
