"""Tracing hooks: step-latency accounting and profiler span no-ops."""

import contextlib
import itertools
import time

import pytest

from dragonboat_tpu import tracing
from dragonboat_tpu.events import Metrics
from dragonboat_tpu.tracing import StepTimer, annotate


def test_step_timer_feeds_metrics():
    m = Metrics()
    t = StepTimer(m, "engine.test")
    for _ in range(3):
        with t.measure():
            pass
    snap = m.snapshot()
    assert snap["engine.test.steps"] == 3
    assert snap["engine.test.total_us"] >= 0
    assert "engine.test.ewma_us" in snap
    assert snap["engine.test.max_us"] >= snap["engine.test.ewma_us"] // 2
    # the typed registry also collects per-step latency as a histogram
    assert snap["engine.test.latency_us.count"] == 3


def test_step_timer_ewma_and_max_accounting(monkeypatch):
    """EWMA: the first sample seeds it directly, later samples fold in
    at 0.9/0.1; max tracks the largest sample.  perf_counter is stubbed
    with a deterministic schedule so the arithmetic is exact."""
    # three measures of 100us, 200us, 50us: each measure() reads the
    # clock twice (entry, exit)
    ticks = iter([0.0, 100e-6,
                  1.0, 1.0 + 200e-6,
                  2.0, 2.0 + 50e-6])
    monkeypatch.setattr(time, "perf_counter", lambda: next(ticks))
    m = Metrics()
    t = StepTimer(m, "engine.test2")
    for _ in range(3):
        with t.measure():
            pass
    # 100 seeds; then 0.9*100+0.1*200 = 110; then 0.9*110+0.1*50 = 104
    # (int truncation of the float microsecond values allows 1us slack)
    assert t._ewma_us == pytest.approx(104.0, abs=0.5)
    assert t._max_us == pytest.approx(200, abs=1)
    snap = m.snapshot()
    assert snap["engine.test2.steps"] == 3
    assert snap["engine.test2.total_us"] == pytest.approx(350, abs=3)
    assert snap["engine.test2.ewma_us"] == pytest.approx(104, abs=1)
    assert snap["engine.test2.max_us"] == pytest.approx(200, abs=1)


def test_annotate_is_safe_without_capture():
    with annotate("noop-span"):
        x = 1 + 1
    assert x == 2


def test_annotate_is_nullcontext_without_capture(monkeypatch):
    """With no active capture, annotate must return a plain
    nullcontext — no jax import, no TraceAnnotation object (the hot
    path relies on this being free)."""
    monkeypatch.setattr(tracing, "_active_trace_dir", None)
    cm = annotate("should-be-free")
    assert isinstance(cm, contextlib.nullcontext)


def test_monotonic_us_is_monotone():
    a = tracing.monotonic_us()
    b = tracing.monotonic_us()
    assert isinstance(a, int) and b >= a >= 0


class _FakeProfiler:
    """Stands in for jax.profiler: records start/stop calls."""

    def __init__(self):
        self.calls = []

    def start_trace(self, d):
        self.calls.append(("start", d))

    def stop_trace(self):
        self.calls.append(("stop", None))


@pytest.fixture
def fake_profiler(monkeypatch):
    import jax

    fake = _FakeProfiler()
    monkeypatch.setattr(jax, "profiler", fake)
    monkeypatch.setattr(tracing, "_active_trace_dir", None)
    monkeypatch.setattr(tracing, "_env_armed", False)
    yield fake
    # never leak an armed capture into the next test
    tracing._active_trace_dir = None
    tracing._env_armed = False


def test_stop_env_trace_ignores_user_capture(fake_profiler, tmp_path):
    """A capture the user started with start_trace is NOT env-armed:
    stop_env_trace must leave it running (the user owns its lifetime)."""
    tracing.start_trace(str(tmp_path))
    assert tracing.stop_env_trace() is None
    assert tracing._active_trace_dir == str(tmp_path)
    assert tracing.stop_trace() == str(tmp_path)


def test_engine_close_stops_env_armed_trace(fake_profiler, tmp_path,
                                            monkeypatch):
    """Regression (satellite): an env-armed capture must be stopped and
    flushed by engine close(), not left to atexit ordering."""
    from dragonboat_tpu.core import params as KP
    from dragonboat_tpu.engine.kernel_engine import KernelEngine

    d = str(tmp_path / "cap")
    monkeypatch.setenv("DRAGONBOAT_TPU_TRACE_DIR", d)
    eng = KernelEngine(KP.KernelParams(), capacity=4, send_message=None)
    assert tracing._active_trace_dir == d
    assert tracing._env_armed
    eng.close()
    assert tracing._active_trace_dir is None
    assert not tracing._env_armed
    assert ("start", d) in fake_profiler.calls
    assert ("stop", None) in fake_profiler.calls
    # idempotent: a second close must not double-stop
    eng.close()
    assert fake_profiler.calls.count(("stop", None)) == 1


def test_double_start_trace_raises(tmp_path, monkeypatch):
    """A second start_trace while one is active must raise a clear
    error instead of silently clobbering _active_trace_dir (which would
    make stop_trace report the wrong capture directory)."""
    monkeypatch.setattr(tracing, "_active_trace_dir", str(tmp_path / "a"))
    with pytest.raises(RuntimeError, match="already active"):
        tracing.start_trace(str(tmp_path / "b"))
