"""Linearizability: history recording, the built-in register checker,
and an E2E chaos run with concurrent clients across a partition.

Reference behavior: docs/test.md — client histories recorded under
monkey tests and checked with Knossos/porcupine; the built-in checker
plays porcupine's role for test-sized histories.
"""

import json
import threading
import time

from dragonboat_tpu.history import HistoryRecorder, Op, check_linearizable_kv

from test_monkey import _mk
from test_nodehost import wait_leader


def _op(process, op, key, value, call, ret, ok=True):
    return Op(process=process, op=op, key=key, value=value, call=call,
              ret=ret, ok=ok)


def test_checker_accepts_sequential_history():
    ops = [
        _op(1, "write", "k", "a", 0.0, 1.0),
        _op(2, "read", "k", "a", 2.0, 3.0),
        _op(1, "write", "k", "b", 4.0, 5.0),
        _op(2, "read", "k", "b", 6.0, 7.0),
    ]
    assert check_linearizable_kv(ops)


def test_checker_rejects_stale_read():
    ops = [
        _op(1, "write", "k", "a", 0.0, 1.0),
        _op(1, "write", "k", "b", 2.0, 3.0),
        # reads AFTER write b completed must not see a
        _op(2, "read", "k", "a", 4.0, 5.0),
    ]
    assert not check_linearizable_kv(ops)


def test_checker_allows_concurrent_read_either_value():
    ops = [
        _op(1, "write", "k", "a", 0.0, 1.0),
        _op(1, "write", "k", "b", 2.0, 6.0),
        _op(2, "read", "k", "a", 3.0, 4.0),   # concurrent with write b
        _op(3, "read", "k", "b", 3.5, 5.0),   # also fine: b linearized first
    ]
    assert check_linearizable_kv(ops)
    # but once a read saw b, a LATER read may not see a again
    bad = ops + [_op(2, "read", "k", "a", 5.5, 7.0)]
    assert not check_linearizable_kv(bad)


def test_checker_open_write_may_or_may_not_apply():
    ops = [
        _op(1, "write", "k", "a", 0.0, 1.0),
        _op(1, "write", "k", "b", 2.0, None),  # timed out: unknown
        _op(2, "read", "k", "a", 3.0, 4.0),    # ok if b never applied
    ]
    assert check_linearizable_kv(ops)
    ops2 = [
        _op(1, "write", "k", "a", 0.0, 1.0),
        _op(1, "write", "k", "b", 2.0, None),
        _op(2, "read", "k", "b", 3.0, 4.0),    # ok if b DID apply
    ]
    assert check_linearizable_kv(ops2)


def test_export_jsonl(tmp_path):
    h = HistoryRecorder()
    r = h.invoke(1, "write", "k", "v1")
    h.complete(r)
    r2 = h.invoke(2, "read", "k")
    h.complete(r2, value="v1")
    r3 = h.invoke(3, "write", "k", "v2")  # left open (timeout)
    path = str(tmp_path / "history.jsonl")
    h.export_jsonl(path)
    lines = [json.loads(line) for line in open(path)]
    assert len(lines) == 3
    assert lines[0] == {"process": 1, "op": "write", "key": "k",
                        "value": "v1", "call": lines[0]["call"],
                        "return": lines[0]["return"], "ok": True}
    assert lines[2]["return"] is None and lines[2]["ok"] is None
    assert r3.ret is None


def test_e2e_history_linearizable_across_partition():
    """Concurrent writers+readers against a 3-replica cluster while the
    leader is partitioned away mid-run; the recorded history must be
    linearizable (the monkey harness's core assertion, docs/test.md)."""
    hosts = _mk(f"hl{time.monotonic_ns()}")
    h = HistoryRecorder()
    stop = threading.Event()

    def client(pid: int) -> None:
        seq = 0
        while not stop.is_set():
            lid = None
            for rid, nh in hosts.items():
                got, ok = nh.get_leader_id(1)
                if ok and got in hosts:
                    lid = got
                    break
            if lid is None:
                time.sleep(0.02)
                continue
            nh = hosts[lid]
            try:
                if pid % 2 == 0:
                    val = f"p{pid}s{seq}"
                    seq += 1
                    rec = h.invoke(pid, "write", "x", val)
                    try:
                        nh.sync_propose(nh.get_noop_session(1),
                                        f"x={val}".encode(), timeout_s=1.0)
                        h.complete(rec)
                    except Exception:
                        pass  # open: outcome unknown
                else:
                    rec = h.invoke(pid, "read", "x")
                    try:
                        v = nh.sync_read(1, "x", timeout_s=1.0)
                        h.complete(rec, value=v)
                    except Exception:
                        pass
            except Exception:
                pass
            time.sleep(0.01)

    threads = [threading.Thread(target=client, args=(p,), daemon=True)
               for p in range(4)]
    try:
        wait_leader(hosts)
        for t in threads:
            t.start()
        time.sleep(1.5)
        lid = wait_leader(hosts)
        hosts[lid].partition_node()   # chaos mid-run
        time.sleep(1.5)
        hosts[lid].restore_partitioned_node()
        time.sleep(1.0)
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=5)
        for nh in hosts.values():
            nh.close()

    completed = [o for o in h.ops if o.ret is not None]
    assert len(completed) >= 10, "history too thin to mean anything"
    assert check_linearizable_kv(h.ops, initial=None), \
        "linearizability violation in recorded history"
