"""Test config: run everything on a virtual 8-device CPU mesh.

Multi-chip hardware is not available in CI; shardings are validated on a
host-platform device mesh (the driver separately dry-runs multichip via
``__graft_entry__.dryrun_multichip``).
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platform_name", "cpu")
