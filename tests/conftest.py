"""Test config: run everything on a virtual 8-device CPU mesh.

Multi-chip hardware is not available in CI; shardings are validated on a
host-platform device mesh (the driver separately dry-runs multichip via
``__graft_entry__.dryrun_multichip``).
"""

import os
import sys

# force CPU: the ambient environment may export JAX_PLATFORMS=axon (the real
# TPU); unit tests always run on the virtual host-platform mesh
os.environ["JAX_PLATFORMS"] = "cpu"
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# hermetic CPU: drop accelerator backend factories registered by the ambient
# environment (the axon TPU plugin initializes its PJRT client on ANY
# backends() call regardless of JAX_PLATFORMS — if the TPU tunnel is wedged,
# that init blocks forever and would hang the whole suite)
import jax._src.xla_bridge as _xb  # noqa: E402

# pop ONLY the axon plugin: removing "tpu"/"cuda" from the factory map
# also erases those names from jax's known-platform registry, which
# breaks importing jax.experimental.pallas (its TPU lowering rules
# register against the "tpu" platform name)
for _plat in ("axon",):
    _xb._backend_factories.pop(_plat, None)

# the ambient JAX_PLATFORMS=axon was latched when the sitecustomize imported
# jax at interpreter start — os.environ edits above are too late; override
# through the config API
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_platform_name", "cpu")
# persistent compile cache: the batched step kernel takes ~10-30s to compile;
# cache it across pytest runs.  The dir is fingerprinted by CPU features
# (build rounds hop machines — hostenv.jax_cache_dir)
from dragonboat_tpu.hostenv import (  # noqa: E402
    jax_cache_dir as _jax_cache_dir,
    purge_donated_cache_entries as _purge_donated,
)

_cache_dir = _jax_cache_dir()
# donated executables must compile fresh each process: jax 0.4.37's cache
# DESERIALIZATION breaks their buffer aliasing (wrong results, then a
# segfault on the first result read) — see hostenv.purge_donated_cache_entries
_purge_donated(_cache_dir)
jax.config.update("jax_compilation_cache_dir", _cache_dir)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)


# -- one-retry for timing-sensitive E2E modules -------------------------------
# This box has ONE core; the multi-NodeHost E2E tests run dozens of engine
# threads against wall-clock deadlines and occasionally miss them under
# full-suite load.  A failed test from these modules is retried once —
# a deterministic regression still fails twice and stays red.

_RETRY_MODULES = (
    "test_nodehost", "test_node_ops", "test_tcp_transport", "test_gossip",
    "test_durable_nodehost", "test_monkey", "test_vfs",
    "test_snapshot_stream", "test_kernel_engine", "test_tools",
    "test_history", "test_tan", "test_encoded", "test_examples",
    "test_chaos_faults", "test_chaos_schedules", "test_health",
)

# module -> number of tests that needed the second attempt, THIS process.
# The silent-rerun policy above hides flake from the pass/fail signal, so
# this tally is the visibility valve: the terminal summary prints it,
# tests/.retry_report.json accumulates it across run_tests.sh's chunked
# pytest processes, and a module leaning on the crutch more than
# _RETRY_LIMIT times fails the run — "flaky but green" may not trend.
_RETRY_STATS: dict = {}
_RETRY_LIMIT = 3
_RETRY_REPORT = os.path.join(os.path.dirname(__file__),
                             ".retry_report.json")


def pytest_runtest_protocol(item, nextitem):
    from _pytest.runner import runtestprotocol

    if item.module.__name__ not in _RETRY_MODULES:
        return None
    item.ihook.pytest_runtest_logstart(nodeid=item.nodeid,
                                       location=item.location)
    reports = runtestprotocol(item, nextitem=nextitem, log=False)
    if any(r.failed for r in reports):
        mod = item.module.__name__
        _RETRY_STATS[mod] = _RETRY_STATS.get(mod, 0) + 1
        reports = runtestprotocol(item, nextitem=nextitem, log=False)
    for r in reports:
        item.ihook.pytest_runtest_logreport(report=r)
    item.ihook.pytest_runtest_logfinish(nodeid=item.nodeid,
                                        location=item.location)
    return True


_RETRY_MERGED: dict = {}     # computed once at sessionfinish


def _merged_retry_report() -> dict:
    """This process's tally merged into the on-disk report, computed at
    most once (sessionfinish rewrites the file, so a second merge would
    double-count this process).  Merging is opt-in via
    DBT_RETRY_REPORT_MERGE (run_tests.sh removes the file at run start
    and sets the flag for its chunked pytest processes); a bare
    ``pytest`` invocation overwrites, so a stale file from an old run
    can never fail a fresh one."""
    import json

    if _RETRY_MERGED.get("done"):
        return _RETRY_MERGED["report"]
    merged: dict = {}
    if os.environ.get("DBT_RETRY_REPORT_MERGE") == "1":
        try:
            with open(_RETRY_REPORT) as f:
                merged = {str(k): int(v) for k, v in json.load(f).items()}
        except (OSError, ValueError):
            merged = {}
    for mod, n in _RETRY_STATS.items():
        merged[mod] = merged.get(mod, 0) + n
    _RETRY_MERGED["done"] = True
    _RETRY_MERGED["report"] = merged
    return merged


def pytest_sessionfinish(session, exitstatus):
    import json

    merged = _merged_retry_report()
    if not merged and not os.path.exists(_RETRY_REPORT):
        return
    try:
        with open(_RETRY_REPORT, "w") as f:
            json.dump(merged, f, indent=2, sort_keys=True)
    except OSError:
        pass
    if exitstatus == 0 and any(n > _RETRY_LIMIT for n in merged.values()):
        session.exitstatus = 1


def pytest_terminal_summary(terminalreporter, exitstatus, config):
    merged = _merged_retry_report()
    if not merged:
        return
    terminalreporter.section("flaky-retry tally")
    for mod in sorted(merged):
        here = _RETRY_STATS.get(mod, 0)
        over = " OVER LIMIT" if merged[mod] > _RETRY_LIMIT else ""
        terminalreporter.write_line(
            f"{mod}: {merged[mod]} retried test(s)"
            f" ({here} this process, limit {_RETRY_LIMIT}){over}")
    if any(n > _RETRY_LIMIT for n in merged.values()):
        terminalreporter.write_line(
            "FAILING RUN: retry budget exceeded — fix the flake or the "
            "test; the silent rerun is a crutch, not a policy.")


_age_counter = {"n": 0, "cleared": 0}

# The "late-process XLA abort" (run_tests.sh header) ROOT CAUSE,
# diagnosed 2026-07-31 by sampling /proc/self/maps across a full run:
# every jitted executable pins mmap'd code/cache segments in jax's
# process-wide caches, and this suite compiles hundreds of distinct
# kernel geometries — the map count crosses vm.max_map_count (65,530
# here) at almost exactly the historical crash position (64,733 maps at
# test 331 vs the deterministic ~340-test SIGABRT/SIGSEGV).  When the
# next compile/cache-load can't mmap, XLA dies inside
# backend_compile/deserialize.  The fence below drops the in-process
# executable caches before the limit; the persistent on-disk compile
# cache makes the re-loads cheap.  Not a product concern at deployment
# shapes (a serving host compiles a handful of geometries), but any
# long-lived process creating hundreds would want the same guard.
_MAP_FENCE = int(os.environ.get("DBT_MAP_FENCE", "45000"))


def _map_count() -> int:
    try:
        with open("/proc/self/maps", "rb") as f:
            return f.read().count(b"\n")
    except OSError:
        return -1


def pytest_runtest_setup(item):
    _age_counter["n"] += 1
    n = _age_counter["n"]
    if _MAP_FENCE and n % 5 == 0:
        maps = _map_count()
        if maps > _MAP_FENCE:
            jax.clear_caches()
            _age_counter["cleared"] += 1
            sys.stderr.write(
                f"\n[conftest] map-count fence: {maps} maps > "
                f"{_MAP_FENCE}, cleared jax caches "
                f"(#{_age_counter['cleared']})\n")
    # DBT_AGE_LOG=1: append (test#, rss, maps, threads, fds) every 10
    # tests — the diagnostic curve this fence was built from
    if os.environ.get("DBT_AGE_LOG") != "1":
        return
    if n % 10 != 1:
        return
    import resource
    import threading

    try:
        fds = len(os.listdir("/proc/self/fd"))
    except OSError:
        fds = -1
    rss = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    with open("/tmp/dbt_age.log", "a") as f:
        f.write(f"{n} rss_mb={rss // 1024} maps={_map_count()} "
                f"threads={threading.active_count()}"
                f" fds={fds} test={item.nodeid}\n")


def pytest_collection_modifyitems(session, config, items):
    """Big-shape jit tests run FIRST.

    Compiling — or even cache-LOADING — the large kernel executables
    (1k-lane engines, the 8-device mesh) after ~340 tests of process
    aging aborts inside XLA's compile/deserialize path (diagnosed
    2026-07-31: deterministic SIGABRT/SIGSEGV at the same collection
    position across four full-suite runs, while every subset and a
    fresh process pass).  A fresh process handles the big shapes
    reliably, so they go to the front of the run."""
    big = [it for it in items if "test_zz_" in it.nodeid]
    if big:
        rest = [it for it in items if "test_zz_" not in it.nodeid]
        items[:] = big + rest
