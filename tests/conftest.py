"""Test config: run everything on a virtual 8-device CPU mesh.

Multi-chip hardware is not available in CI; shardings are validated on a
host-platform device mesh (the driver separately dry-runs multichip via
``__graft_entry__.dryrun_multichip``).
"""

import os

# force CPU: the ambient environment may export JAX_PLATFORMS=axon (the real
# TPU); unit tests always run on the virtual host-platform mesh
os.environ["JAX_PLATFORMS"] = "cpu"
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# hermetic CPU: drop accelerator backend factories registered by the ambient
# environment (the axon TPU plugin initializes its PJRT client on ANY
# backends() call regardless of JAX_PLATFORMS — if the TPU tunnel is wedged,
# that init blocks forever and would hang the whole suite)
import jax._src.xla_bridge as _xb  # noqa: E402

# pop ONLY the axon plugin: removing "tpu"/"cuda" from the factory map
# also erases those names from jax's known-platform registry, which
# breaks importing jax.experimental.pallas (its TPU lowering rules
# register against the "tpu" platform name)
for _plat in ("axon",):
    _xb._backend_factories.pop(_plat, None)

# the ambient JAX_PLATFORMS=axon was latched when the sitecustomize imported
# jax at interpreter start — os.environ edits above are too late; override
# through the config API
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_platform_name", "cpu")
# persistent compile cache: the batched step kernel takes ~10-30s to compile;
# cache it across pytest runs.  The dir is fingerprinted by CPU features
# (build rounds hop machines — hostenv.jax_cache_dir)
from dragonboat_tpu.hostenv import jax_cache_dir as _jax_cache_dir

jax.config.update("jax_compilation_cache_dir", _jax_cache_dir())
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)


# -- one-retry for timing-sensitive E2E modules -------------------------------
# This box has ONE core; the multi-NodeHost E2E tests run dozens of engine
# threads against wall-clock deadlines and occasionally miss them under
# full-suite load.  A failed test from these modules is retried once —
# a deterministic regression still fails twice and stays red.

_RETRY_MODULES = (
    "test_nodehost", "test_node_ops", "test_tcp_transport", "test_gossip",
    "test_durable_nodehost", "test_monkey", "test_vfs",
    "test_snapshot_stream", "test_kernel_engine", "test_tools",
    "test_history",
)


def pytest_runtest_protocol(item, nextitem):
    from _pytest.runner import runtestprotocol

    if item.module.__name__ not in _RETRY_MODULES:
        return None
    item.ihook.pytest_runtest_logstart(nodeid=item.nodeid,
                                       location=item.location)
    reports = runtestprotocol(item, nextitem=nextitem, log=False)
    if any(r.failed for r in reports):
        reports = runtestprotocol(item, nextitem=nextitem, log=False)
    for r in reports:
        item.ihook.pytest_runtest_logreport(report=r)
    item.ihook.pytest_runtest_logfinish(nodeid=item.nodeid,
                                        location=item.location)
    return True


def pytest_collection_modifyitems(session, config, items):
    """Big-shape jit tests run FIRST.

    Compiling — or even cache-LOADING — the large kernel executables
    (1k-lane engines, the 8-device mesh) after ~340 tests of process
    aging aborts inside XLA's compile/deserialize path (diagnosed
    2026-07-31: deterministic SIGABRT/SIGSEGV at the same collection
    position across four full-suite runs, while every subset and a
    fresh process pass).  A fresh process handles the big shapes
    reliably, so they go to the front of the run."""
    big = [it for it in items if "test_zz_" in it.nodeid]
    if big:
        rest = [it for it in items if "test_zz_" not in it.nodeid]
        items[:] = big + rest
