"""Streaming snapshot save: on-disk SMs stream a live image directly into
transport chunks — no sender-side snapshot file.

Reference behaviors: internal/rsm/chunkwriter.go (block stream into
chunks), statemachine.go:568 (Stream), nodehost.go:1888-1891 (on-disk SM
InstallSnapshot goes through the streaming sink), chunk.go (receiver
reassembly keyed on the tail chunk).
"""

import io
import struct
import time

from dragonboat_tpu import raftpb as pb
from dragonboat_tpu.config import Config, NodeHostConfig
from dragonboat_tpu.nodehost import NodeHost
from dragonboat_tpu.rsm.chunkwriter import ChunkWriter
from dragonboat_tpu.rsm.snapshotio import read_snapshot
from dragonboat_tpu.rsm.statemachine import StateMachine
from dragonboat_tpu.statemachine import IOnDiskStateMachine, Result
from dragonboat_tpu.transport.chunks import ChunkSink

from test_nodehost import wait_leader


class DiskKV(IOnDiskStateMachine):
    """In-memory stand-in for an on-disk SM (FakeDiskSM, fakedisk.go:28)."""

    def __init__(self, *a):
        self.kv = {}
        self.applied = 0

    def open(self, stopc):
        return self.applied

    def update(self, entries):
        out = []
        for e in entries:
            k, v = e.cmd.decode().split("=", 1)
            self.kv[k] = v
            self.applied = e.index
            out.append(type(e)(index=e.index, cmd=e.cmd,
                               result=Result(value=len(self.kv))))
        return out

    def lookup(self, q):
        return self.kv.get(q)

    def sync(self):
        pass

    def prepare_snapshot(self):
        return dict(self.kv)

    def save_snapshot(self, ctx, w, done):
        d = "\n".join(f"{k}={v}" for k, v in sorted(ctx.items())).encode()
        w.write(struct.pack("<I", len(d)))
        w.write(d)

    def recover_from_snapshot(self, r, done):
        (n,) = struct.unpack("<I", r.read(4))
        self.kv = dict(
            line.split("=", 1)
            for line in r.read(n).decode().split("\n") if line
        )


def test_chunkwriter_stream_reassembles(tmp_path):
    """stream_snapshot → ChunkWriter(small chunks) → ChunkSink → the
    reassembled file recovers through the ordinary read path."""
    sm = StateMachine(1, 1, DiskKV())
    for i in range(50):
        sm.handle([pb.Entry(term=1, index=i + 1,
                            cmd=f"k{i}=v{i}".encode())])

    delivered = []
    sink = ChunkSink(snapshot_dir=str(tmp_path), deployment_id=7,
                     deliver=lambda m, src: delivered.append((m, src)))
    chunks = []
    cw = ChunkWriter(chunks.append, shard_id=1, to_replica=2, from_=1,
                     deployment_id=7, source_address="src-1",
                     chunk_size=64)  # tiny chunks: force many frames

    def on_meta(index, term, membership):
        cw.index, cw.term = index, term
        cw.message = pb.Message(
            type=pb.MessageType.INSTALL_SNAPSHOT, from_=1, to=2, shard_id=1,
            snapshot=pb.Snapshot(index=index, term=term,
                                 membership=membership, shard_id=1),
        )

    index, term, _ = sm.stream_snapshot(cw, on_meta=on_meta)
    cw.close()
    assert index == 50
    assert len(chunks) > 3                      # really was split
    assert chunks[0].message is not None
    assert all(c.chunk_count == 0 for c in chunks[:-1])
    assert chunks[-1].is_last()
    assert chunks[-1].file_size == sum(len(c.data) for c in chunks)

    for c in chunks:
        assert sink.add(c), c.chunk_id
    assert len(delivered) == 1
    m, src = delivered[0]
    assert src == "src-1"
    assert m.snapshot.index == 50

    # the reassembled file is a valid container holding the image
    with open(m.snapshot.filepath, "rb") as f:
        session, payload = read_snapshot(f)
        image = payload.read()
    sm2 = DiskKV()
    sm2.recover_from_snapshot(io.BytesIO(image), lambda: False)
    assert sm2.kv["k49"] == "v49" and len(sm2.kv) == 50


def test_abandoned_stream_does_not_wedge_the_shard():
    """If the consumer abandons the stream (unresolvable target), the
    producer must unwind instead of deadlocking inside the SM apply lock."""
    nh = NodeHost(NodeHostConfig(raft_address=f"ab-{time.time_ns()}",
                                 rtt_millisecond=5))
    nh.start_replica({1: nh.config.raft_address}, False, DiskKV, Config(
        shard_id=1, replica_id=1, election_rtt=10, heartbeat_rtt=1))
    try:
        deadline = time.time() + 10
        while time.time() < deadline and not nh.get_leader_id(1)[1]:
            time.sleep(0.02)
        s = nh.get_noop_session(1)
        for i in range(200):  # image big enough to overflow the chunk queue
            nh.sync_propose(s, (f"a{i}=" + "x" * 200).encode())
        node = nh._node(1)
        m = pb.Message(type=pb.MessageType.INSTALL_SNAPSHOT, from_=1,
                       to=99, shard_id=1)  # replica 99 resolves nowhere
        nh._stream_snapshot(node, m)
        # the shard must keep serving (apply lock released)
        deadline = time.time() + 5
        ok = False
        while time.time() < deadline and not ok:
            try:
                nh.sync_propose(s, b"alive=yes")
                ok = True
            except Exception:
                time.sleep(0.05)
        assert ok, "shard wedged after abandoned stream"
        assert nh.sync_read(1, "alive") == "yes"
    finally:
        nh.close()


def test_ondisk_lagger_catches_up_via_live_stream(monkeypatch):
    """E2E: an offline replica of an on-disk SM falls behind a compacted
    log; on return the leader live-streams the image (stream_snapshot is
    exercised, not the file path) and the lagger recovers."""
    calls = {"n": 0}
    orig = StateMachine.stream_snapshot

    def counting(self, w, on_meta=None):
        calls["n"] += 1
        return orig(self, w, on_meta=on_meta)

    monkeypatch.setattr(StateMachine, "stream_snapshot", counting)

    addrs = {i: f"stream-{i}" for i in (1, 2, 3)}
    hosts = {}
    for rid, addr in addrs.items():
        nh = NodeHost(NodeHostConfig(raft_address=addr, rtt_millisecond=5))
        nh.start_replica(addrs, False, DiskKV, Config(
            shard_id=1, replica_id=rid, election_rtt=10, heartbeat_rtt=1,
            snapshot_entries=6, compaction_overhead=2))
        hosts[rid] = nh
    try:
        lid = wait_leader(hosts)
        lagger = next(r for r in hosts if r != lid)
        hosts[lagger].close()
        del hosts[lagger]
        s = hosts[lid].get_noop_session(1)
        for i in range(30):
            hosts[lid].sync_propose(s, f"d{i}=v{i}".encode())
        nh2 = NodeHost(NodeHostConfig(raft_address=addrs[lagger],
                                      rtt_millisecond=5))
        nh2.start_replica(addrs, False, DiskKV, Config(
            shard_id=1, replica_id=lagger, election_rtt=10, heartbeat_rtt=1,
            snapshot_entries=6, compaction_overhead=2))
        hosts[lagger] = nh2
        deadline = time.time() + 15
        while time.time() < deadline and nh2.stale_read(1, "d29") != "v29":
            time.sleep(0.05)
        assert nh2.stale_read(1, "d29") == "v29", \
            "lagger never caught up via live stream"
        assert nh2.stale_read(1, "d0") == "v0"
        assert calls["n"] >= 1, "streaming save path was not used"
    finally:
        for h in hosts.values():
            h.close()
