"""Node-level operational features: quiesce, leader-transfer completion,
log query / compaction through the engine path, event listeners, metrics.

Reference behaviors: quiesce.go + quiesce_test.go, node.go:308
(processLeaderUpdate), node.go:1238/319 (log query), node.go:972
(requestCompaction), raftio/listener.go + event.go:54-90.
"""

import threading
import time

import pytest

from dragonboat_tpu import raftpb as pb
from dragonboat_tpu.config import Config, NodeHostConfig
from dragonboat_tpu.nodehost import NodeHost
from dragonboat_tpu.quiesce import QuiesceState
from dragonboat_tpu.request import RequestError, RequestRejectedError
from dragonboat_tpu.statemachine import IStateMachine, Result

from test_nodehost import KVStateMachine, wait_leader


def make_cluster(quiesce=False, snapshot_entries=0, rtt_ms=5, prefix="ops",
                 raft_listener=None, system_listener=None, election_rtt=10):
    addrs = {i: f"{prefix}-{i}" for i in range(1, 4)}
    hosts = {}
    for rid, addr in addrs.items():
        nh = NodeHost(NodeHostConfig(
            raft_address=addr, rtt_millisecond=rtt_ms,
                        raft_event_listener=raft_listener,
            system_event_listener=system_listener,
        ))
        cfg = Config(shard_id=1, replica_id=rid, election_rtt=election_rtt,
                     heartbeat_rtt=1, snapshot_entries=snapshot_entries,
                     compaction_overhead=5, quiesce=quiesce)
        nh.start_replica(addrs, False, KVStateMachine, cfg)
        hosts[rid] = nh
    return hosts


def close_all(hosts):
    for nh in hosts.values():
        nh.close()


# ---------------------------------------------------------------------------
# QuiesceState unit behavior (quiesce_test.go analogs)
# ---------------------------------------------------------------------------


class TestQuiesceState:
    def mk(self):
        return QuiesceState(shard_id=1, replica_id=1, election_tick=10,
                            enabled=True)

    def test_enters_quiesce_after_idle_threshold(self):
        q = self.mk()
        for _ in range(q.threshold() + 1):
            assert not q.quiesced()
            q.tick()
        assert q.quiesced()
        assert q.new_quiesce_state()
        assert not q.new_quiesce_state()  # one-shot flag

    def test_activity_resets_idle_clock(self):
        q = self.mk()
        for _ in range(q.threshold() - 1):
            q.tick()
        q.record(pb.MessageType.PROPOSE)
        for _ in range(q.threshold() - 1):
            q.tick()
        assert not q.quiesced()

    def test_message_exits_quiesce(self):
        q = self.mk()
        for _ in range(q.threshold() + 1):
            q.tick()
        assert q.quiesced()
        q.record(pb.MessageType.PROPOSE)
        assert not q.quiesced()

    def test_trailing_heartbeat_does_not_wake_fresh_quiesce(self):
        q = self.mk()
        for _ in range(q.threshold() + 1):
            q.tick()
        assert q.quiesced()
        q.record(pb.MessageType.HEARTBEAT)  # inside grace window
        assert q.quiesced()
        for _ in range(q.election_tick + 1):
            q.tick()
        q.record(pb.MessageType.HEARTBEAT)  # past grace window
        assert not q.quiesced()

    def test_try_enter_quiesce_respects_recent_exit(self):
        q = self.mk()
        for _ in range(q.threshold() + 1):
            q.tick()
        q.record(pb.MessageType.PROPOSE)  # exit
        q.try_enter_quiesce()             # just exited → refuse
        assert not q.quiesced()
        for _ in range(q.threshold() + 1):
            q.tick()
        q.try_enter_quiesce()
        assert q.quiesced()

    def test_disabled_is_inert(self):
        q = QuiesceState(election_tick=10, enabled=False)
        for _ in range(1000):
            q.tick()
        assert not q.quiesced()


# ---------------------------------------------------------------------------
# End-to-end quiesce: idle cluster goes quiet, proposal wakes it
# ---------------------------------------------------------------------------


def test_cluster_quiesces_and_wakes():
    hosts = make_cluster(quiesce=True, rtt_ms=2, prefix="qui",
                         election_rtt=5)
    try:
        lead = wait_leader(hosts)
        nh = hosts[lead]
        sess = nh.get_noop_session(1)
        nh.sync_propose(sess, b"k0=v0")
        # idle long enough for every node to pass threshold (50 ticks @2ms)
        deadline = time.time() + 10
        while time.time() < deadline:
            if all(n.nodes[1].qs.quiesced() for n in hosts.values()):
                break
            time.sleep(0.05)
        assert all(n.nodes[1].qs.quiesced() for n in hosts.values()), \
            "cluster did not quiesce"
        # a quiesced shard must not hold elections: terms stay put
        terms = {r: n.nodes[1].peer.raft.term for r, n in hosts.items()}
        time.sleep(0.3)
        assert terms == {r: n.nodes[1].peer.raft.term
                         for r, n in hosts.items()}
        # a proposal wakes the group and still commits
        lead = wait_leader(hosts)
        nh = hosts[lead]
        nh.sync_propose(nh.get_noop_session(1), b"k1=v1")
        assert nh.stale_read(1, "k1") == "v1"
        assert not hosts[lead].nodes[1].qs.quiesced()
    finally:
        close_all(hosts)


# ---------------------------------------------------------------------------
# leader transfer future completion
# ---------------------------------------------------------------------------


def test_leader_transfer_future_completes():
    hosts = make_cluster(prefix="xfer")
    try:
        lead = wait_leader(hosts)
        target = next(r for r in hosts if r != lead)
        node = hosts[lead].nodes[1]
        rs = node.request_leader_transfer(target, 1000)
        hosts[lead]._work.set()
        r = rs.wait(10.0)
        assert r.code.name == "COMPLETED", r.code
        assert r.result.value == target
        assert wait_leader(hosts) == target
    finally:
        close_all(hosts)


# ---------------------------------------------------------------------------
# log query + compaction through the engine path
# ---------------------------------------------------------------------------


def test_query_raft_log_engine_path():
    hosts = make_cluster(prefix="lq")
    try:
        lead = wait_leader(hosts)
        nh = hosts[lead]
        sess = nh.get_noop_session(1)
        for i in range(5):
            nh.sync_propose(sess, f"k{i}=v{i}".encode())
        applied = nh.nodes[1].sm.get_last_applied()
        res = nh.query_raft_log(1, 1, applied + 1)
        assert res.error == 0
        assert res.entries, "no entries returned"
        assert res.entries[-1].index <= applied
        # out-of-range query → rejected
        with pytest.raises(RequestError):
            nh.query_raft_log(1, applied + 100, applied + 200, timeout_s=2.0)
    finally:
        close_all(hosts)


def test_sync_request_compaction():
    hosts = make_cluster(prefix="cpt")
    try:
        lead = wait_leader(hosts)
        nh = hosts[lead]
        # before any snapshot: nothing to compact
        with pytest.raises(RequestRejectedError):
            nh.sync_request_compaction(1, timeout_s=2.0)
        sess = nh.get_noop_session(1)
        for i in range(20):
            nh.sync_propose(sess, f"k{i}=v{i}".encode())
        nh.sync_request_snapshot(1)
        nh.sync_request_compaction(1)  # completes now
    finally:
        close_all(hosts)


# ---------------------------------------------------------------------------
# event listeners + metrics
# ---------------------------------------------------------------------------


class Recorder:
    """Records every listener callback it receives, thread-safely."""

    def __init__(self):
        self.mu = threading.Lock()
        self.calls = []

    def __getattr__(self, name):
        def cb(*args):
            with self.mu:
                self.calls.append((name, args))
        return cb

    def names(self):
        with self.mu:
            return [c[0] for c in self.calls]


def test_event_listeners_fire():
    rec_raft = Recorder()
    rec_sys = Recorder()
    hosts = make_cluster(prefix="evt", raft_listener=rec_raft,
                         system_listener=rec_sys)
    try:
        lead = wait_leader(hosts)
        nh = hosts[lead]
        sess = nh.get_noop_session(1)
        for i in range(10):
            nh.sync_propose(sess, f"k{i}=v{i}".encode())
        nh.sync_request_snapshot(1)
        deadline = time.time() + 5
        while time.time() < deadline:
            if ("leader_updated" in rec_raft.names()
                    and "snapshot_created" in rec_sys.names()):
                break
            time.sleep(0.05)
        assert "leader_updated" in rec_raft.names()
        # events include the campaign-start leader_id=0 update; the elected
        # leader must appear among them
        infos = [a[0] for n, a in rec_raft.calls if n == "leader_updated"]
        assert all(i.shard_id == 1 for i in infos)
        assert any(i.leader_id == lead for i in infos)
        sys_names = rec_sys.names()
        assert "node_ready" in sys_names
        assert "snapshot_created" in sys_names
        assert "log_compacted" in sys_names
        m = nh.metrics()
        assert m.get("raft.leader_updated", 0) >= 1
        assert m.get("snapshot.created", 0) >= 1
        assert m.get("transport.sent", 0) > 0
    finally:
        close_all(hosts)
    # shutdown events delivered before hub close
    assert "node_host_shutting_down" in rec_sys.names()
    assert "node_unloaded" in rec_sys.names()


# ---------------------------------------------------------------------------
# NotifyCommit + ingress guards (rate limiter, bounded queues)
# ---------------------------------------------------------------------------


def test_notify_commit_event_fires():
    addrs = {i: f"nc-{i}" for i in (1, 2, 3)}
    hosts = {}
    for rid, addr in addrs.items():
        nh = NodeHost(NodeHostConfig(raft_address=addr, rtt_millisecond=5,
                                     notify_commit=True))
        nh.start_replica(addrs, False, KVStateMachine, Config(
            shard_id=1, replica_id=rid, election_rtt=10, heartbeat_rtt=1))
        hosts[rid] = nh
    try:
        lead = wait_leader(hosts)
        nh = hosts[lead]
        sess = nh.get_noop_session(1)
        rs = nh.propose(sess, b"nc=1")
        assert rs.committed_event.wait(5.0), "commit notification missing"
        r = rs.wait(5.0)
        assert r.code.name == "COMPLETED"
        sess.proposal_completed()
    finally:
        for h in hosts.values():
            h.close()


def test_rate_limiter_rejects_when_full():
    from dragonboat_tpu.request import RequestDroppedError

    addrs = {1: "rl-1"}
    nh = NodeHost(NodeHostConfig(raft_address="rl-1", rtt_millisecond=5),
                  auto_run=False)   # engine stopped: nothing drains
    nh.start_replica(addrs, False, KVStateMachine, Config(
        shard_id=1, replica_id=1, election_rtt=10, heartbeat_rtt=1,
        max_in_mem_log_size=256))
    try:
        node = nh.nodes[1]
        sess = nh.get_noop_session(1)
        with pytest.raises(RequestDroppedError):
            for _ in range(64):
                node.propose(sess, b"x" * 64, 100)
        assert node.rate_limiter.rate_limited()
    finally:
        nh.close()


def test_proposal_queue_bound():
    from dragonboat_tpu.request import RequestDroppedError
    from dragonboat_tpu.server.settings import soft

    addrs = {1: "qb-1"}
    nh = NodeHost(NodeHostConfig(raft_address="qb-1", rtt_millisecond=5),
                  auto_run=False)
    nh.start_replica(addrs, False, KVStateMachine, Config(
        shard_id=1, replica_id=1, election_rtt=10, heartbeat_rtt=1))
    try:
        node = nh.nodes[1]
        sess = nh.get_noop_session(1)
        with pytest.raises(RequestDroppedError):
            for _ in range(soft.incoming_proposal_queue_length + 8):
                node.propose(sess, b"q", 100)
    finally:
        nh.close()


# ---------------------------------------------------------------------------
# quiesce at scale: thousands of idle shards ~ free (README.md:50 of the
# reference — "idle groups are approximately free"; quiesce.go:36)
# ---------------------------------------------------------------------------


def test_quiesce_scale_idle_shards_are_free():
    """200 idle single-replica shards on one host: once quiesced, the
    engine finds NO step work (run_once() == 0) and terms freeze — idle
    shards cost ticks only, mirroring the reference's headline claim."""
    shards = tuple(range(1, 201))
    nh = NodeHost(NodeHostConfig(raft_address="qsc-1", rtt_millisecond=2),
                  auto_run=False)
    try:
        for sid in shards:
            nh.start_replica({1: "qsc-1"}, False, KVStateMachine, Config(
                shard_id=sid, replica_id=1, election_rtt=5, heartbeat_rtt=1,
                quiesce=True))
        # elect every shard (single member: first election tick wins)
        deadline = time.time() + 30
        while time.time() < deadline:
            nh.tick_all()
            nh.run_once()
            if all(nh.get_leader_id(s)[1] for s in shards):
                break
        assert all(nh.get_leader_id(s)[1] for s in shards)

        def drive(rs, deadline_s=10):
            # auto_run=False: nothing steps the nodes, so the test drives
            # the engine until the proposal future completes
            end = time.time() + deadline_s
            while time.time() < end and not rs._event.is_set():
                nh.tick_all()
                nh.run_once()
            assert rs._event.is_set(), "proposal never completed"
            return rs

        s = nh.get_noop_session(1)
        drive(nh.propose(s, b"w=1"))
        # idle: tick until every shard enters quiesce (threshold ~50 ticks)
        for _ in range(80):
            nh.tick_all()
            nh.run_once()
        assert all(n.qs.quiesced() for n in nh.nodes.values()), \
            f"{sum(n.qs.quiesced() for n in nh.nodes.values())}/200 quiesced"
        terms = {sid: n.peer.raft.term for sid, n in nh.nodes.items()}
        # quiesced ticks generate no step work
        steps = 0
        for _ in range(30):
            nh.tick_all()
            steps += nh.run_once()
        assert steps == 0, f"quiesced shards still produced {steps} steps"
        assert terms == {sid: n.peer.raft.term for sid, n in nh.nodes.items()}
        # and activity on one shard wakes exactly that shard
        drive(nh.propose(nh.get_noop_session(7), b"wake=1"))
        assert not nh.nodes[7].qs.quiesced()
        assert nh.nodes[8].qs.quiesced()
    finally:
        nh.close()
