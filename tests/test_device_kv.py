"""DeviceKV — the device-native RSM (rsm/device_kv.py) and the fused
propose→commit→apply bench pipeline (bench_loop.full_step_sm).

Reference behavior matched: the in-memory KV RSM the reference's
benchmarks apply (internal/tests/kvtest.go), re-expressed as a vmapped
scatter-free hash-table kernel (BASELINE.json north star).
"""

import jax.numpy as jnp
import numpy as np

from dragonboat_tpu.bench_loop import (
    elect_all,
    make_cluster,
    make_device_sm,
    run_steps_sm,
    sm_params,
)
from dragonboat_tpu.core import params as KP
from dragonboat_tpu.rsm.device_kv import DeviceKV


def test_put_get_update_roundtrip():
    kv = DeviceKV(table_cap=64, probe_depth=8)
    st = kv.init_state(2)
    cmds = jnp.asarray([
        [[5, 100], [9, 200], [5, 101], [0, 0]],    # shard 0: update key 5
        [[7, 300], [7, 301], [7, 302], [1, 400]],  # shard 1
    ], jnp.int32)
    valid = jnp.asarray([[True, True, True, False],
                         [True, True, True, True]])
    st, (results, ok) = kv.apply_kernel(st, cmds, valid)
    assert kv.lookup(st, 0, 5) == 101          # later write wins
    assert kv.lookup(st, 0, 9) == 200
    assert kv.lookup(st, 0, 0) is None         # invalid lane not applied
    assert kv.lookup(st, 1, 7) == 302
    assert kv.lookup(st, 1, 1) == 400
    assert int(st["count"][0]) == 2 and int(st["count"][1]) == 2
    okn = np.asarray(ok)
    assert not okn[0, 3] and okn[0, :3].all()  # invalid lane not applied


def test_collisions_probe_to_free_slots():
    """Keys that hash to the same bucket must all land via probing."""
    kv = DeviceKV(table_cap=16, probe_depth=16)
    st = kv.init_state(1)
    # 10 distinct keys into a 16-slot table: collisions guaranteed
    keys = list(range(100, 110))
    cmds = jnp.asarray([[[k, k * 7] for k in keys]], jnp.int32)
    valid = jnp.ones((1, len(keys)), bool)
    st, (results, ok) = kv.apply_kernel(st, cmds, valid)
    for k in keys:
        assert kv.lookup(st, 0, k) == k * 7, k
    assert int(st["count"][0]) == len(keys)
    assert np.asarray(ok).all()


def test_full_probe_window_rejects():
    kv = DeviceKV(table_cap=4, probe_depth=4)
    st = kv.init_state(1)
    cmds = jnp.asarray([[[k, k] for k in range(1, 9)]], jnp.int32)
    valid = jnp.ones((1, 8), bool)
    st, (results, ok) = kv.apply_kernel(st, cmds, valid)
    assert (~np.asarray(ok)).any(), "an over-full table must reject writes"
    assert int(st["count"][0]) <= 4


def test_bench_pipeline_applies_to_device_kv():
    """The fused pipeline: every committed write lands in the DeviceKV
    with payload == entry index, on leaders AND followers — payloads
    ride the replicated lv ring, so follower tables hold real values."""
    kp = sm_params(3)
    groups = 16
    state = make_cluster(kp, groups, 3)
    state, box = elect_all(kp, 3, state)
    kv, kv_state = make_device_sm(groups, 3)
    state, box, kv_state, rej = run_steps_sm(
        kp, 3, kv, 12, True, True, state, box, kv_state)
    # settle: no new proposals, so follower applied cursors catch up
    state, box, kv_state, rej2 = run_steps_sm(
        kp, 3, kv, 6, False, False, state, box, kv_state)
    assert int(rej) == 0 and int(rej2) == 0, "committed writes rejected"
    role = np.asarray(state.role)
    applied = np.asarray(state.applied)
    lv = np.asarray(state.lv)
    snap = np.asarray(state.snap_index)
    leaders = np.nonzero(role == KP.LEADER)[0]
    assert len(leaders) == groups
    checked = 0
    for g in range(groups * 3):          # every replica, leader or not
        hi = int(applied[g])
        assert hi > 0, f"lane {g} never applied"
        # the replicated payload ring holds the entry's own index
        for idx in range(max(int(snap[g]) + 1, hi - 5), hi + 1):
            assert lv[g, idx & (kp.log_cap - 1)] == idx, (g, idx)
        # and the KV table's entry for a recent key matches
        v = kv.lookup(kv_state, g, hi & (kv.table_cap - 1))
        assert v is not None and \
            v & (kv.table_cap - 1) == hi & (kv.table_cap - 1)
        checked += 1
    assert checked == groups * 3
    # convergence oracle: all replicas of a group hold identical tables
    keys = np.asarray(kv_state["keys"]).reshape(groups, 3, -1)
    vals = np.asarray(kv_state["vals"]).reshape(groups, 3, -1)
    same = 0
    for n in range(groups):
        a = np.asarray(applied).reshape(groups, 3)[n]
        if a[0] == a[1] == a[2]:         # equal applied -> equal tables
            for r in (1, 2):
                assert (keys[n, 0] == keys[n, r]).all(), (n, r)
                assert (vals[n, 0] == vals[n, r]).all(), (n, r)
            same += 1
    assert same >= 1


def test_negative_keys_rejected():
    kv = DeviceKV(table_cap=16, probe_depth=4)
    st = kv.init_state(1)
    cmds = jnp.asarray([[[-1, 42], [3, 7]]], jnp.int32)
    st, (results, ok) = kv.apply_kernel(st, cmds, jnp.ones((1, 2), bool))
    okn = np.asarray(ok)
    assert not okn[0, 0] and okn[0, 1]
    assert np.asarray(results)[0, 1] == 7
    assert kv.lookup(st, 0, -1) is None
    assert kv.lookup(st, 0, 3) == 7
    assert int(st["count"][0]) == 1


def test_range_apply_matches_sequential():
    """apply_kernel_range must be bit-identical to the probing scan fed
    the same contiguous (key, value) lanes on a direct-mapped table."""
    import numpy as np

    rng = np.random.default_rng(5)
    kv = DeviceKV(table_cap=64, probe_depth=8, hash_keys=False)
    G, B = 7, 16
    st_a = kv.init_state(G)
    st_b = kv.init_state(G)
    first = np.zeros(G, np.int64)
    for _ in range(5):
        vals = rng.integers(0, 1000, size=(G, B), dtype=np.int32)
        valid = jnp.asarray(rng.random((G, B)) < 0.8)
        keys = ((first[:, None] + np.arange(B)) & (kv.table_cap - 1)
                ).astype(np.int32)
        cmds = jnp.asarray(np.stack([keys, vals], axis=-1))
        st_a, (ra, oka) = kv.apply_kernel(st_a, cmds, valid)
        st_b, (rb, okb) = kv.apply_kernel_range(
            st_b, jnp.asarray(first & (kv.table_cap - 1), jnp.int32),
            jnp.asarray(vals), valid)
        for f in ("keys", "vals", "count"):
            assert (np.asarray(st_a[f]) == np.asarray(st_b[f])).all(), f
        assert (np.asarray(ra) == np.asarray(rb)).all()
        assert (np.asarray(oka) == np.asarray(okb)).all()
        first += rng.integers(0, B + 1, size=G)  # windows advance unevenly


def test_range_apply_wraps_and_counts():
    kv = DeviceKV(table_cap=16, hash_keys=False)
    st = kv.init_state(1)
    # window of 8 starting at 12: wraps to slots 12..15, 0..3
    vals = jnp.asarray([[100, 101, 102, 103, 104, 105, 106, 107]], jnp.int32)
    st, (r, ok) = kv.apply_kernel_range(
        st, jnp.asarray([12], jnp.int32), vals, jnp.ones((1, 8), bool))
    import numpy as np

    assert np.asarray(ok).all()
    for j in range(8):
        assert kv.lookup(st, 0, (12 + j) & 15) == 100 + j
    assert int(st["count"][0]) == 8
