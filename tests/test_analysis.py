"""Static-analysis passes (dragonboat_tpu/analysis/): known-bad fixture
snippets must produce findings, waived snippets must come back clean,
and the HLO budget gate must fail when the budget is tightened below
the kernel's actual op counts."""

from __future__ import annotations

import json
import os
import subprocess
import sys
import textwrap

import pytest

from dragonboat_tpu.analysis import (
    common,
    concurrency,
    determinism,
    hlo_budget,
    tracer_safety,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _write(tmp_path, name, src):
    p = tmp_path / name
    p.write_text(textwrap.dedent(src))
    return str(p)


# ---------------------------------------------------------------- tracer-safety

BAD_TRACED = """\
    import time

    import jax
    import numpy as np


    @jax.jit
    def bad(x):
        if x > 0:                    # TS001: python branch on traced
            x = x + 1
        while x > 0:                 # TS001: python loop on traced
            x = x - 1
        y = int(x)                   # TS002: host coercion
        z = x.item()                 # TS002: host sync coercion
        w = np.asarray(x)            # TS003: host materialization
        t = time.time()              # TS004: wall clock under trace
        return helper(x)


    def helper(x):
        return float(x)              # TS002, reached through the call graph
"""


def test_tracer_safety_flags_bad_fixture(tmp_path):
    p = _write(tmp_path, "bad.py", BAD_TRACED)
    findings = tracer_safety.run(str(tmp_path), files=[p])
    rules = sorted(f.rule for f in findings)
    assert rules.count("TS001") == 2
    assert rules.count("TS002") == 3     # int(), .item(), helper's float()
    assert rules.count("TS004") == 1
    assert "TS003" in rules
    # the call-graph hop: helper() is only traced because bad() calls it
    assert any(f.rule == "TS002" and "float" in f.message for f in findings)


def test_tracer_safety_clean_fixture(tmp_path):
    p = _write(tmp_path, "good.py", """\
        import jax
        import jax.numpy as jnp


        @jax.jit
        def good(x, kw):
            if x.ndim > 0:                 # shape metadata is static
                x = x + 1
            for k, v in kw.items():        # dict structure is static
                x = x + v
            if isinstance(x, int):         # host-typed branch: narrowed
                y = int(x)
                x = jnp.asarray(y)
            return jnp.sum(x)
    """)
    assert tracer_safety.run(str(tmp_path), files=[p]) == []


def test_tracer_safety_untraced_function_not_flagged(tmp_path):
    # host-side code may branch on values freely — only jit scope is linted
    p = _write(tmp_path, "host.py", """\
        def host_only(x):
            if x > 0:
                return int(x)
            return 0
    """)
    assert tracer_safety.run(str(tmp_path), files=[p]) == []


# ------------------------------------------------------------------ concurrency

BAD_LOCKED = """\
    import threading
    from collections import deque


    class Book:
        def __init__(self):
            self.mu = threading.Lock()
            self.items = deque()           # CC001: no guarded-by annotation
            self.index = {}                # guarded-by: mu
            self.frozen = []               # guarded-by: <init-only>

        def poke(self):
            self.index["k"] = 1            # CC002: mutation outside lock
            self.frozen.append(1)          # CC002: init-only violated

        def locked_ok(self):
            with self.mu:
                self.index.clear()
"""


def test_concurrency_flags_bad_fixture(tmp_path):
    p = _write(tmp_path, "bad.py", BAD_LOCKED)
    findings = concurrency.run(str(tmp_path), files=[p])
    rules = sorted(f.rule for f in findings)
    assert rules == ["CC001", "CC002", "CC002"]
    msgs = " ".join(f.message for f in findings)
    assert "self.items" in msgs            # the unannotated deque
    assert "init-only" in msgs             # the frozen append


def test_concurrency_sharded_lock_and_inheritance(tmp_path):
    p = _write(tmp_path, "shard.py", """\
        import threading


        class Base:
            def __init__(self):
                self.mu = threading.Lock()
                self.log = []              # guarded-by: mu


        class Shards(Base):
            def __init__(self):
                super().__init__()
                self._locks = [threading.Lock() for _ in range(4)]
                self.shards = [{} for _ in range(4)]   # guarded-by: _locks

            def put(self, k, v):
                with self._locks[k % 4]:   # subscripted lock counts as held
                    self.shards[k % 4][k] = v

            def note(self, x):
                with self.mu:              # inherited lock guards base attr
                    self.log.append(x)

            def bad(self, x):
                self.log.append(x)         # CC002 via inherited guard
    """)
    findings = concurrency.run(str(tmp_path), files=[p])
    assert [f.rule for f in findings] == ["CC002"]
    assert "self.log" in findings[0].message


# ------------------------------------------------------------------ determinism

BAD_REPLAY = """\
    import random
    import time


    def replay(entries):
        t0 = time.time()                   # DT001
        jitter = random.random()           # DT002
        seen = {1, 2, 3}
        for x in seen:                     # DT003
            pass
        for x in sorted(seen):             # ordered: fine
            pass
        return t0 + jitter
"""


def test_determinism_flags_bad_fixture(tmp_path):
    p = _write(tmp_path, "bad.py", BAD_REPLAY)
    findings = determinism.run(str(tmp_path), files=[p])
    assert sorted(f.rule for f in findings) == ["DT001", "DT002", "DT003"]


def test_determinism_allows_seeded_and_ordered(tmp_path):
    p = _write(tmp_path, "good.py", """\
        import jax


        def replay(key, d):
            r = jax.random.uniform(key)    # keyed RNG is deterministic
            for k in d:                    # dict order is insertion order
                pass
            return r
    """)
    assert determinism.run(str(tmp_path), files=[p]) == []


# ---------------------------------------------------------------------- waivers


def test_waiver_suppresses_matching_finding(tmp_path):
    p = _write(tmp_path, "bad.py", BAD_LOCKED)
    findings = concurrency.run(str(tmp_path), files=[p])
    wpath = tmp_path / "waivers.toml"
    wpath.write_text(textwrap.dedent("""\
        # fixture waiver
        [[waiver]]
        pass_name = "concurrency"
        path = "bad.py"
        rule = "CC001"
        reason = "fixture: annotation intentionally omitted"
    """))
    waivers = common.load_waivers(str(wpath))
    unwaived, waived = common.apply_waivers(findings, waivers)
    assert [f.rule for f in unwaived] == ["CC002", "CC002"]
    assert len(waived) == 1
    finding, waiver = waived[0]
    assert finding.rule == "CC001"
    assert waiver.hits == 1
    assert "intentionally omitted" in waiver.reason


def test_waiver_requires_reason(tmp_path):
    wpath = tmp_path / "waivers.toml"
    wpath.write_text('[[waiver]]\npass_name = "determinism"\npath = "*"\n')
    with pytest.raises(common.WaiverError, match="reason"):
        common.load_waivers(str(wpath))


def test_waiver_rejects_unsupported_toml(tmp_path):
    wpath = tmp_path / "waivers.toml"
    wpath.write_text("[table]\nkey = 1\n")
    with pytest.raises(common.WaiverError, match="unsupported"):
        common.load_waivers(str(wpath))


def test_repo_waivers_file_parses():
    path = os.path.join(REPO, "dragonboat_tpu/analysis/waivers.toml")
    common.load_waivers(path)              # malformed entries would raise


# ------------------------------------------------------------------- hlo budget


def _budget_file(tmp_path, budget):
    p = tmp_path / "hlo_budget.json"
    p.write_text(json.dumps({
        "config": {"groups": 4, "replicas": 3, "iters": 2,
                   "onehot_reads": True},
        "budget": budget,
    }))
    return str(p)


def test_hlo_budget_passes_within_budget(tmp_path):
    p = _budget_file(tmp_path, {"gather": 32, "scatter": 0, "while": 5})
    measured = {"gather": 32, "scatter": 0, "while": 5}
    assert hlo_budget.run(str(tmp_path), budget_path=p,
                          measured=measured) == []


def test_hlo_budget_fails_when_exceeded(tmp_path):
    p = _budget_file(tmp_path, {"gather": 31, "scatter": 0, "while": 5})
    measured = {"gather": 32, "scatter": 0, "while": 5}
    findings = hlo_budget.run(str(tmp_path), budget_path=p,
                              measured=measured)
    assert [f.rule for f in findings] == ["HB001"]
    assert "32 exceeds budget 31" in findings[0].message


def test_hlo_budget_missing_file_is_a_finding(tmp_path):
    findings = hlo_budget.run(str(tmp_path))
    assert [f.rule for f in findings] == ["HB000"]


def test_hlo_budget_measure_emits_tracing_spans(monkeypatch):
    """The lowering path annotates build/lower/compile spans and the live
    measurement stays within the checked-in budget."""
    from dragonboat_tpu import tracing

    spans = []
    real = tracing.annotate

    def recording(name):
        spans.append(name)
        return real(name)

    monkeypatch.setattr(tracing, "annotate", recording)
    measured = hlo_budget.measure(groups=4, replicas=3, iters=2)
    assert spans == ["lint.hlo.build", "lint.hlo.lower", "lint.hlo.compile"]
    # gather/scatter/while instruction counts are group-count-independent
    # (PERF.md), so the small-G measurement must match the seeded budget
    spec = hlo_budget.load_budget(
        os.path.join(REPO, hlo_budget.BUDGET_FILE))
    for op, limit in spec["budget"].items():
        assert measured[op] <= limit, (op, measured)


# ----------------------------------------------------------------------- runner


def test_lint_runner_ast_passes_clean_on_repo():
    """The checked-in tree has zero unwaived findings in the AST passes
    (the hlo-budget pass is exercised separately: it costs a compile)."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "lint.py"),
         "--pass", "tracer-safety", "--pass", "concurrency",
         "--pass", "determinism"],
        capture_output=True, text=True, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "OK: no unwaived findings" in proc.stdout
