"""Static-analysis passes (dragonboat_tpu/analysis/): known-bad fixture
snippets must produce findings, waived snippets must come back clean,
and the HLO budget gate must fail when the budget is tightened below
the kernel's actual op counts."""

from __future__ import annotations

import ast
import importlib.util
import json
import os
import subprocess
import sys
import textwrap

import pytest

from dragonboat_tpu.analysis import (
    common,
    concurrency,
    contracts,
    determinism,
    hlo_budget,
    tracer_safety,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_lint_module():
    spec = importlib.util.spec_from_file_location(
        "lint_under_test", os.path.join(REPO, "scripts", "lint.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _write(tmp_path, name, src):
    p = tmp_path / name
    p.write_text(textwrap.dedent(src))
    return str(p)


# ---------------------------------------------------------------- tracer-safety

BAD_TRACED = """\
    import time

    import jax
    import numpy as np


    @jax.jit
    def bad(x):
        if x > 0:                    # TS001: python branch on traced
            x = x + 1
        while x > 0:                 # TS001: python loop on traced
            x = x - 1
        y = int(x)                   # TS002: host coercion
        z = x.item()                 # TS002: host sync coercion
        w = np.asarray(x)            # TS003: host materialization
        t = time.time()              # TS004: wall clock under trace
        return helper(x)


    def helper(x):
        return float(x)              # TS002, reached through the call graph
"""


def test_tracer_safety_flags_bad_fixture(tmp_path):
    p = _write(tmp_path, "bad.py", BAD_TRACED)
    findings = tracer_safety.run(str(tmp_path), files=[p])
    rules = sorted(f.rule for f in findings)
    assert rules.count("TS001") == 2
    assert rules.count("TS002") == 3     # int(), .item(), helper's float()
    assert rules.count("TS004") == 1
    assert "TS003" in rules
    # the call-graph hop: helper() is only traced because bad() calls it
    assert any(f.rule == "TS002" and "float" in f.message for f in findings)


def test_tracer_safety_clean_fixture(tmp_path):
    p = _write(tmp_path, "good.py", """\
        import jax
        import jax.numpy as jnp


        @jax.jit
        def good(x, kw):
            if x.ndim > 0:                 # shape metadata is static
                x = x + 1
            for k, v in kw.items():        # dict structure is static
                x = x + v
            if isinstance(x, int):         # host-typed branch: narrowed
                y = int(x)
                x = jnp.asarray(y)
            return jnp.sum(x)
    """)
    assert tracer_safety.run(str(tmp_path), files=[p]) == []


def test_tracer_safety_untraced_function_not_flagged(tmp_path):
    # host-side code may branch on values freely — only jit scope is linted
    p = _write(tmp_path, "host.py", """\
        def host_only(x):
            if x > 0:
                return int(x)
            return 0
    """)
    assert tracer_safety.run(str(tmp_path), files=[p]) == []


# ------------------------------------------------------------------ concurrency

BAD_LOCKED = """\
    import threading
    from collections import deque


    class Book:
        def __init__(self):
            self.mu = threading.Lock()
            self.items = deque()           # CC001: no guarded-by annotation
            self.index = {}                # guarded-by: mu
            self.frozen = []               # guarded-by: <init-only>

        def poke(self):
            self.index["k"] = 1            # CC002: mutation outside lock
            self.frozen.append(1)          # CC002: init-only violated

        def locked_ok(self):
            with self.mu:
                self.index.clear()
"""


def test_concurrency_flags_bad_fixture(tmp_path):
    p = _write(tmp_path, "bad.py", BAD_LOCKED)
    findings = concurrency.run(str(tmp_path), files=[p])
    rules = sorted(f.rule for f in findings)
    assert rules == ["CC001", "CC002", "CC002"]
    msgs = " ".join(f.message for f in findings)
    assert "self.items" in msgs            # the unannotated deque
    assert "init-only" in msgs             # the frozen append


def test_concurrency_sharded_lock_and_inheritance(tmp_path):
    p = _write(tmp_path, "shard.py", """\
        import threading


        class Base:
            def __init__(self):
                self.mu = threading.Lock()
                self.log = []              # guarded-by: mu


        class Shards(Base):
            def __init__(self):
                super().__init__()
                self._locks = [threading.Lock() for _ in range(4)]
                self.shards = [{} for _ in range(4)]   # guarded-by: _locks

            def put(self, k, v):
                with self._locks[k % 4]:   # subscripted lock counts as held
                    self.shards[k % 4][k] = v

            def note(self, x):
                with self.mu:              # inherited lock guards base attr
                    self.log.append(x)

            def bad(self, x):
                self.log.append(x)         # CC002 via inherited guard
    """)
    findings = concurrency.run(str(tmp_path), files=[p])
    assert [f.rule for f in findings] == ["CC002"]
    assert "self.log" in findings[0].message


# ----------------------------------------------------------- lock order (CC003)

DEADLOCK_FIXTURE = """\
    import threading


    class Deadlocky:
        def __init__(self):
            self.a = threading.Lock()
            self.b = threading.Lock()

        def ab(self):
            with self.a:
                with self.b:
                    pass

        def ba(self):
            with self.b:
                self._grab_a()         # transitive: ba holds b, takes a

        def _grab_a(self):
            with self.a:
                pass


    class SelfLock:
        def __init__(self):
            self.mu = threading.Lock()

        def outer(self):
            with self.mu:
                self.inner()           # re-acquires mu on the same thread

        def inner(self):
            with self.mu:
                pass


    class Reentrant:
        def __init__(self):
            self.mu = threading.RLock()

        def outer(self):
            with self.mu:
                self.inner()           # fine: RLock is reentrant

        def inner(self):
            with self.mu:
                pass


    class FineNested:
        def __init__(self):
            self.outer_mu = threading.Lock()
            self.inner_mu = threading.Lock()

        def f(self):
            with self.outer_mu:
                with self.inner_mu:
                    pass

        def g(self):
            with self.outer_mu:
                with self.inner_mu:    # same order everywhere: no cycle
                    pass
"""


def test_lock_order_cycle_and_self_deadlock(tmp_path):
    p = _write(tmp_path, "locks.py", DEADLOCK_FIXTURE)
    findings = concurrency.run(str(tmp_path), files=[p])
    rules = [f.rule for f in findings]
    assert rules.count("CC003") == 2 and set(rules) == {"CC003"}
    msgs = " ".join(f.message for f in findings)
    # the a->b->a inversion, found through the same-class call graph
    assert "Deadlocky" in msgs and "lock-order cycle" in msgs
    assert "_grab_a" in msgs
    # the non-reentrant re-acquisition
    assert "SelfLock" in msgs and "re-acquired" in msgs
    # RLock re-acquisition and consistently-ordered nesting stay clean
    assert "Reentrant" not in msgs and "FineNested" not in msgs


def test_lock_order_consistent_nesting_is_clean(tmp_path):
    p = _write(tmp_path, "ok.py", """\
        import threading


        class Hub:
            def __init__(self):
                self.mu = threading.Lock()
                self.snap_mu = threading.Lock()

            def send(self):
                with self.mu:
                    pass
                with self.snap_mu:     # sequential, never nested
                    pass
    """)
    assert concurrency.run(str(tmp_path), files=[p]) == []


# ------------------------------------------------------------------ determinism

BAD_REPLAY = """\
    import random
    import time


    def replay(entries):
        t0 = time.time()                   # DT001
        jitter = random.random()           # DT002
        seen = {1, 2, 3}
        for x in seen:                     # DT003
            pass
        for x in sorted(seen):             # ordered: fine
            pass
        return t0 + jitter
"""


def test_determinism_flags_bad_fixture(tmp_path):
    p = _write(tmp_path, "bad.py", BAD_REPLAY)
    findings = determinism.run(str(tmp_path), files=[p])
    assert sorted(f.rule for f in findings) == ["DT001", "DT002", "DT003"]


def test_determinism_allows_seeded_and_ordered(tmp_path):
    p = _write(tmp_path, "good.py", """\
        import jax


        def replay(key, d):
            r = jax.random.uniform(key)    # keyed RNG is deterministic
            for k in d:                    # dict order is insertion order
                pass
            return r
    """)
    assert determinism.run(str(tmp_path), files=[p]) == []


# ---------------------------------------------------------------------- waivers


def test_waiver_suppresses_matching_finding(tmp_path):
    p = _write(tmp_path, "bad.py", BAD_LOCKED)
    findings = concurrency.run(str(tmp_path), files=[p])
    wpath = tmp_path / "waivers.toml"
    wpath.write_text(textwrap.dedent("""\
        # fixture waiver
        [[waiver]]
        pass_name = "concurrency"
        path = "bad.py"
        rule = "CC001"
        reason = "fixture: annotation intentionally omitted"
    """))
    waivers = common.load_waivers(str(wpath))
    unwaived, waived = common.apply_waivers(findings, waivers)
    assert [f.rule for f in unwaived] == ["CC002", "CC002"]
    assert len(waived) == 1
    finding, waiver = waived[0]
    assert finding.rule == "CC001"
    assert waiver.hits == 1
    assert "intentionally omitted" in waiver.reason


def test_waiver_requires_reason(tmp_path):
    wpath = tmp_path / "waivers.toml"
    wpath.write_text('[[waiver]]\npass_name = "determinism"\npath = "*"\n')
    with pytest.raises(common.WaiverError, match="reason"):
        common.load_waivers(str(wpath))


def test_waiver_rejects_unsupported_toml(tmp_path):
    wpath = tmp_path / "waivers.toml"
    wpath.write_text("[table]\nkey = 1\n")
    with pytest.raises(common.WaiverError, match="unsupported"):
        common.load_waivers(str(wpath))


def test_repo_waivers_file_parses():
    path = os.path.join(REPO, "dragonboat_tpu/analysis/waivers.toml")
    common.load_waivers(path)              # malformed entries would raise


# ------------------------------------------------------------------- hlo budget


def _budget_file(tmp_path, budget):
    p = tmp_path / "hlo_budget.json"
    p.write_text(json.dumps({
        "config": {"groups": 4, "replicas": 3, "iters": 2,
                   "onehot_reads": True},
        "budget": budget,
    }))
    return str(p)


def test_hlo_budget_passes_within_budget(tmp_path):
    p = _budget_file(tmp_path, {"gather": 32, "scatter": 0, "while": 5})
    measured = {"gather": 32, "scatter": 0, "while": 5}
    assert hlo_budget.run(str(tmp_path), budget_path=p,
                          measured=measured) == []


def test_hlo_budget_fails_when_exceeded(tmp_path):
    p = _budget_file(tmp_path, {"gather": 31, "scatter": 0, "while": 5})
    measured = {"gather": 32, "scatter": 0, "while": 5}
    findings = hlo_budget.run(str(tmp_path), budget_path=p,
                              measured=measured)
    assert [f.rule for f in findings] == ["HB001"]
    assert "32 exceeds budget 31" in findings[0].message


def test_hlo_budget_missing_file_is_a_finding(tmp_path):
    findings = hlo_budget.run(str(tmp_path))
    assert [f.rule for f in findings] == ["HB000"]


def test_hlo_budget_measure_emits_tracing_spans(monkeypatch):
    """The lowering path annotates build/lower/compile spans and the live
    measurement stays within the checked-in budget."""
    from dragonboat_tpu import tracing

    spans = []
    real = tracing.annotate

    def recording(name):
        spans.append(name)
        return real(name)

    monkeypatch.setattr(tracing, "annotate", recording)
    measured = hlo_budget.measure(groups=4, replicas=3, iters=2)
    assert spans == ["lint.hlo.build", "lint.hlo.lower", "lint.hlo.compile"]
    # gather/scatter/while instruction counts are group-count-independent
    # (PERF.md), so the small-G measurement must match the seeded budget
    spec = hlo_budget.load_budget(
        os.path.join(REPO, hlo_budget.BUDGET_FILE))
    for op, limit in spec["budget"].items():
        assert measured[op] <= limit, (op, measured)


# -------------------------------------------------------------------- contracts

# A self-contained fixture module: carries its own CONTRACTS literal and
# domain constants; `St`-annotated params bind the contract class.  Each
# bad_* function seeds exactly one defect class; ok_* must stay clean.
CONTRACT_FIXTURE = """\
    import functools

    import jax
    import jax.numpy as jnp

    FOLLOWER = 0
    WITNESS = 5

    CONTRACTS = {
        "St": {
            "role": "[G] i32 domain=FOLLOWER..WITNESS",
            "match": "[G, P] i32",
            "lt": "[G, CAP] i32 ring",
            "flag": "[G] bool",
        },
    }


    @functools.partial(jax.jit, static_argnums=0)
    def bad_broadcast(kp, s: St):
        k = jnp.arange(kp.inbox_cap)
        e = jnp.arange(kp.msg_entries)
        return k + e                   # KC001: [K] + [E] cross-axis


    @functools.partial(jax.jit, static_argnums=0)
    def bad_upcast(kp, s: St):
        x = s.match.astype(jnp.float32)
        return x + s.match             # KC002: f32 + i32


    @functools.partial(jax.jit, static_argnums=0)
    def bad_cmp(kp, s: St):
        return s.flag == s.role        # KC003: bool vs i32


    @functools.partial(jax.jit, static_argnums=0)
    def bad_ring(kp, s: St, idx):
        return s.lt[idx]               # KC004: unmasked ring index


    @functools.partial(jax.jit, static_argnums=0)
    def ok_ring(kp, s: St, idx):
        return s.lt[idx & (kp.log_cap - 1)]   # masked: clean


    @functools.partial(jax.jit, static_argnums=0)
    def bad_domain(kp, s: St):
        return s._replace(role=jnp.full_like(s.role, 9))   # KC005: 9 > 5


    @functools.partial(jax.jit, static_argnums=0)
    def bad_store(kp, s: St):
        return s._replace(match=s.flag)   # KC006: [G] bool into [G, P] i32
"""


def _contract_findings(tmp_path):
    p = _write(tmp_path, "fix.py", CONTRACT_FIXTURE)
    return contracts.run(str(tmp_path), files=[p])


def test_contracts_catches_each_defect_class(tmp_path):
    findings = _contract_findings(tmp_path)
    rules = sorted(f.rule for f in findings)
    assert rules == ["KC001", "KC002", "KC003", "KC004", "KC005", "KC006"]


def test_contracts_broadcast_message_names_both_axes(tmp_path):
    f = next(f for f in _contract_findings(tmp_path) if f.rule == "KC001")
    assert "'K'" in f.message and "'E'" in f.message


def test_contracts_masked_ring_index_is_clean(tmp_path):
    findings = _contract_findings(tmp_path)
    src = textwrap.dedent(CONTRACT_FIXTURE).splitlines()
    ok_lines = {i + 1 for i, ln in enumerate(src) if "ok_ring" in ln
                or "masked: clean" in ln}
    assert not [f for f in findings if f.line in ok_lines]


def test_contracts_domain_store_names_bounds(tmp_path):
    f = next(f for f in _contract_findings(tmp_path) if f.rule == "KC005")
    assert "FOLLOWER..WITNESS" in f.message and "9" in f.message


def test_contract_grammar_parses_and_rejects():
    fc = common.parse_contract("[G, P] i32 domain=FOLLOWER..WITNESS")
    assert fc.axes == ("G", "P") and fc.dtype == "i32"
    assert fc.domain == ("FOLLOWER", "WITNESS") and not fc.ring
    fc = common.parse_contract("[G, CAP] bool ring optional")
    assert fc.ring and fc.optional and fc.domain is None
    assert common.parse_contract("[] i32").axes == ()
    with pytest.raises(common.ContractError, match="dtype"):
        common.parse_contract("[G] i16")
    with pytest.raises(common.ContractError, match="tag"):
        common.parse_contract("[G] i32 wat")
    with pytest.raises(common.ContractError, match="domain"):
        common.parse_contract("[G] i32 domain=LOW")


def test_broadcast_axes_lattice():
    assert common.broadcast_axes(("G", "P"), ("P",)) == (("G", "P"), None)
    assert common.broadcast_axes(("G", "1"), ("G", "P")) == (("G", "P"), None)
    axes, conflict = common.broadcast_axes(("K",), ("E",))
    assert conflict is not None and "'K'" in conflict
    # unknown unifies optimistically
    assert common.broadcast_axes(None, ("G",)) == (("G",), None)
    assert common.broadcast_axes(("?",), ("G",)) == (("G",), None)


def test_contracts_pass_clean_on_repo_kernel():
    """The acceptance gate: zero findings on the checked-in kernel,
    including the eval_shape declared-vs-actual diff."""
    assert contracts.run(REPO) == []


@pytest.mark.parametrize("G,P,CAP", [(1, 3, 32), (5, 5, 64), (2, 1, 16),
                                     (7, 4, 128)])
def test_contracts_runtime_roundtrip(G, P, CAP):
    """Declared contracts match the eval-shaped structures across
    geometries (all-distinct satellite axes keep axis names honest)."""
    from dragonboat_tpu.core.params import KernelParams

    kp = KernelParams(num_peers=P, log_cap=CAP, inbox_cap=4, msg_entries=5,
                      proposal_cap=6, readindex_cap=8)
    assert contracts.runtime_check(kp=kp, num_shards=G, root=REPO) == []


def test_contracts_runtime_flags_declared_vs_actual_mismatch(tmp_path):
    """Tampering one declared shape must surface as KC007 against the
    real init_state output."""
    real = os.path.join(REPO, "dragonboat_tpu/core/kstate.py")
    with open(real, encoding="utf-8") as f:
        src = f.read()
    tree = ast.parse(src)
    seg = next(ast.get_source_segment(src, n) for n in tree.body
               if isinstance(n, ast.Assign)
               and getattr(n.targets[0], "id", None) == "CONTRACTS")
    good = '"role": "[G] i32 domain=FOLLOWER..WITNESS part=G"'
    assert good in seg
    tampered = seg.replace(good, '"role": "[G, P] i32"')
    d = tmp_path / "dragonboat_tpu" / "core"
    d.mkdir(parents=True)
    (d / "kstate.py").write_text(tampered + "\n")
    findings = contracts.runtime_check(root=str(tmp_path), eval_step=False)
    role = [f for f in findings if "ShardState.role" in f.message]
    assert role and all(f.rule == "KC007" for f in role)
    assert "['G', 'P']" in role[0].message


# ---------------------------------------------------------------- stale waivers


def test_stale_waiver_pattern_matching_no_file(tmp_path):
    lint = _load_lint_module()
    (tmp_path / "real.py").write_text("x = 1\n")
    w = common.Waiver(pass_name="contracts", path="no/such/*.py",
                      reason="outlived", line=7)
    findings = lint.stale_waiver_findings([w], str(tmp_path))
    assert [f.rule for f in findings] == ["SW001"]
    assert findings[0].line == 7


def test_stale_waiver_with_zero_hits(tmp_path):
    lint = _load_lint_module()
    (tmp_path / "real.py").write_text("x = 1\n")
    w = common.Waiver(pass_name="contracts", path="real.py",
                      reason="outlived", line=3)
    assert [f.rule for f in lint.stale_waiver_findings([w], str(tmp_path))
            ] == ["SW002"]
    w.hits = 1                      # exercised waiver: not stale
    assert lint.stale_waiver_findings([w], str(tmp_path)) == []


def test_stale_waiver_fails_full_lint_run(tmp_path, monkeypatch, capsys):
    lint = _load_lint_module()
    monkeypatch.setattr(lint, "PASSES", {"noop": lambda root: []})
    wpath = tmp_path / "waivers.toml"
    wpath.write_text(textwrap.dedent("""\
        [[waiver]]
        pass_name = "noop"
        path = "no/such/file.py"
        reason = "stale on purpose"
    """))
    monkeypatch.setattr(lint, "ROOT", str(tmp_path))
    monkeypatch.setattr(lint, "WAIVERS_FILE", "waivers.toml")
    assert lint.main([]) == 1
    assert "SW001" in capsys.readouterr().out
    # a --pass subset legitimately skips staleness (other passes unrun)
    assert lint.main(["--pass", "noop"]) == 0


# ------------------------------------------------------------------ json format


def test_lint_format_json_one_finding_per_line(monkeypatch, capsys):
    lint = _load_lint_module()
    hits = [common.Finding("fake", "a.py", 3, "XX001", "boom"),
            common.Finding("fake", "b.py", 9, "XX002", "waive me")]
    monkeypatch.setattr(lint, "PASSES", {"fake": lambda root: list(hits)})
    monkeypatch.setattr(
        lint.common, "load_waivers",
        lambda path: [common.Waiver(pass_name="fake", path="b.py",
                                    reason="fixture")])
    rc = lint.main(["--pass", "fake", "--format", "json"])
    lines = [ln for ln in capsys.readouterr().out.splitlines() if ln]
    rows = [json.loads(ln) for ln in lines]
    assert rc == 1 and len(rows) == 2
    by_path = {r["path"]: r for r in rows}
    assert by_path["a.py"] == {"path": "a.py", "line": 3, "pass": "fake",
                               "rule": "XX001", "message": "boom",
                               "waived": False, "reason": None}
    assert by_path["b.py"]["waived"] and by_path["b.py"]["reason"] == "fixture"


# ----------------------------------------------------------------------- runner


def test_lint_runner_ast_passes_clean_on_repo():
    """The checked-in tree has zero unwaived findings in the AST passes
    (the hlo-budget pass is exercised separately: it costs a compile)."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "lint.py"),
         "--pass", "tracer-safety", "--pass", "concurrency",
         "--pass", "determinism", "--pass", "contracts"],
        capture_output=True, text=True, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "OK: no unwaived findings" in proc.stdout
