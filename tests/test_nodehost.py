"""End-to-end NodeHost tests: multi-NodeHost clusters in one process over
the chan transport (the reference's nodehost_test.go strategy on MemFS +
plugin/chan — SURVEY §4.3)."""

import struct
import time

import pytest

from dragonboat_tpu import raftpb as pb
from dragonboat_tpu.config import Config, NodeHostConfig
from dragonboat_tpu.nodehost import NodeHost
from dragonboat_tpu.statemachine import IStateMachine, Result
from dragonboat_tpu.request import RequestTimeoutError


class KVStateMachine(IStateMachine):
    """cmd = "key=value"; lookup = key; snapshot = whole dict."""

    def __init__(self, shard_id, replica_id):
        self.kv = {}
        self.update_count = 0

    def update(self, entry):
        self.update_count += 1
        k, v = entry.cmd.decode().split("=", 1)
        self.kv[k] = v
        return Result(value=len(self.kv))

    def lookup(self, query):
        return self.kv.get(query)

    def save_snapshot(self, w, files, done):
        data = "\n".join(f"{k}={v}" for k, v in sorted(self.kv.items()))
        w.write(struct.pack("<I", len(data)))
        w.write(data.encode())

    def recover_from_snapshot(self, r, files, done):
        (n,) = struct.unpack("<I", r.read(4))
        data = r.read(n).decode()
        self.kv = dict(line.split("=", 1) for line in data.split("\n") if line)


ADDRS = {1: "nh-1", 2: "nh-2", 3: "nh-3"}


def make_cluster(shard_id=1, n=3, snapshot_entries=0, rtt_ms=5,
                 addr_prefix="nh"):
    addrs = {i: f"{addr_prefix}-{i}" for i in range(1, n + 1)}
    hosts = {}
    for rid, addr in addrs.items():
        nh = NodeHost(NodeHostConfig(raft_address=addr, rtt_millisecond=rtt_ms))
        cfg = Config(shard_id=shard_id, replica_id=rid, election_rtt=10,
                     heartbeat_rtt=1, snapshot_entries=snapshot_entries,
                     compaction_overhead=5)
        nh.start_replica(addrs, False, KVStateMachine, cfg)
        hosts[rid] = nh
    return hosts, addrs


def wait_leader(hosts, shard_id=1, timeout=10.0):
    """Wait until a majority of hosts agree on one leader (avoids returning a
    stale leader right after a partition heals)."""
    deadline = time.time() + timeout
    while time.time() < deadline:
        votes = {}
        for nh in hosts.values():
            lid, ok = nh.get_leader_id(shard_id)
            if ok:
                votes[lid] = votes.get(lid, 0) + 1
        for lid, n in votes.items():
            if n > len(hosts) // 2 and lid in hosts:
                return lid
        time.sleep(0.02)
    raise AssertionError("no leader elected")


@pytest.fixture
def cluster():
    hosts, addrs = make_cluster(addr_prefix=f"nhA{time.monotonic_ns()}")
    yield hosts
    for nh in hosts.values():
        nh.close()


def test_sync_propose_and_read(cluster):
    hosts = cluster
    lid = wait_leader(hosts)
    nh = hosts[lid]
    s = nh.get_noop_session(1)
    r = nh.sync_propose(s, b"alpha=1")
    assert r.value == 1
    nh.sync_propose(s, b"beta=2")
    assert nh.sync_read(1, "alpha") == "1"
    assert nh.sync_read(1, "beta") == "2"
    # replicas converge; stale read from a follower
    frid = next(r for r in hosts if r != lid)
    deadline = time.time() + 5
    while time.time() < deadline:
        if hosts[frid].stale_read(1, "beta") == "2":
            break
        time.sleep(0.02)
    assert hosts[frid].stale_read(1, "beta") == "2"


def test_propose_via_follower_host(cluster):
    """The reference forwards proposals from follower to leader through the
    raft core; host routing makes any NodeHost a valid entry point."""
    hosts = cluster
    lid = wait_leader(hosts)
    frid = next(r for r in hosts if r != lid)
    nh = hosts[frid]
    s = nh.get_noop_session(1)
    r = nh.sync_propose(s, b"k=via-follower")
    assert r.value >= 1
    assert nh.sync_read(1, "k") == "via-follower"


def test_client_session_exactly_once(cluster):
    hosts = cluster
    lid = wait_leader(hosts)
    nh = hosts[lid]
    s = nh.sync_get_session(1)
    r1 = nh.sync_propose(s, b"x=1")
    # replay the same series id (simulating a client retry after timeout):
    s.series_id -= 1
    r2 = nh.sync_propose(s, b"x=SHOULD-NOT-APPLY")
    # dedup: the second proposal returns the cached result, not a new apply
    assert r2.value == r1.value
    assert nh.sync_read(1, "x") == "1"
    # update count proves single application
    leader_sm = nh._node(1).sm.sm
    assert leader_sm.kv["x"] == "1"
    nh.sync_close_session(s)


def test_membership_add_and_remove(cluster):
    hosts = cluster
    lid = wait_leader(hosts)
    nh = hosts[lid]
    m = nh.sync_get_shard_membership(1)
    assert sorted(m.addresses) == [1, 2, 3]
    # add a 4th replica
    addr4 = list(cluster.values())[0].config.raft_address.rsplit("-", 1)[0] + "-4"
    # generous timeout: this test runs late in the suite on a 1-core CI
    # box where neighbors can starve the engine past the 5 s default
    nh.sync_request_add_replica(1, 4, addr4, m.config_change_id,
                                timeout_s=20.0)
    nh4 = NodeHost(NodeHostConfig(raft_address=addr4, rtt_millisecond=5,
                                  ))
    try:
        cfg = Config(shard_id=1, replica_id=4, election_rtt=10, heartbeat_rtt=1)
        nh4.start_replica({}, True, KVStateMachine, cfg)
        s = nh.get_noop_session(1)
        nh.sync_propose(s, b"after=join")
        deadline = time.time() + 5
        while time.time() < deadline:
            if nh4.stale_read(1, "after") == "join":
                break
            time.sleep(0.02)
        assert nh4.stale_read(1, "after") == "join"
        m = nh.sync_get_shard_membership(1)
        assert sorted(m.addresses) == [1, 2, 3, 4]
        # remove it again
        nh.sync_request_delete_replica(1, 4, m.config_change_id,
                                       timeout_s=20.0)
        m = nh.sync_get_shard_membership(1)
        assert sorted(m.addresses) == [1, 2, 3]
        assert 4 in m.removed
    finally:
        nh4.close()


def test_leader_transfer(cluster):
    hosts = cluster
    lid = wait_leader(hosts)
    target = next(r for r in hosts if r != lid)
    # a transfer aborts if the target lags an election timeout behind
    # (raft.go leader-transfer abort); retry like the reference's tests do
    deadline = time.time() + 10
    next_request = 0.0
    while time.time() < deadline:
        nlid, ok = hosts[target].get_leader_id(1)
        if ok and nlid == target:
            break
        if time.time() >= next_request:
            lid2, ok2 = hosts[target].get_leader_id(1)
            if ok2 and lid2 in hosts:
                try:
                    hosts[lid2].request_leader_transfer(1, target)
                except Exception:
                    pass
            next_request = time.time() + 1.0
        time.sleep(0.02)
    assert hosts[target].get_leader_id(1)[0] == target


def test_snapshot_and_restart():
    prefix = f"nhS{time.monotonic_ns()}"
    hosts, addrs = make_cluster(addr_prefix=prefix)
    try:
        lid = wait_leader(hosts)
        nh = hosts[lid]
        s = nh.get_noop_session(1)
        for i in range(20):
            nh.sync_propose(s, f"k{i}={i}".encode())
        idx = nh.sync_request_snapshot(1)
        assert idx >= 20
        # restart one follower from its logdb (simulating process restart)
        frid = next(r for r in hosts if r != lid)
        old = hosts[frid]
        logdb = old.logdb
        old.close()
        nh2 = NodeHost(NodeHostConfig(raft_address=addrs[frid],
                                      rtt_millisecond=5),
                       logdb=logdb)
        hosts[frid] = nh2
        cfg = Config(shard_id=1, replica_id=frid, election_rtt=10,
                     heartbeat_rtt=1)
        nh2.start_replica(addrs, False, KVStateMachine, cfg)
        nh.sync_propose(s, b"post=restart")
        deadline = time.time() + 5
        while time.time() < deadline:
            if nh2.stale_read(1, "post") == "restart":
                break
            time.sleep(0.02)
        assert nh2.stale_read(1, "post") == "restart"
        assert nh2.stale_read(1, "k5") == "5"
    finally:
        for nh_ in hosts.values():
            nh_.close()


def test_partitioned_host_times_out():
    prefix = f"nhP{time.monotonic_ns()}"
    hosts, _ = make_cluster(addr_prefix=prefix)
    try:
        lid = wait_leader(hosts)
        nh = hosts[lid]
        # partition the leader's transport (monkey hook)
        for h in hosts.values():
            h.transport.partitioned = h is nh
        s = nh.get_noop_session(1)
        with pytest.raises(Exception):
            nh.sync_propose(s, b"lost=1", timeout_s=0.4)
        # heal; the cluster recovers (possibly with a new leader)
        for h in hosts.values():
            h.transport.partitioned = False
        lid2 = wait_leader(hosts)
        s2 = hosts[lid2].get_noop_session(1)
        hosts[lid2].sync_propose(s2, b"healed=1")
        assert hosts[lid2].sync_read(1, "healed") == "1"
    finally:
        for nh_ in hosts.values():
            nh_.close()


def test_node_host_info(cluster):
    hosts = cluster
    lid = wait_leader(hosts)
    info = hosts[lid].get_node_host_info()
    assert len(info.shard_info_list) == 1
    si = info.shard_info_list[0]
    assert si.shard_id == 1 and si.is_leader
    assert sorted(si.membership.addresses) == [1, 2, 3]
