"""KernelEngine end-to-end: device-resident shards behind the NodeHost
client API (VERDICT round-1 item 4 — the kernel serving real clients).

Scenarios mirror test_nodehost.py but with ``Config.device_resident=True``:
elections, linearizable writes/reads across hosts, snapshots+compaction,
leader transfer, eviction to the host engine, and a 1k-shard in-process
cluster on one kernel state.
"""

import time

from dragonboat_tpu.config import Config, ExpertConfig, NodeHostConfig
from dragonboat_tpu.nodehost import NodeHost

from test_nodehost import KVStateMachine, wait_leader


def propose_retry(nh, sess, cmd, timeout_s=10, deadline_s=20):
    """sync_propose with retry on the transient not-ready/timeout drops
    raft legitimately returns right after elections (ErrShardNotReady
    semantics — the reference tells callers to retry)."""
    import time as _t

    from dragonboat_tpu.request import RequestDroppedError, RequestTimeoutError

    end = _t.time() + deadline_s
    while True:
        try:
            return nh.sync_propose(sess, cmd, timeout_s=timeout_s)
        except (RequestDroppedError, RequestTimeoutError):
            if _t.time() > end:
                raise
            _t.sleep(0.1)


def make_cluster(prefix, n=3, snapshot_entries=0, rtt_ms=5, shards=(1,),
                 expert=None):
    addrs = {i: f"{prefix}-{i}" for i in range(1, n + 1)}
    hosts = {}
    for rid, addr in addrs.items():
        nh = NodeHost(NodeHostConfig(
            raft_address=addr, rtt_millisecond=rtt_ms,
            expert=expert or ExpertConfig(kernel_log_cap=256,
                                          kernel_capacity=max(8, len(shards)),
                                          kernel_apply_batch=16,
                                          kernel_compaction_overhead=16)))
        for sid in shards:
            cfg = Config(shard_id=sid, replica_id=rid, election_rtt=10,
                         heartbeat_rtt=2, snapshot_entries=snapshot_entries,
                         compaction_overhead=5, device_resident=True)
            nh.start_replica(addrs, False, KVStateMachine, cfg)
        hosts[rid] = nh
    return hosts


def close_all(hosts):
    for nh in hosts.values():
        nh.close()


def test_kernel_shard_is_device_resident():
    hosts = make_cluster("kdr")
    try:
        nh = hosts[1]
        assert nh.kernel_engine is not None
        assert 1 in nh.kernel_engine.by_shard
        assert nh.nodes[1].peer is None  # protocol state lives on device
    finally:
        close_all(hosts)


def test_kernel_propose_and_read():
    hosts = make_cluster("kpr")
    try:
        lead = wait_leader(hosts, timeout=30)
        nh = hosts[lead]
        sess = nh.get_noop_session(1)
        for i in range(10):
            propose_retry(nh, sess, f"k{i}=v{i}".encode())
        assert nh.sync_read(1, "k7", timeout_s=10) == "v7"
        # replication reached the other hosts
        deadline = time.time() + 10
        while time.time() < deadline:
            if all(h.stale_read(1, "k9") == "v9" for h in hosts.values()):
                break
            time.sleep(0.05)
        assert all(h.stale_read(1, "k9") == "v9" for h in hosts.values())
    finally:
        close_all(hosts)


def test_kernel_read_from_follower_host():
    """ReadIndex forwarded from a follower host to the leader lane."""
    hosts = make_cluster("kfr")
    try:
        lead = wait_leader(hosts, timeout=30)
        nh = hosts[lead]
        propose_retry(nh, nh.get_noop_session(1), b"fw=ok")
        follower = next(r for r in hosts if r != lead)
        deadline = time.time() + 10
        val = None
        while time.time() < deadline:
            try:
                val = hosts[follower].sync_read(1, "fw", timeout_s=3)
                if val == "ok":
                    break
            except Exception:
                time.sleep(0.1)
        assert val == "ok"
    finally:
        close_all(hosts)


def test_kernel_snapshot_and_compaction():
    hosts = make_cluster("ksn", snapshot_entries=12)
    try:
        lead = wait_leader(hosts, timeout=30)
        nh = hosts[lead]
        sess = nh.get_noop_session(1)
        for i in range(30):
            propose_retry(nh, sess, f"s{i}=v{i}".encode())
        # auto-snapshot fired on the leader
        deadline = time.time() + 10
        node = nh.nodes[1]
        while time.time() < deadline and node.compacted_to == 0:
            time.sleep(0.05)
        assert node.compacted_to > 0
        assert nh.sync_read(1, "s29", timeout_s=10) == "v29"
        # manual snapshot API also works on a kernel shard
        idx = nh.sync_request_snapshot(1, timeout_s=10)
        assert idx > 0
    finally:
        close_all(hosts)


def test_kernel_leader_transfer():
    hosts = make_cluster("ktr")
    try:
        lead = wait_leader(hosts, timeout=30)
        target = next(r for r in hosts if r != lead)
        # a transfer that cannot finish within one election timeout is
        # ABORTED by design (raft.go:391 timeToAbortLeaderTransfer, p29
        # of the thesis) and the client retries — on this 1-core box the
        # ~50 ms abort window races multi-ms jitted steps, so retry like
        # a real client; exactly-one attempt succeeding is not a raft
        # guarantee
        r = None
        for _ in range(5):
            lead_now = wait_leader(hosts, timeout=30)
            node = hosts[lead_now].nodes[1]
            rs = node.request_leader_transfer(target, 2000)
            hosts[lead_now]._work.set()
            r = rs.wait(15.0)
            if r.code.name == "COMPLETED" or lead_now == target:
                break
        assert r is not None and (r.code.name == "COMPLETED"
                                  or wait_leader(hosts) == target), r.code
        assert wait_leader(hosts, timeout=30) == target
    finally:
        close_all(hosts)


def test_kernel_eviction_to_host_engine():
    """The needs_host slow path: a lane leaves the kernel and continues as
    a pycore Node with every future/book intact."""
    hosts = make_cluster("kev")
    try:
        lead = wait_leader(hosts, timeout=30)
        nh = hosts[lead]
        sess = nh.get_noop_session(1)
        propose_retry(nh, sess, b"pre=evict")
        knode = nh.kernel_engine.by_shard[1]
        with nh.kernel_engine.mu:
            nh.kernel_engine._evict(knode, reason="test")
        node = nh.nodes[1]
        assert node is not knode
        assert node.peer is not None  # host-resident now
        assert nh.stale_read(1, "pre") == "evict"  # SM survived the move
        # the shard keeps serving (possibly after a re-election)
        deadline = time.time() + 30
        ok = False
        while time.time() < deadline and not ok:
            try:
                nh2 = hosts[wait_leader(hosts, timeout=10)]
                nh2.sync_propose(nh2.get_noop_session(1), b"post=evict",
                                 timeout_s=3)
                ok = nh2.sync_read(1, "post", timeout_s=3) == "evict"
            except Exception:
                time.sleep(0.2)
        assert ok
    finally:
        close_all(hosts)


def test_kernel_restart_from_disk(tmp_path):
    """Device-resident shards over durable tan dirs: close, reopen, the
    lane re-injects from persisted state with data intact."""
    addrs = {1: "krs-1"}
    def mk():
        nh = NodeHost(NodeHostConfig(
            raft_address="krs-1", rtt_millisecond=5,
            node_host_dir=str(tmp_path),
            expert=ExpertConfig(kernel_log_cap=256, kernel_capacity=4)))
        nh.start_replica(addrs, False, KVStateMachine, Config(
            shard_id=1, replica_id=1, election_rtt=10, heartbeat_rtt=2,
            device_resident=True))
        deadline = time.time() + 15
        while time.time() < deadline and not nh.get_leader_id(1)[1]:
            time.sleep(0.02)
        return nh

    nh = mk()
    sess = nh.get_noop_session(1)
    for i in range(15):
        propose_retry(nh, sess, f"d{i}=v{i}".encode())
    nh.close()

    nh = mk()
    try:
        deadline = time.time() + 10
        while time.time() < deadline:
            if nh.stale_read(1, "d14") == "v14":
                break
            time.sleep(0.05)
        for i in range(15):
            assert nh.stale_read(1, f"d{i}") == f"v{i}", i
        propose_retry(nh, nh.get_noop_session(1), b"dz=zz")
        assert nh.sync_read(1, "dz", timeout_s=10) == "zz"
    finally:
        nh.close()


def test_sequential_config_changes_on_kernel_shard():
    """A lane must accept a SECOND config change after the first applies:
    the one-in-flight CC gate releases at apply time (pycore add_node/
    add_non_voting clear pending_config_change; the engine mirrors that
    by clearing the lane's pending_cc in update_lane_membership).  A
    regression here limits every device shard to one membership change
    per lifetime, dropping all later ones."""
    hosts = make_cluster(f"cc2-{time.monotonic_ns()}")
    try:
        lid = wait_leader(hosts, timeout=30)
        nh = hosts[lid]
        for rid in (8, 9):   # two back-to-back CCs through the lane
            deadline = time.time() + 30
            while True:
                try:
                    nh.sync_request_add_nonvoting(
                        1, rid, f"cc2-nv-{rid}", 0, timeout_s=10)
                    break
                except Exception:
                    if time.time() > deadline:
                        raise
                    time.sleep(0.2)
        m = nh.sync_get_shard_membership(1, timeout_s=10)
        assert 8 in m.non_votings and 9 in m.non_votings
        assert 1 in nh.kernel_engine.by_shard  # still device-resident
    finally:
        close_all(hosts)
