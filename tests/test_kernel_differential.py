"""Kernel ↔ pycore differential conformance.

Drives the batched device kernel (core/kernel.py) and the host protocol core
(core/pycore.py — itself cited line-by-line against
/root/reference/internal/raft/raft.go) on IDENTICAL schedules of ticks,
proposals, reads, transfers and partitions, each over its own step-structured
message router, then compares converged per-replica state exactly:
term, vote, leader, role, committed, last index and the full log-term array.

Lockstep randomness: both engines draw election timeouts from the shared
splitmix32 counter hash (core/params.py randomized_timeout) keyed by the same
per-row seed, and reset the draw at the same protocol points, so elections
happen on the same tick on both sides and winners match identically —
the etcd-suite scenario families (raft_etcd_test.go:2896-3036) are replayed
here against the kernel with pycore as the oracle.
"""

from __future__ import annotations

import os

import numpy as np
import pytest

from dragonboat_tpu import raftpb as pb
from dragonboat_tpu.core import params as KP
from dragonboat_tpu.core.logentry import InMemoryLogDB
from dragonboat_tpu.core.pycore import CoreConfig, Raft

from tests.kernel_harness import KernelCluster

MT = pb.MessageType


class LockstepRng:
    """pycore rng drawing the kernel's splitmix32 sequence for one row."""

    def __init__(self, seed: int) -> None:
        self.seed = int(seed)
        self.counter = -1  # first draw (Raft.__init__) uses counter 0

    def __call__(self, n: int) -> int:
        self.counter += 1
        return KP.randomized_timeout(self.seed, self.counter, n) - n


class PyMirror:
    """pycore cluster stepped with the kernel's exact discipline:
    ≤K inbox messages, then read, then proposals, then transfer, then tick;
    outputs collected at step end and delivered next step."""

    def __init__(self, kc: KernelCluster, election: int = 10,
                 heartbeat: int = 1, check_quorum: bool = False,
                 pre_vote: bool = False) -> None:
        self.kc = kc
        self.n, self.p = kc.n, kc.p
        self.G = kc.G
        self.K = kc.kp.inbox_cap
        seeds = np.asarray(kc.state.seed)
        self.rafts: list[Raft] = []
        wits = kc.witnesses
        voters = [q for q in range(1, self.p + 1) if q not in wits]
        for row in range(self.G):
            rid = row % self.p + 1
            cfg = CoreConfig(
                shard_id=row // self.p + 1, replica_id=rid,
                election_rtt=election, heartbeat_rtt=heartbeat,
                check_quorum=check_quorum, pre_vote=pre_vote,
                is_witness=rid in wits,
                # lockstep with the kernel's fixed E-entry replicate lanes
                max_entries_per_msg=kc.kp.msg_entries,
            )
            r = Raft(cfg, InMemoryLogDB(), rng=LockstepRng(seeds[row]))
            r.set_initial_members({q: f"a{q}" for q in voters}, {},
                                  {q: f"a{q}" for q in wits})
            self.rafts.append(r)
        self.pending: list[list[pb.Message]] = [[] for _ in range(self.G)]
        self.dropped_pairs: set[tuple[int, int]] = set()
        self.isolated: set[int] = set()
        self._prev_committed = [0] * self.G

    def row(self, group: int, rid: int) -> int:
        return group * self.p + (rid - 1)

    def step(self, tick=False, proposals=None, reads=None, transfers=None):
        # applied cursor mirrors the kernel's 1-step-lagged processed sync
        for row, r in enumerate(self.rafts):
            r.applied = max(r.applied, self._prev_committed[row])
        for row, r in enumerate(self.rafts):
            q = self.pending[row][: self.K]
            self.pending[row] = self.pending[row][self.K:]
            for m in q:
                r.handle(m)
            # local inputs are gated on END-OF-INBOX leadership, exactly
            # like the kernel (can_prop / ri_req are masked on is_leader
            # after the inbox scan).  pycore itself implements the
            # reference's follower FORWARDING (raft.go handleFollowerPropose
            # / handleFollowerReadIndex); the kernel's documented contract
            # instead host-routes to the leader and DROPS stale feeds, so
            # the mirror must feed with the kernel's discipline or a
            # proposal landing on a just-deposed leader diverges (the
            # forwarded copy appends on the new leader only in pycore —
            # found by the seed soak).
            if reads and row in reads and r.is_leader():
                lo, hi = reads[row]
                r.handle(pb.Message(type=MT.READ_INDEX, from_=r.replica_id,
                                    hint=lo, hint_high=hi))
            if proposals and row in proposals and r.is_leader():
                spec = proposals[row]
                if isinstance(spec, int):
                    spec = [False] * spec
                ents = tuple(
                    pb.Entry(type=pb.EntryType.CONFIG_CHANGE,
                             cmd=pb.encode_config_change(pb.ConfigChange()))
                    if is_cc else pb.Entry(cmd=b"x")
                    for is_cc in spec[: self.kc.kp.proposal_cap]
                )
                if ents:
                    r.handle(pb.Message(type=MT.PROPOSE, from_=r.replica_id,
                                        entries=ents))
            if transfers and row in transfers and r.is_leader():
                r.handle(pb.Message(type=MT.LEADER_TRANSFER,
                                    to=r.replica_id, hint=transfers[row]))
            if tick:
                r.handle(pb.Message(type=MT.LOCAL_TICK, reject=False))
        # collect + route
        for row, r in enumerate(self.rafts):
            group = row // self.p
            self._prev_committed[row] = r.log.committed
            msgs, r.msgs = r.msgs, []
            if row in self.isolated:
                continue
            for m in msgs:
                if m.is_local():
                    continue
                to_row = self.row(group, m.to) if 1 <= m.to <= self.p else None
                if to_row is None:
                    continue
                if to_row in self.isolated or (row, to_row) in self.dropped_pairs:
                    continue
                self.pending[to_row].append(m)

    def quiesced(self) -> bool:
        return all(not q for q in self.pending)


class DiffCluster:
    """Drives KernelCluster + PyMirror on one schedule."""

    def __init__(self, groups=2, replicas=3, election=10, heartbeat=1,
                 check_quorum=False, pre_vote=False, witnesses=frozenset(),
                 kp=None):
        self.kc = KernelCluster(groups, replicas, election=election,
                                heartbeat=heartbeat,
                                check_quorum=check_quorum, pre_vote=pre_vote,
                                witnesses=witnesses, kp=kp)
        self.pm = PyMirror(self.kc, election=election, heartbeat=heartbeat,
                           check_quorum=check_quorum, pre_vote=pre_vote)
        self.groups, self.replicas = groups, replicas

    def step(self, **kw):
        self.kc.step(**kw)
        self.pm.step(**kw)

    def isolate(self, row: int) -> None:
        self.kc.isolated.add(row)
        self.pm.isolated.add(row)

    def heal(self) -> None:
        self.kc.isolated.clear()
        self.kc.dropped_pairs.clear()
        self.pm.isolated.clear()
        self.pm.dropped_pairs.clear()

    def drain(self, steps=8):
        for _ in range(steps):
            self.step()

    def _sigs(self):
        kc = self.kc
        sig_k = (tuple(int(x) for x in kc.field("term")),
                 tuple(int(x) for x in kc.field("committed")),
                 tuple(int(x) for x in kc.field("last")))
        sig_p = (tuple(r.term for r in self.pm.rafts),
                 tuple(r.log.committed for r in self.pm.rafts),
                 tuple(r.log.last_index() for r in self.pm.rafts))
        return sig_k, sig_p

    def settle(self, max_cycles=12):
        """Tick+drain until both engines reach a stable COMMON signature.

        The kernel coalesces sends (<=1 replicate per peer per step,
        kernel.py header) while pycore sends per-trigger, so CATCH-UP
        TRAJECTORIES legitimately differ in pacing; the differential
        invariant is the CONVERGED state.  A fixed drain window can
        snapshot the two engines mid-catch-up (soak seed 172) — settle
        until their scalar signatures match and stop moving."""
        prev = None
        for _ in range(max_cycles):
            self.run_ticks(6)
            self.drain(12)
            sig_k, sig_p = self._sigs()
            if sig_k == sig_p and sig_k == prev:
                return
            prev = sig_k
        # fall through: compare() reports the precise field that differs

    def settle_each(self, max_cycles=60):
        """Tick+drain until EACH engine's own signature stops moving —
        for chaos schedules where the engines ride different (both
        correct) trajectories and never become bitwise equal.  A healed
        cluster can take many election rounds to re-stabilize when a
        formerly isolated replica rejoins as a disruptive higher-term
        candidate (the classic scenario pre-vote exists to soften), so
        the cycle budget is generous."""
        prev = None
        stable = 0
        for _ in range(max_cycles):
            self.run_ticks(6)
            self.drain(12)
            sig = self._sigs()
            stable = stable + 1 if sig == prev else 0
            if stable >= 3:  # quiet for 3 consecutive cycles
                return
            prev = sig

    def run_ticks(self, n: int) -> None:
        for _ in range(n):
            self.step(tick=True)

    def tick_until_leader(self, max_ticks=300) -> None:
        for _ in range(max_ticks):
            self.step(tick=True)
            if all(self.kc.leader_row(g) is not None
                   for g in range(self.groups)):
                self.drain()
                return
        raise AssertionError("kernel elected no leader")

    # -- the differential assertion ------------------------------------

    def compare(self, ctx: str = "") -> None:
        kc, pm = self.kc, self.pm
        term = kc.field("term")
        vote = kc.field("vote")
        leader = kc.field("leader")
        role = kc.field("role")
        committed = kc.field("committed")
        last = kc.field("last")
        snap = kc.field("snap_index")
        lt = kc.field("lt")
        CAP = kc.kp.log_cap
        for row in range(kc.G):
            r = pm.rafts[row]
            where = f"{ctx} row={row} rid={row % kc.p + 1}"
            assert int(term[row]) == r.term, \
                f"{where}: term {term[row]} != {r.term}"
            assert int(vote[row]) == r.vote, \
                f"{where}: vote {vote[row]} != {r.vote}"
            assert int(leader[row]) == r.leader_id, \
                f"{where}: leader {leader[row]} != {r.leader_id}"
            assert int(role[row]) == int(r.state), \
                f"{where}: role {role[row]} != {int(r.state)}"
            assert int(committed[row]) == r.log.committed, \
                f"{where}: committed {committed[row]} != {r.log.committed}"
            assert int(last[row]) == r.log.last_index(), \
                f"{where}: last {last[row]} != {r.log.last_index()}"
            for i in range(int(snap[row]) + 1, int(last[row]) + 1):
                kt = int(lt[row, i & (CAP - 1)])
                pt = r.log.term(i)
                assert kt == pt, f"{where}: log[{i}] term {kt} != {pt}"


# ---------------------------------------------------------------------------
# scenario families (raft_etcd_test.go network-harness ports, kernel target)
# ---------------------------------------------------------------------------


def test_diff_election_convergence():
    d = DiffCluster(groups=3, replicas=3)
    d.tick_until_leader()
    d.compare("election")


def test_diff_election_5_replicas():
    d = DiffCluster(groups=2, replicas=5)
    d.tick_until_leader()
    d.compare("election5")


def test_diff_prevote_election():
    d = DiffCluster(groups=2, replicas=3, pre_vote=True)
    d.tick_until_leader()
    d.compare("prevote")


def test_diff_replication():
    d = DiffCluster(groups=2, replicas=3)
    d.tick_until_leader()
    for burst in (1, 3, 2):
        props = {}
        for g in range(d.groups):
            lr = d.kc.leader_row(g)
            assert lr is not None
            props[lr] = burst
        d.step(proposals=props)
        d.drain()
    d.compare("replication")


def test_diff_heartbeat_maintenance():
    d = DiffCluster(groups=2, replicas=3)
    d.tick_until_leader()
    d.run_ticks(30)  # heartbeats flow; no new elections on either side
    d.drain()
    d.compare("heartbeats")


def test_diff_leader_isolation_reelection():
    """Old leader isolated with uncommitted entries; cluster re-elects;
    heal → old leader's conflicting suffix is overwritten on both engines
    (the etcd figure-8 family)."""
    d = DiffCluster(groups=1, replicas=3)
    d.tick_until_leader()
    lr = d.kc.leader_row(0)
    d.step(proposals={lr: 2})
    d.drain()
    d.compare("pre-partition")
    d.isolate(lr)
    # leader appends entries nobody sees
    d.step(proposals={lr: 2})
    # the rest re-elect
    for _ in range(200):
        d.step(tick=True)
        new_lr = d.kc.leader_row(0)
        if new_lr is not None and new_lr != lr:
            break
    else:
        raise AssertionError("no re-election while old leader isolated")
    d.drain()
    props = {new_lr: 1}
    d.step(proposals=props)
    d.drain()
    d.heal()
    # old leader rejoins, gets folded back and overwritten
    d.run_ticks(6)
    d.drain(12)
    d.compare("post-heal")


def test_diff_leader_transfer():
    d = DiffCluster(groups=1, replicas=3)
    d.tick_until_leader()
    lr = d.kc.leader_row(0)
    target_rid = (lr % 3) + 1  # some other replica id in [1..3]
    if target_rid == lr % 3 + 1 and target_rid == (lr % d.replicas) + 1:
        pass
    d.step(proposals={lr: 1})
    d.drain()
    d.step(transfers={lr: target_rid})
    d.drain(12)
    d.compare("transfer")
    assert d.kc.leader_row(0) == d.kc.row(0, target_rid)


def test_diff_readindex():
    d = DiffCluster(groups=1, replicas=3)
    d.tick_until_leader()
    lr = d.kc.leader_row(0)
    d.step(proposals={lr: 2})
    d.drain()
    out = d.kc.step(reads={lr: (7, 9)})
    d.pm.step(reads={lr: (7, 9)})
    d.drain()
    d.compare("readindex")
    # the kernel read context resolves to the same index pycore reports
    rtrs = np.asarray(d.kc.last_out.rtr_valid) if d.kc.last_out else None
    assert rtrs is not None


def test_diff_check_quorum_step_down():
    d = DiffCluster(groups=1, replicas=3, check_quorum=True)
    d.tick_until_leader()
    lr = d.kc.leader_row(0)
    for row in range(3):
        if row != lr:
            d.isolate(row)
    # leader loses contact; checkQuorum folds it back to follower in
    # lockstep on both engines
    d.run_ticks(25)
    d.compare("checkquorum")
    assert d.kc.leader_row(0) is None


def _random_schedule(d, rng, step_no, partitions: bool):
    ev = rng.random()
    if ev < 0.55:
        d.step(tick=True)
    elif ev < 0.75:
        props = {}
        for g in range(d.groups):
            lr = d.kc.leader_row(g)
            if lr is not None:
                props[lr] = int(rng.integers(1, 4))
        d.step(tick=bool(rng.random() < 0.5), proposals=props)
    elif ev < 0.85 or not partitions:
        reads = {}
        for g in range(d.groups):
            lr = d.kc.leader_row(g)
            if lr is not None:
                reads[lr] = (step_no, g)
        d.step(reads=reads)
    elif ev < 0.95 and not d.kc.isolated:
        d.isolate(int(rng.integers(0, d.kc.G)))
        d.step(tick=True)
    else:
        d.heal()
        d.step(tick=True)


@pytest.mark.parametrize("seed", [7, 23, 106, 109, 172, 1009, 2024])
def test_diff_randomized_trace(seed):
    """300-step seeded random schedule: ticks, proposal bursts on current
    leaders, reads.  PARTITION-FREE, so the two engines stay in exact
    lockstep (no catch-up windows) and converged state must match
    bitwise.  Partitioned schedules go through
    test_chaos_randomized_safety instead: the kernel's documented
    coalesced flow control (<=1 replicate per peer per step) paces
    partition recovery differently from pycore's per-trigger sends, and
    an election during a pacing-divergent catch-up window can
    legitimately resolve differently — both trajectories are correct
    raft, so bitwise equality is not an invariant there (the 80-seed
    soak demonstrated exactly this)."""
    rng = np.random.default_rng(seed)
    d = DiffCluster(groups=2, replicas=3)
    d.tick_until_leader()
    for step_no in range(300):
        _random_schedule(d, rng, step_no, partitions=False)
    d.settle()
    d.compare("random-trace")


@pytest.mark.parametrize("cfg", [
    {"pre_vote": True},
    {"check_quorum": True},
    {"pre_vote": True, "check_quorum": True},
])
@pytest.mark.parametrize("seed", [11, 15])
def test_diff_randomized_trace_configs(seed, cfg):
    """The partition-free lockstep family under the pre-vote /
    check-quorum config variants — the drop_rv lease and pre-vote
    campaign paths under randomized schedules."""
    rng = np.random.default_rng(seed)
    d = DiffCluster(groups=2, replicas=3, **cfg)
    d.tick_until_leader()
    for step_no in range(300):
        _random_schedule(d, rng, step_no, partitions=False)
    d.settle()
    d.compare(f"random-trace {cfg}")


@pytest.mark.parametrize("seed", [106, 172, 307, 2024, 9090])
def test_chaos_randomized_safety(seed):
    """Randomized schedule WITH partitions: each engine is a correct raft
    cluster on a (possibly diverging) trajectory, so the assertion is
    RAFT SAFETY per engine after heal+settle — one leader per group,
    replicas of a group hold identical logs, commit within bounds —
    the monkey-harness convergence discipline (docs/test.md) applied to
    both engines rather than bitwise cross-engine equality."""
    rng = np.random.default_rng(seed)
    d = DiffCluster(groups=2, replicas=3)
    d.tick_until_leader()
    for step_no in range(300):
        _random_schedule(d, rng, step_no, partitions=True)
    d.heal()
    d.settle_each()
    kc = d.kc
    role = kc.field("role")
    term = kc.field("term")
    last = kc.field("last")
    committed = kc.field("committed")
    snap = kc.field("snap_index")
    lt = kc.field("lt")
    CAP = kc.kp.log_cap
    for g in range(d.groups):
        rows = list(range(g * 3, g * 3 + 3))
        # exactly one leader, all replicas on its term
        leaders = [r for r in rows if int(role[r]) == KP.LEADER]
        assert len(leaders) == 1, f"group {g}: leaders {leaders}"
        assert len({int(term[r]) for r in rows}) == 1, f"group {g} terms"
        # replicas converged to identical logs and commit
        assert len({int(last[r]) for r in rows}) == 1, f"group {g} last"
        assert len({int(committed[r]) for r in rows}) == 1, f"group {g}"
        lo = max(int(snap[r]) for r in rows) + 1
        hi = int(last[rows[0]])
        for i in range(lo, hi + 1):
            ts = {int(lt[r, i & (CAP - 1)]) for r in rows}
            assert len(ts) == 1, f"group {g} log[{i}] terms {ts}"
        assert 0 < int(committed[rows[0]]) <= hi
    # pycore side: same safety on its own trajectory
    for g in range(d.groups):
        rafts = [d.pm.rafts[r] for r in range(g * 3, g * 3 + 3)]
        assert sum(r.is_leader() for r in rafts) == 1
        assert len({r.term for r in rafts}) == 1
        assert len({r.log.last_index() for r in rafts}) == 1
        assert len({r.log.committed for r in rafts}) == 1
        hi = rafts[0].log.last_index()
        for i in range(1, hi + 1):
            assert len({r.log.term(i) for r in rafts}) == 1, (g, i)


# ---------------------------------------------------------------------------
# witness family (VERDICT r2 weak #8: witness coverage on the kernel path)
# ---------------------------------------------------------------------------


def test_diff_witness_election_and_replication():
    """2 voters + 1 witness: the witness never campaigns, counts toward
    quorum, and tracks the log (terms only) — kernel and pycore in
    bitwise lockstep."""
    d = DiffCluster(groups=2, replicas=3, witnesses={3})
    d.tick_until_leader()
    role = d.kc.field("role")
    for g in range(d.groups):
        assert int(role[d.kc.row(g, 3)]) == KP.WITNESS
    for burst in (2, 1, 3):
        props = {}
        for g in range(d.groups):
            lr = d.kc.leader_row(g)
            assert lr is not None
            assert lr % d.kc.p + 1 != 3, "witness became leader"
            props[lr] = burst
        d.step(proposals=props)
        d.drain()
    d.compare("witness-replication")


def test_diff_witness_sustains_quorum_with_voter_down():
    """With one voter isolated, commits require the witness ack: 2
    voters + 1 witness keeps quorum 2 through (leader, witness)."""
    d = DiffCluster(groups=1, replicas=3, witnesses={3})
    d.tick_until_leader()
    lr = d.kc.leader_row(0)
    other_voter = next(
        r for r in range(d.kc.G)
        if r != lr and (r % d.kc.p + 1) != 3)
    d.isolate(other_voter)
    for _ in range(3):
        d.step(proposals={lr: 2})
        d.drain()
    committed = d.kc.field("committed")
    assert int(committed[lr]) >= 6, "commits stalled without witness acks"
    d.heal()
    d.settle()
    d.compare("witness-quorum")


@pytest.mark.parametrize("seed", [13, 77])
def test_diff_witness_randomized_trace(seed):
    """The partition-free lockstep family with a witness member."""
    rng = np.random.default_rng(seed)
    d = DiffCluster(groups=2, replicas=3, witnesses={3})
    d.tick_until_leader()
    for step_no in range(300):
        _random_schedule(d, rng, step_no, partitions=False)
    d.settle()
    d.compare("witness-random-trace")


@pytest.mark.parametrize("seed", [5, 42])
def test_diff_onehot_reads_lockstep(seed):
    """The platform-tuned read lowering (KernelParams.onehot_reads:
    one-hot select on device, dynamic indexing on CPU — kernel._get1,
    router pick/take) must stay BITWISE identical across the flag.
    Phase plan: elect, drop storm, write load, mixed reads — every
    state leaf compared bitwise at each phase end."""
    import dataclasses

    import jax

    from dragonboat_tpu.bench_loop import (
        bench_params,
        make_cluster,
        run_steps,
        run_steps_mixed,
        run_steps_storm,
        elect_all,
    )

    def drive(kp):
        state, box = elect_all(kp, 3, make_cluster(kp, 64, 3))
        snaps = [jax.tree_util.tree_map(np.asarray, state)]
        state, box = run_steps_storm(kp, 3, 40, 0.25, seed, state, box)
        snaps.append(jax.tree_util.tree_map(np.asarray, state))
        state, box = run_steps(kp, 3, 30, True, True, state, box)
        snaps.append(jax.tree_util.tree_map(np.asarray, state))
        state, box, _ = run_steps_mixed(
            kp, 3, 20, max(1, kp.proposal_cap // 8),
            np.int32(7), state, box, np.int32(0))
        snaps.append(jax.tree_util.tree_map(np.asarray, state))
        return snaps

    kp = bench_params(3)
    a = drive(dataclasses.replace(kp, onehot_reads=False))
    b = drive(dataclasses.replace(kp, onehot_reads=True))
    for phase, (sa, sb) in enumerate(zip(a, b)):
        for name, va, vb in zip(sa._fields, sa, sb):
            assert np.array_equal(va, vb), \
                f"phase {phase} field {name} diverged (seed {seed})"


@pytest.mark.skipif(os.environ.get("DBT_SLOW_DIFF") != "1",
                    reason="XLA:CPU compile of the unrolled body exceeded "
                           "50 CPU-minutes at toy geometry on the 1-core "
                           "box (2026-07-31); DBT_SLOW_DIFF=1 runs it")
@pytest.mark.parametrize("seed", [9])
def test_diff_unroll_scans_lockstep(seed):
    """lax.scan unroll for the family scans (KernelParams.unroll_scans —
    the TPU serial-launch lever the ladder A/Bs) must stay BITWISE
    identical to the rolled form.  unroll= is lax.scan's own scheduling
    parameter with a library-level equivalence contract; this test
    exists to catch an XLA unroll miscompile, not a semantics change.  Env-gated: the unrolled
    XLA:CPU compile is pathologically slow (see skip reason) — run it
    deliberately on a box with headroom, or on TPU where compile is
    tractable, before trusting a ladder A/B that favors the unrolled
    form."""
    import dataclasses

    import jax

    from dragonboat_tpu.bench_loop import (
        make_cluster,
        run_steps,
        run_steps_mixed,
        run_steps_storm,
        elect_all,
    )
    from dragonboat_tpu.core import params as KP

    base = KP.KernelParams(
        num_peers=3, log_cap=32, inbox_cap=10, msg_entries=4,
        proposal_cap=4, readindex_cap=4, apply_batch=8,
        compaction_overhead=4,
    )

    def drive(kp):
        state, box = elect_all(kp, 3, make_cluster(kp, 16, 3))
        snaps = [jax.tree_util.tree_map(np.asarray, state)]
        state, box = run_steps_storm(kp, 3, 30, 0.25, seed, state, box)
        snaps.append(jax.tree_util.tree_map(np.asarray, state))
        state, box = run_steps(kp, 3, 20, True, True, state, box)
        snaps.append(jax.tree_util.tree_map(np.asarray, state))
        state, box, _ = run_steps_mixed(
            kp, 3, 10, 1, np.int32(7), state, box, np.int32(0))
        snaps.append(jax.tree_util.tree_map(np.asarray, state))
        return snaps

    a = drive(base)
    b = drive(dataclasses.replace(base, unroll_scans=True))
    for phase, (sa, sb) in enumerate(zip(a, b)):
        for name, va, vb in zip(sa._fields, sa, sb):
            assert np.array_equal(va, vb), \
                f"phase {phase} field {name} diverged (seed {seed})"
