"""Direct unit coverage for the sharded proposal book (request.py —
parity request.go:524 pendingProposal's keyed shards): registration,
completion, commit notification, timeout GC and termination must behave
identically across shard boundaries."""

import threading

from dragonboat_tpu.request import PendingProposal, RequestResultCode
from dragonboat_tpu.client import Session
from dragonboat_tpu.statemachine import Result


def _book(shards=4):
    return PendingProposal(shards=shards)


def _noop_session():
    return Session.new_noop_session(1)


def test_propose_applied_across_shards():
    book = _book()
    states = []
    for i in range(16):  # keys cover every shard several times
        rs, entry = book.propose(_noop_session(), b"x", 100)
        states.append((rs, entry))
    for rs, entry in states:
        book.applied(entry.key, 0, 0, Result(value=entry.key), False)
    for rs, entry in states:
        assert rs.wait(1.0).code == RequestResultCode.COMPLETED
        assert rs.wait(1.0).result.value == entry.key


def test_committed_then_applied_fires_both():
    book = _book()
    rs, entry = book.propose(_noop_session(), b"x", 100)
    book.committed(entry.key)
    assert rs.committed_event.wait(1.0)
    book.applied(entry.key, 0, 0, Result(), False)
    assert rs.wait(1.0).code == RequestResultCode.COMPLETED


def test_gc_times_out_only_expired():
    book = _book()
    rs_short, e_short = book.propose(_noop_session(), b"x", 2)
    rs_long, e_long = book.propose(_noop_session(), b"x", 100)
    for _ in range(3):
        book.advance()
    book.gc()
    assert rs_short.wait(1.0).code == RequestResultCode.TIMEOUT
    assert not rs_long._event.is_set()
    book.applied(e_long.key, 0, 0, Result(), False)
    assert rs_long.wait(1.0).code == RequestResultCode.COMPLETED


def test_terminate_all_covers_every_shard():
    book = _book()
    states = [book.propose(_noop_session(), b"x", 100)[0] for _ in range(9)]
    book.terminate_all()
    assert all(rs.wait(1.0).code == RequestResultCode.TERMINATED
               for rs in states)
    assert book.pending == {}


def test_concurrent_propose_complete():
    book = _book()
    done = []
    mu = threading.Lock()

    def worker():
        for _ in range(50):
            rs, entry = book.propose(_noop_session(), b"x", 100)
            book.applied(entry.key, 0, 0, Result(value=entry.key), False)
            r = rs.wait(1.0)
            with mu:
                done.append(r.code)

    ts = [threading.Thread(target=worker) for _ in range(4)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert len(done) == 200
    assert all(c == RequestResultCode.COMPLETED for c in done)
    assert book.pending == {}
