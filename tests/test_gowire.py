"""Go-wire interop codec (raftpb/gowire.py) — three layers of evidence:

1. **Hand-traced golden fixtures**: exact byte strings traced from the
   reference's generated marshal code (file:line cited per fixture).
   The build image has no Go toolchain, so these are the closest thing
   to reference-emitted bytes available; each was written by following
   the cited marshaler statement by statement.
2. **protobuf cross-oracle**: a reconstructed raft.proto compiled with
   protoc; python-protobuf must parse gowire's bytes to the same field
   values, and gowire must decode python-protobuf's serialization.
   This independently checks every tag number and wire type (Colfer
   entries excluded — protobuf can't speak Colfer).
3. **Round-trips** over randomized values, including the >= 2**49
   fixed64 Colfer arm and truncation robustness.
"""

import random

import pytest

from dragonboat_tpu import raftpb as pb
from dragonboat_tpu.raftpb import gowire as gw


# --------------------------------------------------------------------------
# 1. golden fixtures
# --------------------------------------------------------------------------


def test_golden_state():
    # state.go:27-41: tag 0x8 term, 0x10 vote, 0x18 commit — all always
    # emitted. term=1 vote=2 commit=300 (300 = 0xAC 0x02 varint).
    got = gw.encode_state(pb.State(term=1, vote=2, commit=300))
    assert got == bytes([0x08, 1, 0x10, 2, 0x18, 0xAC, 0x02])
    # zero state still emits all three fields (gogo nullable=false)
    assert gw.encode_state(pb.State()) == bytes([0x08, 0, 0x10, 0, 0x18, 0])


def test_golden_entry_colfer():
    # raft_optimized.go:166-301. Fields: 0 term, 1 index, 2 type,
    # 3 key, 4 client_id, 5 series_id, 6 responded_to, 7 cmd; zero
    # fields skipped; terminator 0x7f.
    # Entry{Term:5, Index:300, Cmd:"ab"}:
    #   term  -> 0x00 0x05
    #   index -> 0x01 0xAC 0x02          (300 = 0b1_0101100)
    #   cmd   -> 0x07 0x02 'a' 'b'
    #   term  terminator 0x7f
    e = pb.Entry(term=5, index=300, cmd=b"ab")
    assert gw.encode_entry(e) == bytes(
        [0x00, 0x05, 0x01, 0xAC, 0x02, 0x07, 0x02]) + b"ab\x7f"
    # empty entry is just the terminator
    assert gw.encode_entry(pb.Entry()) == b"\x7f"
    # the >= 2**49 arm: header|0x80 + 8-byte BIG-endian fixed
    # (raft_optimized.go:170-172, intconv = binary.BigEndian)
    big = 1 << 49
    e = pb.Entry(term=big)
    assert gw.encode_entry(e) == bytes(
        [0x80, 0x00, 0x02, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x7F])
    # 2**49 - 1 still rides the varint arm (7 groups of 7 bits)
    e = pb.Entry(term=(1 << 49) - 1)
    assert gw.encode_entry(e) == bytes(
        [0x00] + [0xFF] * 6 + [0x7F, 0x7F])


def test_golden_entry_type_field():
    # type (field 2) is int32: positive -> plain header 2 + varint
    # (raft_optimized.go:201-218)
    e = pb.Entry(type=pb.EntryType.CONFIG_CHANGE)     # enum value 1
    assert gw.encode_entry(e) == bytes([0x02, 0x01, 0x7F])


def test_golden_message():
    # message.go:32-96: thirteen fields, scalars always emitted,
    # entries length-delimited Colfer at tag 0x5a, snapshot at 0x62.
    m = pb.Message(type=pb.MessageType.HEARTBEAT, to=2, from_=1,
                   shard_id=7, term=3, log_term=0, log_index=0,
                   commit=9, reject=False, hint=0, hint_high=0)
    snap = gw.encode_snapshot(pb.Snapshot())
    want = bytes([
        0x08, 17,      # type Heartbeat
        0x10, 2,       # to
        0x18, 1,       # from
        0x20, 7,       # shard_id (ClusterId)
        0x28, 3,       # term
        0x30, 0,       # log_term
        0x38, 0,       # log_index
        0x40, 9,       # commit
        0x48, 0,       # reject=false
        0x50, 0,       # hint
        0x62, len(snap)]) + snap + bytes([0x68, 0])
    assert gw.encode_message(m) == want


def test_golden_membership_map_entry():
    # membership.go:34-51: ccid at 0x8; each addresses entry at 0x12
    # wrapping {0x8 key, 0x12 value}
    m = pb.Membership(config_change_id=4, addresses={1: "a"})
    want = bytes([
        0x08, 4,
        0x12, 5,            # map entry, 5 bytes
        0x08, 1,            # key = 1
        0x12, 1]) + b"a"    # value = "a"
    assert gw.encode_membership(m) == want


def test_golden_message_batch():
    # messagebatch.go:23-51: requests(0xa), deployment_id(0x10),
    # source_address(0x1a), bin_ver(0x20)
    got = gw.encode_message_batch([], deployment_id=5,
                                  source_address="x:1", bin_ver=2)
    assert got == bytes([0x10, 5, 0x1A, 3]) + b"x:1" + bytes([0x20, 2])


# --------------------------------------------------------------------------
# 2. protobuf cross-oracle
# --------------------------------------------------------------------------


def _oracle():
    import os
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__),
                                    "gowire_oracle"))
    import raft_oracle_pb2

    return raft_oracle_pb2


def test_oracle_parses_gowire_message():
    po = _oracle()
    m = pb.Message(
        type=pb.MessageType.REPLICATE, to=3, from_=1, shard_id=99,
        term=7, log_term=6, log_index=41, commit=40, reject=True,
        hint=11, hint_high=12,
        entries=(pb.Entry(term=7, index=42, cmd=b"payload"),),
        snapshot=pb.Snapshot(index=5, term=2, shard_id=99,
                             membership=pb.Membership(
                                 config_change_id=3,
                                 addresses={1: "a:1", 2: "b:2"},
                                 removed={9: True})),
    )
    parsed = po.Message()
    parsed.ParseFromString(gw.encode_message(m))
    assert parsed.type == 12 and parsed.to == 3 and getattr(
        parsed, "from") == 1
    assert parsed.shard_id == 99 and parsed.term == 7
    assert parsed.log_term == 6 and parsed.log_index == 41
    assert parsed.commit == 40 and parsed.reject is True
    assert parsed.hint == 11 and parsed.hint_high == 12
    assert len(parsed.entries) == 1
    assert gw.decode_entry(parsed.entries[0]).cmd == b"payload"
    assert parsed.snapshot.index == 5
    assert dict(parsed.snapshot.membership.addresses) == {1: "a:1", 2: "b:2"}
    assert dict(parsed.snapshot.membership.removed) == {9: True}


def test_gowire_decodes_oracle_serialization():
    po = _oracle()
    om = po.Message()
    om.type = 17
    om.to = 2
    setattr(om, "from", 5)
    om.shard_id = 1
    om.term = 9
    om.commit = 33
    om.reject = True
    om.hint = 4
    om.entries.append(gw.encode_entry(pb.Entry(term=9, index=34, cmd=b"z")))
    om.snapshot.index = 3
    om.snapshot.membership.addresses[1] = "h:1"
    om.hint_high = 8
    m = gw.decode_message(om.SerializeToString())
    assert m.type == pb.MessageType.HEARTBEAT
    assert m.to == 2 and m.from_ == 5 and m.term == 9
    assert m.commit == 33 and m.reject and m.hint == 4 and m.hint_high == 8
    assert m.entries[0].index == 34 and m.entries[0].cmd == b"z"
    assert m.snapshot.index == 3
    assert m.snapshot.membership.addresses == {1: "h:1"}


def test_oracle_roundtrip_batch_and_snapshot():
    po = _oracle()
    msgs = [pb.Message(type=pb.MessageType.REPLICATE_RESP, to=1, from_=2,
                       shard_id=i, term=3, log_index=i * 7)
            for i in range(4)]
    blob = gw.encode_message_batch(msgs, deployment_id=77,
                                   source_address="nh:900", bin_ver=1)
    parsed = po.MessageBatch()
    parsed.ParseFromString(blob)
    assert len(parsed.requests) == 4
    assert parsed.deployment_id == 77
    assert parsed.source_address == "nh:900"
    assert parsed.bin_ver == 1
    assert parsed.requests[2].shard_id == 2
    # and back through gowire
    reqs, dep, src, ver, fab = gw.decode_message_batch(
        parsed.SerializeToString())
    assert len(reqs) == 4 and dep == 77 and src == "nh:900" and ver == 1
    assert fab is None  # oracle frame carries no fabric header
    assert reqs[3].log_index == 21

    s = pb.Snapshot(filepath="/x/y", file_size=10, index=9, term=2,
                    shard_id=5, dummy=True, witness=True,
                    on_disk_index=7, checksum=b"\x01\x02",
                    files=(pb.SnapshotFile(file_id=3, filepath="/f",
                                           metadata=b"m", file_size=2),),
                    type=pb.StateMachineType.ON_DISK)
    ps = po.Snapshot()
    ps.ParseFromString(gw.encode_snapshot(s))
    assert ps.filepath == "/x/y" and ps.index == 9 and ps.dummy
    assert ps.witness and ps.on_disk_index == 7 and ps.type == 3
    assert ps.files[0].file_id == 3 and ps.files[0].metadata == b"m"
    s2 = gw.decode_snapshot(ps.SerializeToString())
    assert s2 == s


# --------------------------------------------------------------------------
# 3. round-trips + robustness
# --------------------------------------------------------------------------


def test_entry_roundtrip_randomized():
    rng = random.Random(7)
    for _ in range(300):
        e = pb.Entry(
            term=rng.choice([0, 1, 127, 128, 1 << 20, (1 << 49) - 1,
                             1 << 49, (1 << 64) - 1]),
            index=rng.randrange(1 << 50),
            type=rng.choice(list(pb.EntryType)),
            key=rng.randrange(1 << 52),
            client_id=rng.randrange(1 << 30),
            series_id=rng.randrange(1 << 16),
            responded_to=rng.randrange(1 << 8),
            cmd=bytes(rng.randrange(256)
                      for _ in range(rng.randrange(0, 40))),
        )
        assert gw.decode_entry(gw.encode_entry(e)) == e


def test_state_membership_roundtrip():
    s = pb.State(term=(1 << 63) + 5, vote=3, commit=0)
    assert gw.decode_state(gw.encode_state(s)) == s
    m = pb.Membership(config_change_id=9,
                      addresses={1: "a", 300: "b" * 50},
                      removed={7: True, 8: False},
                      non_votings={2: "nv"}, witnesses={4: "w"})
    got = gw.decode_membership(gw.encode_membership(m))
    assert got == m


def test_entry_batch_roundtrip():
    ents = tuple(pb.Entry(term=1, index=i, cmd=bytes([i])
                          ) for i in range(1, 20))
    assert gw.decode_entry_batch(gw.encode_entry_batch(ents)) == ents


def test_truncation_raises():
    e = pb.Entry(term=5, index=300, cmd=b"abcdef")
    blob = gw.encode_entry(e)
    for cut in range(1, len(blob)):
        with pytest.raises(ValueError):
            gw.decode_entry(blob[:cut])
    m = gw.encode_message(pb.Message(type=pb.MessageType.HEARTBEAT, to=1))
    for cut in (1, 3, len(m) // 2, len(m) - 1):
        try:
            gw.decode_message(m[:cut])
        except ValueError:
            pass   # raising is fine; silently wrong values are not
        # (protobuf prefixes can decode as a valid shorter message)


def test_unknown_fields_skipped():
    # forward compat: an unknown field (100, varint) must be skipped
    blob = gw.encode_state(pb.State(term=1, vote=2, commit=3))
    extra = bytearray(blob)
    # field 100, wire 0: key = 800 -> varint A0 06; value 42
    extra += bytes([0xA0, 0x06, 0x2A])
    s = gw.decode_state(bytes(extra))
    assert s == pb.State(term=1, vote=2, commit=3)


def test_oracle_chunk_both_directions():
    """The go-wire Chunk codec against the protoc oracle BOTH ways: a
    field-number or wire-type mistake mirrored in encode_chunk and
    decode_chunk passes a self-roundtrip but not this — and the real
    counterparty is an untested Go fleet."""
    from dragonboat_tpu.raftpb import gowire

    po = _oracle()
    c = gowire.GoChunk(
        shard_id=5, replica_id=2, from_=1, chunk_id=3, chunk_size=4,
        chunk_count=9, data=b"abcd", index=42, term=7,
        membership=pb.Membership(config_change_id=6,
                                 addresses={1: "a:1", 2: "b:2"},
                                 witnesses={3: "w:3"}),
        filepath="snapshot-000000000000002A.gbsnap", file_size=4096,
        deployment_id=11, file_chunk_id=3, file_chunk_count=9,
        has_file_info=True,
        file_info=pb.SnapshotFile(file_id=4, filepath="ext.bin",
                                  file_size=100, metadata=b"m"),
        bin_ver=1, on_disk_index=40, witness=False)
    raw = gowire.encode_chunk(c)
    oc = po.Chunk()
    oc.ParseFromString(raw)
    assert oc.shard_id == 5 and oc.replica_id == 2
    assert getattr(oc, "from") == 1
    assert (oc.chunk_id, oc.chunk_size, oc.chunk_count) == (3, 4, 9)
    assert oc.data == b"abcd" and oc.index == 42 and oc.term == 7
    assert oc.membership.addresses[1] == "a:1"
    assert oc.membership.witnesses[3] == "w:3"
    assert oc.filepath == "snapshot-000000000000002A.gbsnap"
    assert (oc.file_size, oc.deployment_id) == (4096, 11)
    assert (oc.file_chunk_id, oc.file_chunk_count) == (3, 9)
    assert oc.has_file_info and oc.file_info.file_id == 4
    assert oc.file_info.filepath == "ext.bin" and oc.file_info.metadata == b"m"
    assert oc.bin_ver == 1 and oc.on_disk_index == 40 and not oc.witness

    # oracle-encoded bytes decode to the same record (gogo emits only
    # non-default fields; the decoder must tolerate the sparse form)
    oc2 = po.Chunk(shard_id=3, replica_id=1, **{"from": 2}, chunk_id=1,
                   chunk_size=2, chunk_count=(1 << 64) - 1, data=b"xy",
                   index=8, term=3, filepath="f", file_size=5,
                   deployment_id=1)
    g2 = gowire.decode_chunk(oc2.SerializeToString())
    assert (g2.shard_id, g2.replica_id, g2.from_) == (3, 1, 2)
    assert g2.data == b"xy" and g2.chunk_id == 1
    assert g2.chunk_count == gowire.LAST_CHUNK_COUNT and g2.is_last()
