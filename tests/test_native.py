"""Native runtime primitives: C scanner vs Python fallback parity.

Reference context: the reference's runtime is compiled Go; here the
recovery/framing hot loops run in C (native/dbtpu_native.c) with a
pure-Python fallback that must behave identically.
"""

import struct
import zlib

from dragonboat_tpu import native
from dragonboat_tpu.logdb.tan import MAGIC


def _frame(payload: bytes) -> bytes:
    return struct.pack("<III", MAGIC, len(payload),
                       zlib.crc32(payload)) + payload


def _log(n=100):
    return b"".join(_frame(bytes([i & 0xFF]) * (i * 7 % 50))
                    for i in range(n))


def test_native_builds_here():
    # this container ships a C toolchain; the fallback is for hosts
    # without one
    assert native.available()


def test_scan_parity_clean_torn_corrupt():
    buf = _log()
    cases = [
        buf,                                    # clean
        buf + _frame(b"x" * 30)[:20],           # torn tail (partial frame)
        buf + b"\x01\x02\x03",                  # trailing garbage < header
        b"",                                    # empty file
    ]
    bad = bytearray(buf)
    bad[40] ^= 0xFF                             # corrupt an early payload
    cases.append(bytes(bad))
    for case in cases:
        assert native.tan_scan(case, MAGIC) == \
            native._tan_scan_py(case, MAGIC)


def test_scan_results_are_correct():
    buf = _log(17)
    recs, end, torn = native.tan_scan(buf, MAGIC)
    assert len(recs) == 17 and not torn and end == len(buf)
    off = 0
    for (roff, poff, plen) in recs:
        assert roff == off and poff == off + 12
        off += 12 + plen


def test_frame_check_matches_zlib():
    for payload in (b"", b"x", b"hello world" * 100):
        crc = zlib.crc32(payload)
        assert native.frame_check(payload, crc)
        assert not native.frame_check(payload, crc ^ 1)
