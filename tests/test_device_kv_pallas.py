"""Pallas rsm-apply kernel: exact equivalence with the XLA path.

The pallas kernel (rsm/device_kv_pallas.py) must produce bit-identical
tables, counts, results and ok flags to DeviceKV.apply_kernel for the
same inputs — same probe order, same last-write-wins, same rejects.
Runs in interpret mode on the CPU test mesh; the compiled TPU path
shares the same trace.
"""

import numpy as np
import jax.numpy as jnp

from dragonboat_tpu.rsm.device_kv import DeviceKV
from dragonboat_tpu.rsm.device_kv_pallas import apply_kernel_pallas


def _random_cmds(rng, G, B, key_lo, key_hi):
    keys = rng.integers(key_lo, key_hi, size=(G, B), dtype=np.int32)
    vals = rng.integers(-5, 1000, size=(G, B), dtype=np.int32)
    valid = rng.random((G, B)) < 0.8
    return (jnp.asarray(np.stack([keys, vals], axis=-1)),
            jnp.asarray(valid))


def _assert_same(st_a, ra, oka, st_b, rb, okb):
    for f in ("keys", "vals", "count"):
        assert (np.asarray(st_a[f]) == np.asarray(st_b[f])).all(), f
    assert (np.asarray(ra) == np.asarray(rb)).all()
    assert (np.asarray(oka) == np.asarray(okb)).all()


def test_pallas_matches_xla_hashed():
    rng = np.random.default_rng(7)
    kv = DeviceKV(table_cap=64, probe_depth=8)   # hashed, collisions real
    G, B = 9, 16                                 # G not a block multiple
    st_x = kv.init_state(G)
    st_p = {k: v for k, v in kv.init_state(G).items()}
    for round_ in range(4):                      # sequential windows
        cmds, valid = _random_cmds(rng, G, B, -2, 40)
        st_x, (rx, okx) = kv.apply_kernel(st_x, cmds, valid)
        st_p, (rp, okp) = apply_kernel_pallas(kv, st_p, cmds, valid)
        _assert_same(st_x, rx, okx, st_p, rp, okp)


def test_pallas_matches_xla_direct_mapped():
    rng = np.random.default_rng(11)
    kv = DeviceKV(table_cap=128, probe_depth=8, hash_keys=False)
    G, B = 16, 32
    st_x = kv.init_state(G)
    st_p = kv.init_state(G)
    for _ in range(3):
        cmds, valid = _random_cmds(rng, G, B, 0, 64)
        st_x, (rx, okx) = kv.apply_kernel(st_x, cmds, valid)
        st_p, (rp, okp) = apply_kernel_pallas(kv, st_p, cmds, valid)
        _assert_same(st_x, rx, okx, st_p, rp, okp)


def test_pallas_full_window_rejects_match():
    """Over-full probe windows must reject identically."""
    kv = DeviceKV(table_cap=8, probe_depth=4)
    G, B = 4, 12
    rng = np.random.default_rng(3)
    cmds, valid = _random_cmds(rng, G, B, 0, 30)
    st_x, (rx, okx) = kv.apply_kernel(kv.init_state(G), cmds, valid)
    st_p, (rp, okp) = apply_kernel_pallas(kv, kv.init_state(G), cmds, valid)
    _assert_same(st_x, rx, okx, st_p, rp, okp)
    assert not np.asarray(okx)[np.asarray(valid)].all(), \
        "case should exercise rejects"


def test_full_step_sm_pallas_path_bitwise():
    """The BENCH_PALLAS pipeline (full raft step + fused pallas apply)
    is bit-identical to the XLA range-apply pipeline over many steps —
    the flag flips the implementation, never the data."""
    import dataclasses

    import jax.numpy as jnp

    from dragonboat_tpu.bench_loop import (
        elect_all,
        make_cluster,
        make_device_sm,
        run_steps_sm,
        sm_params,
    )

    replicas, groups = 3, 8
    kp = sm_params(replicas)
    state0 = make_cluster(kp, groups, replicas)
    state0, box0 = elect_all(kp, replicas, state0)

    kv_x, st_x = make_device_sm(groups, replicas, table_cap=256)
    kv_p = dataclasses.replace(kv_x, use_pallas=True)
    st_p = {k: jnp.copy(v) for k, v in st_x.items()}

    sx, bx, st_x, rej_x = run_steps_sm(
        kp, replicas, kv_x, 25, True, True, state0, box0, st_x)
    sp, bp, st_p, rej_p = run_steps_sm(
        kp, replicas, kv_p, 25, True, True, state0, box0, st_p)

    for f, a, b in zip(type(sx)._fields, sx, sp):
        if a is None:
            continue
        assert np.array_equal(np.asarray(a), np.asarray(b)), f
    for k in st_x:
        assert np.array_equal(np.asarray(st_x[k]), np.asarray(st_p[k])), k
    assert int(rej_x) == int(rej_p) == 0
    assert int(np.asarray(st_x["count"]).sum()) > 0
