"""Apply-worker isolation: a deliberately-slow user SM must not stall
stepping or commit latency of the other shards sharing its step worker
(reference engine.go:1153-1204 applyWorkerMain — apply runs on dedicated
workers, never on the step path)."""

import threading
import time

import pytest

from dragonboat_tpu.config import Config, EngineConfig, ExpertConfig, \
    NodeHostConfig
from dragonboat_tpu.engine.apply_pool import ApplyPool
from dragonboat_tpu.client import Session
from dragonboat_tpu.nodehost import NodeHost
from dragonboat_tpu.statemachine import IStateMachine, Result


def _wait_ready(nh, shard_id, timeout=10.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        lid, ok = nh.get_leader_id(shard_id)
        if ok and lid:
            return
        time.sleep(0.02)
    raise AssertionError(f"shard {shard_id} never elected a leader")


class SlowSM(IStateMachine):
    """Every update blocks until released."""

    gate = threading.Event()          # class-wide: set -> applies proceed

    def __init__(self, shard_id, replica_id):
        self.applied = 0

    def update(self, entry):
        SlowSM.gate.wait(timeout=30)
        self.applied += 1
        return Result(value=self.applied)

    def lookup(self, query):
        return self.applied

    def save_snapshot(self, w, files, done):
        w.write(b"\x00")

    def recover_from_snapshot(self, r, files, done):
        r.read(1)


class FastSM(IStateMachine):
    def __init__(self, shard_id, replica_id):
        self.applied = 0

    def update(self, entry):
        self.applied += 1
        return Result(value=self.applied)

    def lookup(self, query):
        return self.applied

    def save_snapshot(self, w, files, done):
        w.write(b"\x00")

    def recover_from_snapshot(self, r, files, done):
        r.read(1)


def test_slow_sm_does_not_stall_sibling_shard():
    """Both shards hash onto ONE step worker (exec_shards=1); the slow
    shard's apply occupies an apply worker while the fast shard's
    proposals keep committing and applying."""
    SlowSM.gate.clear()
    addr = f"ap-{time.monotonic_ns()}"
    nh = NodeHost(NodeHostConfig(
        raft_address=addr, rtt_millisecond=2,
        expert=ExpertConfig(engine=EngineConfig(exec_shards=1,
                                                apply_shards=2))))
    try:
        # snapshot_entries on the SLOW shard: the auto-snapshot its
        # applies trigger must also run on the apply pool — taking it on
        # the step thread would block on the SM lock the wedged update()
        # holds, re-stalling the sibling
        for shard, sm in ((1, SlowSM), (2, FastSM)):
            nh.start_replica(
                {1: addr}, False, sm,
                Config(shard_id=shard, replica_id=1, election_rtt=10,
                       heartbeat_rtt=1, snapshot_entries=3,
                       compaction_overhead=1))
        _wait_ready(nh, 1)
        _wait_ready(nh, 2)
        s1 = Session.new_noop_session(1)
        s2 = Session.new_noop_session(2)
        # wedge shard 1's SM: propose; the apply blocks on the gate
        nh.propose(s1, b"x", timeout_s=5.0)
        time.sleep(0.05)
        # shard 2 must keep committing AND applying at normal latency
        t0 = time.monotonic()
        for i in range(20):
            r = nh.sync_propose(s2, b"y", timeout_s=5.0)
        elapsed = time.monotonic() - t0
        assert r.value == 20
        assert elapsed < 5.0, f"sibling shard stalled: {elapsed:.1f}s"
        assert nh.sync_read(2, None, timeout_s=5.0) == 20
    finally:
        SlowSM.gate.set()
        time.sleep(0.05)
        nh.close()


def test_slow_sm_apply_completes_after_release():
    """The wedged shard's own future completes once the SM unblocks —
    nothing is lost by the handoff."""
    SlowSM.gate.clear()
    addr = f"ap2-{time.monotonic_ns()}"
    nh = NodeHost(NodeHostConfig(
        raft_address=addr, rtt_millisecond=2,
        expert=ExpertConfig(engine=EngineConfig(exec_shards=1,
                                                apply_shards=2))))
    try:
        nh.start_replica(
            {1: addr}, False, SlowSM,
            Config(shard_id=1, replica_id=1, election_rtt=10,
                   heartbeat_rtt=1))
        _wait_ready(nh, 1)
        s1 = Session.new_noop_session(1)
        done = []
        th = threading.Thread(
            target=lambda: done.append(nh.sync_propose(s1, b"x",
                                                       timeout_s=10.0)))
        th.start()
        time.sleep(0.2)
        assert not done  # still blocked in the SM
        SlowSM.gate.set()
        th.join(timeout=10)
        assert done and done[0].value == 1
    finally:
        SlowSM.gate.set()
        nh.close()


def test_apply_pool_per_key_fifo_and_isolation():
    """Unit: per-key order is preserved; a blocked key occupies one
    worker while other keys drain on the rest."""
    order = []
    gate = threading.Event()
    pool = ApplyPool(num_workers=2)
    try:
        pool.submit("slow", gate.wait)
        for i in range(5):
            pool.submit("fast", lambda i=i: order.append(i))
        assert pool.flush("fast", timeout=5.0)
        assert order == [0, 1, 2, 3, 4]
        assert not pool.flush("slow", timeout=0.05)
        gate.set()
        assert pool.flush("slow", timeout=5.0)
    finally:
        gate.set()
        pool.stop()


def test_config_change_applies_through_pool():
    """Membership changes still work with async apply: the CC applies in
    the RSM on the apply worker and the raft core learns via the posted
    notice on the next step."""
    addr1 = f"ap3-{time.monotonic_ns()}"
    nh = NodeHost(NodeHostConfig(raft_address=addr1, rtt_millisecond=2))
    try:
        nh.start_replica(
            {1: addr1}, False, FastSM,
            Config(shard_id=1, replica_id=1, election_rtt=10,
                   heartbeat_rtt=1))
        _wait_ready(nh, 1)
        s = Session.new_noop_session(1)
        nh.sync_propose(s, b"a", timeout_s=5.0)
        nh.sync_request_add_nonvoting(1, 9, "else:1", 0, timeout_s=5.0)
        m = nh.sync_get_shard_membership(1, timeout_s=5.0)
        assert 9 in m.non_votings
        # proposals keep flowing after the CC
        r = nh.sync_propose(s, b"b", timeout_s=5.0)
        assert r.value == 2
    finally:
        nh.close()
