"""Cross-chip replica groups on a virtual 8-device CPU mesh.

Groups whose replicas live on *different devices* elect and replicate with
message exchange riding collectives (parallel/ici.py) — the TPU-native
analog of the reference's multi-NodeHost TCP clusters
(internal/transport/transport.go:86-101; SURVEY §7.8)."""

import jax
import numpy as np
import pytest
from jax.sharding import Mesh

from dragonboat_tpu.core import params as KP
from dragonboat_tpu.parallel.ici import (
    ici_cluster_step,
    ici_run_steps,
    make_ici_cluster,
    self_driving_input,
)


def _params(r):
    return KP.KernelParams(
        num_peers=r, log_cap=256, inbox_cap=5 * (r - 1), msg_entries=8,
        proposal_cap=4, readindex_cap=4, apply_batch=32,
        compaction_overhead=32,
    )


def _mesh(g, r):
    devs = jax.devices()
    if len(devs) < g * r:
        pytest.skip(f"needs {g * r} devices, have {len(devs)}")
    return Mesh(np.array(devs[: g * r]).reshape(g, r), ("g", "r"))


@pytest.mark.parametrize("g_size,replicas", [(2, 4), (4, 2), (1, 3)])
def test_ici_election(g_size, replicas):
    mesh = _mesh(g_size, replicas)
    kp = _params(replicas)
    cluster, state, box = make_ici_cluster(kp, mesh, num_groups=g_size * 2)
    for _ in range(60):
        inp = cluster.shard(self_driving_input(kp, state, propose=False))
        state, box, _ = ici_cluster_step(cluster, state, box, inp)
        role = np.asarray(state.role).reshape(-1, cluster.n_local)
        # rows: (ig, ir) blocks — one leader per group across replica slots
        if _one_leader_per_group(cluster, state):
            break
    assert _one_leader_per_group(cluster, state)


def _roles_by_group(cluster, state):
    """[num_groups, R] role matrix from the block-major layout."""
    role = np.asarray(state.role).reshape(
        cluster.g_size, cluster.replicas, cluster.n_local
    )
    return np.transpose(role, (0, 2, 1)).reshape(-1, cluster.replicas)


def _one_leader_per_group(cluster, state):
    return (_roles_by_group(cluster, state) == KP.LEADER).sum(axis=1).all()


def test_ici_replication_and_commit():
    mesh = _mesh(2, 4)
    kp = _params(4)
    cluster, state, box = make_ici_cluster(kp, mesh, num_groups=4)
    state, box = ici_run_steps(kp, cluster, 120, False, state, box)
    assert _one_leader_per_group(cluster, state)
    c0 = np.asarray(state.committed).astype(np.int64).max()
    # drive proposals through full raft rounds across the mesh
    state, box = ici_run_steps(kp, cluster, 60, True, state, box)
    commits = np.asarray(state.committed).reshape(
        cluster.g_size, cluster.replicas, cluster.n_local
    )
    c1 = commits.max()
    assert c1 > c0, "no cross-device commits"
    # every replica of each group converges on the same committed floor
    by_group = np.transpose(commits, (0, 2, 1)).reshape(-1, cluster.replicas)
    spread = by_group.max(axis=1) - by_group.min(axis=1)
    assert (spread <= kp.msg_entries * 2).all()


def test_ici_matches_single_device_router():
    """The mesh path and the single-device router produce identical commit
    progress for the same geometry and seeds (collectives only move lanes)."""
    from dragonboat_tpu.bench_loop import make_cluster, run_steps
    from dragonboat_tpu.core.kstate import empty_inbox

    replicas, groups = 2, 4
    kp = _params(replicas)

    mesh = _mesh(2, replicas)
    cluster, sstate, sbox = make_ici_cluster(kp, mesh, num_groups=groups)
    sstate, sbox = ici_run_steps(kp, cluster, 80, True, sstate, sbox)

    # single-device reference run: same groups, group-major layout;
    # seeds differ by row order, so compare aggregate liveness not bitwise
    dstate = make_cluster(kp, groups, replicas)
    dbox = empty_inbox(kp, groups * replicas)
    dstate, dbox = run_steps(kp, replicas, 80, True, True, dstate, dbox)

    assert _one_leader_per_group(cluster, sstate)
    assert (np.asarray(dstate.role).reshape(-1, replicas) == KP.LEADER).sum(
        axis=1
    ).all()
    assert np.asarray(sstate.committed).max() > 0
    assert np.asarray(dstate.committed).max() > 0
