"""Entry-compression envelope (rsm/encoded.py) — codec correctness and
the propose->replicate->apply path with compression on.

The snappy block codec is an independent implementation of the public
format; the decoder is additionally pinned against handcrafted spec
vectors (literal and overlapping-copy elements built by hand from the
format definition), so encoder and decoder cannot share a bug and both
stay honest against the format a Go fleet speaks."""

import random

import pytest

from dragonboat_tpu import raftpb as pb
from dragonboat_tpu.rsm import encoded as ee


# ---------------------------------------------------------------------------
# snappy block codec
# ---------------------------------------------------------------------------


def test_snappy_roundtrip_basic():
    for data in (b"a", b"hello world", b"ab" * 500, bytes(1000),
                 b"the quick brown fox " * 64):
        assert ee.snappy_block_decode(ee.snappy_block_encode(data)) == data


def test_snappy_roundtrip_random():
    rng = random.Random(7)
    for trial in range(30):
        n = rng.randrange(1, 5000)
        if trial % 3 == 0:      # incompressible
            data = bytes(rng.randrange(256) for _ in range(n))
        elif trial % 3 == 1:    # repetitive
            unit = bytes(rng.randrange(256) for _ in range(rng.randrange(1, 9)))
            data = (unit * (n // max(len(unit), 1) + 1))[:n]
        else:                   # text-ish
            data = bytes(rng.choice(b"abcdefgh \n") for _ in range(n))
        assert ee.snappy_block_decode(ee.snappy_block_encode(data)) == data


def test_snappy_compresses_repetitive():
    data = b"0123456789abcdef" * 256           # 4096 bytes
    enc = ee.snappy_block_encode(data)
    assert len(enc) < len(data) // 4


def test_snappy_decoder_spec_vectors():
    # literal-only stream: uvarint(5) + tag(len 5 -> (5-1)<<2) + bytes
    assert ee.snappy_block_decode(bytes([5, 4 << 2]) + b"abcde") == b"abcde"
    # overlapping copy: "ab" then copy-2(offset=2, len=6) -> "abababab"
    buf = bytes([8, (2 - 1) << 2]) + b"ab" + bytes([((6 - 1) << 2) | 2, 2, 0])
    assert ee.snappy_block_decode(buf) == b"abababab"
    # copy-1: offset=3 packed in tag high bits + 1 byte, len=4
    buf = bytes([7, (3 - 1) << 2]) + b"xyz" + bytes([((4 - 4) << 2) | 1, 3])
    assert ee.snappy_block_decode(buf) == b"xyzxyzx"


def test_snappy_decoder_rejects_corruption():
    good = ee.snappy_block_encode(b"hello hello hello hello")
    with pytest.raises(ValueError):
        ee.snappy_block_decode(good[:-2])          # truncated element
    with pytest.raises(ValueError):
        ee.snappy_block_decode(good + b"\x00" * 3)  # length mismatch
    with pytest.raises(ValueError):                # copy before any output
        ee.snappy_block_decode(bytes([4, ((4 - 1) << 2) | 2, 1, 0]))


# ---------------------------------------------------------------------------
# the envelope
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("ct", ee.COMPRESSION_TYPES)
def test_envelope_roundtrip(ct):
    payload = b"payload " * 64
    enc = ee.get_encoded(ct, payload)
    e = pb.Entry(type=pb.EntryType.ENCODED, cmd=enc)
    assert ee.get_payload(e) == payload


def test_envelope_passthrough_plain_entries():
    e = pb.Entry(type=pb.EntryType.APPLICATION, cmd=b"raw")
    assert ee.get_payload(e) == b"raw"


def test_envelope_rejects():
    with pytest.raises(ValueError):
        ee.get_encoded("snappy", b"")
    with pytest.raises(ValueError):
        ee.get_payload(pb.Entry(type=pb.EntryType.ENCODED, cmd=b""))
    with pytest.raises(ValueError):    # unknown compression flag (3<<1)
        ee.get_payload(pb.Entry(type=pb.EntryType.ENCODED,
                                cmd=bytes([3 << 1]) + b"x"))
    with pytest.raises(ValueError):    # unknown version
        ee.get_payload(pb.Entry(type=pb.EntryType.ENCODED,
                                cmd=bytes([1 << 4]) + b"x"))


def test_config_validates_compression():
    from dragonboat_tpu.config import Config, ConfigError

    Config(shard_id=1, replica_id=1, election_rtt=10, heartbeat_rtt=2,
           entry_compression="snappy").validate()
    with pytest.raises(ConfigError):
        Config(shard_id=1, replica_id=1, election_rtt=10, heartbeat_rtt=2,
               entry_compression="lz4").validate()


def test_gowire_carries_encoded_entries():
    """An ENCODED entry survives the go-wire codec with type + envelope
    intact (a compression-enabled Go fleet ships exactly this shape)."""
    from dragonboat_tpu.raftpb import gowire

    payload = b"interop " * 32
    e = pb.Entry(term=3, index=9, type=pb.EntryType.ENCODED, key=77,
                 cmd=ee.get_encoded("snappy", payload))
    m = pb.Message(type=pb.MessageType.REPLICATE, to=2, from_=1,
                   shard_id=5, term=3, entries=[e])
    raw = gowire.encode_message_batch([m], 0, "")
    msgs = gowire.decode_message_batch(raw)[0]
    got = msgs[0].entries[0]
    assert got.type == pb.EntryType.ENCODED
    assert got.cmd == e.cmd
    assert ee.get_payload(got) == payload


# ---------------------------------------------------------------------------
# end to end: compression on the full propose -> apply path
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("ct", ["snappy", "zlib"])
def test_propose_apply_with_compression(ct, tmp_path):
    """3-replica shard over the chan transport with entry compression:
    payloads arrive at every replica's SM decompressed, and dedup
    (session-managed path) still works over the envelope."""
    import time

    from dragonboat_tpu.client import Session
    from dragonboat_tpu.config import Config, NodeHostConfig
    from dragonboat_tpu.nodehost import NodeHost
    from dragonboat_tpu.statemachine import IStateMachine, Result

    class KV(IStateMachine):
        def __init__(self, *a):
            self.d = {}

        def update(self, e):
            k, v = e.cmd.decode().split("=", 1)
            self.d[k] = v
            return Result(value=len(self.d))

        def lookup(self, q):
            return self.d.get(q.decode(), "")

        def save_snapshot(self, w, files, done):
            import json

            w.write(json.dumps(self.d).encode())

        def recover_from_snapshot(self, r, files, done):
            import json

            self.d = json.loads(r.read().decode())

    addrs = {1: "ec-1", 2: "ec-2", 3: "ec-3"}
    hosts = {r: NodeHost(NodeHostConfig(raft_address=a, rtt_millisecond=2))
             for r, a in addrs.items()}
    try:
        for r, nh in hosts.items():
            nh.start_replica(addrs, False, KV, Config(
                shard_id=1, replica_id=r, election_rtt=10, heartbeat_rtt=2,
                entry_compression=ct))
        deadline = time.time() + 60
        lead = None
        while time.time() < deadline:
            lid, ok = hosts[1].get_leader_id(1)
            if ok and lid in hosts:
                lead = hosts[lid]
                break
            time.sleep(0.05)
        assert lead is not None
        s = Session.new_noop_session(1)
        big = "v" * 4096                     # compresses well
        lead.propose(s, f"big={big}".encode(), timeout_s=10).get(10)
        for r, h in hosts.items():
            assert h.sync_read(1, b"big", timeout_s=10) == big, r
    finally:
        for nh in hosts.values():
            nh.close()
