"""Witness parity on the kernel path (VERDICT r2 weak #8): a kernel-lane
leader must replicate METADATA entries (no payloads) to witness peers
(raft.go:756-784 makeMetadataEntries), answer a lagging witness with a
stripped file-less snapshot WITHOUT evicting the shard (raft.go:713-735
makeWitnessSnapshot), and count witness acks toward commit quorum."""

import time

from dragonboat_tpu import raftpb as pb
from dragonboat_tpu.config import Config, ExpertConfig, NodeHostConfig
from dragonboat_tpu.nodehost import NodeHost

from test_kernel_engine import propose_retry
from test_nodehost import KVStateMachine, wait_leader


def _mk_host(addr, expert=None, rtt_ms=5):
    return NodeHost(NodeHostConfig(
        raft_address=addr, rtt_millisecond=rtt_ms,
        expert=expert or ExpertConfig(kernel_log_cap=256,
                                      kernel_capacity=8,
                                      kernel_apply_batch=16,
                                      kernel_compaction_overhead=16)))


def _witness_cluster(prefix, snapshot_entries=0):
    """2 voters (device-resident) + 1 witness (host-resident)."""
    addrs = {1: f"{prefix}-1", 2: f"{prefix}-2"}
    waddr = f"{prefix}-3"
    hosts = {}
    for rid, addr in addrs.items():
        nh = _mk_host(addr)
        nh.start_replica(addrs, False, KVStateMachine, Config(
            shard_id=1, replica_id=rid, election_rtt=10, heartbeat_rtt=2,
            snapshot_entries=snapshot_entries, compaction_overhead=5,
            device_resident=True))
        hosts[rid] = nh
    lid = wait_leader(hosts, timeout=30.0)
    hosts[lid].sync_request_add_witness(1, 3, waddr, 0, timeout_s=10.0)
    wnh = _mk_host(waddr)
    wnh.start_replica({}, True, KVStateMachine, Config(
        shard_id=1, replica_id=3, election_rtt=10, heartbeat_rtt=2,
        is_witness=True, compaction_overhead=5))
    hosts[3] = wnh
    return hosts, lid


def test_witness_receives_metadata_entries_from_kernel_leader():
    hosts, lid = _witness_cluster(f"kw-{time.monotonic_ns()}")
    try:
        s = hosts[lid].get_noop_session(1)
        for i in range(10):
            propose_retry(hosts[lid], s, f"k{i}=v{i}".encode())
        # the voters hold the payloads
        assert hosts[lid].stale_read(1, "k9") == "v9"

        # the witness's durable log must hold METADATA entries only
        # (CCs excepted) — and its SM must never see a payload
        wnh = hosts[3]
        deadline = time.time() + 10
        ents = []
        while time.time() < deadline:
            ents = wnh.logdb.iterate_entries(1, 3, 1, 64, 0)
            if sum(1 for e in ents
                   if e.type == pb.EntryType.METADATA) >= 10:
                break
            time.sleep(0.05)
        meta = [e for e in ents if e.type == pb.EntryType.METADATA]
        assert len(meta) >= 10, f"witness got {len(meta)} metadata entries"
        assert all(not e.cmd for e in meta)
        assert wnh._node(1).sm.sm.kv == {}, "payload leaked to witness SM"
        # the leader shard is still on the kernel (no eviction happened)
        assert 1 in hosts[lid].kernel_engine.by_shard
    finally:
        for nh in hosts.values():
            nh.close()


def test_witness_ack_sustains_commit_quorum():
    """2 voters + 1 witness = quorum 2: with one voter dead, commits
    require the witness's metadata acks through the kernel leader."""
    hosts, lid = _witness_cluster(f"kq-{time.monotonic_ns()}")
    try:
        s = hosts[lid].get_noop_session(1)
        propose_retry(hosts[lid], s, b"warm=up")
        dead = next(r for r in (1, 2) if r != lid)
        hosts[dead].close()
        del hosts[dead]
        # leader + witness must keep committing
        for i in range(5):
            propose_retry(hosts[lid], s, f"solo{i}=v{i}".encode(),
                          deadline_s=30)
        assert hosts[lid].stale_read(1, "solo4") == "v4"
        assert 1 in hosts[lid].kernel_engine.by_shard
    finally:
        for nh in hosts.values():
            nh.close()


def test_witness_added_after_compaction_gets_stripped_snapshot():
    """A witness that joins AFTER the leader's device ring compacted
    (match=0 < device snap floor) is served a file-less stripped
    snapshot by the kernel leader (raft.go:713-735) — no stream, no
    eviction — and then follows via metadata replication.

    The witness must be added after compaction: while a witness is
    merely partitioned, the device ring floor waits for every present
    peer's match, so the s_wit_snap path would never fire (the earlier
    version of this test asserted catch-up that plain replication
    provided)."""
    prefix = f"ks-{time.monotonic_ns()}"
    addrs = {1: f"{prefix}-1", 2: f"{prefix}-2"}
    hosts = {}
    for rid, addr in addrs.items():
        nh = _mk_host(addr)
        nh.start_replica(addrs, False, KVStateMachine, Config(
            shard_id=1, replica_id=rid, election_rtt=10, heartbeat_rtt=2,
            snapshot_entries=8, compaction_overhead=2,
            device_resident=True))
        hosts[rid] = nh
    try:
        lid = wait_leader(hosts, timeout=30.0)
        s = hosts[lid].get_noop_session(1)
        for i in range(60):
            propose_retry(hosts[lid], s, f"p{i}=v{i}".encode())
        # wait for the leader LANE's device ring to actually compact
        eng = hosts[lid].kernel_engine
        lane = eng.by_shard[1].lane
        deadline = time.time() + 15
        while time.time() < deadline:
            if int(eng.state.snap_index[lane]) > 0:
                break
            propose_retry(hosts[lid], s, b"more=x")
            time.sleep(0.05)
        assert int(eng.state.snap_index[lane]) > 0, \
            "device ring never compacted; test cannot exercise wit_snap"

        # NOW add the witness: its match=0 is below the device floor,
        # so replication to it must go through the stripped snapshot
        waddr = f"{prefix}-w"
        propose_retry(hosts[lid], s, b"pre=add")
        hosts[lid].sync_request_add_witness(1, 3, waddr, 0, timeout_s=10.0)
        wnh = _mk_host(waddr)
        wnh.start_replica({}, True, KVStateMachine, Config(
            shard_id=1, replica_id=3, election_rtt=10, heartbeat_rtt=2,
            is_witness=True, compaction_overhead=2))
        hosts["w"] = wnh
        wnode = wnh._node(1)
        target = hosts[lid]._node(1).sm.get_last_applied()
        deadline = time.time() + 20
        while time.time() < deadline:
            if wnode.sm.get_last_applied() >= target:
                break
            propose_retry(hosts[lid], s, b"tick=t")
            time.sleep(0.1)
        assert wnode.sm.get_last_applied() >= target, \
            "witness never caught up past the compaction gap"
        # caught up via a WITNESS snapshot record, not a data file
        wss = wnh.logdb.get_snapshot(1, 3)
        assert wss is not None and wss.witness, \
            "witness snapshot record missing — catch-up used another path"
        # the leader never left the kernel
        assert 1 in hosts[lid].kernel_engine.by_shard, \
            "kernel leader was evicted serving a witness snapshot"
        assert wnode.sm.sm.kv == {}
    finally:
        for nh in hosts.values():
            nh.close()
