"""Partition-safety pass (dragonboat_tpu/analysis/partition.py): every
PS001-PS006 defect class must fire on a known-bad fixture, the licensed
spellings of the same patterns must come back clean, the repo itself
must be clean both statically and under the 2-device dynamic sharding
diff, and the mesh-check / hlo-budget caches must invalidate on source
or jax-version changes."""

from __future__ import annotations

import importlib.util
import json
import os
import textwrap

import pytest

from dragonboat_tpu.analysis import common, hlo_budget, partition

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_lint_module():
    spec = importlib.util.spec_from_file_location(
        "lint_under_test", os.path.join(REPO, "scripts", "lint.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _write(tmp_path, name, src):
    p = tmp_path / name
    p.write_text(textwrap.dedent(src))
    return str(p)


def _run_fixture(tmp_path, src):
    p = _write(tmp_path, "fix.py", src)
    return partition.run(str(tmp_path), files=[p], dynamic=False)


# ------------------------------------------------------- contract grammar

def test_part_and_collective_tags_parse():
    fc = common.parse_contract("[G] i32 part=G")
    assert fc.part == "G" and fc.collective is None
    fc = common.parse_contract("[] i32 part=replicated collective=declared")
    assert fc.part == "replicated" and fc.collective == "declared"
    fc = common.parse_contract("[G, K] i32 ring collective=none")
    assert fc.collective == "none" and fc.part is None


def test_bad_part_and_collective_values_raise():
    with pytest.raises(common.ContractError, match="part"):
        common.parse_contract("[G] i32 part=R")
    with pytest.raises(common.ContractError, match="collective"):
        common.parse_contract("[G] i32 collective=psum")
    # the unknown-tag diagnostic is not shadowed by the new tags
    with pytest.raises(common.ContractError, match="tag"):
        common.parse_contract("[G] i32 wat")


# ------------------------------------------------- PS001 cross-G reduction

PS001_BAD = """\
    CONTRACTS = {"ShardState": {"term": "[G] i32 part=G"}}

    def bad_total(state: ShardState):
        return state.term.sum()
"""

PS001_DECLARED = """\
    CONTRACTS = {
        "ShardState": {"term": "[G] i32 part=G"},
        "Stats": {"total": "[] i32 part=replicated collective=declared"},
    }

    def ok_total(state: ShardState):
        return Stats(total=state.term.sum())
"""


def test_ps001_cross_g_reduction_fires(tmp_path):
    findings = _run_fixture(tmp_path, PS001_BAD)
    assert [f.rule for f in findings] == ["PS001"]
    assert "G" in findings[0].message


def test_ps001_declared_collective_result_is_licensed(tmp_path):
    assert _run_fixture(tmp_path, PS001_DECLARED) == []


# ------------------------------------------------- PS002 shard_map specs

PS002_BAD = """\
    import jax
    from jax.sharding import PartitionSpec as PS

    CONTRACTS = {"ShardState": {"term": "[G] i32 part=G"}}

    def body(state: ShardState):
        return state

    def bad_specs(mesh, state):
        f = jax.shard_map(body, mesh=mesh, in_specs=(PS(),),
                          out_specs=(PS(),))
        return f(state)
"""

PS002_OK = """\
    import jax
    from jax.sharding import PartitionSpec as PS

    CONTRACTS = {"ShardState": {"term": "[G] i32 part=G"}}

    def body(state: ShardState):
        return state

    def ok_specs(mesh, state):
        f = jax.shard_map(body, mesh=mesh, in_specs=(PS(("g", "r")),),
                          out_specs=(PS(("g", "r")),))
        return f(state)
"""


def test_ps002_unsharded_specs_for_g_part_fire(tmp_path):
    findings = _run_fixture(tmp_path, PS002_BAD)
    rules = [f.rule for f in findings]
    assert rules == ["PS002", "PS002"]  # in_specs and out_specs


def test_ps002_g_axis_specs_are_clean(tmp_path):
    assert _run_fixture(tmp_path, PS002_OK) == []


# --------------------------------------- PS003 replicated x sharded mixes

PS003_BAD = """\
    import jax

    CONTRACTS = {"ShardState": {"term": "[G] i32 part=G"}}

    def bad_mix(state: ShardState):
        total = jax.lax.psum(state.term, ("g", "r"))
        return state.term + total
"""

PS003_OK = """\
    import jax
    import jax.numpy as jnp

    CONTRACTS = {"ShardState": {"term": "[G] i32 part=G"}}

    def ok_mix(state: ShardState):
        total = jax.lax.psum(state.term, ("g", "r"))
        return state.term + jnp.broadcast_to(total, state.term.shape)
"""


def test_ps003_unannotated_replicated_mix_fires(tmp_path):
    findings = _run_fixture(tmp_path, PS003_BAD)
    assert [f.rule for f in findings] == ["PS003"]


def test_ps003_broadcast_annotation_is_clean(tmp_path):
    assert _run_fixture(tmp_path, PS003_OK) == []


# ------------------------------------------- PS004 donation sharding identity

PS004_BAD = """\
    CONTRACTS = {
        "ShardState": {"term": "[G] i32 part=G"},
        "Stats": {"total": "[] i32 part=replicated"},
    }

    DONATION = {
        "step_donated": {
            "argnums": (0,),
            "params": ("state",),
            "donor_classes": ("ShardState",),
            "result_classes": ("Stats",),
        },
    }
"""


def test_ps004_donor_partition_missing_from_results_fires(tmp_path):
    findings = _run_fixture(tmp_path, PS004_BAD)
    assert [f.rule for f in findings] == ["PS004"]
    assert "ShardState" in findings[0].message


# --------------------------------------- PS005 callbacks inside shard_map

PS005_BAD = """\
    import jax
    from jax.sharding import PartitionSpec as PS

    def cb_body(x):
        return jax.pure_callback(int, x, x)

    def run_cb(mesh, x):
        return jax.shard_map(cb_body, mesh=mesh, in_specs=PS(),
                             out_specs=PS())(x)
"""


def test_ps005_callback_in_shard_map_body_fires(tmp_path):
    findings = _run_fixture(tmp_path, PS005_BAD)
    assert [f.rule for f in findings] == ["PS005"]
    assert "pure_callback" in findings[0].message


# --------------------------------------- PS006 host syncs in hot paths

PS006_BAD = """\
    class Eng:
        def step_all(self):
            state, out = self._kernel_call(None, None)
            return int(state.term[0])
"""

PS006_OK = """\
    class Eng:
        def step_all(self):
            state, out = self._kernel_call(None, None)
            self.state = state
            return out
"""


def test_ps006_host_sync_in_hot_path_fires(tmp_path):
    findings = _run_fixture(tmp_path, PS006_BAD)
    assert [f.rule for f in findings] == ["PS006"]


def test_ps006_device_resident_hot_path_is_clean(tmp_path):
    assert _run_fixture(tmp_path, PS006_OK) == []


# ---------------------------------------------------------- repo is clean

def test_repo_static_partition_clean():
    assert partition.run(REPO, dynamic=False) == []


def test_repo_dynamic_sharding_clean_and_cached(tmp_path):
    findings = partition.sharding_check(REPO)
    assert findings == []
    cache = os.path.join(REPO, partition.CACHE_FILE)
    assert os.path.exists(cache)
    with open(cache, encoding="utf-8") as f:
        blob = json.load(f)
    assert blob["source_hash"] == partition._source_key(REPO)


def test_dynamic_check_catches_tampered_declaration():
    findings = partition.sharding_check(
        REPO, parts_override={("ShardState", "term"): "replicated"})
    assert findings, "tampered part= declaration went undetected"
    assert any("ShardState.term" in f.message for f in findings)
    assert all(f.rule == "PS002" for f in findings)


def test_partition_cache_rejects_stale_key(tmp_path):
    path = str(tmp_path / "cache.json")
    partition._cache_save(
        path, "key-a",
        [common.Finding("partition", "x.py", 1, "PS002", "m")])
    hit = partition._cache_load(path, "key-a")
    assert hit is not None and hit[0].rule == "PS002"
    assert partition._cache_load(path, "key-b") is None


# --------------------------------------------- hlo-budget cache keying

def test_hlo_cache_invalidates_on_jax_version_bump(tmp_path, monkeypatch):
    import jax

    key_now = hlo_budget.source_hash(REPO)
    monkeypatch.setattr(jax, "__version__", "0.0.0-test", raising=False)
    key_bumped = hlo_budget.source_hash(REPO)
    assert key_now != key_bumped

    root = str(tmp_path)
    os.makedirs(os.path.join(root, "dragonboat_tpu", "analysis"))
    hlo_budget._cache_store(root, key_now, {"run_steps": {"gather": 1}})
    assert hlo_budget._cache_load(root, key_now) == {
        "run_steps": {"gather": 1}}
    # the same cache under the bumped compiler version must miss
    assert hlo_budget._cache_load(root, key_bumped) is None


# --------------------------------------------- lint runner integration

def test_lint_registers_partition_pass_and_scopes():
    mod = _load_lint_module()
    assert "partition" in mod.PASSES
    assert "dragonboat_tpu/parallel/ici.py" in mod.PASS_SCOPES["partition"]


def test_changed_only_selection():
    mod = _load_lint_module()
    assert "partition" in mod.select_changed(
        ["dragonboat_tpu/parallel/ici.py"])
    assert mod.select_changed(["README.md"]) == []
    # analyzer edits invalidate every pass
    assert mod.select_changed(
        ["dragonboat_tpu/analysis/partition.py"]) == sorted(mod.PASSES)


def test_lint_summary_table_and_exit():
    spec = importlib.util.spec_from_file_location(
        "lint_summary_under_test",
        os.path.join(REPO, "scripts", "lint_summary.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)

    rows = [
        json.dumps({"path": "a.py", "line": 3, "pass": "partition",
                    "rule": "PS001", "message": "boom", "waived": False,
                    "reason": None}),
        json.dumps({"path": "b.py", "line": 9, "pass": "contracts",
                    "rule": "KC001", "message": "ok", "waived": True,
                    "reason": "why"}),
    ]
    report, unwaived = mod.summarize(rows)
    assert unwaived == 1
    assert "PS001" in report and "FAIL: 1 unwaived, 1 waived" in report

    report, unwaived = mod.summarize([])
    assert unwaived == 0 and "no findings" in report

    with pytest.raises(ValueError, match="not JSON"):
        mod.summarize(["{nope"])
