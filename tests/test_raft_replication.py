"""Log replication / commit conformance — spirit of raft_etcd_test.go and
raft_etcd_paper_test.go sections 5.3/5.4."""

from dragonboat_tpu import raftpb as pb
from dragonboat_tpu.core.pycore import RaftState, RemoteState
from raft_harness import Network, make_network, new_raft

MT = pb.MessageType


def committed_cmds(r):
    return [e.cmd for e in r.log.get_entries(1, r.log.committed + 1) if e.cmd]


def test_propose_replicates_and_commits():
    nt = make_network(3)
    nt.elect(1)
    nt.propose(1, b"hello")
    for rid in (1, 2, 3):
        r = nt.nodes[rid]
        assert r.log.committed == 2  # noop + proposal
        assert committed_cmds(r) == [b"hello"]


def test_proposal_forwarded_by_follower():
    nt = make_network(3)
    nt.elect(1)
    nt.propose(2, b"via-follower")  # follower redirects to leader
    assert committed_cmds(nt.nodes[1]) == [b"via-follower"]
    assert nt.nodes[1].log.committed == 2


def test_proposal_dropped_without_leader():
    r = new_raft(1, [1, 2, 3])
    r.handle(pb.Message(type=MT.PROPOSE, from_=1, entries=(pb.Entry(cmd=b"x"),)))
    assert r.dropped_entries and r.dropped_entries[0].cmd == b"x"
    assert r.log.last_index() == 0


def test_old_term_entries_not_committed_by_counting():
    """p8 raft paper: never commit previous-term entries by counting replicas.
    Modeled after the figure-8 scenario."""
    nt = make_network(3)
    nt.elect(1)
    r1 = nt.nodes[1]
    # leader appends an entry that does NOT reach quorum (partition followers)
    nt.isolate(2)
    nt.isolate(3)
    nt.propose(1, b"stale")
    assert r1.log.committed == 1  # not committed
    nt.heal()
    # new election at higher term by node 2 (has only the noop)
    nt.nodes[2].applied = nt.nodes[2].log.committed
    nt.elect(2)
    r2 = nt.nodes[2]
    assert r2.state == RaftState.LEADER
    # r1's uncommitted 'stale' entry at old term was overwritten by r2's log
    assert b"stale" not in committed_cmds(nt.nodes[1])


def test_follower_conflicting_entries_truncated():
    r = new_raft(2, [1, 2, 3])
    # local uncommitted entries at term 1
    r.term = 1
    r.handle(
        pb.Message(
            type=MT.REPLICATE, from_=1, term=1, log_index=0, log_term=0,
            entries=(pb.Entry(term=1, index=1, cmd=b"a"),
                     pb.Entry(term=1, index=2, cmd=b"b")),
        )
    )
    assert r.log.last_index() == 2
    # new leader at term 2 overwrites index 2
    r.handle(
        pb.Message(
            type=MT.REPLICATE, from_=3, term=2, log_index=1, log_term=1,
            entries=(pb.Entry(term=2, index=2, cmd=b"c"),), commit=2,
        )
    )
    assert r.log.last_index() == 2
    assert r.log.term(2) == 2
    assert r.log.committed == 2


def test_replicate_reject_carries_hint_and_backtracks():
    nt = make_network(3)
    nt.elect(1)
    r1, r2 = nt.nodes[1], nt.nodes[2]
    # forge a follower whose log is shorter: rebuild node 2 fresh
    fresh = new_raft(2, [1, 2, 3])
    fresh.term = r1.term
    nt.nodes[2] = fresh
    # leader proposes; follower 2 rejects (no matching log at next-1)
    nt.propose(1, b"x")
    # after drain the follower must have caught up via backtracking
    assert committed_cmds(nt.nodes[2]) == [b"x"]
    assert nt.nodes[2].log.committed == r1.log.committed


def test_leader_commit_advances_follower_commit_via_heartbeat():
    nt = make_network(3)
    nt.elect(1)
    nt.propose(1, b"x")
    r1 = nt.nodes[1]
    # heartbeat propagates commit index
    r1.handle(pb.Message(type=MT.LEADER_HEARTBEAT, from_=1))
    nt.send(nt.collect())
    for rid in (2, 3):
        assert nt.nodes[rid].log.committed == r1.log.committed


def test_remote_flow_control_states():
    nt = make_network(3)
    nt.elect(1)
    r1 = nt.nodes[1]
    rp = r1.remotes[2]
    # after successful replication rounds the remote pipelines (replicate state)
    nt.propose(1, b"x")
    assert rp.state in (RemoteState.REPLICATE, RemoteState.RETRY, RemoteState.WAIT)
    # unreachable report degrades replicate -> retry
    rp.state = RemoteState.REPLICATE
    r1.handle(pb.Message(type=MT.UNREACHABLE, from_=2))
    assert rp.state == RemoteState.RETRY


def test_paused_remote_not_sent_replicate():
    nt = make_network(3)
    nt.elect(1)
    r1 = nt.nodes[1]
    r1.remotes[2].state = RemoteState.WAIT
    r1.msgs = []
    r1.handle(pb.Message(type=MT.PROPOSE, from_=1, entries=(pb.Entry(cmd=b"z"),)))
    tos = [m.to for m in r1.msgs if m.type == MT.REPLICATE]
    assert 2 not in tos and 3 in tos


def test_single_node_commits_immediately():
    nt = make_network(1)
    nt.elect(1)
    nt.propose(1, b"solo")
    assert nt.nodes[1].log.committed == 2


def test_batch_proposals():
    nt = make_network(3)
    nt.elect(1)
    nt.start(
        pb.Message(
            type=MT.PROPOSE, to=1, from_=1,
            entries=tuple(pb.Entry(cmd=f"c{i}".encode()) for i in range(10)),
        )
    )
    assert nt.nodes[2].log.committed == 11


def test_quorum_commit_with_five_nodes():
    nt = make_network(5)
    nt.elect(1)
    # only 2 of 5 get the entry (leader + one): no commit
    for rid in (3, 4, 5):
        nt.isolate(rid)
    nt.propose(1, b"x")
    assert nt.nodes[1].log.committed == 1
    # heal one more: 3/5 -> commit. trigger via heartbeat response cycle
    nt.heal()
    nt.isolate(4)
    nt.isolate(5)
    nt.nodes[1].handle(pb.Message(type=MT.LEADER_HEARTBEAT, from_=1))
    nt.send(nt.collect())
    assert nt.nodes[1].log.committed == 2


def test_leader_transfer_basic():
    nt = make_network(3)
    nt.elect(1)
    nt.start(pb.Message(type=MT.LEADER_TRANSFER, to=1, from_=1, hint=2))
    assert nt.nodes[2].state == RaftState.LEADER
    assert nt.nodes[1].state == RaftState.FOLLOWER
    assert nt.nodes[2].term == nt.nodes[1].term


def test_leader_transfer_via_follower_forwarded():
    nt = make_network(3)
    nt.elect(1)
    # request sent to a follower gets forwarded to the leader
    nt.start(pb.Message(type=MT.LEADER_TRANSFER, to=3, from_=3, hint=2))
    assert nt.nodes[2].state == RaftState.LEADER


def test_leader_transfer_to_lagging_node_waits_for_catchup():
    nt = make_network(3)
    nt.elect(1)
    r1 = nt.nodes[1]
    nt.isolate(2)
    nt.propose(1, b"x")
    nt.heal()
    # node 2 lags; the transfer waits, and the next heartbeat cycle drives
    # catch-up -> TimeoutNow (p29 raft thesis). In the engine the RTT tick
    # provides the heartbeat; here we trigger it explicitly.
    nt.start(pb.Message(type=MT.LEADER_TRANSFER, to=1, from_=1, hint=2))
    assert r1.leader_transfer_target == 2
    nt.start(pb.Message(type=MT.LEADER_HEARTBEAT, to=1, from_=1))
    assert nt.nodes[2].state == RaftState.LEADER
    assert nt.nodes[2].log.committed == r1.log.committed


def test_leader_transfer_aborts_after_election_timeout():
    nt = make_network(3)
    nt.elect(1)
    r1 = nt.nodes[1]
    nt.isolate(2)
    r1.handle(pb.Message(type=MT.LEADER_TRANSFER, to=1, from_=1, hint=2))
    assert r1.leader_transfer_target == 2
    # proposals are dropped while transferring
    r1.handle(pb.Message(type=MT.PROPOSE, from_=1, entries=(pb.Entry(cmd=b"x"),)))
    assert r1.dropped_entries
    for _ in range(r1.election_timeout + 1):
        r1.tick()
    assert r1.leader_transfer_target == 0  # aborted
    r1.msgs = []
    r1.handle(pb.Message(type=MT.PROPOSE, from_=1, entries=(pb.Entry(cmd=b"y"),)))
    assert any(m.type == MT.REPLICATE for m in r1.msgs)


def test_read_index_quorum_protocol():
    nt = make_network(3)
    nt.elect(1)
    r1 = nt.nodes[1]
    ctx = pb.SystemCtx(low=7, high=9)
    nt.start(pb.Message(type=MT.READ_INDEX, to=1, from_=1, hint=7, hint_high=9))
    assert len(r1.ready_to_read) == 1
    rtr = r1.ready_to_read[0]
    assert rtr.index == r1.log.committed
    assert rtr.system_ctx == ctx


def test_read_index_single_node_fast_path():
    nt = make_network(1)
    nt.elect(1)
    r1 = nt.nodes[1]
    r1.handle(pb.Message(type=MT.READ_INDEX, from_=1, hint=3, hint_high=4))
    assert len(r1.ready_to_read) == 1


def test_read_index_dropped_before_first_commit():
    """Section 6.4 raft thesis: leader must have committed an entry in its
    current term before serving ReadIndex."""
    r = new_raft(1, [1, 2, 3])
    r.handle(pb.Message(type=MT.ELECTION, from_=1))
    r.handle(pb.Message(type=MT.REQUEST_VOTE_RESP, from_=2, term=1))
    assert r.state == RaftState.LEADER
    assert r.log.committed == 0  # noop not yet acked
    r.handle(pb.Message(type=MT.READ_INDEX, from_=1, hint=1, hint_high=1))
    assert not r.ready_to_read
    assert r.dropped_read_indexes == [pb.SystemCtx(low=1, high=1)]


def test_read_index_forwarded_by_follower():
    nt = make_network(3)
    nt.elect(1)
    nt.start(pb.Message(type=MT.READ_INDEX, to=2, from_=2, hint=5, hint_high=6))
    # follower 2 receives ReadIndexResp and surfaces ready-to-read
    r2 = nt.nodes[2]
    assert len(r2.ready_to_read) == 1
    assert r2.ready_to_read[0].system_ctx == pb.SystemCtx(low=5, high=6)


def test_read_index_not_confirmed_without_quorum():
    nt = make_network(3)
    nt.elect(1)
    r1 = nt.nodes[1]
    nt.isolate(2)
    nt.isolate(3)
    r1.handle(pb.Message(type=MT.READ_INDEX, from_=1, hint=5, hint_high=6))
    r1.msgs = []
    assert not r1.ready_to_read
    assert r1.read_index.has_pending_request()


def test_witness_gets_metadata_entries():
    nt = Network(
        {
            1: new_raft(1, [1, 2], witnesses=[3]),
            2: new_raft(2, [1, 2], witnesses=[3]),
            3: new_raft(3, [1, 2], witnesses=[3], is_witness=True),
        }
    )
    nt.elect(1)
    nt.propose(1, b"secret")
    w = nt.nodes[3]
    assert w.state == RaftState.WITNESS
    assert w.log.committed == nt.nodes[1].log.committed
    # witness log must contain metadata entries, never the payload
    ents = w.log.get_entries(1, w.log.committed + 1)
    assert all(e.type == pb.EntryType.METADATA for e in ents)
    assert all(e.cmd == b"" for e in ents)
    # witness match counts toward quorum
    r1 = nt.nodes[1]
    assert r1.witnesses[3].match == r1.log.committed


def test_nonvoting_replicates_but_no_quorum():
    nt = Network(
        {
            1: new_raft(1, [1, 2], non_votings=[3]),
            2: new_raft(2, [1, 2], non_votings=[3]),
            3: new_raft(3, [1, 2], non_votings=[3], is_non_voting=True),
        }
    )
    nt.elect(1)
    nt.propose(1, b"x")
    assert nt.nodes[3].log.committed == nt.nodes[1].log.committed
    assert committed_cmds(nt.nodes[3]) == [b"x"]
    # nonvoting doesn't count toward quorum: isolate node 2 -> no commit
    nt.isolate(2)
    nt.propose(1, b"y")
    assert b"y" not in committed_cmds(nt.nodes[1])


def test_logs_converge_after_partition():
    nt = make_network(3)
    nt.elect(1)
    nt.isolate(1)
    # other side elects node 2 (its log: noop@term1)
    nt.nodes[2].applied = nt.nodes[2].log.committed
    nt.elect(2)
    nt.propose(2, b"new")
    # old leader keeps proposing into the void
    nt.propose(1, b"lost")
    nt.heal()
    # heartbeat from the real leader makes node1 catch up
    nt.start(pb.Message(type=MT.LEADER_HEARTBEAT, to=2, from_=2))
    nt.propose(2, b"after")
    logs = [committed_cmds(nt.nodes[i]) for i in (1, 2, 3)]
    assert logs[0] == logs[1] == logs[2]
    assert b"lost" not in logs[0]
    assert logs[0][-2:] == [b"new", b"after"]
