"""Engine-equivalence differential: the two engines' kernel dispatch
paths are the same state machine, bit for bit, under a randomized
schedule.

Both engines run KernelEngine.step_all over the unified dispatch seam
(engine/dispatch.py): the serial backend drives the router-layout
kernel (core/router.cluster_step: step + host-shaped routing), the
mesh backend drives the shard_map serving entry (parallel/ici.py:
step + device psum routing on a (g, r) mesh).  Everything above that
seam — staging, retirement, node bookkeeping — is shared KernelEngine
code, so the backends' jit entries are the exact point where the two
engines can diverge — and each backend exposes a donated + non-donated
entry pair, so BOTH depths need pinning: the depth-0 arm drives the
non-donated oracles, the depth-1 arm the donated entries under the
engine's retire-before-dispatch protocol (step N-1's state is pulled
to the host before step N's dispatch hands the buffers to XLA).

tests/test_mesh_differential.py pins the seam under the deterministic
self-driving schedule.  This file pins it under an ADVERSARIAL one: 300
micro-steps of randomized leader-masked proposals and randomized ticks
(missed ticks reorder election timeouts; bursty proposals exercise
batch-full paths), generated once per step in router layout and
permuted onto the mesh rows, so both paths consume identical inputs.
After every step the mesh ShardState — permuted back to the router's
group-major layout — must equal the router state bitwise, and the
mesh's device-side pending count must equal the router inbox's
occupancy.  Runs on the forced multi-device CPU mesh (conftest sets
xla_force_host_platform_device_count=8); skips when fewer than 2
devices are available.

Both loops run under ``capacity.METER.guard()``
(``jax.transfer_guard("disallow")``) from step 1 on: step 0 compiles
the jit entries, after that every device<->host crossing the loop
makes is declared through ``METER.sanctioned`` — an undeclared one
(a numpy tree slipping into a jit call, an implicit ``int()`` of a
device scalar) raises instead of silently round-tripping the host.
"""

import contextlib

import jax
import numpy as np
import pytest
from jax.sharding import Mesh

from dragonboat_tpu import capacity as _capacity
from dragonboat_tpu.core import params as KP
from dragonboat_tpu.core.router import cluster_step, cluster_step_donated
from dragonboat_tpu.parallel.ici import (
    ici_serve_step,
    jit_serve_step_donated,
    make_ici_cluster,
)
from dragonboat_tpu.core.kstate import StepInput

STEPS = 300
G_SIZE, REPLICAS, N_LOCAL = 1, 2, 4  # 8 rows on 2 mesh devices


def _kp(replicas: int) -> KP.KernelParams:
    return KP.KernelParams(
        num_peers=replicas,
        log_cap=64,
        inbox_cap=5 * max(1, replicas - 1),
        msg_entries=4,
        proposal_cap=4,
        readindex_cap=4,
        apply_batch=16,
        compaction_overhead=16,
    )


def _mesh(g_size: int, replicas: int) -> Mesh:
    devs = jax.devices()
    need = g_size * replicas
    if len(devs) < need:
        pytest.skip(f"needs {need} devices")
    return Mesh(np.array(devs[:need]).reshape(g_size, replicas), ("g", "r"))


def _perm(g_size: int, replicas: int, n_local: int) -> np.ndarray:
    """perm[router_row] = mesh_row for the same (group, replica)."""
    N = g_size * n_local
    perm = np.empty(N * replicas, np.int64)
    for g in range(N):
        ig, n = divmod(g, n_local)
        for ir in range(replicas):
            perm[g * replicas + ir] = (ig * replicas + ir) * n_local + n
    return perm


def _pull(tree):
    # np.array, not np.asarray: on CPU np.asarray of a jax array is a
    # ZERO-COPY view of the device buffer, and the depth-1 arm donates
    # those buffers right after retiring them — a view would be read
    # after XLA reclaimed the storage (observed as a segfault)
    return jax.tree.map(lambda x: np.array(x), tree)


def _permute(tree, perm):
    return jax.tree.map(lambda x: x[perm], tree)


def _assert_equal(tag, a, b):
    for f, xa, xb in zip(type(a)._fields, a, b):
        if xa is None and xb is None:
            continue
        assert np.array_equal(np.asarray(xa), np.asarray(xb)), (
            f"{tag}: field {f} diverged")


def _random_input(kp: KP.KernelParams, rng: np.random.Generator,
                  state_np, layout_perm: np.ndarray | None) -> StepInput:
    """One randomized step input, derived from ROUTER-ROW randomness.

    The raw draws are indexed by router row; ``layout_perm`` (the
    inverse row permutation, or None for the router side) re-lands them
    on the mesh rows so both paths see the same (group, replica)
    schedule.  Leader masking and the applied cursor come from the
    caller's own state, which the lockstep invariant keeps bitwise
    equal across layouts.
    """
    G, B = state_np.term.shape[0], kp.proposal_cap
    pv = rng.random((G, B)) < 0.5
    tick = rng.random(G) < 0.9
    if layout_perm is not None:
        pv, tick = pv[layout_perm], tick[layout_perm]
    is_leader = np.asarray(state_np.role) == KP.LEADER
    z = lambda: np.zeros((G,), np.int32)  # noqa: E731
    return StepInput(
        prop_valid=pv & is_leader[:, None],
        prop_cc=np.zeros((G, B), bool),
        ri_valid=np.zeros((G,), bool),
        ri_low=z(),
        ri_high=z(),
        transfer_to=z(),
        tick=tick,
        quiesced=np.zeros((G,), bool),
        applied=np.asarray(state_np.processed),
    )


@pytest.mark.parametrize("seed", [3, 11])
def test_engine_kernel_paths_bitwise_equal(seed):
    """300 randomized micro-steps, lockstep, bitwise-identical state."""
    kp = _kp(REPLICAS)
    mesh = _mesh(G_SIZE, REPLICAS)
    cluster, state_m, box_m = make_ici_cluster(
        kp, mesh, num_groups=G_SIZE * N_LOCAL)
    perm = _perm(G_SIZE, REPLICAS, N_LOCAL)
    iperm = np.argsort(perm)  # mesh_row -> router_row source index
    cut = cluster.shard(np.zeros((cluster.total_rows,), bool))

    # identical starting state, router layout
    state_r = _permute(_pull(state_m), perm)
    box_r = _permute(_pull(box_m), perm)

    # one generator; each step draws router-layout randomness that both
    # paths consume (mesh side via iperm), so the schedules are identical
    rng = np.random.default_rng(seed)
    committed = 0
    guard = contextlib.ExitStack()  # entered after the compile step
    try:
        for step_no in range(STEPS):
            draws = rng.bit_generator.state  # rewind: same draws twice
            with _capacity.METER.sanctioned("retire"):
                st_r_np, st_m_np = _pull(state_r), _pull(state_m)
            inp_r = _random_input(kp, rng, st_r_np, None)
            rng.bit_generator.state = draws
            inp_m = _random_input(kp, rng, st_m_np, iperm)
            # explicit staging: a numpy tree into a jit call is exactly
            # what the guard exists to catch
            with _capacity.METER.sanctioned("input_up"):
                inp_m_dev = cluster.shard(inp_m)
                inp_r_dev = jax.device_put(inp_r)

            state_m, box_m, _, pending = ici_serve_step(
                cluster, state_m, box_m, inp_m_dev, cut)
            state_r, box_r, _ = cluster_step(
                kp, REPLICAS, state_r, box_r, inp_r_dev)

            with _capacity.METER.sanctioned("retire"):
                _assert_equal(f"seed {seed} step {step_no} state",
                              _permute(_pull(state_m), perm),
                              _pull(state_r))
                _assert_equal(f"seed {seed} step {step_no} box",
                              _permute(_pull(box_m), perm), _pull(box_r))
                occupancy = int((np.asarray(box_r.mtype) != 0).sum())
                committed = int(np.asarray(state_r.committed).max())
            # the mesh's device-side pending count is the router occupancy
            with _capacity.METER.sanctioned("mesh_pending"):
                assert int(pending) == occupancy, (
                    f"seed {seed} step {step_no}: pending diverged")
            if step_no == 0:
                guard.enter_context(_capacity.METER.guard())
    finally:
        guard.close()
    assert committed > 0, "randomized differential ran but never committed"


@pytest.mark.parametrize("seed", [3, 11])
def test_engine_kernel_paths_bitwise_equal_depth1(seed):
    """The donated depth-1 arm: 300 randomized micro-steps through BOTH
    engines' pipelined dispatch entries (core/router.cluster_step_donated
    vs parallel/ici.py jit_serve_step_donated), bitwise-identical.

    Mirrors the engine's retire-before-dispatch protocol: step N-1's
    state/box are pulled to the host (retired) BEFORE step N's dispatch
    donates the device buffers to XLA, inputs are built from the retired
    copies, and the mesh's device-side pending scalar is consumed one
    step late — exactly how KernelEngine.step_all at pipeline_depth=1
    consumes MeshDispatch's deferred count."""
    kp = _kp(REPLICAS)
    mesh = _mesh(G_SIZE, REPLICAS)
    cluster, state_m, box_m = make_ici_cluster(
        kp, mesh, num_groups=G_SIZE * N_LOCAL)
    perm = _perm(G_SIZE, REPLICAS, N_LOCAL)
    iperm = np.argsort(perm)
    cut = cluster.shard(np.zeros((cluster.total_rows,), bool))

    state_r = _permute(_pull(state_m), perm)
    box_r = _permute(_pull(box_m), perm)

    rng = np.random.default_rng(seed)
    committed = 0
    pending_dev = None
    guard = contextlib.ExitStack()  # entered after the compile step
    try:
        for step_no in range(STEPS):
            # retire step N-1: pull BEFORE dispatch — after the donating
            # call the old device buffers belong to XLA
            with _capacity.METER.sanctioned("retire"):
                st_m_mesh = _pull(state_m)
                bx_m = _permute(_pull(box_m), perm)
                st_r = _pull(state_r)
                bx_r = _pull(box_r)
            st_m = _permute(st_m_mesh, perm)
            _assert_equal(f"seed {seed} step {step_no} state (depth1)",
                          st_m, st_r)
            _assert_equal(f"seed {seed} step {step_no} box (depth1)",
                          bx_m, bx_r)
            if pending_dev is not None:
                # the deferred device scalar from step N-1's dispatch
                # must equal the router inbox occupancy after step N-1
                with _capacity.METER.sanctioned("mesh_pending"):
                    assert int(pending_dev) == int(
                        (bx_r.mtype != 0).sum()), (
                        f"seed {seed} step {step_no}: pending diverged "
                        "(depth1)")
            committed = int(st_r.committed.max())

            draws = rng.bit_generator.state
            inp_r = _random_input(kp, rng, st_r, None)
            rng.bit_generator.state = draws
            inp_m = _random_input(kp, rng, st_m_mesh, iperm)
            with _capacity.METER.sanctioned("input_up"):
                inp_m_dev = cluster.shard(inp_m)
                inp_r_dev = jax.device_put(inp_r)

            state_m, box_m, _, pending_dev = jit_serve_step_donated(
                kp, cluster, state_m, box_m, inp_m_dev, cut)
            state_r, box_r, _ = cluster_step_donated(
                kp, REPLICAS, state_r, box_r, inp_r_dev)
            if step_no == 0:
                guard.enter_context(_capacity.METER.guard())
    finally:
        guard.close()

    # final retire: the last dispatched step must still agree
    _assert_equal(f"seed {seed} final state (depth1)",
                  _permute(_pull(state_m), perm), _pull(state_r))
    _assert_equal(f"seed {seed} final box (depth1)",
                  _permute(_pull(box_m), perm), _pull(box_r))
    assert int(pending_dev) == int(
        (np.asarray(box_r.mtype) != 0).sum()), "final pending diverged"
    assert committed > 0, "depth-1 differential ran but never committed"
