"""Engine-equivalence differential: the two engines' kernel dispatch
paths are the same state machine, bit for bit, under a randomized
schedule.

KernelEngine._kernel_call drives the router-layout kernel
(core/router.cluster_step: step + host-shaped routing); MeshEngine
._kernel_call drives ici_serve_step (parallel/ici.py: step + device
psum routing under shard_map on a (g, r) mesh).  Everything above that
seam — staging, retirement, node bookkeeping — is shared KernelEngine
code, so this is the exact point where the two engines can diverge.

tests/test_mesh_differential.py pins the seam under the deterministic
self-driving schedule.  This file pins it under an ADVERSARIAL one: 300
micro-steps of randomized leader-masked proposals and randomized ticks
(missed ticks reorder election timeouts; bursty proposals exercise
batch-full paths), generated once per step in router layout and
permuted onto the mesh rows, so both paths consume identical inputs.
After every step the mesh ShardState — permuted back to the router's
group-major layout — must equal the router state bitwise, and the
mesh's device-side pending count must equal the router inbox's
occupancy.  Runs on the forced multi-device CPU mesh (conftest sets
xla_force_host_platform_device_count=8); skips when fewer than 2
devices are available.
"""

import jax
import numpy as np
import pytest
from jax.sharding import Mesh

from dragonboat_tpu.core import params as KP
from dragonboat_tpu.core.router import cluster_step
from dragonboat_tpu.parallel.ici import (
    ici_serve_step,
    make_ici_cluster,
)
from dragonboat_tpu.core.kstate import StepInput

STEPS = 300
G_SIZE, REPLICAS, N_LOCAL = 1, 2, 4  # 8 rows on 2 mesh devices


def _kp(replicas: int) -> KP.KernelParams:
    return KP.KernelParams(
        num_peers=replicas,
        log_cap=64,
        inbox_cap=5 * max(1, replicas - 1),
        msg_entries=4,
        proposal_cap=4,
        readindex_cap=4,
        apply_batch=16,
        compaction_overhead=16,
    )


def _mesh(g_size: int, replicas: int) -> Mesh:
    devs = jax.devices()
    need = g_size * replicas
    if len(devs) < need:
        pytest.skip(f"needs {need} devices")
    return Mesh(np.array(devs[:need]).reshape(g_size, replicas), ("g", "r"))


def _perm(g_size: int, replicas: int, n_local: int) -> np.ndarray:
    """perm[router_row] = mesh_row for the same (group, replica)."""
    N = g_size * n_local
    perm = np.empty(N * replicas, np.int64)
    for g in range(N):
        ig, n = divmod(g, n_local)
        for ir in range(replicas):
            perm[g * replicas + ir] = (ig * replicas + ir) * n_local + n
    return perm


def _pull(tree):
    return jax.tree.map(lambda x: np.asarray(x), tree)


def _permute(tree, perm):
    return jax.tree.map(lambda x: x[perm], tree)


def _assert_equal(tag, a, b):
    for f, xa, xb in zip(type(a)._fields, a, b):
        if xa is None and xb is None:
            continue
        assert np.array_equal(np.asarray(xa), np.asarray(xb)), (
            f"{tag}: field {f} diverged")


def _random_input(kp: KP.KernelParams, rng: np.random.Generator,
                  state_np, layout_perm: np.ndarray | None) -> StepInput:
    """One randomized step input, derived from ROUTER-ROW randomness.

    The raw draws are indexed by router row; ``layout_perm`` (the
    inverse row permutation, or None for the router side) re-lands them
    on the mesh rows so both paths see the same (group, replica)
    schedule.  Leader masking and the applied cursor come from the
    caller's own state, which the lockstep invariant keeps bitwise
    equal across layouts.
    """
    G, B = state_np.term.shape[0], kp.proposal_cap
    pv = rng.random((G, B)) < 0.5
    tick = rng.random(G) < 0.9
    if layout_perm is not None:
        pv, tick = pv[layout_perm], tick[layout_perm]
    is_leader = np.asarray(state_np.role) == KP.LEADER
    z = lambda: np.zeros((G,), np.int32)  # noqa: E731
    return StepInput(
        prop_valid=pv & is_leader[:, None],
        prop_cc=np.zeros((G, B), bool),
        ri_valid=np.zeros((G,), bool),
        ri_low=z(),
        ri_high=z(),
        transfer_to=z(),
        tick=tick,
        quiesced=np.zeros((G,), bool),
        applied=np.asarray(state_np.processed),
    )


@pytest.mark.parametrize("seed", [3, 11])
def test_engine_kernel_paths_bitwise_equal(seed):
    """300 randomized micro-steps, lockstep, bitwise-identical state."""
    kp = _kp(REPLICAS)
    mesh = _mesh(G_SIZE, REPLICAS)
    cluster, state_m, box_m = make_ici_cluster(
        kp, mesh, num_groups=G_SIZE * N_LOCAL)
    perm = _perm(G_SIZE, REPLICAS, N_LOCAL)
    iperm = np.argsort(perm)  # mesh_row -> router_row source index
    cut = cluster.shard(np.zeros((cluster.total_rows,), bool))

    # identical starting state, router layout
    state_r = _permute(_pull(state_m), perm)
    box_r = _permute(_pull(box_m), perm)

    # one generator; each step draws router-layout randomness that both
    # paths consume (mesh side via iperm), so the schedules are identical
    rng = np.random.default_rng(seed)
    committed = 0
    for step_no in range(STEPS):
        draws = rng.bit_generator.state  # rewind point: same draws twice
        inp_r = _random_input(kp, rng, _pull(state_r), None)
        rng.bit_generator.state = draws
        inp_m = _random_input(kp, rng, _pull(state_m), iperm)

        state_m, box_m, _, pending = ici_serve_step(
            cluster, state_m, box_m, cluster.shard(inp_m), cut)
        state_r, box_r, _ = cluster_step(kp, REPLICAS, state_r, box_r, inp_r)

        _assert_equal(f"seed {seed} step {step_no} state",
                      _permute(_pull(state_m), perm), _pull(state_r))
        _assert_equal(f"seed {seed} step {step_no} box",
                      _permute(_pull(box_m), perm), _pull(box_r))
        # the mesh's device-side pending count is the router occupancy
        assert int(pending) == int((np.asarray(box_r.mtype) != 0).sum()), (
            f"seed {seed} step {step_no}: pending diverged")
        committed = int(np.asarray(state_r.committed).max())
    assert committed > 0, "randomized differential ran but never committed"
