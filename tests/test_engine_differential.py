"""Engine-equivalence differential: the two engines' kernel dispatch
paths are the same state machine, bit for bit, under a randomized
schedule.

Both engines run KernelEngine.step_all over the unified dispatch seam
(engine/dispatch.py): the serial backend drives the router-layout
kernel (core/router.cluster_step: step + host-shaped routing), the
mesh backend drives the shard_map serving entry (parallel/ici.py:
step + device psum routing on a (g, r) mesh).  Everything above that
seam — staging, retirement, node bookkeeping — is shared KernelEngine
code, so the backends' jit entries are the exact point where the two
engines can diverge — and each backend exposes a donated + non-donated
entry pair, so BOTH depths need pinning: the depth-0 arm drives the
non-donated oracles, the depth-1 arm the donated entries under the
engine's retire-before-dispatch protocol (step N-1's state is pulled
to the host before step N's dispatch hands the buffers to XLA).

tests/test_mesh_differential.py pins the seam under the deterministic
self-driving schedule.  This file pins it under an ADVERSARIAL one: 300
micro-steps of randomized leader-masked proposals and randomized ticks
(missed ticks reorder election timeouts; bursty proposals exercise
batch-full paths), generated once per step in router layout and
permuted onto the mesh rows, so both paths consume identical inputs.
After every step the mesh ShardState — permuted back to the router's
group-major layout — must equal the router state bitwise, box
included.  Runs on the forced multi-device CPU mesh (conftest sets
xla_force_host_platform_device_count=8); skips when fewer than 2
devices are available.

Round 17 adds a THIRD arm: the same randomized schedule driven once
over the device-resident exchange (open per-link cut mask: messages
ride the in-step collective) and once over the hub-delivery path
(every link cut: messages leave via the out-lanes and are staged back
host-side through the router's slot layout — the same addressing the
engine's slot-exact _InboxBuilder uses).  The two arms must be
bitwise-identical at every step, at pipeline depth 0 and 1, proving a
link falling back to the hub cannot change the state machine.

Both loops run under ``capacity.METER.guard()``
(``jax.transfer_guard("disallow")``) from step 1 on: step 0 compiles
the jit entries, after that every device<->host crossing the loop
makes is declared through ``METER.sanctioned`` — an undeclared one
(a numpy tree slipping into a jit call, an implicit ``int()`` of a
device scalar) raises instead of silently round-tripping the host.
"""

import contextlib

import jax
import numpy as np
import pytest
from jax.sharding import Mesh

from dragonboat_tpu import capacity as _capacity
from dragonboat_tpu.core import params as KP
from dragonboat_tpu.core import router as _router
from dragonboat_tpu.core.router import cluster_step, cluster_step_donated, route
from dragonboat_tpu.parallel.ici import (
    ici_serve_step,
    jit_serve_step_donated,
    make_ici_cluster,
)
from dragonboat_tpu.core.kstate import StepInput

STEPS = 300
G_SIZE, REPLICAS, N_LOCAL = 1, 2, 4  # 8 rows on 2 mesh devices


def _kp(replicas: int) -> KP.KernelParams:
    return KP.KernelParams(
        num_peers=replicas,
        log_cap=64,
        inbox_cap=5 * max(1, replicas - 1),
        msg_entries=4,
        proposal_cap=4,
        readindex_cap=4,
        apply_batch=16,
        compaction_overhead=16,
    )


def _mesh(g_size: int, replicas: int) -> Mesh:
    devs = jax.devices()
    need = g_size * replicas
    if len(devs) < need:
        pytest.skip(f"needs {need} devices")
    return Mesh(np.array(devs[:need]).reshape(g_size, replicas), ("g", "r"))


def _perm(g_size: int, replicas: int, n_local: int) -> np.ndarray:
    """perm[router_row] = mesh_row for the same (group, replica)."""
    N = g_size * n_local
    perm = np.empty(N * replicas, np.int64)
    for g in range(N):
        ig, n = divmod(g, n_local)
        for ir in range(replicas):
            perm[g * replicas + ir] = (ig * replicas + ir) * n_local + n
    return perm


def _pull(tree):
    # np.array, not np.asarray: on CPU np.asarray of a jax array is a
    # ZERO-COPY view of the device buffer, and the depth-1 arm donates
    # those buffers right after retiring them — a view would be read
    # after XLA reclaimed the storage (observed as a segfault)
    return jax.tree.map(lambda x: np.array(x), tree)


def _permute(tree, perm):
    return jax.tree.map(lambda x: x[perm], tree)


def _assert_equal(tag, a, b):
    for f, xa, xb in zip(type(a)._fields, a, b):
        if xa is None and xb is None:
            continue
        assert np.array_equal(np.asarray(xa), np.asarray(xb)), (
            f"{tag}: field {f} diverged")


def _random_input(kp: KP.KernelParams, rng: np.random.Generator,
                  state_np, layout_perm: np.ndarray | None) -> StepInput:
    """One randomized step input, derived from ROUTER-ROW randomness.

    The raw draws are indexed by router row; ``layout_perm`` (the
    inverse row permutation, or None for the router side) re-lands them
    on the mesh rows so both paths see the same (group, replica)
    schedule.  Leader masking and the applied cursor come from the
    caller's own state, which the lockstep invariant keeps bitwise
    equal across layouts.
    """
    G, B = state_np.term.shape[0], kp.proposal_cap
    pv = rng.random((G, B)) < 0.5
    tick = rng.random(G) < 0.9
    if layout_perm is not None:
        pv, tick = pv[layout_perm], tick[layout_perm]
    is_leader = np.asarray(state_np.role) == KP.LEADER
    z = lambda: np.zeros((G,), np.int32)  # noqa: E731
    return StepInput(
        prop_valid=pv & is_leader[:, None],
        prop_cc=np.zeros((G, B), bool),
        ri_valid=np.zeros((G,), bool),
        ri_low=z(),
        ri_high=z(),
        transfer_to=z(),
        tick=tick,
        quiesced=np.zeros((G,), bool),
        applied=np.asarray(state_np.processed),
    )


@pytest.mark.parametrize("seed", [3, 11])
def test_engine_kernel_paths_bitwise_equal(seed):
    """300 randomized micro-steps, lockstep, bitwise-identical state."""
    kp = _kp(REPLICAS)
    mesh = _mesh(G_SIZE, REPLICAS)
    cluster, state_m, box_m = make_ici_cluster(
        kp, mesh, num_groups=G_SIZE * N_LOCAL)
    perm = _perm(G_SIZE, REPLICAS, N_LOCAL)
    iperm = np.argsort(perm)  # mesh_row -> router_row source index
    cut = cluster.shard(
        np.zeros((cluster.total_rows, kp.num_peers), bool))

    # identical starting state, router layout
    state_r = _permute(_pull(state_m), perm)
    box_r = _permute(_pull(box_m), perm)

    # one generator; each step draws router-layout randomness that both
    # paths consume (mesh side via iperm), so the schedules are identical
    rng = np.random.default_rng(seed)
    committed = 0
    guard = contextlib.ExitStack()  # entered after the compile step
    try:
        for step_no in range(STEPS):
            draws = rng.bit_generator.state  # rewind: same draws twice
            with _capacity.METER.sanctioned("retire"):
                st_r_np, st_m_np = _pull(state_r), _pull(state_m)
            inp_r = _random_input(kp, rng, st_r_np, None)
            rng.bit_generator.state = draws
            inp_m = _random_input(kp, rng, st_m_np, iperm)
            # explicit staging: a numpy tree into a jit call is exactly
            # what the guard exists to catch
            with _capacity.METER.sanctioned("input_up"):
                inp_m_dev = cluster.shard(inp_m)
                inp_r_dev = jax.device_put(inp_r)

            state_m, box_m, _ = ici_serve_step(
                cluster, state_m, box_m, inp_m_dev, cut)
            state_r, box_r, _ = cluster_step(
                kp, REPLICAS, state_r, box_r, inp_r_dev)

            with _capacity.METER.sanctioned("retire"):
                _assert_equal(f"seed {seed} step {step_no} state",
                              _permute(_pull(state_m), perm),
                              _pull(state_r))
                _assert_equal(f"seed {seed} step {step_no} box",
                              _permute(_pull(box_m), perm), _pull(box_r))
                committed = int(np.asarray(state_r.committed).max())
            if step_no == 0:
                guard.enter_context(_capacity.METER.guard())
    finally:
        guard.close()
    assert committed > 0, "randomized differential ran but never committed"


@pytest.mark.parametrize("seed", [3, 11])
def test_engine_kernel_paths_bitwise_equal_depth1(seed):
    """The donated depth-1 arm: 300 randomized micro-steps through BOTH
    engines' pipelined dispatch entries (core/router.cluster_step_donated
    vs parallel/ici.py jit_serve_step_donated), bitwise-identical.

    Mirrors the engine's retire-before-dispatch protocol: step N-1's
    state/box are pulled to the host (retired) BEFORE step N's dispatch
    donates the device buffers to XLA and inputs are built from the
    retired copies — exactly how KernelEngine.step_all at
    pipeline_depth=1 runs MeshDispatch."""
    kp = _kp(REPLICAS)
    mesh = _mesh(G_SIZE, REPLICAS)
    cluster, state_m, box_m = make_ici_cluster(
        kp, mesh, num_groups=G_SIZE * N_LOCAL)
    perm = _perm(G_SIZE, REPLICAS, N_LOCAL)
    iperm = np.argsort(perm)
    cut = cluster.shard(
        np.zeros((cluster.total_rows, kp.num_peers), bool))

    state_r = _permute(_pull(state_m), perm)
    box_r = _permute(_pull(box_m), perm)

    rng = np.random.default_rng(seed)
    committed = 0
    guard = contextlib.ExitStack()  # entered after the compile step
    try:
        for step_no in range(STEPS):
            # retire step N-1: pull BEFORE dispatch — after the donating
            # call the old device buffers belong to XLA
            with _capacity.METER.sanctioned("retire"):
                st_m_mesh = _pull(state_m)
                bx_m = _permute(_pull(box_m), perm)
                st_r = _pull(state_r)
                bx_r = _pull(box_r)
            st_m = _permute(st_m_mesh, perm)
            _assert_equal(f"seed {seed} step {step_no} state (depth1)",
                          st_m, st_r)
            _assert_equal(f"seed {seed} step {step_no} box (depth1)",
                          bx_m, bx_r)
            committed = int(st_r.committed.max())

            draws = rng.bit_generator.state
            inp_r = _random_input(kp, rng, st_r, None)
            rng.bit_generator.state = draws
            inp_m = _random_input(kp, rng, st_m_mesh, iperm)
            with _capacity.METER.sanctioned("input_up"):
                inp_m_dev = cluster.shard(inp_m)
                inp_r_dev = jax.device_put(inp_r)

            state_m, box_m, _ = jit_serve_step_donated(
                kp, cluster, state_m, box_m, inp_m_dev, cut)
            state_r, box_r, _ = cluster_step_donated(
                kp, REPLICAS, state_r, box_r, inp_r_dev)
            if step_no == 0:
                guard.enter_context(_capacity.METER.guard())
    finally:
        guard.close()

    # final retire: the last dispatched step must still agree
    _assert_equal(f"seed {seed} final state (depth1)",
                  _permute(_pull(state_m), perm), _pull(state_r))
    _assert_equal(f"seed {seed} final box (depth1)",
                  _permute(_pull(box_m), perm), _pull(box_r))
    assert committed > 0, "depth-1 differential ran but never committed"


def _audit_slots(box_np, R: int) -> None:
    """Every occupied inbox slot must be one the hub's slot-exact
    builder would have picked for that (target, source, type) — pins
    core/router.slot_candidates against route()'s actual placement."""
    mt, frm = box_np.mtype, box_np.from_
    rows, K = mt.shape
    for row in range(rows):
        t_rid = row % R + 1
        for k in range(K):
            m = int(mt[row, k])
            if m == 0:
                continue
            cands = _router.slot_candidates(t_rid, int(frm[row, k]), R, m)
            assert k in cands, (
                f"row {row} slot {k}: type {m} from {int(frm[row, k])} "
                f"landed outside its slot candidates {cands}")


@pytest.mark.parametrize("depth", [0, 1])
def test_resident_exchange_bitwise_matches_hub_delivery(depth):
    """Third arm (round 17): device-resident exchange vs hub delivery.

    Arm A serves with an all-open per-link mask — messages ride the
    in-step collective.  Arm B serves with EVERY link cut — the step
    emits but exchanges nothing on the mesh, and the host stages the
    out-lanes back through the router slot layout (the hub fallback's
    addressing, core/router.slot_candidates) as the next step's inbox.
    The same router-layout randomness drives both arms; their states
    must stay bitwise-identical for 300 micro-steps at pipeline depth 0
    (lockstep entries) and depth 1 (donated entries, retire-before-
    dispatch), and every hub-staged slot must be one the slot-exact
    builder would have picked.  This is the proof that a link falling
    back to the hub cannot change the state machine — only where the
    bytes travel."""
    kp = _kp(REPLICAS)
    mesh = _mesh(G_SIZE, REPLICAS)
    cluster, state_a, box_a = make_ici_cluster(
        kp, mesh, num_groups=G_SIZE * N_LOCAL)
    perm = _perm(G_SIZE, REPLICAS, N_LOCAL)
    iperm = np.argsort(perm)
    total = cluster.total_rows
    cut_open = cluster.shard(np.zeros((total, kp.num_peers), bool))
    cut_all = cluster.shard(np.ones((total, kp.num_peers), bool))

    # arm B starts from a bitwise copy of arm A's state (fresh buffers:
    # the depth-1 arm donates, so the two arms cannot share storage)
    with _capacity.METER.sanctioned("retire"):
        init_np, box_np = _pull(state_a), _pull(box_a)
    state_b, box_b = cluster.shard(init_np), cluster.shard(box_np)

    def serve(state, box, inp, cutm):
        if depth == 0:
            return ici_serve_step(cluster, state, box, inp, cutm)
        return jit_serve_step_donated(kp, cluster, state, box, inp, cutm)

    route_jit = jax.jit(route, static_argnums=(0, 1))
    rng = np.random.default_rng(7)
    committed = 0
    guard = contextlib.ExitStack()  # entered after the compile step
    try:
        for step_no in range(STEPS):
            with _capacity.METER.sanctioned("retire"):
                st_a, st_b = _pull(state_a), _pull(state_b)
            _assert_equal(f"depth {depth} step {step_no} arm state",
                          st_a, st_b)
            committed = int(st_a.committed.max())

            draws = rng.bit_generator.state  # same draws for both arms
            inp_a = _random_input(kp, rng, st_a, iperm)
            rng.bit_generator.state = draws
            inp_b = _random_input(kp, rng, st_b, iperm)
            with _capacity.METER.sanctioned("input_up"):
                inp_a_dev = cluster.shard(inp_a)
                inp_b_dev = cluster.shard(inp_b)

            state_a, box_a, _ = serve(state_a, box_a, inp_a_dev, cut_open)
            state_b, box_ret, out_b = serve(
                state_b, box_b, inp_b_dev, cut_all)

            with _capacity.METER.sanctioned("retire"):
                ret_np = _pull(box_ret)
                out_rt = _permute(_pull(out_b), perm)   # router layout
                box_a_np = _permute(_pull(box_a), perm)
            assert not ret_np.mtype.any(), (
                "all-links-cut serve leaked traffic onto the mesh")
            # hub delivery: route the emitted lanes host-side and stage
            # the result as arm B's next inbox
            with _capacity.METER.sanctioned("hub_route"):
                hub_box = _pull(route_jit(kp, REPLICAS, out_rt))
            _assert_equal(f"depth {depth} step {step_no} arm box",
                          box_a_np, hub_box)
            _audit_slots(hub_box, REPLICAS)
            with _capacity.METER.sanctioned("inbox_up"):
                box_b = cluster.shard(_permute(hub_box, iperm))
            if step_no == 0:
                guard.enter_context(_capacity.METER.guard())
    finally:
        guard.close()
    assert committed > 0, "third-arm differential ran but never committed"
