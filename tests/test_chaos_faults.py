"""Fault-injection seams, unit-level: CrashPointFS (crash-at-Nth-op,
torn writes), the breaker's capped exponential backoff, the ErrorFS
fail_after matrix over every op against the snapshot writer and the env
flag files, per-subtree MemFS power loss, tan log quarantine, and the
controlled-crash -> restart() acceptance paths.

The composed end-to-end schedules live in test_chaos_schedules.py; this
file proves each seam in isolation so a schedule failure bisects.
"""

import json
import os
import time

import pytest

from dragonboat_tpu.chaos import CrashPointFS, FaultPlan
from dragonboat_tpu.chaos.faultplan import DOWN_KINDS, HEAL_FOR
from dragonboat_tpu.chaos.oracle import (
    check_monotone_applied,
    check_no_acked_loss,
    check_prefix_consistent,
)
from dragonboat_tpu.chaos.runner import _Cluster
from dragonboat_tpu.logdb.tan import CorruptLogError, TanLogDB
from dragonboat_tpu.transport.hub import (
    BREAKER_JITTER,
    CircuitBreaker,
)
from dragonboat_tpu.vfs import ErrorFS, InjectedError, MemFS
from dragonboat_tpu import raftpb as pb


# -- CrashPointFS ------------------------------------------------------------


def test_crashfs_counts_down_then_sticks():
    fs = CrashPointFS(MemFS())
    fs.arm(after_ops=2)
    with fs.open("/f", "wb") as f:
        f.write(b"a")            # matching op 1
        f.write(b"b")            # matching op 2
        with pytest.raises(InjectedError):
            f.write(b"c")        # trips
        assert fs.tripped
        with pytest.raises(InjectedError):
            f.write(b"d")        # stays dead until healed
    assert fs.trip_count == 2
    fs.heal()
    with fs.open("/f", "ab") as f:
        f.write(b"e")
    with fs.open("/f", "rb") as f:
        assert f.read() == b"abe"


def test_crashfs_fsync_is_a_matching_op():
    fs = CrashPointFS(MemFS())
    fs.arm(after_ops=1)
    with fs.open("/f", "wb") as f:
        f.write(b"a")                    # op 1
        with pytest.raises(InjectedError):
            fs.fsync(f)                  # op 2 trips


def test_crashfs_torn_write_lands_a_prefix():
    mem = MemFS()
    fs = CrashPointFS(mem)
    fs.arm(after_ops=1, torn=True)
    with fs.open("/f", "wb") as f:
        f.write(b"12345678")
        with pytest.raises(InjectedError):
            f.write(b"ABCDEFGH")         # torn: a strict prefix lands
    with mem.open("/f", "rb") as f:
        data = f.read()
    assert data.startswith(b"12345678")
    tail = data[8:]
    assert 0 < len(tail) < 8 and b"ABCDEFGH".startswith(tail)
    # only the TRIPPING write tears; the stuck state fails cleanly
    fs2 = CrashPointFS(mem)
    fs2.arm(after_ops=0, torn=True)
    with pytest.raises(InjectedError):
        with fs2.open("/g", "wb") as f:
            f.write(b"XY")
    with mem.open("/g", "rb") as f:
        assert f.read() == b"X"


def test_crashfs_path_substr_scopes_the_fault():
    fs = CrashPointFS(MemFS(), path_substr="/wal/")
    fs.arm(after_ops=0)
    with fs.open("/data/f", "wb") as f:
        f.write(b"fine")                 # not under /wal/
    with fs.open("/wal/g", "wb") as f:
        with pytest.raises(InjectedError):
            f.write(b"boom")


def test_crashfs_unarmed_is_transparent():
    fs = CrashPointFS(MemFS())
    with fs.open("/f", "wb") as f:
        f.write(b"data")
        fs.fsync(f)
    assert not fs.tripped and fs.trip_count == 0


# -- MemFS.crash(prefix): per-host power loss on a shared tree ---------------


def test_memfs_crash_prefix_scopes_power_loss():
    mem = MemFS()
    for host in ("/a", "/b"):
        with mem.open(host + "/synced", "wb") as f:
            f.write(b"durable")
            mem.fsync(f)
        with mem.open(host + "/dirty", "wb") as f:
            f.write(b"volatile")
    mem.crash("/a")
    assert not mem.exists("/a/dirty")          # unsynced: gone
    with mem.open("/a/synced", "rb") as f:
        assert f.read() == b"durable"
    with mem.open("/b/dirty", "rb") as f:      # other subtree untouched
        assert f.read() == b"volatile"


# -- CircuitBreaker backoff --------------------------------------------------


def test_breaker_closed_open_halfopen_closed():
    b = CircuitBreaker(reset_after=1.0, max_reset=30.0, seed=0)
    assert b.state(now=0.0) == "closed"
    assert b.ready(now=5.0)      # fresh breaker: ready once base elapses
    b.fail(now=10.0)
    assert b.state(now=10.0) == "open"
    assert not b.ready(now=10.5)
    # first cooldown: base * (1 + jitter in [0, BREAKER_JITTER))
    assert 1.0 <= b.reset_after <= 1.0 * (1 + BREAKER_JITTER)
    t = 10.0 + b.reset_after
    assert b.state(now=t) == "half-open"
    assert b.ready(now=t)
    b.succeed()
    assert b.state(now=t) == "closed"
    assert b.reset_after == 1.0          # backoff fully reset


def test_breaker_backoff_doubles_and_caps():
    b = CircuitBreaker(reset_after=1.0, max_reset=30.0, seed=3)
    seen = []
    for i in range(8):
        b.fail(now=float(i * 1000))
        seen.append(b.reset_after)
    # 2x growth dominates the <=25% jitter: strictly increasing to the cap
    for prev, cur in zip(seen, seen[1:]):
        assert cur > prev or cur == 30.0
    assert seen[-1] == 30.0
    assert not b.ready(now=7000.0 + 29.9)
    assert b.ready(now=7000.0 + 30.0)
    b.succeed()
    b.fail(now=99999.0)
    assert b.reset_after <= 1.0 * (1 + BREAKER_JITTER)


def test_breaker_jitter_is_seed_deterministic():
    fails = [float(i * 100) for i in range(6)]

    def cooldowns(seed):
        b = CircuitBreaker(reset_after=1.0, max_reset=3600.0, seed=seed)
        out = []
        for t in fails:
            b.fail(now=t)
            out.append(b.reset_after)
        return out

    assert cooldowns(7) == cooldowns(7)          # replayable
    assert cooldowns(7) != cooldowns(8)          # but per-seed distinct


def test_hub_trip_breaker_forces_open():
    from dragonboat_tpu.chaos.runner import ChaosKV
    from dragonboat_tpu.config import Config, NodeHostConfig
    from dragonboat_tpu.nodehost import NodeHost

    nh = NodeHost(NodeHostConfig(raft_address="trip-1", rtt_millisecond=5))
    try:
        nh.start_replica({1: "trip-1"}, False, ChaosKV,
                         Config(shard_id=1, replica_id=1, election_rtt=10,
                                heartbeat_rtt=1))
        b = nh.hub.trip_breaker("elsewhere-1", count=3)
        assert b.state() == "open"
        assert b.trip_streak == 3
        # same addr on a fresh hub -> same per-addr jitter seed: the
        # cooldown sequence is identical (replay contract)
        b2 = CircuitBreaker(seed=__import__("zlib").crc32(b"elsewhere-1"))
        for _ in range(3):
            b2.fail(now=0.0)
        assert b2.reset_after == b.reset_after
    finally:
        nh.close()


# -- ErrorFS fail_after matrix: snapshot writer + env flag files -------------

_ALL_OPS = ("open", "write", "read", "fsync", "remove", "replace", "listdir")


def _snapshot_env_workload(fs, root="/d"):
    """Exercises every ErrorFS op against the two durability surfaces the
    matrix targets: rsm/snapshotio.py container IO and server/env.py
    flag files."""
    from dragonboat_tpu.rsm.snapshotio import read_snapshot, write_snapshot
    from dragonboat_tpu.server.env import Env

    env = Env(root, "addr-1", fs=fs)
    env.check_node_host_dir("sharded-tan")       # flag: open/write/fsync/replace
    snap = os.path.join(env.root, "snap.gbsnap")
    tmp = snap + ".tmp"
    with fs.open(tmp, "wb") as f:
        write_snapshot(f, b"sess", lambda w: w.write(b"payload" * 64))
        fs.fsync(f)
    fs.replace(tmp, snap)
    with fs.open(snap, "rb") as f:
        sess, reader = read_snapshot(f)
        assert sess == b"sess"
        assert reader.read() == b"payload" * 64
    assert "snap.gbsnap" in fs.listdir(env.root)
    scratch = os.path.join(env.root, "scratch")
    with fs.open(scratch, "wb") as f:
        f.write(b"x")
    fs.remove(scratch)
    env.close()


def _op_counts():
    counts = {}

    def tally(op, path):
        counts[op] = counts.get(op, 0) + 1
        return False

    _snapshot_env_workload(ErrorFS(MemFS(), tally))
    return counts


@pytest.mark.parametrize("op", _ALL_OPS)
def test_fail_after_matrix_controlled_crash_then_recover(op):
    """For every ErrorFS op: fail it early / midway / last, assert the
    workload dies with InjectedError (controlled crash, never silent
    corruption), then heal and assert full recovery — with the flag file
    either absent or complete valid JSON at every crash point (the
    tmp+fsync+replace discipline of env._write_flag)."""
    n = _op_counts()[op]
    assert n >= 1, f"workload never performs {op!r}"
    for after in sorted({0, n // 2, n - 1}):
        mem = MemFS()
        fs = CrashPointFS(mem, ops=(op,))
        fs.arm(after_ops=after)
        with pytest.raises(InjectedError):
            _snapshot_env_workload(fs)
        assert fs.tripped
        # atomicity at the crash point: a flag file, if present, parses
        flag = "/d/addr-1/dragonboat.ds"
        if mem.exists(flag):
            with mem.open(flag, "r") as f:
                assert json.loads(f.read())["address"] == "addr-1"
        fs.heal()
        _snapshot_env_workload(fs)       # recovery: the same dir reopens


# -- tan quarantine: corrupt NON-TAIL record ---------------------------------


def _fill_tan(root, fs, n_entries=60, max_file_size=512):
    db = TanLogDB(root, max_file_size=max_file_size, fs=fs)
    for i in range(1, n_entries + 1):
        db.save_raft_state([pb.Update(
            shard_id=1, replica_id=1,
            state=pb.State(term=1, vote=1, commit=i),
            entries_to_save=(pb.Entry(index=i, term=1,
                                      cmd=f"cmd-{i:04d}".encode()),),
        )], worker_id=0)
    db.close()


def _tan_files(root, fs):
    return sorted(f for f in fs.listdir(root) if f.endswith(".tan"))


def test_tan_corrupt_nontail_strict_refuses_quarantine_recovers():
    mem = MemFS()
    _fill_tan("/tan", mem)
    files = _tan_files("/tan", mem)
    assert len(files) >= 3, "need multiple files to corrupt a non-tail one"
    victim = os.path.join("/tan", files[len(files) // 2])
    with mem.open(victim, "r+b") as f:
        size = len(f.read())
        f.seek(size // 2)
        f.write(b"\xff")                 # flip mid-file: non-tail corruption
    with pytest.raises(CorruptLogError):
        TanLogDB("/tan", max_file_size=512, fs=mem)
    db = TanLogDB("/tan", max_file_size=512, fs=mem,
                  recovery_mode="quarantine")
    try:
        assert db.quarantined and victim in db.quarantined[0]
        rs = db.read_raft_state(1, 1, 0)
        # the commit clamp: persisted commit (60) exceeded what survived,
        # so it was pulled back inside the contiguous range still on disk
        assert rs is not None
        avail = rs.first_index + rs.entry_count - 1
        assert 0 < rs.state.commit <= avail < 60
        # the surviving prefix reads back intact
        ents = db.iterate_entries(1, 1, rs.first_index, avail + 1, 0)
        assert [e.cmd for e in ents] == [
            f"cmd-{i:04d}".encode()
            for i in range(rs.first_index, avail + 1)]
    finally:
        db.close()


def test_tan_tail_file_torn_truncation_still_default():
    mem = MemFS()
    _fill_tan("/tan", mem, n_entries=20, max_file_size=1 << 20)
    files = _tan_files("/tan", mem)
    assert len(files) == 1
    victim = os.path.join("/tan", files[0])
    with mem.open(victim, "r+b") as f:
        size = len(f.read())
        f.seek(size - 3)
        f.write(b"\xff")                 # torn tail: strict mode truncates
    db = TanLogDB("/tan", max_file_size=1 << 20, fs=mem)   # strict: opens
    try:
        assert db.quarantined == []
        rs = db.read_raft_state(1, 1, 0)
        assert rs is not None and rs.entry_count >= 1
    finally:
        db.close()


# -- acceptance: controlled storage crash -> restart() -> converged ----------


def test_storage_crash_restart_rejoins_converged():
    """ISSUE acceptance: a NodeHost whose CrashPointFS tripped mid-write
    controlled-crashes (fatal_error set, workers parked), then
    restart() reopens the SAME data dir in place and the replica rejoins
    and reconverges — proven by the monkey hash oracles."""
    c = _Cluster(seed=901, n=3)
    try:
        c.start()
        assert c.propose(b"seed=1", timeout=10.0)
        victim = 2
        c.fss[victim].arm(after_ops=3, torn=True)
        assert c._pump_until(
            lambda: c.hosts[victim].fatal_error is not None, timeout=15.0)
        assert c.hosts[victim]._stopped          # controlled crash, not hung
        assert c.live_rids() == [1, 3]
        assert c.propose(b"during=crash", timeout=10.0)   # quorum holds
        c.fss[victim].heal()
        c.hosts[victim].restart()
        c.epochs[victim] += 1
        c.reset_breakers()
        assert c.propose(b"after=restart", timeout=10.0)
        deadline = time.time() + 20
        while time.time() < deadline:
            js = c.journals()
            if len(js) == 3 and len({tuple(j) for j in js.values()}) == 1:
                break
            time.sleep(0.1)
        js = c.journals()
        assert len(js) == 3
        assert check_prefix_consistent(js).ok
        assert len({tuple(j) for j in js.values()}) == 1, {
            r: len(j) for r, j in js.items()}
        for kind in ("sm", "session", "membership"):
            hs = c.hashes(kind)
            assert len(set(hs.values())) == 1, (kind, hs)
    finally:
        c.close()


def test_restart_refuses_live_host():
    from dragonboat_tpu.chaos.runner import ChaosKV
    from dragonboat_tpu.config import Config, NodeHostConfig
    from dragonboat_tpu.nodehost import NodeHost
    from dragonboat_tpu.request import RequestError

    nh = NodeHost(NodeHostConfig(raft_address="live-1", rtt_millisecond=5))
    try:
        nh.start_replica({1: "live-1"}, False, ChaosKV,
                         Config(shard_id=1, replica_id=1, election_rtt=10,
                                heartbeat_rtt=1))
        with pytest.raises(RequestError):
            nh.restart()                 # only a stopped host restarts
    finally:
        nh.close()


# -- acceptance: corrupt non-tail log on disk -> snapshot re-replication -----


def test_corrupt_follower_log_requarantines_and_rejoins(tmp_path):
    """A follower's tan log corrupted mid-history (non-tail) under
    recovery_mode="quarantine" reopens, clamps, and is re-replicated
    back to the shard state — via leader snapshot when the lost suffix
    is already compacted away.  Real disk: the snapshot path checks
    os.path filepaths."""
    from dragonboat_tpu.chaos.runner import ChaosKV
    from dragonboat_tpu.config import Config, NodeHostConfig
    from dragonboat_tpu.logdb.sharded import ShardedLogDBFactory
    from dragonboat_tpu.nodehost import NodeHost

    addrs = {i: f"cq-{i}" for i in (1, 2, 3)}

    def mk(rid, mode="quarantine"):
        nh = NodeHost(NodeHostConfig(
            raft_address=addrs[rid], rtt_millisecond=5,
            node_host_dir=str(tmp_path),
            logdb_factory=ShardedLogDBFactory(
                str(tmp_path / f"db-{rid}"), num_shards=1,
                max_file_size=1024, recovery_mode=mode)))
        nh.start_replica(dict(addrs), False, ChaosKV, Config(
            shard_id=1, replica_id=rid, election_rtt=10, heartbeat_rtt=1,
            snapshot_entries=10, compaction_overhead=3))
        return nh

    def leader_of(hosts, timeout=10.0):
        deadline = time.time() + timeout
        while time.time() < deadline:
            for rid in sorted(hosts):
                lid, ok = hosts[rid].get_leader_id(1)
                if ok and lid in hosts:
                    return lid
            time.sleep(0.05)
        raise AssertionError("no leader elected")

    hosts = {rid: mk(rid) for rid in addrs}
    try:
        leader = leader_of(hosts)
        sess = hosts[leader].get_noop_session(1)
        for i in range(60):
            hosts[leader].sync_propose(sess, f"k{i}=v{i}".encode())
        victim = next(r for r in (1, 2, 3) if r != leader)
        # wait for the victim to have applied everything, then detach it
        deadline = time.time() + 10
        while time.time() < deadline and \
                hosts[victim].stale_read(1, "k59") != "v59":
            time.sleep(0.05)
        assert hosts[victim].stale_read(1, "k59") == "v59"
        hosts[victim].close()

        part = tmp_path / f"db-{victim}" / "part-00"
        tans = sorted(p for p in os.listdir(part) if p.endswith(".tan"))
        assert len(tans) >= 3
        vf = part / tans[len(tans) // 2]
        blob = bytearray(vf.read_bytes())
        blob[len(blob) // 2] ^= 0xFF     # corrupt a non-tail record
        vf.write_bytes(bytes(blob))

        # strict mode refuses the directory outright
        with pytest.raises(CorruptLogError):
            NodeHost(NodeHostConfig(
                raft_address=addrs[victim], rtt_millisecond=5,
                node_host_dir=str(tmp_path),
                logdb_factory=ShardedLogDBFactory(
                    str(tmp_path / f"db-{victim}"), num_shards=1,
                    max_file_size=1024, recovery_mode="strict")))

        # quarantine mode reopens and the shard heals the replica
        hosts[victim] = mk(victim)
        assert hosts[victim].logdb.quarantined
        # keep the shard moving so compaction passes the lost range
        for i in range(60, 75):
            h = hosts[leader_of(hosts)]
            h.sync_propose(h.get_noop_session(1), f"k{i}=v{i}".encode())
        deadline = time.time() + 30
        ok = False
        while time.time() < deadline and not ok:
            ok = all(hosts[victim].stale_read(1, f"k{i}") == f"v{i}"
                     for i in range(75))
            time.sleep(0.1)
        assert ok, "quarantined replica never reconverged"
        hs = {r: h.get_sm_hash(1) for r, h in hosts.items()}
        deadline = time.time() + 10
        while time.time() < deadline and len(set(hs.values())) != 1:
            time.sleep(0.1)
            hs = {r: h.get_sm_hash(1) for r, h in hosts.items()}
        assert len(set(hs.values())) == 1, hs
    finally:
        for h in hosts.values():
            try:
                h.close()
            except Exception:
                pass


# -- FaultPlan generator invariants ------------------------------------------


def test_faultplan_same_seed_same_bytes():
    for seed in range(30):
        a = FaultPlan.generate(seed).to_json()
        b = FaultPlan.generate(seed).to_json()
        assert a == b
        assert FaultPlan.from_json(a).to_json() == a


def test_faultplan_invariants_over_many_seeds():
    """Every generated schedule is recoverable by construction: at most
    one replica down at a time, every fault healed by the end, final
    step all-clear."""
    for seed in range(60):
        plan = FaultPlan.generate(seed)
        down = None
        open_soft = set()
        for ev in plan.events:
            if ev.kind in DOWN_KINDS:
                assert down is None, (seed, ev)
                down = (ev.target, ev.kind)
            elif ev.kind in ("restart_inplace", "restart_process",
                             "restore_partition"):
                assert down is not None and down[0] == ev.target \
                    and HEAL_FOR[down[1]] == ev.kind, (seed, ev)
                down = None
            elif ev.kind in ("drop", "delay", "duplicate", "reorder"):
                open_soft.add((ev.target, ev.kind))
            elif ev.kind == "heal_transport":
                open_soft = {(r, k) for r, k in open_soft
                             if r != ev.target}
        assert down is None, seed
        assert not open_soft, (seed, open_soft)


# -- oracle unit checks -------------------------------------------------------


def test_oracle_flags_divergence_and_loss():
    ok = check_prefix_consistent({1: [b"a", b"b"], 2: [b"a"]})
    assert ok.ok
    bad = check_prefix_consistent({1: [b"a", b"b"], 2: [b"a", b"X"]})
    assert not bad.ok and "diverge" in bad.failures[0]
    lost = check_no_acked_loss([b"a", b"z"], {1: [b"a"]})
    assert not lost.ok and "lost" in lost.failures[0]


def test_oracle_monotone_applied_respects_restart_epochs():
    # regression within one epoch: flagged
    bad = check_monotone_applied({1: [(0, 5), (0, 3)]})
    assert not bad.ok
    # a restart (epoch bump) legitimately replays from a lower index
    good = check_monotone_applied({1: [(0, 5), (1, 2), (1, 9)]})
    assert good.ok
