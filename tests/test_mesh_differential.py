"""Bitwise mesh differential: the ICI path must be the single-device
router path with rows living elsewhere (VERDICT round-2 item 8 — replaces
the liveness-only comparison).

The mesh layout permutes rows (block-major by replica slot,
parallel/ici.py docstring) and init seeds by row, so the two paths are
started from the SAME per-(group, replica) state: the mesh cluster's
initial state is pulled to the host, permuted into the router's
group-major layout, and both are driven step by step with identical
self-driving inputs.  After every step, every field of the mesh state —
permuted back to router layout — must equal the router state bit for bit
(the same lockstep discipline the kernel↔pycore oracle uses,
tests/test_kernel_differential.py)."""

import jax
import numpy as np
import pytest
from jax.sharding import Mesh

from dragonboat_tpu.core import params as KP
from dragonboat_tpu.core.kstate import empty_inbox
from dragonboat_tpu.core.router import cluster_step
from dragonboat_tpu.parallel.ici import (
    ici_cluster_step,
    ici_serve_step,
    make_ici_cluster,
    self_driving_input,
)


def _kp(replicas: int) -> KP.KernelParams:
    return KP.KernelParams(
        num_peers=replicas,
        log_cap=64,
        inbox_cap=5 * max(1, replicas - 1),
        msg_entries=4,
        proposal_cap=4,
        readindex_cap=4,
        apply_batch=16,
        compaction_overhead=16,
    )


def _mesh(g_size: int, replicas: int) -> Mesh:
    devs = jax.devices()
    need = g_size * replicas
    if len(devs) < need:
        pytest.skip(f"needs {need} devices")
    return Mesh(np.array(devs[:need]).reshape(g_size, replicas), ("g", "r"))


def _perm(g_size: int, replicas: int, n_local: int) -> np.ndarray:
    """perm[router_row] = mesh_row for the same (group, replica)."""
    N = g_size * n_local
    perm = np.empty(N * replicas, np.int64)
    for g in range(N):
        ig, n = divmod(g, n_local)
        for ir in range(replicas):
            perm[g * replicas + ir] = (ig * replicas + ir) * n_local + n
    return perm


def _pull(tree):
    return jax.tree.map(lambda x: np.asarray(x), tree)


def _permute(tree, perm):
    return jax.tree.map(lambda x: x[perm], tree)


def _assert_equal(tag, a, b):
    for f, xa, xb in zip(type(a)._fields, a, b):
        if xa is None and xb is None:
            continue
        assert np.array_equal(np.asarray(xa), np.asarray(xb)), (
            f"{tag}: field {f} diverged")


@pytest.mark.parametrize("g_size,replicas,n_local",
                         [(2, 3, 4), (2, 4, 2), (1, 5, 3)])
def test_ici_bitwise_matches_router(g_size, replicas, n_local):
    """Elections + replicated commits, field-equal at every step."""
    kp = _kp(replicas)
    mesh = _mesh(g_size, replicas)
    cluster, state_m, box_m = make_ici_cluster(
        kp, mesh, num_groups=g_size * n_local)
    perm = _perm(g_size, replicas, n_local)

    # identical starting state, router layout
    state_r = _permute(_pull(state_m), perm)
    box_r = _permute(_pull(box_m), perm)

    committed = 0
    for step_no in range(60):
        inp_m = self_driving_input(kp, state_m, tick=True, propose=True)
        inp_r = self_driving_input(
            kp, jax.tree.map(np.asarray, state_r), tick=True, propose=True)
        state_m, box_m, _ = ici_cluster_step(
            cluster, state_m, box_m, cluster.shard(inp_m))
        state_r, box_r, _ = cluster_step(kp, replicas, state_r, box_r, inp_r)
        pm = _permute(_pull(state_m), perm)
        _assert_equal(f"step {step_no} state", pm, _pull(state_r))
        _assert_equal(f"step {step_no} box",
                      _permute(_pull(box_m), perm), _pull(box_r))
        committed = int(np.asarray(state_r.committed).max())
    assert committed > 0, "differential ran but nothing committed"


def test_serve_step_with_open_mask_matches_router():
    """The serving-path body (host-staged input + persistent box + per-
    link cut mask) with an all-open mask is the router path bit for
    bit — state AND carried inbox."""
    g_size, replicas, n_local = 2, 3, 4
    kp = _kp(replicas)
    mesh = _mesh(g_size, replicas)
    cluster, state_m, box_m = make_ici_cluster(
        kp, mesh, num_groups=g_size * n_local)
    perm = _perm(g_size, replicas, n_local)
    state_r = _permute(_pull(state_m), perm)
    box_r = _permute(_pull(box_m), perm)
    cut = cluster.shard(
        np.zeros((cluster.total_rows, kp.num_peers), bool))

    for step_no in range(40):
        inp_m = self_driving_input(kp, state_m, tick=True, propose=True)
        inp_r = self_driving_input(
            kp, jax.tree.map(np.asarray, state_r), tick=True, propose=True)
        state_m, box_m, _ = ici_serve_step(
            cluster, state_m, box_m, cluster.shard(inp_m), cut)
        state_r, box_r, _ = cluster_step(kp, replicas, state_r, box_r, inp_r)
        _assert_equal(f"serve step {step_no}",
                      _permute(_pull(state_m), perm), _pull(state_r))
        _assert_equal(f"serve step {step_no} box",
                      _permute(_pull(box_m), perm), _pull(box_r))


def test_serve_step_cut_row_is_isolated():
    """A cut row's messages neither leave nor arrive: the rest of the
    cluster behaves exactly like a router run where that replica's
    traffic is dropped at the seam."""
    g_size, replicas, n_local = 2, 3, 2
    kp = _kp(replicas)
    mesh = _mesh(g_size, replicas)
    cluster, state_m, box_m = make_ici_cluster(
        kp, mesh, num_groups=g_size * n_local)
    perm = _perm(g_size, replicas, n_local)
    state_r = _permute(_pull(state_m), perm)
    box_r = _permute(_pull(box_m), perm)

    # cut replica 2 of group 0 (mesh row for (g=0, ir=1)): severing
    # every link of the row reproduces the whole-row partition
    cut_np = np.zeros((cluster.total_rows, kp.num_peers), bool)
    cut_mesh_row = _perm(g_size, replicas, n_local)[0 * replicas + 1]
    cut_np[cut_mesh_row, :] = True
    cut = cluster.shard(cut_np)
    cut_router_row = 0 * replicas + 1

    def drop_router(box):
        """Host-side equivalent of the device mask on the router box.
        The device path suppresses messages BEFORE routing, so dropped
        slots come out all-zero (route writes where(valid, ..., 0)) —
        zero every field, not just mtype."""
        frm = np.asarray(box.from_)
        drop = np.zeros_like(frm, dtype=bool)
        # nothing arrives at the cut row
        drop[cut_router_row, :] = True
        # nothing sent by the cut row arrives at its group peers
        g0 = slice(0, replicas)
        sender_rid = 1 + 1  # replica id of the cut row
        drop[g0] |= frm[g0] == sender_rid
        fields = {}
        for f, x in zip(type(box)._fields, box):
            if x is None:
                fields[f] = None
                continue
            x = np.asarray(x).copy()
            d = drop if x.ndim == drop.ndim else drop[..., None]
            x[np.broadcast_to(d, x.shape)] = 0
            fields[f] = x
        return type(box)(**fields)

    for step_no in range(40):
        inp_m = self_driving_input(kp, state_m, tick=True, propose=True)
        inp_r = self_driving_input(
            kp, jax.tree.map(np.asarray, state_r), tick=True, propose=True)
        state_m, box_m, _ = ici_serve_step(
            cluster, state_m, box_m, cluster.shard(inp_m), cut)
        state_r, box_r, _ = cluster_step(kp, replicas, state_r, box_r, inp_r)
        box_r = drop_router(jax.tree.map(np.asarray, box_r))
        _assert_equal(f"cut step {step_no}",
                      _permute(_pull(state_m), perm), _pull(state_r))
    # the un-cut majority of group 0 still elected and committed
    role = np.asarray(state_r.role).reshape(-1, replicas)
    assert (role[0] == KP.LEADER).sum() == 1
