"""KVLogDB (sorted-KV LSM engine): the same contract scenarios as
tests/test_tan.py — round-trips, crash recovery, conflict overwrite,
compaction — plus LSM-specific coverage (memtable flush, SST merge,
tombstone GC, torn-WAL truncation) and the sharded-kv geometry marker."""

import os
import struct

import pytest

from dragonboat_tpu import raftpb as pb
from dragonboat_tpu.logdb.kv import _WAL_HDR, CorruptKVError, OrderedKV
from dragonboat_tpu.logdb.kvdb import KVLogDB, KVLogDBFactory
from dragonboat_tpu.logdb.sharded import ShardedLogDB, ShardGeometryError


def _update(shard=1, replica=1, term=1, first=1, n=3, commit=0):
    ents = tuple(
        pb.Entry(term=term, index=first + i, cmd=f"e{first + i}".encode())
        for i in range(n)
    )
    return pb.Update(
        shard_id=shard, replica_id=replica,
        state=pb.State(term=term, vote=2, commit=commit),
        entries_to_save=ents,
    )


# ---------------------------------------------------------------------------
# OrderedKV engine
# ---------------------------------------------------------------------------


def test_kv_roundtrip_and_restart(tmp_path):
    kv = OrderedKV(str(tmp_path))
    kv.put(b"b", b"2")
    kv.put(b"a", b"1")
    kv.put(b"c", b"3")
    kv.delete(b"b")
    assert kv.get(b"a") == b"1" and kv.get(b"b") is None
    assert [k for k, _ in kv.scan(b"a", b"z")] == [b"a", b"c"]
    kv.close()
    kv2 = OrderedKV(str(tmp_path))
    assert kv2.get(b"a") == b"1" and kv2.get(b"b") is None
    assert [k for k, _ in kv2.scan(b"a", b"z")] == [b"a", b"c"]
    kv2.close()


def test_kv_flush_and_merge_newest_wins(tmp_path):
    kv = OrderedKV(str(tmp_path), memtable_bytes=64)  # force flushes
    for round_ in range(5):
        for i in range(16):
            kv.put(f"k{i:02d}".encode(), f"v{round_}".encode())
    vals = [v for _, v in kv.scan(b"k", b"l")]
    assert len(vals) == 16 and all(v == b"v4" for v in vals)
    ssts = [f for f in os.listdir(tmp_path) if f.endswith(".kv")]
    assert ssts, "memtable_bytes=64 must have flushed"
    kv.close()
    kv2 = OrderedKV(str(tmp_path))
    assert all(v == b"v4" for _, v in kv2.scan(b"k", b"l"))
    kv2.close()


def test_kv_compaction_drops_tombstones_and_filtered(tmp_path):
    dead: set[bytes] = set()
    kv = OrderedKV(str(tmp_path), memtable_bytes=64, max_ssts=2,
                   compaction_filter=lambda k: k in dead)
    for i in range(20):
        kv.put(f"k{i:02d}".encode(), b"x" * 8)
    kv.delete(b"k00")
    dead.add(b"k01")
    kv.compact()
    assert kv.get(b"k00") is None and kv.get(b"k01") is None
    assert kv.get(b"k02") == b"x" * 8
    ssts = [f for f in os.listdir(tmp_path) if f.endswith(".kv")]
    assert len(ssts) == 1, "full merge must leave one table"
    kv.close()


def test_kv_torn_wal_tail_truncated(tmp_path):
    kv = OrderedKV(str(tmp_path))
    kv.put(b"a", b"1")
    kv.put(b"b", b"2")
    kv._wal.close()                    # crash: no clean close, no flush
    wal = os.path.join(tmp_path, "wal")
    size = os.path.getsize(wal)
    with open(wal, "r+b") as f:
        f.truncate(size - 3)
    kv2 = OrderedKV(str(tmp_path))
    assert kv2.get(b"a") == b"1" and kv2.get(b"b") is None
    kv2.close()


def test_kv_mid_wal_corruption_refuses_open(tmp_path):
    kv = OrderedKV(str(tmp_path))
    kv.put(b"a", b"1")
    kv.put(b"b", b"2")
    kv._wal.close()                    # crash: the WAL still holds both
    wal = os.path.join(tmp_path, "wal")
    with open(wal, "r+b") as f:
        f.seek(_WAL_HDR.size + 2)      # payload of the FIRST record
        b = f.read(1)
        f.seek(_WAL_HDR.size + 2)
        f.write(bytes([b[0] ^ 0x10]))
    with pytest.raises(CorruptKVError):
        OrderedKV(str(tmp_path))


def test_kv_corrupt_sst_refuses_open(tmp_path):
    kv = OrderedKV(str(tmp_path))
    kv.put(b"a", b"1" * 64)
    kv.flush()
    kv.close()
    sst = [f for f in os.listdir(tmp_path) if f.endswith(".kv")][0]
    path = os.path.join(tmp_path, sst)
    with open(path, "r+b") as f:
        f.seek(20)
        b = f.read(1)
        f.seek(20)
        f.write(bytes([b[0] ^ 0x10]))
    with pytest.raises(CorruptKVError):
        OrderedKV(str(tmp_path))


def test_kv_unpublished_tmp_swept(tmp_path):
    kv = OrderedKV(str(tmp_path))
    kv.put(b"a", b"1")
    kv.close()
    tmp = os.path.join(tmp_path, "sst-99999999.kv.tmp")
    with open(tmp, "wb") as f:
        f.write(b"partial flush never renamed")
    kv2 = OrderedKV(str(tmp_path))
    assert not os.path.exists(tmp)
    assert kv2.get(b"a") == b"1"
    kv2.close()


# ---------------------------------------------------------------------------
# KVLogDB contract (mirrors tests/test_tan.py)
# ---------------------------------------------------------------------------


def test_save_and_iterate(tmp_path):
    db = KVLogDB(str(tmp_path))
    db.save_raft_state([_update(n=5)], worker_id=0)
    ents = db.iterate_entries(1, 1, 1, 6, 0)
    assert [e.index for e in ents] == [1, 2, 3, 4, 5]
    assert ents[2].cmd == b"e3"
    rs = db.read_raft_state(1, 1, 0)
    assert rs.state.vote == 2 and rs.first_index == 1 and rs.entry_count == 5
    db.close()


def test_restart_from_disk(tmp_path):
    db = KVLogDB(str(tmp_path))
    db.save_bootstrap_info(1, 1, pb.Bootstrap(addresses={1: "a", 2: "b"}))
    db.save_raft_state([_update(n=4, commit=2)], worker_id=0)
    db.save_raft_state([_update(term=2, first=5, n=2, commit=4)], worker_id=0)
    db.close()

    db2 = KVLogDB(str(tmp_path))
    ents = db2.iterate_entries(1, 1, 1, 7, 0)
    assert [e.index for e in ents] == [1, 2, 3, 4, 5, 6]
    assert ents[5].term == 2
    rs = db2.read_raft_state(1, 1, 0)
    assert rs.state.term == 2 and rs.state.commit == 4
    assert db2.get_bootstrap_info(1, 1).addresses == {1: "a", 2: "b"}
    assert db2.list_node_info() != []
    db2.close()


def test_conflict_overwrite_survives_restart(tmp_path):
    db = KVLogDB(str(tmp_path))
    db.save_raft_state([_update(term=1, first=1, n=5)], worker_id=0)
    # a new-term overwrite of the suffix from index 3: the watermark must
    # hide the stale 4 and 5 even though their keys still exist
    db.save_raft_state([_update(term=3, first=3, n=1)], worker_id=0)
    assert [e.term for e in db.iterate_entries(1, 1, 1, 10, 0)] == [1, 1, 3]
    assert db.read_raft_state(1, 1, 0).entry_count == 3
    db.close()
    db2 = KVLogDB(str(tmp_path))
    assert [e.term for e in db2.iterate_entries(1, 1, 1, 10, 0)] == [1, 1, 3]
    # and compaction physically drops them without changing reads
    db2.kv.compact()
    assert [e.term for e in db2.iterate_entries(1, 1, 1, 10, 0)] == [1, 1, 3]
    db2.close()


def test_remove_entries_floor_and_compaction(tmp_path):
    db = KVLogDB(str(tmp_path))
    for k in range(10):
        db.save_raft_state([_update(term=1, first=1 + 3 * k, n=3)], 0)
    db.remove_entries_to(1, 1, 27)
    assert db.iterate_entries(1, 1, 1, 31, 0) == []
    assert [e.index for e in db.iterate_entries(1, 1, 28, 31, 0)] == [28, 29, 30]
    db.compact_entries_to(1, 1, 27)
    assert [e.index for e in db.iterate_entries(1, 1, 28, 31, 0)] == [28, 29, 30]
    db.close()
    db2 = KVLogDB(str(tmp_path))  # floor survives restart
    assert db2.iterate_entries(1, 1, 1, 31, 0) == []
    assert [e.index for e in db2.iterate_entries(1, 1, 28, 31, 0)] == [28, 29, 30]
    db2.close()


def test_fsync_called(tmp_path, monkeypatch):
    calls = []
    real = os.fsync
    monkeypatch.setattr(os, "fsync", lambda fd: (calls.append(fd), real(fd)))
    db = KVLogDB(str(tmp_path))
    db.save_raft_state([_update()], worker_id=0)
    assert calls, "save_raft_state must fsync"
    db.close()


def test_remove_node_data(tmp_path):
    db = KVLogDB(str(tmp_path))
    db.save_raft_state([_update()], worker_id=0)
    db.save_raft_state([_update(shard=2, replica=1)], worker_id=0)
    db.remove_node_data(1, 1)
    assert db.read_raft_state(1, 1, 0) is None
    assert db.iterate_entries(1, 1, 1, 5, 0) == []
    assert db.read_raft_state(2, 1, 0) is not None  # neighbor untouched
    db.close()
    db2 = KVLogDB(str(tmp_path))
    assert db2.read_raft_state(1, 1, 0) is None
    assert db2.read_raft_state(2, 1, 0) is not None
    db2.close()


def test_import_snapshot_restart(tmp_path):
    db = KVLogDB(str(tmp_path))
    ss = pb.Snapshot(index=100, term=7, shard_id=1,
                     membership=pb.Membership(addresses={1: "a", 3: "c"}))
    db.import_snapshot(ss, 1)
    db.close()
    db2 = KVLogDB(str(tmp_path))
    got = db2.get_snapshot(1, 1)
    assert got.index == 100 and got.term == 7
    assert db2.read_raft_state(1, 1, 0).state.commit == 100
    assert db2.get_bootstrap_info(1, 1).addresses == {1: "a", 3: "c"}
    db2.close()


def test_factory(tmp_path):
    db = KVLogDBFactory(str(tmp_path)).create()
    assert db.name() == "kv"
    db.save_raft_state([_update()], worker_id=0)
    db.close()


# ---------------------------------------------------------------------------
# sharded-kv geometry
# ---------------------------------------------------------------------------


def test_sharded_kv_roundtrip_and_geometry(tmp_path):
    db = ShardedLogDB(str(tmp_path), num_shards=4, engine="kv")
    assert db.name() == "sharded-kv-4"
    for shard in (1, 2, 3, 7):
        db.save_raft_state([_update(shard=shard, n=3)], worker_id=0)
    db.close()
    # engine mismatch on reopen is refused
    with pytest.raises(ShardGeometryError):
        ShardedLogDB(str(tmp_path), num_shards=4, engine="tan")
    db2 = ShardedLogDB(str(tmp_path), num_shards=4, engine="kv")
    for shard in (1, 2, 3, 7):
        ents = db2.iterate_entries(shard, 1, 1, 4, 0)
        assert [e.index for e in ents] == [1, 2, 3]
    db2.close()


def test_sharded_legacy_marker_reads_as_tan(tmp_path):
    # a pre-engine marker (bare count) must open as tan and refuse kv
    os.makedirs(tmp_path / "db")
    with open(tmp_path / "db" / "TANSHARDS", "w") as f:
        f.write("4\n")
    db = ShardedLogDB(str(tmp_path / "db"), num_shards=4, engine="tan")
    db.close()
    with pytest.raises(ShardGeometryError):
        ShardedLogDB(str(tmp_path / "db"), num_shards=4, engine="kv")


# ---------------------------------------------------------------------------
# power-loss (MemFS.crash) and fault injection (ErrorFS) — the same
# storage-fault coverage the tan engine carries in tests/test_vfs.py
# ---------------------------------------------------------------------------


from dragonboat_tpu.vfs import ErrorFS, InjectedError, MemFS  # noqa: E402


def test_kvdb_on_memfs_crash_keeps_synced_state(tmp_path):
    fs = MemFS()
    db = KVLogDB(str(tmp_path), fs=fs)
    for k in range(1, 11):
        db.save_raft_state([_update(first=3 * k - 2, n=3, commit=3 * k)], 0)
    # an unsynced write vanishes at power loss and must not be visible
    db.kv.put(b"\x7funsynced", b"x", sync=False)
    fs.crash()

    db2 = KVLogDB(str(tmp_path), fs=fs)
    ents = db2.iterate_entries(1, 1, 1, 31, 0)
    assert [e.index for e in ents] == list(range(1, 31))
    assert db2.read_raft_state(1, 1, 0).state.commit == 30
    assert db2.kv.get(b"\x7funsynced") is None
    db2.close()


def test_kvdb_memfs_crash_after_flush_keeps_sst_data(tmp_path):
    fs = MemFS()
    db = KVLogDB(str(tmp_path), fs=fs, memtable_bytes=256)  # force flushes
    for k in range(1, 21):
        db.save_raft_state([_update(first=3 * k - 2, n=3)], 0)
    db.kv.flush()
    fs.crash()  # WAL is empty now; everything must come from SSTs

    db2 = KVLogDB(str(tmp_path), fs=fs)
    ents = db2.iterate_entries(1, 1, 1, 61, 0)
    assert [e.index for e in ents] == list(range(1, 61))
    db2.close()


def test_kvdb_errorfs_injects_on_fsync(tmp_path):
    fs = ErrorFS.on_op(MemFS(), "fsync")
    db = KVLogDB(str(tmp_path), fs=fs)
    with pytest.raises(InjectedError):
        db.save_raft_state([_update()], worker_id=0)


def test_kvdb_survives_injected_write_failure(tmp_path):
    base = MemFS()
    fs = ErrorFS(base)
    db = KVLogDB(str(tmp_path), fs=fs)
    for k in range(1, 6):
        db.save_raft_state([_update(first=3 * k - 2, n=3)], 0)
    armed = {"on": False}
    fs.inject = lambda op, path, a=armed: a["on"] and op in ("write", "fsync")
    armed["on"] = True
    with pytest.raises(InjectedError):
        db.save_raft_state([_update(first=16, n=3)], worker_id=0)
    armed["on"] = False
    # power loss on top of the fault: acked state only
    base.crash()
    db2 = KVLogDB(str(tmp_path), fs=base)
    ents = db2.iterate_entries(1, 1, 1, 100, 0)
    assert [e.index for e in ents] == list(range(1, 16))
    db2.close()


def test_kvdb_flush_failure_after_durable_batch(tmp_path):
    """A flush/compaction failure AFTER the WAL fsync must not roll the
    watermark back: the batch is durable, and a rolled-back watermark
    would make a later compaction drop the batch's own entries while
    the MAXINDEX point key survives (review r4 finding)."""
    from dragonboat_tpu.logdb.kv import FlushError

    base = MemFS()
    fs = ErrorFS(base)
    # tiny memtable: the failing save triggers a flush
    db = KVLogDB(str(tmp_path), fs=fs, memtable_bytes=512)
    db.save_raft_state([_update(first=1, n=3)], 0)
    # fail only SST writes — the WAL path stays healthy
    fs.inject = lambda op, path: ("sst-" in path
                                  and op in ("open", "write", "fsync"))
    with pytest.raises(FlushError):
        for k in range(2, 30):
            db.save_raft_state([_update(first=3 * k - 2, n=3)], 0)
    fs.inject = lambda op, path: False
    hi = max(db._maxidx.values())
    # every batch up to the recorded watermark is readable (memtable +
    # WAL hold them; the failed flush lost nothing)
    ents = db.iterate_entries(1, 1, 1, hi + 1, 0)
    assert [e.index for e in ents] == list(range(1, hi + 1))
    # power loss: WAL replay alone must reproduce the same state
    base.crash()
    db2 = KVLogDB(str(tmp_path), fs=base)
    ents = db2.iterate_entries(1, 1, 1, hi + 1, 0)
    assert [e.index for e in ents] == list(range(1, hi + 1))
    assert db2._maxidx[(1, 1)] == hi
    db2.close()
