"""TCP transport: framed wire protocol, E2E NodeHost cluster over localhost
sockets, snapshot chunk streaming (incl. follower catch-up via
InstallSnapshot), and a two-OS-process cluster."""

import os
import socket
import struct
import subprocess
import sys
import time

import pytest

from dragonboat_tpu import raftpb as pb
from dragonboat_tpu.config import Config, NodeHostConfig
from dragonboat_tpu.nodehost import NodeHost
from dragonboat_tpu.statemachine import IStateMachine, Result
from dragonboat_tpu.transport.tcp import (
    TCPTransportFactory,
    _decode_header,
    _encode_header,
    RAFT_TYPE,
)


def free_ports(n):
    """Allocate n distinct free ports (hold sockets until all are chosen)."""
    socks = []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


def free_port():
    return free_ports(1)[0]


class KV(IStateMachine):
    def __init__(self, *a):
        self.kv = {}

    def update(self, e):
        k, v = e.cmd.decode().split("=", 1)
        self.kv[k] = v
        return Result(value=len(self.kv))

    def lookup(self, q):
        return self.kv.get(q)

    def save_snapshot(self, w, files, done):
        d = "\n".join(f"{k}={v}" for k, v in sorted(self.kv.items())).encode()
        w.write(struct.pack("<I", len(d)))
        w.write(d)

    def recover_from_snapshot(self, r, files, done):
        (n,) = struct.unpack("<I", r.read(4))
        self.kv = dict(
            line.split("=", 1)
            for line in r.read(n).decode().split("\n") if line
        )


# -- wire-level unit tests ---------------------------------------------------


def test_header_roundtrip_and_corruption():
    payload = b"hello world"
    raw = _encode_header(RAFT_TYPE, payload)
    method, size, pcrc = _decode_header(raw)
    assert method == RAFT_TYPE and size == len(payload)
    bad = bytearray(raw)
    bad[3] ^= 0x01
    with pytest.raises(ValueError):
        _decode_header(bytes(bad))


def test_chunk_codec_roundtrip():
    m = pb.Message(type=pb.MessageType.INSTALL_SNAPSHOT, to=2, from_=1,
                   shard_id=9, term=4,
                   snapshot=pb.Snapshot(index=10, term=4, filepath="/x"))
    c = pb.Chunk(shard_id=9, replica_id=2, from_=1, chunk_id=0, chunk_count=3,
                 chunk_size=5, file_size=15, index=10, term=4,
                 deployment_id=7, data=b"abcde", message=m)
    wire = pb.encode_chunk(c)
    rt = pb.decode_chunk(wire)
    assert rt.data == b"abcde" and rt.chunk_count == 3
    assert rt.message.snapshot.index == 10
    bad = bytearray(wire)
    bad[10] ^= 0x80
    with pytest.raises(ValueError):
        pb.decode_chunk(bytes(bad))


# -- in-process cluster over real sockets ------------------------------------


def _tcp_cluster(n=3, snapshot_entries=0, wire="native"):
    ports = free_ports(n)
    addrs = {i: f"127.0.0.1:{ports[i - 1]}" for i in range(1, n + 1)}
    hosts = {}
    for rid, addr in addrs.items():
        nh = NodeHost(NodeHostConfig(
            raft_address=addr, rtt_millisecond=5,
            transport_factory=TCPTransportFactory(wire=wire)))
        cfg = Config(shard_id=1, replica_id=rid, election_rtt=10,
                     heartbeat_rtt=1, snapshot_entries=snapshot_entries,
                     compaction_overhead=2)
        nh.start_replica(addrs, False, KV, cfg)
        hosts[rid] = nh
    return hosts


def _leader(hosts, timeout=10.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        votes = {}
        for nh in hosts.values():
            lid, ok = nh.get_leader_id(1)
            if ok:
                votes[lid] = votes.get(lid, 0) + 1
        for lid, cnt in votes.items():
            if cnt > len(hosts) // 2 and lid in hosts:
                return lid
        time.sleep(0.02)
    raise AssertionError("no leader over tcp")


def test_tcp_cluster_propose_and_read():
    hosts = _tcp_cluster()
    try:
        lid = _leader(hosts)
        nh = hosts[lid]
        s = nh.get_noop_session(1)
        assert nh.sync_propose(s, b"net=tcp").value == 1
        assert nh.sync_read(1, "net") == "tcp"
        # all replicas converge
        deadline = time.time() + 5
        while time.time() < deadline:
            if all(h.stale_read(1, "net") == "tcp" for h in hosts.values()):
                break
            time.sleep(0.02)
        assert all(h.stale_read(1, "net") == "tcp" for h in hosts.values())
    finally:
        for h in hosts.values():
            h.close()


@pytest.mark.parametrize("wire", ["native", "go"])
def test_tcp_snapshot_chunk_catchup(wire):
    """A stopped replica falls behind a compacted log; on restart the leader
    must stream an InstallSnapshot via the chunk path over TCP — on the
    native wire AND the reference byte format (method-200 requests
    carrying gogo-marshaled Chunks, split per file, message synthesized
    receiver-side: the in-band heal a mixed Go/TPU shard relies on)."""
    hosts = _tcp_cluster(snapshot_entries=6, wire=wire)
    stopped_cfg = None
    try:
        lid = _leader(hosts)
        nh = hosts[lid]
        lagger = next(r for r in hosts if r != lid)
        # take the lagger offline (simulate machine loss)
        hosts[lagger].close()
        stopped = hosts.pop(lagger)
        s = nh.get_noop_session(1)
        for i in range(30):  # drives auto-snapshot + compaction past lagger
            nh.sync_propose(s, f"k{i}=v{i}".encode())
        # bring a fresh replica back at the same address with empty state
        # (bind may need a beat while the old listener's threads unwind)
        addr = stopped.config.raft_address
        nh2 = None
        for attempt in range(50):
            try:
                nh2 = NodeHost(NodeHostConfig(
                    raft_address=addr, rtt_millisecond=5,
                    transport_factory=TCPTransportFactory(wire=wire)))
                break
            except OSError:
                time.sleep(0.1)
        assert nh2 is not None, "could not rebind the stopped replica's port"
        addrs = {r: h.config.raft_address for r, h in hosts.items()}
        addrs[lagger] = addr
        nh2.start_replica(addrs, False, KV, Config(
            shard_id=1, replica_id=lagger, election_rtt=10, heartbeat_rtt=1,
            snapshot_entries=6, compaction_overhead=2))
        hosts[lagger] = nh2
        deadline = time.time() + 15
        while time.time() < deadline:
            if nh2.stale_read(1, "k29") == "v29":
                break
            time.sleep(0.05)
        assert nh2.stale_read(1, "k29") == "v29", \
            "lagging replica never caught up via snapshot streaming"
        assert nh2.stale_read(1, "k0") == "v0"
    finally:
        for h in hosts.values():
            h.close()


# -- two OS processes --------------------------------------------------------

_WORKER = r"""
import sys, time, struct
sys.path.insert(0, {repo!r})
from dragonboat_tpu.config import Config, NodeHostConfig
from dragonboat_tpu.nodehost import NodeHost
from dragonboat_tpu.statemachine import IStateMachine, Result
from dragonboat_tpu.transport.tcp import TCPTransportFactory

class KV(IStateMachine):
    def __init__(self, *a): self.kv = {{}}
    def update(self, e):
        k, v = e.cmd.decode().split("=", 1); self.kv[k] = v
        return Result(value=len(self.kv))
    def lookup(self, q): return self.kv.get(q)
    def save_snapshot(self, w, files, done):
        d = "\n".join(f"{{k}}={{v}}" for k, v in sorted(self.kv.items())).encode()
        w.write(struct.pack("<I", len(d))); w.write(d)
    def recover_from_snapshot(self, r, files, done):
        (n,) = struct.unpack("<I", r.read(4))
        self.kv = dict(l.split("=", 1) for l in r.read(n).decode().split("\n") if l)

addrs = {addrs!r}
rid = {rid}
nh = NodeHost(NodeHostConfig(raft_address=addrs[rid], rtt_millisecond=5,
                                                          transport_factory=TCPTransportFactory()))
nh.start_replica(addrs, False, KV,
                 Config(shard_id=1, replica_id=rid, election_rtt=10,
                        heartbeat_rtt=1))
print("READY", flush=True)
deadline = time.time() + 60
while time.time() < deadline:
    if nh.stale_read(1, "cross") == "process":
        print("GOT-IT", flush=True)
        break
    time.sleep(0.05)
nh.close()
"""


def test_two_os_processes():
    # under full-suite load, port reuse between free_ports() probing and
    # the actual binds can race with other tests' ephemeral sockets —
    # retry the whole scenario with fresh ports
    last = None
    for _ in range(3):
        try:
            _run_two_os_processes()
            return
        except AssertionError as e:
            last = e
    raise last


def _run_two_os_processes():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    p1, p2, p3 = free_ports(3)
    addrs = {1: f"127.0.0.1:{p1}", 2: f"127.0.0.1:{p2}",
             3: f"127.0.0.1:{p3}"}
    env = dict(os.environ, PYTHONPATH="", JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [sys.executable, "-c", _WORKER.format(repo=repo, addrs=addrs, rid=3)],
        stdout=subprocess.PIPE, text=True, env=env,
    )
    hosts = {}
    try:
        assert proc.stdout.readline().strip() == "READY"
        for rid in (1, 2):
            nh = NodeHost(NodeHostConfig(
                raft_address=addrs[rid], rtt_millisecond=5,
                                transport_factory=TCPTransportFactory()))
            nh.start_replica(addrs, False, KV, Config(
                shard_id=1, replica_id=rid, election_rtt=10, heartbeat_rtt=1))
            hosts[rid] = nh
        lid = _leader(hosts, timeout=60)
        s = hosts[lid].get_noop_session(1)
        hosts[lid].sync_propose(s, b"cross=process")
        assert hosts[lid].sync_read(1, "cross") == "process"
        # the out-of-process replica observed the write
        line = proc.stdout.readline().strip()
        assert line == "GOT-IT", f"worker never saw the write: {line!r}"
    finally:
        for h in hosts.values():
            h.close()
        proc.terminate()
        proc.wait(timeout=10)


# -- mutual TLS + listen address ---------------------------------------------


def _make_certs(d):
    """CA + one shared node certificate, via the openssl CLI."""
    import subprocess as sp

    ca_key, ca_crt = f"{d}/ca.key", f"{d}/ca.crt"
    key, csr, crt = f"{d}/node.key", f"{d}/node.csr", f"{d}/node.crt"
    sp.run(["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
            "-keyout", ca_key, "-out", ca_crt, "-days", "1",
            "-subj", "/CN=test-ca"], check=True, capture_output=True)
    sp.run(["openssl", "req", "-newkey", "rsa:2048", "-nodes",
            "-keyout", key, "-out", csr, "-subj", "/CN=node",
            "-addext", "subjectAltName=IP:127.0.0.1,DNS:localhost"],
           check=True, capture_output=True)
    # -copy_extensions needs openssl 3; an -extfile with the same SAN
    # works on 1.1 and 3 alike
    extfile = f"{d}/san.cnf"
    with open(extfile, "w") as f:
        f.write("subjectAltName=IP:127.0.0.1,DNS:localhost\n")
    sp.run(["openssl", "x509", "-req", "-in", csr, "-CA", ca_crt,
            "-CAkey", ca_key, "-CAcreateserial", "-out", crt, "-days", "1",
            "-extfile", extfile],
           check=True, capture_output=True)
    return ca_crt, crt, key


def test_mutual_tls_cluster(tmp_path):
    ca, crt, key = _make_certs(str(tmp_path))
    ports = free_ports(3)
    addrs = {i: f"127.0.0.1:{p}" for i, p in enumerate(ports, 1)}
    hosts = {}
    try:
        for rid, addr in addrs.items():
            nh = NodeHost(NodeHostConfig(
                raft_address=addr, rtt_millisecond=5,
                mutual_tls=True, ca_file=ca, cert_file=crt, key_file=key,
                transport_factory=TCPTransportFactory()))
            nh.start_replica(addrs, False, KV, Config(
                shard_id=1, replica_id=rid, election_rtt=10,
                heartbeat_rtt=1))
            hosts[rid] = nh
        lid = _leader(hosts, timeout=30)
        s = hosts[lid].get_noop_session(1)
        hosts[lid].sync_propose(s, b"secure=yes", timeout_s=10)
        assert hosts[lid].sync_read(1, "secure", timeout_s=10) == "yes"
    finally:
        for h in hosts.values():
            h.close()


def test_plaintext_peer_rejected_by_tls_listener(tmp_path):
    """A non-TLS client cannot feed frames into a mutual-TLS listener."""
    import socket as sk

    ca, crt, key = _make_certs(str(tmp_path))
    (port,) = free_ports(1)
    addr = f"127.0.0.1:{port}"
    nh = NodeHost(NodeHostConfig(
        raft_address=addr, rtt_millisecond=5,
        mutual_tls=True, ca_file=ca, cert_file=crt, key_file=key,
        transport_factory=TCPTransportFactory()))
    nh.start_replica({1: addr}, False, KV, Config(
        shard_id=1, replica_id=1, election_rtt=10, heartbeat_rtt=1))
    try:
        c = sk.create_connection(("127.0.0.1", port), timeout=3)
        c.sendall(b"\x00" * 64)  # not a TLS handshake
        c.settimeout(3)
        try:
            data = c.recv(64)   # server should drop us
            assert data == b""
        except OSError:
            pass
        c.close()
    finally:
        nh.close()


def test_listen_address_differs_from_raft_address():
    """The LISTENER binds listen_address, not the advertised raft_address
    (config.go ListenAddress semantics) — checked directly by connecting
    to both ports."""
    import socket as sk

    p_advertised, p_listen = free_ports(2)
    nh = NodeHost(NodeHostConfig(
        raft_address=f"127.0.0.1:{p_advertised}",
        listen_address=f"127.0.0.1:{p_listen}",
        rtt_millisecond=5, transport_factory=TCPTransportFactory()))
    nh.start_replica({1: f"127.0.0.1:{p_advertised}"}, False, KV, Config(
        shard_id=1, replica_id=1, election_rtt=10, heartbeat_rtt=1))
    try:
        # the listen port accepts
        c = sk.create_connection(("127.0.0.1", p_listen), timeout=3)
        c.close()
        # the advertised (unbound) port refuses
        import pytest as _pytest

        with _pytest.raises(OSError):
            sk.create_connection(("127.0.0.1", p_advertised), timeout=1)
    finally:
        nh.close()


# ---------------------------------------------------------------------------
# go-wire mode: a live cluster speaking the reference's exact byte format
# (magic preamble + 18-byte crc'd header + gogo-protobuf MessageBatch)
# ---------------------------------------------------------------------------


def test_cluster_over_go_wire():
    """Three NodeHosts exchange ALL raft traffic framed byte-for-byte the
    way the reference frames it (tcp.go:43,64-110 + raft_optimized.go
    marshaling via raftpb/gowire.py): elect, replicate, commit, read.
    The codec itself is fixture-proven in tests/test_gowire.py; this
    proves it drives a real cluster end-to-end over real sockets."""
    ports = free_ports(3)
    addrs = {i: f"127.0.0.1:{ports[i - 1]}" for i in range(1, 4)}
    hosts = {}
    for rid, addr in addrs.items():
        nh = NodeHost(NodeHostConfig(
            raft_address=addr, rtt_millisecond=5,
            transport_factory=TCPTransportFactory(wire="go")))
        assert nh.transport.name() == "go-tcp-transport"
        cfg = Config(shard_id=1, replica_id=rid, election_rtt=10,
                     heartbeat_rtt=1)
        nh.start_replica(addrs, False, KV, cfg)
        hosts[rid] = nh
    try:
        lid = _leader(hosts)
        s = hosts[lid].get_noop_session(1)
        assert hosts[lid].sync_propose(s, b"wire=go").value == 1
        hosts[lid].sync_propose(s, b"k=v")
        # linearizable read through a follower host exercises the
        # ReadIndex round over the go wire too
        fid = next(i for i in hosts if i != lid)
        assert hosts[fid].sync_read(1, "k") == "v"
    finally:
        for nh in hosts.values():
            nh.close()


def test_go_chunk_split_and_reassemble(tmp_path):
    """split_snapshot_message_go -> GoChunkSink: the reassembled
    container + external files are byte-identical and the synthesized
    InstallSnapshot (chunk.go toMessage parity) carries the snapshot
    metadata — with NO embedded message on the wire."""
    from dragonboat_tpu.raftpb import gowire
    from dragonboat_tpu.transport.chunks import (
        GoChunkSink,
        split_snapshot_message_go,
    )

    import io

    from dragonboat_tpu.rsm.snapshotio import read_snapshot, write_snapshot

    main = tmp_path / "snap.gbsnap"
    user_payload = b"M" * (3 * 1024) + b"main-tail"
    buf = io.BytesIO()
    write_snapshot(buf, b"", lambda w: w.write(user_payload))
    main.write_bytes(buf.getvalue())
    xf1 = tmp_path / "ext1.bin"
    xf1.write_bytes(b"X" * 2048)
    xf2 = tmp_path / "ext2.bin"
    xf2.write_bytes(b"Y" * 10)
    ss = pb.Snapshot(
        filepath=str(main), file_size=main.stat().st_size, index=42, term=7,
        membership=pb.Membership(config_change_id=3,
                                 addresses={1: "a:1", 2: "b:2"}),
        files=(pb.SnapshotFile(file_id=1, filepath=str(xf1),
                               file_size=xf1.stat().st_size),
               pb.SnapshotFile(file_id=2, filepath=str(xf2),
                               file_size=xf2.stat().st_size)),
        shard_id=9, on_disk_index=42)
    m = pb.Message(type=pb.MessageType.INSTALL_SNAPSHOT, to=2, from_=1,
                   shard_id=9, term=7, snapshot=ss)
    chunks = list(split_snapshot_message_go(m, deployment_id=5,
                                            chunk_size=1024))
    # per-file split: N main (reference-container transcoded) + 2 + 1
    # external chunks, global ids contiguous
    assert [c.chunk_id for c in chunks] == list(range(len(chunks)))
    assert all(c.chunk_count == len(chunks) for c in chunks)
    assert chunks[0].has_file_info is False
    assert chunks[-1].has_file_info and chunks[-1].file_info.file_id == 2
    # every chunk survives the reference byte format
    chunks = [gowire.decode_chunk(gowire.encode_chunk(c)) for c in chunks]

    delivered = []
    sink = GoChunkSink(str(tmp_path / "in"), deployment_id=5,
                       deliver=lambda msg, src: delivered.append(msg))
    for c in chunks:
        assert sink.add(c), c.chunk_id
    assert len(delivered) == 1
    got = delivered[0]
    assert got.type == pb.MessageType.INSTALL_SNAPSHOT
    assert (got.shard_id, got.to, got.from_) == (9, 2, 1)
    gss = got.snapshot
    assert gss.index == 42 and gss.term == 7 and gss.on_disk_index == 42
    assert gss.membership.addresses == {1: "a:1", 2: "b:2"}
    # the delivered main image is naturalized back to our container:
    # byte layout differs (sessions re-banked through the go format),
    # the recovered content must not
    session_bytes, reader = read_snapshot(open(gss.filepath, "rb"))
    assert b"".join(iter(lambda: reader.read(1 << 20), b"")) == user_payload
    assert len(gss.files) == 2
    assert open(gss.files[0].filepath, "rb").read() == xf1.read_bytes()
    assert open(gss.files[1].filepath, "rb").read() == xf2.read_bytes()


def test_go_chunk_sink_rejects(tmp_path):
    """Ordering and deployment gates (chunk.go validate): wrong
    deployment, out-of-order, and mid-stream restart are refused."""
    from dragonboat_tpu.transport.chunks import (
        GoChunkSink,
        split_snapshot_message_go,
    )

    import io

    from dragonboat_tpu.rsm.snapshotio import write_snapshot

    main = tmp_path / "s.gbsnap"
    buf = io.BytesIO()
    write_snapshot(buf, b"", lambda w: w.write(b"z" * 4096))
    main.write_bytes(buf.getvalue())
    m = pb.Message(type=pb.MessageType.INSTALL_SNAPSHOT, to=2, from_=1,
                   shard_id=3, term=2,
                   snapshot=pb.Snapshot(filepath=str(main),
                                        file_size=main.stat().st_size,
                                        index=10, term=2, shard_id=3))
    chunks = list(split_snapshot_message_go(m, deployment_id=1,
                                            chunk_size=1024))
    assert len(chunks) >= 4
    sink = GoChunkSink(str(tmp_path / "in"), deployment_id=1,
                       deliver=lambda *a: None)
    import dataclasses as dc

    assert not sink.add(dc.replace(chunks[0], deployment_id=9))
    assert sink.add(chunks[0])
    assert not sink.add(chunks[2])        # skipped chunk 1: abort
    assert sink.inflight() == 0           # transfer dropped
    # a fresh ordered stream completes
    done = []
    sink2 = GoChunkSink(str(tmp_path / "in2"), deployment_id=1,
                        deliver=lambda msg, src: done.append(msg))
    for c in chunks:
        assert sink2.add(c)
    assert len(done) == 1


def test_tcp_ondisk_live_stream_go_wire(monkeypatch):
    """On-disk SM live stream over the reference byte format: the
    native ChunkWriter stream is transcoded IN FLIGHT into the
    reference container (hub adapt_native_chunks_to_go ->
    GoStreamTranscoder) and reassembled by the go-wire sink's
    streamed-tail rules, then naturalized back — the second interop
    shape (chunkwriter.go LastChunkCount streams) after the file-based
    catchup above."""
    from dragonboat_tpu.rsm.statemachine import StateMachine
    from test_snapshot_stream import DiskKV

    calls = {"n": 0}
    orig = StateMachine.stream_snapshot

    def counting(self, w, on_meta=None):
        calls["n"] += 1
        return orig(self, w, on_meta=on_meta)

    monkeypatch.setattr(StateMachine, "stream_snapshot", counting)

    ports = free_ports(3)
    addrs = {i: f"127.0.0.1:{ports[i - 1]}" for i in (1, 2, 3)}
    hosts = {}
    for rid, addr in addrs.items():
        nh = NodeHost(NodeHostConfig(
            raft_address=addr, rtt_millisecond=5,
            transport_factory=TCPTransportFactory(wire="go")))
        nh.start_replica(addrs, False, DiskKV, Config(
            shard_id=1, replica_id=rid, election_rtt=10, heartbeat_rtt=1,
            snapshot_entries=6, compaction_overhead=2))
        hosts[rid] = nh
    try:
        lid = _leader(hosts)
        lagger = next(r for r in hosts if r != lid)
        hosts[lagger].close()
        stopped = hosts.pop(lagger)
        s = hosts[lid].get_noop_session(1)
        for i in range(30):
            hosts[lid].sync_propose(s, f"d{i}=v{i}".encode())
        addr = stopped.config.raft_address
        nh2 = None
        for _ in range(50):
            try:
                nh2 = NodeHost(NodeHostConfig(
                    raft_address=addr, rtt_millisecond=5,
                    transport_factory=TCPTransportFactory(wire="go")))
                break
            except OSError:
                time.sleep(0.1)
        assert nh2 is not None
        a2 = {r: h.config.raft_address for r, h in hosts.items()}
        a2[lagger] = addr
        nh2.start_replica(a2, False, DiskKV, Config(
            shard_id=1, replica_id=lagger, election_rtt=10, heartbeat_rtt=1,
            snapshot_entries=6, compaction_overhead=2))
        hosts[lagger] = nh2
        deadline = time.time() + 20
        while time.time() < deadline and nh2.stale_read(1, "d29") != "v29":
            time.sleep(0.05)
        assert nh2.stale_read(1, "d29") == "v29", \
            "on-disk lagger never caught up over the go-wire live stream"
        assert calls["n"] >= 1, "the live-stream path was never exercised"
    finally:
        for h in hosts.values():
            h.close()


def test_go_witness_chunk_roundtrip(tmp_path):
    """Witness InstallSnapshot over the go wire (snapshot.go:262
    getWitnessChunk): one synthetic chunk, witness=True end to end, the
    receiver synthesizes a witness InstallSnapshot (bookkeeping-only
    recovery — the image bytes are never parsed)."""
    from dragonboat_tpu.raftpb import gowire
    from dragonboat_tpu.transport.chunks import (
        GoChunkSink,
        split_snapshot_message_go,
        witness_image_bytes,
    )

    m = pb.Message(
        type=pb.MessageType.INSTALL_SNAPSHOT, to=3, from_=1, shard_id=7,
        term=4,
        snapshot=pb.Snapshot(index=20, term=4, shard_id=7, witness=True,
                             membership=pb.Membership(
                                 config_change_id=2,
                                 addresses={1: "a:1", 2: "b:2"},
                                 witnesses={3: "w:3"})))
    chunks = list(split_snapshot_message_go(m, deployment_id=9))
    assert len(chunks) == 1
    c = chunks[0]
    assert c.witness and c.chunk_count == 1 and c.is_last()
    assert c.filepath == "witness.snapshot"
    assert c.data == witness_image_bytes() and c.file_size == len(c.data)
    # survives the reference byte format
    c = gowire.decode_chunk(gowire.encode_chunk(c))
    assert c.witness and c.bin_ver == gowire.TRANSPORT_BIN_VERSION

    got = []
    sink = GoChunkSink(str(tmp_path / "in"), deployment_id=9,
                       deliver=lambda msg, src: got.append(msg))
    assert sink.add(c)
    assert len(got) == 1
    gm = got[0]
    assert gm.snapshot.witness and gm.snapshot.index == 20
    assert gm.snapshot.membership.witnesses == {3: "w:3"}
    assert (gm.to, gm.from_, gm.shard_id) == (3, 1, 7)


def test_witness_image_passes_reference_validator():
    """The witness chunk payload must survive the EXACT validation a Go
    receiver runs on chunk-0 (chunk.go:214 -> rwv.go v2validator):
    1024-byte SnapshotHeader region, CRC'd blocks, magic'd tail —
    validate_v2 reimplements that algorithm from the reference source."""
    import struct
    import zlib

    from dragonboat_tpu.rsm import gosnapshot as gs

    img = gs.witness_image()
    assert len(img) >= gs.HEADER_SIZE
    assert gs.validate_v2(img)
    # header region parses: u64 LE length then a protobuf whose
    # unconditional fields land at the reference's tag bytes
    (hlen,) = struct.unpack_from("<Q", img, 0)
    assert 0 < hlen <= gs.HEADER_SIZE - 8
    hdr = img[8:8 + hlen]
    assert hdr[0] == 0x08                  # field 1 varint (session_size)
    # payload is the empty LRU session bank: 4096 max, 0 sessions
    body = img[gs.HEADER_SIZE:-gs.TAIL_SIZE]
    payload, crc = body[:-4], body[-4:]
    assert payload == struct.pack("<QQ", 4096, 0)
    assert crc == struct.pack("<I", zlib.crc32(payload))
    # corruption is caught by the same validator
    bad = bytearray(img)
    bad[gs.HEADER_SIZE + 3] ^= 0xFF
    assert not gs.validate_v2(bytes(bad))
    assert not gs.validate_v2(img[:-1])


def test_go_image_transcode_roundtrip():
    """Our container -> reference container -> ours: sessions (dedup
    state included) and the user payload survive the fleet boundary,
    and the intermediate bytes pass the reference validator."""
    import io

    from dragonboat_tpu.rsm import gosnapshot as gs
    from dragonboat_tpu.rsm.session import LRUSession, Session
    from dragonboat_tpu.rsm.snapshotio import read_snapshot, write_snapshot
    from dragonboat_tpu.statemachine import Result

    lru = LRUSession()
    s1 = Session(client_id=7, responded_to=3)
    s1.history[4] = Result(value=40, data=b"resp-4")
    s1.history[5] = Result(value=50, data=b"")
    lru.sessions[7] = s1
    lru.sessions[9] = Session(client_id=9, responded_to=0)
    sbuf = io.BytesIO()
    lru.save(sbuf)
    payload = b"user-sm-bytes " * 300
    out = io.BytesIO()
    write_snapshot(out, sbuf.getvalue(), lambda w: w.write(payload))
    native = out.getvalue()

    go_img = gs.native_image_to_go(native)
    assert gs.validate_v2(go_img)          # a Go receiver accepts it
    # the Go payload stream = go session bank + verbatim user bytes
    stream = gs.read_v2(go_img)
    sessions, consumed = gs.go_session_bank_decode(stream)
    assert stream[consumed:] == payload
    assert {c for c, _, _ in sessions} == {7, 9}

    back = gs.go_image_to_native(go_img)
    session_bytes, reader = read_snapshot(io.BytesIO(back))
    got = LRUSession.load(io.BytesIO(session_bytes))
    assert got.sessions[7].responded_to == 3
    assert got.sessions[7].history[4].value == 40
    assert got.sessions[7].history[4].data == b"resp-4"
    assert got.sessions[9].client_id == 9
    assert b"".join(iter(lambda: reader.read(1 << 20), b"")) == payload
