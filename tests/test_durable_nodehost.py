"""Durable-mode NodeHost: tan-backed data dirs, locking, flag files, and
real restart/crash recovery (the round-1 restart test reused the same
in-memory LogDB object — these rebuild everything from the files).

Reference behaviors: environment.go (LOCK, dragonboat.ds, identity),
tan/db.go (durability), nodehost_test.go restart scenarios.
"""

import os
import subprocess
import sys
import time

import pytest

from dragonboat_tpu.config import Config, NodeHostConfig
from dragonboat_tpu.nodehost import NodeHost
from dragonboat_tpu.server.env import (
    DirLockedError,
    Env,
    IncompatibleDataError,
    NotOwnerError,
)

from test_nodehost import KVStateMachine, wait_leader


def make_hosts(base_dir, n=3, prefix="dur", snapshot_entries=0):
    addrs = {i: f"{prefix}-{i}" for i in range(1, n + 1)}
    hosts = {}
    for rid, addr in addrs.items():
        nh = NodeHost(NodeHostConfig(
            raft_address=addr, rtt_millisecond=5,
            node_host_dir=str(base_dir)))
        cfg = Config(shard_id=1, replica_id=rid, election_rtt=10,
                     heartbeat_rtt=1, snapshot_entries=snapshot_entries,
                     compaction_overhead=5)
        nh.start_replica(addrs, False, KVStateMachine, cfg)
        hosts[rid] = nh
    return hosts, addrs


def test_tan_is_default_with_node_host_dir(tmp_path):
    nh = NodeHost(NodeHostConfig(raft_address="t-1", rtt_millisecond=5,
                                 node_host_dir=str(tmp_path)))
    try:
        # the default engine is tan, sharded into single-writer
        # partitions (logdb/sharded.py; internal/logdb/sharded.go:34)
        assert nh.logdb.name().startswith("sharded-tan")
        assert nh.env is not None
        assert os.path.exists(os.path.join(nh.env.root, "LOCK"))
        assert os.path.exists(os.path.join(nh.env.root, "dragonboat.ds"))
    finally:
        nh.close()


def test_nodehost_id_persists(tmp_path):
    nh = NodeHost(NodeHostConfig(raft_address="t-1", rtt_millisecond=5,
                                 node_host_dir=str(tmp_path)))
    nhid = nh.id
    nh.close()
    nh2 = NodeHost(NodeHostConfig(raft_address="t-1", rtt_millisecond=5,
                                  node_host_dir=str(tmp_path)))
    try:
        assert nh2.id == nhid
    finally:
        nh2.close()


def test_dir_lock_excludes_second_host(tmp_path):
    nh = NodeHost(NodeHostConfig(raft_address="t-1", rtt_millisecond=5,
                                 node_host_dir=str(tmp_path)))
    try:
        with pytest.raises(DirLockedError):
            NodeHost(NodeHostConfig(raft_address="t-1", rtt_millisecond=5,
                                    node_host_dir=str(tmp_path)))
    finally:
        nh.close()
    # after release the dir opens fine
    nh2 = NodeHost(NodeHostConfig(raft_address="t-1", rtt_millisecond=5,
                                  node_host_dir=str(tmp_path)))
    nh2.close()


def test_flag_file_pins_owner_and_settings(tmp_path):
    env = Env(str(tmp_path), "addr-1", deployment_id=7)
    env.check_node_host_dir("tan")
    # same address reopens fine
    Env(str(tmp_path), "addr-1", deployment_id=7).check_node_host_dir("tan")
    # a different deployment id in the same subdir is a different tree —
    # simulate corruption by rewriting the flag in place instead
    import json
    fp = os.path.join(env.root, "dragonboat.ds")
    saved = json.load(open(fp))
    saved["address"] = "someone-else"
    json.dump(saved, open(fp, "w"))
    with pytest.raises(NotOwnerError):
        Env(str(tmp_path), "addr-1", deployment_id=7).check_node_host_dir("tan")
    saved["address"] = "addr-1"
    saved["hard_hash"] = 12345
    json.dump(saved, open(fp, "w"))
    with pytest.raises(IncompatibleDataError):
        Env(str(tmp_path), "addr-1", deployment_id=7).check_node_host_dir("tan")
    saved["hard_hash"] = None  # restore not needed; fresh tmp_path per test


def test_snapshot_dir_tombstone(tmp_path):
    env = Env(str(tmp_path), "addr-1")
    d = env.snapshot_dir(1, 2)
    open(os.path.join(d, "snap.gbsnap"), "w").write("x")
    env.remove_snapshot_dir(1, 2)
    assert env.snapshot_dir_removed(1, 2)
    assert not os.path.exists(os.path.join(d, "snap.gbsnap"))


def test_cluster_restart_from_disk(tmp_path):
    """Full lifecycle: write, snapshot, CLOSE every host, reopen the same
    dirs with brand-new NodeHosts (fresh TanLogDB built from the files),
    and verify state + liveness."""
    hosts, addrs = make_hosts(tmp_path, snapshot_entries=10)
    lead = wait_leader(hosts)
    nh = hosts[lead]
    sess = nh.get_noop_session(1)
    for i in range(25):
        nh.sync_propose(sess, f"k{i}=v{i}".encode())
    # let replication reach everyone
    deadline = time.time() + 5
    while time.time() < deadline:
        if all(h.stale_read(1, "k24") == "v24" for h in hosts.values()):
            break
        time.sleep(0.05)
    for h in hosts.values():
        h.close()

    hosts2 = {}
    for rid, addr in addrs.items():
        nh = NodeHost(NodeHostConfig(
            raft_address=addr, rtt_millisecond=5,
            node_host_dir=str(tmp_path)))
        cfg = Config(shard_id=1, replica_id=rid, election_rtt=10,
                     heartbeat_rtt=1, snapshot_entries=10,
                     compaction_overhead=5)
        # restart: initial_members comes from persisted state
        nh.start_replica({}, False, KVStateMachine, cfg)
        hosts2[rid] = nh
    try:
        lead = wait_leader(hosts2)
        # recovered data (snapshot + log replay through the RSM)
        for i in range(25):
            assert hosts2[lead].stale_read(1, f"k{i}") == f"v{i}", i
        # the cluster is live again
        nh = hosts2[lead]
        nh.sync_propose(nh.get_noop_session(1), b"post=restart")
        assert nh.sync_read(1, "post") == "restart"
    finally:
        for h in hosts2.values():
            h.close()


_CRASH_WORKER = """
import os, sys, time
sys.path.insert(0, {repo!r})
os.environ.setdefault("JAX_PLATFORMS", "cpu")
from dragonboat_tpu.config import Config, NodeHostConfig
from dragonboat_tpu.nodehost import NodeHost
sys.path.insert(0, os.path.join({repo!r}, "tests"))
from test_nodehost import KVStateMachine

nh = NodeHost(NodeHostConfig(raft_address="crash-1", rtt_millisecond=2,
                             node_host_dir={dir!r}))
nh.start_replica({{1: "crash-1"}}, False, KVStateMachine,
                 Config(shard_id=1, replica_id=1, election_rtt=10,
                        heartbeat_rtt=1))
deadline = time.time() + 10
while time.time() < deadline and not nh.get_leader_id(1)[1]:
    time.sleep(0.02)
s = nh.get_noop_session(1)
for i in range(40):
    nh.sync_propose(s, f"c{{i}}=v{{i}}".encode())
print("WROTE", flush=True)
os._exit(9)   # crash: no close(), no logdb flush beyond the fsyncs
"""


def test_crash_kill_recovers_from_fsynced_log(tmp_path):
    """A single-replica shard killed with os._exit after 40 committed
    writes must recover every write from the tan files alone."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, PYTHONPATH="", JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, "-c",
         _CRASH_WORKER.format(repo=repo, dir=str(tmp_path))],
        capture_output=True, text=True, env=env, timeout=120,
    )
    assert "WROTE" in out.stdout, out.stderr[-2000:]
    assert out.returncode == 9

    nh = NodeHost(NodeHostConfig(raft_address="crash-1", rtt_millisecond=2,
                                 node_host_dir=str(tmp_path)))
    nh.start_replica({}, False, KVStateMachine,
                     Config(shard_id=1, replica_id=1, election_rtt=10,
                            heartbeat_rtt=1))
    try:
        deadline = time.time() + 10
        ok = False
        while time.time() < deadline and not ok:
            ok = all(nh.stale_read(1, f"c{i}") == f"v{i}" for i in range(40))
            time.sleep(0.05)
        assert ok, "crash recovery lost fsynced writes"
        # and the shard is live
        nh.sync_propose(nh.get_noop_session(1), b"after=crash")
        assert nh.sync_read(1, "after") == "crash"
    finally:
        nh.close()


def test_wal_dir_separates_log_volume(tmp_path):
    """WALDir (config.go): the raft log lands on the WAL volume; the WAL
    dir is locked and pinned in the flag file like the main dir."""
    from dragonboat_tpu.server.env import IncompatibleDataError

    cfg = NodeHostConfig(raft_address="wd-1", rtt_millisecond=5,
                         node_host_dir=str(tmp_path / "main"),
                         wal_dir=str(tmp_path / "wal"))
    nh = NodeHost(cfg)
    nh.start_replica({1: "wd-1"}, False, KVStateMachine, Config(
        shard_id=1, replica_id=1, election_rtt=10, heartbeat_rtt=1))
    deadline = time.time() + 10
    while time.time() < deadline and not nh.get_leader_id(1)[1]:
        time.sleep(0.02)
    sess = nh.get_noop_session(1)
    for i in range(5):
        nh.sync_propose(sess, f"wl{i}=v{i}".encode())
    logdb_dir = nh.env.logdb_dir
    assert str(tmp_path / "wal") in logdb_dir
    assert any(f.endswith(".tan")
               for _, _, files in os.walk(logdb_dir) for f in files)
    # a second host sharing ONLY the WAL volume is excluded
    with pytest.raises(DirLockedError):
        NodeHost(NodeHostConfig(raft_address="wd-1", rtt_millisecond=5,
                                node_host_dir=str(tmp_path / "other"),
                                wal_dir=str(tmp_path / "wal")))
    nh.close()
    # dropping wal_dir on reopen is refused (the log would be left behind)
    with pytest.raises(IncompatibleDataError):
        NodeHost(NodeHostConfig(raft_address="wd-1", rtt_millisecond=5,
                                node_host_dir=str(tmp_path / "main")))
    # with the same wal_dir it reopens and recovers
    nh = NodeHost(cfg)
    nh.start_replica({}, False, KVStateMachine, Config(
        shard_id=1, replica_id=1, election_rtt=10, heartbeat_rtt=1))
    try:
        deadline = time.time() + 10
        while time.time() < deadline and nh.stale_read(1, "wl4") is None:
            time.sleep(0.05)
        assert nh.stale_read(1, "wl4") == "v4"
    finally:
        nh.close()
