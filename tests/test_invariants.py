"""Runtime invariant probe (core/invariants.py): randomized
differential vs the pure-python recount, clean verdicts on reachable
cluster states, the jitted pass under a 2-device G-sharded placement,
digest carry through the live engines at both pipeline depths, and the
sticky /healthz degradation (ISSUE 14 leg c)."""

import json
import time

import numpy as np
import pytest

import jax

from dragonboat_tpu.core import invariants as inv
from dragonboat_tpu.core import params as KP


def _inv_fields():
    return sorted({f for i in inv.PARSED.values() for f in i.fields})


def _perturb(state, rng):
    """Random host-side mutation of every invariant-participating
    column — the differential must hold for ANY state (violating ones
    included), not just reachable ones."""
    G = state.committed.shape[0]
    fields = {}
    for name in _inv_fields():
        col = np.array(jax.device_get(getattr(state, name)))
        if col.ndim == 1:
            mask = rng.random(G) < 0.4
            col[mask] = rng.integers(0, 8, mask.sum())
        else:                       # [G, P] columns (match / kind)
            mask = rng.random(col.shape) < 0.3
            col[mask] = rng.integers(0, 8, mask.sum())
        fields[name] = jax.numpy.asarray(col.astype(np.int32))
    return state._replace(**fields)


def _digest_from_dict(d):
    return inv.InvariantDigest(**{
        f: jax.numpy.asarray(np.array(v, np.int32)) for f, v in d.items()})


@pytest.mark.parametrize("groups,replicas,seed", [(1, 3, 5), (4, 3, 17),
                                                  (6, 5, 29)])
def test_probe_matches_recount_randomized(groups, replicas, seed):
    """Drive real elections, then randomized perturbations, carrying
    the digest across ticks on BOTH sides — the jitted report and the
    host recount must agree exactly every tick (violations included:
    perturbation freely manufactures them)."""
    from tests.kernel_harness import KernelCluster

    c = KernelCluster(groups, replicas)
    for _ in range(30):
        c.step(tick=True)
    rng = np.random.default_rng(seed)
    state = c.state
    digest = inv.empty_digest(c.G)
    saw_violation = False
    for tick in range(8):
        state = _perturb(state, rng)
        report, new_digest = inv.check_invariants(state, digest)
        got = inv.report_to_dict(report)
        want, want_digest = inv.recount(jax.device_get(state),
                                        jax.device_get(digest))
        assert got == want, f"tick {tick}: {got} != {want}"
        got_digest = {f: [int(v) for v in jax.device_get(
            getattr(new_digest, f))] for f in inv.InvariantDigest._fields}
        assert got_digest == want_digest, f"tick {tick} digest"
        saw_violation = saw_violation or want["total"] > 0
        digest = new_digest
    # the perturbation must actually exercise the violating branch, or
    # this differential silently degenerates to all-zeros == all-zeros
    assert saw_violation


def test_probe_clean_on_reachable_states():
    """Every state an unmutated cluster actually reaches — elections,
    appends, commits — satisfies all declared invariants."""
    from tests.kernel_harness import KernelCluster

    c = KernelCluster(2, 3)
    digest = inv.empty_digest(c.G)
    for step in range(60):
        c.step(tick=True)
        report, digest = inv.check_invariants(c.state, digest)
        d = inv.report_to_dict(report)
        assert d["total"] == 0, f"step {step}: {d}"
    assert d["checked"] == c.G     # every replica lane occupied + evaluated


def test_probe_sharded_two_device_mesh():
    """The jitted probe under a 2-device G-sharded placement (the
    ``part=G`` digest contract) agrees with the host recount."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as PS

    from tests.kernel_harness import KernelCluster

    devs = jax.devices()
    if len(devs) < 2:
        pytest.skip("needs 2 devices")
    c = KernelCluster(4, 3)        # G = 12 lanes, divisible by 2
    for _ in range(30):
        c.step(tick=True)
    mesh = Mesh(np.array(devs[:2]), ("g",))

    def put(leaf):
        if getattr(leaf, "ndim", 0) >= 1 and leaf.shape[0] == c.G:
            spec = PS("g", *([None] * (leaf.ndim - 1)))
        else:
            spec = PS()
        return jax.device_put(leaf, NamedSharding(mesh, spec))

    state = jax.tree.map(put, c.state)
    digest = jax.tree.map(put, inv.empty_digest(c.G))
    for _ in range(2):
        report, digest = inv.check_invariants(state, digest)
    got = inv.report_to_dict(report)
    ref_digest = inv.empty_digest(c.G)
    for _ in range(2):
        want, ref_d = inv.recount(jax.device_get(state),
                                  jax.device_get(ref_digest))
        ref_digest = _digest_from_dict(ref_d)
    assert got == want
    assert got["total"] == 0


def test_step_scoped_invariants_vacuous_without_prev():
    """ticks=0 marks the digest invalid: a state that would violate a
    step-scoped invariant against a bogus prev must pass until the
    first carry establishes a real one."""
    from tests.kernel_harness import KernelCluster

    c = KernelCluster(1, 3)
    for _ in range(40):
        c.step(tick=True)
    # committed regression is a step-scope violation — but only once a
    # prev exists
    lowered = c.state._replace(
        committed=c.state.committed * 0,
        applied=c.state.applied * 0)
    report, digest = inv.check_invariants(
        c.state, inv.empty_digest(c.G))
    assert inv.report_to_dict(report)["total"] == 0
    report2, _ = inv.check_invariants(lowered, digest)
    d2 = inv.report_to_dict(report2)
    if int(jax.device_get(c.state.committed)[0]) > 0:
        assert d2["per_invariant"]["commit_monotone"] >= 1
        assert "commit_monotone" in d2["first"]["invariants"]


# ---------------------------------------------------------------------
# live engines: probe rides the decimation at both pipeline depths


def _cluster(prefix, depth):
    from dragonboat_tpu.config import Config, ExpertConfig, NodeHostConfig
    from dragonboat_tpu.nodehost import NodeHost

    from test_nodehost import KVStateMachine

    addrs = {1: f"{prefix}-1", 2: f"{prefix}-2", 3: f"{prefix}-3"}
    hosts = {rid: NodeHost(NodeHostConfig(
        raft_address=a, rtt_millisecond=5, enable_metrics=True,
        expert=ExpertConfig(kernel_log_cap=256, kernel_capacity=4,
                            fleet_stats_every=5,
                            kernel_pipeline_depth=depth)))
        for rid, a in addrs.items()}
    for rid in addrs:
        hosts[rid].start_replica(addrs, False, KVStateMachine, Config(
            shard_id=1, replica_id=rid, election_rtt=10, heartbeat_rtt=1,
            device_resident=True))
    return hosts


def _wait(cond, timeout):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return True
        time.sleep(0.1)
    return cond()


@pytest.mark.parametrize("depth", [0, 1])
def test_probe_rides_decimation_on_live_engine(depth):
    """The probe ticks with the fleet-stats decimation on the live
    engine — at pipeline depth 0 and through the overlapped donating
    step loop at depth 1 — and a healthy cluster stays violation-free
    (sticky counter included)."""
    hosts = _cluster(f"ip{depth}", depth)
    try:
        assert _wait(lambda: any(
            h.get_leader_id(1)[1] and h.get_leader_id(1)[0]
            for h in hosts.values()), 45)
        eng = hosts[1].kernel_engine
        assert _wait(lambda: eng._inv_seq >= 3, 30), "no probe ticks"
        with eng.mu:
            seq = eng._inv_seq
            last = dict(eng.last_invariants)
            ticks = jax.device_get(eng._inv_digest.ticks)
        inv.validate_invariants(last)
        assert last["total"] == 0 and last["violations_seen"] == 0, last
        assert last["checked"] >= 1
        # ticks is the carry of exactly the probe ticks taken (dirty-
        # lane resets can only lower individual lanes, never exceed seq)
        assert max(int(t) for t in ticks) <= seq
        # the merged snapshot a scrape serves agrees
        snap = hosts[1]._invariants_snapshot()
        assert snap["violations_seen"] == 0
    finally:
        for h in hosts.values():
            h.close()


def test_healthz_degrades_sticky_on_violation():
    """A violations_seen that latched (live total back to zero) still
    degrades /healthz — a past protocol violation is a bug, not a
    condition that clears."""
    from dragonboat_tpu.server.metrics_http import MetricsServer

    counters = dict(inv.empty_dict(), violations_seen=0)
    srv = MetricsServer([], address="127.0.0.1:0",
                        invariants_source=lambda: dict(counters))
    try:
        status, body, _ = srv.healthz()
        assert status == 200, body
        counters["violations_seen"] = 3   # latched; live total stays 0
        status, body, _ = srv.healthz()
        assert status == 503
        payload = json.loads(body)
        assert payload["invariants"]["violations_seen"] == 3
        assert payload["invariants"]["total"] == 0
    finally:
        srv.close()


def test_declarations_parse_and_bind():
    """Every declared invariant parsed (import-time PARSED) and every
    field it references exists on ShardState — the same contract the
    safety pass enforces statically (RS001)."""
    from dragonboat_tpu.core.kstate import CONTRACTS, INVARIANTS

    assert set(inv.PARSED) == set(INVARIANTS)
    assert inv.NUM_INVARIANTS >= 5
    for i in inv.PARSED.values():
        for f in i.fields:
            assert f in CONTRACTS["ShardState"], (i.name, f)
