"""Snapshot format: V2/V3 round-trips, compression envelope, corruption
detection, orphan GC (rsm/snapshotio.go + rwv.go + encoded.go +
snapshotter.go:200 behaviors).
"""

import io
import os
import time

import pytest

from dragonboat_tpu.config import Config, NodeHostConfig
from dragonboat_tpu.nodehost import NodeHost
from dragonboat_tpu.rsm.snapshotio import (
    SnapshotFormatError,
    read_snapshot,
    write_snapshot,
)

from test_nodehost import KVStateMachine


def _roundtrip(payload: bytes, compress: bool) -> bytes:
    buf = io.BytesIO()
    write_snapshot(buf, b"sess", lambda w: w.write(payload),
                   compress=compress)
    buf.seek(0)
    session, reader = read_snapshot(buf)
    assert session == b"sess"
    return reader.read()


def test_v2_roundtrip():
    data = os.urandom(700_000)
    assert _roundtrip(data, compress=False) == data


def test_v3_compressed_roundtrip_compressible():
    data = b"abcdefgh" * 100_000   # compresses well
    buf = io.BytesIO()
    write_snapshot(buf, b"", lambda w: w.write(data), compress=True)
    stored = buf.tell()
    assert stored < len(data) // 2, "compression did not shrink the file"
    buf.seek(0)
    _, reader = read_snapshot(buf)
    assert reader.read() == data


def test_v3_roundtrip_incompressible():
    data = os.urandom(700_000)     # falls back to raw blocks per-block
    assert _roundtrip(data, compress=True) == data


@pytest.mark.parametrize("compress", [False, True])
def test_bitflip_detected(compress):
    data = b"xyz" * 200_000
    buf = io.BytesIO()
    write_snapshot(buf, b"s", lambda w: w.write(data), compress=compress)
    raw = bytearray(buf.getvalue())
    raw[len(raw) // 2] ^= 0x10
    _, reader = read_snapshot(io.BytesIO(bytes(raw)))
    with pytest.raises(SnapshotFormatError):
        reader.read()


def test_truncated_payload_detected():
    buf = io.BytesIO()
    write_snapshot(buf, b"s", lambda w: w.write(b"q" * 100_000))
    raw = buf.getvalue()[:-6]
    _, reader = read_snapshot(io.BytesIO(raw))
    with pytest.raises(Exception):
        reader.read()


def test_compressed_snapshot_end_to_end(tmp_path):
    """Config.snapshot_compression drives the V3 format through a full
    snapshot + restart."""
    def mk():
        nh = NodeHost(NodeHostConfig(raft_address="cmp-1", rtt_millisecond=5,
                                     node_host_dir=str(tmp_path)))
        nh.start_replica({1: "cmp-1"}, False, KVStateMachine, Config(
            shard_id=1, replica_id=1, election_rtt=10, heartbeat_rtt=1,
            snapshot_compression=True))
        deadline = time.time() + 10
        while time.time() < deadline and not nh.get_leader_id(1)[1]:
            time.sleep(0.02)
        return nh

    nh = mk()
    sess = nh.get_noop_session(1)
    for i in range(20):
        nh.sync_propose(sess, f"c{i}={'v' * 200}".encode())
    idx = nh.sync_request_snapshot(1)
    assert idx > 0
    nh.close()
    nh = mk()
    try:
        deadline = time.time() + 10
        while time.time() < deadline and nh.stale_read(1, "c19") is None:
            time.sleep(0.05)
        assert nh.stale_read(1, "c19") == "v" * 200
    finally:
        nh.close()


def test_orphan_snapshot_gc(tmp_path):
    nh = NodeHost(NodeHostConfig(raft_address="gc-1", rtt_millisecond=5,
                                 node_host_dir=str(tmp_path)))
    nh.start_replica({1: "gc-1"}, False, KVStateMachine, Config(
        shard_id=1, replica_id=1, election_rtt=10, heartbeat_rtt=1))
    deadline = time.time() + 10
    while time.time() < deadline and not nh.get_leader_id(1)[1]:
        time.sleep(0.02)
    sess = nh.get_noop_session(1)
    for i in range(5):
        nh.sync_propose(sess, f"g{i}=v{i}".encode())
    nh.sync_request_snapshot(1)
    snap_dir = nh.nodes[1].snapshot_dir
    live = [f for f in os.listdir(snap_dir) if f.endswith(".gbsnap")]
    assert len(live) == 1
    # plant orphans: a half-written temp and a superseded old snapshot
    stale = os.path.join(
        snap_dir, f"snapshot-{1:016X}-{1:016X}-{1:016X}.gbsnap")
    open(stale, "wb").write(b"old")
    open(stale + ".generating", "wb").write(b"tmp")
    # a foreign shard's temp must NOT be touched by this replica's GC
    foreign = os.path.join(snap_dir, "x.gbsnap.generating")
    open(foreign, "wb").write(b"other")
    nh.close()

    nh = NodeHost(NodeHostConfig(raft_address="gc-1", rtt_millisecond=5,
                                 node_host_dir=str(tmp_path)))
    nh.start_replica({}, False, KVStateMachine, Config(
        shard_id=1, replica_id=1, election_rtt=10, heartbeat_rtt=1))
    try:
        names = os.listdir(snap_dir)
        assert os.path.basename(stale) + ".generating" not in names
        assert os.path.basename(stale) not in names
        assert "x.gbsnap.generating" in names  # foreign temp untouched
        assert live[0] in names  # the live snapshot survived GC
        deadline = time.time() + 10
        while time.time() < deadline and nh.stale_read(1, "g4") is None:
            time.sleep(0.05)
        assert nh.stale_read(1, "g4") == "v4"
    finally:
        nh.close()
