"""Test harness: in-memory message router over Raft cores.

The model for this fixture is the reference's etcd-derived ``network`` test
router (raft_etcd_test.go:2896-2913, with the ``blackHole`` drop target at
:3036): instances are stepped message-by-message until the network drains,
with optional drop/isolate filters.  Used by the conformance suites and by
the kernel differential tests.
"""

from __future__ import annotations

from dragonboat_tpu import raftpb as pb
from dragonboat_tpu.core.logentry import InMemoryLogDB
from dragonboat_tpu.core.pycore import CoreConfig, Raft, RaftState


def new_raft(
    replica_id: int,
    peers: list[int],
    election: int = 10,
    heartbeat: int = 1,
    *,
    check_quorum: bool = False,
    pre_vote: bool = False,
    logdb: InMemoryLogDB | None = None,
    non_votings: list[int] | None = None,
    witnesses: list[int] | None = None,
    is_non_voting: bool = False,
    is_witness: bool = False,
    rng=None,
) -> Raft:
    cfg = CoreConfig(
        shard_id=1,
        replica_id=replica_id,
        election_rtt=election,
        heartbeat_rtt=heartbeat,
        check_quorum=check_quorum,
        pre_vote=pre_vote,
        is_non_voting=is_non_voting,
        is_witness=is_witness,
    )
    # deterministic per-replica randomized timeouts: replica i gets
    # election_rtt + (i-1), so lower ids campaign first under tick_all
    r = Raft(cfg, logdb if logdb is not None else InMemoryLogDB(),
             rng=rng if rng is not None else (lambda n, i=replica_id: (i - 1) % n))
    r.set_initial_members(
        {p: f"a{p}" for p in peers},
        {p: f"a{p}" for p in (non_votings or [])},
        {p: f"a{p}" for p in (witnesses or [])},
    )
    return r


class Network:
    def __init__(self, raft_nodes: dict[int, Raft], auto_apply: bool = True) -> None:
        self.nodes = raft_nodes
        self.dropped: set[tuple[int, int]] = set()
        self.isolated: set[int] = set()
        # simulate an RSM that applies committed entries instantly, so the
        # committed>applied campaign gate doesn't wedge harness elections
        self.auto_apply = auto_apply

    def isolate(self, rid: int) -> None:
        self.isolated.add(rid)

    def heal(self) -> None:
        self.isolated.clear()
        self.dropped.clear()

    def drop(self, frm: int, to: int) -> None:
        self.dropped.add((frm, to))

    def _deliverable(self, m: pb.Message) -> bool:
        if m.from_ in self.isolated or m.to in self.isolated:
            return False
        if (m.from_, m.to) in self.dropped:
            return False
        return m.to in self.nodes

    def collect(self) -> list[pb.Message]:
        out: list[pb.Message] = []
        for r in self.nodes.values():
            out.extend(m for m in r.msgs if not m.is_local())
            r.msgs = []
        return out

    def _sync_applied(self) -> None:
        if self.auto_apply:
            for r in self.nodes.values():
                r.applied = max(r.applied, r.log.committed)

    def send(self, msgs: list[pb.Message]) -> None:
        """Deliver messages, stepping recipients, until the network drains."""
        queue = list(msgs)
        while queue:
            self._sync_applied()
            m = queue.pop(0)
            if self._deliverable(m):
                self.nodes[m.to].handle(m)
            queue.extend(self.collect())
        self._sync_applied()

    def start(self, m: pb.Message) -> None:
        """Inject a local message at m.to and run to quiescence."""
        self._sync_applied()
        self.nodes[m.to].handle(m)
        self.send(self.collect())

    def elect(self, rid: int) -> None:
        self.start(pb.Message(type=pb.MessageType.ELECTION, to=rid, from_=rid))

    def propose(self, rid: int, cmd: bytes = b"data") -> None:
        self.start(
            pb.Message(
                type=pb.MessageType.PROPOSE,
                to=rid,
                from_=rid,
                entries=(pb.Entry(cmd=cmd),),
            )
        )

    def tick_all(self, n: int = 1) -> None:
        for _ in range(n):
            self._sync_applied()
            for r in self.nodes.values():
                r.tick()
            self.send(self.collect())

    def leader(self) -> Raft | None:
        leaders = [r for r in self.nodes.values() if r.state == RaftState.LEADER]
        return leaders[0] if leaders else None


def make_network(n: int, **kwargs) -> Network:
    peers = list(range(1, n + 1))
    return Network({i: new_raft(i, peers, **kwargs) for i in peers})
