"""Small-scope exhaustive model checker (scripts/model_check.py): the
fast scope must be violation-free on the real kernel, the seeded
protocol bugs it owns must be caught within that same scope, and the
mutation catalogue must track the kernel source (a drifted find-snippet
is a silently-dead mutation test, so it raises instead)."""

from __future__ import annotations

import importlib.util
import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load():
    spec = importlib.util.spec_from_file_location(
        "model_check_under_test",
        os.path.join(REPO, "scripts", "model_check.py"))
    mod = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = mod
    spec.loader.exec_module(mod)
    return mod


mc = _load()


def test_fast_scope_clean_on_real_kernel():
    """BFS over all interleavings within the fast bounds, transition
    relation = the real jitted kernel step: zero violations, and the
    run must actually cover a nontrivial state count."""
    res = mc.run_scope("fast")
    assert res["violations"] == [], res["violations"]
    assert res["scope_complete"]
    assert res["states_explored"] >= 100
    assert res["transitions"] >= res["states_explored"] // 2
    assert set(res["properties"]) >= {
        "election_safety", "leader_append_only", "log_matching",
        "leader_completeness", "state_machine_safety"}


@pytest.mark.parametrize("mutation,expect", [
    # the checker OWNS double_vote (no store-shape signature for the
    # static pass to key on); commit_without_quorum is also caught here
    # (defense in depth on top of its static RS002 owner)
    ("double_vote", "vote_once_per_term"),
    ("commit_without_quorum", "leader_commit_quorum"),
])
def test_checker_catches_mutation(mutation, expect):
    res = mc.run_scope("fast", mutation=mutation)
    assert res["violations"], f"{mutation} escaped the fast scope"
    names = " ".join(v["property"] for v in res["violations"])
    assert expect in names, (mutation, res["violations"][:3])
    # a violation report must carry a replayable trail
    assert res["violations"][0]["trail"]


def test_quiesce_scope_clean_and_catches_masked_campaign():
    """The quiesce scope seeds quiesced=True states directly (natural
    entry needs e_timeout*10 idle ticks, outside the depth bound): the
    real kernel must hold quiesced_no_campaign / quiesced_no_vote, and
    a kernel whose tick path ignores the device mask must be caught."""
    res = mc.run_scope("quiesce")
    assert res["violations"] == [], res["violations"]
    assert res["scope_complete"]
    assert {"invariant:quiesced_no_campaign",
            "invariant:quiesced_no_vote"} <= set(res["properties"])

    mut = mc.run_scope("quiesce", mutation="quiesce_campaigns")
    assert mut["violations"], "quiesce_campaigns escaped the quiesce scope"
    names = " ".join(v["property"] for v in mut["violations"])
    assert "quiesced_no_campaign" in names, mut["violations"][:3]
    assert mut["violations"][0]["trail"]


def test_mutation_snippets_track_kernel_source():
    src = open(os.path.join(
        REPO, "dragonboat_tpu", "core", "kernel.py")).read()
    for name, (find, replace) in mc.MUTATIONS.items():
        assert find in src, f"mutation {name!r} target drifted"
        assert find != replace


def test_drifted_snippet_raises(monkeypatch):
    monkeypatch.setitem(mc.MUTATIONS, "double_vote",
                        ("nonexistent snippet", "x"))
    with pytest.raises(RuntimeError, match="double_vote"):
        mc.load_kernel_module("double_vote")


def test_every_seeded_bug_is_caught_by_some_leg():
    """The PR's acceptance criterion in executable form: each mutation
    is owned by the model checker or by a static safety rule — none may
    fall through both legs."""
    from tests.test_safety import STATIC_OWNER

    checker_owned = {"double_vote", "quiesce_campaigns"}
    assert set(mc.MUTATIONS) == checker_owned | set(STATIC_OWNER)
