"""Sharded tan engine: partition routing, restart recovery across
partitions, overlapping fsyncs (the single-lock bug the r3 VERDICT
flagged), geometry pinning, legacy-layout migration, and spanning-batch
saves from the device engine's [G]-batch shape.

Parity target: internal/logdb/sharded.go:34-80 (ShardedDB over N
single-writer DBs), internal/server/partition.go:59 (DoubleFixed
partitioner), raftio/logdb.go:78-83 (single-writer-per-worker fsync
contract)."""

import os
import threading
import time

import pytest

from dragonboat_tpu import raftpb as pb
from dragonboat_tpu.logdb.sharded import (
    ShardedLogDB,
    ShardGeometryError,
)
from dragonboat_tpu.logdb.tan import TanLogDB


def _update(shard=1, replica=1, term=1, first=1, n=3, commit=0):
    ents = tuple(
        pb.Entry(term=term, index=first + i, cmd=f"s{shard}e{first + i}".encode())
        for i in range(n)
    )
    return pb.Update(
        shard_id=shard, replica_id=replica,
        state=pb.State(term=term, vote=2, commit=commit),
        entries_to_save=ents,
    )


def test_routing_spreads_partitions(tmp_path):
    db = ShardedLogDB(str(tmp_path), num_shards=4)
    for sid in range(1, 9):
        db.save_raft_state([_update(shard=sid, n=2)], worker_id=sid % 4)
    # every shard readable through the facade
    for sid in range(1, 9):
        ents = db.iterate_entries(sid, 1, 1, 3, 0)
        assert [e.index for e in ents] == [1, 2]
        assert ents[0].cmd == f"s{sid}e1".encode()
    # and the files really are spread over >1 partition dir
    parts_with_data = [
        d for d in os.listdir(tmp_path)
        if d.startswith("part-")
        and any(f.startswith("log-") and os.path.getsize(
            os.path.join(tmp_path, d, f)) > 0
            for f in os.listdir(os.path.join(tmp_path, d)))
    ]
    assert len(parts_with_data) == 4
    db.close()


def test_restart_across_partitions(tmp_path):
    db = ShardedLogDB(str(tmp_path), num_shards=4)
    db.save_bootstrap_info(3, 1, pb.Bootstrap(addresses={1: "a"}))
    for sid in (1, 2, 3, 6, 7):
        db.save_raft_state([_update(shard=sid, n=4, commit=2)], worker_id=0)
    db.close()

    db2 = ShardedLogDB(str(tmp_path), num_shards=4)
    infos = {(ni.shard_id, ni.replica_id) for ni in db2.list_node_info()}
    assert infos == {(1, 1), (2, 1), (3, 1), (6, 1), (7, 1)}
    for sid in (1, 2, 3, 6, 7):
        rs = db2.read_raft_state(sid, 1, 0)
        assert rs.entry_count == 4 and rs.state.commit == 2
    assert db2.get_bootstrap_info(3, 1).addresses == {1: "a"}
    db2.close()


def test_geometry_change_refused(tmp_path):
    db = ShardedLogDB(str(tmp_path), num_shards=4)
    db.save_raft_state([_update()], worker_id=0)
    db.close()
    with pytest.raises(ShardGeometryError):
        ShardedLogDB(str(tmp_path), num_shards=8)
    with pytest.raises(ShardGeometryError):
        ShardedLogDB(str(tmp_path), num_shards=2)
    # the original geometry still opens
    db2 = ShardedLogDB(str(tmp_path), num_shards=4)
    assert db2.read_raft_state(1, 1, 0) is not None
    db2.close()


def test_legacy_flat_layout_migrates(tmp_path):
    old = TanLogDB(str(tmp_path))
    old.save_bootstrap_info(1, 1, pb.Bootstrap(addresses={1: "x", 2: "y"}))
    for sid in (1, 2, 5):
        old.save_raft_state([_update(shard=sid, n=3, commit=1)], worker_id=0)
    old.save_snapshots([pb.Update(
        shard_id=2, replica_id=1,
        snapshot=pb.Snapshot(index=1, term=1, shard_id=2),
    )])
    old.close()
    assert any(f.startswith("log-") for f in os.listdir(tmp_path))

    db = ShardedLogDB(str(tmp_path), num_shards=4)
    # flat files folded into partitions and removed from the root
    assert not any(f.startswith("log-") for f in os.listdir(tmp_path))
    for sid in (1, 5):
        ents = db.iterate_entries(sid, 1, 1, 4, 0)
        assert [e.index for e in ents] == [1, 2, 3]
    # shard 2 had a snapshot at index 1: migration keeps the live suffix
    # (snapshot.index+1 ..), exactly what restart-from-disk reads
    assert [e.index for e in db.iterate_entries(2, 1, 2, 4, 0)] == [2, 3]
    assert db.get_bootstrap_info(1, 1).addresses == {1: "x", 2: "y"}
    ss = db.get_snapshot(2, 1)
    assert ss is not None and ss.index == 1
    db.close()

    # and the migrated layout survives another restart
    db2 = ShardedLogDB(str(tmp_path), num_shards=4)
    assert [e.index for e in db2.iterate_entries(5, 1, 1, 4, 0)] == [1, 2, 3]
    db2.close()


def test_spanning_batch_save_and_snapshot_routing(tmp_path):
    """The device engine saves one [G]-lane batch covering many
    partitions in ONE call (engine/kernel_engine.py step loop)."""
    db = ShardedLogDB(str(tmp_path), num_shards=4)
    batch = [_update(shard=sid, n=2, commit=1) for sid in range(1, 33)]
    db.save_raft_state(batch, worker_id=0)
    for sid in range(1, 33):
        assert db.read_raft_state(sid, 1, 0).entry_count == 2
    db.save_snapshots([pb.Update(
        shard_id=sid, replica_id=1,
        snapshot=pb.Snapshot(index=2, term=1, shard_id=sid))
        for sid in range(1, 33)])
    db.close()
    db2 = ShardedLogDB(str(tmp_path), num_shards=4)
    for sid in range(1, 33):
        assert db2.get_snapshot(sid, 1).index == 2
    db2.close()


def test_remove_and_compact_route(tmp_path):
    db = ShardedLogDB(str(tmp_path), num_shards=4)
    for sid in (1, 2):
        db.save_raft_state([_update(shard=sid, n=6, commit=5)], worker_id=0)
    db.remove_entries_to(1, 1, 3)
    assert [e.index for e in db.iterate_entries(1, 1, 4, 7, 0)] == [4, 5, 6]
    assert db.iterate_entries(1, 1, 1, 7, 0) == []   # below the floor
    db.remove_node_data(2, 1)
    assert db.read_raft_state(2, 1, 0) is None
    infos = {ni.shard_id for ni in db.list_node_info()}
    assert infos == {1}
    db.close()


class _SlowFsyncFS:
    """OSFS wrapper whose fsync sleeps — makes overlap measurable."""

    def __init__(self, delay):
        from dragonboat_tpu.vfs import OSFS

        self._fs = OSFS()
        self.delay = delay
        self.fsyncs = 0
        self._mu = threading.Lock()

    def __getattr__(self, name):
        return getattr(self._fs, name)

    def fsync(self, f):
        with self._mu:
            self.fsyncs += 1
        time.sleep(self.delay)
        self._fs.fsync(f)


def test_fsyncs_overlap_across_partitions(tmp_path):
    """THE r3 VERDICT finding: with the single-file engine, W workers
    serialized on one lock+file. Two workers flushing different
    partitions must overlap their fsyncs (wall << 2 x serial)."""
    delay = 0.15
    fs = _SlowFsyncFS(delay)
    db = ShardedLogDB(str(tmp_path), num_shards=4, fs=fs)
    n_each = 4

    def worker(sid, wid):
        for i in range(n_each):
            db.save_raft_state(
                [_update(shard=sid, first=1 + 2 * i, n=2)], worker_id=wid)

    t0 = time.time()
    ts = [threading.Thread(target=worker, args=(sid, sid % 4))
          for sid in (1, 2, 3, 4)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    wall = time.time() - t0
    serial = 4 * n_each * delay     # what the single-lock engine would cost
    # four truly-concurrent streams should land near n_each * delay;
    # allow generous scheduler slack on the 1-core CI box
    assert wall < serial * 0.6, (wall, serial)
    db.close()


def test_crash_kill_recovery_all_partitions(tmp_path):
    """Kill the process image (skip close) after spanning writes; every
    partition must recover, including a torn tail in each partition."""
    db = ShardedLogDB(str(tmp_path), num_shards=4)
    for sid in range(1, 9):
        db.save_raft_state([_update(shard=sid, n=3, commit=2)], worker_id=0)
    # simulate the crash: no close(), then garble a torn tail onto every
    # partition's active file (an unsynced partial record)
    for i in range(4):
        pdir = os.path.join(tmp_path, f"part-{i:02d}")
        logs = sorted(f for f in os.listdir(pdir) if f.startswith("log-"))
        with open(os.path.join(pdir, logs[-1]), "ab") as f:
            f.write(b"\x02\x00NE\x7f")     # half a header
    db2 = ShardedLogDB(str(tmp_path), num_shards=4)
    for sid in range(1, 9):
        assert [e.index for e in db2.iterate_entries(sid, 1, 1, 4, 0)] == \
            [1, 2, 3]
    # and the recovered engine accepts new writes
    db2.save_raft_state([_update(shard=1, first=4, n=1)], worker_id=0)
    assert db2.read_raft_state(1, 1, 0).entry_count == 4
    db2.close()


def test_nodehost_default_is_sharded(tmp_path):
    from dragonboat_tpu.config import NodeHostConfig
    from dragonboat_tpu.nodehost import NodeHost

    nh = NodeHost(NodeHostConfig(
        node_host_dir=str(tmp_path / "nh"),
        raft_address="localhost:26000",
    ), auto_run=False)
    try:
        assert nh.logdb.name().startswith("sharded-tan")
        assert os.path.isdir(os.path.join(nh.env.logdb_dir and
                                          nh.env.logdb_dir, "part-00"))
    finally:
        nh.close()


def test_legacy_dir_flag_bumped_on_migration(tmp_path):
    """A flat-'tan' NodeHost dir migrates AND gets its flag rewritten to
    sharded-tan, so a rolled-back pre-sharding binary refuses the dir
    instead of silently starting from an empty log."""
    import json

    from dragonboat_tpu.config import NodeHostConfig
    from dragonboat_tpu.nodehost import NodeHost
    from dragonboat_tpu.server.env import FLAG_FILENAME

    nh = NodeHost(NodeHostConfig(node_host_dir=str(tmp_path),
                                 raft_address="flag-1"), auto_run=False)
    nh.close()
    fp = None
    for dirpath, _, files in os.walk(tmp_path):
        if FLAG_FILENAME in files:
            fp = os.path.join(dirpath, FLAG_FILENAME)
            break
    assert fp is not None
    with open(fp) as f:
        assert json.load(f)["logdb_type"] == "sharded-tan"
    # simulate a legacy dir: rewrite the flag back to "tan"
    with open(fp) as f:
        saved = json.load(f)
    saved["logdb_type"] = "tan"
    with open(fp, "w") as f:
        json.dump(saved, f)
    nh2 = NodeHost(NodeHostConfig(node_host_dir=str(tmp_path),
                                  raft_address="flag-1"), auto_run=False)
    nh2.close()
    with open(fp) as f:
        assert json.load(f)["logdb_type"] == "sharded-tan"
