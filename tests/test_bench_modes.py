"""Bench instrumentation correctness: the latency histogram, the
ReadIndex mixed mode and the election-storm loop (BASELINE configs #3/#4
— README.md:47,53-64 read-mix and latency tables)."""

import jax.numpy as jnp
import numpy as np

from dragonboat_tpu.bench_loop import (
    bench_params,
    elect_all,
    lat_init,
    make_cluster,
    run_steps,
    run_steps_lat,
    run_steps_storm,
)
from dragonboat_tpu.core import params as KP
from dragonboat_tpu.core.kstate import empty_inbox


def _elect(groups=8, replicas=3):
    kp = bench_params(replicas)
    state = make_cluster(kp, groups, replicas)
    state, box = elect_all(kp, replicas, state)
    return kp, state, box


def test_latency_histogram_counts_leader_releases():
    """Every leader-side released write lands in exactly one bucket, and
    steady-state commit latency is a small constant (the pipeline depth),
    not the window length."""
    kp, state, box = _elect()
    replicas = 3
    G = state.term.shape[0]
    stamp, hist, reads = lat_init(kp, G)
    lead = np.asarray(state.role) == KP.LEADER

    # settle the pipeline first (fill the propose->release queue)
    state, box, stamp, hist, reads = run_steps_lat(
        kp, replicas, 10, kp.proposal_cap, False, True, True,
        jnp.asarray(0, jnp.int32), state, box, stamp, hist, reads)
    h0 = np.asarray(hist).astype(np.int64)
    a0 = np.asarray(state.processed)[lead].astype(np.int64).sum()

    state, box, stamp, hist, reads = run_steps_lat(
        kp, replicas, 25, kp.proposal_cap, False, True, True,
        jnp.asarray(10, jnp.int32), state, box, stamp, hist, reads)
    dh = np.asarray(hist).astype(np.int64) - h0
    released = (np.asarray(state.processed)[lead].astype(np.int64).sum()
                - a0)
    assert dh.sum() == released, (dh.sum(), released)
    # steady state: all releases within a few steps of proposing
    assert dh[:8].sum() == dh.sum(), dh.nonzero()
    assert released > 0


def test_mixed_mode_completes_read_contexts():
    kp, state, box = _elect()
    G = state.term.shape[0]
    stamp, hist, reads = lat_init(kp, G)
    state, box, stamp, hist, reads = run_steps_lat(
        kp, 3, 20, 4, True, True, True,
        jnp.asarray(0, jnp.int32), state, box, stamp, hist, reads)
    n_groups = G // 3
    ctx = int(np.asarray(reads))
    # every leader completes ~one quorum-read ctx per settled step
    assert ctx > 10 * n_groups // 2, ctx
    # writes still flow at the narrow width
    assert int(np.asarray(state.committed).max()) > 0


def test_storm_recovers_to_single_leader():
    replicas = 3
    kp = bench_params(replicas)
    state = make_cluster(kp, 16, replicas)
    state = state._replace(pre_vote=jnp.ones_like(state.pre_vote))
    box = empty_inbox(kp, state.term.shape[0])

    # cold start with 30% drops
    state, box = run_steps_storm(kp, replicas, 30, 0.3, 7, state, box)
    # clean network: must converge to exactly one leader per group
    for _ in range(40):
        role = np.asarray(state.role).reshape(-1, replicas)
        if ((role == KP.LEADER).sum(axis=1) == 1).all():
            break
        state, box = run_steps(kp, replicas, 5, True, False, state, box)
    role = np.asarray(state.role).reshape(-1, replicas)
    assert ((role == KP.LEADER).sum(axis=1) == 1).all()
    # pre-vote kept failed campaigns from inflating terms unboundedly
    assert int(np.asarray(state.term).max()) < 30
