"""Bench instrumentation correctness: the latency histogram, the
ReadIndex mixed mode and the election-storm loop (BASELINE configs #3/#4
— README.md:47,53-64 read-mix and latency tables)."""

import jax.numpy as jnp
import numpy as np

from dragonboat_tpu.bench_loop import (
    bench_params,
    elect_all,
    lat_init,
    make_cluster,
    run_steps,
    run_steps_lat,
    run_steps_storm,
)
from dragonboat_tpu.core import params as KP
from dragonboat_tpu.core.kstate import empty_inbox


def _elect(groups=8, replicas=3):
    kp = bench_params(replicas)
    state = make_cluster(kp, groups, replicas)
    state, box = elect_all(kp, replicas, state)
    return kp, state, box


def test_latency_histogram_counts_leader_releases():
    """Every leader-side released write lands in exactly one bucket, and
    steady-state commit latency is a small constant (the pipeline depth),
    not the window length."""
    kp, state, box = _elect()
    replicas = 3
    G = state.term.shape[0]
    stamp, hist, reads = lat_init(kp, G)
    lead = np.asarray(state.role) == KP.LEADER

    # settle the pipeline first (fill the propose->release queue)
    state, box, stamp, hist, reads = run_steps_lat(
        kp, replicas, 10, kp.proposal_cap, False, True, True,
        jnp.asarray(0, jnp.int32), state, box, stamp, hist, reads)
    h0 = np.asarray(hist).astype(np.int64)
    a0 = np.asarray(state.processed)[lead].astype(np.int64).sum()

    state, box, stamp, hist, reads = run_steps_lat(
        kp, replicas, 25, kp.proposal_cap, False, True, True,
        jnp.asarray(10, jnp.int32), state, box, stamp, hist, reads)
    dh = np.asarray(hist).astype(np.int64) - h0
    released = (np.asarray(state.processed)[lead].astype(np.int64).sum()
                - a0)
    assert dh.sum() == released, (dh.sum(), released)
    # steady state: all releases within a few steps of proposing
    assert dh[:8].sum() == dh.sum(), dh.nonzero()
    assert released > 0


def test_mixed_mode_completes_read_contexts():
    kp, state, box = _elect()
    G = state.term.shape[0]
    stamp, hist, reads = lat_init(kp, G)
    state, box, stamp, hist, reads = run_steps_lat(
        kp, 3, 20, 4, True, True, True,
        jnp.asarray(0, jnp.int32), state, box, stamp, hist, reads)
    n_groups = G // 3
    ctx = int(np.asarray(reads))
    # every leader completes ~one quorum-read ctx per settled step
    assert ctx > 10 * n_groups // 2, ctx
    # writes still flow at the narrow width
    assert int(np.asarray(state.committed).max()) > 0


def test_storm_recovers_to_single_leader():
    replicas = 3
    kp = bench_params(replicas)
    state = make_cluster(kp, 16, replicas)
    state = state._replace(pre_vote=jnp.ones_like(state.pre_vote))
    box = empty_inbox(kp, state.term.shape[0])

    # cold start with 30% drops
    state, box = run_steps_storm(kp, replicas, 30, 0.3, 7, state, box)
    # clean network: must converge to exactly one leader per group
    for _ in range(40):
        role = np.asarray(state.role).reshape(-1, replicas)
        if ((role == KP.LEADER).sum(axis=1) == 1).all():
            break
        state, box = run_steps(kp, replicas, 5, True, False, state, box)
    role = np.asarray(state.role).reshape(-1, replicas)
    assert ((role == KP.LEADER).sum(axis=1) == 1).all()
    # pre-vote kept failed campaigns from inflating terms unboundedly
    assert int(np.asarray(state.term).max()) < 30


def test_mixed_sm_serves_reads_with_correct_values():
    """run_steps_mixed_sm: every counted read is an executed lookup.
    With index-valued payloads on a direct-mapped table, a served window
    below a ctx index must read back exactly those indices — the
    checksum is predictable, not just non-zero."""
    from dragonboat_tpu.bench_loop import (
        make_device_sm,
        run_steps_mixed_sm,
        sm_params,
    )

    kp = sm_params(3)
    state = make_cluster(kp, 8, 3)
    state, box = elect_all(kp, 3, state)
    kv, kv_state = make_device_sm(8, 3)
    rd = jnp.asarray(0, jnp.int32)
    acc = jnp.asarray(0, jnp.int32)
    rej = jnp.asarray(0, jnp.int32)
    WW = 4
    state, box, kv_state, rd, acc, rej = run_steps_mixed_sm(
        kp, 3, kv, 25, WW, jnp.asarray(0, jnp.int32),
        state, box, kv_state, rd, acc, rej)
    RB = 9 * WW
    served_ctx = int(np.asarray(rd))
    assert served_ctx > 0, served_ctx
    assert int(np.asarray(rej)) == 0
    # payloads are the entry's own index and the table is direct-mapped,
    # so a served window [rix-RB, rix) reads values == those indices;
    # every served read is therefore a known positive contribution
    assert int(np.asarray(acc)) > 0
    # writes flowed at full width alongside the reads
    assert int(np.asarray(state.committed).max()) > RB


def test_mixed_sm_read_gate_respects_apply_cursor():
    """A confirmed ctx whose index the SM has not applied past yet is
    dropped, not served stale.  Discriminating setup: apply_batch=2
    with write width 8 makes the apply cursor fall ~6 entries/step
    behind the commit point, so ctx indexes (at the commit point when
    confirmed) stay ahead of ``processed`` and the gate must suppress
    serving almost entirely — without the gate, ~one ctx per leader per
    settled step would be served."""
    import dataclasses

    from dragonboat_tpu.bench_loop import (
        make_device_sm,
        run_steps_mixed_sm,
        sm_params,
    )

    kp = dataclasses.replace(sm_params(3), apply_batch=2)
    state = make_cluster(kp, 4, 3)
    state, box = elect_all(kp, 3, state)
    kv, kv_state = make_device_sm(4, 3)
    rd = jnp.asarray(0, jnp.int32)
    acc = jnp.asarray(0, jnp.int32)
    rej = jnp.asarray(0, jnp.int32)
    steps = 12
    state, box, kv_state, rd, acc, rej = run_steps_mixed_sm(
        kp, 3, kv, steps, 8, jnp.asarray(0, jnp.int32),
        state, box, kv_state, rd, acc, rej)
    leaders = int((np.asarray(state.role) == KP.LEADER).sum())
    ungated_ctx_floor = (steps - 4) * leaders  # ~1 ctx/leader/settled step
    served_ctx = int(np.asarray(rd))
    assert served_ctx < ungated_ctx_floor // 2, (
        f"gate ineffective: served {served_ctx} ctxs vs ungated floor "
        f"{ungated_ctx_floor}")
    # and the cursor really did lag: committed far ahead of processed
    lag = (np.asarray(state.committed) - np.asarray(state.processed))
    assert int(lag.max()) > 10


def test_mixed_sm_served_reads_hashed_equals_direct():
    """The hashed-table slot scan (stored-key window test) serves the
    SAME reads as the direct-mapped form: identical cluster trajectory,
    identical served-ctx count AND identical read-value checksum —
    payload values are keyed by entry index in both layouts, so any
    divergence means one of the scans served the wrong slots."""
    from dragonboat_tpu.bench_loop import (
        make_device_sm,
        run_steps_mixed_sm,
        sm_params,
    )
    from dragonboat_tpu.rsm.device_kv import DeviceKV

    kp = sm_params(3)
    results = {}
    for kind, hash_keys in (("direct", False), ("hashed", True)):
        state = make_cluster(kp, 8, 3)
        state, box = elect_all(kp, 3, state)
        if hash_keys:
            kv = DeviceKV(table_cap=1024, hash_keys=True)
            kv_state = kv.init_state(8 * 3)
        else:
            kv, kv_state = make_device_sm(8, 3)
        rd = jnp.asarray(0, jnp.int32)
        acc = jnp.asarray(0, jnp.int32)
        rej = jnp.asarray(0, jnp.int32)
        state, box, kv_state, rd, acc, rej = run_steps_mixed_sm(
            kp, 3, kv, 25, 4, jnp.asarray(0, jnp.int32),
            state, box, kv_state, rd, acc, rej)
        results[kind] = (int(np.asarray(rd)), int(np.asarray(acc)),
                         int(np.asarray(rej)))
    assert results["direct"][0] > 0
    assert results["direct"][2] == 0 and results["hashed"][2] == 0
    # same trajectory, same served windows, same values -> same numbers
    assert results["hashed"][0] == results["direct"][0], results
    assert results["hashed"][1] == results["direct"][1], results
