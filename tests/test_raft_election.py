"""Election conformance — in the spirit of raft_etcd_test.go/raft_etcd_paper_test.go
(tests named after the behaviors they pin, not ports of Go code)."""

import pytest

from dragonboat_tpu import raftpb as pb
from dragonboat_tpu.core.pycore import RaftState
from raft_harness import Network, make_network, new_raft

MT = pb.MessageType


def test_single_node_becomes_leader_immediately():
    nt = make_network(1)
    nt.elect(1)
    assert nt.nodes[1].state == RaftState.LEADER
    assert nt.nodes[1].term == 1


def test_three_node_election():
    nt = make_network(3)
    nt.elect(1)
    r1 = nt.nodes[1]
    assert r1.state == RaftState.LEADER
    assert r1.term == 1
    for rid in (2, 3):
        assert nt.nodes[rid].state == RaftState.FOLLOWER
        assert nt.nodes[rid].term == 1
        assert nt.nodes[rid].leader_id == 1


def test_election_by_tick_timeout():
    nt = make_network(3)
    # node 1 has the lowest randomized timeout (rng returns 0 -> timeout = 10)
    nt.tick_all(10)
    assert nt.leader() is not None


def test_candidate_votes_for_self_and_bumps_term():
    r = new_raft(1, [1, 2, 3])
    r.handle(pb.Message(type=MT.ELECTION, from_=1))
    assert r.state == RaftState.CANDIDATE
    assert r.term == 1
    assert r.vote == 1
    # sent RequestVote to both peers
    targets = sorted(m.to for m in r.msgs if m.type == MT.REQUEST_VOTE)
    assert targets == [2, 3]


def test_vote_granted_once_per_term():
    r = new_raft(1, [1, 2, 3])
    r.handle(pb.Message(type=MT.REQUEST_VOTE, from_=2, term=1, log_index=0, log_term=0))
    resp = [m for m in r.msgs if m.type == MT.REQUEST_VOTE_RESP]
    assert len(resp) == 1 and not resp[0].reject
    assert r.vote == 2
    r.msgs = []
    # same term, different candidate -> reject
    r.handle(pb.Message(type=MT.REQUEST_VOTE, from_=3, term=1, log_index=0, log_term=0))
    resp = [m for m in r.msgs if m.type == MT.REQUEST_VOTE_RESP]
    assert len(resp) == 1 and resp[0].reject
    # same candidate again -> grant (idempotent)
    r.msgs = []
    r.handle(pb.Message(type=MT.REQUEST_VOTE, from_=2, term=1, log_index=0, log_term=0))
    resp = [m for m in r.msgs if m.type == MT.REQUEST_VOTE_RESP]
    assert len(resp) == 1 and not resp[0].reject


def test_vote_rejected_for_stale_log():
    """2nd paragraph section 5.4 of the raft paper: voters reject candidates
    with less up-to-date logs."""
    r = new_raft(1, [1, 2, 3])
    r.log.append([pb.Entry(term=2, index=1), pb.Entry(term=2, index=2)])
    r.term = 2
    # candidate with lower last log term
    r.handle(pb.Message(type=MT.REQUEST_VOTE, from_=2, term=3, log_index=5, log_term=1))
    resp = [m for m in r.msgs if m.type == MT.REQUEST_VOTE_RESP]
    assert resp[0].reject
    # candidate with equal term but shorter log
    r.msgs = []
    r.handle(pb.Message(type=MT.REQUEST_VOTE, from_=3, term=3, log_index=1, log_term=2))
    resp = [m for m in r.msgs if m.type == MT.REQUEST_VOTE_RESP]
    assert resp[0].reject
    # candidate with same log -> grant
    r.msgs = []
    r.handle(pb.Message(type=MT.REQUEST_VOTE, from_=2, term=3, log_index=2, log_term=2))
    resp = [m for m in r.msgs if m.type == MT.REQUEST_VOTE_RESP]
    assert not resp[0].reject


def test_candidate_steps_down_on_majority_rejection():
    r = new_raft(1, [1, 2, 3])
    r.handle(pb.Message(type=MT.ELECTION, from_=1))
    assert r.state == RaftState.CANDIDATE
    r.handle(pb.Message(type=MT.REQUEST_VOTE_RESP, from_=2, term=1, reject=True))
    assert r.state == RaftState.CANDIDATE
    r.handle(pb.Message(type=MT.REQUEST_VOTE_RESP, from_=3, term=1, reject=True))
    assert r.state == RaftState.FOLLOWER


def test_candidate_becomes_leader_on_quorum():
    r = new_raft(1, [1, 2, 3, 4, 5])
    r.handle(pb.Message(type=MT.ELECTION, from_=1))
    r.handle(pb.Message(type=MT.REQUEST_VOTE_RESP, from_=2, term=1))
    assert r.state == RaftState.CANDIDATE
    r.handle(pb.Message(type=MT.REQUEST_VOTE_RESP, from_=3, term=1))
    assert r.state == RaftState.LEADER
    # noop entry appended on promotion (p72 raft thesis)
    assert r.log.last_index() == 1


def test_leader_appends_noop_on_election():
    nt = make_network(3)
    nt.elect(1)
    leader = nt.nodes[1]
    assert leader.log.last_index() == 1
    assert leader.log.committed == 1  # replicated to followers during drain
    assert nt.nodes[2].log.last_index() == 1


def test_higher_term_message_converts_to_follower():
    nt = make_network(3)
    nt.elect(1)
    r1 = nt.nodes[1]
    # bogus higher-term heartbeat: r1 must step down
    r1.handle(pb.Message(type=MT.HEARTBEAT, from_=3, term=5))
    assert r1.state == RaftState.FOLLOWER
    assert r1.term == 5
    assert r1.leader_id == 3


def test_lower_term_message_ignored():
    nt = make_network(3)
    nt.elect(1)
    r1 = nt.nodes[1]
    before = r1.term
    r1.handle(pb.Message(type=MT.REQUEST_VOTE, from_=2, term=0))
    assert r1.state == RaftState.LEADER and r1.term == before


def test_disrupted_node_campaign_bumps_cluster_term():
    nt = make_network(3)
    nt.elect(1)
    nt.isolate(3)
    # node 3 times out repeatedly and self-campaigns twice
    nt.nodes[3].handle(pb.Message(type=MT.ELECTION, from_=3))
    nt.nodes[3].handle(pb.Message(type=MT.ELECTION, from_=3))
    nt.nodes[3].msgs = []
    assert nt.nodes[3].term == 3
    nt.heal()
    # when it rejoins with a RequestVote at higher term, leader steps down
    # (no checkQuorum lease protection in this config)
    nt.start(pb.Message(type=MT.ELECTION, to=3, from_=3))
    assert nt.nodes[1].term == nt.nodes[3].term


def test_check_quorum_lease_drops_high_term_request_vote():
    """Last paragraph of section 6 (raft paper): servers disregard RequestVote
    when they believe a current leader exists within election timeout."""
    nt = make_network(3, check_quorum=True)
    nt.elect(1)
    # follower 2 recently heard from the leader
    r2 = nt.nodes[2]
    r2.handle(pb.Message(type=MT.REQUEST_VOTE, from_=3, term=99, log_index=99, log_term=99))
    assert r2.term == 1  # dropped, no term bump
    assert not any(m.type == MT.REQUEST_VOTE_RESP for m in r2.msgs)


def test_check_quorum_lease_allows_vote_with_transfer_hint():
    """p42 raft thesis: leadership-transfer campaigns carry the candidate id
    as hint and bypass the lease."""
    nt = make_network(3, check_quorum=True)
    nt.elect(1)
    r2 = nt.nodes[2]
    r2.handle(
        pb.Message(
            type=MT.REQUEST_VOTE, from_=3, term=2, log_index=1, log_term=1, hint=3
        )
    )
    assert r2.term == 2


def test_leader_steps_down_without_quorum():
    nt = make_network(3, check_quorum=True)
    nt.elect(1)
    r1 = nt.nodes[1]
    assert r1.state == RaftState.LEADER
    nt.isolate(2)
    nt.isolate(3)
    # two election timeouts with no responses -> leader loses quorum
    for _ in range(2 * r1.election_timeout):
        r1.tick()
    r1.msgs = []
    assert r1.state == RaftState.FOLLOWER


def test_prevote_isolated_node_does_not_bump_term():
    """Pre-vote alone keeps the partitioned node's term from growing; on
    rejoin the election happens at term+1 (one step), not term+N."""
    nt = make_network(3, pre_vote=True)
    nt.elect(1)
    assert nt.nodes[1].state == RaftState.LEADER
    term_before = nt.nodes[1].term
    nt.isolate(3)
    for _ in range(5):
        nt.nodes[3].handle(pb.Message(type=MT.ELECTION, from_=3))
        nt.nodes[3].msgs = []
    assert nt.nodes[3].term == term_before
    assert nt.nodes[3].state == RaftState.PRE_VOTE_CANDIDATE
    nt.heal()
    nt.start(pb.Message(type=MT.ELECTION, to=3, from_=3))
    assert nt.leader() is not None
    assert nt.leader().term == term_before + 1


def test_prevote_with_check_quorum_blocks_disruption():
    """The full non-disruption guarantee: pre-vote + check-quorum lease.
    A rejoining node's RequestPreVote is dropped by lease holders
    (raft.go:1507 dropRequestVoteFromHighTermNode covers pre-votes too)."""
    nt = make_network(3, pre_vote=True, check_quorum=True)
    nt.elect(1)
    term_before = nt.nodes[1].term
    nt.isolate(3)
    for _ in range(5):
        nt.nodes[3].handle(pb.Message(type=MT.ELECTION, from_=3))
        nt.nodes[3].msgs = []
    nt.heal()
    nt.start(pb.Message(type=MT.ELECTION, to=3, from_=3))
    assert nt.nodes[1].state == RaftState.LEADER
    assert nt.nodes[1].term == term_before
    assert nt.nodes[3].state == RaftState.PRE_VOTE_CANDIDATE


def test_prevote_election_succeeds_cluster_wide():
    nt = make_network(3, pre_vote=True)
    nt.elect(2)
    assert nt.nodes[2].state == RaftState.LEADER
    assert nt.nodes[2].term == 1


def test_prevote_candidate_state_and_no_term_change_on_reject():
    r = new_raft(1, [1, 2, 3], pre_vote=True)
    r.handle(pb.Message(type=MT.ELECTION, from_=1))
    assert r.state == RaftState.PRE_VOTE_CANDIDATE
    assert r.term == 0
    reqs = [m for m in r.msgs if m.type == MT.REQUEST_PREVOTE]
    assert len(reqs) == 2 and all(m.term == 1 for m in reqs)
    r.handle(pb.Message(type=MT.REQUEST_PREVOTE_RESP, from_=2, term=0, reject=True))
    r.handle(pb.Message(type=MT.REQUEST_PREVOTE_RESP, from_=3, term=0, reject=True))
    assert r.state == RaftState.FOLLOWER
    assert r.term == 0


def test_prevote_quorum_starts_real_campaign():
    r = new_raft(1, [1, 2, 3], pre_vote=True)
    r.handle(pb.Message(type=MT.ELECTION, from_=1))
    r.handle(pb.Message(type=MT.REQUEST_PREVOTE_RESP, from_=2, term=1))
    assert r.state == RaftState.CANDIDATE
    assert r.term == 1


def test_non_voting_never_campaigns():
    r = new_raft(4, [1, 2, 3], non_votings=[4], is_non_voting=True)
    for _ in range(100):
        r.tick()
    assert r.state == RaftState.NON_VOTING
    assert not any(m.type == MT.REQUEST_VOTE for m in r.msgs)


def test_witness_never_campaigns_but_votes():
    r = new_raft(4, [1, 2, 3], witnesses=[4], is_witness=True)
    for _ in range(100):
        r.tick()
    assert r.state == RaftState.WITNESS
    r.handle(pb.Message(type=MT.REQUEST_VOTE, from_=2, term=3, log_index=0, log_term=0))
    resp = [m for m in r.msgs if m.type == MT.REQUEST_VOTE_RESP]
    assert len(resp) == 1 and not resp[0].reject


def test_randomized_timeout_in_range():
    import random

    r = new_raft(1, [1, 2, 3], election=10, rng=lambda n: random.randrange(n))
    seen = set()
    for _ in range(200):
        r.set_randomized_election_timeout()
        seen.add(r.randomized_election_timeout)
        assert 10 <= r.randomized_election_timeout < 20
    assert len(seen) > 3


def test_election_skipped_with_unapplied_committed_entries():
    """raft.go:1632-1645: campaigns are skipped while config changes may be
    committed-but-unapplied (conservative committed>applied check)."""
    nt = make_network(3)
    nt.auto_apply = False
    nt.elect(1)
    nt.propose(1)
    r2 = nt.nodes[2]
    assert r2.log.committed > r2.applied
    r2.handle(pb.Message(type=MT.ELECTION, from_=2))
    assert r2.state == RaftState.FOLLOWER  # campaign skipped
    r2.applied = r2.log.committed
    r2.handle(pb.Message(type=MT.ELECTION, from_=2))
    assert r2.state == RaftState.CANDIDATE
