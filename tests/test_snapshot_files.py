"""External snapshot files (rsm/files.go + ISnapshotFileCollection):
user SMs attach extra files at save time; they are recorded on the
snapshot, shipped through the chunk stream to installing peers, handed
back at recover time, GC'd with their snapshot, and carried through
export/import."""

import json
import os
import struct
import time

from dragonboat_tpu import raftpb as pb
from dragonboat_tpu.config import Config, NodeHostConfig
from dragonboat_tpu.nodehost import NodeHost
from dragonboat_tpu.rsm.statemachine import StateMachine
from dragonboat_tpu.statemachine import IStateMachine, Result

from test_nodehost import wait_leader


class FileKV(IStateMachine):
    """KV whose snapshot stores the dict in an EXTERNAL file; the main
    payload holds only a marker (like the reference's example of large
    side artifacts shipped as snapshot files)."""

    def __init__(self, shard_id=0, replica_id=0):
        self.kv = {}
        self.recovered_files = None
        self._scratch = f"/tmp/filekv-{os.getpid()}-{id(self)}.json"

    def update(self, entry):
        k, v = entry.cmd.decode().split("=", 1)
        self.kv[k] = v
        return Result(value=len(self.kv))

    def lookup(self, q):
        return self.kv.get(q)

    def save_snapshot(self, w, files, done):
        with open(self._scratch, "w") as f:
            json.dump(self.kv, f)
        files.add_file(1, self._scratch, b"kv-image")
        w.write(struct.pack("<I", 0xF11E))

    def recover_from_snapshot(self, r, files, done):
        (marker,) = struct.unpack("<I", r.read(4))
        assert marker == 0xF11E
        self.recovered_files = list(files)
        main = next(f for f in files if f.file_id == 1)
        assert main.metadata == b"kv-image"
        with open(main.filepath) as f:
            self.kv = json.load(f)


def test_files_roundtrip_local(tmp_path):
    sm = StateMachine(1, 1, FileKV())
    for i in range(5):
        sm.handle([pb.Entry(term=1, index=i + 1, cmd=f"k{i}=v{i}".encode())])
    path = str(tmp_path / "snap.gbsnap")
    index, term, membership, files = sm.save_snapshot_with_files(path)
    assert len(files) == 1 and files[0].file_id == 1
    assert files[0].filepath == path + ".xf1"
    assert files[0].file_size == os.path.getsize(path + ".xf1")

    sm2 = StateMachine(1, 1, FileKV())
    ss = pb.Snapshot(index=index, term=term, membership=membership,
                     filepath=path, files=files)
    sm2.recover_from_snapshot(path, ss)
    assert sm2.lookup("k4") == "v4"
    assert sm2.sm.recovered_files is not None


def test_files_ship_through_chunked_install():
    """A lagging replica recovers the external file via the chunk
    stream (sender concatenates, receiver splits)."""
    addrs = {i: f"sf-{time.monotonic_ns()}-{i}" for i in (1, 2, 3)}
    hosts = {}
    for rid, addr in addrs.items():
        nh = NodeHost(NodeHostConfig(raft_address=addr, rtt_millisecond=5))
        nh.start_replica(addrs, False, FileKV, Config(
            shard_id=1, replica_id=rid, election_rtt=10, heartbeat_rtt=1,
            snapshot_entries=6, compaction_overhead=2))
        hosts[rid] = nh
    try:
        lid = wait_leader(hosts)
        lag = next(r for r in hosts if r != lid)
        hosts[lag].close()
        del hosts[lag]
        s = hosts[lid].get_noop_session(1)
        for i in range(30):
            hosts[lid].sync_propose(s, f"d{i}=v{i}".encode(), timeout_s=10)
        nh2 = NodeHost(NodeHostConfig(raft_address=addrs[lag],
                                      rtt_millisecond=5))
        nh2.start_replica(addrs, False, FileKV, Config(
            shard_id=1, replica_id=lag, election_rtt=10, heartbeat_rtt=1,
            snapshot_entries=6, compaction_overhead=2))
        hosts[lag] = nh2
        deadline = time.time() + 20
        while time.time() < deadline and nh2.stale_read(1, "d29") != "v29":
            time.sleep(0.05)
        assert nh2.stale_read(1, "d29") == "v29", \
            "lagger never caught up via the file-carrying snapshot"
        node = nh2._node(1)
        assert node.sm.sm.recovered_files, \
            "external file never reached the installing SM"
        got = node.sm.sm.recovered_files[0]
        assert got.metadata == b"kv-image" and os.path.exists(got.filepath)
    finally:
        for nh in hosts.values():
            nh.close()


def test_files_gc_with_superseded_snapshots(tmp_path):
    """Startup GC removes .xf companions of superseded snapshots and
    keeps the live one's."""
    addr = f"sfgc-{time.monotonic_ns()}"
    nh = NodeHost(NodeHostConfig(raft_address=addr, rtt_millisecond=2))
    try:
        nh.start_replica({1: addr}, False, FileKV, Config(
            shard_id=1, replica_id=1, election_rtt=10, heartbeat_rtt=1,
            snapshot_entries=4, compaction_overhead=1))
        deadline = time.time() + 10
        while time.time() < deadline and not nh.get_leader_id(1)[1]:
            time.sleep(0.02)
        s = nh.get_noop_session(1)
        for i in range(20):   # several snapshot generations
            nh.sync_propose(s, f"g{i}=v{i}".encode(), timeout_s=10)
        node = nh._node(1)
        snapdir = node.snapshot_dir
        live = nh.logdb.get_snapshot(1, 1)
        assert live is not None and live.files
        # restart-time GC: a fresh Node in the same dir prunes orphans
        node._gc_snapshot_dir(live)
        xfs = [fn for fn in os.listdir(snapdir) if ".gbsnap.xf" in fn]
        live_base = os.path.basename(live.filepath)
        assert xfs == [f"{live_base}.xf1"], xfs
    finally:
        nh.close()


def test_files_survive_export_import(tmp_path):
    """sync_request_snapshot(export) carries the external file; tools
    import places it next to the imported image and the restarted
    single-member shard recovers through it."""
    from dragonboat_tpu import tools

    root = str(tmp_path / "nh")
    addr = f"sfx-{time.monotonic_ns()}"
    nh = NodeHost(NodeHostConfig(raft_address=addr, node_host_dir=root,
                                 rtt_millisecond=2))
    export_dir = tmp_path / "export"
    export_dir.mkdir()
    export_path = str(export_dir / "exported.gbsnap")
    try:
        nh.start_replica({1: addr}, False, FileKV, Config(
            shard_id=1, replica_id=1, election_rtt=10, heartbeat_rtt=1))
        deadline = time.time() + 10
        while time.time() < deadline and not nh.get_leader_id(1)[1]:
            time.sleep(0.02)
        s = nh.get_noop_session(1)
        for i in range(8):
            nh.sync_propose(s, f"e{i}=v{i}".encode(), timeout_s=10)
        nh.sync_request_snapshot(1, export_path=export_path, timeout_s=10)
        assert os.path.exists(export_path + ".xf1")
    finally:
        nh.close()

    tools.import_snapshot(
        NodeHostConfig(raft_address=addr, node_host_dir=root),
        export_path, {1: addr}, 1)
    nh2 = NodeHost(NodeHostConfig(raft_address=addr, node_host_dir=root,
                                  rtt_millisecond=2))
    try:
        nh2.start_replica({1: addr}, False, FileKV, Config(
            shard_id=1, replica_id=1, election_rtt=10, heartbeat_rtt=1))
        deadline = time.time() + 10
        while time.time() < deadline and nh2.stale_read(1, "e7") != "v7":
            time.sleep(0.05)
        assert nh2.stale_read(1, "e7") == "v7"
        assert nh2._node(1).sm.sm.recovered_files
    finally:
        nh2.close()


def test_gc_sweeps_superseded_installed_snapshots():
    """Installed snapshots land as incoming-*; once superseded by a
    newer local snapshot they must be swept like snapshot-* files (they
    previously lingered forever)."""
    addr = f"sfin-{time.monotonic_ns()}"
    nh = NodeHost(NodeHostConfig(raft_address=addr, rtt_millisecond=2))
    try:
        nh.start_replica({1: addr}, False, FileKV, Config(
            shard_id=1, replica_id=1, election_rtt=10, heartbeat_rtt=1,
            snapshot_entries=4, compaction_overhead=1))
        deadline = time.time() + 10
        while time.time() < deadline and not nh.get_leader_id(1)[1]:
            time.sleep(0.02)
        s = nh.get_noop_session(1)
        for i in range(10):
            nh.sync_propose(s, f"q{i}=v{i}".encode(), timeout_s=10)
        node = nh._node(1)
        snapdir = node.snapshot_dir
        # plant a stale installed snapshot + companion for THIS replica
        # and a foreign shard's file that must survive
        stale = os.path.join(
            snapdir, f"incoming-{1:016X}-{1:016X}-{3:016X}.gbsnap")
        open(stale, "wb").write(b"stale")
        open(stale + ".xf1", "wb").write(b"stale-xf")
        foreign = os.path.join(
            snapdir, f"incoming-{2:016X}-{9:016X}-{3:016X}.gbsnap")
        open(foreign, "wb").write(b"other-shard")
        live = nh.logdb.get_snapshot(1, 1)
        node._gc_snapshot_dir(live)
        names = set(os.listdir(snapdir))
        assert os.path.basename(stale) not in names
        assert os.path.basename(stale) + ".xf1" not in names
        assert os.path.basename(foreign) in names
        assert os.path.basename(live.filepath) in names
    finally:
        nh.close()
