"""Capacity & compilation observability (capacity.py): the contracts
model vs measured device bytes at three geometries, the compile
tracker's retrace semantics, the steady-state one-compile-per-entry
regression on live engines at both pipeline depths, the
/debug/capacity + /healthz endpoints, the doctor CLIs, and the strict
schema validator."""

import importlib.util
import json
import os
import time
import urllib.error
import urllib.request

import pytest

import jax

from dragonboat_tpu import capacity, flight, telemetry
from dragonboat_tpu.core import health, kstate
from dragonboat_tpu.core.params import KernelParams

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _isolate_global_tracker():
    """Live engines in this module wrap into the process-wide
    capacity.TRACKER; drop their states/spans afterwards so later
    modules' /trace exports see only their own compile spans."""
    yield
    capacity.TRACKER.clear()


def _load_script(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(ROOT, "scripts", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ---------------------------------------------------------------------
# contracts model vs measured bytes (the differential the ISSUE pins)

GEOMETRIES = [
    ("default", KernelParams(), 4),
    ("inline-payloads", KernelParams(inline_payloads=True), 6),
    ("custom", KernelParams(num_peers=5, log_cap=256, readindex_cap=8), 3),
]


@pytest.mark.parametrize("name,kp,groups", GEOMETRIES,
                         ids=[g[0] for g in GEOMETRIES])
def test_model_matches_measured_bytes(name, kp, groups):
    """Analytic bytes-per-group from the CONTRACTS grammar must track
    what the constructors actually allocate, within 1%, per class."""
    state = kstate.init_state(kp, groups, replica_id=1,
                              peer_ids=list(range(1, kp.num_peers + 1)))
    box = kstate.empty_inbox(kp, groups)
    inp = kstate.empty_input(kp, groups)
    digest = health.empty_digest(groups)
    trees = {"ShardState": state, "Inbox": box, "StepInput": inp,
             "HealthDigest": digest}
    per = capacity.model_bytes_per_group(kp)
    for cls, tree in trees.items():
        predicted = per[cls] * groups
        measured = capacity.measure_tree_bytes(tree)
        assert measured > 0, f"{cls}: empty measurement"
        delta = abs(predicted - measured) / measured
        assert delta <= 0.01, (
            f"{name}/{cls}: predicted {predicted} vs measured {measured} "
            f"({delta:.2%} off)")


def test_predict_and_max_g_consistency():
    kp = KernelParams()
    per = capacity.model_bytes_per_group(kp, capacity.RESIDENT_CLASSES)
    total = per["total"]
    assert total == sum(per[c] for c in capacity.RESIDENT_CLASSES)
    assert capacity.predict_bytes(kp, 7, capacity.RESIDENT_CLASSES) \
        == 7 * total
    # max_g * per_group fits the budget; one more group does not
    budget = 1000 * total + total // 2
    g = capacity.max_g_for_budget(kp, budget)
    assert g == 1000
    assert g * total <= budget < (g + 1) * total
    assert capacity.max_g_for_budget(kp, 0) == 0


def test_measure_tree_bytes_tolerates_none_and_scalars():
    assert capacity.measure_tree_bytes(None) == 0
    assert capacity.measure_tree_bytes({"a": None, "b": 3}) == 0
    arr = jax.numpy.zeros((4, 2), jax.numpy.int32)
    assert capacity.measure_tree_bytes((arr, None), arr) == 2 * arr.nbytes


# ---------------------------------------------------------------------
# compile tracker unit semantics (injected clock / registry / recorder)


class _FakeJit:
    """Callable with a jit-style executable cache: compiles whenever
    told to, so tests script exact compile/clean sequences."""

    def __init__(self):
        self.cache = 0
        self.compile_next = True

    def _cache_size(self):
        return self.cache

    def __call__(self):
        if self.compile_next:
            self.cache += 1
            self.compile_next = False
        return self.cache


class _Recorder:
    def __init__(self):
        self.records = []

    def record(self, kind, **fields):
        self.records.append(dict(fields, kind=kind))
        return len(self.records) - 1


def _mk_tracker():
    clock = {"t": 0}

    def tick():
        clock["t"] += 10
        return clock["t"]

    rec = _Recorder()
    reg = telemetry.Registry()
    return capacity.CompileTracker(clock=tick, registry=reg,
                                   recorder=rec), rec, reg


def test_tracker_counts_compiles_and_edge_triggers_storm():
    tracker, rec, reg = _mk_tracker()
    fn = _FakeJit()
    entry = tracker.wrap("step", fn)
    entry()                      # first compile: expected, not a retrace
    entry()                      # clean call -> steady state
    st = entry.stats()
    assert st["calls"] == 2 and st["compiles"] == 1
    assert st["retraces"] == 0 and st["compile_us_total"] == 10
    assert rec.records == []
    fn.compile_next = True
    entry()                      # compile after steady state: retrace
    st = entry.stats()
    assert st["compiles"] == 2 and st["retraces"] == 1
    assert [r["kind"] for r in rec.records] == [capacity.RETRACE_STORM]
    assert rec.records[0]["entry"] == "step"
    assert rec.records[0]["tick"] == 3    # call count, not wall clock
    entry()                      # clean
    fn.compile_next = True
    entry()                      # second retrace: storm already latched
    assert entry.stats()["retraces"] == 2
    assert len(rec.records) == 1, "storm flight event must edge-trigger"
    # the compile histogram carries every compile under the entry label
    expo = reg.exposition()
    assert 'compile_us_count{entry="step"} 3' in expo


def test_tracker_per_wrap_counters_are_independent():
    tracker, rec, _ = _mk_tracker()
    fn = _FakeJit()
    a = tracker.wrap("step", fn)
    a()
    a()
    # a NEW engine wrapping the same function: its first compile (cache
    # grows under ITS call) must not count as a retrace of `a`
    b = tracker.wrap("step", fn)
    fn.compile_next = True
    b()
    assert b.stats()["compiles"] == 1 and b.stats()["retraces"] == 0
    assert a.stats()["compiles"] == 1 and a.stats()["retraces"] == 0
    assert rec.records == []
    # snapshot aggregates the two wraps under one entry label
    snap = tracker.snapshot()
    assert snap["step"]["calls"] == 3 and snap["step"]["compiles"] == 2


def test_tracker_counts_functions_without_cache_probe():
    tracker, rec, _ = _mk_tracker()
    entry = tracker.wrap("plain", lambda: 7)
    assert entry() == 7
    st = entry.stats()
    assert st["calls"] == 1 and st["compiles"] == 0
    assert tracker.chrome_events() == []


def test_tracker_chrome_events_are_valid_spans():
    from dragonboat_tpu.lifecycle import validate_chrome_trace

    tracker, _, _ = _mk_tracker()
    fn = _FakeJit()
    entry = tracker.wrap("step", fn)
    entry()
    entry()
    fn.compile_next = True
    entry()
    events = tracker.chrome_events()
    assert len(events) == 2
    assert validate_chrome_trace({"traceEvents": events}) == 2
    assert events[0]["pid"] == "compile" and events[0]["tid"] == "step"
    assert events[0]["args"]["retrace"] is False
    assert events[1]["args"]["retrace"] is True


# ---------------------------------------------------------------------
# snapshot assembly, merge, exposition, strict validation


def _entries(**over):
    base = {"calls": 10, "compiles": 1, "retraces": 0,
            "compile_us_total": 500, "last_compile_us": 500}
    base.update(over)
    return base


def test_engine_snapshot_trips_watermark_on_budget():
    kp = KernelParams()
    snap = capacity.engine_snapshot(
        kp, 4, live_bytes=950, peak_bytes=960, entries={},
        budget_bytes=1000, watermark_pct=10.0, ticks=3)
    capacity.validate_capacity(snap)
    assert snap["memory_pressure"] is True and snap["headroom_pct"] < 10
    assert snap["model_predicted_bytes"] == \
        snap["model_bytes_per_group"] * 4
    assert snap["model_max_g_at_budget"] == \
        1000 // snap["model_bytes_per_group"]
    roomy = capacity.engine_snapshot(
        kp, 4, live_bytes=10, peak_bytes=10, entries={},
        budget_bytes=1 << 30, ticks=4)
    assert roomy["memory_pressure"] is False
    storm = capacity.engine_snapshot(
        kp, 4, live_bytes=10, peak_bytes=10,
        entries={"step": _entries(retraces=2)}, ticks=5)
    assert storm["retrace_storm"] is True


def test_merge_into_sums_footprints_and_tags_entries():
    base = capacity.empty_dict()
    kp = KernelParams()
    a = capacity.engine_snapshot(kp, 4, 100, 120,
                                 {"step": _entries()}, ticks=2)
    b = capacity.engine_snapshot(kp, 2, 50, 60,
                                 {"step": _entries(retraces=1)}, ticks=5)
    capacity.merge_into(base, a, engine="kernel")
    capacity.merge_into(base, b, engine="mesh")
    capacity.validate_capacity(base)
    assert base["ticks"] == 5 and base["capacity"] == 6
    assert base["bytes_in_use"] == 150 and base["bytes_peak"] == 180
    assert base["retrace_storm"] is True
    assert set(base["entries"]) == {"kernel:step", "mesh:step"}
    assert base["model_predicted_bytes"] == \
        a["model_predicted_bytes"] + b["model_predicted_bytes"]


def test_register_exposition_idempotent_and_renders_gauges():
    reg = telemetry.Registry()
    snap = capacity.engine_snapshot(
        KernelParams(), 4, 2048, 4096,
        {"step": _entries(), "fleet_stats": _entries(retraces=1)},
        ticks=1)
    capacity.register_exposition(reg, lambda: snap)
    # idempotent: a second claim with a different source is a no-op
    capacity.register_exposition(reg, lambda: None)
    expo = reg.exposition()
    assert "capacity_bytes_in_use 2048" in expo
    assert "capacity_bytes_peak 4096" in expo
    assert 'capacity_compile_total{entry="step"} 1' in expo
    assert 'capacity_retrace_total{entry="fleet_stats"} 1' in expo
    # replace=True re-points (the NodeHost merged view claims the names
    # over any engine's device-only registration)
    capacity.register_exposition(reg, lambda: None, replace=True)
    assert "capacity_bytes_in_use 0" in reg.exposition()


def test_validate_capacity_is_strict():
    good = capacity.empty_dict()
    capacity.validate_capacity(good)
    missing = capacity.empty_dict()
    del missing["bytes_peak"]
    with pytest.raises(ValueError, match="bytes_peak"):
        capacity.validate_capacity(missing)
    boolish = capacity.empty_dict()
    boolish["ticks"] = True        # bool is an int subclass: reject
    with pytest.raises(ValueError, match="ticks"):
        capacity.validate_capacity(boolish)
    extra = capacity.empty_dict()
    extra["surprise"] = 1
    with pytest.raises(ValueError, match="surprise"):
        capacity.validate_capacity(extra)
    flagless = capacity.empty_dict()
    flagless["memory_pressure"] = 0
    with pytest.raises(ValueError, match="memory_pressure"):
        capacity.validate_capacity(flagless)
    badent = capacity.empty_dict()
    badent["entries"]["step"] = dict(_entries(), junk=1)
    with pytest.raises(ValueError, match="junk"):
        capacity.validate_capacity(badent)
    shorted = capacity.empty_dict()
    shorted["entries"]["step"] = {"calls": 1}
    with pytest.raises(ValueError, match="compiles"):
        capacity.validate_capacity(shorted)


# ---------------------------------------------------------------------
# live engines: steady state compiles each entry EXACTLY once per
# geometry, at both pipeline depths


def _clear_jit_caches():
    from dragonboat_tpu.core import fleet, kernel

    for fn in (kernel.step, kernel.step_donated, fleet.fleet_stats,
               health.fleet_health):
        clear = getattr(fn, "_clear_cache", None)
        if clear is not None:
            clear()


def _wait(cond, timeout):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return True
        time.sleep(0.1)
    return cond()


def _single_host(prefix, depth, groups):
    import sys
    sys.path.insert(0, os.path.join(ROOT, "tests"))
    from dragonboat_tpu.config import Config, ExpertConfig, NodeHostConfig
    from dragonboat_tpu.nodehost import NodeHost

    from test_nodehost import KVStateMachine

    nh = NodeHost(NodeHostConfig(
        raft_address=f"{prefix}-1", rtt_millisecond=5, enable_metrics=True,
        expert=ExpertConfig(kernel_log_cap=64, kernel_capacity=groups,
                            fleet_stats_every=2,
                            kernel_pipeline_depth=depth)))
    nh.start_replica({1: f"{prefix}-1"}, False, KVStateMachine, Config(
        shard_id=1, replica_id=1, election_rtt=10, heartbeat_rtt=2,
        device_resident=True))
    return nh


@pytest.mark.parametrize("depth", [0, 1])
def test_steady_state_compiles_each_entry_once_per_geometry(depth):
    """50-step steady-state run: every active jit entry compiles exactly
    once, zero retraces, no retrace_storm flight event — then a SECOND
    engine at a different geometry compiles its own entries exactly once
    without tripping the first engine's counters."""
    _clear_jit_caches()
    seq0 = flight.RECORDER.next_seq
    active = "step_donated" if depth > 0 else "step"
    idle = "step" if depth > 0 else "step_donated"
    nh = _single_host(f"cap{depth}", depth, groups=4)
    try:
        assert _wait(lambda: nh.get_leader_id(1)[1], 45), "no leader"
        eng = nh.kernel_engine
        assert _wait(lambda: eng._capacity_seq >= 25, 60), \
            "fewer than 50 steady-state steps"
        with eng.mu:
            snap = eng.last_capacity
        capacity.validate_capacity(snap)
        ent = snap["entries"]
        assert ent[active]["compiles"] == 1, ent[active]
        assert ent[active]["retraces"] == 0
        assert ent[active]["calls"] >= 50
        assert ent[idle]["calls"] == 0
        for name in ("fleet_stats", "fleet_health"):
            assert ent[name]["compiles"] == 1, (name, ent[name])
            assert ent[name]["retraces"] == 0
        assert snap["retrace_storm"] is False
        assert snap["ticks"] == eng._capacity_seq
        assert snap["bytes_in_use"] > 0
        assert snap["model_predicted_bytes"] == snap["bytes_in_use"], \
            "contracts model must match the resident trees exactly"
    finally:
        # stop engine 1 before engine 2 compiles the shared jit entries:
        # cache growth is attributed to whichever call window it lands
        # in, so an overlapping compile would smear into eng1's counters
        nh.close()

    # second geometry: a fresh engine at a different capacity pays its
    # own single compile per entry — no retrace anywhere
    nh2 = _single_host(f"cap{depth}b", depth, groups=8)
    try:
        assert _wait(lambda: nh2.get_leader_id(1)[1], 45)
        eng2 = nh2.kernel_engine
        assert _wait(lambda: eng2._capacity_seq >= 5, 60)
        with eng2.mu:
            snap2 = eng2.last_capacity
        assert snap2["entries"][active]["compiles"] == 1
        assert snap2["entries"][active]["retraces"] == 0
        assert snap2["retrace_storm"] is False
    finally:
        nh2.close()
    # the first engine's counters are untouched by engine 2's compiles
    # (per-wrap independence)
    with eng.mu:
        snap = eng.last_capacity
    assert snap["entries"][active]["compiles"] == 1
    assert snap["entries"][active]["retraces"] == 0
    storms = [r for r in flight.RECORDER.tail()
              if r["kind"] == flight.RETRACE_STORM
              and r["seq"] >= seq0]
    assert storms == [], storms


def test_compile_cache_env_veto(monkeypatch):
    """DRAGONBOAT_TPU_COMPILE_CACHE=0 vetoes the persistent compile
    cache (scale_100k / tpu_pallas_ab / ExpertConfig.compile_cache all
    route through this helper); the cache dir is CPU-fingerprinted and
    stable within a box."""
    from dragonboat_tpu import hostenv

    monkeypatch.setenv("DRAGONBOAT_TPU_COMPILE_CACHE", "0")
    assert hostenv.enable_compile_cache() is None
    assert hostenv.jax_cache_dir("/tmp/x") == hostenv.jax_cache_dir("/tmp/x")
    assert hostenv.jax_cache_dir("/tmp/x").startswith("/tmp/x_")


def test_donated_cache_purge(tmp_path):
    """Persisted executables for donated entries are purged whenever a
    process points jax at the cache: jax 0.4.37's deserialization
    breaks donated-buffer aliasing (wrong results, then a segfault on
    the first result read), so donated entries must compile fresh in
    every process.  Non-donated entries stay cached."""
    from dragonboat_tpu import hostenv

    keep = tmp_path / "jit_step-aaaa-cache"
    drop1 = tmp_path / "jit_step_donated-bbbb-cache"
    drop2 = tmp_path / "jit_jit_serve_step_donated-cccc-atime"
    for p in (keep, drop1, drop2):
        p.write_bytes(b"x")
    n = hostenv.purge_donated_cache_entries(str(tmp_path))
    assert n == 2
    assert keep.exists() and not drop1.exists() and not drop2.exists()
    # idempotent on an already-clean (or missing) dir
    assert hostenv.purge_donated_cache_entries(str(tmp_path)) == 0
    assert hostenv.purge_donated_cache_entries(str(tmp_path / "nope")) == 0


# ---------------------------------------------------------------------
# endpoints + doctor CLIs (synthetic sources, no cluster)


def _mk_server(cap_snapshot):
    from dragonboat_tpu.server.metrics_http import MetricsServer

    state = {"cap": cap_snapshot}
    info = {"node_host_id": "nhid-test", "raft_address": "t-1",
            "health": health.empty_dict(),
            "shards": [{"shard_id": 1, "replica_id": 2, "leader_id": 3,
                        "term": 4, "is_leader": False, "last_applied": 5,
                        "membership": {"addresses": {1: "t-1"},
                                       "non_votings": {}, "witnesses": {},
                                       "config_change_id": 1},
                        "resident": "host"}]}
    srv = MetricsServer([], address="127.0.0.1:0",
                        health_source=health.empty_dict,
                        capacity_source=lambda: state["cap"],
                        info_source=lambda: dict(
                            info, capacity=state["cap"]))
    return srv, state


def test_debug_capacity_roundtrip_and_healthz_degradation():
    srv, state = _mk_server(capacity.empty_dict())
    try:
        got = json.loads(urllib.request.urlopen(
            f"http://{srv.address}/debug/capacity", timeout=5).read())
        capacity.validate_capacity(got)
        assert got == json.loads(json.dumps(state["cap"]))
        ok = urllib.request.urlopen(f"http://{srv.address}/healthz",
                                    timeout=5)
        assert ok.status == 200 and ok.read() == b"ok\n"
        # memory pressure AND retrace storm each degrade /healthz
        for flag in ("memory_pressure", "retrace_storm"):
            bad = capacity.empty_dict()
            bad[flag] = True
            state["cap"] = bad
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(f"http://{srv.address}/healthz",
                                       timeout=5)
            assert ei.value.code == 503
            body = json.loads(ei.value.read())
            assert body["capacity"]["tripped"] == [flag]
        state["cap"] = capacity.empty_dict()
        assert urllib.request.urlopen(f"http://{srv.address}/healthz",
                                      timeout=5).status == 200
    finally:
        srv.close()


def test_metrics_dump_capacity_and_fleet_doctor(capsys):
    import sys

    md = _load_script("metrics_dump")
    fd = _load_script("fleet_doctor")
    srv, state = _mk_server(capacity.empty_dict())
    argv = sys.argv
    try:
        # clean snapshot: validates, exits 0
        sys.argv = ["metrics_dump.py", srv.address, "--capacity"]
        assert md.main() == 0
        out = capsys.readouterr()
        assert "ok: 0 compile entrie(s)" in out.err
        assert json.loads(out.out)["bytes_in_use"] == 0
        # doctor renders the capacity block and exits 0
        sys.argv = ["fleet_doctor.py", srv.address]
        assert fd.main() == 0
        out = capsys.readouterr().out
        assert "capacity: OK" in out
        # degraded on retrace storm: both CLIs exit 1
        bad = capacity.empty_dict()
        bad["retrace_storm"] = True
        bad["entries"]["kernel:step"] = _entries(retraces=3)
        state["cap"] = bad
        sys.argv = ["metrics_dump.py", srv.address, "--capacity"]
        assert md.main() == 1
        out = capsys.readouterr()
        assert "degraded: retrace_storm" in out.err
        sys.argv = ["fleet_doctor.py", srv.address]
        assert fd.main() == 1
        out = capsys.readouterr().out
        assert "DEGRADED (retrace_storm)" in out
        assert "kernel:step" in out
        # memory pressure degrades the same way
        bad2 = capacity.empty_dict()
        bad2["memory_pressure"] = True
        state["cap"] = bad2
        sys.argv = ["fleet_doctor.py", srv.address]
        assert fd.main() == 1
        capsys.readouterr()
        # schema drift is exit 1 (dump) / 2 (doctor), not a crash
        state["cap"] = dict(capacity.empty_dict(), surprise=1)
        sys.argv = ["metrics_dump.py", srv.address, "--capacity"]
        assert md.main() == 1
        assert "schema validation failed" in capsys.readouterr().err
        sys.argv = ["fleet_doctor.py", srv.address]
        assert fd.main() == 2
        capsys.readouterr()
    finally:
        sys.argv = argv
        srv.close()


def test_trace_endpoint_merges_compile_spans():
    from dragonboat_tpu.lifecycle import validate_chrome_trace
    from dragonboat_tpu.server.metrics_http import MetricsServer

    tracker, _, _ = _mk_tracker()
    fn = _FakeJit()
    tracker.wrap("step", fn)()
    srv = MetricsServer([], address="127.0.0.1:0",
                        compile_tracker=tracker)
    try:
        trace = json.loads(urllib.request.urlopen(
            f"http://{srv.address}/trace", timeout=5).read())
        assert validate_chrome_trace(trace) >= 1
        compiles = [e for e in trace["traceEvents"]
                    if e.get("cat") == "compile"]
        assert len(compiles) == 1
        assert compiles[0]["name"] == "compile:step"
    finally:
        srv.close()
