"""Gossip registry: NodeHostID-based dynamic addressing
(internal/registry/gossip.go behavior over a self-contained UDP
anti-entropy protocol).
"""

import socket
import time

import pytest

from dragonboat_tpu.config import Config, GossipConfig, NodeHostConfig
from dragonboat_tpu.gossip import GossipManager, GossipRegistry
from dragonboat_tpu.nodehost import NodeHost

from test_nodehost import KVStateMachine, wait_leader


def free_udp_ports(n):
    socks = [socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
             for _ in range(n)]
    ports = []
    for s in socks:
        s.bind(("127.0.0.1", 0))
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def test_gossip_view_propagates():
    p1, p2, p3 = free_udp_ports(3)
    m1 = GossipManager("nhid-a", "addr-a:1", f"127.0.0.1:{p1}")
    m2 = GossipManager("nhid-b", "addr-b:1", f"127.0.0.1:{p2}",
                       seeds=[f"127.0.0.1:{p1}"])
    m3 = GossipManager("nhid-c", "addr-c:1", f"127.0.0.1:{p3}",
                       seeds=[f"127.0.0.1:{p1}"])
    try:
        deadline = time.time() + 10
        while time.time() < deadline:
            if all(m.lookup("nhid-a") and m.lookup("nhid-b")
                   and m.lookup("nhid-c") for m in (m1, m2, m3)):
                break
            time.sleep(0.05)
        for m in (m1, m2, m3):
            assert m.lookup("nhid-a") == "addr-a:1"
            assert m.lookup("nhid-b") == "addr-b:1"
            assert m.lookup("nhid-c") == "addr-c:1"
        # address change re-advertises with a newer version
        m2.set_raft_address("addr-b:2")
        deadline = time.time() + 10
        while time.time() < deadline and m1.lookup("nhid-b") != "addr-b:2":
            time.sleep(0.05)
        assert m1.lookup("nhid-b") == "addr-b:2"
    finally:
        for m in (m1, m2, m3):
            m.close()


def test_gossip_registry_resolves_nhid():
    p1, p2 = free_udp_ports(2)
    m1 = GossipManager("nhid-x", "real-addr:7", f"127.0.0.1:{p1}")
    m2 = GossipManager("nhid-y", "other:9", f"127.0.0.1:{p2}",
                       seeds=[f"127.0.0.1:{p1}"])
    reg = GossipRegistry(m2)
    try:
        reg.add(5, 1, "nhid-x")
        reg.add(5, 2, "plain-addr:3")   # non-nhid targets pass through
        deadline = time.time() + 10
        addr = None
        while time.time() < deadline:
            try:
                addr, _ = reg.resolve(5, 1)
                break
            except KeyError:
                time.sleep(0.05)
        assert addr == "real-addr:7"
        assert reg.resolve(5, 2)[0] == "plain-addr:3"
    finally:
        m1.close()
        reg.close()


def test_cluster_over_nhid_addressing():
    """Full E2E: initial members are NodeHostIDs; gossip resolves them to
    chan-transport addresses; the cluster elects and serves."""
    ports = free_udp_ports(3)
    seed = [f"127.0.0.1:{ports[0]}"]
    hosts = {}
    for i, port in enumerate(ports, start=1):
        nh = NodeHost(NodeHostConfig(
            raft_address=f"gsp-{i}", rtt_millisecond=5,
            address_by_node_host_id=True,
            gossip=GossipConfig(bind_address=f"127.0.0.1:{port}",
                                seed=list(seed)),
        ))
        hosts[i] = nh
    members = {i: hosts[i].id for i in hosts}   # rid -> NodeHostID
    try:
        for rid, nh in hosts.items():
            nh.start_replica(members, False, KVStateMachine, Config(
                shard_id=1, replica_id=rid, election_rtt=10,
                heartbeat_rtt=1))
        lead = wait_leader(hosts, timeout=30)
        nh = hosts[lead]
        sess = nh.get_noop_session(1)
        nh.sync_propose(sess, b"dyn=addr", timeout_s=10)
        assert nh.sync_read(1, "dyn", timeout_s=10) == "addr"
        deadline = time.time() + 10
        while time.time() < deadline:
            if all(h.stale_read(1, "dyn") == "addr" for h in hosts.values()):
                break
            time.sleep(0.05)
        assert all(h.stale_read(1, "dyn") == "addr" for h in hosts.values())
    finally:
        for nh in hosts.values():
            nh.close()


def test_gossip_required_for_nhid_addressing():
    with pytest.raises(Exception):
        NodeHost(NodeHostConfig(raft_address="x-1",
                                address_by_node_host_id=True))


def test_shard_view_merge_semantics():
    """view.go:121 mergeShardView: membership wins by config-change
    index, leadership by higher term; an unknown leader never clobbers
    a known one."""
    from dragonboat_tpu.gossip import ShardView, _merge_shard_view

    cur = ShardView(7, {1: "a", 2: "b"}, config_change_index=3,
                    leader_id=1, term=5)
    # older membership + unknown leader: nothing changes
    out = _merge_shard_view(cur, ShardView(7, {9: "z"}, 2, 0, 9))
    assert out.replicas == {1: "a", 2: "b"} and out.config_change_index == 3
    assert out.leader_id == 1 and out.term == 5
    # newer membership, lower term: membership updates, leadership kept
    out = _merge_shard_view(out, ShardView(7, {1: "a", 3: "c"}, 4, 2, 4))
    assert out.replicas == {1: "a", 3: "c"} and out.config_change_index == 4
    assert out.leader_id == 1 and out.term == 5
    # higher term leader wins
    out = _merge_shard_view(out, ShardView(7, {}, 0, 3, 6))
    assert out.leader_id == 3 and out.term == 6


def test_shard_view_gossips_to_non_hosting_host():
    """VERDICT r3 item 6: a host that never hosts shard 1 learns its
    membership and leadership via the gossip shard view + GetShardInfo
    (internal/registry/nodehost.go:41)."""
    ports = free_udp_ports(3)
    seed = [f"127.0.0.1:{ports[0]}"]
    hosts = {}
    for i, port in enumerate(ports, start=1):
        hosts[i] = NodeHost(NodeHostConfig(
            raft_address=f"sv-{i}", rtt_millisecond=5,
            address_by_node_host_id=True,
            gossip=GossipConfig(bind_address=f"127.0.0.1:{port}",
                                seed=list(seed)),
        ))
    # shard 1 lives on hosts 1 and 2 ONLY; host 3 just gossips
    members = {1: hosts[1].id, 2: hosts[2].id}
    try:
        for rid in (1, 2):
            hosts[rid].start_replica(members, False, KVStateMachine, Config(
                shard_id=1, replica_id=rid, election_rtt=10,
                heartbeat_rtt=1))
        lead = wait_leader({1: hosts[1], 2: hosts[2]}, timeout=30)
        reg, ok = hosts[3].get_node_host_registry()
        assert ok
        deadline = time.time() + 20
        view = None
        while time.time() < deadline:
            view = reg.get_shard_info(1)
            if view is not None and view.leader_id == lead \
                    and len(view.replicas) == 2:
                break
            time.sleep(0.05)
        assert view is not None, "host 3 never learned shard 1"
        assert view.leader_id == lead and view.term > 0
        assert set(view.replicas) == {1, 2}
        # replica addresses are the NodeHostIDs the members registered
        assert view.replicas[1] == hosts[1].id
        assert reg.num_of_shards() >= 1
    finally:
        for nh in hosts.values():
            nh.close()


def test_shard_payload_chunks_under_datagram_limit():
    """A big shard set must span datagrams, not EMSGSIZE (memberlist
    chunks broadcasts the same way)."""
    from dragonboat_tpu.gossip import ShardView

    p1, p2 = free_udp_ports(2)
    many = [ShardView(i, {1: "nhid-" + "x" * 60, 2: "nhid-" + "y" * 60,
                          3: "nhid-" + "z" * 60},
                      config_change_index=5, leader_id=1, term=9)
            for i in range(3000)]
    m1 = GossipManager("nhid-big", "addr-big:1", f"127.0.0.1:{p1}",
                       shard_info_fn=lambda: many)
    m2 = GossipManager("nhid-rx", "addr-rx:1", f"127.0.0.1:{p2}",
                       seeds=[f"127.0.0.1:{p1}"])
    try:
        payloads = m1._payloads()
        assert len(payloads) > 1
        assert all(len(p) <= 65507 for p in payloads)
        # the receiver assembles the whole set from the chunks
        deadline = time.time() + 20
        while time.time() < deadline and m2.num_of_shards() < 3000:
            time.sleep(0.05)
        assert m2.num_of_shards() == 3000
        v = m2.get_shard_info(2999)
        assert v is not None and v.leader_id == 1 and len(v.replicas) == 3
    finally:
        m1.close()
        m2.close()
