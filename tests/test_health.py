"""Fleet health engine (core/health.py): randomized differential vs the
pure-python recount, top-K tie determinism, O(K) transfer shapes, digest
carry through the live engines at both pipeline depths, the honest
/healthz + /debug drill-down endpoints, the doctor CLIs, and the chaos
detector differential."""

import importlib.util
import json
import os
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import jax

from dragonboat_tpu.core import health
from dragonboat_tpu.core import params as KP

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_script(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(ROOT, "scripts", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _perturb(state, rng):
    """Random host-side mutation of the health-relevant columns — the
    differential must hold for ANY state, not just reachable ones."""
    G = state.committed.shape[0]
    fields = {}
    for name in ("committed", "applied", "term", "leader", "last"):
        col = np.array(jax.device_get(getattr(state, name)))
        mask = rng.random(G) < 0.4
        col[mask] = rng.integers(0, 12, mask.sum())
        fields[name] = jax.numpy.asarray(col.astype(np.int32))
    # vacate a group or two so occupancy gating is exercised
    kind = np.array(jax.device_get(state.kind))
    for g in rng.integers(0, G, 2):
        if rng.random() < 0.5:
            kind[g, :] = KP.K_ABSENT
    fields["kind"] = jax.numpy.asarray(kind.astype(np.int32))
    return state._replace(**fields)


@pytest.mark.parametrize("groups,replicas,seed", [(1, 3, 11), (4, 3, 22),
                                                  (8, 5, 33)])
def test_fleet_health_matches_recount_randomized(groups, replicas, seed):
    """Drive real elections, then randomized perturbations, carrying the
    digest across ticks on BOTH sides — report and digest must agree
    byte-for-byte every tick."""
    from tests.kernel_harness import KernelCluster

    c = KernelCluster(groups, replicas)
    for _ in range(30):
        c.step(tick=True)
    rng = np.random.default_rng(seed)
    state = c.state
    inbox = c._build_inbox().from_
    digest = health.empty_digest(c.G)
    for tick in range(6):
        state = _perturb(state, rng)
        report, new_digest = health.fleet_health(state, inbox, digest, k=4)
        got = health.report_to_dict(report)
        want, want_digest = health.recount(
            jax.device_get(state), jax.device_get(inbox),
            jax.device_get(digest), k=4)
        assert got == want, f"tick {tick}: {got} != {want}"
        got_digest = {f: [int(v) for v in jax.device_get(getattr(
            new_digest, f))] for f in health.HealthDigest._fields}
        assert got_digest == want_digest, f"tick {tick} digest"
        digest = new_digest


def test_fleet_health_sharded_two_device_mesh():
    """The jitted pass under a 2-device G-sharded placement (the
    ``part=G`` contract) returns the same report as the recount."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as PS

    from tests.kernel_harness import KernelCluster

    devs = jax.devices()
    if len(devs) < 2:
        pytest.skip("needs 2 devices")
    c = KernelCluster(4, 3)          # G = 12, divisible by 2
    for _ in range(30):
        c.step(tick=True)
    mesh = Mesh(np.array(devs[:2]), ("g",))

    def put(leaf):
        if getattr(leaf, "ndim", 0) >= 1 and leaf.shape[0] == c.G:
            spec = PS("g", *([None] * (leaf.ndim - 1)))
        else:
            spec = PS()
        return jax.device_put(leaf, NamedSharding(mesh, spec))

    state = jax.tree.map(put, c.state)
    inbox = put(c._build_inbox().from_)
    digest = jax.tree.map(put, health.empty_digest(c.G))
    for _ in range(2):
        report, digest = health.fleet_health(state, inbox, digest, k=4)
    got = health.report_to_dict(report)
    ref_digest = health.empty_digest(c.G)
    for _ in range(2):
        want, ref_digest_d = health.recount(
            jax.device_get(state), jax.device_get(inbox),
            jax.device_get(ref_digest), k=4)
        ref_digest = health.HealthDigest(**{
            f: jax.numpy.asarray(np.array(v, np.int32))
            for f, v in ref_digest_d.items()})
    assert got == want


def _synthetic_state(G, leaderless_rows):
    """Minimal stand-in with the columns fleet_health reads: every row
    occupied, ``leaderless_rows`` have no leader."""
    from collections import namedtuple

    S = namedtuple("S", "kind role term vote leader committed applied "
                   "last stable processed snap_index snap_term")
    i32 = np.int32
    leader = np.full(G, 2, i32)
    leader[list(leaderless_rows)] = KP.NO_LEADER
    z = np.zeros(G, i32)
    return S(kind=np.full((G, 3), KP.K_VOTER, i32), role=z,
             term=np.ones(G, i32), vote=z, leader=leader,
             committed=z, applied=z, last=z, stable=z, processed=z,
             snap_index=z, snap_term=z)


def test_top_k_tie_determinism():
    """Equal severity scores order by ascending lane index, stably."""
    G, k = 16, 8
    tied = [3, 7, 11, 14]
    state = _synthetic_state(G, tied)
    inbox = np.zeros((G, 4), np.int32)
    digest = health.empty_digest(G)
    # tick past the leaderless threshold so all four trip with EQUAL
    # scores (identical counters)
    for _ in range(health.DEFAULT_THRESHOLDS.leaderless_ticks + 1):
        report, digest = health.fleet_health(state, inbox, digest, k=k)
    idx = [int(v) for v in jax.device_get(report.worst_idx)]
    score = [int(v) for v in jax.device_get(report.worst_score)]
    assert idx[:4] == tied                 # ascending lane among ties
    assert score[0] == score[3] > 0
    # stable across repeated calls on identical inputs, and the recount
    # agrees on the tie order (digest here is the PRE-tick carry that
    # produced `report`, i.e. the value before the last loop iteration)
    prev = health.empty_digest(G)
    for _ in range(health.DEFAULT_THRESHOLDS.leaderless_ticks):
        _, prev = health.fleet_health(state, inbox, prev, k=k)
    rerun, _ = health.fleet_health(state, inbox, prev, k=k)
    assert health.report_to_dict(rerun) == health.report_to_dict(report)
    want, _ = health.recount(state, inbox, jax.device_get(prev), k=k)
    assert health.report_to_dict(report) == want


def test_report_shapes_are_o_k_not_o_g():
    """The host transfer is O(K) regardless of G (asserted via fetched
    array shapes), and the drill-down row is O(1) scalars."""
    k = 8
    shapes = {}
    for G in (16, 256):
        state = _synthetic_state(G, [1])
        inbox = np.zeros((G, 4), np.int32)
        report, digest = health.fleet_health(state, inbox,
                                             health.empty_digest(G), k=k)
        shapes[G] = [tuple(leaf.shape) for leaf in report]
        row = health.shard_row(state, inbox, digest, np.int32(1))
        assert all(leaf.shape == () for leaf in row)
    assert shapes[16] == shapes[256] == [
        (health.NUM_CLASSES,), (), (), (k,), (k,), (k, health.ROW_WIDTH)]


def test_top_k_clamps_to_small_fleets():
    """k larger than G must clamp, not fail the trace (regression: the
    default k=8 on a capacity-4 engine)."""
    G = 4
    state = _synthetic_state(G, [0])
    report, _ = health.fleet_health(state, np.zeros((G, 4), np.int32),
                                    health.empty_digest(G), k=8)
    assert report.worst_idx.shape == (G,)


# ---------------------------------------------------------------------
# live engines: digest carry at both pipeline depths + shard_info parity


def _cluster(prefix, depth):
    from dragonboat_tpu.config import Config, ExpertConfig, NodeHostConfig
    from dragonboat_tpu.nodehost import NodeHost

    from test_nodehost import KVStateMachine

    addrs = {1: f"{prefix}-1", 2: f"{prefix}-2", 3: f"{prefix}-3"}
    hosts = {rid: NodeHost(NodeHostConfig(
        raft_address=a, rtt_millisecond=5, enable_metrics=True,
        expert=ExpertConfig(kernel_log_cap=256, kernel_capacity=4,
                            fleet_stats_every=5,
                            kernel_pipeline_depth=depth)))
        for rid, a in addrs.items()}
    for rid in addrs:
        hosts[rid].start_replica(addrs, False, KVStateMachine, Config(
            shard_id=1, replica_id=rid, election_rtt=10, heartbeat_rtt=1,
            device_resident=True))
    return hosts


def _wait(cond, timeout):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return True
        time.sleep(0.1)
    return cond()


@pytest.mark.parametrize("depth", [0, 1])
def test_digest_carries_across_decimated_ticks(depth):
    """The per-group digest advances one tick per health collection on
    the live engine — at pipeline depth 0 and through the overlapped
    donating step loop at depth 1."""
    hosts = _cluster(f"hc{depth}", depth)
    try:
        assert _wait(lambda: any(
            h.get_leader_id(1)[1] and h.get_leader_id(1)[0]
            for h in hosts.values()), 45)
        eng = hosts[1].kernel_engine
        assert _wait(lambda: eng._health_seq >= 3, 30), "no health ticks"
        with eng.mu:
            seq = eng._health_seq
            ticks = jax.device_get(eng._health_digest.ticks)
            lane = hosts[1].nodes[1].lane
        # the digest is the carry of exactly the ticks taken; occupied
        # and vacant lanes advance together (ticks is uniform)
        assert int(ticks[lane]) == seq
        assert all(int(t) == seq for t in ticks)
        # healthy steady state: no anomaly classes tripped
        assert _wait(lambda: eng.last_health is not None
                     and not any(eng.last_health["class_count"].values()),
                     10)
    finally:
        for h in hosts.values():
            h.close()


def test_shard_info_matches_device_row_recount():
    """NodeHost.shard_info's device row equals a recount of that row
    from the (test-only) full-state fetch, and round-trips through
    /debug/group/<id> and fleet_doctor --json."""
    hosts = _cluster("hs", 0)
    try:
        lid = None

        def leader():
            nonlocal lid
            for rid, h in hosts.items():
                l, ok = h.get_leader_id(1)
                if ok and l:
                    lid = rid
                    return True
            return False

        assert _wait(leader, 45)
        nh = hosts[lid]
        eng = nh.kernel_engine
        assert _wait(lambda: eng._health_seq >= 1, 30)
        node = nh.nodes[1]
        with eng.mu:
            # snapshot the inbox ONCE (transport threads mutate the host
            # buffer in place) and feed the same copy to both sides; the
            # jnp state pytree is immutable, so sampling it twice under
            # mu is consistent
            inbox_h = np.array(jax.device_get(eng._fleet_inbox_from()))
            row = health.shard_row(eng.state, inbox_h,
                                   eng._health_digest, np.int32(node.lane),
                                   thresholds=eng.health_thresholds)
            state_h = jax.device_get(eng.state)
        got = health.row_to_dict(row)
        g = node.lane
        for f in ("role", "term", "vote", "leader", "committed", "applied",
                  "last", "stable", "processed", "snap_index", "snap_term"):
            assert got[f] == int(getattr(state_h, f)[g]), f
        assert got["inbox_occ"] == int((np.asarray(inbox_h)[g] != 0).sum())

        si = nh.shard_info(1)
        health.validate_shard_info(si)
        assert si["resident"] == "device" and si["device"] is not None
        # HTTP round-trip (json normalizes int membership keys)
        addr = nh.metrics_address
        got_ep = json.loads(urllib.request.urlopen(
            f"http://{addr}/debug/group/1", timeout=5).read())
        health.validate_shard_info(got_ep)
        assert set(got_ep) == set(si)
        assert got_ep["membership"] == json.loads(
            json.dumps(si["membership"]))
        # /debug/groups serves info() with the same schema
        groups = json.loads(urllib.request.urlopen(
            f"http://{addr}/debug/groups", timeout=5).read())
        assert health.validate_info(groups) == 1
        # unknown group -> 404
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f"http://{addr}/debug/group/99",
                                   timeout=5)
        assert ei.value.code == 404
    finally:
        for h in hosts.values():
            h.close()


# ---------------------------------------------------------------------
# /healthz honesty + doctor CLIs (synthetic sources, no cluster)


def _mk_server(snapshot):
    from dragonboat_tpu.server.metrics_http import MetricsServer

    state = {"h": snapshot}
    info = {"node_host_id": "nhid-test", "raft_address": "t-1",
            "health": snapshot,
            "shards": [{"shard_id": 1, "replica_id": 2, "leader_id": 3,
                        "term": 4, "is_leader": False, "last_applied": 5,
                        "membership": {"addresses": {1: "t-1"},
                                       "non_votings": {}, "witnesses": {},
                                       "config_change_id": 1},
                        "resident": "host"}]}
    srv = MetricsServer([], address="127.0.0.1:0",
                        health_source=lambda: state["h"],
                        info_source=lambda: dict(info, health=state["h"]),
                        shard_info_source=lambda sid: None)
    return srv, state


def test_healthz_honest_on_anomalies():
    srv, state = _mk_server(health.empty_dict())
    try:
        ok = urllib.request.urlopen(f"http://{srv.address}/healthz",
                                    timeout=5)
        assert ok.status == 200 and ok.read() == b"ok\n"
        bad = health.empty_dict()
        bad["class_count"]["commit_stall"] = 2
        bad["anomalous"] = 2
        state["h"] = bad
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f"http://{srv.address}/healthz",
                                   timeout=5)
        assert ei.value.code == 503
        body = json.loads(ei.value.read())
        assert body["status"] == "degraded"
        assert body["class_count"]["commit_stall"] == 2
        # back to healthy -> 200 again
        state["h"] = health.empty_dict()
        ok = urllib.request.urlopen(f"http://{srv.address}/healthz",
                                    timeout=5)
        assert ok.status == 200
    finally:
        srv.close()


def test_fleet_doctor_cli_and_metrics_dump_doctor(capsys):
    fd = _load_script("fleet_doctor")
    md = _load_script("metrics_dump")
    srv, state = _mk_server(health.empty_dict())
    try:
        import sys

        argv = sys.argv
        try:
            sys.argv = ["fleet_doctor.py", srv.address]
            assert fd.main() == 0
            out = capsys.readouterr().out
            assert "health: OK" in out and "shard 1" in out
            # degraded fleet: nonzero exit + offender table
            bad = health.empty_dict()
            bad["class_count"]["leaderless"] = 1
            bad["anomalous"] = 1
            bad["worst"] = [dict({f: 0 for f in health.ROW_FIELDS},
                                 lane=3, score=24, flags=1,
                                 classes=["leaderless"], engine="kernel")]
            state["h"] = bad
            sys.argv = ["fleet_doctor.py", srv.address]
            assert fd.main() == 1
            out = capsys.readouterr().out
            assert "DEGRADED" in out and "worst offenders" in out
            # --json round-trips the endpoint payload verbatim
            sys.argv = ["fleet_doctor.py", srv.address, "--json"]
            assert fd.main() == 1
            cli = json.loads(capsys.readouterr().out)
            ep = json.loads(urllib.request.urlopen(
                f"http://{srv.address}/debug/groups", timeout=5).read())
            assert cli == ep
            # metrics_dump --doctor validates strictly and prints JSON
            sys.argv = ["metrics_dump.py", srv.address, "--doctor"]
            assert md.main() == 0
            captured = capsys.readouterr()
            assert json.loads(captured.out) == ep
            assert "ok: 1 shard(s)" in captured.err
        finally:
            sys.argv = argv
    finally:
        srv.close()


def test_schema_validation_is_strict():
    good = health.empty_dict()
    health.validate_health(good)
    bad = health.empty_dict()
    bad["class_count"]["bogus"] = 1
    with pytest.raises(ValueError, match="class_count"):
        health.validate_health(bad)
    bad2 = health.empty_dict()
    bad2["anomalous"] = "3"
    with pytest.raises(ValueError, match="anomalous"):
        health.validate_health(bad2)
    with pytest.raises(ValueError, match="missing key"):
        health.validate_info({"node_host_id": "x", "raft_address": "y",
                              "health": health.empty_dict()})
    with pytest.raises(ValueError, match="resident"):
        health.validate_info({
            "node_host_id": "x", "raft_address": "y",
            "health": health.empty_dict(),
            "shards": [{"shard_id": 1, "replica_id": 1, "leader_id": 0,
                        "term": 0, "last_applied": 0, "is_leader": False,
                        "membership": {"addresses": {}, "non_votings": {},
                                       "witnesses": {},
                                       "config_change_id": 0},
                        "resident": "gpu"}]})


# ---------------------------------------------------------------------
# chaos detector differential


@pytest.mark.parametrize("seed", [1, 2, 3])
def test_detector_differential(seed):
    """Each fault kind raises its mapped anomaly class inside the fault
    window, everything clears after convergence, and the device report
    agrees with the pure-python recount at every sampled instant."""
    from dragonboat_tpu.chaos.runner import (
        DETECTOR_FAULT_CLASS,
        DETECTOR_FAULTS,
        run_detector_differential,
    )

    r = run_detector_differential(seed)
    assert r.fault == DETECTOR_FAULTS[seed % len(DETECTOR_FAULTS)]
    assert r.anomaly_class == DETECTOR_FAULT_CLASS[r.fault]
    assert r.ok, r.failures
    assert r.raised and r.cleared
    assert r.differential_checks >= 2
