"""The examples/ scripts must run end-to-end (a user's first contact)."""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(script, *args, timeout=300):
    env = dict(os.environ, PYTHONPATH="", JAX_PLATFORMS="cpu")
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "examples", script), *args],
        capture_output=True, text=True, env=env, timeout=timeout,
        cwd=os.path.join(REPO, "examples"),
    )


def test_helloworld():
    r = _run("helloworld.py")
    assert r.returncode == 0, r.stderr[-2000:]
    assert "dublin (linearizable read via follower host): rain" in r.stdout


def test_ondisk_two_runs(tmp_path):
    r1 = _run("ondisk.py", str(tmp_path))
    assert r1.returncode == 0, r1.stderr[-2000:]
    assert "wrote boot" in r1.stdout
    r2 = _run("ondisk.py", str(tmp_path))
    assert r2.returncode == 0, r2.stderr[-2000:]
    assert "recovered from disk: boot =" in r2.stdout


@pytest.mark.slow
def test_multigroup_device():
    r = _run("multigroup_device.py", timeout=420)
    assert r.returncode == 0, r.stderr[-2000:]
    assert "32/32 shards elected" in r.stdout
    assert "wrote to 32/32 shards" in r.stdout
