"""Elastic fleet control plane (dragonboat_tpu/control.py): the pure
planner's determinism, hysteresis, rate limiting and cooldown; the
capacity admission gate's modes; and the NodeHost admission wiring
(structured refusal + counters + flight record)."""

from __future__ import annotations

import pytest

from dragonboat_tpu import control


def _row(lane, score=10, lag=0, classes=("commit_stall",)):
    return {"lane": lane, "score": score, "lag": lag,
            "classes": list(classes)}


def _shard(sid, lane, leader=True, term=3, voters=(1, 2, 3), rid=1):
    return {
        "shard_id": sid, "replica_id": rid, "lane": lane,
        "is_leader": leader, "term": term,
        "membership": {"addresses": {v: "" for v in voters}},
    }


def _ctl(**kw):
    kw.setdefault("enabled", True)
    kw.setdefault("hysteresis", 1)
    kw.setdefault("warmup_obs", 0)
    return control.FleetController(control.ControlPolicy(**kw))


# -- target selection ---------------------------------------------------


def test_pick_target_deterministic_and_excludes_self():
    a = control.pick_target(7, 42, 3, (1, 2, 3), exclude=1)
    b = control.pick_target(7, 42, 3, (1, 2, 3), exclude=1)
    assert a == b and a in (2, 3)


def test_pick_target_varies_with_term_and_seed():
    picks = {control.pick_target(7, 42, t, tuple(range(1, 9)), 1)
             for t in range(16)}
    assert len(picks) > 1        # term in the key: a retry can move
    picks = {control.pick_target(s, 42, 3, tuple(range(1, 9)), 1)
             for s in range(16)}
    assert len(picks) > 1


def test_pick_target_singleton_returns_zero():
    assert control.pick_target(0, 1, 1, (5,), exclude=5) == 0


# -- planner ------------------------------------------------------------


def test_transfer_planned_for_hot_leader():
    c = _ctl()
    ds = c.observe([_row(0, score=10)], [_shard(100, 0)])
    assert len(ds) == 1
    d = ds[0]
    assert d.kind == control.TRANSFER
    assert d.shard_id == 100 and d.target in (2, 3)
    assert d.evidence["score"] == 10 and d.evidence["lane"] == 0
    assert d.evidence["classes"] == ["commit_stall"]


def test_identical_observations_plan_identically():
    worst, shards = [_row(0), _row(1)], [_shard(100, 0), _shard(101, 1)]
    plan = lambda: _ctl(max_transfers=8).observe(worst, shards)
    assert plan() == plan()


def test_not_leader_never_transfers():
    c = _ctl()
    assert c.observe([_row(0)], [_shard(100, 0, leader=False)]) == []


def test_cold_shard_not_transferred():
    c = _ctl(hot_score=8, lag_hot=64)
    assert c.observe([_row(0, score=3, lag=5)], [_shard(100, 0)]) == []


def test_lag_alone_trips_hot():
    c = _ctl(hot_score=8, lag_hot=64)
    ds = c.observe([_row(0, score=1, lag=100)], [_shard(100, 0)])
    assert len(ds) == 1


def test_disabled_policy_plans_nothing():
    c = _ctl(enabled=False)
    assert c.observe([_row(0)], [_shard(100, 0)]) == []


def test_hysteresis_requires_consecutive_hot():
    c = _ctl(hysteresis=3)
    assert c.observe([_row(0)], [_shard(100, 0)]) == []
    assert c.observe([_row(0)], [_shard(100, 0)]) == []
    assert len(c.observe([_row(0)], [_shard(100, 0)])) == 1


def test_hysteresis_streak_resets_when_cold():
    c = _ctl(hysteresis=2)
    assert c.observe([_row(0)], [_shard(100, 0)]) == []
    # shard drops out of the digest entirely: streak must restart
    assert c.observe([], []) == []
    assert c.observe([_row(0)], [_shard(100, 0)]) == []
    assert len(c.observe([_row(0)], [_shard(100, 0)])) == 1


def test_max_transfers_per_observation():
    c = _ctl(max_transfers=2)
    worst = [_row(i, score=20 - i) for i in range(5)]
    shards = [_shard(100 + i, i) for i in range(5)]
    ds = c.observe(worst, shards)
    assert len(ds) == 2
    # severity order: the two hottest lanes moved first
    assert [d.shard_id for d in ds] == [100, 101]


def test_cooldown_blocks_repeat_transfer():
    c = _ctl(cooldown_obs=3)
    assert len(c.observe([_row(0)], [_shard(100, 0)])) == 1
    assert c.observe([_row(0)], [_shard(100, 0)]) == []   # obs 2
    assert c.observe([_row(0)], [_shard(100, 0)]) == []   # obs 3
    assert len(c.observe([_row(0)], [_shard(100, 0)])) == 1  # obs 4


def test_host_hot_drains_every_led_shard():
    c = _ctl(hot_score=1000, lag_hot=10**6)
    # nothing trips per-lane thresholds, but the host itself is hot:
    # every led shard is a candidate, digest row or not (host-level
    # overload is not attributable to one lane), in severity order
    ds = c.observe([_row(0, score=1)],
                   [_shard(100, 0), _shard(101, 7)], host_hot=True)
    assert [d.shard_id for d in ds] == [100, 101]
    assert ds[0].evidence["host_hot"] is True
    assert ds[1].evidence["score"] == 0       # lane 7: no digest row


def test_warmup_suppresses_host_hot_not_digest():
    c = _ctl(warmup_obs=2, hot_score=8)
    # obs 1-2: host_hot alone is compile noise, ignored...
    assert c.observe([], [_shard(100, 0)], host_hot=True) == []
    # ...but a genuine digest verdict still acts during warmup
    assert len(c.observe([_row(1, score=10)],
                         [_shard(200, 1)], host_hot=False)) == 1
    # obs 3: past the warmup, host_hot drains again
    assert len(c.observe([], [_shard(100, 0)], host_hot=True)) == 1


def test_singleton_skipped_but_next_candidate_taken():
    c = _ctl(max_transfers=1)
    worst = [_row(0, score=20), _row(1, score=10)]
    shards = [_shard(100, 0, voters=(1,)), _shard(101, 1)]
    ds = c.observe(worst, shards)
    assert [d.shard_id for d in ds] == [101]


# -- admission ----------------------------------------------------------


def test_admission_limit_derates_by_watermark():
    fake = lambda kp, budget: 100
    assert control.admission_limit(None, 1 << 30, 10.0, fake) == 90
    assert control.admission_limit(None, 0, 10.0, fake) == 0
    assert control.admission_limit(None, 1 << 30, 100.0, fake) == 1


def test_check_admission_modes():
    assert control.check_admission(1, 5, 10) is None
    d = control.check_admission(1, 10, 10)
    assert d is not None and d.kind == control.REFUSE
    assert d.evidence == {"occupied": 10, "limit": 10, "mode": "enforce"}
    assert control.check_admission(1, 10, 10,
                                   mode=control.ADMISSION_OFF) is None
    w = control.check_admission(1, 10, 10, mode=control.ADMISSION_WARN)
    assert w is not None and w.evidence["mode"] == "warn"
    # no resolvable budget: never refuse
    assert control.check_admission(1, 10, 0) is None


# -- NodeHost wiring ----------------------------------------------------


@pytest.fixture
def host(tmp_path):
    from dragonboat_tpu.config import NodeHostConfig
    from dragonboat_tpu.nodehost import NodeHost

    nhc = NodeHostConfig(raft_address="adm-1:9001", deployment_id=1)
    nhc.expert.admission_policy = control.ADMISSION_ENFORCE
    # a budget that models exactly 2 lanes, zero watermark so limit == 2
    from dragonboat_tpu import capacity as _capacity

    nhc.expert.kernel_log_cap = 64
    nhc.expert.kernel_inbox_cap = 4
    nhc.expert.kernel_msg_entries = 4
    nhc.expert.kernel_proposal_cap = 2
    nhc.expert.capacity_watermark_pct = 0.0
    nh = NodeHost(nhc, auto_run=False)
    per = _capacity.model_bytes_per_group(
        nh._kernel_params(), _capacity.RESIDENT_CLASSES)["total"]
    nhc.expert.capacity_device_budget_bytes = 2 * per
    yield nh
    nh.close()


def _start(nh, sid, device=True):
    from dragonboat_tpu.config import Config
    from test_nodehost import KVStateMachine

    nh.start_replica(
        {1: nh.raft_address}, False, KVStateMachine,
        Config(shard_id=sid, replica_id=1, election_rtt=10,
               heartbeat_rtt=1, snapshot_entries=0,
               device_resident=device))


def test_nodehost_admission_refuses_past_watermark(host):
    from dragonboat_tpu import flight
    from dragonboat_tpu.nodehost import AdmissionRefusedError

    _start(host, 1)
    _start(host, 2)
    with pytest.raises(AdmissionRefusedError) as ei:
        _start(host, 3)
    assert ei.value.evidence["occupied"] == 2
    assert ei.value.evidence["limit"] == 2
    m = host.metrics()
    assert m.get("control_admission_total") == 3
    assert m.get("control_admission_refused") == 1
    kinds = [r["kind"] for r in flight.RECORDER.tail()]
    assert flight.ADMISSION_REFUSED in kinds
    # host-resident replicas bypass the device admission gate
    _start(host, 4, device=False)
    assert host.metrics().get("control_admission_total") == 3


def test_nodehost_admission_warn_admits(host):
    host.config.expert.admission_policy = control.ADMISSION_WARN
    for sid in (1, 2, 3):
        _start(host, sid)
    m = host.metrics()
    assert m.get("control_admission_refused") == 1
    assert 3 in host.nodes


# -- fleet_doctor --plan (read-only dry run) ----------------------------


def _plan_info(worst=(), shards=(), capacity=None, quiesced=0):
    """A minimal valid NodeHost.info() payload for the doctor."""
    from dragonboat_tpu.core import health

    h = health.empty_dict()
    h["worst"] = list(worst)
    h["anomalous"] = len(h["worst"])
    for w in h["worst"]:
        for c in w["classes"]:
            h["class_count"][c] += 1
    info = {"node_host_id": "nhid-plan", "raft_address": "p-1",
            "health": h, "shards": list(shards)}
    if capacity is not None:
        info["capacity"] = capacity
    info["fleet"] = {"quiesced": quiesced}
    return info


def _offender(lane, score=24, classes=("leaderless",)):
    from dragonboat_tpu.core import health

    return dict({f: 0 for f in health.ROW_FIELDS}, lane=lane, score=score,
                flags=1, classes=list(classes), engine="kernel")


def _info_shard(sid, lane, leader=True, resident="device"):
    return {"shard_id": sid, "replica_id": 1, "leader_id": 1, "term": 5,
            "is_leader": leader, "last_applied": 0,
            "membership": {"addresses": {1: "p-1", 2: "p-2", 3: "p-3"},
                           "non_votings": {}, "witnesses": {},
                           "config_change_id": 1},
            "resident": resident, "lane": lane}


def _doctor():
    import importlib.util
    import os

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "fleet_doctor", os.path.join(root, "scripts", "fleet_doctor.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_plan_schema_is_strict():
    ds = [control.Decision(
        kind=control.TRANSFER, shard_id=7, target=2,
        evidence={"obs": 1, "lane": 3, "score": 24, "lag": 0, "streak": 1,
                  "term": 5, "host_hot": False, "classes": ["leaderless"]}),
          control.Decision(
        kind=control.REFUSE, shard_id=0, target=0,
        evidence={"occupied": 4, "limit": 4, "mode": "warn"})]
    plan = control.plan_to_dict(ds, quiesced=2)
    control.validate_plan(plan)
    assert plan["counts"] == {"transfer": 1, "refuse": 1, "quiesced": 2}

    with pytest.raises(ValueError, match="keys"):
        control.validate_plan(dict(plan, extra=1))
    bad = control.plan_to_dict(ds, quiesced=2)
    bad["counts"]["transfer"] = 5
    with pytest.raises(ValueError, match="counts"):
        control.validate_plan(bad)
    bad = control.plan_to_dict(ds, quiesced=2)
    del bad["transfers"][0]["evidence"]["score"]
    with pytest.raises(ValueError, match="score"):
        control.validate_plan(bad)
    bad = control.plan_to_dict(ds, quiesced=2)
    bad["refusals"][0]["evidence"]["mode"] = "bogus"
    with pytest.raises(ValueError, match="mode"):
        control.validate_plan(bad)
    bad = control.plan_to_dict(ds, quiesced=2)
    bad["counts"]["quiesced"] = True
    with pytest.raises(ValueError, match="quiesced"):
        control.validate_plan(bad)


def test_build_plan_dry_run():
    fd = _doctor()
    # hot led shard on lane 3, host at its modeled device capacity,
    # two lanes masked-quiesced: all three verbs show up
    info = _plan_info(
        worst=[_offender(3)],
        shards=[_info_shard(7, 3), _info_shard(8, 4),
                _info_shard(9, -1, resident="host")],
        capacity={"model_max_g_at_budget": 2}, quiesced=2)
    plan = fd.build_plan(info)
    control.validate_plan(plan)
    assert plan["counts"] == {"transfer": 1, "refuse": 1, "quiesced": 2}
    t = plan["transfers"][0]
    assert t["shard_id"] == 7 and t["target"] in (2, 3)
    assert t["evidence"]["score"] == 24
    # host-resident shard 9 is not admission-relevant: occupied == 2
    assert plan["refusals"][0]["evidence"] == {
        "occupied": 2, "limit": 2, "mode": "warn"}
    # healthy host, capacity headroom: empty plan
    empty = fd.build_plan(_plan_info(
        shards=[_info_shard(7, 3)],
        capacity={"model_max_g_at_budget": 8}))
    control.validate_plan(empty)
    assert empty["counts"] == {"transfer": 0, "refuse": 0, "quiesced": 0}


def test_fleet_doctor_plan_cli(capsys):
    import json
    import sys

    from dragonboat_tpu.server.metrics_http import MetricsServer

    fd = _doctor()
    state = {"i": _plan_info(worst=[_offender(3)],
                             shards=[_info_shard(7, 3)], quiesced=1)}
    srv = MetricsServer([], address="127.0.0.1:0",
                        health_source=lambda: state["i"]["health"],
                        info_source=lambda: state["i"],
                        shard_info_source=lambda sid: None)
    argv = sys.argv
    try:
        # pending transfer -> exit 1, human report carries evidence
        sys.argv = ["fleet_doctor.py", srv.address, "--plan"]
        assert fd.main() == 1
        out = capsys.readouterr().out
        assert "transfers=1" in out and "quiesced=1" in out
        assert "transfer shard 7" in out and "score=24" in out
        # --json round-trips through the strict schema
        sys.argv = ["fleet_doctor.py", srv.address, "--plan", "--json"]
        assert fd.main() == 1
        plan = json.loads(capsys.readouterr().out)["plan"]
        control.validate_plan(plan)
        assert plan["counts"]["transfer"] == 1
        # nothing hot -> empty plan, exit 0
        state["i"] = _plan_info(shards=[_info_shard(7, 3)])
        sys.argv = ["fleet_doctor.py", srv.address, "--plan"]
        assert fd.main() == 0
        assert "nothing pending" in capsys.readouterr().out
    finally:
        sys.argv = argv
        srv.close()
