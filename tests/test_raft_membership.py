"""Membership change + snapshot-restore conformance (§3.5 of the survey;
one-at-a-time config changes per p33-35 of the raft thesis)."""

from dragonboat_tpu import raftpb as pb
from dragonboat_tpu.core.pycore import RaftState
from raft_harness import Network, make_network, new_raft

MT = pb.MessageType


def cc_event(rid, cctype):
    return pb.Message(
        type=MT.CONFIG_CHANGE_EVENT, hint=rid, hint_high=int(cctype)
    )


def test_add_node_expands_membership():
    r = new_raft(1, [1, 2])
    r.handle(cc_event(3, pb.ConfigChangeType.ADD_NODE))
    assert sorted(r.remotes) == [1, 2, 3]
    assert r.quorum() == 2
    assert r.remotes[3].next == r.log.last_index() + 1


def test_remove_node_shrinks_membership():
    r = new_raft(1, [1, 2, 3])
    r.handle(cc_event(3, pb.ConfigChangeType.REMOVE_NODE))
    assert sorted(r.remotes) == [1, 2]
    assert r.quorum() == 2


def test_removed_leader_steps_down():
    nt = make_network(3)
    nt.elect(1)
    r1 = nt.nodes[1]
    r1.handle(cc_event(1, pb.ConfigChangeType.REMOVE_NODE))
    assert r1.state == RaftState.FOLLOWER
    assert 1 not in r1.remotes


def test_removal_can_advance_commit():
    """Removing a lagging member may unblock commit (raft.go:1294-1298)."""
    nt = make_network(3)
    nt.elect(1)
    r1 = nt.nodes[1]
    nt.isolate(3)
    nt.isolate(2)
    nt.propose(1, b"x")
    assert r1.log.committed == 1
    # removing one unreachable member turns quorum into 2-of-2... still no.
    # removing reduces to 2 members (1,2): match of 2 is 1. no commit.
    r1.handle(cc_event(3, pb.ConfigChangeType.REMOVE_NODE))
    assert r1.log.committed == 1
    # now node 2's ack arrives (heal + heartbeat round)
    nt.heal()
    nt.start(pb.Message(type=MT.LEADER_HEARTBEAT, to=1, from_=1))
    assert r1.log.committed == r1.log.last_index()


def test_one_config_change_at_a_time():
    nt = make_network(3)
    nt.elect(1)
    r1 = nt.nodes[1]
    cc1 = pb.Entry(type=pb.EntryType.CONFIG_CHANGE, cmd=b"cc1")
    r1.handle(pb.Message(type=MT.PROPOSE, from_=1, entries=(cc1,)))
    assert r1.pending_config_change
    # second CC while one is pending is replaced by a noop and reported dropped
    cc2 = pb.Entry(type=pb.EntryType.CONFIG_CHANGE, cmd=b"cc2")
    r1.handle(pb.Message(type=MT.PROPOSE, from_=1, entries=(cc2,)))
    assert r1.dropped_entries and r1.dropped_entries[0].cmd == b"cc2"
    ents = r1.log.get_entries(1, r1.log.last_index() + 1)
    assert sum(1 for e in ents if e.type == pb.EntryType.CONFIG_CHANGE) == 1
    # applying the CC clears the flag
    r1.handle(cc_event(4, pb.ConfigChangeType.ADD_NODE))
    assert not r1.pending_config_change


def test_rejected_config_change_clears_flag():
    nt = make_network(3)
    nt.elect(1)
    r1 = nt.nodes[1]
    r1.handle(
        pb.Message(
            type=MT.PROPOSE, from_=1,
            entries=(pb.Entry(type=pb.EntryType.CONFIG_CHANGE, cmd=b"cc"),),
        )
    )
    assert r1.pending_config_change
    r1.handle(pb.Message(type=MT.CONFIG_CHANGE_EVENT, reject=True))
    assert not r1.pending_config_change


def test_become_leader_restores_pending_cc_flag():
    """A new leader with an uncommitted CC entry in its log must restore the
    pending flag (raft.go:1075 preLeaderPromotionHandleConfigChange)."""
    r = new_raft(1, [1, 2, 3])
    r.term = 1
    # follower receives a CC entry it hasn't applied
    r.handle(
        pb.Message(
            type=MT.REPLICATE, from_=2, term=1, log_index=0, log_term=0,
            entries=(pb.Entry(term=1, index=1, type=pb.EntryType.CONFIG_CHANGE),),
        )
    )
    # let the campaign gate pass (committed entries treated as applied)
    r.applied = r.log.committed
    r.handle(pb.Message(type=MT.ELECTION, from_=1))
    r.handle(pb.Message(type=MT.REQUEST_VOTE_RESP, from_=2, term=2))
    assert r.state == RaftState.LEADER
    assert r.pending_config_change


def test_promote_nonvoting_to_voter():
    nt = Network(
        {
            1: new_raft(1, [1, 2], non_votings=[3]),
            2: new_raft(2, [1, 2], non_votings=[3]),
            3: new_raft(3, [1, 2], non_votings=[3], is_non_voting=True),
        }
    )
    nt.elect(1)
    nt.propose(1, b"x")
    r1, r3 = nt.nodes[1], nt.nodes[3]
    match_before = r1.non_votings[3].match
    assert match_before == r1.log.last_index()  # nonvoting keeps up
    for r in nt.nodes.values():
        r.handle(cc_event(3, pb.ConfigChangeType.ADD_NODE))
    assert 3 in r1.remotes and 3 not in r1.non_votings
    # progress inherited on promotion (raft.go:1246-1252)
    assert r1.remotes[3].match == match_before
    assert r3.state == RaftState.FOLLOWER
    assert r1.quorum() == 2


def test_add_witness():
    r = new_raft(1, [1, 2])
    r.handle(cc_event(3, pb.ConfigChangeType.ADD_WITNESS))
    assert 3 in r.witnesses
    assert r.num_voting_members() == 3
    assert r.quorum() == 2


def test_snapshot_restore_follower():
    r = new_raft(2, [1, 2, 3])
    r.term = 2
    ss = pb.Snapshot(
        index=10,
        term=2,
        membership=pb.Membership(
            config_change_id=5, addresses={1: "a1", 2: "a2", 4: "a4"}
        ),
    )
    r.handle(pb.Message(type=MT.INSTALL_SNAPSHOT, from_=1, term=2, snapshot=ss))
    assert r.log.committed == 10
    assert r.log.last_index() == 10
    assert r.log.term(10) == 2
    assert sorted(r.remotes) == [1, 2, 4]
    resp = [m for m in r.msgs if m.type == MT.REPLICATE_RESP]
    assert resp and resp[0].log_index == 10


def test_snapshot_restore_ignored_when_stale():
    r = new_raft(2, [1, 2, 3])
    r.term = 2
    # local log already committed past the snapshot
    r.handle(
        pb.Message(
            type=MT.REPLICATE, from_=1, term=2, log_index=0, log_term=0,
            entries=tuple(pb.Entry(term=2, index=i) for i in range(1, 6)),
            commit=5,
        )
    )
    assert r.log.committed == 5
    r.msgs = []
    ss = pb.Snapshot(index=3, term=2, membership=pb.Membership(addresses={1: "a"}))
    r.handle(pb.Message(type=MT.INSTALL_SNAPSHOT, from_=1, term=2, snapshot=ss))
    # stale snapshot rejected; responds with committed index
    resp = [m for m in r.msgs if m.type == MT.REPLICATE_RESP]
    assert resp and resp[0].log_index == 5
    assert r.log.last_index() == 5


def test_snapshot_covered_by_matching_log_fast_forwards_commit():
    r = new_raft(2, [1, 2, 3])
    r.term = 2
    r.handle(
        pb.Message(
            type=MT.REPLICATE, from_=1, term=2, log_index=0, log_term=0,
            entries=tuple(pb.Entry(term=2, index=i) for i in range(1, 6)),
            commit=1,
        )
    )
    assert r.log.committed == 1
    ss = pb.Snapshot(index=4, term=2, membership=pb.Membership(addresses={1: "a"}))
    r.msgs = []
    r.handle(pb.Message(type=MT.INSTALL_SNAPSHOT, from_=1, term=2, snapshot=ss))
    # log matches snapshot: no restore, but commit fast-forwarded
    assert r.log.committed == 4
    assert r.log.last_index() == 5  # log kept


def test_bootstrap_via_peer_launch():
    from dragonboat_tpu.core.logentry import InMemoryLogDB
    from dragonboat_tpu.core.peer import Peer
    from dragonboat_tpu.core.pycore import CoreConfig

    cfg = CoreConfig(shard_id=1, replica_id=1, election_rtt=10, heartbeat_rtt=1)
    p = Peer.launch(cfg, InMemoryLogDB(), {1: "a1", 2: "a2", 3: "a3"},
                    initial=True, new_node=True, rng=lambda n: 0)
    r = p.raft
    assert sorted(r.remotes) == [1, 2, 3]
    assert r.log.last_index() == 3
    assert r.log.committed == 3
    ents = r.log.get_entries(1, 4)
    assert all(e.type == pb.EntryType.CONFIG_CHANGE for e in ents)
    ccs = [pb.decode_config_change(e.cmd) for e in ents]
    assert [c.replica_id for c in ccs] == [1, 2, 3]
    assert all(c.initialize for c in ccs)
