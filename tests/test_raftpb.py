"""raftpb record/serialization tests — round-trip and predicate parity."""

from dragonboat_tpu import raftpb as pb


def test_message_type_values_match_reference():
    # parity: /root/reference/raftpb/types.go:8-38
    assert pb.MessageType.LOCAL_TICK == 0
    assert pb.MessageType.PROPOSE == 7
    assert pb.MessageType.REPLICATE == 12
    assert pb.MessageType.REPLICATE_RESP == 13
    assert pb.MessageType.REQUEST_VOTE == 14
    assert pb.MessageType.INSTALL_SNAPSHOT == 16
    assert pb.MessageType.HEARTBEAT == 17
    assert pb.MessageType.READ_INDEX == 19
    assert pb.MessageType.TIMEOUT_NOW == 24
    assert pb.MessageType.REQUEST_PREVOTE == 26
    assert pb.MessageType.LOG_QUERY == 28
    assert pb.NUM_MESSAGE_TYPES == 29


def test_entry_roundtrip():
    e = pb.Entry(term=3, index=17, type=pb.EntryType.APPLICATION,
                 key=99, client_id=12345, series_id=2, responded_to=1,
                 cmd=b"hello world")
    buf = bytearray()
    pb.encode_entry(e, buf)
    got, off = pb.decode_entry(memoryview(bytes(buf)), 0)
    assert got == e
    assert off == len(buf)


def test_state_roundtrip():
    s = pb.State(term=7, vote=2, commit=55)
    assert pb.decode_state(pb.encode_state(s)) == s
    assert pb.State().is_empty()
    assert not s.is_empty()


def test_message_batch_roundtrip():
    snap = pb.Snapshot(
        filepath="/tmp/snap.gbsnap", file_size=1024, index=10, term=2,
        membership=pb.Membership(
            config_change_id=3,
            addresses={1: "a1", 2: "a2"},
            non_votings={4: "a4"},
            witnesses={5: "a5"},
            removed={9: True},
        ),
        files=(pb.SnapshotFile(1, "/tmp/f1", b"meta"),),
        checksum=b"\x01\x02",
        shard_id=7,
        type=pb.StateMachineType.REGULAR,
        on_disk_index=5,
    )
    msgs = (
        pb.Message(type=pb.MessageType.REPLICATE, to=2, from_=1, shard_id=7,
                   term=3, log_term=2, log_index=9, commit=8,
                   entries=(pb.Entry(term=3, index=10, cmd=b"x" * 16),)),
        pb.Message(type=pb.MessageType.INSTALL_SNAPSHOT, to=3, from_=1,
                   shard_id=7, term=3, snapshot=snap),
        pb.Message(type=pb.MessageType.HEARTBEAT_RESP, to=1, from_=2,
                   shard_id=7, term=3, hint=123, hint_high=456, reject=True),
    )
    b = pb.MessageBatch(requests=msgs, deployment_id=42, source_address="h1:9876",
                        bin_ver=1)
    got = pb.decode_message_batch(pb.encode_message_batch(b))
    assert got == b


def test_message_batch_checksum():
    b = pb.MessageBatch(requests=(pb.Message(type=pb.MessageType.PING),))
    data = bytearray(pb.encode_message_batch(b))
    data[10] ^= 0xFF
    try:
        pb.decode_message_batch(bytes(data))
    except ValueError:
        pass
    else:
        raise AssertionError("corrupted batch must fail checksum")


def test_bootstrap_and_config_change_roundtrip():
    bs = pb.Bootstrap(addresses={1: "x:1", 2: "y:2"}, join=True,
                      type=pb.StateMachineType.ON_DISK)
    assert pb.decode_bootstrap(pb.encode_bootstrap(bs)) == bs
    cc = pb.ConfigChange(config_change_id=9, type=pb.ConfigChangeType.ADD_WITNESS,
                         replica_id=5, address="z:3", initialize=True)
    assert pb.decode_config_change(pb.encode_config_change(cc)) == cc


def test_entry_predicates():
    # parity: raftpb/raft.go:63-140 predicate semantics
    cc = pb.Entry(type=pb.EntryType.CONFIG_CHANGE, cmd=b"cfg")
    assert cc.is_config_change() and not cc.is_session_managed()
    noop_session = pb.Entry(client_id=0, series_id=pb.NOOP_SERIES_ID, cmd=b"v")
    assert noop_session.is_noop_session()
    assert not noop_session.is_session_managed()
    reg = pb.Entry(client_id=7, series_id=pb.SERIES_ID_FOR_REGISTER)
    assert reg.is_new_session_request() and not reg.is_update()
    unreg = pb.Entry(client_id=7, series_id=pb.SERIES_ID_FOR_UNREGISTER)
    assert unreg.is_end_of_session_request()
    upd = pb.Entry(client_id=7, series_id=3, cmd=b"v")
    assert upd.is_update() and upd.is_session_managed() and upd.is_proposal()


def test_entries_to_apply():
    ents = tuple(pb.Entry(term=1, index=i) for i in range(5, 11))
    assert pb.entries_to_apply(ents, 4) == ents
    assert pb.entries_to_apply(ents, 7)[0].index == 8
    assert pb.entries_to_apply(ents, 10) == ()
    assert pb.entries_to_apply((), 3) == ()
    try:
        pb.entries_to_apply(ents, 3)
    except ValueError:
        pass
    else:
        raise AssertionError("gap must raise")
