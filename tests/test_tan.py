"""tan durable log engine: round-trips, crash recovery, compaction GC,
and NodeHost restart-from-disk with a NEW TanLogDB built from the files
(the r1 restart test reused the same in-memory object; these kill it)."""

import os
import struct
import time

import pytest

from dragonboat_tpu import raftpb as pb
from dragonboat_tpu.logdb.tan import (
    _HDR,
    CorruptLogError,
    TanLogDB,
    TanLogDBFactory,
)


def _update(shard=1, replica=1, term=1, first=1, n=3, commit=0):
    ents = tuple(
        pb.Entry(term=term, index=first + i, cmd=f"e{first + i}".encode())
        for i in range(n)
    )
    return pb.Update(
        shard_id=shard, replica_id=replica,
        state=pb.State(term=term, vote=2, commit=commit),
        entries_to_save=ents,
    )


def test_save_and_iterate(tmp_path):
    db = TanLogDB(str(tmp_path))
    db.save_raft_state([_update(n=5)], worker_id=0)
    ents = db.iterate_entries(1, 1, 1, 6, 0)
    assert [e.index for e in ents] == [1, 2, 3, 4, 5]
    assert ents[2].cmd == b"e3"
    rs = db.read_raft_state(1, 1, 0)
    assert rs.state.vote == 2 and rs.first_index == 1 and rs.entry_count == 5
    db.close()


def test_restart_from_disk(tmp_path):
    db = TanLogDB(str(tmp_path))
    db.save_bootstrap_info(1, 1, pb.Bootstrap(addresses={1: "a", 2: "b"}))
    db.save_raft_state([_update(n=4, commit=2)], worker_id=0)
    db.save_raft_state([_update(term=2, first=5, n=2, commit=4)], worker_id=0)
    db.close()

    db2 = TanLogDB(str(tmp_path))  # NEW object, index rebuilt from files
    ents = db2.iterate_entries(1, 1, 1, 7, 0)
    assert [e.index for e in ents] == [1, 2, 3, 4, 5, 6]
    assert ents[5].term == 2
    rs = db2.read_raft_state(1, 1, 0)
    assert rs.state.term == 2 and rs.state.commit == 4
    bs = db2.get_bootstrap_info(1, 1)
    assert bs.addresses == {1: "a", 2: "b"}
    db2.close()


def test_conflict_overwrite_survives_restart(tmp_path):
    db = TanLogDB(str(tmp_path))
    db.save_raft_state([_update(term=1, first=1, n=5)], worker_id=0)
    # a new-term overwrite of the suffix from index 3
    db.save_raft_state([_update(term=3, first=3, n=1)], worker_id=0)
    assert [e.term for e in db.iterate_entries(1, 1, 1, 10, 0)] == [1, 1, 3]
    db.close()
    db2 = TanLogDB(str(tmp_path))
    assert [e.term for e in db2.iterate_entries(1, 1, 1, 10, 0)] == [1, 1, 3]
    db2.close()


def test_torn_tail_truncated(tmp_path):
    db = TanLogDB(str(tmp_path))
    db.save_raft_state([_update(n=3)], worker_id=0)
    db.save_raft_state([_update(term=2, first=4, n=2)], worker_id=0)
    db.close()
    # simulate a crash mid-append: chop bytes off the file tail
    logs = [f for f in os.listdir(tmp_path) if f.endswith(".tan")]
    path = os.path.join(tmp_path, sorted(logs)[-1])
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.truncate(size - 7)
    db2 = TanLogDB(str(tmp_path))
    # the second record is gone, the first is intact
    assert [e.index for e in db2.iterate_entries(1, 1, 1, 10, 0)] == [1, 2, 3]
    db2.close()


def test_mid_file_corruption_refuses_open(tmp_path):
    db = TanLogDB(str(tmp_path), max_file_size=200)  # force rotation
    for k in range(6):
        db.save_raft_state([_update(term=1, first=1 + 3 * k, n=3)], 0)
    db.close()
    logs = sorted(f for f in os.listdir(tmp_path) if f.endswith(".tan"))
    assert len(logs) > 1, "test needs multiple files"
    path = os.path.join(tmp_path, logs[0])
    with open(path, "r+b") as f:
        f.seek(_HDR.size + 4)
        b = f.read(1)
        f.seek(_HDR.size + 4)
        f.write(bytes([b[0] ^ 0x10]))
    with pytest.raises(CorruptLogError):
        TanLogDB(str(tmp_path))


def test_compaction_deletes_files(tmp_path):
    db = TanLogDB(str(tmp_path), max_file_size=256)
    for k in range(10):
        db.save_raft_state([_update(term=1, first=1 + 3 * k, n=3)], 0)
    files_before = len([f for f in os.listdir(tmp_path) if f.endswith(".tan")])
    assert files_before > 2
    db.remove_entries_to(1, 1, 27)
    files_after = len([f for f in os.listdir(tmp_path) if f.endswith(".tan")])
    assert files_after < files_before
    # live suffix still readable, state survived the re-homing
    ents = db.iterate_entries(1, 1, 28, 31, 0)
    assert [e.index for e in ents] == [28, 29, 30]
    assert db.read_raft_state(1, 1, 0).state.term == 1
    db.close()
    db2 = TanLogDB(str(tmp_path))
    assert [e.index for e in db2.iterate_entries(1, 1, 28, 31, 0)] == [28, 29, 30]
    assert db2.read_raft_state(1, 1, 0) is not None
    db2.close()


def test_fsync_called(tmp_path, monkeypatch):
    calls = []
    real = os.fsync
    monkeypatch.setattr(os, "fsync", lambda fd: (calls.append(fd), real(fd)))
    db = TanLogDB(str(tmp_path))
    db.save_raft_state([_update()], worker_id=0)
    assert calls, "save_raft_state must fsync"
    db.close()


def test_remove_node_data(tmp_path):
    db = TanLogDB(str(tmp_path))
    db.save_raft_state([_update()], worker_id=0)
    db.remove_node_data(1, 1)
    assert db.read_raft_state(1, 1, 0) is None
    assert db.iterate_entries(1, 1, 1, 5, 0) == []
    db.close()
    db2 = TanLogDB(str(tmp_path))
    assert db2.read_raft_state(1, 1, 0) is None
    db2.close()


def test_import_snapshot_restart(tmp_path):
    db = TanLogDB(str(tmp_path))
    ss = pb.Snapshot(index=100, term=7, shard_id=1,
                     membership=pb.Membership(addresses={1: "a", 3: "c"}))
    db.import_snapshot(ss, 1)
    db.close()
    db2 = TanLogDB(str(tmp_path))
    got = db2.get_snapshot(1, 1)
    assert got.index == 100 and got.term == 7
    rs = db2.read_raft_state(1, 1, 0)
    assert rs.state.commit == 100
    assert db2.get_bootstrap_info(1, 1).addresses == {1: "a", 3: "c"}
    db2.close()


# ---------------------------------------------------------------------------
# NodeHost end-to-end on tan: kill every process object, restart from disk
# ---------------------------------------------------------------------------


from dragonboat_tpu.statemachine import IStateMachine


class KV(IStateMachine):
    def __init__(self, *a):
        self.kv = {}

    def update(self, e):
        from dragonboat_tpu.statemachine import Result

        k, v = e.cmd.decode().split("=", 1)
        self.kv[k] = v
        return Result(value=len(self.kv))

    def lookup(self, q):
        return self.kv.get(q)

    def save_snapshot(self, w, files, done):
        d = "\n".join(f"{k}={v}" for k, v in sorted(self.kv.items())).encode()
        w.write(struct.pack("<I", len(d)))
        w.write(d)

    def recover_from_snapshot(self, r, files, done):
        (n,) = struct.unpack("<I", r.read(4))
        self.kv = dict(
            line.split("=", 1)
            for line in r.read(n).decode().split("\n") if line
        )


def _start_hosts(tmp_path, addrs, prefix):
    from dragonboat_tpu.config import Config, NodeHostConfig
    from dragonboat_tpu.nodehost import NodeHost

    hosts = {}
    for rid, addr in addrs.items():
        nh = NodeHost(
            NodeHostConfig(
                raft_address=addr, rtt_millisecond=5,
                node_host_dir=str(tmp_path),
                logdb_factory=TanLogDBFactory(
                    os.path.join(tmp_path, f"tan-{rid}")),
            ))
        nh.start_replica(
            addrs, False, KV,
            Config(shard_id=1, replica_id=rid, election_rtt=10,
                   heartbeat_rtt=1))
        hosts[rid] = nh
    return hosts


def _leader(hosts, timeout=10.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        votes = {}
        for nh in hosts.values():
            lid, ok = nh.get_leader_id(1)
            if ok:
                votes[lid] = votes.get(lid, 0) + 1
        for lid, n in votes.items():
            if n > len(hosts) // 2 and lid in hosts:
                return lid
        time.sleep(0.02)
    raise AssertionError("no leader")


def test_nodehost_restart_from_tan(tmp_path):
    addrs = {i: f"tanE2E{time.monotonic_ns()}-{i}" for i in (1, 2, 3)}
    hosts = _start_hosts(tmp_path, addrs, "a")
    try:
        lid = _leader(hosts)
        s = hosts[lid].get_noop_session(1)
        hosts[lid].sync_propose(s, b"durable=yes")
        hosts[lid].sync_propose(s, b"second=2")
        assert hosts[lid].sync_read(1, "durable") == "yes"
    finally:
        for nh in hosts.values():
            nh.close()

    # full restart: new NodeHosts, new TanLogDB objects, same directories
    # (same addresses — the bootstrap record pins initial membership)
    hosts = _start_hosts(tmp_path, addrs, "b")
    try:
        lid = _leader(hosts)
        deadline = time.time() + 10
        while time.time() < deadline:
            if hosts[lid].stale_read(1, "durable") == "yes":
                break
            time.sleep(0.02)
        assert hosts[lid].sync_read(1, "durable") == "yes"
        assert hosts[lid].sync_read(1, "second") == "2"
        # cluster still writable after recovery
        s = hosts[lid].get_noop_session(1)
        hosts[lid].sync_propose(s, b"post=restart")
        assert hosts[lid].sync_read(1, "post") == "restart"
    finally:
        for nh in hosts.values():
            nh.close()
