"""Composed chaos schedules end-to-end (the ISSUE 3 tentpole).

Every test runs ``run_schedule(seed)``: a fixed seed generates a
FaultPlan composing storage / transport / process faults, the runner
executes it against a 3-replica MemFS cluster under a write workload,
and the convergence oracle must hold — zero committed-entry loss,
identical committed prefixes, monotone applied indices, equal hash
oracles.

Two tiers:

- ``chaos_fast``: five seeds chosen to cover all three seams plus the
  deterministic-replay contract; wired into run_tests.sh tier-1 and the
  plain ``-m 'not slow'`` suite.  Budget: well under 60 s total.
- ``slow``: twenty more seeds for the nightly-style sweep
  (``pytest tests/test_chaos_schedules.py -m slow``).

Seed coverage (from FaultPlan.generate; see test_chaos_faults.py for
the generator invariants): seed 1 = kill + torn crash_write + breaker +
drop; 7 = partition + kill + delay; 9 = torn crash_write + duplicate;
13 = partition + clean crash_write + reorder; 25 = two crash_writes in
one schedule.
"""

import pytest

from dragonboat_tpu.chaos import FaultPlan, run_schedule

FAST_SEEDS = (1, 7, 9, 13, 25)
SLOW_SEEDS = (2, 3, 4, 5, 6, 8, 10, 11, 12, 14,
              15, 16, 17, 21, 22, 32, 36, 42, 47, 48)
assert len(FAST_SEEDS) + len(SLOW_SEEDS) >= 25
assert not set(FAST_SEEDS) & set(SLOW_SEEDS)


def _run_and_check(seed):
    r = run_schedule(seed)
    assert r.report.ok, (seed, r.report.failures)
    assert r.acked_count > 0, seed
    return r


@pytest.mark.chaos_fast
@pytest.mark.parametrize("seed", FAST_SEEDS)
def test_schedule_converges_fast(seed):
    _run_and_check(seed)


@pytest.mark.slow
@pytest.mark.parametrize("seed", SLOW_SEEDS)
def test_schedule_converges_slow(seed):
    _run_and_check(seed)


@pytest.mark.chaos_fast
def test_schedule_converges_on_pipelined_kernel():
    """Faults against device-resident shards served through the depth-1
    pipelined engine loop (PR 6): a kill/crash now lands while a donated
    step is in flight, and restart/recovery must still converge with the
    same oracle.  Seed 1 covers kill + torn crash_write + breaker + drop."""
    r = run_schedule(1, device_resident=True, pipeline_depth=1)
    assert r.report.ok, r.report.failures
    assert r.acked_count > 0


@pytest.mark.chaos_fast
def test_schedule_trace_is_byte_identical_and_replayable():
    """The deterministic-replay contract (COVERAGE.md): the same seed
    twice yields byte-identical fault traces, and the recorded plan JSON
    replays to the same trace."""
    a = _run_and_check(9)
    b = _run_and_check(9)
    assert a.trace_json == b.trace_json
    assert a.plan_json == b.plan_json
    replay = run_schedule(9, plan=FaultPlan.from_json(a.plan_json))
    assert replay.report.ok, replay.report.failures
    assert replay.trace_json == a.trace_json
