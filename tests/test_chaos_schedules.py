"""Composed chaos schedules end-to-end (the ISSUE 3 tentpole).

Every test runs ``run_schedule(seed)``: a fixed seed generates a
FaultPlan composing storage / transport / process faults, the runner
executes it against a 3-replica MemFS cluster under a write workload,
and the convergence oracle must hold — zero committed-entry loss,
identical committed prefixes, monotone applied indices, equal hash
oracles.

Two tiers:

- ``chaos_fast``: five seeds chosen to cover all three seams plus the
  deterministic-replay contract; wired into run_tests.sh tier-1 and the
  plain ``-m 'not slow'`` suite.  Budget: well under 60 s total.
- ``slow``: twenty more seeds for the nightly-style sweep
  (``pytest tests/test_chaos_schedules.py -m slow``).

Seed coverage (from FaultPlan.generate; see test_chaos_faults.py for
the generator invariants): seed 1 = kill + torn crash_write + breaker +
drop; 7 = partition + kill + delay; 9 = torn crash_write + duplicate;
13 = partition + clean crash_write + reorder; 25 = two crash_writes in
one schedule.
"""

import pytest

from dragonboat_tpu.chaos import FaultPlan, run_schedule

FAST_SEEDS = (1, 7, 9, 13, 25)
SLOW_SEEDS = (2, 3, 4, 5, 6, 8, 10, 11, 12, 14,
              15, 16, 17, 21, 22, 32, 36, 42, 47, 48)
assert len(FAST_SEEDS) + len(SLOW_SEEDS) >= 25
assert not set(FAST_SEEDS) & set(SLOW_SEEDS)


def _run_and_check(seed):
    r = run_schedule(seed)
    assert r.report.ok, (seed, r.report.failures)
    assert r.acked_count > 0, seed
    return r


@pytest.mark.chaos_fast
@pytest.mark.parametrize("seed", FAST_SEEDS)
def test_schedule_converges_fast(seed):
    _run_and_check(seed)


@pytest.mark.slow
@pytest.mark.parametrize("seed", SLOW_SEEDS)
def test_schedule_converges_slow(seed):
    _run_and_check(seed)


@pytest.mark.chaos_fast
def test_schedule_converges_on_pipelined_kernel():
    """Faults against device-resident shards served through the depth-1
    pipelined engine loop (PR 6): a kill/crash now lands while a donated
    step is in flight, and restart/recovery must still converge with the
    same oracle.  Seed 1 covers kill + torn crash_write + breaker + drop."""
    r = run_schedule(1, device_resident=True, pipeline_depth=1)
    assert r.report.ok, r.report.failures
    assert r.acked_count > 0


@pytest.mark.chaos_fast
def test_schedule_partitioned_mesh_link_falls_back_to_hub():
    """Round 17: the same composed schedule against MESH-resident shards
    (one shared ('g','r') engine, one replica per device).  Partition
    faults drive the full-row per-link cut mask, delay faults cut this
    host's mesh links onto the host hub (chaos/runner.py wires both
    through MeshDispatch.set_cut / set_link_hub_served), so consensus
    traffic for a cut link falls back to the hub — where the transport
    fault actually applies — or stalls safely.  The oracle still
    requires zero acked-entry loss and post-heal convergence.  Seed 7
    composes partition + kill + delay."""
    r = run_schedule(7, mesh_resident=True)
    assert r.report.ok, r.report.failures
    assert r.acked_count > 0


@pytest.mark.chaos_fast
def test_probe_catches_commit_without_quorum_mutation(monkeypatch):
    """Mutation acceptance for the runtime invariant probe (ISSUE 14):
    a kernel seeded with the commit-without-quorum bug from the model
    checker's catalogue, serving a LIVE 3-replica device-resident
    cluster, must trip ``leader_commit_quorum`` — the flight recorder
    carries the invariant_violation edge, ``violations_seen`` latches,
    and /healthz degrades to 503 (stickily: a violation is a bug, not a
    condition that clears)."""
    import importlib.util
    import json
    import os
    import sys
    import time

    from dragonboat_tpu import flight
    from dragonboat_tpu.config import ExpertConfig
    from dragonboat_tpu.engine import kernel_engine as ke
    from dragonboat_tpu.server.metrics_http import MetricsServer

    from test_kernel_engine import close_all, make_cluster, propose_retry
    from test_nodehost import wait_leader

    mc_path = os.path.join(os.path.dirname(__file__), os.pardir,
                           "scripts", "model_check.py")
    spec = importlib.util.spec_from_file_location("_chaos_model_check",
                                                  mc_path)
    mc = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = mc
    spec.loader.exec_module(mc)
    mut = mc.load_kernel_module("commit_without_quorum")
    # the engine binds these module globals at construction time
    monkeypatch.setattr(ke, "kernel_step", mut.step)
    monkeypatch.setattr(ke, "kernel_step_donated", mut.step_donated)

    hosts = make_cluster("mutq", expert=ExpertConfig(
        kernel_log_cap=256, kernel_capacity=8, kernel_apply_batch=16,
        kernel_compaction_overhead=16, fleet_stats_every=1))
    server = None
    try:
        lead = wait_leader(hosts, timeout=30)
        nh = hosts[lead]
        sess = nh.get_noop_session(1)
        # keep proposing so the mutated leader path (commit = last,
        # quorum unconsulted) keeps advancing ahead of the acks; the
        # probe rides every step at fleet_stats_every=1
        deadline = time.time() + 30
        snap = nh._invariants_snapshot()
        i = 0
        while time.time() < deadline and not snap["violations_seen"]:
            try:
                propose_retry(nh, sess, f"m{i}=x".encode(), deadline_s=2)
            except Exception:
                pass
            i += 1
            snap = nh._invariants_snapshot()
        assert snap["violations_seen"] > 0, snap
        assert snap["per_invariant"]["leader_commit_quorum"] > 0 \
            or snap["first"] is not None, snap
        assert any(r.get("kind") == flight.INVARIANT_VIOLATION
                   for r in flight.RECORDER.tail(256)), \
            "no invariant_violation flight record"
        server = MetricsServer(
            [nh.events.metrics.registry],
            invariants_source=nh._invariants_snapshot)
        status, body, _ = server.healthz()
        assert status == 503, (status, body)
        assert json.loads(body)["invariants"]["violations_seen"] > 0
    finally:
        if server is not None:
            server.close()
        close_all(hosts)


@pytest.mark.chaos_fast
def test_schedule_trace_is_byte_identical_and_replayable():
    """The deterministic-replay contract (COVERAGE.md): the same seed
    twice yields byte-identical fault traces, and the recorded plan JSON
    replays to the same trace."""
    a = _run_and_check(9)
    b = _run_and_check(9)
    assert a.trace_json == b.trace_json
    assert a.plan_json == b.plan_json
    replay = run_schedule(9, plan=FaultPlan.from_json(a.plan_json))
    assert replay.report.ok, replay.report.failures
    assert replay.trace_json == a.trace_json
