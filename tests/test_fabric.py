"""Fabric observability (round 16): header wire codecs, FabricMeter
units, cross-host trace stitching E2E over BOTH transports, the hop
census differential, chaos in link telemetry, read-path spans, the
/debug/fabric endpoint, and the CLI exit matrices.

The meter is process-global (like lifecycle.TRACER), so every test
snapshots/restores it via the autouse fixture — including the tracer's
finish/scrub hooks, which unit tests re-point at private meters.
"""

import importlib.util
import json
import os
import time
import urllib.request

import pytest

from dragonboat_tpu import fabric, lifecycle, telemetry
from dragonboat_tpu import raftpb as pb
from dragonboat_tpu.config import Config, ExpertConfig, NodeHostConfig
from dragonboat_tpu.fabric import FabricMeter, validate_fabric
from dragonboat_tpu.lifecycle import validate_chrome_trace
from dragonboat_tpu.nodehost import NodeHost
from dragonboat_tpu.raftpb import gowire as gw
from dragonboat_tpu.request import LogicalClock, PendingReadIndex
from dragonboat_tpu.transport.tcp import TCPTransportFactory

from test_kernel_engine import close_all, propose_retry
from test_lifecycle import make_tracer
from test_nodehost import KVStateMachine, wait_leader
from test_tcp_transport import KV, free_ports

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _isolate_global_tracer_and_meter():
    """Tracer AND meter are process-global; tests also re-point the
    tracer's census hooks at private meters — always wire them back to
    the global METER on the way out."""
    t = lifecycle.TRACER
    m = fabric.METER
    t_before = (t._every, t._slow_us)
    m_before = m.enabled
    t.reset()
    m.reset()
    yield
    t.configure(sample_every=t_before[0], slow_commit_us=t_before[1])
    t.reset()
    t.set_hooks(on_finish=m._census_finish, on_scrub=m._census_drop)
    m.configure(enabled=m_before)
    m.reset()


def make_meter(**kw):
    """Fully-isolated meter: injected counting clock + private registry
    (the GLOBAL ones must not see test samples)."""
    kw.setdefault("clock", iter(range(0, 10_000_000, 10)).__next__)
    kw.setdefault("registry", telemetry.Registry())
    return FabricMeter(**kw)


def _load_script(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(ROOT, "scripts", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# -- header wire codecs ------------------------------------------------------

def _header():
    return pb.FabricHeader(sent_us=12345, ctxs=(
        pb.FabricContext(key=7, origin="nh-a", hop=0, shard_id=3),
        pb.FabricContext(key=9, origin="nh-b:9021", hop=2, shard_id=1),
    ))


def test_fabric_header_blob_roundtrip():
    h = _header()
    blob = pb.encode_fabric_header(h)
    assert pb.decode_fabric_header(blob) == h
    # unknown version -> None (forward compat: header degrades to
    # absent in a mixed-version cluster, never to a parse error)
    newer = pb.encode_fabric_header(
        pb.FabricHeader(version=pb.FABRIC_WIRE_VERSION + 1, sent_us=1))
    assert pb.decode_fabric_header(newer) is None
    # truncation of a KNOWN version is corruption, not skew
    with pytest.raises(ValueError, match="truncated"):
        pb.decode_fabric_header(blob[:-1])


def test_native_frame_trailer_and_old_frames():
    msgs = (pb.Message(type=pb.MessageType.REPLICATE, to=2, from_=1,
                       shard_id=3, term=4,
                       entries=(pb.Entry(index=1, term=4, key=7),)),)
    # headerless batch: byte format identical to the pre-fabric frame,
    # decodes with fabric absent
    plain = pb.MessageBatch(requests=msgs, deployment_id=5,
                            source_address="nh-a", bin_ver=1)
    rt0 = pb.decode_message_batch(pb.encode_message_batch(plain))
    assert rt0.fabric is None and rt0.requests == msgs
    # header rides the magic-guarded trailer inside the CRC body
    h = _header()
    rt1 = pb.decode_message_batch(pb.encode_message_batch(
        pb.MessageBatch(requests=msgs, deployment_id=5,
                        source_address="nh-a", bin_ver=1, fabric=h)))
    assert rt1.fabric == h
    assert rt1.requests == msgs and rt1.deployment_id == 5
    # an unknown-version trailer decodes as no-header, not an error
    rt2 = pb.decode_message_batch(pb.encode_message_batch(
        pb.MessageBatch(requests=msgs, fabric=pb.FabricHeader(
            version=pb.FABRIC_WIRE_VERSION + 1))))
    assert rt2.fabric is None and rt2.requests == msgs


def test_gowire_field15_roundtrip_and_old_frame():
    msgs = (pb.Message(type=pb.MessageType.REPLICATE, to=2, from_=1,
                       shard_id=3, term=4,
                       entries=(pb.Entry(index=1, term=4, key=7),)),)
    h = _header()
    wire = gw.encode_message_batch(msgs, deployment_id=8,
                                   source_address="nh-a", bin_ver=1,
                                   fabric=pb.encode_fabric_header(h))
    reqs, dep, src, ver, fab = gw.decode_message_batch(wire)
    assert reqs == msgs and dep == 8 and src == "nh-a" and ver == 1
    assert pb.decode_fabric_header(fab) == h
    # the reference's decoder treats field 15 as unknown and skips it:
    # the oracle parse in test_gowire proves that side; here the frame
    # WITHOUT the field keeps decoding as fabric-absent (old peers)
    old = gw.encode_message_batch(msgs, deployment_id=8,
                                  source_address="nh-a", bin_ver=1)
    assert gw.decode_message_batch(old)[4] is None


# -- meter units -------------------------------------------------------------

def test_cross_host_propagation_census_and_remote_spans():
    t = lifecycle.TRACER
    t.configure(sample_every=1)
    m = make_meter()
    t.set_hooks(on_finish=m._census_finish, on_scrub=m._census_drop)
    key = 64
    assert t.begin(key, shard_id=3)
    rep = pb.Message(type=pb.MessageType.REPLICATE, to=2, from_=1,
                     shard_id=3, term=1,
                     entries=(pb.Entry(index=1, term=1, key=key),))
    # origin flush: sampled replicate key becomes an outbound context
    hdr = m.header_for("nh-a", "nh-b", (rep,))
    assert hdr is not None and hdr.ctxs == (
        pb.FabricContext(key=key, origin="nh-a", hop=0, shard_id=3),)
    m.on_send("nh-a", "nh-b", (rep,), 100, hdr)
    # remote receive: hub_recv stamp + child span + parked return ctx
    m.on_batch_received("nh-b", pb.MessageBatch(
        requests=(rep,), source_address="nh-a", fabric=hdr), nbytes=100)
    snap = m.snapshot()
    assert snap["remote_spans"]["active"] == 1
    # the quorum ack carries the context home with its hop advanced
    resp = pb.Message(type=pb.MessageType.REPLICATE_RESP, to=1, from_=2,
                      shard_id=3, term=1)
    hdr2 = m.header_for("nh-b", "nh-a", (resp,))
    assert hdr2.ctxs == (
        pb.FabricContext(key=key, origin="nh-a", hop=1, shard_id=3),)
    m.on_send("nh-b", "nh-a", (resp,), 40, hdr2)
    m.on_batch_received("nh-a", pb.MessageBatch(
        requests=(resp,), source_address="nh-b", fabric=hdr2), nbytes=40)
    # remote child span retired: remote_recv -> remote_step -> ack_return
    ev = m.chrome_events()
    assert [e["name"] for e in ev] == [
        "remote_recv", "remote_step", "ack_return"]
    assert all(e["pid"] == fabric.HOST_PID_BASE and e["tid"] == key
               for e in ev)
    assert [e["ts"] for e in ev] == sorted(e["ts"] for e in ev)
    # finish retires the census: 2 crossings, 2 distinct hosts
    t.finish(key)
    snap = m.snapshot()
    assert snap["census"]["finished"] == 1
    assert snap["census"]["active"] == 0
    assert snap["census"]["p50_commit_host_hops"] == 2.0
    assert snap["census"]["hop_counts"] == {"2": 1}
    assert snap["remote_spans"] == {"active": 0, "retired": 1}
    # the origin span absorbed the cross-host stamps
    names = [s for s, _ in t.completed()[-1]["stamps"]]
    assert lifecycle.STAGE_HUB_RECV in names
    assert lifecycle.STAGE_ACK_RETURN in names
    assert validate_fabric(snap) == 2


def test_link_tallies_classes_and_delivery():
    clock = iter(range(0, 10_000_000, 10)).__next__
    m = make_meter(clock=clock)
    msgs = (
        pb.Message(type=pb.MessageType.REQUEST_VOTE, to=2, from_=1),
        pb.Message(type=pb.MessageType.REPLICATE, to=2, from_=1),
        pb.Message(type=pb.MessageType.HEARTBEAT, to=2, from_=1),
        pb.Message(type=pb.MessageType.READ_INDEX, to=2, from_=1),
        pb.Message(type=pb.MessageType.LOCAL_TICK, to=2, from_=1),
    )
    m.on_send("nh-a", "nh-b", msgs, 500)
    m.on_chunk_sent("nh-a", "nh-b", 4096)
    m.on_batch_received("nh-b", pb.MessageBatch(
        requests=msgs[:2], source_address="nh-a",
        fabric=pb.FabricHeader(sent_us=0)), nbytes=200)
    snap = m.snapshot()
    (li,) = snap["links"]
    assert (li["src"], li["dst"]) == ("nh-a", "nh-b")
    assert li["sent"] == {"request_vote": 1, "append": 1, "heartbeat": 1,
                          "read_index": 1, "snapshot_chunk": 1, "other": 1}
    assert li["recv"]["request_vote"] == 1 and li["recv"]["append"] == 1
    assert li["bytes_sent"] == 500 + 4096 and li["bytes_recv"] == 200
    assert li["batches_sent"] == 1 and li["batches_recv"] == 1
    # delivery latency off the header's sender stamp and OUR clock
    assert li["delivery_count"] == 1 and li["delivery_p50_us"] >= 0
    assert validate_fabric(snap) == 1


def test_disabled_meter_is_noop_and_scrub_drops_census():
    t = lifecycle.TRACER
    t.configure(sample_every=1)
    off = make_meter(enabled=False)
    rep = pb.Message(type=pb.MessageType.REPLICATE, to=2, from_=1,
                     entries=(pb.Entry(index=1, term=1, key=64),))
    assert t.begin(64)
    assert off.header_for("nh-a", "nh-b", (rep,)) is None
    off.on_send("nh-a", "nh-b", (rep,), 100)
    off.on_batch_received("nh-b", pb.MessageBatch(
        requests=(rep,), source_address="nh-a"))
    snap = off.snapshot()
    assert snap["enabled"] is False and snap["links"] == []
    t.scrub(64)

    # scrub hook: a census entry for a dead span is dropped, not hung
    m = make_meter()
    t.set_hooks(on_finish=m._census_finish, on_scrub=m._census_drop)
    assert t.begin(128)
    hdr = m.header_for("nh-a", "nh-b", (pb.Message(
        type=pb.MessageType.REPLICATE, to=2, from_=1,
        entries=(pb.Entry(index=1, term=1, key=128),)),))
    m.on_send("nh-a", "nh-b", (), 0, hdr)
    assert m.snapshot()["census"]["active"] == 1
    t.scrub(128)
    cen = m.snapshot()["census"]
    assert cen == {"active": 0, "finished": 0, "dropped": 1,
                   "p50_commit_host_hops": 0.0, "hop_counts": {}}


def test_link_classes_snapshot_validate_and_doctor_render():
    """Round 17 carrier classes: set_link_class lands in the snapshot,
    validate_fabric gates the vocabulary, drop_link_classes forgets a
    detached endpoint both directions, and fleet_doctor renders the
    resident/hub split."""
    m = make_meter()
    m.set_link_class("nh-a", "nh-b", "resident")
    m.set_link_class("nh-b", "nh-a", "resident")
    m.set_link_class("nh-a", "nh-c", "hub")
    with pytest.raises(ValueError, match="unknown link class"):
        m.set_link_class("nh-a", "nh-d", "warp")
    snap = m.snapshot()
    assert snap["link_classes"] == {"nh-a->nh-b": "resident",
                                    "nh-b->nh-a": "resident",
                                    "nh-a->nh-c": "hub"}
    validate_fabric(snap)
    bad = json.loads(json.dumps(snap))
    bad["link_classes"]["nh-a->nh-b"] = "warp"
    with pytest.raises(ValueError, match="unknown link class"):
        validate_fabric(bad)
    bad = json.loads(json.dumps(snap))
    del bad["link_classes"]
    with pytest.raises(ValueError, match="link_classes"):
        validate_fabric(bad)
    fd = _load_script("fleet_doctor")
    out = fd.render_fabric(snap)
    assert "link classes: hub=1 resident=2" in out
    assert "resident: nh-a->nh-b nh-b->nh-a" in out
    assert "hub: nh-a->nh-c" in out
    m.drop_link_classes("nh-b")
    assert m.snapshot()["link_classes"] == {"nh-a->nh-c": "hub"}


def test_validate_fabric_rejections():
    m = make_meter()
    m.on_send("nh-a", "nh-b", (pb.Message(
        type=pb.MessageType.HEARTBEAT, to=2, from_=1),), 64)
    ok = m.snapshot()
    assert validate_fabric(ok) == 1
    with pytest.raises(ValueError, match="must be an object"):
        validate_fabric([])
    for missing in ("enabled", "links", "census", "remote_spans", "hubs"):
        bad = dict(ok)
        del bad[missing]
        with pytest.raises(ValueError, match=missing):
            validate_fabric(bad)
    bad = json.loads(json.dumps(ok))
    bad["links"][0]["sent"]["warp"] = 1
    with pytest.raises(ValueError, match="unknown message class"):
        validate_fabric(bad)
    bad = json.loads(json.dumps(ok))
    bad["links"][0]["bytes_sent"] = -1
    with pytest.raises(ValueError, match="non-negative"):
        validate_fabric(bad)
    bad = json.loads(json.dumps(ok))
    bad["census"]["hop_counts"] = {"x": 1}
    with pytest.raises(ValueError, match="digit string"):
        validate_fabric(bad)
    bad = json.loads(json.dumps(ok))
    bad["hubs"]["nh-a"] = {"queue_msgs": 0, "queue_bytes": 0,
                           "breakers": {"nh-b": "melted"}}
    with pytest.raises(ValueError, match="unknown.*state"):
        validate_fabric(bad)


# -- read-path lifecycle spans (satellite 1) ---------------------------------

def test_read_span_stages_and_histogram_labels():
    reg = telemetry.Registry()
    t = make_tracer(registry=reg)
    assert t.begin_read(5, shard_id=2)
    t.stamp(5, lifecycle.STAGE_READ_QUORUM)
    t.finish(5)
    (tr,) = t.completed()
    assert tr["kind"] == lifecycle.KIND_READ and tr["shard_id"] == 2
    assert [s for s, _ in tr["stamps"]] == [
        "read_propose", "read_quorum", "read_serve"]
    fams = telemetry.parse_exposition(reg.exposition())
    by_label = {lb.get("stage"): v
                for nm, lb, v in fams["commit_stage_us"]["samples"]
                if nm.endswith("_count")}
    assert by_label == {"read_quorum": 1, "read_serve": 1,
                        "read_total": 1}


def test_read_book_traces_quorum_to_serve_and_scrubs():
    t = lifecycle.TRACER
    t.configure(sample_every=1)
    book = PendingReadIndex(clock=LogicalClock(), shard_id=4)
    rs = book.read(timeout_ticks=100)
    assert t.active_count() == 1
    ctx = book.peep()
    book.add_ready(ctx, 5)
    book.applied(5)
    assert rs.wait(1).completed()
    tr = t.completed()[-1]
    assert tr["kind"] == lifecycle.KIND_READ and tr["key"] == rs.key
    assert [s for s, _ in tr["stamps"]] == [
        "read_propose", "read_quorum", "read_serve"]
    # removal verbs scrub, never trace
    book.read(timeout_ticks=100)
    book.terminate_all()
    assert t.active_count() == 0 and t.counts()["scrubbed"] == 1


# -- E2E: stitched cross-host traces over both transports --------------------

def _chan_cluster(prefix, depth):
    addrs = {i: f"{prefix}-{i}" for i in range(1, 4)}
    hosts = {}
    for rid, addr in addrs.items():
        nh = NodeHost(NodeHostConfig(
            raft_address=addr, rtt_millisecond=5,
            expert=ExpertConfig(kernel_log_cap=256, kernel_capacity=8,
                                kernel_apply_batch=16,
                                kernel_compaction_overhead=16,
                                kernel_pipeline_depth=depth,
                                trace_sample_every=1)))
        nh.start_replica(addrs, False, KVStateMachine, Config(
            shard_id=1, replica_id=rid, election_rtt=10, heartbeat_rtt=2,
            compaction_overhead=5, device_resident=True))
        hosts[rid] = nh
    return hosts


def _tcp_cluster(wire):
    ports = free_ports(3)
    addrs = {i: f"127.0.0.1:{ports[i - 1]}" for i in range(1, 4)}
    hosts = {}
    for rid, addr in addrs.items():
        nh = NodeHost(NodeHostConfig(
            raft_address=addr, rtt_millisecond=5,
            transport_factory=TCPTransportFactory(wire=wire),
            expert=ExpertConfig(trace_sample_every=1)))
        nh.start_replica(addrs, False, KV, Config(
            shard_id=1, replica_id=rid, election_rtt=10, heartbeat_rtt=1,
            compaction_overhead=2))
        hosts[rid] = nh
    return hosts


def _wait_fabric(pred, timeout=30):
    deadline = time.time() + timeout
    snap = None
    while time.time() < deadline:
        snap = fabric.METER.snapshot()
        if pred(snap):
            return snap
        time.sleep(0.1)
    raise AssertionError(f"fabric condition never met; last census="
                         f"{snap and snap['census']} remote="
                         f"{snap and snap['remote_spans']}")


def _assert_stitched_trace(min_hosts=2):
    """The acceptance check: the merged lifecycle + fabric export is one
    valid Chrome trace with remote child spans from >= min_hosts hosts
    sharing tids with the origin's lifecycle spans."""
    events = fabric.METER.chrome_events()
    pids = {e["pid"] for e in events}
    assert len(pids) >= min_hosts, f"remote spans from {pids} only"
    assert all(p >= fabric.HOST_PID_BASE for p in pids)
    merged = lifecycle.TRACER.export_chrome_trace()
    lc_tids = {e["tid"] for e in merged["traceEvents"]}
    merged["traceEvents"] = merged["traceEvents"] + events
    obj = json.loads(json.dumps(merged))
    assert validate_chrome_trace(obj) == len(merged["traceEvents"])
    # stitching: a remote span rides the SAME tid as its origin span
    assert any(e["tid"] in lc_tids for e in events), \
        "no remote span shares a tid with a lifecycle span"


@pytest.mark.parametrize("depth", [0, 1], ids=["serial", "pipelined"])
def test_e2e_stitched_trace_chan(depth):
    hosts = _chan_cluster(f"fab{depth}", depth)
    try:
        assert fabric.METER.enabled    # NodeHost wired the expert knob
        lead = wait_leader(hosts, timeout=30)
        nh = hosts[lead]
        sess = nh.get_noop_session(1)
        for i in range(8):
            propose_retry(nh, sess, f"f{i}=v{i}".encode())
        snap = _wait_fabric(
            lambda s: s["remote_spans"]["retired"] >= 2
            and s["census"]["finished"] >= 1
            and len({e["pid"]
                     for e in fabric.METER.chrome_events()}) >= 2)
        _assert_stitched_trace(min_hosts=2)
        # a full cross-host span: hub_send at the origin, hub_recv on
        # the remote (the PR 7 fix), the quorum ack returning home
        deadline = time.time() + 20
        want = {lifecycle.STAGE_HUB_SEND, lifecycle.STAGE_HUB_RECV,
                lifecycle.STAGE_ACK_RETURN}
        while time.time() < deadline:
            if any(want <= {s for s, _ in tr["stamps"]}
                   for tr in lifecycle.TRACER.completed()):
                break
            propose_retry(nh, sess, b"more=1")
            time.sleep(0.1)
        else:
            raise AssertionError("no trace crossed hub_send/hub_recv/"
                                 "ack_return")
        # census: every quorum round hops >= 2 (out and back)
        assert snap["census"]["p50_commit_host_hops"] >= 2.0
        # the snapshot rides NodeHost.info() and validates strictly
        assert validate_fabric(nh.info()["fabric"]) >= 2
        # both directions of at least one link carry append traffic
        by_pair = {(li["src"], li["dst"]): li for li in snap["links"]}
        assert any((d, s) in by_pair and li["sent"]["append"] > 0
                   for (s, d), li in by_pair.items())
        # read path: a served read completes a read-kind span
        assert nh.sync_read(1, "f0") == "v0"
        deadline = time.time() + 10
        while time.time() < deadline:
            if any(tr.get("kind") == lifecycle.KIND_READ
                   for tr in lifecycle.TRACER.completed()):
                break
            time.sleep(0.1)
        else:
            raise AssertionError("no completed read span")
    finally:
        close_all(hosts)


@pytest.mark.parametrize("wire", ["native", "go"])
def test_e2e_stitched_trace_tcp(wire):
    """The header survives real sockets on BOTH wire formats: the
    native frame's magic trailer and the go-wire protobuf field 15."""
    hosts = _tcp_cluster(wire)
    try:
        lead = wait_leader(hosts, timeout=30)
        nh = hosts[lead]
        sess = nh.get_noop_session(1)
        for i in range(8):
            propose_retry(nh, sess, f"t{i}=v{i}".encode())
        _wait_fabric(
            lambda s: s["remote_spans"]["retired"] >= 2
            and s["census"]["finished"] >= 1
            and len({e["pid"]
                     for e in fabric.METER.chrome_events()}) >= 2)
        _assert_stitched_trace(min_hosts=2)
        snap = fabric.METER.snapshot()
        assert snap["census"]["p50_commit_host_hops"] >= 2.0
        # delivery latency is measurable over real sockets
        assert any(li["delivery_count"] > 0 for li in snap["links"])
    finally:
        close_all(hosts)


# -- hop-census differential -------------------------------------------------

def test_hop_census_matches_pure_python_recount(monkeypatch):
    """The meter's hop histogram must equal an independent recount of
    header crossings observed at the send seam."""
    crossings = {}
    finished = []
    orig_send = fabric.METER.on_send

    def spy_send(src, dst, msgs, nbytes, header=None):
        if header is not None:
            for c in header.ctxs:
                crossings[c.key] = crossings.get(c.key, 0) + 1
        orig_send(src, dst, msgs, nbytes, header)

    def spy_finish(key, kind):
        fabric.METER._census_finish(key, kind)
        if kind == lifecycle.KIND_PROPOSAL:
            finished.append(key)

    monkeypatch.setattr(fabric.METER, "on_send", spy_send)
    lifecycle.TRACER.set_hooks(on_finish=spy_finish,
                               on_scrub=fabric.METER._census_drop)
    hosts = _chan_cluster("fabcensus", 0)
    try:
        lead = wait_leader(hosts, timeout=30)
        nh = hosts[lead]
        sess = nh.get_noop_session(1)
        for i in range(10):
            propose_retry(nh, sess, f"c{i}=v{i}".encode())
        _wait_fabric(lambda s: s["census"]["finished"] >= 5)
    finally:
        close_all(hosts)
    with fabric.METER.mu:
        hops_done = list(fabric.METER._hops_done)
    recount = sorted(crossings[k] for k in finished if k in crossings)
    assert len(hops_done) == len(finished)
    assert sorted(hops_done) == recount, (hops_done, recount)
    assert all(h >= 2 for h in recount)   # out + quorum ack, minimum


# -- hop-census regression: device-resident fabric (round 17) ----------------

# PR 19 measured the co-located quorum round over the host hub: every
# sampled commit crossed it 4 times (fabric.p50_commit_host_hops = 4.0).
# Round 17 moves co-located consensus onto the mesh, so the commit path
# must stop touching the hub entirely.
_PR19_P50_COMMIT_HOST_HOPS = 4.0


def _mesh_cluster(prefix):
    from dragonboat_tpu.config import MeshSpec

    spec = MeshSpec(name=prefix, g_size=2, replicas=3, n_local=4)
    addrs = {i: f"{prefix}-{i}" for i in range(1, 4)}
    hosts = {}
    for rid, addr in addrs.items():
        nh = NodeHost(NodeHostConfig(
            raft_address=addr, rtt_millisecond=5,
            expert=ExpertConfig(mesh=spec, kernel_log_cap=256,
                                kernel_apply_batch=16,
                                kernel_compaction_overhead=16,
                                trace_sample_every=1)))
        nh.start_replica(addrs, False, KVStateMachine, Config(
            shard_id=1, replica_id=rid, election_rtt=10, heartbeat_rtt=2,
            compaction_overhead=5, mesh_resident=True))
        hosts[rid] = nh
    return hosts


def test_hop_census_mesh_colocated_commits_skip_the_hub():
    """Mesh-co-located replicas commit WITHOUT the host hub: sampled
    commit traces carry zero hub_send/hub_recv stamps and the hop
    census medians 0 (down from the PR 19 co-located baseline of 4.0).
    Off-mesh links (a host-resident cluster in the same process) still
    stamp hub spans — the census distinguishes link classes, it does
    not go blind."""
    hub_stages = {lifecycle.STAGE_HUB_SEND, lifecycle.STAGE_HUB_RECV}

    hosts = _mesh_cluster(f"fabmesh{time.monotonic_ns()}")
    try:
        lead = wait_leader(hosts, timeout=60)
        nh = hosts[lead]
        sess = nh.get_noop_session(1)
        for i in range(8):
            propose_retry(nh, sess, f"m{i}=v{i}".encode())
        deadline = time.time() + 30
        done = []
        while time.time() < deadline:
            done = [tr for tr in lifecycle.TRACER.completed()
                    if tr.get("kind") == lifecycle.KIND_PROPOSAL]
            if len(done) >= 5:
                break
            propose_retry(nh, sess, b"mz=1")
            time.sleep(0.1)
        assert len(done) >= 5, "no sampled commit traces completed"
        for tr in done:
            stamps = {s for s, _ in tr["stamps"]}
            assert not (stamps & hub_stages), (
                f"co-located commit trace crossed the host hub: {stamps}")
        p50 = fabric.METER.snapshot()["census"]["p50_commit_host_hops"]
        assert p50 < _PR19_P50_COMMIT_HOST_HOPS, p50
        assert p50 == 0.0, (
            f"mesh-co-located commits still hop the hub (p50={p50})")
    finally:
        close_all(hosts)

    # off-mesh arm: host-resident replicas in the SAME process still
    # stamp their hub crossings (the instrumentation did not go dark)
    lifecycle.TRACER.reset()
    fabric.METER.reset()
    hosts = _chan_cluster(f"fabhub{time.monotonic_ns()}", 0)
    try:
        lead = wait_leader(hosts, timeout=30)
        nh = hosts[lead]
        sess = nh.get_noop_session(1)
        deadline = time.time() + 30
        while time.time() < deadline:
            propose_retry(nh, sess, b"h=1")
            if any({s for s, _ in tr["stamps"]} & hub_stages
                   for tr in lifecycle.TRACER.completed()
                   if tr.get("kind") == lifecycle.KIND_PROPOSAL):
                break
            time.sleep(0.1)
        else:
            raise AssertionError(
                "off-mesh commit traces lost their hub stamps")
    finally:
        close_all(hosts)


# -- chaos: partitions and delays land in the link telemetry -----------------

def test_chaos_delay_and_breaker_in_link_telemetry():
    hosts = _chan_cluster("fabchaos", 0)
    try:
        lead = wait_leader(hosts, timeout=30)
        nh = hosts[lead]
        followers = [r for r in hosts if r != lead]
        slow = followers[0]
        lead_addr = nh.config.raft_address
        slow_addr = hosts[slow].config.raft_address
        # 30ms delivery delay into one follower (receiver-side hook,
        # under the 50ms election timeout): its link's latency
        # histogram must move while the other follower's stays put
        hosts[slow].transport.delay_func = lambda m: 0.03
        sess = nh.get_noop_session(1)
        for i in range(10):
            propose_retry(nh, sess, f"d{i}=v{i}".encode())

        def delayed_visible(s):
            li = next((li for li in s["links"]
                       if (li["src"], li["dst"]) ==
                       (lead_addr, slow_addr)), None)
            return (li is not None and li["delivery_count"] >= 3
                    and li["delivery_p50_us"] >= 20_000)
        snap = _wait_fabric(delayed_visible)
        fast_addr = hosts[followers[1]].config.raft_address
        fast = next((li for li in snap["links"]
                     if (li["src"], li["dst"]) == (lead_addr, fast_addr)),
                    None)
        if fast is not None and fast["delivery_count"] >= 3:
            assert fast["delivery_p50_us"] < 20_000
        hosts[slow].transport.delay_func = None

        # kill the other follower's listener: the leader's breaker for
        # it must trip, and the snapshot must report it as non-closed
        dead = followers[1]
        hosts[dead].transport.close()
        deadline = time.time() + 30
        while time.time() < deadline:
            for i in range(3):
                try:
                    propose_retry(nh, sess, b"p=1", timeout_s=5)
                except Exception:
                    pass
            snap = fabric.METER.snapshot()
            hub = snap["hubs"].get(lead_addr, {"breakers": {}})
            if hub["breakers"].get(fast_addr, "closed") != "closed":
                break
            time.sleep(0.2)
        else:
            raise AssertionError(f"breaker never tripped: {snap['hubs']}")
        assert validate_fabric(snap) >= 2
        # the doctor's degradation rule sees exactly this
        fd = _load_script("fleet_doctor")
        assert fd._fabric_degraded(snap)
        assert "DEGRADED" in fd.render_fabric(snap)
    finally:
        close_all(hosts)


# -- /debug/fabric endpoint --------------------------------------------------

def test_debug_fabric_endpoint_and_merged_trace():
    from dragonboat_tpu.server.metrics_http import MetricsServer

    t = lifecycle.TRACER
    t.configure(sample_every=1)
    m = make_meter()
    t.set_hooks(on_finish=m._census_finish, on_scrub=m._census_drop)
    key = 64
    assert t.begin(key, shard_id=1)
    rep = pb.Message(type=pb.MessageType.REPLICATE, to=2, from_=1,
                     shard_id=1, entries=(pb.Entry(index=1, term=1,
                                                   key=key),))
    hdr = m.header_for("nh-a", "nh-b", (rep,))
    m.on_send("nh-a", "nh-b", (rep,), 80, hdr)
    m.on_batch_received("nh-b", pb.MessageBatch(
        requests=(rep,), source_address="nh-a", fabric=hdr), nbytes=80)
    resp = pb.Message(type=pb.MessageType.REPLICATE_RESP, to=1, from_=2,
                      shard_id=1)
    hdr2 = m.header_for("nh-b", "nh-a", (resp,))
    m.on_send("nh-b", "nh-a", (resp,), 30, hdr2)
    m.on_batch_received("nh-a", pb.MessageBatch(
        requests=(resp,), source_address="nh-b", fabric=hdr2), nbytes=30)
    t.finish(key)
    srv = MetricsServer([telemetry.Registry()], tracer=t,
                        fabric_source=m.snapshot,
                        fabric_trace_source=m.chrome_events)
    try:
        with urllib.request.urlopen(
                f"http://{srv.address}/debug/fabric", timeout=5) as resp:
            assert resp.headers["Content-Type"] == "application/json"
            obj = json.loads(resp.read().decode("utf-8"))
        assert validate_fabric(obj) == 2
        assert obj["census"]["finished"] == 1
        # /trace merges the remote child spans beside lifecycle spans
        with urllib.request.urlopen(
                f"http://{srv.address}/trace", timeout=5) as resp:
            trace = json.loads(resp.read().decode("utf-8"))
        assert validate_chrome_trace(trace) > 0
        cats = {e.get("cat") for e in trace["traceEvents"]}
        assert "fabric" in cats
        fab_pids = {e["pid"] for e in trace["traceEvents"]
                    if e.get("cat") == "fabric"}
        assert all(p >= fabric.HOST_PID_BASE for p in fab_pids)
    finally:
        srv.close()


# -- CLI exit matrices (satellite 3) -----------------------------------------

def _meter_snapshot(tripped=False, inconsistent=False):
    """A small real snapshot via a private meter; optionally doctored
    AFTER the fact (the meter itself cannot produce these states)."""
    m = make_meter()
    msgs = (pb.Message(type=pb.MessageType.REPLICATE, to=2, from_=1),)
    m.on_send("nh-a", "nh-b", msgs, 100)
    m.on_batch_received("nh-b", pb.MessageBatch(
        requests=msgs, source_address="nh-a"), nbytes=100)
    snap = m.snapshot()
    if tripped:
        snap["hubs"]["nh-a"] = {"queue_msgs": 3, "queue_bytes": 300,
                                "breakers": {"nh-b": "open"}}
    if inconsistent:
        li = snap["links"][0]
        li["recv"] = dict(li["recv"], append=li["sent"]["append"] + 5)
    return snap


def test_metrics_dump_and_fleet_doctor_fabric_matrix(capsys, tmp_path):
    import sys

    from dragonboat_tpu.server.metrics_http import MetricsServer

    md = _load_script("metrics_dump")
    fd = _load_script("fleet_doctor")
    state = {"fab": _meter_snapshot()}
    srv = MetricsServer([telemetry.Registry()],
                        fabric_source=lambda: state["fab"])
    argv = sys.argv
    out_path = str(tmp_path / "fabric_census.json")
    try:
        # healthy: dump validates, writes the artifact, exits 0
        sys.argv = ["metrics_dump.py", srv.address, "--fabric",
                    "--out", out_path]
        assert md.main() == 0
        out = capsys.readouterr()
        assert "ok: 1 link(s)" in out.err
        artifact = json.loads(out.out)
        assert artifact["class_totals"]["sent"]["append"] == 1
        assert artifact["consistency"]["failures"] == []
        with open(out_path, encoding="utf-8") as f:
            assert json.load(f) == artifact
        # doctor renders and exits 0
        sys.argv = ["fleet_doctor.py", srv.address, "--fabric"]
        assert fd.main() == 0
        out = capsys.readouterr().out
        assert "fabric: OK" in out and "hottest links" in out
        # --json round-trips the payload verbatim
        sys.argv = ["fleet_doctor.py", srv.address, "--fabric", "--json"]
        assert fd.main() == 0
        assert json.loads(capsys.readouterr().out) == state["fab"]
        # tripped breaker: doctor degrades (exit 1)
        state["fab"] = _meter_snapshot(tripped=True)
        sys.argv = ["fleet_doctor.py", srv.address, "--fabric"]
        assert fd.main() == 1
        assert "DEGRADED" in capsys.readouterr().out
        # send/recv inconsistency on a both-ends-visible link: dump
        # exits 1 and names the class
        state["fab"] = _meter_snapshot(inconsistent=True)
        sys.argv = ["metrics_dump.py", srv.address, "--fabric",
                    "--out", out_path]
        assert md.main() == 1
        assert "consistency" in capsys.readouterr().err
        # schema drift: dump 1, doctor 2
        state["fab"] = dict(_meter_snapshot(), surprise=1)
        del state["fab"]["census"]
        sys.argv = ["metrics_dump.py", srv.address, "--fabric"]
        assert md.main() == 1
        assert "schema validation failed" in capsys.readouterr().err
        sys.argv = ["fleet_doctor.py", srv.address, "--fabric"]
        assert fd.main() == 2
        capsys.readouterr()
        # flag conflicts are argparse errors
        sys.argv = ["fleet_doctor.py", srv.address, "--fabric",
                    "--shard", "1"]
        with pytest.raises(SystemExit):
            fd.main()
        capsys.readouterr()
    finally:
        sys.argv = argv
        srv.close()
    # unreachable endpoint: both exit 2
    sys.argv = ["metrics_dump.py", srv.address, "--fabric"]
    try:
        assert md.main() == 2
        sys.argv = ["fleet_doctor.py", srv.address, "--fabric"]
        assert fd.main() == 2
    finally:
        sys.argv = argv
    capsys.readouterr()


def test_build_fabric_census_pairs_transfer_ledger(tmp_path):
    md = _load_script("metrics_dump")
    snap = _meter_snapshot()
    artifact = md.build_fabric_census(snap)
    assert artifact["p50_commit_host_hops"] == \
        snap["census"]["p50_commit_host_hops"]
    assert artifact["consistency"]["checked_links"] == 1
    # a one-sided link (cross-process peer) is exempt from the check
    one_sided = _meter_snapshot()
    one_sided["links"][0]["batches_recv"] = 0
    one_sided["links"][0]["recv"] = dict.fromkeys(
        fabric.MESSAGE_CLASSES, 0)
    a2 = md.build_fabric_census(one_sided)
    assert a2["consistency"]["checked_links"] == 0
    assert a2["consistency"]["failures"] == []
