"""Engine-unity pass (analysis/engine_unity.py): every EU rule must
fire on a tampered fixture and stay silent on the clean one, the real
repo must be clean, the lint runner must treat engine/ edits as
invalidating the pass under --changed-only, and EU findings must flow
through the json artifact into lint_summary."""

from __future__ import annotations

import importlib.util
import json
import os
import textwrap

from dragonboat_tpu.analysis import engine_unity

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load(path, name):
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# A minimal unified-engine repo: one step-loop owner, one subclass that
# only uses sanctioned seams, one dispatch backend wiring the declared
# donated + non-donated entry pair through TRACKER.wrap.  All fixture
# sources are column-0 so they compose by plain concatenation.
DISPATCH_SRC = '''\
STEP_LOOP_OWNER = "Owner"
STEP_LOOP_METHODS = ("step_all", "_kernel_call", "_process_outputs")
DISPATCH_SEAMS = ("_make_dispatch",)
ENGINE_FEATURE_KNOBS = ("pipeline_depth",)
ENGINE_FEATURE_CALLS = ("output_row_flags",)
DISPATCH_ENTRIES = {
    "step": {
        "module": "core/kernel.py",
        "function": "step",
        "donated": False,
        "waiver": "depth-0 oracle must leave inputs readable",
    },
    "step_donated": {
        "module": "core/kernel.py",
        "function": "step_donated",
        "donated": True,
        "waiver": "",
    },
}


class SerialBackend:
    def __init__(self, cap, step_fn, donated_fn):
        self.entries = {
            "step": cap.TRACKER.wrap("step", step_fn),
            "step_donated": cap.TRACKER.wrap("step_donated", donated_fn),
        }

    def dispatch(self, state, inbox, inp, donate):
        entry = self.entries["step_donated" if donate else "step"]
        return entry(state, inbox, inp)
'''

ENGINE_SRC = '''\
class Owner:
    def __init__(self):
        self._pending_ctx = None
        self._dispatch = self._make_dispatch()

    def _make_dispatch(self):
        return None

    def step_all(self):
        if self.pipeline_depth > 0 and self._pending_ctx is not None:
            pending, self._pending_ctx = self._pending_ctx, None
            self._process_outputs(pending)
        ctx = self._kernel_call()
        if self.pipeline_depth > 0:
            self._pending_ctx = ctx
        else:
            self._process_outputs(ctx)
        return True

    def _kernel_call(self):
        return self._dispatch.dispatch(
            None, None, None, donate=self.pipeline_depth > 0)

    def _process_outputs(self, ctx):
        return output_row_flags(ctx)


class MeshSub(Owner):
    def _make_dispatch(self):
        return None
'''

KERNEL_SRC = '''\
import functools

import jax


def step(kp, state, inbox):
    return state


@functools.partial(jax.jit, static_argnums=(0,), donate_argnums=(1, 2))
def step_donated(kp, state, inbox):
    return state
'''


def _mini_repo(tmp_path, dispatch=DISPATCH_SRC, engine=ENGINE_SRC,
               kernel=KERNEL_SRC, extra=None):
    eng = tmp_path / "dragonboat_tpu" / "engine"
    eng.mkdir(parents=True)
    (eng / "dispatch.py").write_text(dispatch)
    (eng / "engine.py").write_text(engine)
    core = tmp_path / "core"
    core.mkdir()
    (core / "kernel.py").write_text(kernel)
    for name, src in (extra or {}).items():
        p = tmp_path / name
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return str(tmp_path)


def _rules(findings):
    return {f.rule for f in findings}


# ------------------------------------------------------------------ clean


def test_clean_unified_fixture_has_no_findings(tmp_path):
    assert engine_unity.run(_mini_repo(tmp_path)) == []


def test_real_repo_is_clean():
    assert engine_unity.run(REPO) == []


# ------------------------------------------------------------------ EU001


def test_eu001_subclass_step_loop_override_fires(tmp_path):
    root = _mini_repo(tmp_path, engine=ENGINE_SRC + '''

class Rogue(MeshSub):
    def _process_outputs(self, ctx):
        return ctx
''')
    fs = engine_unity.run(root)
    eu1 = [f for f in fs if f.rule == "EU001"]
    assert len(eu1) == 1
    assert "Rogue._process_outputs" in eu1[0].message
    assert eu1[0].path.endswith("engine.py")


def test_eu001_sanctioned_seam_override_is_clean(tmp_path):
    # MeshSub overrides _make_dispatch (a DISPATCH_SEAMS member) in the
    # base fixture and produces nothing
    fs = engine_unity.run(_mini_repo(tmp_path))
    assert "EU001" not in _rules(fs)


# ------------------------------------------------------------------ EU002


def test_eu002_per_path_feature_drift_fires(tmp_path):
    # the subclass grows its own step_all that never consults
    # pipeline_depth: the knob gates dispatch on Owner only
    root = _mini_repo(tmp_path, engine=ENGINE_SRC + '''

class Drifted(Owner):
    def step_all(self):
        pending, self._pending_ctx = self._pending_ctx, None
        self._process_outputs(pending)
        self._pending_ctx = self._kernel_call()
        return True

    def _kernel_call(self):
        return self._dispatch.dispatch(None, None, None, donate=True)
''')
    fs = engine_unity.run(root)
    drift = [f for f in fs if f.rule == "EU002"]
    assert any("pipeline_depth" in f.message and "Drifted" in f.message
               for f in drift)


def test_eu002_dead_knob_fires_at_declaration(tmp_path):
    root = _mini_repo(tmp_path, dispatch=DISPATCH_SRC.replace(
        'ENGINE_FEATURE_KNOBS = ("pipeline_depth",)',
        'ENGINE_FEATURE_KNOBS = ("pipeline_depth", "ghost_knob")'))
    fs = engine_unity.run(root)
    dead = [f for f in fs if f.rule == "EU002"
            and "ghost_knob" in f.message]
    assert len(dead) == 1
    assert dead[0].path == engine_unity.DISPATCH_FILE
    assert "dead dispatch feature" in dead[0].message


# ------------------------------------------------------------------ EU003


def test_eu003_donated_entry_without_donate_argnums(tmp_path):
    root = _mini_repo(tmp_path, kernel='''\
def step(kp, state, inbox):
    return state


def step_donated(kp, state, inbox):
    return state
''')
    fs = engine_unity.run(root)
    assert any(f.rule == "EU003" and "no donate_argnums" in f.message
               for f in fs)


def test_eu003_non_donated_entry_without_waiver(tmp_path):
    root = _mini_repo(tmp_path, dispatch=DISPATCH_SRC.replace(
        '"waiver": "depth-0 oracle must leave inputs readable",',
        '"waiver": "",'))
    fs = engine_unity.run(root)
    assert any(f.rule == "EU003" and "declares no waiver" in f.message
               and "'step'" in f.message for f in fs)


def test_eu003_backend_selecting_undeclared_entry(tmp_path):
    root = _mini_repo(tmp_path, dispatch=DISPATCH_SRC + '''

class RogueBackend:
    def __init__(self, cap, fn):
        self.entries = {
            "step": cap.TRACKER.wrap("step", fn),
            "step_donated": cap.TRACKER.wrap("step_donated", fn),
        }

    def dispatch(self, state, inbox, inp, donate):
        return self.entries["bespoke_step"](state, inbox, inp)
''')
    fs = engine_unity.run(root)
    assert any(f.rule == "EU003" and "bespoke_step" in f.message
               and "undeclared" in f.message for f in fs)


def test_eu003_donated_entry_missing_kstate_donation(tmp_path):
    # a kstate DONATION table exists but never declares the entry
    root = _mini_repo(tmp_path, extra={
        "dragonboat_tpu/core/kstate.py": "DONATION = {}\n"})
    fs = engine_unity.run(root)
    assert any(f.rule == "EU003"
               and "kstate.DONATION" in f.message for f in fs)


def test_eu003_kstate_donation_declared_is_clean(tmp_path):
    root = _mini_repo(tmp_path, extra={
        "dragonboat_tpu/core/kstate.py": """\
            DONATION = {
                "step_donated": {
                    "module": "core/kernel.py",
                    "function": "step_donated",
                },
            }
        """})
    assert "EU003" not in _rules(engine_unity.run(root))


# ------------------------------------------------------------------ EU004

ENGINE_DISPATCH_FIRST_SRC = '''\
class Owner:
    def __init__(self):
        self._pending_ctx = None
        self._dispatch = self._make_dispatch()

    def _make_dispatch(self):
        return None

    def step_all(self):
        ctx = self._kernel_call()
        if self.pipeline_depth > 0 and self._pending_ctx is not None:
            pending, self._pending_ctx = self._pending_ctx, None
            self._process_outputs(pending)
        self._pending_ctx = ctx
        return True

    def _kernel_call(self):
        return self._dispatch.dispatch(
            None, None, None, donate=self.pipeline_depth > 0)

    def _process_outputs(self, ctx):
        return output_row_flags(ctx)
'''


def test_eu004_dispatch_before_retire_fires(tmp_path):
    root = _mini_repo(tmp_path, engine=ENGINE_DISPATCH_FIRST_SRC)
    fs = engine_unity.run(root)
    assert any(f.rule == "EU004"
               and "retire-before-dispatch order broken" in f.message
               for f in fs)


def test_eu004_no_carried_ctx_fires(tmp_path):
    root = _mini_repo(tmp_path, engine='''\
class Owner:
    def __init__(self):
        self._dispatch = self._make_dispatch()

    def _make_dispatch(self):
        return None

    def step_all(self):
        ctx = self._kernel_call()
        self._process_outputs(ctx)
        return True

    def _kernel_call(self):
        return self._dispatch.dispatch(
            None, None, None, donate=self.pipeline_depth > 0)

    def _process_outputs(self, ctx):
        return output_row_flags(ctx)
''')
    fs = engine_unity.run(root)
    assert any(f.rule == "EU004" and "_pending_ctx" in f.message
               for f in fs)


DISPATCH_NO_DONATE_SRC = DISPATCH_SRC[:DISPATCH_SRC.index(
    "class SerialBackend")] + '''\
class SerialBackend:
    def __init__(self, cap, step_fn, donated_fn):
        self.entries = {
            "step": cap.TRACKER.wrap("step", step_fn),
        }

    def dispatch(self, state, inbox, inp, donate):
        return self.entries["step"](state, inbox, inp)
'''


def test_eu004_backend_without_donated_entry_fires(tmp_path):
    root = _mini_repo(tmp_path, dispatch=DISPATCH_NO_DONATE_SRC)
    fs = engine_unity.run(root)
    assert any(f.rule == "EU004" and "pipelining parity" in f.message
               and "SerialBackend" in f.message for f in fs)
    # the declared donated entry is also no longer tracker-wrapped
    assert any(f.rule == "EU005" and "never" in f.message for f in fs)


# ------------------------------------------------------------------ EU005


def test_eu005_untracked_jit_in_engine_layer(tmp_path):
    root = _mini_repo(tmp_path, extra={
        "dragonboat_tpu/engine/rogue.py": """\
            import jax


            def make_entry(fn):
                return jax.jit(fn, donate_argnums=(0,))
        """})
    fs = engine_unity.run(root)
    assert any(f.rule == "EU005" and "jax.jit" in f.message
               and f.path.endswith("rogue.py") for f in fs)


def test_eu005_jit_inside_tracker_wrap_is_clean(tmp_path):
    root = _mini_repo(tmp_path, extra={
        "dragonboat_tpu/engine/wrapped.py": """\
            import jax

            from dragonboat_tpu import capacity as _cap


            def make_entry(fn):
                return _cap.TRACKER.wrap("aux", jax.jit(fn))
        """})
    fs = engine_unity.run(root)
    assert not any(f.rule == "EU005" and f.path.endswith("wrapped.py")
                   for f in fs)


def test_eu005_direct_entry_call_bypassing_tracker(tmp_path):
    root = _mini_repo(tmp_path, extra={
        "dragonboat_tpu/engine/direct.py": """\
            from core.kernel import step_donated as fast_step


            def sneak(state):
                return fast_step(None, state, None)
        """})
    fs = engine_unity.run(root)
    assert any(f.rule == "EU005" and "step_donated" in f.message
               and f.path.endswith("direct.py") for f in fs)


# ------------------------------------------------------------------ EU006


def test_eu006_private_import_from_kernel_internals(tmp_path):
    root = _mini_repo(tmp_path, extra={
        "dragonboat_tpu/engine/leaky.py": """\
            from dragonboat_tpu.core.kernel import _ring_advance


            def poke(state):
                return _ring_advance(state)
        """})
    fs = engine_unity.run(root)
    assert any(f.rule == "EU006" and "_ring_advance" in f.message
               for f in fs)


def test_eu006_private_attribute_through_module_alias(tmp_path):
    root = _mini_repo(tmp_path, extra={
        "dragonboat_tpu/engine/leaky.py": """\
            from dragonboat_tpu.parallel import ici as _ici


            def poke(kp, cluster, state, box, inp, cut):
                return _ici._jit_serve_step(
                    kp, cluster, state, box, inp, cut)
        """})
    fs = engine_unity.run(root)
    assert any(f.rule == "EU006" and "_jit_serve_step" in f.message
               for f in fs)


def test_eu006_public_imports_are_clean(tmp_path):
    root = _mini_repo(tmp_path, extra={
        "dragonboat_tpu/engine/fine.py": """\
            from dragonboat_tpu.core import params as KP
            from dragonboat_tpu.parallel.ici import IciCluster


            def shape(spec):
                return KP.KernelParams, IciCluster
        """})
    fs = engine_unity.run(root)
    assert "EU006" not in _rules(fs)


# -------------------------------------------------- lint.py integration


def test_lint_registers_engine_unity_pass():
    lint = _load(os.path.join(REPO, "scripts", "lint.py"), "lint_eu")
    assert "engine-unity" in lint.PASSES
    assert lint.PASS_SCOPES["engine-unity"] == engine_unity.SCOPE


def test_changed_only_engine_edit_invalidates_pass():
    lint = _load(os.path.join(REPO, "scripts", "lint.py"), "lint_eu2")
    for changed in (["dragonboat_tpu/engine/kernel_engine.py"],
                    ["dragonboat_tpu/engine/dispatch.py"],
                    ["dragonboat_tpu/core/kernel.py"],
                    ["dragonboat_tpu/parallel/ici.py"]):
        assert "engine-unity" in lint.select_changed(changed), changed
    assert "engine-unity" not in lint.select_changed(["README.md"])


def test_eu_findings_flow_through_json_and_summary(tmp_path):
    root = _mini_repo(tmp_path, extra={
        "dragonboat_tpu/engine/leaky.py": """\
            from dragonboat_tpu.core.kernel import _ring_advance
        """})
    fs = engine_unity.run(root)
    assert fs
    lint = _load(os.path.join(REPO, "scripts", "lint.py"), "lint_eu3")
    sarif = lint.to_sarif(fs, [])
    assert any(r["ruleId"] == "EU006"
               for r in sarif["runs"][0]["results"])
    lines = [json.dumps({"path": f.path, "line": f.line,
                         "pass": f.pass_name, "rule": f.rule,
                         "message": f.message, "waived": False,
                         "reason": None}) for f in fs]
    summary = _load(os.path.join(REPO, "scripts", "lint_summary.py"),
                    "lint_summary_eu")
    report, unwaived = summary.summarize(lines)
    assert unwaived == len(fs)
    assert "engine-unity" in report and "EU006" in report
