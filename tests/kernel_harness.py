"""Kernel test harness: loopback router over the batched step kernel.

One kernel row = one replica of one raft group.  The router plays transport:
it gathers each step's outbound lanes (responses, replicate/heartbeat/vote
lanes) and scatters them into the inboxes of target rows — the in-process
analog of the reference's chan transport (plugin/chan), and the model for
device-to-device ICI routing later.
"""

from __future__ import annotations

import numpy as np

from dragonboat_tpu import raftpb as pb
from dragonboat_tpu.core import params as KP
from dragonboat_tpu.core.kernel import step
from dragonboat_tpu.core.kstate import ShardState, empty_inbox, empty_input, init_state

MT = pb.MessageType


class Msg:
    __slots__ = ("mtype", "frm", "to", "term", "log_term", "log_index",
                 "commit", "reject", "hint", "hint_high", "ents")

    def __init__(self, mtype, frm, to, term, log_term=0, log_index=0, commit=0,
                 reject=False, hint=0, hint_high=0, ents=()):
        self.mtype = int(mtype)
        self.frm = int(frm)
        self.to = int(to)
        self.term = int(term)
        self.log_term = int(log_term)
        self.log_index = int(log_index)
        self.commit = int(commit)
        self.reject = bool(reject)
        self.hint = int(hint)
        self.hint_high = int(hint_high)
        self.ents = ents  # list[(term, is_cc)]

    def __repr__(self):
        return (f"Msg({MT(self.mtype).name} {self.frm}->{self.to} t{self.term} "
                f"li{self.log_index} c{self.commit} rej{int(self.reject)} "
                f"ents{len(self.ents)})")


class KernelCluster:
    """num_groups raft groups × replicas-per-group rows in one kernel state."""

    def __init__(self, num_groups: int, replicas: int = 3,
                 kp: KP.KernelParams | None = None,
                 election: int = 10, heartbeat: int = 1,
                 check_quorum: bool = False, pre_vote: bool = False,
                 witnesses: frozenset[int] | set[int] = frozenset()):
        # one shared small geometry across tests → a single kernel compile
        self.kp = kp or KP.KernelParams(
            num_peers=max(3, replicas), log_cap=256, inbox_cap=4,
            msg_entries=4, proposal_cap=4, readindex_cap=4,
        )
        self.n = num_groups
        self.p = replicas
        self.witnesses = frozenset(witnesses)
        G = num_groups * replicas
        self.G = G
        rids = np.tile(np.arange(1, replicas + 1, dtype=np.int32), num_groups)
        peer_ids = np.zeros((G, self.kp.num_peers), np.int32)
        peer_ids[:, :replicas] = np.arange(1, replicas + 1, dtype=np.int32)
        peer_kinds = np.where(peer_ids != 0, KP.K_VOTER,
                              KP.K_ABSENT).astype(np.int32)
        for rid_w in self.witnesses:
            peer_kinds[:, rid_w - 1] = KP.K_WITNESS
        self.state: ShardState = init_state(
            self.kp, G, rids, peer_ids, peer_kinds=peer_kinds,
            election_timeout=election, heartbeat_timeout=heartbeat,
            check_quorum=check_quorum, pre_vote=pre_vote,
        )
        self.pending: list[list[Msg]] = [[] for _ in range(G)]  # inbox queues
        self.dropped_pairs: set[tuple[int, int]] = set()  # (row_from, row_to)
        self.isolated: set[int] = set()
        self.last_out = None

    def row(self, group: int, rid: int) -> int:
        return group * self.p + (rid - 1)

    def enqueue(self, row: int, msg: Msg) -> None:
        self.pending[row].append(msg)

    def _route(self, out) -> None:
        """Scatter one step's outbound lanes into pending queues."""
        o = {k: (np.asarray(v) if v is not None else None)
             for k, v in out._asdict().items()}
        K, P_, E = self.kp.inbox_cap, self.kp.num_peers, self.kp.msg_entries
        for g in range(self.G):
            group = g // self.p
            my_rid = g % self.p + 1
            if g in self.isolated:
                continue

            def deliver(to_rid, msg):
                if to_rid < 1 or to_rid > self.p:
                    return
                row = self.row(group, to_rid)
                if row in self.isolated or (g, row) in self.dropped_pairs:
                    return
                self.pending[row].append(msg)

            for k in range(K):
                t = int(o["r_type"][g, k])
                if t != 0:
                    deliver(int(o["r_to"][g, k]), Msg(
                        t, my_rid, int(o["r_to"][g, k]), int(o["r_term"][g, k]),
                        log_index=int(o["r_log_index"][g, k]),
                        reject=bool(o["r_reject"][g, k]),
                        hint=int(o["r_hint"][g, k]),
                        hint_high=int(o["r_hint_high"][g, k]),
                    ))
            for p_ in range(P_):
                to_rid = p_ + 1
                if bool(o["s_rep"][g, p_]):
                    n = int(o["s_n_ent"][g, p_])
                    ents = [
                        (int(o["s_ent_term"][g, p_, e]), bool(o["s_ent_cc"][g, p_, e]))
                        for e in range(n)
                    ]
                    deliver(to_rid, Msg(
                        MT.REPLICATE, my_rid, to_rid, int(o["term"][g]),
                        log_term=int(o["s_prev_term"][g, p_]),
                        log_index=int(o["s_prev_index"][g, p_]),
                        commit=int(o["s_commit"][g, p_]), ents=ents,
                    ))
                if bool(o["s_hb"][g, p_]):
                    deliver(to_rid, Msg(
                        MT.HEARTBEAT, my_rid, to_rid, int(o["term"][g]),
                        commit=int(o["s_hb_commit"][g, p_]),
                        hint=int(o["s_hb_low"][g, p_]),
                        hint_high=int(o["s_hb_high"][g, p_]),
                    ))
                v = int(o["s_vote"][g, p_])
                if v:
                    deliver(to_rid, Msg(
                        MT.REQUEST_VOTE if v == 1 else MT.REQUEST_PREVOTE,
                        my_rid, to_rid, int(o["s_vote_term"][g, p_]),
                        log_term=int(o["s_vote_lterm"][g, p_]),
                        log_index=int(o["s_vote_lindex"][g, p_]),
                        hint=int(o["s_vote_hint"][g, p_]),
                    ))
                if bool(o["s_timeout_now"][g, p_]):
                    deliver(to_rid, Msg(MT.TIMEOUT_NOW, my_rid, to_rid,
                                        int(o["term"][g])))

    def _build_inbox(self):
        K, E = self.kp.inbox_cap, self.kp.msg_entries
        box = {
            "mtype": np.zeros((self.G, K), np.int32),
            "from_": np.zeros((self.G, K), np.int32),
            "term": np.zeros((self.G, K), np.int32),
            "log_term": np.zeros((self.G, K), np.int32),
            "log_index": np.zeros((self.G, K), np.int32),
            "commit": np.zeros((self.G, K), np.int32),
            "reject": np.zeros((self.G, K), bool),
            "hint": np.zeros((self.G, K), np.int32),
            "hint_high": np.zeros((self.G, K), np.int32),
            "n_ent": np.zeros((self.G, K), np.int32),
            "ent_term": np.zeros((self.G, K, E), np.int32),
            "ent_cc": np.zeros((self.G, K, E), bool),
        }
        for g in range(self.G):
            q = self.pending[g][:K]
            self.pending[g] = self.pending[g][K:]
            for k, m in enumerate(q):
                box["mtype"][g, k] = m.mtype
                box["from_"][g, k] = m.frm
                box["term"][g, k] = m.term
                box["log_term"][g, k] = m.log_term
                box["log_index"][g, k] = m.log_index
                box["commit"][g, k] = m.commit
                box["reject"][g, k] = m.reject
                box["hint"][g, k] = m.hint
                box["hint_high"][g, k] = m.hint_high
                ents = m.ents[:E]
                box["n_ent"][g, k] = len(ents)
                for e, (t, cc) in enumerate(ents):
                    box["ent_term"][g, k, e] = t
                    box["ent_cc"][g, k, e] = cc
        from dragonboat_tpu.core.kstate import Inbox

        return Inbox(**{k: np.asarray(v) for k, v in box.items()})

    def step(self, tick=False, proposals=None, reads=None, transfers=None,
             applied_sync=True):
        """One kernel step. proposals: {row: n_entries or [(is_cc)...]},
        reads: {row: (low, high)}, transfers: {row: target_rid}."""
        inp = empty_input(self.kp, self.G)
        d = {k: (np.asarray(v).copy() if v is not None else None)
             for k, v in inp._asdict().items()}
        if tick:
            d["tick"][:] = True
        if proposals:
            for row, spec in proposals.items():
                if isinstance(spec, int):
                    spec = [False] * spec
                for b, is_cc in enumerate(spec[: self.kp.proposal_cap]):
                    d["prop_valid"][row, b] = True
                    d["prop_cc"][row, b] = is_cc
        if reads:
            for row, (lo, hi) in reads.items():
                d["ri_valid"][row] = True
                d["ri_low"][row] = lo
                d["ri_high"][row] = hi
        if transfers:
            for row, target in transfers.items():
                d["transfer_to"][row] = target
        if applied_sync:
            d["applied"] = np.asarray(self.state.processed)
        from dragonboat_tpu.core.kstate import StepInput

        box = self._build_inbox()
        self.state, out = step(
            self.kp, self.state, box,
            StepInput(**{k: (np.asarray(v) if v is not None else None)
                         for k, v in d.items()}))
        self.last_out = out
        self._route(out)
        return out

    def run_until_leader(self, group: int = 0, max_steps: int = 200):
        for i in range(max_steps):
            self.step(tick=True)
            if self.leader_row(group) is not None:
                # drain in-flight messages without ticking
                for _ in range(6):
                    self.step()
                return i
        raise AssertionError("no leader elected")

    def leader_row(self, group: int):
        role = np.asarray(self.state.role)
        for r in range(group * self.p, (group + 1) * self.p):
            if role[r] == KP.LEADER:
                return r
        return None

    def drain(self, steps: int = 10):
        for _ in range(steps):
            self.step()

    def field(self, name: str):
        return np.asarray(getattr(self.state, name))
