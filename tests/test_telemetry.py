"""Telemetry layer: typed instruments, exposition round-trip, the
device-side fleet reduction vs a pure-Python recount, the flight
recorder, and the live /metrics endpoint."""

import json
import threading
import time
import urllib.request

import numpy as np
import pytest

from dragonboat_tpu.flight import FlightRecorder
from dragonboat_tpu.telemetry import (
    InstrumentTypeError,
    Registry,
    parse_exposition,
)

# ---------------------------------------------------------------------
# instruments


def test_counter_inc_and_negative_rejected():
    r = Registry()
    c = r.counter("reqs.total")
    c.inc()
    c.inc(5)
    assert r.snapshot()["reqs.total"] == 6
    with pytest.raises(ValueError):
        c.inc(-1)


def test_typed_rejections():
    r = Registry()
    r.counter("a.counter")
    r.gauge("a.gauge")
    # wrong verb on an existing name
    with pytest.raises(InstrumentTypeError):
        r.gauge("a.counter")
    with pytest.raises(InstrumentTypeError):
        r.counter("a.gauge")
    # a histogram name cannot be re-registered as either
    r.histogram("a.hist")
    with pytest.raises(InstrumentTypeError):
        r.counter("a.hist")
    with pytest.raises(InstrumentTypeError):
        r.gauge("a.hist")


def test_metrics_shim_warns_once_and_applies_legacy_semantics():
    from dragonboat_tpu.events import Metrics

    m = Metrics()
    m.set("x.level", 3)           # registers a gauge
    m.inc("x.level", 2)           # legacy inc on a gauge: warn, then add
    assert m.snapshot()["x.level"] == 5
    m.inc("y.count", 4)           # registers a counter
    m.set("y.count", 1)           # legacy set on a counter: warn, force-set
    assert m.snapshot()["y.count"] == 1
    # second offence on the same name stays silent and still applies
    m.inc("x.level")
    assert m.snapshot()["x.level"] == 6


def test_histogram_bucket_boundaries():
    r = Registry()
    h = r.histogram("lat", buckets=(1.0, 10.0, 100.0))
    for v in (0.5, 1.0, 1.5, 10.0, 99.9, 1000.0):
        h.observe(v)
    cum, s, total = h.snapshot_hist()
    # le=1: 0.5, 1.0; le=10: +1.5, 10.0; le=100: +99.9; +Inf: +1000
    assert cum == [2, 4, 5, 6]
    assert total == 6
    assert abs(s - (0.5 + 1.0 + 1.5 + 10.0 + 99.9 + 1000.0)) < 1e-9


def test_concurrent_counter_inc():
    r = Registry()
    c = r.counter("par.total")
    N, T = 2000, 8

    def worker():
        for _ in range(N):
            c.inc()

    ts = [threading.Thread(target=worker) for _ in range(T)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert r.snapshot()["par.total"] == N * T


def test_labeled_family_and_label_validation():
    r = Registry()
    fam = r.counter("http.reqs", labelnames=("code",))
    fam.labels("200").inc(3)
    fam.labels(code="500").inc()
    snap = r.snapshot()
    assert snap["http.reqs{code=200}"] == 3
    assert snap["http.reqs{code=500}"] == 1
    with pytest.raises(ValueError):
        fam.labels("200", "extra")


# ---------------------------------------------------------------------
# exposition round trip


def test_exposition_round_trip_golden():
    r = Registry()
    r.counter("rt.sent", help="messages sent").inc(7)
    r.gauge("rt.depth").set(3)
    h = r.histogram("rt.lat_us", buckets=(10.0, 100.0))
    h.observe(5)
    h.observe(50)
    h.observe(5000)
    fam = r.counter("rt.coded", labelnames=("code",))
    fam.labels('we"ird\\la\nbel').inc(2)
    r.gauge_fn("rt.cb", lambda: 42.0, help="callback")
    text = r.exposition()
    fams = parse_exposition(text)

    assert fams["rt_sent"]["type"] == "counter"
    assert fams["rt_sent"]["samples"][0][2] == 7.0
    assert fams["rt_depth"]["samples"][0][2] == 3.0
    assert fams["rt_cb"]["samples"][0][2] == 42.0
    # label escaping survives the round trip
    coded = fams["rt_coded"]["samples"]
    assert coded[0][1]["code"] == 'we"ird\\la\nbel'
    # histogram: cumulative buckets, +Inf == _count, _sum preserved
    hist = fams["rt_lat_us"]
    buckets = {s[1]["le"]: s[2] for s in hist["samples"]
               if s[0].endswith("_bucket")}
    assert buckets == {"10.0": 1.0, "100.0": 2.0, "+Inf": 3.0}
    count = [s for s in hist["samples"] if s[0].endswith("_count")][0][2]
    assert count == 3.0


def test_parser_rejects_malformed():
    with pytest.raises(ValueError):
        parse_exposition("no_type_line 3\n")
    with pytest.raises(ValueError):
        parse_exposition("# TYPE x counter\n# TYPE x counter\nx 1\n")
    # non-cumulative histogram buckets
    bad = ("# TYPE h histogram\n"
           'h_bucket{le="1"} 5\nh_bucket{le="2"} 3\n'
           'h_bucket{le="+Inf"} 5\nh_sum 1\nh_count 5\n')
    with pytest.raises(ValueError):
        parse_exposition(bad)
    # missing +Inf
    bad2 = ("# TYPE h histogram\n"
            'h_bucket{le="1"} 5\nh_sum 1\nh_count 5\n')
    with pytest.raises(ValueError):
        parse_exposition(bad2)


# ---------------------------------------------------------------------
# fleet_stats differential vs a pure-Python recount


def _recount(state, inbox_from, replicas):
    """Pure-Python fleet recount — the oracle fleet_stats must match."""
    from dragonboat_tpu.core import fleet
    from dragonboat_tpu.core import params as KP

    kind = np.asarray(state.kind)
    role = np.asarray(state.role)
    leader = np.asarray(state.leader)
    term = np.asarray(state.term)
    committed = np.asarray(state.committed)
    applied = np.asarray(state.applied)
    frm = np.asarray(inbox_from)
    occ = (kind != KP.K_ABSENT).any(axis=1)
    out = {
        "occupied": int(occ.sum()),
        "role_count": [int(((role == i) & occ).sum())
                       for i in range(fleet.NUM_ROLES)],
        "leaderless": int((occ & (leader == KP.NO_LEADER)).sum()),
        "election_active": int((occ & ((role == KP.CANDIDATE)
                                       | (role == KP.PRE_VOTE_CANDIDATE))
                                ).sum()),
        "term_max": int(term[occ].max()) if occ.any() else 0,
        "term_min": int(term[occ].min()) if occ.any() else 0,
    }
    lag = committed - applied
    out["lag_hist"] = [int(((lag <= b) & occ).sum())
                       for b in fleet.LAG_BUCKETS] + [out["occupied"]]
    iocc = (frm != 0).sum(axis=1)
    out["inbox_hist"] = [int(((iocc <= b) & occ).sum())
                         for b in fleet.INBOX_BUCKETS] + [out["occupied"]]
    return out


@pytest.mark.parametrize("groups,replicas", [(1, 3), (4, 3), (8, 5)])
def test_fleet_stats_matches_python_recount(groups, replicas):
    from dragonboat_tpu.core import fleet
    from tests.kernel_harness import KernelCluster

    c = KernelCluster(groups, replicas)
    # drive real elections + some writes so roles/terms/lag are nontrivial
    for _ in range(30):
        c.step(tick=True)
    leads = [g for g in range(c.G)
             if int(np.asarray(c.state.role)[g]) == 3]
    if leads:
        c.step(proposals={leads[0]: 2})
        c.step()
    box = c._build_inbox()
    got = fleet.stats_to_dict(fleet.fleet_stats(c.state, box.from_))
    want = _recount(c.state, box.from_, replicas)
    assert got["occupied"] == want["occupied"]
    assert list(got["role_count"].values()) == want["role_count"]
    assert got["leaderless"] == want["leaderless"]
    assert got["election_active"] == want["election_active"]
    assert got["term_max"] == want["term_max"]
    assert got["term_min"] == want["term_min"]
    assert list(got["lag_hist"].values()) == want["lag_hist"]
    assert list(got["inbox_hist"].values()) == want["inbox_hist"]


# ---------------------------------------------------------------------
# flight recorder


def test_flight_wraparound_keeps_newest():
    fr = FlightRecorder(capacity=4)
    for i in range(10):
        fr.record("k", i=i)
    assert len(fr) == 4
    tail = fr.tail()
    assert [r["i"] for r in tail] == [6, 7, 8, 9]
    assert [r["seq"] for r in tail] == [6, 7, 8, 9]
    assert fr.next_seq == 10
    # tail(k) returns the newest k, oldest first
    assert [r["i"] for r in fr.tail(2)] == [8, 9]


def test_flight_crash_dump(tmp_path):
    fr = FlightRecorder(capacity=8)
    fr.record("leader_change", shard_id=1, term=3)
    fr.record("breaker_trip", addr="n2")
    path = fr.dump(str(tmp_path / "flight.json"))
    data = json.loads(open(path).read())
    assert [r["kind"] for r in data] == ["leader_change", "breaker_trip"]
    assert data[0]["term"] == 3
    # canonical: dump_json is stable across identical record streams
    fr2 = FlightRecorder(capacity=8)
    fr2.record("leader_change", shard_id=1, term=3)
    fr2.record("breaker_trip", addr="n2")
    assert fr.dump_json() == fr2.dump_json()


def test_oracle_failure_attaches_flight_tail():
    """A failing oracle report carries the flight tail (runner contract:
    the attach happens in run_schedule; here we exercise the report
    field stays pure data)."""
    from dragonboat_tpu.chaos.oracle import OracleReport

    rep = OracleReport()
    assert rep.flight_tail == []
    rep.fail("boom")
    rep.flight_tail = [{"seq": 0, "kind": "chaos_fault"}]
    assert not rep.ok and rep.flight_tail[0]["kind"] == "chaos_fault"


# ---------------------------------------------------------------------
# live endpoint


@pytest.mark.slow
def test_metrics_endpoint_live_cluster():
    """Acceptance: scraping /metrics on a running 3-replica cluster
    yields strict-parsing Prometheus text with a nonzero
    fleet_role_count{role="leader"} and populated lag buckets."""
    from dragonboat_tpu.config import Config, NodeHostConfig
    from dragonboat_tpu.nodehost import NodeHost
    from dragonboat_tpu.statemachine import IStateMachine, Result

    class _KV(IStateMachine):
        def __init__(self, *a):
            self.kv = {}

        def update(self, entry):
            k, v = bytes(entry.cmd).decode().split("=", 1)
            self.kv[k] = v
            return Result(value=len(self.kv))

        def lookup(self, q):
            return self.kv.get(q)

        def save_snapshot(self, w, files, done):
            w.write(b"\x00")

        def recover_from_snapshot(self, r, files, done):
            r.read(1)

    addrs = {1: "tm-1", 2: "tm-2", 3: "tm-3"}
    hosts = {rid: NodeHost(NodeHostConfig(
        raft_address=a, rtt_millisecond=5, enable_metrics=True))
        for rid, a in addrs.items()}
    try:
        for rid in addrs:
            hosts[rid].start_replica(addrs, False, _KV, Config(
                shard_id=1, replica_id=rid, election_rtt=10,
                heartbeat_rtt=1))
        deadline = time.time() + 30
        lid, ok = 0, False
        while time.time() < deadline:
            lid, ok = hosts[1].get_leader_id(1)
            if ok and lid:
                break
            time.sleep(0.05)
        assert ok and lid, "cluster never elected"
        for i in range(5):
            hosts[1].sync_propose(hosts[1].get_noop_session(1),
                                  f"k{i}=v".encode(), timeout_s=5)
        addr = hosts[lid].metrics_address
        assert addr and ":" in addr
        text = urllib.request.urlopen(
            f"http://{addr}/metrics", timeout=5).read().decode()
        fams = parse_exposition(text)       # strict round trip
        leader = [s for s in fams["fleet_role_count"]["samples"]
                  if s[1].get("role") == "leader"]
        assert leader and leader[0][2] >= 1.0
        lag = [s for s in fams["fleet_commit_lag_bucket"]["samples"]
               if s[1].get("le") == "+Inf"]
        assert lag and lag[0][2] >= 1.0
        # leaderless returns to 0 after convergence; the acked-write
        # counter lives on the host that served the proposals (host 1)
        snap = hosts[lid].events.metrics.snapshot()
        assert snap.get("fleet.leaderless_shards") == 0
        assert hosts[1].events.metrics.snapshot().get(
            "raft.proposals_acked") == 5
        # /flight serves JSON with the election's leader_change records
        fl = json.loads(urllib.request.urlopen(
            f"http://{addr}/flight", timeout=5).read().decode())
        assert any(r["kind"] == "leader_change" for r in fl)
    finally:
        for nh in hosts.values():
            nh.close()
