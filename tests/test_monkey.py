"""Monkey/chaos surface: NodeHost-level partitions, delay/reorder
transport hooks, and the hash convergence oracles.

Reference behaviors: monkey.go:170 PartitionNode / :178 Restore,
:83-89 transport drop hooks (extended with delay/reorder), :113-121
state/session/membership hash getters used to assert replica
convergence in the nightly chaos harness (docs/test.md).
"""

import random
import time
import zlib

from dragonboat_tpu.config import Config, NodeHostConfig
from dragonboat_tpu.nodehost import NodeHost
from dragonboat_tpu.statemachine import Result

from test_nodehost import KVStateMachine, make_cluster, wait_leader


class HashKV(KVStateMachine):
    def get_hash(self) -> int:
        data = "\n".join(f"{k}={v}" for k, v in sorted(self.kv.items()))
        return zlib.crc32(data.encode())


def _mk(prefix, rtt_ms=5):
    addrs = {i: f"{prefix}-{i}" for i in (1, 2, 3)}
    hosts = {}
    for rid, addr in addrs.items():
        nh = NodeHost(NodeHostConfig(raft_address=addr,
                                     rtt_millisecond=rtt_ms))
        nh.start_replica(addrs, False, HashKV, Config(
            shard_id=1, replica_id=rid, election_rtt=10, heartbeat_rtt=1))
        hosts[rid] = nh
    return hosts


def _converged(hosts, n_keys, timeout=15.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        hashes = {h.get_sm_hash(1) for h in hosts.values()}
        counts = [len(h._node(1).sm.sm.kv) for h in hosts.values()]
        if len(hashes) == 1 and all(c >= n_keys for c in counts):
            return True
        time.sleep(0.05)
    return False


def test_partition_heals_and_hashes_converge():
    """Partition the leader: survivors elect a new one and keep serving;
    restore: the old leader rejoins and every oracle converges."""
    hosts = _mk(f"mp{time.monotonic_ns()}")
    try:
        lid = wait_leader(hosts)
        hosts[lid].partition_node()
        survivors = {r: h for r, h in hosts.items() if r != lid}
        new_lid = wait_leader(survivors)
        assert new_lid != lid
        s = survivors[new_lid].get_noop_session(1)
        for i in range(10):
            survivors[new_lid].sync_propose(s, f"p{i}=v{i}".encode())
        hosts[lid].restore_partitioned_node()
        assert _converged(hosts, 10), "hashes did not converge after heal"
        assert len({h.get_session_hash(1) for h in hosts.values()}) == 1
        assert len({h.get_membership_hash(1) for h in hosts.values()}) == 1
    finally:
        for h in hosts.values():
            h.close()


def test_delay_and_reorder_hooks_preserve_safety():
    """With every inter-host batch delayed and shuffled, the cluster still
    commits and all replicas converge to identical state."""
    hosts = _mk(f"md{time.monotonic_ns()}")
    try:
        rng = random.Random(42)
        for h in hosts.values():
            h.transport.reorder_rng = rng
            h.transport.delay_func = lambda m: 0.002
        lid = wait_leader(hosts)
        s = hosts[lid].get_noop_session(1)
        for i in range(20):
            hosts[lid].sync_propose(s, f"d{i}=v{i}".encode())
        assert _converged(hosts, 20), "no convergence under delay+reorder"
    finally:
        for h in hosts.values():
            h.close()
