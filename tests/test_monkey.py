"""Monkey/chaos surface: NodeHost-level partitions, delay/reorder
transport hooks, and the hash convergence oracles.

Reference behaviors: monkey.go:170 PartitionNode / :178 Restore,
:83-89 transport drop hooks (extended with delay/reorder), :113-121
state/session/membership hash getters used to assert replica
convergence in the nightly chaos harness (docs/test.md).
"""

import random
import time
import zlib

from dragonboat_tpu.config import Config, NodeHostConfig
from dragonboat_tpu.nodehost import NodeHost
from dragonboat_tpu.statemachine import Result

from test_nodehost import KVStateMachine, make_cluster, wait_leader


class HashKV(KVStateMachine):
    def get_hash(self) -> int:
        data = "\n".join(f"{k}={v}" for k, v in sorted(self.kv.items()))
        return zlib.crc32(data.encode())


def _mk(prefix, rtt_ms=5, expert=None, device_resident=False):
    addrs = {i: f"{prefix}-{i}" for i in (1, 2, 3)}
    hosts = {}
    for rid, addr in addrs.items():
        kw = {"expert": expert} if expert is not None else {}
        nh = NodeHost(NodeHostConfig(raft_address=addr,
                                     rtt_millisecond=rtt_ms, **kw))
        nh.start_replica(addrs, False, HashKV, Config(
            shard_id=1, replica_id=rid, election_rtt=10, heartbeat_rtt=1,
            device_resident=device_resident))
        hosts[rid] = nh
    return hosts


def _converged(hosts, n_keys, timeout=15.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        hashes = {h.get_sm_hash(1) for h in hosts.values()}
        counts = [len(h._node(1).sm.sm.kv) for h in hosts.values()]
        if len(hashes) == 1 and all(c >= n_keys for c in counts):
            return True
        time.sleep(0.05)
    return False


def test_partition_heals_and_hashes_converge():
    """Partition the leader: survivors elect a new one and keep serving;
    restore: the old leader rejoins and every oracle converges."""
    hosts = _mk(f"mp{time.monotonic_ns()}")
    try:
        lid = wait_leader(hosts)
        hosts[lid].partition_node()
        survivors = {r: h for r, h in hosts.items() if r != lid}
        new_lid = wait_leader(survivors)
        assert new_lid != lid
        s = survivors[new_lid].get_noop_session(1)
        for i in range(10):
            survivors[new_lid].sync_propose(s, f"p{i}=v{i}".encode())
        hosts[lid].restore_partitioned_node()
        assert _converged(hosts, 10), "hashes did not converge after heal"
        assert len({h.get_session_hash(1) for h in hosts.values()}) == 1
        assert len({h.get_membership_hash(1) for h in hosts.values()}) == 1
    finally:
        for h in hosts.values():
            h.close()


def test_delay_and_reorder_hooks_preserve_safety():
    """With every inter-host batch delayed and shuffled, the cluster still
    commits and all replicas converge to identical state."""
    hosts = _mk(f"md{time.monotonic_ns()}")
    try:
        rng = random.Random(42)
        for h in hosts.values():
            h.transport.reorder_rng = rng
            h.transport.delay_func = lambda m: 0.002
        lid = wait_leader(hosts)
        s = hosts[lid].get_noop_session(1)
        for i in range(20):
            hosts[lid].sync_propose(s, f"d{i}=v{i}".encode())
        assert _converged(hosts, 20), "no convergence under delay+reorder"
    finally:
        for h in hosts.values():
            h.close()


def test_kernel_engine_partition_linearizable():
    """Chaos on the DEVICE path: 3 hosts run the shard as kernel lanes;
    concurrent clients run through a leader-host partition + heal, and
    the recorded history must be linearizable (docs/test.md monkey
    assertion, here over the batched kernel engine)."""
    import threading

    from dragonboat_tpu.config import ExpertConfig
    from dragonboat_tpu.history import HistoryRecorder, check_linearizable_kv

    hosts = _mk(f"mk{time.monotonic_ns()}",
                expert=ExpertConfig(kernel_log_cap=256, kernel_capacity=8),
                device_resident=True)
    h = HistoryRecorder()
    stop = threading.Event()

    def client(pid: int) -> None:
        rng = random.Random(pid)
        seq = 0
        while not stop.is_set():
            lid = None
            rids = list(hosts)
            rng.shuffle(rids)  # don't pin every client to a partitioned
            # old leader that still believes in itself
            for rid in rids:
                nh = hosts[rid]
                if nh._partitioned:
                    continue  # this client can see the machine is gone
                got, ok = nh.get_leader_id(1)
                if ok and got in hosts and not hosts[got]._partitioned:
                    lid = got
                    break
            if lid is None:
                time.sleep(0.02)
                continue
            nh = hosts[lid]
            try:
                if pid % 2 == 0:
                    val = f"p{pid}s{seq}"
                    seq += 1
                    rec = h.invoke(pid, "write", "x", val)
                    try:
                        nh.sync_propose(nh.get_noop_session(1),
                                        f"x={val}".encode(), timeout_s=1.0)
                        h.complete(rec)
                    except Exception:
                        pass  # open op: outcome unknown
                else:
                    rec = h.invoke(pid, "read", "x")
                    try:
                        h.complete(rec, value=nh.sync_read(1, "x",
                                                           timeout_s=1.0))
                    except Exception:
                        pass
            except Exception:
                pass
            time.sleep(0.01)

    threads = [threading.Thread(target=client, args=(p,), daemon=True)
               for p in range(4)]
    try:
        assert all(nh.nodes[1].peer is None for nh in hosts.values()), \
            "shards must be device-resident"
        wait_leader(hosts, timeout=60)  # warmup: first kernel compile is slow
        for t in threads:
            t.start()
        time.sleep(2.0)
        lid = wait_leader(hosts, timeout=30)
        hosts[lid].partition_node()
        partition_at = time.monotonic()
        time.sleep(2.0)
        heal_at = time.monotonic()
        hosts[lid].restore_partitioned_node()
        time.sleep(1.5)
        stop.set()
        for t in threads:
            t.join(timeout=5)
        completed = [o for o in h.ops if o.ret is not None]
        assert len(completed) >= 10, "history too thin to mean anything"
        # the check must certify ops that SPAN the chaos window, not just
        # steady state: require ops whose [call, ret] interval intersects
        # the partition window itself (post-heal ops don't count)
        chaos_ops = [o for o in completed
                     if o.call < heal_at and o.ret > partition_at]
        assert len(chaos_ops) >= 3, \
            f"only {len(chaos_ops)} completed ops overlap the chaos window"
        assert check_linearizable_kv(h.ops), \
            "linearizability violation on the kernel-engine path"
    finally:
        stop.set()
        for t in threads:
            if t.ident is not None:  # only join threads that started
                t.join(timeout=5)
        for nh in hosts.values():
            nh.close()
