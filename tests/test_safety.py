"""Raft safety pass (dragonboat_tpu/analysis/safety.py): the repo's
own kernel must be clean under every static obligation, each seeded
protocol mutation from the model checker's catalogue must be caught by
the rule that owns it, the RS001/RS006 declaration lint must fire on
malformed fixtures, the model-check gate must cache by source hash, and
the lint runner must register the seventh pass (including the explicit
waivers.toml invalidation and the SARIF emitter)."""

from __future__ import annotations

import importlib.util
import json
import os
import shutil
import textwrap

import pytest

from dragonboat_tpu.analysis import safety

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_lint_module():
    spec = importlib.util.spec_from_file_location(
        "lint_under_safety_test", os.path.join(REPO, "scripts", "lint.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _mutations():
    import sys

    spec = importlib.util.spec_from_file_location(
        "model_check_under_safety_test",
        os.path.join(REPO, "scripts", "model_check.py"))
    mod = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = mod
    spec.loader.exec_module(mod)
    return mod.MUTATIONS


def _mutated_root(tmp_path, find, replace):
    """A tmp repo root holding the real kstate + a mutated kernel."""
    core = tmp_path / "dragonboat_tpu" / "core"
    core.mkdir(parents=True)
    shutil.copy(os.path.join(REPO, "dragonboat_tpu/core/kstate.py"),
                core / "kstate.py")
    src = open(os.path.join(REPO, "dragonboat_tpu/core/kernel.py")).read()
    assert find in src, "mutation target drifted from kernel source"
    (core / "kernel.py").write_text(src.replace(find, replace))
    return str(tmp_path)


# ----------------------------------------------------- repo is clean


def test_repo_static_legs_clean():
    assert safety.run(REPO, dynamic=False) == []


# ------------------------------------------- seeded-mutation coverage
# ownership: which static rule catches which protocol bug (double_vote
# has no store-shape signature — the model checker owns it, see
# test_model_check.py)

STATIC_OWNER = {
    "skip_vote_persist": "RS003",
    "commit_without_quorum": "RS002",
    "truncate_committed": "RS004",
}


@pytest.mark.parametrize("mutation", sorted(STATIC_OWNER))
def test_static_rule_catches_mutation(tmp_path, mutation):
    find, replace = _mutations()[mutation]
    root = _mutated_root(tmp_path, find, replace)
    rules = {f.rule for f in safety.run(root, dynamic=False)}
    assert STATIC_OWNER[mutation] in rules, (mutation, rules)


# ------------------------------------------------- declaration lint


def _kstate_fixture(tmp_path, invariants_src):
    core = tmp_path / "dragonboat_tpu" / "core"
    core.mkdir(parents=True)
    p = core / "kstate.py"
    p.write_text(textwrap.dedent(f"""\
        CONTRACTS = {{
            "ShardState": {{
                "committed": "i32[G] part=G",
                "term": "i32[G] part=G",
            }},
        }}
        {invariants_src}
        """))
    return str(p)


def test_rs001_unparseable_invariant(tmp_path):
    p = _kstate_fixture(
        tmp_path, 'INVARIANTS = {"bad": "committed <=> term"}')
    findings, parsed = safety.check_declarations(str(tmp_path), p)
    assert [f.rule for f in findings] == ["RS001"]
    assert parsed == {}


def test_rs001_unknown_field(tmp_path):
    p = _kstate_fixture(
        tmp_path, 'INVARIANTS = {"ghost": "committed <= made_up_field"}')
    findings, _ = safety.check_declarations(str(tmp_path), p)
    assert [f.rule for f in findings] == ["RS001"]
    assert "made_up_field" in findings[0].message


def test_rs006_missing_and_empty(tmp_path):
    p = _kstate_fixture(tmp_path, "")
    findings, _ = safety.check_declarations(str(tmp_path), p)
    assert [f.rule for f in findings] == ["RS006"]
    p2 = _kstate_fixture((tmp_path / "e"), "INVARIANTS = {}")
    findings, _ = safety.check_declarations(str(tmp_path / "e"), p2)
    assert [f.rule for f in findings] == ["RS006"]


def test_rs006_empty_declarations_flagged_via_run(tmp_path):
    """run() on a fixture file set surfaces the declaration findings
    and skips the dynamic gate."""
    p = _kstate_fixture(tmp_path, "INVARIANTS = {}")
    findings = safety.run(str(tmp_path), files=[p])
    assert [f.rule for f in findings] == ["RS006"]


# ------------------------------------------------ model-check caching


def test_gate_cache_hit_and_source_invalidation(tmp_path, monkeypatch):
    """A cached verdict is replayed verbatim; any hashed-source edit
    misses.  The gate itself is monkeypatched out so this stays fast."""
    calls = {"n": 0}

    class _FakeMC:
        @staticmethod
        def run_scope(scope, root=None):
            calls["n"] += 1
            return {"scope": scope, "states_explored": 1,
                    "transitions": 0, "frontier_exhausted": True,
                    "scope_complete": True, "violations": []}

    monkeypatch.setattr(safety, "_load_model_check", lambda root: _FakeMC)
    root = str(tmp_path)
    os.makedirs(os.path.join(root, "dragonboat_tpu/analysis"))
    os.makedirs(os.path.join(root, "dragonboat_tpu/core"))
    kernel = os.path.join(root, "dragonboat_tpu/core/kernel.py")
    open(kernel, "w").write("x = 1\n")

    assert safety.model_check_gate(root) == []
    assert calls["n"] == 1
    assert safety.model_check_gate(root) == []
    assert calls["n"] == 1                       # cache hit
    open(kernel, "w").write("x = 2\n")
    assert safety.model_check_gate(root) == []
    assert calls["n"] == 2                       # source edit missed


def test_gate_replays_cached_violations(tmp_path):
    root = str(tmp_path)
    os.makedirs(os.path.join(root, "dragonboat_tpu/analysis"))
    key = safety._source_key(root)
    with open(os.path.join(root, safety.CACHE_FILE), "w") as f:
        json.dump({"key": key, "messages": ["boom"]}, f)
    findings = safety.model_check_gate(root)
    assert [f.rule for f in findings] == ["RS005"]
    assert findings[0].message == "boom"


# --------------------------------------------- lint runner integration


def test_lint_registers_safety_pass_and_scope():
    mod = _load_lint_module()
    assert "safety" in mod.PASSES
    assert "dragonboat_tpu/core/kernel.py" in mod.PASS_SCOPES["safety"]
    assert "scripts/model_check.py" in mod.PASS_SCOPES["safety"]


def test_changed_only_waivers_edit_invalidates_every_pass():
    """A waivers.toml edit can un-suppress a finding in ANY pass, so it
    must select all of them — spelled out, not left to the analysis/
    prefix coincidence."""
    mod = _load_lint_module()
    assert mod.select_changed([mod.WAIVERS_FILE]) == sorted(mod.PASSES)
    # kernel edits select the safety pass (among others in its scope)
    assert "safety" in mod.select_changed(["dragonboat_tpu/core/kernel.py"])
    assert mod.select_changed(["README.md"]) == []


def test_sarif_output_shape():
    mod = _load_lint_module()
    common = __import__("dragonboat_tpu.analysis.common",
                        fromlist=["Finding", "Waiver"])
    f1 = common.Finding("safety", "dragonboat_tpu/core/kernel.py", 7,
                        "RS002", "commit store unproven")
    f2 = common.Finding("partition", "a.py", 1, "PS001", "leaked axis")
    wv = common.Waiver(pass_name="partition", path="a.py", rule="PS001",
                       reason="known", line=1)
    doc = mod.to_sarif([f1], [(f2, wv)])
    assert doc["version"] == "2.1.0"
    run = doc["runs"][0]
    assert run["tool"]["driver"]["name"] == "dragonboat-tpu-lint"
    rules = {r["id"] for r in run["tool"]["driver"]["rules"]}
    assert rules == {"RS002", "PS001"}
    by_rule = {r["ruleId"]: r for r in run["results"]}
    assert by_rule["RS002"]["level"] == "error"
    assert by_rule["RS002"]["locations"][0]["physicalLocation"][
        "artifactLocation"]["uri"] == "dragonboat_tpu/core/kernel.py"
    assert by_rule["RS002"]["locations"][0]["physicalLocation"][
        "region"]["startLine"] == 7
    assert by_rule["PS001"]["level"] == "note"
    assert by_rule["PS001"]["properties"]["waiverReason"] == "known"
    json.dumps(doc)                  # must be serializable as-is
