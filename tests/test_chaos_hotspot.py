"""Hotspot chaos fault end-to-end (the ISSUE 18 observe→act closure).

``run_hotspot(seed)`` drives a 3-replica device-resident cluster with
two shards, makes one shard's state machine pathologically slow to
apply under a 100:1 skewed write load, and requires the elastic control
plane to close the loop on its own: the step-latency EWMA trips the
host-hot gate, the fleet controller plans a leadership transfer for the
hot shard, the NodeHost issues it, and leadership actually leaves the
initial leader — all while the convergence oracle holds (zero acked
loss, equal journals, leaderless gauge drained, invariant probes
clean).

The scenario regression-covers two load-dependent liveness bugs this
closure flushed out: the kernel's campaign gate must not refuse
elections merely because apply backpressure keeps committed > applied
(core/kernel.py _campaign), and an armed-then-aborted leader transfer
must re-arm from the sticky lease instead of being lost
(engine/kernel_engine.py _stage_lane).

Budget: ~22 s per seed; two fixed seeds ride tier-1 as ``chaos_fast``.
"""

import pytest

from dragonboat_tpu.chaos import run_hotspot

FAST_SEEDS = (11, 23)


@pytest.mark.chaos_fast
@pytest.mark.parametrize("seed", FAST_SEEDS)
def test_hotspot_drains_and_converges(seed):
    r = run_hotspot(seed)
    assert r.report.ok, (seed, r.report.failures)
    assert r.transfers, (seed, "controller never planned a transfer")
    assert r.final_leader != r.initial_leader, (seed, r.final_leader)
    assert r.acked_count > 0, seed
    # every transfer decision carries its evidence row (the flight
    # record IS the audit trail the doctor replays)
    for t in r.transfers:
        ev = t.get("evidence", {})
        assert {"obs", "lane", "score", "lag", "streak",
                "term"} <= set(ev), (seed, t)
