#!/usr/bin/env python
"""Benchmark: sustained replicated writes/sec across raft groups on TPU.

BASELINE config #2 shape: N groups × 3 replicas, 16B payloads, vmapped step
loop with on-device message routing; every write is a full raft round
(leader append → replicate → quorum ack → commit) with instant-apply RSM
feedback and device-side log compaction.  Prints ONE JSON line — always,
even on backend failure (the r1 bench died with a raw traceback when the
axon backend was unavailable; now the backend is probed in a subprocess
with a timeout and the bench degrades to CPU rather than recording nothing).

Baseline: the reference's 9M writes/s peak (3× 22-core Xeon servers,
BASELINE.md) — vs_baseline is measured/9e6.

Env knobs: BENCH_GROUPS (default 8192 on device, 1024 on the CPU
fallback — one core crunches the batch serially, so scale only slows the
same measurement), BENCH_STEPS (default 200),
BENCH_PROBE_TIMEOUT (default 180 s), BENCH_FORCE_CPU=1, BENCH_DEVICE_SM=1
(run the full data path: committed writes applied to the device-resident
KV state machine by the fused rsm-apply kernel, rsm/device_kv.py).
"""

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)

from dragonboat_tpu.hostenv import clean_cpu_env, probe_devices  # noqa: E402

BASELINE_WPS = 9e6


def emit(result: dict) -> None:
    print(json.dumps(result))


def fail(stage: str, err: str) -> None:
    emit({
        "metric": "replicated writes/sec (bench failed)",
        "value": 0,
        "unit": "writes/s",
        "vs_baseline": 0.0,
        "error": {"stage": stage, "detail": err[-2000:]},
    })


def cpu_env() -> dict:
    env = clean_cpu_env(BENCH_IN_CPU_FALLBACK="1")
    # CPU runs (probe-timeout fallback AND BENCH_FORCE_CPU) default to a
    # smaller scale: one core crunches the [G] batch serially, so the
    # device-scale default just measures the same code slower.  An
    # explicit BENCH_GROUPS always wins; the metric line reports the
    # group count either way.
    env.setdefault("BENCH_GROUPS", "1024")
    return env


def run_bench() -> None:
    import jax

    jax.config.update("jax_compilation_cache_dir",
                      "/tmp/dragonboat_tpu_jax_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    platform = jax.devices()[0].platform
    default_groups = "8192" if platform != "cpu" else "1024"
    groups = int(os.environ.get("BENCH_GROUPS", default_groups))
    steps = int(os.environ.get("BENCH_STEPS", "200"))
    # a TPU device error at one scale (watchdog on long launches, or a
    # wedged tunnel mid-run) must not cost the whole record: retry the
    # measurement at smaller G before giving up
    last = None
    # always attempt the configured scale; only the fallback scales are
    # floored at 64 groups
    ladder = [groups] + [g for g in (groups // 2, groups // 8) if g >= 64]
    for g in ladder:
        try:
            return _measure(platform, g, steps)
        except Exception:
            import traceback

            last = traceback.format_exc()
    fail("run", last or "no config attempted")


def _measure(platform: str, groups: int, steps: int) -> None:
    import numpy as np

    from dragonboat_tpu.bench_loop import (  # noqa: F401
        bench_params,
        elect_all,
        make_cluster,
        run_steps,
    )
    from dragonboat_tpu.core import params as KP

    replicas = 3
    device_sm = os.environ.get("BENCH_DEVICE_SM") == "1"
    if device_sm:
        from dragonboat_tpu.bench_loop import sm_params

        kp = sm_params(replicas)
    else:
        kp = bench_params(replicas)

    t_build = time.time()
    state = make_cluster(kp, groups, replicas)
    state, box = elect_all(kp, replicas, state)
    lead = np.asarray(state.role) == KP.LEADER
    assert lead.reshape(-1, replicas).any(axis=1).all()
    sm_rejects = []   # device arrays: no per-chunk host sync in the
    # timed loop (the plain path measures with async dispatch overlap)
    if device_sm:
        from dragonboat_tpu.bench_loop import make_device_sm, run_steps_sm

        kv, kv_state = make_device_sm(groups, replicas)

        def run_steps(kp_, r_, n_, tick_, prop_, st_, bx_):
            nonlocal kv_state
            st_, bx_, kv_state, rej = run_steps_sm(
                kp_, r_, kv, n_, tick_, prop_, st_, bx_, kv_state)
            sm_rejects.append(rej)
            return st_, bx_

    # warmup: compile exactly the loop variants the timed region will run
    # (iters is a static jit arg — chunk and remainder sizes each compile).
    # Default chunk scales inversely with G to keep every device launch
    # well under the ~60 s TPU watchdog
    default_chunk = max(2, min(25, (25 * 1024) // max(groups, 1)))
    chunk = max(1, int(os.environ.get("BENCH_CHUNK", str(default_chunk))))
    t_compile = time.time()
    state, box = run_steps(kp, replicas, min(chunk, steps), True, True,
                           state, box)
    if steps % chunk:
        state, box = run_steps(kp, replicas, steps % chunk, True, True,
                               state, box)
    state.term.block_until_ready()
    compile_s = time.time() - t_compile

    sm_rejects.clear()  # warmup-phase rejects are outside the window
    c0 = np.asarray(state.committed)[lead].astype(np.int64).sum()
    # chunk the device loop: one fori_loop launch of N*step_ms can trip
    # the TPU watchdog ("TPU device error") when a run exceeds ~60 s —
    # bounded launches keep each dispatch well under it
    t0 = time.time()
    done = 0
    while done < steps:
        n = min(chunk, steps - done)
        state, box = run_steps(kp, replicas, n, True, True, state, box)
        done += n
    state.committed.block_until_ready()
    dt = time.time() - t0
    c1 = np.asarray(state.committed)[lead].astype(np.int64).sum()

    writes = int(c1 - c0)
    wps = writes / dt
    sm_note = ", device-SM apply" if device_sm else ""
    emit({
        "metric": (f"replicated writes/sec, {groups} groups x 3 replicas, "
                   f"16B{sm_note}"),
        "value": round(wps),
        "unit": "writes/s",
        "vs_baseline": round(wps / BASELINE_WPS, 4),
        "detail": {
            "platform": platform,
            "groups": groups,
            "steps": steps,
            "wall_s": round(dt, 3),
            "step_ms": round(dt / steps * 1e3, 3),
            "writes": writes,
            "writes_per_group_step": round(writes / steps / groups, 2),
            "warmup_steps_s": round(compile_s, 1),
            "total_setup_s": round(t0 - t_build, 1),
            **({"sm_rejected_writes": int(sum(int(r) for r in sm_rejects))}
               if device_sm else {}),
        },
    })


def run_cpu_subprocess(degraded_note: str | None) -> None:
    """Re-exec on CPU and re-emit its JSON line (annotated if degraded)."""
    r = subprocess.run(
        [sys.executable, os.path.abspath(__file__)], env=cpu_env(),
        capture_output=True, text=True,
    )
    line = (r.stdout.strip().splitlines() or [""])[-1]
    try:
        parsed = json.loads(line)
        if degraded_note:
            parsed["detail"] = parsed.get("detail", {})
            parsed["detail"]["degraded"] = degraded_note
        emit(parsed)
    except Exception:
        fail("cpu-fallback", r.stdout + r.stderr)


def main() -> None:
    if os.environ.get("BENCH_IN_CPU_FALLBACK") != "1":
        if os.environ.get("BENCH_FORCE_CPU") == "1":
            run_cpu_subprocess(None)
            return
        timeout_s = float(os.environ.get("BENCH_PROBE_TIMEOUT", "180"))
        ndev, why = probe_devices(timeout_s)
        if ndev is None:
            # record the REAL failure (hang vs fast crash) in the artifact
            run_cpu_subprocess(f"device backend unavailable: {why}")
            return
    try:
        run_bench()
    except Exception:
        import traceback

        fail("run", traceback.format_exc())


if __name__ == "__main__":
    main()
