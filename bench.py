#!/usr/bin/env python
"""Benchmark: sustained replicated writes/sec across raft groups on TPU.

BASELINE config #2 shape: N groups × 3 replicas, 16B payloads, vmapped step
loop with on-device message routing; every write is a full raft round
(leader append → replicate → quorum ack → commit) with instant-apply RSM
feedback and device-side log compaction.  The LAST stdout line is the
record — always a valid JSON measurement, even on backend failure (the
backend is probed in a subprocess with a timeout and the bench degrades
to CPU rather than recording nothing); an earlier provisional line may
precede it (emitted after phase A so an externally killed slow run
still records the headline).

Baseline: the reference's 9M writes/s peak (3× 22-core Xeon servers,
BASELINE.md) — vs_baseline is measured/9e6.

Phases (one JSON line carries all of them): A headline write throughput
(uninstrumented, MEDIAN-OF-3 timed windows with a cross-phase
contention verdict — a noisy box inflates a window, it must not inflate
the record), A2 commit-latency percentiles (stamp-ring instrumented
loop, leader-side release), B 9:1 ReadIndex:write PERMIT capacity
(secondary diagnostic), B2 9:1 mix with reads SERVED against the
device-resident state machine (THE config-#3 number —
read_accounting: "served"; BENCH_SERVED=0 skips), C 10k-shard election
storm with randomized drops + pre-vote (config #4), D
membership-change wave + device log compaction under load (config #5:
every group commits a CC mid-stream; BENCH_CC=0 skips,
BENCH_CC_ROUNDS sets the wave count), E config #1 single-shard
datapoint (one 3-replica shard at G=1, vs the reference's 1.25M w/s
single-shard peak; BENCH_CONFIG1=0 skips).  BENCH_TIME_BUDGET (default
2400 s) soft-bounds the run: a phase that would overrun is skipped
with a note in the record, never silently truncated.

Env knobs: BENCH_GROUPS (default 8192 on device, 1024 on the CPU
fallback — one core crunches the batch serially, so scale only slows the
same measurement), BENCH_STEPS (default 200), BENCH_CHUNK (device-launch
chunking under the ~60 s watchdog), BENCH_PROBE_TIMEOUT (default 180 s),
BENCH_FORCE_CPU=1, BENCH_LAT_STEPS / BENCH_MIXED_STEPS (phase lengths),
BENCH_MIXED_WRITE_WIDTH (phase B write lanes; default full batch width —
the 9:1 ratio rides the per-ctx read batch, capped at 9 reads per
committed write),
BENCH_STORM=0 (skip phase C), BENCH_STORM_GROUPS / BENCH_STORM_STEPS /
BENCH_STORM_DROP (storm shape), BENCH_DEVICE_SM=1 (full data path:
committed writes applied to the device-resident KV state machine by the
fused rsm-apply kernel, rsm/device_kv.py), BENCH_PALLAS=1 (with
BENCH_DEVICE_SM: route the apply through the pallas block kernel,
rsm/device_kv_pallas.py), BENCH_TELEMETRY=1 (standalone mode: A-B
overhead of the device-side fleet_stats telemetry reduction at the
engine's decimation cadence — see run_telemetry_ab), BENCH_HEALTH=1
(standalone mode: interleaved A-B overhead of the fleet_health anomaly
pass + O(K) report fetch on top of the fleet_stats baseline — see
run_health_ab), BENCH_PIPELINE=1
(standalone mode: interleaved A-B of the serial vs fused depth-1
pipelined step loops with commit-latency percentiles per arm — see
run_pipeline_ab), BENCH_TRACE=1 (standalone mode: interleaved A-B
overhead of proposal-lifecycle tracing at default 1/64 sampling on the
full serving path — see run_trace_ab), BENCH_FABRIC=1 (standalone
mode: interleaved A-B overhead of the fabric observability stack —
per-link transport telemetry + trace propagation + hop census on top
of lifecycle tracing — see run_fabric_ab), BENCH_CAPACITY=1 (standalone
mode: interleaved A-B overhead of the capacity rail — compile-tracker
wrappers + tree-bytes walk + snapshot assembly — on top of the
stats+health path — see run_capacity_ab), BENCH_SAFETY=1 (standalone
mode: interleaved A-B overhead of the runtime invariant probe —
check_invariants + digest carry + O(NI) report fetch — on top of the
stats+health path — see run_safety_ab), BENCH_TRANSFER=1 (standalone
mode: interleaved A-B overhead of the transfer-guard rail —
capacity.METER tag counters + scoped jax.transfer_guard around the
dispatch seam — see run_transfer_ab), BENCH_ELASTIC=1 (standalone
mode: the elastic control plane's two closing numbers — skew-vs-uniform
acked throughput with the fleet controller on, and the masked-quiesce
step-time reduction at 90% cold — see run_elastic_ab),
BENCH_FABRIC_RESIDENT=1 (standalone mode: the round-17 tentpole's A-B
— co-located consensus over the in-step collective vs round-tripped
through the host hub's route() staging, on the serving loop, with
compile telemetry pinning compiles=1/retraces=0 on the resident entry
— see run_fabric_resident_ab).
"""

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)

from dragonboat_tpu.hostenv import clean_cpu_env, probe_devices  # noqa: E402

BASELINE_WPS = 9e6
# BASELINE config #1: ONE 3-replica shard, 16B payloads — the
# reference's single-shard peak (BASELINE.md)
CONFIG1_BASELINE_WPS = 1.25e6
# set once any provisional measurement line has been emitted: a later
# total failure must not print a value=0 line OVER a valid headline
_PROVISIONAL_EMITTED = False


def emit(result: dict) -> None:
    print(json.dumps(result))


def fail(stage: str, err: str) -> None:
    emit({
        "metric": "replicated writes/sec (bench failed)",
        "value": 0,
        "unit": "writes/s",
        "vs_baseline": 0.0,
        "error": {"stage": stage, "detail": err[-2000:]},
    })


def cpu_env() -> dict:
    env = clean_cpu_env(BENCH_IN_CPU_FALLBACK="1")
    # CPU runs (probe-timeout fallback AND BENCH_FORCE_CPU) default to a
    # smaller scale: one core crunches the [G] batch serially, so the
    # device-scale default just measures the same code slower.  An
    # explicit BENCH_GROUPS always wins; the metric line reports the
    # group count either way.
    env.setdefault("BENCH_GROUPS", "1024")
    return env


def run_bench() -> None:
    import jax

    jax.config.update("jax_compilation_cache_dir",
                      "/tmp/dragonboat_tpu_jax_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    platform = jax.devices()[0].platform
    default_groups = "8192" if platform != "cpu" else "1024"
    groups = int(os.environ.get("BENCH_GROUPS", default_groups))
    steps = int(os.environ.get("BENCH_STEPS", "200"))
    # a TPU device error at one scale (watchdog on long launches, or a
    # wedged tunnel mid-run) must not cost the whole record: retry the
    # measurement at smaller G before giving up
    last = None
    # always attempt the configured scale; only the fallback scales are
    # floored at 64 groups
    ladder = [groups] + [g for g in (groups // 2, groups // 8) if g >= 64]
    for g in ladder:
        try:
            return _measure(platform, g, steps)
        except Exception:
            import traceback

            last = traceback.format_exc()
    if _PROVISIONAL_EMITTED:
        # the last provisional line stands as the record; a value=0
        # fail line would overwrite a valid measurement for last-line
        # consumers
        sys.stderr.write(last or "")
        return
    fail("run", last or "no config attempted")


def _pctile(hist, q: float):
    """Percentile (in steps) from the latency bucket histogram."""
    import numpy as np

    h = np.asarray(hist, np.int64)
    c = h.cumsum()
    if c[-1] == 0:
        return None
    return int(np.searchsorted(c, q * c[-1], side="left"))


def _run_storm(platform: str) -> dict:
    """BASELINE config #4: election storm with randomized drops +
    pre-vote across BENCH_STORM_GROUPS shards — 10k by default on every
    platform (the CPU fallback pays the wall cost; shrinking the config
    made r3's number incomparable to the baseline).  ``platform`` rides
    into the record for provenance."""
    import time as _t

    import numpy as np

    from dragonboat_tpu.bench_loop import (
        bench_params,
        make_cluster,
        run_steps,
        run_steps_storm,
    )
    from dragonboat_tpu.core import params as KP
    from dragonboat_tpu.core.kstate import empty_inbox
    import jax.numpy as jnp

    replicas = 3
    # config #4 says 10k shards; the CPU fallback pays the wall cost
    # rather than shrinking the config (VERDICT r3 weak #5)
    g = int(os.environ.get("BENCH_STORM_GROUPS", "10000"))
    storm_steps = int(os.environ.get("BENCH_STORM_STEPS", "30"))
    drop_p = float(os.environ.get("BENCH_STORM_DROP", "0.25"))
    kp = bench_params(replicas)
    state = make_cluster(kp, g, replicas)
    # pre-vote everywhere: failed campaigns must not inflate terms
    state = state._replace(pre_vote=jnp.ones_like(state.pre_vote))
    box = empty_inbox(kp, state.term.shape[0])

    # compile the recovery-loop executable BEFORE the timed window (a
    # first-call jit would otherwise inflate recovery_ms); 10 pre-storm
    # ticks are semantically part of the cold start
    chunk = 10
    state, box = run_steps(kp, replicas, chunk, True, False, state, box)
    state.term.block_until_ready()

    # cold start under drops IS the storm: g simultaneous campaigns
    state, box = run_steps_storm(kp, replicas, storm_steps, drop_p, 42,
                                 state, box)
    state.term.block_until_ready()
    role = np.asarray(state.role).reshape(-1, replicas)
    storm_coverage = float((role == KP.LEADER).sum(axis=1).clip(0, 1).mean())

    # clean network: measure steps (and wall) to one leader everywhere
    t0 = _t.time()
    recovered_steps = None
    done = 0
    while done < 400:
        state, box = run_steps(kp, replicas, chunk, True, False, state, box)
        done += chunk
        role = np.asarray(state.role).reshape(-1, replicas)
        if ((role == KP.LEADER).sum(axis=1) == 1).all():
            recovered_steps = done
            break
    dt = _t.time() - t0
    step_ms = dt / max(done, 1) * 1e3
    # recovery is only complete at EXACTLY one leader per group
    post_cov = float(((role == KP.LEADER).sum(axis=1) == 1).mean())
    return {
        "groups": g,
        "platform": platform,
        "storm_steps": storm_steps,
        "drop_p": drop_p,
        "leader_coverage_after_storm": round(storm_coverage, 4),
        "post_recovery_coverage": round(post_cov, 4),
        "recovered": recovered_steps is not None,
        "recovery_steps": recovered_steps,
        # null when the cluster never reached one-leader-everywhere — a
        # 400-step timeout must not read as an achieved latency
        "recovery_ms": (round(step_ms * recovered_steps, 1)
                        if recovered_steps is not None else None),
        "recovery_step_ms": round(step_ms, 2),
        **({} if recovered_steps is not None
           else {"timed_out_after_steps": done}),
    }


def _run_served(replicas: int, groups: int, mixed_steps: int,
                write_width: int, chunk: int) -> dict:
    """Phase B2: the 9:1 mix with every read EXECUTED against the
    device-resident table (run_steps_mixed_sm) — a fresh device-SM
    cluster at the bench G, its own warmup, its own timed window.
    Standalone so the main-phase state is untouched and a failure here
    cannot poison the rest of the record."""
    import numpy as np
    import jax.numpy as jnp

    from dragonboat_tpu.bench_loop import (
        elect_all,
        make_cluster,
        make_device_sm,
        run_steps_mixed_sm,
        sm_params,
    )
    from dragonboat_tpu.core import params as KP

    kp = sm_params(replicas)
    state = make_cluster(kp, groups, replicas)
    state, box = elect_all(kp, replicas, state)
    lead = np.asarray(state.role) == KP.LEADER
    kv, kv_state = make_device_sm(groups, replicas)
    WW = max(1, min(kp.proposal_cap, write_width))
    rd = jnp.asarray(0, jnp.int32)
    acc = jnp.asarray(0, jnp.int32)
    rej = jnp.asarray(0, jnp.int32)
    now = 0

    def run(iters):
        nonlocal state, box, kv_state, rd, acc, rej, now
        state, box, kv_state, rd, acc, rej = run_steps_mixed_sm(
            kp, replicas, kv, iters, WW, jnp.asarray(now, jnp.int32),
            state, box, kv_state, rd, acc, rej)
        now += iters

    def committed() -> int:
        return int(np.asarray(state.committed)[lead].astype(np.int64).sum())

    # warm the exact chunk/remainder executables outside the window
    run(min(chunk, mixed_steps))
    if mixed_steps % chunk:
        run(mixed_steps % chunk)
    state.committed.block_until_ready()
    c0, r0 = committed(), int(np.asarray(rd))
    t0 = time.time()
    done = 0
    while done < mixed_steps:
        n = min(chunk, mixed_steps - done)
        run(n)
        done += n
    state.committed.block_until_ready()
    dt = time.time() - t0
    writes = committed() - c0
    served = (int(np.asarray(rd)) - r0) * 9 * WW
    # the declared mix is 9:1 — lookups beyond 9 per committed write
    # are executed but do not count toward the mixed number
    reads_ops = min(served, 9 * writes)
    ops = (writes + reads_ops) / dt
    return {
        "read_accounting": "served",
        "ops_per_s": round(ops),
        "writes_per_s": round(writes / dt),
        "reads_served_per_s": round(served / dt),
        "read_checksum": int(np.asarray(acc)),
        "sm_rejected_writes": int(np.asarray(rej)),
        "steps": mixed_steps,
        "step_ms": round(dt / mixed_steps * 1e3, 3),
        "table": "direct-mapped",
        "vs_baseline_mixed": round(ops / 11e6, 4),
    }


def _run_single_shard(replicas: int, steps: int) -> dict:
    """BASELINE config #1: one 3-replica shard, 16B payloads.  The [G]
    batch parallelism that carries the headline cannot help at G=1 —
    this datapoint isolates per-shard pipeline depth (proposal_cap
    writes per device step) against the reference's 1.25M writes/s
    single-shard peak.  Standalone cluster so the main-phase state is
    untouched and a failure here cannot poison the rest of the record."""
    import numpy as np

    from dragonboat_tpu.bench_loop import (
        bench_params,
        elect_all,
        make_cluster,
        run_steps,
    )
    from dragonboat_tpu.core import params as KP

    kp = bench_params(replicas)
    state = make_cluster(kp, 1, replicas)
    state, box = elect_all(kp, replicas, state)
    lead = np.asarray(state.role) == KP.LEADER

    def run(iters):
        nonlocal state, box
        state, box = run_steps(kp, replicas, iters, True, True, state, box)

    def committed() -> int:
        return int(np.asarray(state.committed)[lead].astype(np.int64).sum())

    # G=1 launches are tiny; one fixed chunk keeps the jit-variant count
    # (and so the warmup compile cost) at exactly two executables
    chunk = 25
    run(min(chunk, steps))
    if steps % chunk:
        run(steps % chunk)
    state.committed.block_until_ready()
    c0 = committed()
    t0 = time.time()
    done = 0
    while done < steps:
        n = min(chunk, steps - done)
        run(n)
        done += n
    state.committed.block_until_ready()
    dt = time.time() - t0
    writes = committed() - c0
    wps = writes / dt
    return {
        "groups": 1,
        "steps": steps,
        "step_ms": round(dt / steps * 1e3, 3),
        "writes": writes,
        "writes_per_s": round(wps),
        "vs_baseline_config1": round(wps / CONFIG1_BASELINE_WPS, 4),
    }


def _measure(platform: str, groups: int, steps: int) -> None:
    import numpy as np

    from dragonboat_tpu.bench_loop import (  # noqa: F401
        bench_params,
        elect_all,
        lat_init,
        make_cluster,
        run_steps,
        run_steps_lat,
    )
    from dragonboat_tpu.core import params as KP

    replicas = 3
    device_sm = os.environ.get("BENCH_DEVICE_SM") == "1"
    if device_sm:
        from dragonboat_tpu.bench_loop import sm_params

        kp = sm_params(replicas)
    else:
        kp = bench_params(replicas)

    import jax.numpy as jnp

    t_build = time.time()
    # soft wall budget: the driver/watcher runs this under an external
    # timeout — a phase that would overrun it must be skipped WITH a
    # note rather than silently truncating the record (VERDICT r4: the
    # artifact is the scoreboard)
    budget_s = float(os.environ.get("BENCH_TIME_BUDGET", "2400"))

    def time_left(margin_s: float) -> bool:
        return (time.time() - t_build) < (budget_s - margin_s)
    state = make_cluster(kp, groups, replicas)
    state, box = elect_all(kp, replicas, state)
    lead = np.asarray(state.role) == KP.LEADER
    assert lead.reshape(-1, replicas).any(axis=1).all()
    sm_rejects = []   # device arrays: no per-chunk host sync in the
    # timed loop (the plain path measures with async dispatch overlap)
    if device_sm:
        from dragonboat_tpu.bench_loop import make_device_sm, run_steps_sm

        # BENCH_PALLAS=1 flips the apply to the fused pallas kernel
        # (VMEM-resident table block; interpret-mode off-TPU)
        kv, kv_state = make_device_sm(
            groups, replicas,
            use_pallas=os.environ.get("BENCH_PALLAS") == "1")

        def run_steps(kp_, r_, n_, tick_, prop_, st_, bx_):
            nonlocal kv_state
            st_, bx_, kv_state, rej = run_steps_sm(
                kp_, r_, kv, n_, tick_, prop_, st_, bx_, kv_state)
            sm_rejects.append(rej)
            return st_, bx_

    B = kp.proposal_cap
    now = 0
    if not device_sm:
        # latency instrumentation state — only the non-SM phases use it,
        # and the [G, log_cap] stamp ring is real HBM at device scale
        stamp, hist, reads = lat_init(kp, state.term.shape[0])

    def lat_run(iters, width, do_reads, tick, propose):
        nonlocal state, box, stamp, hist, reads, now
        state, box, stamp, hist, reads = run_steps_lat(
            kp, replicas, iters, width, do_reads, tick, propose,
            jnp.asarray(now, jnp.int32), state, box, stamp, hist, reads)
        now += iters

    def committed():
        return np.asarray(state.committed)[lead].astype(np.int64).sum()

    def timed_window(run_fn, total, snap=None):
        """Warm the exact chunk/remainder executables, call ``snap`` to
        capture pre-window baselines, then run ``total`` steps in
        watchdog-safe chunks (one long device launch can trip the ~60 s
        TPU watchdog).  Returns (warmup_s, window_s).  ONE helper so the
        three phases cannot drift in methodology."""
        tw = time.time()
        run_fn(min(chunk, total))
        if total % chunk:
            run_fn(total % chunk)
        state.term.block_until_ready()
        warm_s = time.time() - tw
        if snap is not None:
            snap()
        t0 = time.time()
        done = 0
        while done < total:
            n = min(chunk, total - done)
            run_fn(n)
            done += n
        state.committed.block_until_ready()
        return warm_s, time.time() - t0

    # Default chunk scales inversely with G to keep every device launch
    # well under the ~60 s TPU watchdog; iters is a static jit arg, so
    # timed_window warms exactly the chunk/remainder variants it runs
    default_chunk = max(2, min(25, (25 * 1024) // max(groups, 1)))
    chunk = max(1, int(os.environ.get("BENCH_CHUNK", str(default_chunk))))

    # ---- phase A: write-only throughput (the headline metric runs the
    # UNinstrumented loop; latency capture is a separate phase below —
    # its stamp/histogram one-hots roughly double the step cost).
    # Measured as MEDIAN-OF-3 windows: one long window has no defense
    # against a transiently noisy box (the r2->r4 headline decline was
    # measurement contention, not code — PERF.md), and the lower-middle
    # median discards a single inflated window while never inventing a
    # number faster than a window actually measured. ----
    def plain_run(iters):
        nonlocal state, box
        state, box = run_steps(kp, replicas, iters, True, True, state, box)

    snaps = {}
    windows: list[dict] = []
    wsteps = max(20, steps // 3)

    def run_a_window():
        def snap():
            sm_rejects.clear()  # warmup rejects are outside the window
            snaps["c0"] = committed()

        warm, dtw = timed_window(plain_run, wsteps, snap)
        # accumulate in-window rejects across windows (the clear above
        # discards only warmup-segment rejects)
        snaps["rej"] = snaps.get("rej", 0) + sum(int(r) for r in sm_rejects)
        w = int(committed() - snaps["c0"])
        windows.append({
            "steps": wsteps,
            "wall_s": round(dtw, 3),
            "step_ms": round(dtw / wsteps * 1e3, 3),
            "writes": w,
            "writes_per_s": round(w / dtw),
        })
        return warm

    def median_window() -> dict:
        # lower-middle: contention only ever inflates a window, so ties
        # break toward the measurement the box actually achieved
        ws = sorted(windows, key=lambda r: r["step_ms"])
        return ws[(len(ws) - 1) // 2]

    t0 = time.time()
    compile_s = run_a_window()
    for _ in range(2):
        run_a_window()
    med = median_window()
    writes = sum(w["writes"] for w in windows)
    dt = sum(w["wall_s"] for w in windows)
    wps = med["writes_per_s"]
    step_ms = med["step_ms"]

    # provisional record: if a slow-tunnel run is killed externally in a
    # later phase, the LAST stdout line is still a valid measurement of
    # the headline instead of nothing (the complete line below
    # supersedes it on a full run)
    global _PROVISIONAL_EMITTED
    _PROVISIONAL_EMITTED = True
    _sm_note = ", device-SM apply" if device_sm else ""
    emit({
        "metric": (f"replicated writes/sec, {groups} groups x 3 replicas, "
                   f"16B{_sm_note} (provisional: phase A only)"),
        "value": round(wps),
        "unit": "writes/s",
        "vs_baseline": round(wps / BASELINE_WPS, 4),
        "detail": {"platform": platform, "groups": groups,
                   "provisional": "later phases may still be running"},
    })

    detail = {
        "platform": platform,
        "groups": groups,
        "steps": len(windows) * wsteps,
        "wall_s": round(dt, 3),
        "step_ms": round(step_ms, 3),
        "writes": writes,
        "writes_per_group_step": round(
            med["writes"] / med["steps"] / groups, 2),
        "headline_policy": "lower-median of timed windows",
        "headline_windows": windows,
        "warmup_steps_s": round(compile_s, 1),
        "total_setup_s": round(t0 - t_build + compile_s, 1),
    }
    if device_sm:
        detail["sm_rejected_writes"] = int(snaps.get("rej", 0))
        detail["sm_apply"] = ("pallas" if kv.use_pallas else
                              ("range" if not kv.hash_keys else "scan"))
        # ---- device-SM phase B: the same served-read mix the default
        # bench records — ONE implementation (_run_served) so the two
        # modes cannot drift in accounting or record schema ----
        mixed_steps = int(os.environ.get(
            "BENCH_MIXED_STEPS", str(max(40, steps // 2))))
        WW = max(1, min(B, int(os.environ.get(
            "BENCH_MIXED_WRITE_WIDTH", str(B)))))
        try:
            detail["mixed_9to1_served"] = _run_served(
                replicas, groups, mixed_steps, WW, chunk)
        except Exception as e:
            detail["mixed_9to1_served"] = {"error": repr(e)[-300:]}
    else:
        # ---- phase A2: commit-latency percentiles (instrumented loop) ----
        lat_steps = int(os.environ.get("BENCH_LAT_STEPS",
                                       str(max(40, steps // 2))))

        def snap_lat():
            snaps["hist0"] = np.asarray(hist).astype(np.int64)

        _, dtL = timed_window(
            lambda n: lat_run(n, B, False, True, True), lat_steps, snap_lat)
        lat_step_ms = dtL / lat_steps * 1e3
        histA = np.asarray(hist).astype(np.int64) - snaps["hist0"]
        lat_ms = {}
        for name, q in (("p50", 0.50), ("p99", 0.99), ("p99.9", 0.999)):
            p = _pctile(histA, q)
            # latency in instrumented steps, scaled to the HEADLINE
            # step_ms: the pipeline depth (steps) is what the kernel
            # determines; the production step cost is the uninstrumented
            # one
            lat_ms[name] = (round(p * step_ms, 3) if p is not None
                            else None)
        # resolution is one device step: a release in the proposing step
        # reports 0 buckets -> "< step_ms"
        lat_ms["resolution_ms"] = round(step_ms, 3)
        lat_ms["instrumented_step_ms"] = round(lat_step_ms, 3)
        detail["commit_latency_ms"] = lat_ms

        # ---- phase B: 9:1 read:write mix over ReadIndex (config #3) —
        # measured on the UNinstrumented mixed loop (run_steps_mixed):
        # reads are counted by the completed-ctx carry, not the stamp
        # ring, so the number is apples-to-apples with phase A ----
        from dragonboat_tpu.bench_loop import run_steps_mixed

        mixed_steps = int(os.environ.get(
            "BENCH_MIXED_STEPS", str(max(40, steps // 2))))
        # writes keep the full batch width: the 9:1 ratio is carried by
        # the read batch behind each ReadIndex ctx (raft.go ReadIndex
        # batching serves every read queued at confirmation time), and
        # ctx confirmation throughput (~1/group/step, one piggybacked
        # heartbeat round) is independent of the write width — narrowing
        # writes only shrank both terms of the mix
        WW = max(1, min(B, int(os.environ.get("BENCH_MIXED_WRITE_WIDTH",
                                              str(B)))))

        def mixed_run(iters):
            nonlocal state, box, reads, now
            state, box, reads = run_steps_mixed(
                kp, replicas, iters, WW, jnp.asarray(now, jnp.int32),
                state, box, reads)
            now += iters

        def snap_mixed():
            snaps["reads0"], snaps["cB0"] = int(np.asarray(reads)), committed()

        _, dtB = timed_window(mixed_run, mixed_steps, snap_mixed)
        writes_b = int(committed() - snaps["cB0"])
        ctx = int(np.asarray(reads)) - snaps["reads0"]
        # one ReadIndex ctx serves the read batch queued behind it
        # (raft.go ReadIndex batching); 9:1 mix => 9 reads per write
        read_batch = 9 * WW
        reads_ops = min(ctx * read_batch, 9 * writes_b)
        mixed_ops = (writes_b + reads_ops) / dtB
        mixed_step_ms = dtB / mixed_steps * 1e3
        # SECONDARY diagnostic: reads here are ReadIndex PERMITS
        # (confirmed-ctx batch capacity, capped at 9 per committed
        # write), NOT executed lookups — the recorded config-#3 number
        # is mixed_9to1_served below, where every counted read is a real
        # table lookup.  No vs_baseline field here on purpose: permit
        # capacity must not be comparable against the reference's 11M
        # served ops/s.
        detail["mixed_9to1_permits"] = {
            "read_accounting": "permits",
            "ops_per_s": round(mixed_ops),
            "writes_per_s": round(writes_b / dtB),
            "read_ctx_per_s": round(ctx / dtB),
            "read_batch_per_ctx": read_batch,
            "steps": mixed_steps,
            "step_ms": round(mixed_step_ms, 3),
        }

        # ---- cross-phase consistency: the mixed loop runs the SAME
        # kernel plus ReadIndex work, so write-only step_ms above mixed
        # step_ms by >15% means phase A was measured on a contended box
        # (exactly r4's self-contradicting record).  Re-measure phase A
        # once and let the median absorb the inflated windows. ----
        contended = step_ms > 1.15 * mixed_step_ms
        if contended:
            run_a_window()
            med = median_window()
            writes = sum(w["writes"] for w in windows)
            dt = sum(w["wall_s"] for w in windows)
            wps = med["writes_per_s"]
            step_ms = med["step_ms"]
            detail.update(
                steps=len(windows) * wsteps,
                wall_s=round(dt, 3), step_ms=round(step_ms, 3),
                writes=writes,
                writes_per_group_step=round(
                    med["writes"] / med["steps"] / groups, 2))
        detail["contention"] = {
            "write_only_vs_mixed_step": round(
                step_ms / max(mixed_step_ms, 1e-9), 3),
            "detected": bool(contended),
            "extra_windows_measured": len(windows) - 3,
        }

        # ---- phase D: membership-change wave + compaction under load
        # (config #5, kernel rendition): every group commits a config
        # change mid-stream while the write pipeline and the device ring
        # compaction keep running; the host clears the one-in-flight
        # gate after each wave, as the engine's CC apply does ----
        if os.environ.get("BENCH_CC", "1") == "1":
            from dragonboat_tpu.bench_loop import cc_step

            cc_rounds = max(1, int(os.environ.get("BENCH_CC_ROUNDS", "3")))
            cc_period = max(4, chunk)
            # warm BOTH executables outside the window (iters is a
            # static jit arg: cc_period-1 is a fresh run_steps variant)
            state, box, acc0, idx0 = cc_step(kp, replicas, state, box)
            state, box = run_steps(kp, replicas, cc_period - 1,
                                   True, True, state, box)
            state.term.block_until_ready()
            snap0 = int(np.asarray(state.snap_index)[lead]
                        .astype(np.int64).sum())
            cD0 = committed()
            waves = []
            tD = time.time()
            for _ in range(cc_rounds):
                # gate release: the engine does this when the CC applies
                state = state._replace(
                    pending_cc=jnp.zeros_like(state.pending_cc))
                state, box, acc, idx = cc_step(kp, replicas, state, box)
                waves.append((acc, idx))
                state, box = run_steps(kp, replicas, cc_period - 1,
                                       True, True, state, box)
            state.committed.block_until_ready()
            dtD = time.time() - tD
            writes_d = int(committed() - cD0)
            committed_now = np.asarray(state.committed)
            cc_done = cc_acc = 0
            for acc, idx in waves:
                # prop_accepted is only ever set on the at-step leader
                # row — no extra role mask (a stale leadership snapshot
                # would undercount groups whose leader moved)
                a = np.asarray(acc)
                cc_acc += int(a.sum())
                cc_done += int((a & (committed_now >= np.asarray(idx))).sum())
            snap1 = int(np.asarray(state.snap_index)[lead]
                        .astype(np.int64).sum())
            total_d = cc_rounds * cc_period
            detail["membership_wave"] = {
                "rounds": cc_rounds,
                "cc_accepted": cc_acc,
                "cc_committed": cc_done,
                "writes_per_s": round(writes_d / dtD),
                "step_ms": round(dtD / total_d * 1e3, 3),
                # throughput under the wave vs the write-only phase A
                "vs_write_only": round((writes_d / dtD) / max(wps, 1), 3),
                # device-side log compaction kept running under load
                "compaction_floor_advance": snap1 - snap0,
            }

        # ---- phase E: config #1 single-shard datapoint — the G=1
        # write throughput every other phase deliberately avoids
        # (batching across groups is the whole thesis; this measures
        # what ONE shard gets) ----
        if os.environ.get("BENCH_CONFIG1", "1") != "1":
            detail["config1_single_shard"] = {"skipped": "BENCH_CONFIG1=0"}
        elif not time_left(120):
            detail["config1_single_shard"] = {
                "skipped": "time budget exhausted before config-1 phase"}
        else:
            try:
                detail["config1_single_shard"] = _run_single_shard(
                    replicas, max(50, steps))
            except Exception as e:  # must not cost the whole record
                detail["config1_single_shard"] = {"error": repr(e)[-300:]}

        # ---- phase B2: 9:1 mix with reads SERVED — the recorded
        # config-#3 number.  A fresh device-SM cluster at the same G:
        # payloads ride the replicated lv ring into the range apply, and
        # every counted read is an EXECUTED slot-scan lookup against the
        # device-resident table, checksum-folded so XLA cannot elide it
        # (bench_loop.run_steps_mixed_sm).  Direct-mapped table: raft
        # applies a contiguous index window, which is also the
        # reference's bench-SM shape (kvtest-style fixed keyspace);
        # hashed-table serving exists and is differential-tested, but
        # its probing apply measures the hash scheme, not the mix. ----
        if os.environ.get("BENCH_SERVED", "1") != "1":
            detail["mixed_9to1_served"] = {"skipped": "BENCH_SERVED=0"}
        elif not time_left(180):
            detail["mixed_9to1_served"] = {
                "skipped": "time budget exhausted before served phase"}
        else:
            try:
                detail["mixed_9to1_served"] = _run_served(
                    replicas, groups, mixed_steps, WW, chunk)
            except Exception as e:  # must not cost the whole record
                detail["mixed_9to1_served"] = {"error": repr(e)[-300:]}

        # ---- phase C: 10k-shard election storm (config #4) ----
        if os.environ.get("BENCH_STORM", "1") == "1":
            if time_left(240):
                try:
                    detail["election_storm"] = _run_storm(platform)
                except Exception as e:  # failure must not cost the run
                    detail["election_storm"] = {"error": repr(e)[-300:]}
            else:
                detail["election_storm"] = {
                    "skipped": "time budget exhausted before storm phase"}

    sm_note = ", device-SM apply" if device_sm else ""
    emit({
        "metric": (f"replicated writes/sec, {groups} groups x 3 replicas, "
                   f"16B{sm_note}"),
        "value": round(wps),
        "unit": "writes/s",
        "vs_baseline": round(wps / BASELINE_WPS, 4),
        "detail": detail,
    })


def run_serve_bench() -> None:
    """BENCH_SERVE=1: the SERVING-PATH benchmark — clients propose
    through the real NodeHost API into device-resident shards across
    three in-process hosts (chan transport), every write a full raft
    round ending in one batched fsync.  This is the apples-to-apples
    shape of the reference's own benchmark (3 servers, client sessions,
    full stack) — the kernel-only phases above measure the device
    ceiling; this measures the product.

    Two payload phases: 16B uncompressed (the headline shape), then
    1024B with entry_compression="snappy" on a second shard set — the
    r4 entry-compression codec measured on the path that actually
    invokes it (node.propose encodes at propose time, node.py:301).

    Knobs: BENCH_SERVE_SHARDS (default 32), BENCH_SERVE_SECONDS (5),
    BENCH_SERVE_WINDOW (pipelined proposals per shard, 32),
    BENCH_SERVE_1024_SHARDS (default min(8, shards); 0 skips the
    compressed-payload phase)."""
    import shutil
    import tempfile
    import threading
    import time as _t

    from dragonboat_tpu.client import Session
    from dragonboat_tpu.config import Config, ExpertConfig, NodeHostConfig
    from dragonboat_tpu.nodehost import NodeHost
    from dragonboat_tpu.statemachine import IStateMachine, Result

    class NullSM(IStateMachine):
        """16B-payload sink (the reference benchmark SM records nothing)."""

        def __init__(self, *a):
            self.n = 0

        def update(self, entry):
            self.n += 1
            return Result(value=self.n)

        def lookup(self, q):
            return self.n

        def save_snapshot(self, w, files, done):
            w.write(b"\x00")

        def recover_from_snapshot(self, r, files, done):
            r.read(1)

    n_shards = int(os.environ.get("BENCH_SERVE_SHARDS", "32"))
    seconds = float(os.environ.get("BENCH_SERVE_SECONDS", "5"))
    window = int(os.environ.get("BENCH_SERVE_WINDOW", "32"))
    n_comp = int(os.environ.get("BENCH_SERVE_1024_SHARDS",
                                str(min(8, n_shards))))
    shards = tuple(range(1, n_shards + 1))
    # the compressed-payload shard set rides the same hosts under its
    # own shard ids; both sets exist from startup (one election wait)
    comp_shards = tuple(range(n_shards + 1, n_shards + 1 + n_comp))
    addrs = {1: "sv-1", 2: "sv-2", 3: "sv-3"}
    ex = ExpertConfig(kernel_log_cap=128,
                      kernel_capacity=n_shards + n_comp,
                      kernel_apply_batch=32, kernel_compaction_overhead=16)
    hosts = {}
    # REAL durability: each host gets a tan LogDB on disk so every write
    # ends in an actual batched fsync (an empty node_host_dir would fall
    # back to the in-memory LogDB and void the durability claim)
    root = tempfile.mkdtemp(prefix="dbtpu-serve-")
    try:
        for rid, addr in addrs.items():
            nh = NodeHost(NodeHostConfig(
                raft_address=addr, rtt_millisecond=2, expert=ex,
                node_host_dir=os.path.join(root, f"nh{rid}")))
            hosts[rid] = nh
            for sid in shards:
                nh.start_replica(addrs, False, NullSM, Config(
                    shard_id=sid, replica_id=rid, election_rtt=10,
                    heartbeat_rtt=2, device_resident=True))
            for sid in comp_shards:
                nh.start_replica(addrs, False, NullSM, Config(
                    shard_id=sid, replica_id=rid, election_rtt=10,
                    heartbeat_rtt=2, device_resident=True,
                    entry_compression="snappy"))
        all_shards = shards + comp_shards
        deadline = _t.time() + 120
        elected = 0
        while _t.time() < deadline:
            elected = sum(1 for s in all_shards
                          if any(hosts[r].get_leader_id(s)[1]
                                 for r in addrs))
            if elected == len(all_shards):
                break
            _t.sleep(0.1)

        def measure_window(sids: tuple, payload: bytes,
                           run_s: float) -> dict:
            done = threading.Event()
            counts = [0] * len(sids)
            lats: list[list[float]] = [[] for _ in sids]

            def writer(i: int, sid: int) -> None:
                # steady pipelined client: the window stays FULL — one
                # new proposal is issued as each oldest completes (no
                # batch barrier); the leader host is re-resolved on
                # failures
                from collections import deque

                sess = Session.new_noop_session(sid)

                def leader_host():
                    lid, ok = hosts[1].get_leader_id(sid)
                    return hosts[lid if ok and lid in hosts else 1]

                futs: deque = deque()
                while not done.is_set():
                    try:
                        nh = leader_host()
                        while len(futs) < window:
                            futs.append((nh.propose(sess, payload,
                                                    timeout_s=10.0),
                                         _t.time()))
                        f, t0 = futs.popleft()
                        f.get(10.0)
                        counts[i] += 1
                        lats[i].append(_t.time() - t0)
                    except Exception:
                        futs.clear()   # window poisoned by a leader move
                        _t.sleep(0.02)

            threads = [threading.Thread(target=writer, args=(i, sid),
                                        daemon=True)
                       for i, sid in enumerate(sids)]
            t_start = _t.time()
            for t in threads:
                t.start()
            _t.sleep(run_s)
            # snapshot the window BEFORE done/join: the drain tail
            # (writers blocked in f.get timeouts) must not dilute the
            # steady-state rate
            wall = _t.time() - t_start
            total = sum(counts)
            done.set()
            for t in threads:
                t.join(timeout=15)
            all_lats = sorted(x for li in lats for x in li)

            def pct(q):
                return (round(all_lats[int(q * (len(all_lats) - 1))]
                              * 1e3, 2) if all_lats else None)

            return {
                "shards": len(sids),
                "seconds": round(wall, 2),
                "writes": total,
                "writes_per_s": round(total / wall),
                "client_latency_ms": {"p50": pct(0.50), "p99": pct(0.99)},
            }

        main_rec = measure_window(shards, b"x" * 16, seconds)
        detail = {
            "mode": "serve",
            "shards": n_shards,
            "window": window,
            "seconds": main_rec["seconds"],
            "writes": main_rec["writes"],
            "elected": elected,
            "client_latency_ms": main_rec["client_latency_ms"],
        }
        # ---- 1024B payload phase: large writes through the snappy
        # entry-compression codec (node.propose encodes; the 16B phase
        # never invokes it — 1024B is the shape compression exists for)
        if n_comp > 0:
            comp_rec = measure_window(comp_shards, b"x" * 1024, seconds)
            comp_rec["payload_bytes"] = 1024
            comp_rec["entry_compression"] = "snappy"
            detail["payload_1024"] = comp_rec
        wps = main_rec["writes"] / main_rec["seconds"]
        emit({
            "metric": (f"serving-path writes/sec, {n_shards} shards x 3 "
                       f"replicas, 16B, window {window}"),
            "value": round(wps),
            "unit": "writes/s",
            "vs_baseline": round(wps / BASELINE_WPS, 4),
            "detail": detail,
        })
    finally:
        for nh in hosts.values():
            nh.close()
        shutil.rmtree(root, ignore_errors=True)


def run_telemetry_ab() -> None:
    """BENCH_TELEMETRY=1: A-B overhead of the device-side fleet_stats
    reduction (core/fleet.py) at the engine's decimation cadence.

    Arm A runs the plain bench loop in ``every``-step launches; arm B
    runs the identical launches plus one jitted ``fleet_stats`` call and
    its host fetch per launch — exactly what KernelEngine's
    ``_collect_fleet_stats`` adds every ``fleet_stats_every`` steps.
    Arms are interleaved A,B,A,B,... (median-of-3 per arm) so box drift
    lands on both.  Knobs: BENCH_TELEM_GROUPS (default 10000),
    BENCH_TELEM_STEPS (120), BENCH_TELEM_EVERY (10)."""
    import numpy as np  # noqa: F401

    import jax

    from dragonboat_tpu.bench_loop import (
        bench_params,
        elect_all,
        make_cluster,
        run_steps,
    )
    from dragonboat_tpu.core import fleet

    platform = jax.devices()[0].platform
    replicas = 3
    g = int(os.environ.get("BENCH_TELEM_GROUPS", "10000"))
    steps = int(os.environ.get("BENCH_TELEM_STEPS", "120"))
    every = max(1, int(os.environ.get("BENCH_TELEM_EVERY", "10")))
    kp = bench_params(replicas)
    state = make_cluster(kp, g, replicas)
    state, box = elect_all(kp, replicas, state)

    def window(with_stats: bool) -> float:
        nonlocal state, box
        t0 = time.time()
        done = 0
        while done < steps:
            state, box = run_steps(kp, replicas, every, True, True,
                                   state, box)
            done += every
            if with_stats:
                fleet.stats_to_dict(fleet.fleet_stats(state, box.from_))
        state.term.block_until_ready()
        return time.time() - t0

    # warm both executables (run_steps at `every`, fleet_stats) outside
    # the timed windows
    window(True)
    a_walls, b_walls = [], []
    for _ in range(3):
        a_walls.append(window(False))
        b_walls.append(window(True))
    a = sorted(a_walls)[1]
    b = sorted(b_walls)[1]
    overhead_pct = (b - a) / a * 100.0
    emit({
        "metric": (f"fleet_stats step-latency overhead, {g} groups x "
                   f"{replicas} replicas, decimation N={every}"),
        "value": round(overhead_pct, 2),
        "unit": "% vs uninstrumented step",
        "vs_baseline": 0.0,
        "detail": {
            "platform": platform,
            "groups": g,
            "replicas": replicas,
            "steps_per_arm_window": steps,
            "decimation_every": every,
            "plain_wall_s": [round(x, 3) for x in a_walls],
            "telemetry_wall_s": [round(x, 3) for x in b_walls],
            "plain_step_ms": round(a / steps * 1e3, 3),
            "telemetry_step_ms": round(b / steps * 1e3, 3),
            "policy": "median-of-3 interleaved windows per arm",
        },
    })


def run_health_ab() -> None:
    """BENCH_HEALTH=1: interleaved A-B overhead of the device-side
    fleet_health pass (core/health.py) on top of the fleet_stats
    baseline, at the engine's decimation cadence.

    Arm A is the pre-health production path: the bench loop in
    ``every``-step launches plus one fleet_stats call + fetch per launch.
    Arm B adds exactly what KernelEngine._collect_health adds — one
    jitted ``fleet_health`` call carrying the HealthDigest between
    launches, plus its O(K) report fetch.  Arms interleave A,B,A,B,...
    (median-of-3 per arm) so box drift lands on both.  Knobs:
    BENCH_HEALTH_GROUPS (default 10000), BENCH_HEALTH_STEPS (120),
    BENCH_HEALTH_EVERY (10)."""
    import jax

    from dragonboat_tpu.bench_loop import (
        bench_params,
        elect_all,
        make_cluster,
        run_steps,
    )
    from dragonboat_tpu.core import fleet, health

    platform = jax.devices()[0].platform
    replicas = 3
    g = int(os.environ.get("BENCH_HEALTH_GROUPS", "10000"))
    steps = int(os.environ.get("BENCH_HEALTH_STEPS", "120"))
    every = max(1, int(os.environ.get("BENCH_HEALTH_EVERY", "10")))
    kp = bench_params(replicas)
    state = make_cluster(kp, g, replicas)
    state, box = elect_all(kp, replicas, state)
    num_lanes = int(state.term.shape[0])
    digest = health.empty_digest(num_lanes)

    def window(with_health: bool) -> float:
        nonlocal state, box, digest
        t0 = time.time()
        done = 0
        while done < steps:
            state, box = run_steps(kp, replicas, every, True, True,
                                   state, box)
            done += every
            fleet.stats_to_dict(fleet.fleet_stats(state, box.from_))
            if with_health:
                report, digest = health.fleet_health(state, box.from_,
                                                     digest)
                health.report_to_dict(report)
        state.term.block_until_ready()
        return time.time() - t0

    # warm all executables (run_steps, fleet_stats, fleet_health)
    # outside the timed windows
    window(True)
    a_walls, b_walls = [], []
    for _ in range(3):
        a_walls.append(window(False))
        b_walls.append(window(True))
    a = sorted(a_walls)[1]
    b = sorted(b_walls)[1]
    overhead_pct = (b - a) / a * 100.0
    emit({
        "metric": (f"fleet_health step-latency overhead, {g} groups x "
                   f"{replicas} replicas, decimation N={every}"),
        "value": round(overhead_pct, 2),
        "unit": "% vs fleet_stats-only step",
        "vs_baseline": 0.0,
        "detail": {
            "platform": platform,
            "groups": g,
            "replicas": replicas,
            "steps_per_arm_window": steps,
            "decimation_every": every,
            "stats_only_wall_s": [round(x, 3) for x in a_walls],
            "health_wall_s": [round(x, 3) for x in b_walls],
            "stats_only_step_ms": round(a / steps * 1e3, 3),
            "health_step_ms": round(b / steps * 1e3, 3),
            "top_k": health.DEFAULT_TOP_K,
            "policy": "median-of-3 interleaved windows per arm",
        },
    })


def run_transfer_ab() -> None:
    """BENCH_TRANSFER=1: interleaved A-B overhead of the transfer-guard
    rail (capacity.METER + jax.transfer_guard) on the engine dispatch
    seam.

    Arm A drives SerialDispatch + the staging builders + the per-step
    flags fetch bare; arm B runs the identical loop inside
    ``METER.guard()`` — every declared crossing then enters a scoped
    ``transfer_guard("allow")`` and bumps its tag counter, which is
    exactly what the transfer lint pass's dynamic leg and the guarded
    differential tests add on top of production.  Arms interleave
    A,B,A,B,... (median-of-3 per arm) so cluster drift lands on both.
    The detail block carries the static per-step ledger bytes at this
    geometry plus the observed METER tag counts, tying the measured
    loop to the transfer_ledger crossing inventory.  Knobs:
    BENCH_TRANSFER_GROUPS (default 2048), BENCH_TRANSFER_STEPS (200).
    Expected: noise floor — the rail is a dict bump and a context
    manager per crossing."""
    import contextlib

    import jax
    import numpy as np

    from dragonboat_tpu import capacity
    from dragonboat_tpu.analysis import transfer as transfer_pass
    from dragonboat_tpu.bench_loop import bench_params, make_cluster
    from dragonboat_tpu.core.kernel import output_row_flags
    from dragonboat_tpu.engine import kernel_engine as _ke
    from dragonboat_tpu.engine.dispatch import SerialDispatch

    platform = jax.devices()[0].platform
    replicas = 3
    g = int(os.environ.get("BENCH_TRANSFER_GROUPS", "2048"))
    steps = int(os.environ.get("BENCH_TRANSFER_STEPS", "200"))
    kp = bench_params(replicas)
    state = make_cluster(kp, g, replicas)
    lanes = int(state.term.shape[0])
    disp = SerialDispatch(kp)
    inbox = _ke._InboxBuilder(lanes, kp.inbox_cap, kp.msg_entries)
    inp = _ke._InputBuilder(lanes, kp.proposal_cap)

    def window(guarded: bool) -> float:
        nonlocal state
        ctx = (capacity.METER.guard() if guarded
               else contextlib.nullcontext())
        t0 = time.time()
        with ctx:
            for _ in range(steps):
                state, out = disp.dispatch(state, inbox, inp,
                                           donate=False)
                with capacity.METER.sanctioned("output_flags"):
                    np.asarray(output_row_flags(out))
        state.term.block_until_ready()
        return time.time() - t0

    window(True)  # warm every compile and the guard path itself
    capacity.METER.reset()
    a_walls, b_walls = [], []
    for _ in range(3):
        a_walls.append(window(False))
        b_walls.append(window(True))
    a = sorted(a_walls)[1]
    b = sorted(b_walls)[1]
    overhead_pct = (b - a) / a * 100.0
    cfg = dict(transfer_pass.DEFAULT_CONFIG)
    cfg.update(num_groups=lanes, num_peers=kp.num_peers,
               log_cap=kp.log_cap, inbox_cap=kp.inbox_cap,
               msg_entries=kp.msg_entries, proposal_cap=kp.proposal_cap,
               readindex_cap=kp.readindex_cap,
               inline_payloads=bool(kp.inline_payloads))
    ledger = transfer_pass.build_ledger(
        os.path.dirname(os.path.abspath(__file__)), cfg=cfg)
    emit({
        "metric": (f"transfer-guard rail step-latency overhead, "
                   f"{g} groups x {replicas} replicas"),
        "value": round(overhead_pct, 2),
        "unit": "% vs unguarded dispatch loop",
        "vs_baseline": 0.0,
        "detail": {
            "platform": platform,
            "groups": g,
            "replicas": replicas,
            "steps_per_arm_window": steps,
            "plain_wall_s": [round(x, 3) for x in a_walls],
            "guarded_wall_s": [round(x, 3) for x in b_walls],
            "plain_step_ms": round(a / steps * 1e3, 3),
            "guarded_step_ms": round(b / steps * 1e3, 3),
            "meter_counts_all_windows": capacity.METER.counts(),
            "ledger_per_step_serial": ledger["per_step"]["serial"],
            "policy": "median-of-3 interleaved windows per arm",
        },
    })


def run_elastic_ab() -> None:
    """BENCH_ELASTIC=1: the elastic control plane's two closing numbers
    (ROADMAP item 4) in one artifact.

    Leg 1 — controller under 100:1 skew.  Three arms on the chaos
    hotspot harness (3 in-process NodeHosts, 2 device-resident shards,
    the slow-apply HotspotKV SM): uniform load with the controller ON,
    100:1 skew with the controller OFF (reference), 100:1 skew with
    the controller ON.  Each arm is its own cluster (the controller is
    an ExpertConfig bit) pumped async for one fixed wall window then
    drained; acked throughput counts resolved-completed futures over
    the pump+drain wall.  The headline value is skew-on/uniform (the
    acceptance bar: within ~15% of uniform).  The skew-off reference
    can EXCEED uniform in this harness: all three hosts share one
    process (and the GIL), so concentrating every proposal on one
    shard pipelines the slow apply back to back while uniform pays
    cross-shard staging on both — it is reported to show the harness
    ceiling, not as a bar the controller must beat.  Transfers per arm
    come from the flight recorder (CONTROL_TRANSFER records).

    Leg 2 — masked quiesce at 90% cold.  3 NodeHosts x
    BENCH_ELASTIC_SHARDS device-resident shards on one kernel; 10% of
    the shards carry continuous pipelined writers, the rest idle.  Arm
    A starts every shard with Config.quiesce=False (cold leaders keep
    heartbeating); arm B starts the cold 90% with Config.quiesce=True
    and waits for the fleet.quiesced_shards gauge to report every cold
    lane masked on every host (leaders included — heartbeats neither
    wake nor defer the masked form).  Arms run on separate sequential
    clusters (quiesce is a start-time Config bit); median-of-3 windows
    per arm read the engines' own step counters.  The saving is the
    host seam — fewer staged/emitted messages per engine round — and
    in this harness the engine thread is tick-saturated in BOTH arms
    (steps take ~10x the tick interval, so ticks coalesce and duty
    pegs at ~one core per host), which means the saving surfaces as
    cheaper per-step time, not lower duty: the headline is median
    per-step ms reduction, with duty/steps/writes in the detail.
    Knobs: BENCH_ELASTIC_PUMP_S (12), BENCH_ELASTIC_SHARDS (20),
    BENCH_ELASTIC_SECONDS (per quiesce window, 4),
    BENCH_ELASTIC_WINDOW (pipelined proposals per hot shard, 16)."""
    import shutil
    import tempfile
    import threading
    import time as _t
    from collections import deque
    from random import Random

    import jax

    from dragonboat_tpu import flight
    from dragonboat_tpu.chaos.runner import (
        _Cluster, HotspotKV, HOTSPOT_HOT_EWMA_US, HOTSPOT_MAX_PENDING,
        HOTSPOT_SKEW)
    from dragonboat_tpu.client import Session
    from dragonboat_tpu.config import Config, ExpertConfig, NodeHostConfig
    from dragonboat_tpu.nodehost import NodeHost
    from dragonboat_tpu.statemachine import IStateMachine, Result

    platform = jax.devices()[0].platform
    pump_s = float(os.environ.get("BENCH_ELASTIC_PUMP_S", "12"))
    n_shards = int(os.environ.get("BENCH_ELASTIC_SHARDS", "20"))
    seconds = float(os.environ.get("BENCH_ELASTIC_SECONDS", "4"))
    window = int(os.environ.get("BENCH_ELASTIC_WINDOW", "16"))
    seed = 11

    # -- leg 1: controller A/B under skew --------------------------------

    def leg1_arm(name: str, controller_on: bool, skew: bool) -> dict:
        rng = Random(seed)
        shards = (1, 2)
        hot, cold = 1, 2
        overrides = dict(
            fleet_stats_every=5,
            control_enabled=controller_on, control_hysteresis=2,
            control_cooldown_obs=8, control_max_transfers=1,
            control_seed=seed, control_hot_ewma_us=HOTSPOT_HOT_EWMA_US)
        cluster = _Cluster(seed=seed, n=3, device_resident=True,
                           expert_overrides=overrides, shards=shards,
                           sm_cls=HotspotKV)
        pending: list = []

        def fire(sid: int, cmd: bytes) -> None:
            rids = cluster.live_rids()
            nh = cluster.hosts[rids[len(pending) % len(rids)]]
            try:
                rs = nh.propose(nh.get_noop_session(sid), cmd,
                                timeout_s=30.0)
            except Exception:
                return      # book full / not ready: a drop, not an ack
            pending.append(rs)

        def unresolved() -> int:
            return sum(1 for rs in pending if not rs._event.is_set())

        def max_ewma() -> int:
            return max((int(cluster.hosts[rid].events.metrics.snapshot()
                            .get("engine.kernel_step.ewma_us", 0))
                        for rid in cluster.live_rids()), default=0)

        try:
            cluster.start()
            for sid in shards:
                assert cluster.propose(f"g{sid}=1".encode(), timeout=45.0,
                                       shard=sid), f"shard {sid} stuck"
            # let the jit-compile EWMA spike decay so the controller's
            # warmup guard is not what the arms measure
            deadline = _t.time() + 60.0
            while (max_ewma() >= HOTSPOT_HOT_EWMA_US
                   and _t.time() < deadline):
                _t.sleep(0.25)
            start_seq = flight.RECORDER.next_seq
            t0 = _t.time()
            i = 0
            while _t.time() - t0 < pump_s:
                if unresolved() < HOTSPOT_MAX_PENDING:
                    if skew:
                        batch = [hot] * HOTSPOT_SKEW + [cold]
                    else:
                        batch = [hot, cold] * (HOTSPOT_SKEW // 2)
                    rng.shuffle(batch)
                    for sid in batch:
                        if _t.time() - t0 >= pump_s:
                            break
                        fire(sid, f"h{sid}i{i}=v".encode())
                        i += 1
                _t.sleep(0.02)
            deadline = _t.time() + 60.0
            while unresolved() and _t.time() < deadline:
                _t.sleep(0.1)
            wall = _t.time() - t0
            acked = sum(1 for rs in pending if rs.wait(0).completed())
            transfers = sum(
                1 for r in flight.RECORDER.tail()
                if r["seq"] >= start_seq
                and r["kind"] == flight.CONTROL_TRANSFER)
            return {"arm": name, "fired": len(pending), "acked": acked,
                    "wall_s": round(wall, 1), "transfers": transfers,
                    "unresolved": unresolved(),
                    "acked_per_s": round(acked / wall, 1)}
        finally:
            cluster.close()

    uniform = leg1_arm("uniform-ctl-on", True, False)
    skew_off = leg1_arm("skew-ctl-off", False, True)
    skew_on = leg1_arm("skew-ctl-on", True, True)
    ratio = skew_on["acked_per_s"] / max(1e-9, uniform["acked_per_s"])
    emit({
        "metric": ("elastic controller: 100:1-skew acked throughput "
                   "vs uniform, controller on"),
        "value": round(ratio * 100.0, 1),
        "unit": "% of uniform acked throughput",
        "vs_baseline": 0.0,
        "detail": {
            "platform": platform,
            "pump_s": pump_s,
            "skew": HOTSPOT_SKEW,
            "arms": [uniform, skew_off, skew_on],
            "skew_off_over_uniform": round(
                skew_off["acked_per_s"]
                / max(1e-9, uniform["acked_per_s"]), 3),
            "policy": ("one pumped window per arm, one cluster per arm "
                       "(controller on/off is start-time ExpertConfig); "
                       "single-process GIL-shared harness, slow-apply "
                       "SM — the skew-off reference shows the "
                       "apply-bound ceiling of one concentrated shard"),
        },
    })

    # -- leg 2: masked quiesce at 90% cold -------------------------------

    class NullSM(IStateMachine):
        def __init__(self, *a):
            self.n = 0

        def update(self, entry):
            self.n += 1
            return Result(value=self.n)

        def lookup(self, q):
            return self.n

        def save_snapshot(self, w, files, done):
            w.write(b"\x00")

        def recover_from_snapshot(self, r, files, done):
            r.read(1)

    shards = tuple(range(1, n_shards + 1))
    hot_shards = shards[:max(1, n_shards // 10)]
    cold_shards = shards[len(hot_shards):]
    addrs = {1: "el-1", 2: "el-2", 3: "el-3"}

    def leg2_arm(quiesce_cold: bool) -> dict:
        ex = ExpertConfig(kernel_log_cap=128, kernel_capacity=n_shards,
                          kernel_apply_batch=32,
                          kernel_compaction_overhead=16,
                          fleet_stats_every=8)
        hosts: dict = {}
        root = tempfile.mkdtemp(prefix="dbtpu-elastic-")
        stop = threading.Event()
        writers: list = []
        try:
            for rid, addr in addrs.items():
                nh = NodeHost(NodeHostConfig(
                    raft_address=addr, rtt_millisecond=2, expert=ex,
                    node_host_dir=os.path.join(root, f"nh{rid}")))
                hosts[rid] = nh
                for sid in shards:
                    # heartbeat_rtt=1: the cold 90%'s heartbeat volume
                    # IS what the quiesce mask deletes — run it at the
                    # chaos harness's rate so the off arm carries it
                    nh.start_replica(addrs, False, NullSM, Config(
                        shard_id=sid, replica_id=rid, election_rtt=10,
                        heartbeat_rtt=1, device_resident=True,
                        quiesce=quiesce_cold and sid in cold_shards))
            deadline = _t.time() + 120
            while _t.time() < deadline:
                if all(any(hosts[r].get_leader_id(s)[1] for r in addrs)
                       for s in shards):
                    break
                _t.sleep(0.1)

            acked = [0] * len(hot_shards)

            def writer(i: int, sid: int) -> None:
                sess = Session.new_noop_session(sid)

                def leader_host():
                    lid, ok = hosts[1].get_leader_id(sid)
                    return hosts[lid if ok and lid in hosts else 1]

                futs: deque = deque()
                payload = b"x" * 16
                while not stop.is_set():
                    try:
                        nh = leader_host()
                        while len(futs) < window:
                            futs.append(nh.propose(sess, payload,
                                                   timeout_s=10.0))
                        futs.popleft().get(10.0)
                        acked[i] += 1
                    except Exception:
                        futs.clear()
                        _t.sleep(0.02)

            writers = [threading.Thread(target=writer, args=(i, sid),
                                        daemon=True)
                       for i, sid in enumerate(hot_shards)]
            for t in writers:
                t.start()

            def quiesced_total() -> int:
                return sum(
                    int(hosts[r].events.metrics.snapshot()
                        .get("fleet.quiesced_shards", 0)) for r in addrs)

            # idle cold lanes cross the e_timeout*10 idle threshold in
            # ~200 ms here; wait for EVERY cold lane on EVERY host so
            # the windows measure the fully-engaged mask (arm A settles
            # the same wall time so warmup drift lands on both arms)
            want = len(cold_shards) * len(addrs) if quiesce_cold else 0
            deadline = _t.time() + 30.0
            while quiesced_total() < want and _t.time() < deadline:
                _t.sleep(0.1)
            _t.sleep(1.0)

            def step_totals() -> tuple[int, int]:
                steps = us = 0
                for nh in hosts.values():
                    snap = nh.events.metrics.snapshot()
                    steps += snap.get("engine.kernel_step.steps", 0)
                    us += snap.get("engine.kernel_step.total_us", 0)
                return steps, us

            def measure() -> dict:
                s0, u0 = step_totals()
                w0 = sum(acked)
                _t.sleep(seconds)
                s1, u1 = step_totals()
                w1 = sum(acked)
                return {
                    "steps": s1 - s0,
                    "step_ms": round((u1 - u0) / max(1, s1 - s0) / 1e3,
                                     3),
                    "duty_ms_per_s": round((u1 - u0) / 1e3 / seconds, 1),
                    "writes_per_s": round((w1 - w0) / seconds),
                }
            measure()    # warm one throwaway window
            runs = [measure() for _ in range(3)]
            return {"runs": runs, "quiesced_gauge": quiesced_total(),
                    "step_ms": sorted(r["step_ms"] for r in runs)[1],
                    "duty_ms_per_s": sorted(
                        r["duty_ms_per_s"] for r in runs)[1]}
        finally:
            stop.set()
            for t in writers:
                t.join(timeout=15)
            for nh in hosts.values():
                nh.close()
            shutil.rmtree(root, ignore_errors=True)

    off = leg2_arm(False)
    on = leg2_arm(True)
    a, b = off["step_ms"], on["step_ms"]
    reduction_pct = (a - b) / max(1e-9, a) * 100.0
    emit({
        "metric": (f"masked quiesce: engine step-time reduction, "
                   f"{n_shards} shards x 3 replicas, "
                   f"{len(cold_shards)} cold"),
        "value": round(reduction_pct, 1),
        "unit": "% median per-step ms vs quiesce-off",
        "vs_baseline": 0.0,
        "detail": {
            "platform": platform,
            "shards": n_shards,
            "hot_shards": len(hot_shards),
            "cold_shards": len(cold_shards),
            "seconds_per_window": seconds,
            "off_arm": off,
            "on_arm": on,
            "expected_quiesced_gauge": len(cold_shards) * len(addrs),
            "policy": ("median-of-3 windows per arm, arms on separate "
                       "sequential clusters (quiesce is start-time "
                       "Config); engine threads are tick-saturated in "
                       "both arms (duty pegs ~1 core/host), so the "
                       "host-seam saving lands in per-step ms — "
                       "device shapes are fixed by design"),
        },
    })


def run_safety_ab() -> None:
    """BENCH_SAFETY=1: interleaved A-B overhead of the runtime
    invariant probe (core/invariants.py) on top of the fleet_stats +
    fleet_health production path, at the engine's decimation cadence.

    Arm A is the pre-probe production path: the bench loop in
    ``every``-step launches plus one fleet_stats and one fleet_health
    call + fetch per launch.  Arm B adds exactly what
    KernelEngine._collect_invariants adds — one jitted
    ``check_invariants`` call carrying the InvariantDigest between
    launches, plus its O(NI) report fetch.  Arms interleave A,B,A,B,...
    (median-of-3 per arm) so box drift lands on both.  Knobs:
    BENCH_SAFETY_GROUPS (default 10000), BENCH_SAFETY_STEPS (120),
    BENCH_SAFETY_EVERY (10)."""
    import jax

    from dragonboat_tpu.bench_loop import (
        bench_params,
        elect_all,
        make_cluster,
        run_steps,
    )
    from dragonboat_tpu.core import fleet, health, invariants

    platform = jax.devices()[0].platform
    replicas = 3
    g = int(os.environ.get("BENCH_SAFETY_GROUPS", "10000"))
    steps = int(os.environ.get("BENCH_SAFETY_STEPS", "120"))
    every = max(1, int(os.environ.get("BENCH_SAFETY_EVERY", "10")))
    kp = bench_params(replicas)
    state = make_cluster(kp, g, replicas)
    state, box = elect_all(kp, replicas, state)
    num_lanes = int(state.term.shape[0])
    h_digest = health.empty_digest(num_lanes)
    i_digest = invariants.empty_digest(num_lanes)
    violations_seen = 0

    def window(with_probe: bool) -> float:
        nonlocal state, box, h_digest, i_digest, violations_seen
        t0 = time.time()
        done = 0
        while done < steps:
            state, box = run_steps(kp, replicas, every, True, True,
                                   state, box)
            done += every
            fleet.stats_to_dict(fleet.fleet_stats(state, box.from_))
            h_report, h_digest = health.fleet_health(state, box.from_,
                                                     h_digest)
            health.report_to_dict(h_report)
            if with_probe:
                i_report, i_digest = invariants.check_invariants(
                    state, i_digest)
                violations_seen += invariants.report_to_dict(
                    i_report)["total"]
        state.term.block_until_ready()
        return time.time() - t0

    # warm all executables (run_steps, fleet_stats, fleet_health,
    # check_invariants) outside the timed windows
    window(True)
    a_walls, b_walls = [], []
    for _ in range(3):
        a_walls.append(window(False))
        b_walls.append(window(True))
    a = sorted(a_walls)[1]
    b = sorted(b_walls)[1]
    overhead_pct = (b - a) / a * 100.0
    emit({
        "metric": (f"invariant-probe step-latency overhead, {g} groups "
                   f"x {replicas} replicas, decimation N={every}"),
        "value": round(overhead_pct, 2),
        "unit": "% vs stats+health step",
        "vs_baseline": 0.0,
        "detail": {
            "platform": platform,
            "groups": g,
            "replicas": replicas,
            "steps_per_arm_window": steps,
            "decimation_every": every,
            "plain_wall_s": [round(x, 3) for x in a_walls],
            "probe_wall_s": [round(x, 3) for x in b_walls],
            "plain_step_ms": round(a / steps * 1e3, 3),
            "probe_step_ms": round(b / steps * 1e3, 3),
            "num_invariants": invariants.NUM_INVARIANTS,
            # the probed windows double as a scaled safety check: a
            # healthy 10k-group bench cluster must stay violation-free
            "violations_seen": int(violations_seen),
            "policy": "median-of-3 interleaved windows per arm",
        },
    })


def run_capacity_ab() -> None:
    """BENCH_CAPACITY=1: interleaved A-B overhead of the capacity rail
    (capacity.py) on top of the fleet_stats + fleet_health production
    path, at the engine's decimation cadence.

    Arm A is the post-health production path: the bench loop in
    ``every``-step launches plus one fleet_stats and one fleet_health
    call + fetch per launch.  Arm B routes the same three dispatches
    through CompileTracker wrappers (the cache-size probe around every
    call) and adds exactly what KernelEngine._collect_capacity adds per
    launch — one measure_tree_bytes walk over the live trees plus one
    engine_snapshot assembly (contracts model + allocator stats +
    watermark check).  Arms interleave A,B,A,B,... (median-of-3 per
    arm) so box drift lands on both.  Knobs: BENCH_CAPACITY_GROUPS
    (default 10000), BENCH_CAPACITY_STEPS (120), BENCH_CAPACITY_EVERY
    (10)."""
    import jax

    from dragonboat_tpu import capacity
    from dragonboat_tpu.bench_loop import (
        bench_params,
        elect_all,
        make_cluster,
        run_steps,
    )
    from dragonboat_tpu.core import fleet, health

    platform = jax.devices()[0].platform
    replicas = 3
    g = int(os.environ.get("BENCH_CAPACITY_GROUPS", "10000"))
    steps = int(os.environ.get("BENCH_CAPACITY_STEPS", "120"))
    every = max(1, int(os.environ.get("BENCH_CAPACITY_EVERY", "10")))
    kp = bench_params(replicas)
    state = make_cluster(kp, g, replicas)
    state, box = elect_all(kp, replicas, state)
    num_lanes = int(state.term.shape[0])
    digest = health.empty_digest(num_lanes)
    classes = ("ShardState", "HealthDigest")   # KernelEngine's model set

    wrapped = {
        "bench_run_steps":
            capacity.TRACKER.wrap("bench_run_steps", run_steps),
        "bench_fleet_stats":
            capacity.TRACKER.wrap("bench_fleet_stats", fleet.fleet_stats),
        "bench_fleet_health":
            capacity.TRACKER.wrap("bench_fleet_health",
                                  health.fleet_health),
    }
    peak = 0
    seq = 0

    def window(with_capacity: bool) -> float:
        nonlocal state, box, digest, peak, seq
        t0 = time.time()
        done = 0
        while done < steps:
            done += every
            if not with_capacity:
                state, box = run_steps(kp, replicas, every, True, True,
                                       state, box)
                fleet.stats_to_dict(fleet.fleet_stats(state, box.from_))
                report, digest = health.fleet_health(state, box.from_,
                                                     digest)
                health.report_to_dict(report)
                continue
            state, box = wrapped["bench_run_steps"](
                kp, replicas, every, True, True, state, box)
            fleet.stats_to_dict(
                wrapped["bench_fleet_stats"](state, box.from_))
            report, digest = wrapped["bench_fleet_health"](
                state, box.from_, digest)
            health.report_to_dict(report)
            seq += 1
            live = capacity.measure_tree_bytes(state, digest)
            peak = max(peak, live)
            capacity.engine_snapshot(
                kp, num_lanes, live, peak,
                {n: w.stats() for n, w in wrapped.items()},
                ticks=seq, classes=classes)
        state.term.block_until_ready()
        return time.time() - t0

    # warm every executable (run_steps at `every`, fleet_stats,
    # fleet_health, and the capacity host path) outside the timed windows
    window(True)
    a_walls, b_walls = [], []
    for _ in range(3):
        a_walls.append(window(False))
        b_walls.append(window(True))
    a = sorted(a_walls)[1]
    b = sorted(b_walls)[1]
    overhead_pct = (b - a) / a * 100.0
    emit({
        "metric": (f"capacity-rail step-latency overhead, {g} groups x "
                   f"{replicas} replicas, decimation N={every}"),
        "value": round(overhead_pct, 2),
        "unit": "% vs stats+health step",
        "vs_baseline": 0.0,
        "detail": {
            "platform": platform,
            "groups": g,
            "replicas": replicas,
            "steps_per_arm_window": steps,
            "decimation_every": every,
            "plain_wall_s": [round(x, 3) for x in a_walls],
            "capacity_wall_s": [round(x, 3) for x in b_walls],
            "plain_step_ms": round(a / steps * 1e3, 3),
            "capacity_step_ms": round(b / steps * 1e3, 3),
            "bench_entries": {n: w.stats() for n, w in wrapped.items()},
            "policy": "median-of-3 interleaved windows per arm",
        },
    })


def run_trace_ab() -> None:
    """BENCH_TRACE=1: interleaved A-B overhead of proposal-lifecycle
    tracing (lifecycle.py) at the default 1-in-64 sampling.

    The tracer lives in the HOST plumbing (request books, staging,
    retire, logdb, apply pool, transport hub), so the pure jitted loops
    the telemetry A/B used have no tracer presence at all — this bench
    drives the full serving path instead (the run_serve_bench harness:
    3 in-process NodeHosts, chan transport, device-resident shards,
    steady pipelined writer per shard) with traffic running
    CONTINUOUSLY while the arms alternate: each window re-points the
    process-global tracer (sample_every 0 = off vs the default 64) and
    reads the engines' own step-latency counters over the window.  Arms
    interleave A,B,A,B,... (median-of-3 per arm) so box drift lands on
    both.  Knobs: BENCH_TRACE_SHARDS (default 16), BENCH_TRACE_SECONDS
    (per window, default 4), BENCH_TRACE_WINDOW (pipelined proposals
    per shard, 16), BENCH_TRACE_EVERY (sampling rate in arm B, 64)."""
    import shutil
    import tempfile
    import threading
    import time as _t
    from collections import deque

    import jax

    from dragonboat_tpu import lifecycle
    from dragonboat_tpu.client import Session
    from dragonboat_tpu.config import Config, ExpertConfig, NodeHostConfig
    from dragonboat_tpu.nodehost import NodeHost
    from dragonboat_tpu.statemachine import IStateMachine, Result

    class NullSM(IStateMachine):
        def __init__(self, *a):
            self.n = 0

        def update(self, entry):
            self.n += 1
            return Result(value=self.n)

        def lookup(self, q):
            return self.n

        def save_snapshot(self, w, files, done):
            w.write(b"\x00")

        def recover_from_snapshot(self, r, files, done):
            r.read(1)

    platform = jax.devices()[0].platform
    n_shards = int(os.environ.get("BENCH_TRACE_SHARDS", "16"))
    seconds = float(os.environ.get("BENCH_TRACE_SECONDS", "4"))
    window = int(os.environ.get("BENCH_TRACE_WINDOW", "16"))
    every = int(os.environ.get("BENCH_TRACE_EVERY", "64"))
    shards = tuple(range(1, n_shards + 1))
    addrs = {1: "tr-1", 2: "tr-2", 3: "tr-3"}
    ex = ExpertConfig(kernel_log_cap=128, kernel_capacity=n_shards,
                      kernel_apply_batch=32,
                      kernel_compaction_overhead=16,
                      trace_sample_every=0)    # arm A state at start
    hosts = {}
    root = tempfile.mkdtemp(prefix="dbtpu-trace-")
    stop = threading.Event()
    writers = []
    try:
        for rid, addr in addrs.items():
            nh = NodeHost(NodeHostConfig(
                raft_address=addr, rtt_millisecond=2, expert=ex,
                node_host_dir=os.path.join(root, f"nh{rid}")))
            hosts[rid] = nh
            for sid in shards:
                nh.start_replica(addrs, False, NullSM, Config(
                    shard_id=sid, replica_id=rid, election_rtt=10,
                    heartbeat_rtt=2, device_resident=True))
        deadline = _t.time() + 120
        while _t.time() < deadline:
            if all(any(hosts[r].get_leader_id(s)[1] for r in addrs)
                   for s in shards):
                break
            _t.sleep(0.1)

        acked = [0] * n_shards

        def writer(i: int, sid: int) -> None:
            sess = Session.new_noop_session(sid)

            def leader_host():
                lid, ok = hosts[1].get_leader_id(sid)
                return hosts[lid if ok and lid in hosts else 1]

            futs: deque = deque()
            payload = b"x" * 16
            while not stop.is_set():
                try:
                    nh = leader_host()
                    while len(futs) < window:
                        futs.append(nh.propose(sess, payload,
                                               timeout_s=10.0))
                    futs.popleft().get(10.0)
                    acked[i] += 1
                except Exception:
                    futs.clear()
                    _t.sleep(0.02)

        writers = [threading.Thread(target=writer, args=(i, sid),
                                    daemon=True)
                   for i, sid in enumerate(shards)]
        for t in writers:
            t.start()
        _t.sleep(1.0)    # settle: windows full, elections over

        def step_totals() -> tuple[int, int]:
            steps = us = 0
            for nh in hosts.values():
                snap = nh.events.metrics.snapshot()
                steps += snap.get("engine.kernel_step.steps", 0)
                us += snap.get("engine.kernel_step.total_us", 0)
            return steps, us

        def measure(sample_every: int) -> dict:
            lifecycle.TRACER.configure(sample_every=sample_every)
            _t.sleep(0.2)    # flush windows staged under the old arm
            s0, u0 = step_totals()
            w0 = sum(acked)
            _t.sleep(seconds)
            s1, u1 = step_totals()
            w1 = sum(acked)
            return {
                "steps": s1 - s0,
                "step_ms": round((u1 - u0) / max(1, s1 - s0) / 1e3, 3),
                "writes_per_s": round((w1 - w0) / seconds),
            }

        a_runs, b_runs = [], []
        measure(0)           # warm one throwaway window
        for _ in range(3):
            a_runs.append(measure(0))
            b_runs.append(measure(every))
        stop.set()
        a = sorted(r["step_ms"] for r in a_runs)[1]
        b = sorted(r["step_ms"] for r in b_runs)[1]
        overhead_pct = (b - a) / a * 100.0
        traces = len(lifecycle.TRACER.completed())
        emit({
            "metric": (f"lifecycle-trace step-latency overhead, "
                       f"{n_shards} shards x 3 replicas, serving path, "
                       f"sampling 1/{every}"),
            "value": round(overhead_pct, 2),
            "unit": "% vs tracing-off arm",
            "vs_baseline": 0.0,
            "detail": {
                "platform": platform,
                "shards": n_shards,
                "window": window,
                "seconds_per_window": seconds,
                "sample_every": every,
                "off_arm": a_runs,
                "on_arm": b_runs,
                "off_step_ms": a,
                "on_step_ms": b,
                "completed_traces_in_ring": traces,
                "policy": "median-of-3 interleaved windows per arm, "
                          "continuous traffic",
            },
        })
    finally:
        stop.set()
        for t in writers:
            t.join(timeout=15)
        for nh in hosts.values():
            nh.close()
        shutil.rmtree(root, ignore_errors=True)


def run_fabric_ab() -> None:
    """BENCH_FABRIC=1: interleaved A-B overhead of the full fabric
    observability stack (fabric.py) — per-link transport telemetry +
    trace propagation + hop census — on top of lifecycle tracing.

    Same harness as run_trace_ab (3 in-process NodeHosts, chan
    transport, device-resident shards, continuous pipelined writers)
    but the arms toggle BOTH dials together: arm A = tracer off +
    fabric meter off, arm B = tracer at the default 1-in-64 sampling +
    fabric meter on, so the B arm pays the per-batch link tallies AND
    the sampled header/census path — the whole round-16 addition.
    Knobs: BENCH_FABRIC_SHARDS (default 16), BENCH_FABRIC_SECONDS (per
    window, default 4), BENCH_FABRIC_WINDOW (pipelined proposals per
    shard, 16), BENCH_FABRIC_EVERY (sampling rate in arm B, 64)."""
    import shutil
    import tempfile
    import threading
    import time as _t
    from collections import deque

    import jax

    from dragonboat_tpu import fabric, lifecycle
    from dragonboat_tpu.client import Session
    from dragonboat_tpu.config import Config, ExpertConfig, NodeHostConfig
    from dragonboat_tpu.nodehost import NodeHost
    from dragonboat_tpu.statemachine import IStateMachine, Result

    class NullSM(IStateMachine):
        def __init__(self, *a):
            self.n = 0

        def update(self, entry):
            self.n += 1
            return Result(value=self.n)

        def lookup(self, q):
            return self.n

        def save_snapshot(self, w, files, done):
            w.write(b"\x00")

        def recover_from_snapshot(self, r, files, done):
            r.read(1)

    platform = jax.devices()[0].platform
    n_shards = int(os.environ.get("BENCH_FABRIC_SHARDS", "16"))
    seconds = float(os.environ.get("BENCH_FABRIC_SECONDS", "4"))
    window = int(os.environ.get("BENCH_FABRIC_WINDOW", "16"))
    every = int(os.environ.get("BENCH_FABRIC_EVERY", "64"))
    shards = tuple(range(1, n_shards + 1))
    addrs = {1: "fb-1", 2: "fb-2", 3: "fb-3"}
    ex = ExpertConfig(kernel_log_cap=128, kernel_capacity=n_shards,
                      kernel_apply_batch=32,
                      kernel_compaction_overhead=16,
                      trace_sample_every=0,      # arm A state at start
                      fabric_telemetry=False)
    hosts = {}
    root = tempfile.mkdtemp(prefix="dbtpu-fabric-")
    stop = threading.Event()
    writers = []
    try:
        for rid, addr in addrs.items():
            nh = NodeHost(NodeHostConfig(
                raft_address=addr, rtt_millisecond=2, expert=ex,
                node_host_dir=os.path.join(root, f"nh{rid}")))
            hosts[rid] = nh
            for sid in shards:
                nh.start_replica(addrs, False, NullSM, Config(
                    shard_id=sid, replica_id=rid, election_rtt=10,
                    heartbeat_rtt=2, device_resident=True))
        deadline = _t.time() + 120
        while _t.time() < deadline:
            if all(any(hosts[r].get_leader_id(s)[1] for r in addrs)
                   for s in shards):
                break
            _t.sleep(0.1)

        acked = [0] * n_shards

        def writer(i: int, sid: int) -> None:
            sess = Session.new_noop_session(sid)

            def leader_host():
                lid, ok = hosts[1].get_leader_id(sid)
                return hosts[lid if ok and lid in hosts else 1]

            futs: deque = deque()
            payload = b"x" * 16
            while not stop.is_set():
                try:
                    nh = leader_host()
                    while len(futs) < window:
                        futs.append(nh.propose(sess, payload,
                                               timeout_s=10.0))
                    futs.popleft().get(10.0)
                    acked[i] += 1
                except Exception:
                    futs.clear()
                    _t.sleep(0.02)

        writers = [threading.Thread(target=writer, args=(i, sid),
                                    daemon=True)
                   for i, sid in enumerate(shards)]
        for t in writers:
            t.start()
        _t.sleep(1.0)    # settle: windows full, elections over

        def step_totals() -> tuple[int, int]:
            steps = us = 0
            for nh in hosts.values():
                snap = nh.events.metrics.snapshot()
                steps += snap.get("engine.kernel_step.steps", 0)
                us += snap.get("engine.kernel_step.total_us", 0)
            return steps, us

        def measure(sample_every: int, fabric_on: bool) -> dict:
            lifecycle.TRACER.configure(sample_every=sample_every)
            fabric.METER.configure(enabled=fabric_on)
            _t.sleep(0.2)    # flush windows staged under the old arm
            s0, u0 = step_totals()
            w0 = sum(acked)
            _t.sleep(seconds)
            s1, u1 = step_totals()
            w1 = sum(acked)
            return {
                "steps": s1 - s0,
                "step_ms": round((u1 - u0) / max(1, s1 - s0) / 1e3, 3),
                "writes_per_s": round((w1 - w0) / seconds),
            }

        a_runs, b_runs = [], []
        measure(0, False)    # warm one throwaway window
        for _ in range(3):
            a_runs.append(measure(0, False))
            b_runs.append(measure(every, True))
        stop.set()
        a = sorted(r["step_ms"] for r in a_runs)[1]
        b = sorted(r["step_ms"] for r in b_runs)[1]
        overhead_pct = (b - a) / a * 100.0
        snap = fabric.METER.snapshot()
        emit({
            "metric": (f"fabric-telemetry step-latency overhead, "
                       f"{n_shards} shards x 3 replicas, serving path, "
                       f"tracer+meter vs neither, sampling 1/{every}"),
            "value": round(overhead_pct, 2),
            "unit": "% vs fabric-off arm",
            "vs_baseline": 0.0,
            "detail": {
                "platform": platform,
                "shards": n_shards,
                "window": window,
                "seconds_per_window": seconds,
                "sample_every": every,
                "off_arm": a_runs,
                "on_arm": b_runs,
                "off_step_ms": a,
                "on_step_ms": b,
                "links_seen": len(snap["links"]),
                "census_finished": snap["census"]["finished"],
                "p50_commit_host_hops":
                    snap["census"]["p50_commit_host_hops"],
                "policy": "median-of-3 interleaved windows per arm, "
                          "continuous traffic, both dials per arm",
            },
        })
    finally:
        stop.set()
        for t in writers:
            t.join(timeout=15)
        for nh in hosts.values():
            nh.close()
        shutil.rmtree(root, ignore_errors=True)


def run_pipeline_ab() -> None:
    """BENCH_PIPELINE=1: A-B of the serial depth-0 loop vs the fused
    depth-1 pipelined loop (PR 6) at MATCHED micro-step counts — the
    pipelined arm runs half as many fori iterations, each two fused
    micro-steps, so both arms advance the protocol identically (they
    are bitwise-equal loops, tests/test_pipeline_differential.py).

    Phase 1 interleaves throughput windows A,B,A,B,... (median-of-3 per
    arm, same policy as the headline bench) and reports step_ms +
    writes/s per arm.  Phase 2 runs the instrumented latency loop per
    arm and reports commit percentiles in each arm's OWN clock unit:
    device steps for serial, pipeline steps for pipelined — raft's
    propose->commit chain spans 2 micro-steps, so the pipelined arm's
    p50 lands at <= 1 pipeline step where the serial arm needs 2.
    Knobs: BENCH_PIPE_GROUPS (default 1024 — the BENCH_r06 comparison
    geometry), BENCH_PIPE_STEPS (micro-steps per window, default 120),
    BENCH_PIPE_LAT_STEPS (default max(40, steps // 2))."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    from dragonboat_tpu.bench_loop import (
        bench_params,
        elect_all,
        lat_init,
        make_cluster,
        run_steps,
        run_steps_lat,
        run_steps_lat_pipelined,
        run_steps_pipelined,
    )
    from dragonboat_tpu.core import params as KP

    platform = jax.devices()[0].platform
    replicas = 3
    g = int(os.environ.get("BENCH_PIPE_GROUPS", "1024"))
    micro = int(os.environ.get("BENCH_PIPE_STEPS", "120"))
    micro -= micro % 2
    lat_steps = int(os.environ.get("BENCH_PIPE_LAT_STEPS",
                                   str(max(40, micro // 2))))
    lat_steps -= lat_steps % 2
    kp = bench_params(replicas)
    B = kp.proposal_cap
    state0, box0 = elect_all(kp, replicas, make_cluster(kp, g, replicas))
    lead = np.asarray(state0.role) == KP.LEADER

    arms = {"serial": {"state": state0, "box": box0},
            "pipelined": {"state": state0, "box": box0}}

    def committed(st):
        return np.asarray(st.committed)[lead].astype(np.int64).sum()

    def window(arm):
        a = arms[arm]
        if arm == "serial":
            def run():
                a["state"], a["box"] = run_steps(
                    kp, replicas, micro, True, True, a["state"], a["box"])
        else:
            def run():
                a["state"], a["box"] = run_steps_pipelined(
                    kp, replicas, micro // 2, True, True,
                    a["state"], a["box"])
        c0 = committed(a["state"])
        t0 = time.time()
        run()
        a["state"].term.block_until_ready()
        dt = time.time() - t0
        w = int(committed(a["state"]) - c0)
        return {"wall_s": round(dt, 3),
                "micro_step_ms": round(dt / micro * 1e3, 3),
                "writes": w,
                "writes_per_s": round(w / dt)}

    # warm both executables outside the timed windows
    for arm in arms:
        window(arm)
    wins = {"serial": [], "pipelined": []}
    for _ in range(3):
        for arm in ("serial", "pipelined"):
            wins[arm].append(window(arm))
    med = {arm: sorted(ws, key=lambda r: r["micro_step_ms"])[1]
           for arm, ws in wins.items()}

    def lat_arm(arm):
        a = arms[arm]
        pipe = arm == "pipelined"
        loop = run_steps_lat_pipelined if pipe else run_steps_lat
        iters = lat_steps // 2 if pipe else lat_steps
        stamp, hist, reads = lat_init(kp, a["state"].term.shape[0])
        # warm the exact executable; its stamps stay in the baseline
        st, bx, sp, hi, rd = loop(
            kp, replicas, iters, B, False, True, True,
            jnp.asarray(0, jnp.int32), a["state"], a["box"],
            stamp, hist, reads)
        hi0 = np.asarray(hi).astype(np.int64)
        t0 = time.time()
        st, bx, sp, hi, rd = loop(
            kp, replicas, iters, B, False, True, True,
            jnp.asarray(iters, jnp.int32), st, bx, sp, hi, rd)
        st.term.block_until_ready()
        dt = time.time() - t0
        histw = np.asarray(hi).astype(np.int64) - hi0
        # latency unit = this arm's dispatch clock; cost scaled to the
        # UNinstrumented step_ms, as the headline latency phase does
        unit_ms = med[arm]["micro_step_ms"] * (2 if pipe else 1)
        out = {"unit": "pipeline steps" if pipe else "device steps",
               "unit_step_ms": round(unit_ms, 3),
               "instrumented_wall_s": round(dt, 3)}
        for name, q in (("p50", 0.50), ("p99", 0.99), ("p99.9", 0.999)):
            p = _pctile(histw, q)
            out[name + "_steps"] = p
            out[name + "_ms"] = (round(p * unit_ms, 3) if p is not None
                                 else None)
        return out

    lat = {arm: lat_arm(arm) for arm in ("serial", "pipelined")}
    s_ms, p_ms = med["serial"]["micro_step_ms"], med["pipelined"]["micro_step_ms"]
    emit({
        "metric": (f"pipelined vs serial step loop, {g} groups x "
                   f"{replicas} replicas, 16B"),
        "value": med["pipelined"]["writes_per_s"],
        "unit": "writes/s (pipelined arm)",
        "vs_baseline": round(med["pipelined"]["writes_per_s"]
                             / BASELINE_WPS, 4),
        "detail": {
            "platform": platform,
            "groups": g,
            "micro_steps_per_window": micro,
            "policy": "median-of-3 interleaved windows per arm",
            "serial": {**med["serial"], "windows": wins["serial"],
                       "commit_latency": lat["serial"]},
            "pipelined": {**med["pipelined"], "windows": wins["pipelined"],
                          "commit_latency": lat["pipelined"]},
            "micro_step_ms_ratio": round(p_ms / s_ms, 4) if s_ms else None,
        },
    })


def run_cpu_subprocess(degraded_note: str | None) -> None:
    """Re-exec on CPU, STREAMING the child's lines through as they
    appear (an external kill then still leaves the child's provisional
    line as our last output); on a clean finish the last line is
    re-emitted with the degradation note attached."""
    p = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__)], env=cpu_env(),
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
    )
    last = None
    assert p.stdout is not None
    for line in p.stdout:
        line = line.strip()
        if not line:
            continue
        print(line, flush=True)
        last = line
    p.wait()
    try:
        parsed = json.loads(last or "")
        if degraded_note:
            parsed["detail"] = parsed.get("detail", {})
            parsed["detail"]["degraded"] = degraded_note
            emit(parsed)
    except Exception:
        fail("cpu-fallback", f"no JSON from fallback (rc={p.returncode})")


def run_mesh_pipeline_ab() -> None:
    """BENCH_MESH_PIPELINE=1: A-B of the MESH dispatch path's two jit
    entries (engine/dispatch.py MeshDispatch) under the same host
    protocol the engine runs — serial depth-0 (non-donated
    jit_serve_step, blocking per-step staging) vs pipelined depth-1
    (jit_serve_step_donated: buffers donated to XLA, host staging
    built from one-step-stale retired copies) — at 1024 groups x 3
    replicas on a ('g','r') = (1, 3) host mesh.

    Interleaved windows A,B,A,B,... (median-of-3 per arm, the headline
    bench's policy); each arm reports wall, per-micro-step time and
    committed writes/s on leader rows.  Knobs: BENCH_MESH_GROUPS
    (default 1024), BENCH_MESH_STEPS (micro-steps per window, default
    120)."""
    import numpy as np

    import jax

    from dragonboat_tpu.bench_loop import bench_params
    from dragonboat_tpu.core import params as KP
    from dragonboat_tpu.core.kstate import StepInput
    from dragonboat_tpu.parallel.ici import (
        jit_serve_step,
        jit_serve_step_donated,
        make_ici_cluster,
    )
    from jax.sharding import Mesh

    replicas = 3
    devs = jax.devices()
    if len(devs) < replicas:
        raise RuntimeError(
            f"mesh A/B needs {replicas} host devices, have {len(devs)} "
            "(main() forces xla_force_host_platform_device_count "
            "before jax loads — do not preimport jax)")
    groups = int(os.environ.get("BENCH_MESH_GROUPS", "1024"))
    micro = int(os.environ.get("BENCH_MESH_STEPS", "120"))
    platform = devs[0].platform
    kp = bench_params(replicas)
    B = kp.proposal_cap
    mesh = Mesh(np.array(devs[:replicas]).reshape(1, replicas),
                ("g", "r"))
    cluster, state0, box0 = make_ici_cluster(kp, mesh, groups)
    cut = cluster.shard(
        np.zeros((cluster.total_rows, kp.num_peers), bool))

    def host_input(role_h, proc_h, propose=True):
        # the engine's _InputBuilder shape: staged from HOST copies, so
        # nothing aliases the donated device buffers
        G = role_h.shape[0]
        lead = role_h == KP.LEADER
        z = lambda: np.zeros((G,), np.int32)  # noqa: E731
        return StepInput(
            prop_valid=np.broadcast_to(
                lead[:, None] & propose, (G, B)).copy(),
            prop_cc=np.zeros((G, B), bool),
            ri_valid=np.zeros((G,), bool),
            ri_low=z(), ri_high=z(), transfer_to=z(),
            tick=np.ones((G,), bool),
            quiesced=np.zeros((G,), bool),
            applied=proc_h)

    # election pump: tick until every group has one leader
    state, box = state0, box0
    for _ in range(40):
        role_h = np.asarray(state.role)
        if int((role_h == KP.LEADER).sum()) >= groups:
            break
        inp = cluster.shard(host_input(
            role_h, np.asarray(state.processed), propose=False))
        state, box, _ = jit_serve_step(
            kp, cluster, state, box, inp, cut)
    lead_rows = np.asarray(state.role) == KP.LEADER

    def committed(st):
        return int(np.asarray(st.committed)[lead_rows]
                   .astype(np.int64).sum())

    arms = {"serial": {"state": state, "box": box},
            "pipelined": {"state": state, "box": box}}

    def window(arm):
        a = arms[arm]
        c0 = committed(a["state"])
        t0 = time.time()
        if arm == "serial":
            # depth-0 protocol: stage from the CURRENT state (blocking
            # host fetch), dispatch the non-donated oracle
            for _ in range(micro):
                inp = cluster.shard(host_input(
                    np.asarray(a["state"].role),
                    np.asarray(a["state"].processed)))
                a["state"], a["box"], _ = jit_serve_step(
                    kp, cluster, a["state"], a["box"], inp, cut)
        else:
            # depth-1 protocol: stage from one-step-stale retired
            # copies (host build overlaps the in-flight device step),
            # pull the NEXT staging copies right before dispatch hands
            # the buffers to XLA.
            # np.array (a real copy), never np.asarray: on CPU that is
            # a zero-copy view of a buffer this arm donates away
            role_h = np.array(a["state"].role)
            proc_h = np.array(a["state"].processed)
            for _ in range(micro):
                inp = cluster.shard(host_input(role_h, proc_h))
                role_h = np.array(a["state"].role)
                proc_h = np.array(a["state"].processed)
                a["state"], a["box"], _ = \
                    jit_serve_step_donated(
                        kp, cluster, a["state"], a["box"], inp, cut)
        a["state"].term.block_until_ready()
        dt = time.time() - t0
        w = committed(a["state"]) - c0
        return {"wall_s": round(dt, 3),
                "micro_step_ms": round(dt / micro * 1e3, 3),
                "writes": w,
                "writes_per_s": round(w / dt)}

    for arm in arms:  # warm both executables outside the timed windows
        window(arm)
    wins = {"serial": [], "pipelined": []}
    for _ in range(3):
        for arm in ("serial", "pipelined"):
            wins[arm].append(window(arm))
    med = {arm: sorted(ws, key=lambda r: r["micro_step_ms"])[1]
           for arm, ws in wins.items()}
    speedup = (med["serial"]["micro_step_ms"]
               / max(med["pipelined"]["micro_step_ms"], 1e-9))
    emit({
        "metric": ("mesh dispatch serial vs pipelined (donated), "
                   f"{groups} groups x {replicas} replicas"),
        "value": round(speedup, 3),
        "unit": "x serial/pipelined micro-step time",
        "vs_baseline": 0.0,
        "detail": {
            "platform": platform,
            "mesh": f"('g','r') = (1, {replicas})",
            "groups": groups,
            "micro_steps_per_window": micro,
            "serial": med["serial"],
            "pipelined": med["pipelined"],
            "windows": wins,
            "policy": "median-of-3 interleaved windows per arm",
        },
    })


def run_fabric_resident_ab() -> None:
    """BENCH_FABRIC_RESIDENT=1: the round-17 tentpole's closing number
    — co-located consensus traffic over the interconnect vs through the
    host hub, on the SERVING loop (parallel/ici.py jit_serve_step).

    Arm A (resident) serves with an all-open per-link cut mask:
    messages ride the in-step collective and the host stages nothing
    but StepInput.  Arm B (hub) serves with EVERY link cut — the step
    emits but exchanges nothing on the mesh; its out-lanes are pulled
    to the host, staged through core/router.route (the hub fallback's
    slot addressing) and re-uploaded as the next inbox, which is
    exactly what every co-located message paid before round 17.  Arms
    interleave A,B,A,B,... (median-of-3 per arm); the resident entry
    runs under a CompileTracker wrapper and must show compiles=1 /
    retraces=0 across pump + warm + all windows.  Knobs:
    BENCH_FABRIC_RESIDENT_GROUPS (default 1024),
    BENCH_FABRIC_RESIDENT_STEPS (micro-steps per window, default
    120)."""
    import numpy as np

    import jax

    from dragonboat_tpu import capacity
    from dragonboat_tpu.bench_loop import bench_params
    from dragonboat_tpu.core import params as KP
    from dragonboat_tpu.core.router import route
    from dragonboat_tpu.parallel.ici import (
        jit_serve_step,
        make_ici_cluster,
        self_driving_input,
    )
    from jax.sharding import Mesh

    replicas = 3
    devs = jax.devices()
    if len(devs) < replicas:
        raise RuntimeError(
            f"fabric A/B needs {replicas} host devices, have {len(devs)} "
            "(main() forces xla_force_host_platform_device_count "
            "before jax loads — do not preimport jax)")
    groups = int(os.environ.get("BENCH_FABRIC_RESIDENT_GROUPS", "1024"))
    micro = int(os.environ.get("BENCH_FABRIC_RESIDENT_STEPS", "120"))
    platform = devs[0].platform
    kp = bench_params(replicas)
    mesh = Mesh(np.array(devs[:replicas]).reshape(1, replicas),
                ("g", "r"))
    cluster, state, box = make_ici_cluster(kp, mesh, groups)
    # g_size=1 layout: router row n*R+ir lives at mesh row ir*groups+n
    perm = np.empty(groups * replicas, np.int64)
    for n in range(groups):
        for ir in range(replicas):
            perm[n * replicas + ir] = ir * groups + n
    iperm = np.argsort(perm)
    total = cluster.total_rows
    cut_open = cluster.shard(
        np.zeros((total, kp.num_peers), bool))
    cut_all = cluster.shard(
        np.ones((total, kp.num_peers), bool))

    # prime the startup-only signature: the very first call sees the
    # fresh device_put arrays from make_ici_cluster, whose committed
    # layouts differ from every later jit-output step — a one-time
    # second lowering that exists at any engine's startup, not a
    # retrace the serving loop can hit
    inp = self_driving_input(kp, state, propose=False)
    state, box, _ = jit_serve_step(kp, cluster, state, box, inp,
                                   cut_open)

    # the resident entry under compile telemetry: the acceptance gate
    # is ONE compile (the steady-state signature) and ZERO retraces
    # across pump + warm + every window — cut is a traced argument, so
    # flipping the mask must not re-lower the executable
    tracker = capacity.CompileTracker()
    serve_resident = tracker.wrap("fabric_resident_serve",
                                  jit_serve_step)

    # election pump (resident path) until every group has one leader
    for _ in range(40):
        if int((np.asarray(state.role) == KP.LEADER).sum()) >= groups:
            break
        inp = self_driving_input(kp, state, propose=False)
        state, box, _ = serve_resident(
            kp, cluster, state, box, inp, cut_open)
    lead_rows = np.asarray(state.role) == KP.LEADER

    route_jit = jax.jit(route, static_argnums=(0, 1))
    pull = lambda t: jax.tree.map(  # noqa: E731
        lambda x: np.array(x), t)
    repermute = lambda t, p: jax.tree.map(  # noqa: E731
        lambda x: x[p], t)

    def committed(st):
        return int(np.asarray(st.committed)[lead_rows]
                   .astype(np.int64).sum())

    arms = {"resident": {"state": state, "box": box},
            "hub": {"state": state, "box": box}}

    def window(arm):
        a = arms[arm]
        c0 = committed(a["state"])
        t0 = time.time()
        for _ in range(micro):
            inp = self_driving_input(kp, a["state"], propose=True)
            if arm == "resident":
                a["state"], a["box"], _ = serve_resident(
                    kp, cluster, a["state"], a["box"], inp, cut_open)
            else:
                # hub delivery: the mesh exchanges nothing (every link
                # cut); out-lanes round-trip the host through route()
                a["state"], _, outgoing = jit_serve_step(
                    kp, cluster, a["state"], a["box"], inp, cut_all)
                hub_box = route_jit(
                    kp, replicas, repermute(pull(outgoing), perm))
                a["box"] = cluster.shard(repermute(pull(hub_box), iperm))
        a["state"].term.block_until_ready()
        dt = time.time() - t0
        w = committed(a["state"]) - c0
        return {"wall_s": round(dt, 3),
                "micro_step_ms": round(dt / micro * 1e3, 3),
                "writes": w,
                "writes_per_s": round(w / dt)}

    for arm in arms:  # warm both executables outside the timed windows
        window(arm)
    wins = {"resident": [], "hub": []}
    for _ in range(3):
        for arm in ("resident", "hub"):
            wins[arm].append(window(arm))
    med = {arm: sorted(ws, key=lambda r: r["micro_step_ms"])[1]
           for arm, ws in wins.items()}
    speedup = (med["hub"]["micro_step_ms"]
               / max(med["resident"]["micro_step_ms"], 1e-9))
    ct = serve_resident.stats()
    if ct["compiles"] != 1 or ct["retraces"] != 0:
        raise RuntimeError(
            f"resident serve entry re-lowered: {ct} (cut-mask flips or "
            "input staging changed the traced signature)")
    emit({
        "metric": ("device-resident fabric vs host-hub delivery, "
                   f"{groups} groups x {replicas} replicas, "
                   "serving loop"),
        "value": round(speedup, 3),
        "unit": "x hub/resident micro-step time",
        "vs_baseline": 0.0,
        "detail": {
            "platform": platform,
            "mesh": f"('g','r') = (1, {replicas})",
            "groups": groups,
            "micro_steps_per_window": micro,
            "resident": med["resident"],
            "hub": med["hub"],
            "windows": wins,
            "resident_compile": {"calls": ct["calls"],
                                 "compiles": ct["compiles"],
                                 "retraces": ct["retraces"]},
            "policy": "median-of-3 interleaved windows per arm",
        },
    })


def main() -> None:
    if os.environ.get("BENCH_FABRIC_RESIDENT") == "1":
        # must run before anything imports jax: the 3-replica mesh
        # needs one host device per replica slot
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        _flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in _flags:
            os.environ["XLA_FLAGS"] = (
                _flags + " --xla_force_host_platform_device_count=3"
            ).strip()
        try:
            run_fabric_resident_ab()
        except Exception:
            import traceback

            fail("fabric-resident-ab", traceback.format_exc())
        return
    if os.environ.get("BENCH_MESH_PIPELINE") == "1":
        # must run before anything imports jax: the 3-replica mesh
        # needs one host device per replica slot
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        _flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in _flags:
            os.environ["XLA_FLAGS"] = (
                _flags + " --xla_force_host_platform_device_count=3"
            ).strip()
        try:
            run_mesh_pipeline_ab()
        except Exception:
            import traceback

            fail("mesh-pipeline-ab", traceback.format_exc())
        return
    if os.environ.get("BENCH_ELASTIC") == "1":
        try:
            run_elastic_ab()
        except Exception:
            import traceback

            fail("elastic-ab", traceback.format_exc())
        return
    if os.environ.get("BENCH_TRANSFER") == "1":
        try:
            run_transfer_ab()
        except Exception:
            import traceback

            fail("transfer-ab", traceback.format_exc())
        return
    if os.environ.get("BENCH_SAFETY") == "1":
        try:
            run_safety_ab()
        except Exception:
            import traceback

            fail("safety-ab", traceback.format_exc())
        return
    if os.environ.get("BENCH_CAPACITY") == "1":
        try:
            run_capacity_ab()
        except Exception:
            import traceback

            fail("capacity-ab", traceback.format_exc())
        return
    if os.environ.get("BENCH_FABRIC") == "1":
        try:
            run_fabric_ab()
        except Exception:
            import traceback

            fail("fabric-ab", traceback.format_exc())
        return
    if os.environ.get("BENCH_TRACE") == "1":
        try:
            run_trace_ab()
        except Exception:
            import traceback

            fail("trace-ab", traceback.format_exc())
        return
    if os.environ.get("BENCH_PIPELINE") == "1":
        try:
            run_pipeline_ab()
        except Exception:
            import traceback

            fail("pipeline-ab", traceback.format_exc())
        return
    if os.environ.get("BENCH_TELEMETRY") == "1":
        try:
            run_telemetry_ab()
        except Exception:
            import traceback

            fail("telemetry-ab", traceback.format_exc())
        return
    if os.environ.get("BENCH_HEALTH") == "1":
        try:
            run_health_ab()
        except Exception:
            import traceback

            fail("health-ab", traceback.format_exc())
        return
    if os.environ.get("BENCH_SERVE") == "1":
        try:
            run_serve_bench()
        except Exception:
            import traceback

            fail("serve", traceback.format_exc())
        return
    if os.environ.get("BENCH_IN_CPU_FALLBACK") != "1":
        if os.environ.get("BENCH_FORCE_CPU") == "1":
            run_cpu_subprocess(None)
            return
        timeout_s = float(os.environ.get("BENCH_PROBE_TIMEOUT", "180"))
        ndev, why = probe_devices(timeout_s)
        if ndev is None:
            # record the REAL failure (hang vs fast crash) in the artifact
            run_cpu_subprocess(f"device backend unavailable: {why}")
            return
    try:
        run_bench()
    except Exception:
        import traceback

        fail("run", traceback.format_exc())


if __name__ == "__main__":
    main()
