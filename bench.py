#!/usr/bin/env python
"""Benchmark: sustained replicated writes/sec across raft groups on TPU.

BASELINE config #2 shape: N groups × 3 replicas, 16B payloads, vmapped step
loop with on-device message routing; every write is a full raft round
(leader append → replicate → quorum ack → commit) with instant-apply RSM
feedback and device-side log compaction.  Prints ONE JSON line.

Baseline: the reference's 9M writes/s peak (3× 22-core Xeon servers,
BASELINE.md) — vs_baseline is measured/9e6.

Env knobs: BENCH_GROUPS (default 8192), BENCH_STEPS (default 200).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax  # noqa: E402

jax.config.update("jax_compilation_cache_dir", "/tmp/dragonboat_tpu_jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

import numpy as np  # noqa: E402

from dragonboat_tpu.bench_loop import (  # noqa: E402
    bench_params,
    elect_all,
    make_cluster,
    run_steps,
)
from dragonboat_tpu.core import params as KP  # noqa: E402


def main() -> None:
    groups = int(os.environ.get("BENCH_GROUPS", "8192"))
    steps = int(os.environ.get("BENCH_STEPS", "200"))
    replicas = 3
    kp = bench_params(replicas)

    state = make_cluster(kp, groups, replicas)
    state, box = elect_all(kp, replicas, state)
    lead = np.asarray(state.role) == KP.LEADER
    assert lead.reshape(-1, replicas).any(axis=1).all()

    # warmup (compile the propose-loop variant)
    state, box = run_steps(kp, replicas, 5, True, True, state, box)
    state.term.block_until_ready()

    c0 = np.asarray(state.committed)[lead].astype(np.int64).sum()
    t0 = time.time()
    state, box = run_steps(kp, replicas, steps, True, True, state, box)
    state.committed.block_until_ready()
    dt = time.time() - t0
    c1 = np.asarray(state.committed)[lead].astype(np.int64).sum()

    writes = int(c1 - c0)
    wps = writes / dt
    result = {
        "metric": f"replicated writes/sec, {groups} groups x 3 replicas, 16B",
        "value": round(wps),
        "unit": "writes/s",
        "vs_baseline": round(wps / 9e6, 4),
        "detail": {
            "groups": groups,
            "steps": steps,
            "wall_s": round(dt, 3),
            "step_ms": round(dt / steps * 1e3, 3),
            "writes": writes,
            "writes_per_group_step": round(writes / steps / groups, 2),
        },
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
