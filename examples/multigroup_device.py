"""Many raft groups on the TPU kernel: device-resident shards.

The dragonboat-example/multigroup analog, TPU-first: 32 shards run as
lanes of ONE batched device kernel (Config.device_resident) — a single
jitted step advances all of them. The host keeps the client API,
durable log, and snapshots.

Run: python examples/multigroup_device.py
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from dragonboat_tpu.config import Config, ExpertConfig, NodeHostConfig
from dragonboat_tpu.nodehost import NodeHost
from dragonboat_tpu.request import RequestDroppedError, RequestTimeoutError

from helloworld import KVStore


def main() -> int:
    shards = tuple(range(1, 33))
    nh = NodeHost(NodeHostConfig(
        raft_address="multi-1", rtt_millisecond=5,
        expert=ExpertConfig(kernel_log_cap=256, kernel_capacity=64)))
    for sid in shards:
        nh.start_replica({1: "multi-1"}, False, KVStore, Config(
            shard_id=sid, replica_id=1, election_rtt=10, heartbeat_rtt=1,
            device_resident=True))         # <- lane of the batched kernel
    deadline = time.time() + 120           # first jit compile is slow
    while time.time() < deadline:
        if all(nh.get_leader_id(s)[1] for s in shards):
            break
        time.sleep(0.2)
    elected = sum(nh.get_leader_id(s)[1] for s in shards)
    print(f"{elected}/32 shards elected on the device kernel")
    assert nh.nodes[1].peer is None, "raft state lives on the device"

    wrote = 0
    deadline = time.time() + 60
    for sid in shards:
        session = nh.get_noop_session(sid)
        while time.time() < deadline:
            try:
                nh.sync_propose(session, f"shard={sid}".encode(),
                                timeout_s=2.0)
                wrote += 1
                break
            except (RequestDroppedError, RequestTimeoutError):
                time.sleep(0.05)
    print(f"wrote to {wrote}/32 shards through one batched kernel")
    deadline = time.time() + 30
    read_value = None
    while time.time() < deadline:
        try:
            read_value = nh.sync_read(17, "shard")
            break
        except (RequestDroppedError, RequestTimeoutError):
            time.sleep(0.05)  # transient right after elections; retry
    assert read_value is not None, "shard 17 never served the read"
    print("shard 17 reads:", read_value)
    nh.close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
