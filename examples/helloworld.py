"""Hello, dragonboat-tpu: a 3-replica KV shard in one process.

The in-process analog of dragonboat-example/helloworld: three NodeHosts
over the chan transport host one replicated KV state machine; writes go
through SyncPropose (full raft round), reads through SyncRead
(linearizable ReadIndex).

Run: python examples/helloworld.py
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from dragonboat_tpu.config import Config, NodeHostConfig
from dragonboat_tpu.nodehost import NodeHost
from dragonboat_tpu.statemachine import IStateMachine, Result


class KVStore(IStateMachine):
    """cmd = b"key=value"; lookup(key) -> value."""

    def __init__(self, shard_id, replica_id):
        self.kv = {}

    def update(self, entry):
        k, v = entry.cmd.decode().split("=", 1)
        self.kv[k] = v
        return Result(value=len(self.kv))

    def lookup(self, key):
        return self.kv.get(key)

    def save_snapshot(self, w, files, done):
        data = "\n".join(f"{k}={v}" for k, v in sorted(self.kv.items()))
        w.write(data.encode())

    def recover_from_snapshot(self, r, files, done):
        self.kv = dict(line.split("=", 1)
                       for line in r.read().decode().split("\n") if line)


def main() -> int:
    members = {1: "hello-1", 2: "hello-2", 3: "hello-3"}
    hosts = {}
    for replica_id, addr in members.items():
        nh = NodeHost(NodeHostConfig(raft_address=addr, rtt_millisecond=5))
        nh.start_replica(members, False, KVStore, Config(
            shard_id=128, replica_id=replica_id,
            election_rtt=10, heartbeat_rtt=1,
            snapshot_entries=1000, compaction_overhead=50))
        hosts[replica_id] = nh

    # wait for a leader
    leader = None
    deadline = time.time() + 15
    while time.time() < deadline and leader is None:
        for rid, nh in hosts.items():
            lid, ok = nh.get_leader_id(128)
            if ok:
                leader = lid
                break
        time.sleep(0.05)
    assert leader is not None, "no leader elected"
    print(f"leader of shard 128: replica {leader}")

    nh = hosts[leader]
    session = nh.get_noop_session(128)
    for city, weather in [("tokyo", "sunny"), ("dublin", "rain"),
                          ("oakland", "fog")]:
        nh.sync_propose(session, f"{city}={weather}".encode())
        print(f"wrote {city}={weather}")

    # linearizable read from any host (follower hosts forward ReadIndex)
    reader = hosts[1 if leader != 1 else 2]
    print("dublin (linearizable read via follower host):",
          reader.sync_read(128, "dublin"))

    for nh in hosts.values():
        nh.close()
    print("ok")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
