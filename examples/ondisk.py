"""Durable mode: tan-backed raft log, crash-safe restart.

The dragonboat-example/ondisk analog: one shard on a real data
directory. Run it twice — the second run recovers every write from the
tan log + snapshots without initial members (they come from storage).

Run: python examples/ondisk.py /tmp/dbtpu-example
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from dragonboat_tpu.config import Config, NodeHostConfig
from dragonboat_tpu.nodehost import NodeHost

from helloworld import KVStore  # same SM, durable host


def main() -> int:
    data_dir = sys.argv[1] if len(sys.argv) > 1 else "/tmp/dbtpu-example"
    nh = NodeHost(NodeHostConfig(
        raft_address="durable-1", rtt_millisecond=5,
        node_host_dir=data_dir))           # <- durable: tan is the LogDB
    print("LogDB engine:", nh.logdb.name())
    restarting = nh.has_node_info(1, 1)
    nh.start_replica({} if restarting else {1: "durable-1"}, False,
                     KVStore, Config(
                         shard_id=1, replica_id=1, election_rtt=10,
                         heartbeat_rtt=1, snapshot_entries=100,
                         compaction_overhead=10))
    deadline = time.time() + 15
    while time.time() < deadline and not nh.get_leader_id(1)[1]:
        time.sleep(0.05)

    if restarting:
        deadline = time.time() + 10
        while time.time() < deadline and nh.stale_read(1, "boot") is None:
            time.sleep(0.05)
        print("recovered from disk: boot =", nh.stale_read(1, "boot"))

    session = nh.get_noop_session(1)
    stamp = str(int(time.time()))
    nh.sync_propose(session, f"boot={stamp}".encode())
    print("wrote boot =", stamp, "| run me again to see it recovered")
    nh.close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
