#!/usr/bin/env python
"""Render a per-check summary table from a lint findings artifact.

Input: the file produced by ``scripts/lint.py --format json`` (one
JSON object per line: path, line, pass, rule, message, waived, reason).
scripts/run_tests.sh writes it to ``build/lint_findings.jsonl`` (or
``$LINT_ARTIFACT``) so CI can upload it and diff findings between
commits, then runs this to fail the build with a readable breakdown
instead of a raw JSON wall.

Exit status: 1 iff any unwaived finding is present, 2 on a malformed
artifact.
"""

from __future__ import annotations

import json
import sys


def summarize(lines: list[str]) -> tuple[str, int]:
    """-> (report text, number of unwaived findings)."""
    rows: list[dict] = []
    for n, ln in enumerate(lines, 1):
        ln = ln.strip()
        if not ln:
            continue
        try:
            row = json.loads(ln)
        except ValueError as e:
            raise ValueError(f"line {n}: not JSON ({e})") from e
        if not isinstance(row, dict) or "rule" not in row:
            raise ValueError(f"line {n}: not a finding object")
        rows.append(row)

    counts: dict[tuple[str, str], list[int]] = {}
    for r in rows:
        c = counts.setdefault((str(r.get("pass")), str(r["rule"])), [0, 0])
        c[1 if r.get("waived") else 0] += 1

    out: list[str] = []
    unwaived = [r for r in rows if not r.get("waived")]
    for r in unwaived:
        out.append(f"  {r.get('path')}:{r.get('line')}: "
                   f"[{r['rule']}] {r.get('message')}")
    if out:
        out.append("")
    header = f"{'pass':<14} {'check':<7} {'unwaived':>8} {'waived':>7}"
    out.append(header)
    out.append("-" * len(header))
    for (pname, rule), (u, w) in sorted(counts.items()):
        out.append(f"{pname:<14} {rule:<7} {u:>8} {w:>7}")
    if not counts:
        out.append("(no findings)")
    total_u = len(unwaived)
    total_w = len(rows) - total_u
    out.append("-" * len(header))
    status = "FAIL" if total_u else "OK"
    out.append(f"{status}: {total_u} unwaived, {total_w} waived")
    return "\n".join(out), total_u


def main(argv: list[str]) -> int:
    if len(argv) != 2:
        print("usage: lint_summary.py <findings.jsonl>", file=sys.stderr)
        return 2
    try:
        with open(argv[1], encoding="utf-8") as f:
            lines = f.readlines()
    except OSError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    try:
        report, unwaived = summarize(lines)
    except ValueError as e:
        print(f"error: malformed artifact: {e}", file=sys.stderr)
        return 2
    print(report)
    return 1 if unwaived else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
