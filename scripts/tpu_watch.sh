#!/bin/sh
# Tunnel watcher: probe the TPU backend every ~8 min; on revival, spend
# the window on the measurement queue (profile first — it answers the
# current optimization question — then the throughput ladder).  Every
# result lands in PERF_TPU.jsonl / tpu_profile_*.log, so a window is
# never wasted even if the tunnel dies mid-run.
REPO="$(dirname "$(dirname "$(readlink -f "$0")")")"
LOG="$REPO/tpu_watch.log"
cd "$REPO" || exit 1
while true; do
    if timeout 90 python -c "import jax; assert jax.default_backend() == 'tpu'" 2>/dev/null; then
        echo "$(date -u +%FT%TZ) tunnel ALIVE; measuring" >> "$LOG"
        timeout 900 python scripts/tpu_profile.py 1024 \
            > "$REPO/tpu_profile_$(date -u +%F_%H%M).log" 2>&1
        # small rung first pins the fixed-cost intercept of the new
        # kernel; big rungs amortize it
        timeout 3700 python scripts/tpu_grab.py --ladder 64,1024,4096,8192 \
            >> "$LOG" 2>&1
        # the pallas rsm-apply verdict (compiled, not interpret mode)
        timeout 1200 python scripts/tpu_pallas_ab.py 1024 >> "$LOG" 2>&1
        # the scoreboard itself: a full bench on device (provisional
        # lines survive a mid-run wedge)
        timeout 3000 python "$REPO/bench.py" \
            > "$REPO/bench_tpu_$(date -u +%F_%H%M).json" \
            2>> "$LOG"
        echo "$(date -u +%FT%TZ) measurement pass done" >> "$LOG"
        sleep 1800
    else
        echo "$(date -u +%FT%TZ) tunnel wedged" >> "$LOG"
        sleep 480
    fi
done
