#!/usr/bin/env python
"""One-shot /metrics scraper for a running NodeHost.

Fetches the Prometheus text exposition from a NodeHost's opt-in
metrics endpoint (NodeHostConfig.enable_metrics / metrics_address),
validates it with the repo's strict parser (telemetry.parse_exposition
— the same one the round-trip golden test uses), and prints either the
raw text or a flat JSON object.

    python scripts/metrics_dump.py 127.0.0.1:9090
    python scripts/metrics_dump.py 127.0.0.1:9090 --json
    python scripts/metrics_dump.py 127.0.0.1:9090 --flight
    python scripts/metrics_dump.py 127.0.0.1:9090 --doctor
    python scripts/metrics_dump.py 127.0.0.1:9090 --capacity
    python scripts/metrics_dump.py 127.0.0.1:9090 --trace > trace.json

``--doctor`` scrapes /debug/groups — the fleet-health drill-down
(NodeHost.info(): merged anomaly snapshot + NodeHostInfo-parity shard
list) — and strictly validates it against the core/health.py schema
before printing (see scripts/fleet_doctor.py for the human report).

``--capacity`` scrapes /debug/capacity — the merged capacity snapshot
(capacity.py: live/peak bytes, headroom, contracts-model prediction,
per-entry compile/retrace counters) — strictly validated against the
capacity schema; exit 1 when memory pressure or a retrace storm is
flagged, so CI can gate on it.

``--trace`` scrapes /trace — the proposal-lifecycle spans as
Chrome-trace-event JSON — and validates it strictly
(lifecycle.validate_chrome_trace: required ph/ts/pid/tid keys,
monotone non-negative timestamps per span) before printing; the
output loads directly in Perfetto (ui.perfetto.dev) or
chrome://tracing.

``--fabric`` scrapes /debug/fabric — the per-link transport telemetry
+ hop-census snapshot (fabric.py) — strictly validated
(fabric.validate_fabric), cross-checks the per-class link totals for
send/recv symmetry, and writes the hop-census baseline artifact
(``build/fabric_census.json`` by default, ``--out`` to override): the
``p50_commit_host_hops`` number ROADMAP item 2 must drive to zero,
paired with PR 17's ``build/transfer_ledger.json`` per-step crossing
profile when that artifact exists.  Exit 1 on schema or consistency
failure.

Stdlib-only on the wire (urllib); exit status is non-zero when the
endpoint is unreachable or the exposition fails strict parsing.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import urllib.error
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def fetch(address: str, path: str, timeout: float) -> str:
    url = f"http://{address}{path}"
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.read().decode("utf-8")


def build_fabric_census(obj: dict) -> dict:
    """The hop-census baseline artifact from a validated /debug/fabric
    snapshot: the census block + per-class link totals + send/recv
    consistency over every link whose BOTH ends are visible in this
    process (a cross-process link legitimately shows only one side)."""
    sent_totals: dict = {}
    recv_totals: dict = {}
    failures: list[str] = []
    checked = 0
    for li in obj["links"]:
        for cls, n in li["sent"].items():
            sent_totals[cls] = sent_totals.get(cls, 0) + n
        for cls, n in li["recv"].items():
            recv_totals[cls] = recv_totals.get(cls, 0) + n
        if li["batches_sent"] > 0 and li["batches_recv"] > 0:
            checked += 1
            for cls, n in li["recv"].items():
                if n > li["sent"].get(cls, 0):
                    failures.append(
                        f"link {li['src']}->{li['dst']} class {cls}: "
                        f"recv {n} > sent {li['sent'].get(cls, 0)}")
    return {
        "enabled": obj["enabled"],
        "census": dict(obj["census"]),
        "p50_commit_host_hops": obj["census"]["p50_commit_host_hops"],
        "links": [{
            "src": li["src"], "dst": li["dst"],
            "bytes_sent": li["bytes_sent"],
            "delivery_p99_us": li["delivery_p99_us"],
        } for li in obj["links"]],
        "class_totals": {"sent": sent_totals, "recv": recv_totals},
        "consistency": {"checked_links": checked, "failures": failures},
    }


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("address", help="host:port of the /metrics endpoint")
    ap.add_argument("--json", action="store_true",
                    help="print samples as a flat JSON object instead of "
                         "the raw exposition text")
    ap.add_argument("--flight", action="store_true",
                    help="dump /flight (the flight-recorder tail) instead "
                         "of /metrics")
    ap.add_argument("--trace", action="store_true",
                    help="dump /trace (proposal-lifecycle spans as "
                         "Chrome-trace-event JSON, Perfetto-loadable) "
                         "instead of /metrics")
    ap.add_argument("--doctor", action="store_true",
                    help="dump /debug/groups (fleet-health drill-down) "
                         "instead of /metrics, strictly schema-validated")
    ap.add_argument("--capacity", action="store_true",
                    help="dump /debug/capacity (capacity snapshot: bytes, "
                         "headroom, compile counters) instead of /metrics, "
                         "strictly schema-validated; exit 1 on memory "
                         "pressure or retrace storm")
    ap.add_argument("--fabric", action="store_true",
                    help="dump /debug/fabric (per-link transport "
                         "telemetry + hop census) instead of /metrics, "
                         "strictly schema-validated, and write the "
                         "hop-census baseline artifact (--out)")
    ap.add_argument("--out", default=None,
                    help="artifact path for --fabric (default "
                         "build/fabric_census.json under the repo root)")
    ap.add_argument("--no-validate", action="store_true",
                    help="skip strict validation (exposition parsing / "
                         "Chrome-trace checks)")
    ap.add_argument("--timeout", type=float, default=5.0)
    args = ap.parse_args()

    path = ("/trace" if args.trace
            else "/flight" if args.flight
            else "/debug/groups" if args.doctor
            else "/debug/capacity" if args.capacity
            else "/debug/fabric" if args.fabric else "/metrics")
    try:
        text = fetch(args.address, path, args.timeout)
    except (urllib.error.URLError, OSError) as e:
        print(f"error: cannot scrape http://{args.address}{path}: {e}",
              file=sys.stderr)
        return 2

    if args.trace:
        try:
            obj = json.loads(text)
        except ValueError as e:
            print(f"error: /trace is not valid JSON: {e}", file=sys.stderr)
            return 1
        if not args.no_validate:
            from dragonboat_tpu.lifecycle import validate_chrome_trace

            try:
                n = validate_chrome_trace(obj)
            except ValueError as e:
                print(f"error: Chrome-trace validation failed: {e}",
                      file=sys.stderr)
                return 1
            print(f"ok: {n} trace event(s)", file=sys.stderr)
        print(text, end="" if text.endswith("\n") else "\n")
        return 0

    if args.doctor:
        try:
            obj = json.loads(text)
        except ValueError as e:
            print(f"error: /debug/groups is not valid JSON: {e}",
                  file=sys.stderr)
            return 1
        if not args.no_validate:
            from dragonboat_tpu.core.health import validate_info

            try:
                n = validate_info(obj)
            except ValueError as e:
                print(f"error: /debug/groups schema validation failed: {e}",
                      file=sys.stderr)
                return 1
            print(f"ok: {n} shard(s)", file=sys.stderr)
        print(json.dumps(obj, indent=2, sort_keys=True))
        return 0

    if args.capacity:
        try:
            obj = json.loads(text)
        except ValueError as e:
            print(f"error: /debug/capacity is not valid JSON: {e}",
                  file=sys.stderr)
            return 1
        if not args.no_validate:
            from dragonboat_tpu.capacity import validate_capacity

            try:
                validate_capacity(obj)
            except ValueError as e:
                print(f"error: /debug/capacity schema validation failed: "
                      f"{e}", file=sys.stderr)
                return 1
            print(f"ok: {len(obj['entries'])} compile entrie(s)",
                  file=sys.stderr)
        print(json.dumps(obj, indent=2, sort_keys=True))
        degraded = [k for k in ("memory_pressure", "retrace_storm")
                    if obj.get(k)]
        if degraded:
            print(f"degraded: {' '.join(degraded)}", file=sys.stderr)
            return 1
        return 0

    if args.fabric:
        try:
            obj = json.loads(text)
        except ValueError as e:
            print(f"error: /debug/fabric is not valid JSON: {e}",
                  file=sys.stderr)
            return 1
        if not args.no_validate:
            from dragonboat_tpu.fabric import validate_fabric

            try:
                n = validate_fabric(obj)
            except ValueError as e:
                print(f"error: /debug/fabric schema validation failed: "
                      f"{e}", file=sys.stderr)
                return 1
            print(f"ok: {n} link(s)", file=sys.stderr)
        artifact = build_fabric_census(obj)
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        out = args.out or os.path.join(root, "build", "fabric_census.json")
        ledger_path = os.path.join(root, "build", "transfer_ledger.json")
        if os.path.exists(ledger_path):
            # pair the host-hop baseline with PR 17's device-crossing
            # profile: ROADMAP item 2 drives BOTH to zero
            try:
                with open(ledger_path, encoding="utf-8") as f:
                    ledger = json.load(f)
                artifact["transfer_ledger"] = {
                    "path": os.path.relpath(ledger_path, root),
                    "per_step": ledger.get("per_step", {}),
                }
            except (OSError, ValueError) as e:
                print(f"warning: cannot pair {ledger_path}: {e}",
                      file=sys.stderr)
        os.makedirs(os.path.dirname(out), exist_ok=True)
        with open(out, "w", encoding="utf-8") as f:
            json.dump(artifact, f, indent=2, sort_keys=True)
            f.write("\n")
        print(f"wrote {out}", file=sys.stderr)
        print(json.dumps(artifact, indent=2, sort_keys=True))
        if not args.no_validate and artifact["consistency"]["failures"]:
            for msg in artifact["consistency"]["failures"]:
                print(f"error: consistency: {msg}", file=sys.stderr)
            return 1
        return 0

    if args.flight:
        print(text, end="" if text.endswith("\n") else "\n")
        return 0

    families = None
    if args.json or not args.no_validate:
        from dragonboat_tpu.telemetry import parse_exposition

        try:
            families = parse_exposition(text)
        except ValueError as e:
            print(f"error: exposition failed strict parsing: {e}",
                  file=sys.stderr)
            return 1

    if args.json:
        flat = {}
        for fam in sorted(families):
            for sname, labels, value in families[fam]["samples"]:
                key = sname
                if labels:
                    key += "{" + ",".join(
                        f"{k}={labels[k]}" for k in sorted(labels)) + "}"
                flat[key] = value
        print(json.dumps(flat, indent=2, sort_keys=True))
    else:
        print(text, end="" if text.endswith("\n") else "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
