#!/usr/bin/env python
"""One-shot /metrics scraper for a running NodeHost.

Fetches the Prometheus text exposition from a NodeHost's opt-in
metrics endpoint (NodeHostConfig.enable_metrics / metrics_address),
validates it with the repo's strict parser (telemetry.parse_exposition
— the same one the round-trip golden test uses), and prints either the
raw text or a flat JSON object.

    python scripts/metrics_dump.py 127.0.0.1:9090
    python scripts/metrics_dump.py 127.0.0.1:9090 --json
    python scripts/metrics_dump.py 127.0.0.1:9090 --flight

Stdlib-only on the wire (urllib); exit status is non-zero when the
endpoint is unreachable or the exposition fails strict parsing.
"""

from __future__ import annotations

import argparse
import json
import sys
import urllib.error
import urllib.request


def fetch(address: str, path: str, timeout: float) -> str:
    url = f"http://{address}{path}"
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.read().decode("utf-8")


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("address", help="host:port of the /metrics endpoint")
    ap.add_argument("--json", action="store_true",
                    help="print samples as a flat JSON object instead of "
                         "the raw exposition text")
    ap.add_argument("--flight", action="store_true",
                    help="dump /flight (the flight-recorder tail) instead "
                         "of /metrics")
    ap.add_argument("--no-validate", action="store_true",
                    help="skip strict exposition parsing")
    ap.add_argument("--timeout", type=float, default=5.0)
    args = ap.parse_args()

    path = "/flight" if args.flight else "/metrics"
    try:
        text = fetch(args.address, path, args.timeout)
    except (urllib.error.URLError, OSError) as e:
        print(f"error: cannot scrape http://{args.address}{path}: {e}",
              file=sys.stderr)
        return 2

    if args.flight:
        print(text, end="" if text.endswith("\n") else "\n")
        return 0

    families = None
    if args.json or not args.no_validate:
        from dragonboat_tpu.telemetry import parse_exposition

        try:
            families = parse_exposition(text)
        except ValueError as e:
            print(f"error: exposition failed strict parsing: {e}",
                  file=sys.stderr)
            return 1

    if args.json:
        flat = {}
        for fam in sorted(families):
            for sname, labels, value in families[fam]["samples"]:
                key = sname
                if labels:
                    key += "{" + ",".join(
                        f"{k}={labels[k]}" for k in sorted(labels)) + "}"
                flat[key] = value
        print(json.dumps(flat, indent=2, sort_keys=True))
    else:
        print(text, end="" if text.endswith("\n") else "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
