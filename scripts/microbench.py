"""Host-path microbenchmarks — parity with the reference's in-tree suite
(benchmark_test.go: marshaling :244, SaveRaftState 16/128/1024B :361,
fsync latency :276, RSM step with/without sessions :618, transport echo
:508, chunk writer :649; run via `make benchmark`).

Usage: python scripts/microbench.py [quick]
Prints one JSON line per benchmark: {"bench", "value", "unit"}.
"""

import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def out(bench: str, value: float, unit: str, **extra) -> None:
    print(json.dumps({"bench": bench, "value": round(value, 1),
                      "unit": unit, **extra}), flush=True)


def timeit(fn, n: int, min_s: float = 0.5):
    fn()  # warmup
    reps = 0
    t0 = time.perf_counter()
    while True:
        fn()
        reps += 1
        dt = time.perf_counter() - t0
        if dt >= min_s:
            return reps * n / dt


def bench_marshaling(quick):
    from dragonboat_tpu import raftpb as pb

    msgs = tuple(
        pb.Message(type=pb.MessageType.REPLICATE, from_=1, to=2, shard_id=7,
                   term=3, log_term=3, log_index=i, commit=i,
                   entries=(pb.Entry(term=3, index=i + 1, cmd=b"k" * 16),))
        for i in range(64)
    )
    batch = pb.MessageBatch(requests=msgs, deployment_id=1,
                            source_address="bench-host-1")
    enc = pb.encode_message_batch(batch)
    min_s = 0.2 if quick else 0.5
    out("marshal MessageBatch (64 msgs, 16B)",
        timeit(lambda: pb.encode_message_batch(batch), 64, min_s), "msgs/s")
    out("unmarshal MessageBatch (64 msgs, 16B)",
        timeit(lambda: pb.decode_message_batch(enc), 64, min_s), "msgs/s")


def bench_save_raft_state(quick):
    from dragonboat_tpu import raftpb as pb
    from dragonboat_tpu.logdb.tan import TanLogDB

    for size in (16, 128, 1024):
        with tempfile.TemporaryDirectory() as d:
            db = TanLogDB(d)
            i = [0]

            def one():
                base = i[0] * 48
                ud = pb.Update(
                    shard_id=1, replica_id=1,
                    state=pb.State(term=1, vote=1, commit=base),
                    entries_to_save=tuple(
                        pb.Entry(term=1, index=base + j + 1, cmd=b"x" * size)
                        for j in range(48)),
                )
                db.save_raft_state([ud], 0)  # batch of 48 + ONE fsync
                i[0] += 1

            out(f"SaveRaftState {size}B x48/batch (tan, fsync)",
                timeit(one, 48, 0.3 if quick else 1.0), "entries/s")
            db.close()


def bench_fsync(quick):
    with tempfile.TemporaryDirectory() as d:
        f = open(os.path.join(d, "probe"), "ab")

        def one():
            f.write(b"x" * 512)
            f.flush()
            os.fsync(f.fileno())

        n = 50 if quick else 200
        t0 = time.perf_counter()
        for _ in range(n):
            one()
        out("fsync latency (512B append)",
            (time.perf_counter() - t0) / n * 1e6, "us")
        f.close()


def bench_rsm_step(quick):
    from dragonboat_tpu import raftpb as pb
    from dragonboat_tpu.rsm.statemachine import StateMachine

    class KV:
        def __init__(self):
            self.d = {}

        def update(self, e):
            from dragonboat_tpu.statemachine import Result

            k, v = e.cmd.split(b"=", 1)
            self.d[k] = v
            return Result(value=len(self.d))

        def lookup(self, q):
            return self.d.get(q)

        def save_snapshot(self, w, fc, done):
            pass

        def recover_from_snapshot(self, r, files, done):
            pass

        def close(self):
            pass

    from dragonboat_tpu.statemachine import IStateMachine

    IStateMachine.register(KV)

    for sessions in (False, True):
        sm = StateMachine(1, 1, KV())
        if sessions:
            # RegisterClientID entry (client.go session registration)
            sm.handle([pb.Entry(term=1, index=1, client_id=77,
                                series_id=pb.SERIES_ID_FOR_REGISTER,
                                cmd=b"")])
        i = [2]

        def one():
            base = i[0]
            ents = [
                pb.Entry(term=1, index=base + j,
                         client_id=(77 if sessions else 0),
                         series_id=((base + j) if sessions else 0),
                         # real clients acknowledge as they go; keeps the
                         # session response cache bounded
                         responded_to=((base + j - 1) if sessions else 0),
                         cmd=b"key%d=val" % (j % 97))
                for j in range(64)
            ]
            sm.handle(ents)
            i[0] += 64

        label = "with sessions" if sessions else "no-op session"
        out(f"RSM step 64/batch ({label})",
            timeit(one, 64, 0.2 if quick else 0.5), "entries/s")


def bench_transport_echo(quick):
    from dragonboat_tpu import raftpb as pb
    from dragonboat_tpu.transport.chan import ChanTransport

    got = [0]

    def handler(batch):
        got[0] += len(batch.requests)

    t1 = ChanTransport("echo-a", handler, lambda c: True)
    t2 = ChanTransport("echo-b", handler, lambda c: True)
    t1.start()
    t2.start()
    conn = t1.get_connection("echo-b")
    batch = pb.MessageBatch(
        requests=tuple(
            pb.Message(type=pb.MessageType.HEARTBEAT, from_=1, to=2,
                       shard_id=1, term=1) for _ in range(64)),
        deployment_id=0, source_address="echo-a")
    out("chan transport send (64-msg batch)",
        timeit(lambda: conn.send_message_batch(batch), 64,
               0.2 if quick else 0.5), "msgs/s")
    t1.close()
    t2.close()


def bench_chunk_writer(quick):
    from dragonboat_tpu.rsm.chunkwriter import ChunkWriter

    sink = []

    def one():
        sink.clear()
        cw = ChunkWriter(sink.append, shard_id=1, to_replica=2, from_=1,
                         deployment_id=0, chunk_size=256 * 1024)
        from dragonboat_tpu import raftpb as pb

        cw.message = pb.Message(type=pb.MessageType.INSTALL_SNAPSHOT,
                                from_=1, to=2, shard_id=1)
        block = b"z" * 65536
        for _ in range(16):  # 1 MiB image
            cw.write(block)
        cw.close()

    out("ChunkWriter stream (1MiB image)",
        timeit(one, 1 << 20, 0.3 if quick else 1.0), "bytes/s")


def bench_native_scan(quick):
    import struct
    import zlib

    from dragonboat_tpu import native
    from dragonboat_tpu.logdb.tan import MAGIC

    payload = b"p" * 200
    frame = struct.pack("<III", MAGIC, len(payload),
                        zlib.crc32(payload)) + payload
    buf = frame * 5000  # ~1MB log image

    min_s = 0.2 if quick else 0.5
    label = "C" if native.available() else "no-native: py"
    out(f"tan replay scan ({label})",
        timeit(lambda: native.tan_scan(buf, MAGIC), len(buf), min_s),
        "bytes/s")
    out("tan replay scan (py reference)",
        timeit(lambda: native._tan_scan_py(buf, MAGIC), len(buf), min_s),
        "bytes/s")


if __name__ == "__main__":
    quick = len(sys.argv) > 1 and sys.argv[1] == "quick"
    bench_marshaling(quick)
    bench_save_raft_state(quick)
    bench_fsync(quick)
    bench_rsm_step(quick)
    bench_transport_echo(quick)
    bench_chunk_writer(quick)
    bench_native_scan(quick)
