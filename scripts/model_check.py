#!/usr/bin/env python3
"""Small-scope exhaustive model checker for the vectorized Raft kernel.

The transition relation is the REAL jitted ``core/kernel.py`` step — not
a re-implementation — driven over an exhaustively enumerated small
scope: one group of 3 replicas, a <= ``MAX_LOG``-entry log, bounded
exploration depth, all interleavings of message delivery / drop /
duplication, and at most one network partition (isolate + heal) per
path.  Exploration is a deterministic BFS with state-hash dedup; every
explored state is checked against

* the five classical Raft safety properties —

  - ``election_safety``     at most one leader per term
  - ``leader_append_only``  a stable leader never rewrites its own log
  - ``log_matching``        same (index, term) => identical prefixes
  - ``leader_completeness`` a leader holds every committed entry
  - ``state_machine_safety``no two replicas disagree below their commits

* every declared ``core/kstate.py INVARIANTS`` row, evaluated through
  the same pure-python oracle (``core/invariants.eval_row``) the runtime
  probe's differential tests cite.

Because cold-start election takes many timer ticks, exploration seeds
from a deterministically scripted happy-path prefix (full delivery, all
messages): the initial state, mid-election, leader-just-elected, and
entries-in-flight/committed states — then turns full nondeterminism
loose from each seed.

Mutation testing: ``MUTATIONS`` maps seeded protocol bugs (skip vote
persistence, commit without quorum, truncate a committed suffix, grant
double votes) to exact source edits of ``kernel.py``; ``--mutation``
re-runs the scope against the mutated kernel and must catch each.

CLI:
    python scripts/model_check.py [--scope fast|deep] [--json]
                                  [--mutation NAME | --all-mutations]

Exit status: 0 = scope explored, zero violations (or, with a mutation,
the mutation WAS caught); 1 = violations on the unmutated kernel or a
mutation that escaped.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import sys
import types
from collections import deque
from dataclasses import dataclass, field

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

from dragonboat_tpu import raftpb as pb  # noqa: E402
from dragonboat_tpu.core import invariants as inv_mod  # noqa: E402
from dragonboat_tpu.core import params as KP  # noqa: E402
from dragonboat_tpu.core.kstate import (  # noqa: E402
    Inbox,
    ShardState,
    StepInput,
    empty_input,
    init_state,
)

MT = pb.MessageType

#: replicas in the model (one raft group; kernel rows 0..2 = rids 1..3)
N_REP = 3
#: log-length bound: proposals stop once the leader's last reaches this
MAX_LOG = 4
#: in-flight network bound; routing past it drops (counted, reported)
NET_CAP = 12

#: kernel geometry for the scope (log_cap covers MAX_LOG with headroom
#: and keeps ring wrap out of scope; one compile for the whole run)
KP_SCOPE = dict(num_peers=N_REP, log_cap=8, inbox_cap=4, msg_entries=4,
                proposal_cap=1, readindex_cap=4)
ELECTION_TIMEOUT = 3
HEARTBEAT_TIMEOUT = 1

SCOPES = {
    # depth = BFS radius around each seed; max_states = exploration
    # budget (dedup'd); fast must stay tier-1-cheap (it is also cached
    # by kernel-source hash in analysis/safety.py)
    "fast": dict(depth=3, max_states=600),
    "deep": dict(depth=5, max_states=20000),
    # quiesced=True seeds with a banked election clock: the natural
    # entry path needs e_timeout*10 idle ticks — unreachable at these
    # depths — so the scope seeds the mask directly and checks the
    # quiesced_no_campaign / quiesced_no_vote invariants
    "quiesce": dict(depth=3, max_states=600, quiesce=True),
}

KERNEL_FILE = os.path.join("dragonboat_tpu", "core", "kernel.py")

#: seeded protocol bugs: name -> (find, replace) exact source edits.
#: Each must be caught by at least one verifier leg (model checker /
#: runtime probe / static safety pass) — asserted by the test suite.
MUTATIONS = {
    # granting a vote without persisting who it went to: a second
    # candidate of the same term can then also be granted
    "skip_vote_persist": (
        "    s = mrep(s, grant, vote=m.from_, e_tick=0)\n",
        "    s = mrep(s, grant, e_tick=0)\n",
    ),
    # advancing the commit index to the leader's own last entry without
    # consulting the quorum match book
    "commit_without_quorum": (
        "    ok = (q > s.committed) & (t == s.term) & (s.role == P.LEADER)\n"
        "    return mrep(s, ok, committed=q)\n",
        "    ok = (s.last > s.committed) & (s.role == P.LEADER)\n"
        "    return mrep(s, ok, committed=s.last)\n",
    ),
    # accepting a replicate that truncates below the local commit index
    "truncate_committed": (
        "    accept = h_rep & ~below_commit & prev_ok & ~over_cap\n",
        "    accept = h_rep & prev_ok & ~over_cap\n",
    ),
    # vote-once check disabled: any second candidate is also granted
    "double_vote": (
        "    can_grant = (s.vote == 0) | (s.vote == m.from_)\n",
        "    can_grant = (s.vote == 0) | (s.vote != 0)\n",
    ),
    # tick masking ignores the device-resident quiesced mask: a
    # quiesced lane with a banked election clock campaigns while its
    # mask is still raised (caught by quiesced_no_campaign under the
    # quiesce scope's seeded-mask states)
    "quiesce_campaigns": (
        "    q_any = inp.quiesced | s.quiesced\n",
        "    q_any = inp.quiesced\n",
    ),
}


def load_kernel_module(mutation: str, root: str = _ROOT):
    """A throwaway copy of ``core.kernel`` with one seeded bug applied
    (the real module and its jit cache are untouched).  Exposes the
    full module so callers can also reach ``step_donated`` — the chaos
    mutation test drives a live engine through the mutated kernel."""
    find, replace = MUTATIONS[mutation]
    path = os.path.join(root, KERNEL_FILE)
    with open(path, encoding="utf-8") as f:
        src = f.read()
    if find not in src:
        raise RuntimeError(
            f"mutation {mutation!r}: target snippet not found in "
            f"{KERNEL_FILE} — update MUTATIONS to match the kernel source")
    src = src.replace(find, replace)
    mod = types.ModuleType(f"dragonboat_tpu.core.kernel__mut_{mutation}")
    mod.__file__ = path + f"<mutated:{mutation}>"
    exec(compile(src, mod.__file__, "exec"), mod.__dict__)
    return mod


def load_kernel_step(mutation: str | None = None, root: str = _ROOT):
    """The kernel's jitted ``step``, optionally with one seeded bug."""
    if mutation is None:
        from dragonboat_tpu.core.kernel import step

        return step
    return load_kernel_module(mutation, root).step


# ---------------------------------------------------------------------------
# model state: kernel arrays + in-flight network + partition ghost
# ---------------------------------------------------------------------------

# message tuple layout (hashable, canonical):
# (mtype, frm, to, term, log_term, log_index, commit, reject, hint,
#  hint_high, ents) with ents = ((term, is_cc), ...)


@dataclass
class Node:
    """One explored model state (ghost fields ride outside the hash)."""

    arrs: dict                      # ShardState field -> np array [3,...]
    net: tuple                      # sorted tuple of in-flight messages
    isolated: int                   # row cut off by the partition, or -1
    part_used: bool                 # the <=1 partition event is spent
    depth: int
    leaders: dict = field(default_factory=dict)   # ghost: term -> rid
    trail: tuple = ()               # action names from the seed


def _state_arrays(state: ShardState) -> dict:
    import jax

    host = jax.device_get(state)
    return {f: np.asarray(v) for f, v in zip(ShardState._fields, host)
            if v is not None}


def state_key(n: Node) -> bytes:
    h = hashlib.blake2b(digest_size=16)
    for f in ShardState._fields:
        a = n.arrs.get(f)
        if a is not None:
            h.update(np.ascontiguousarray(a).tobytes())
    h.update(repr((n.net, n.isolated, n.part_used)).encode())
    return h.digest()


def _log_term(arrs: dict, row: int, idx: int, cap: int) -> int:
    return int(arrs["lt"][row, idx & (cap - 1)])


# ---------------------------------------------------------------------------
# routing: StepOutput lanes -> message tuples (harness-parity transport)
# ---------------------------------------------------------------------------


def collect_messages(out, kp) -> list:
    """All outbound messages of one step over the 3-row group, as
    message tuples (from the same lanes tests/kernel_harness.py routes)."""
    o = {k: (np.asarray(v) if v is not None else None)
         for k, v in out._asdict().items()}
    msgs: list = []
    K, Pn, E = kp.inbox_cap, kp.num_peers, kp.msg_entries
    for g in range(N_REP):
        my = g + 1
        for k in range(K):
            t = int(o["r_type"][g, k])
            if t:
                msgs.append((t, my, int(o["r_to"][g, k]),
                             int(o["r_term"][g, k]), 0,
                             int(o["r_log_index"][g, k]), 0,
                             int(bool(o["r_reject"][g, k])),
                             int(o["r_hint"][g, k]),
                             int(o["r_hint_high"][g, k]), ()))
        for p in range(Pn):
            to = p + 1
            if bool(o["s_rep"][g, p]):
                n = int(o["s_n_ent"][g, p])
                ents = tuple(
                    (int(o["s_ent_term"][g, p, e]),
                     int(bool(o["s_ent_cc"][g, p, e]))) for e in range(n))
                msgs.append((int(MT.REPLICATE), my, to, int(o["term"][g]),
                             int(o["s_prev_term"][g, p]),
                             int(o["s_prev_index"][g, p]),
                             int(o["s_commit"][g, p]), 0, 0, 0, ents))
            if bool(o["s_hb"][g, p]):
                msgs.append((int(MT.HEARTBEAT), my, to, int(o["term"][g]),
                             0, 0, int(o["s_hb_commit"][g, p]), 0,
                             int(o["s_hb_low"][g, p]),
                             int(o["s_hb_high"][g, p]), ()))
            v = int(o["s_vote"][g, p])
            if v:
                mt = MT.REQUEST_VOTE if v == 1 else MT.REQUEST_PREVOTE
                msgs.append((int(mt), my, to, int(o["s_vote_term"][g, p]),
                             int(o["s_vote_lterm"][g, p]),
                             int(o["s_vote_lindex"][g, p]), 0, 0,
                             int(o["s_vote_hint"][g, p]), 0, ()))
            if bool(o["s_timeout_now"][g, p]):
                msgs.append((int(MT.TIMEOUT_NOW), my, to,
                             int(o["term"][g]), 0, 0, 0, 0, 0, 0, ()))
    return [m for m in msgs if 1 <= m[2] <= N_REP and m[2] != m[1]]


def build_inbox(kp, deliveries: dict) -> Inbox:
    """Inbox arrays with ``deliveries[row] = [msg, ...]`` placed in the
    leading slots (others empty)."""
    K, E = kp.inbox_cap, kp.msg_entries
    z = lambda *s: np.zeros((N_REP, *s), np.int32)  # noqa: E731
    box = dict(mtype=z(K), from_=z(K), term=z(K), log_term=z(K),
               log_index=z(K), commit=z(K),
               reject=np.zeros((N_REP, K), bool), hint=z(K),
               hint_high=z(K), n_ent=z(K), ent_term=z(K, E),
               ent_cc=np.zeros((N_REP, K, E), bool))
    for row, ms in deliveries.items():
        for k, m in enumerate(ms[:K]):
            (box["mtype"][row, k], box["from_"][row, k], _,
             box["term"][row, k], box["log_term"][row, k],
             box["log_index"][row, k], box["commit"][row, k],
             box["reject"][row, k], box["hint"][row, k],
             box["hint_high"][row, k]) = m[:10]
            ents = m[10][:E]
            box["n_ent"][row, k] = len(ents)
            for e, (t, cc) in enumerate(ents):
                box["ent_term"][row, k, e] = t
                box["ent_cc"][row, k, e] = cc
    if "ent_val" in Inbox._fields:
        box["ent_val"] = None
    return Inbox(**box)


# ---------------------------------------------------------------------------
# the checker
# ---------------------------------------------------------------------------


class ModelChecker:
    def __init__(self, mutation: str | None = None, scope: str = "fast",
                 root: str = _ROOT):
        self.kp = KP.KernelParams(**KP_SCOPE)
        self.step_fn = load_kernel_step(mutation, root)
        self.scope = dict(SCOPES[scope])
        self.scope_name = scope
        self.mutation = mutation
        self.violations: list[dict] = []
        self.states_explored = 0
        self.transitions = 0
        self.net_overflow = 0
        self.frontier_exhausted = False
        self.scope_complete = False
        self._seen: set[bytes] = set()

    # -- kernel driving --------------------------------------------------
    def _step(self, arrs: dict, deliveries: dict, tick_rows=(),
              propose_row: int | None = None):
        kp = self.kp
        inp = empty_input(kp, N_REP)
        d = {k: (np.asarray(v).copy() if v is not None else None)
             for k, v in inp._asdict().items()}
        for r in tick_rows:
            d["tick"][r] = True
        if propose_row is not None:
            d["prop_valid"][propose_row, 0] = True
        d["applied"] = np.asarray(arrs["processed"])
        state = ShardState(**{f: arrs.get(f)
                              for f in ShardState._fields})
        new_state, out = self.step_fn(kp, state, build_inbox(kp, deliveries),
                                      StepInput(**d))
        self.transitions += 1
        return _state_arrays(new_state), collect_messages(out, kp)

    def _route(self, node: Node, new_msgs: list) -> tuple:
        net = list(node.net)
        for m in new_msgs:
            if node.isolated >= 0 and (m[1] - 1 == node.isolated
                                       or m[2] - 1 == node.isolated):
                continue       # partition eats traffic crossing the cut
            if len(net) >= NET_CAP:
                self.net_overflow += 1
                continue
            net.append(m)
        return tuple(sorted(net))

    # -- safety properties ----------------------------------------------
    def _violate(self, prop: str, node: Node, detail: str) -> None:
        self.violations.append(dict(
            property=prop, detail=detail, depth=node.depth,
            trail=list(node.trail)[-10:], mutation=self.mutation))

    def check_node(self, node: Node, prev: Node | None,
                   action: str) -> None:
        a = node.arrs
        cap = self.kp.log_cap
        roles = [int(a["role"][r]) for r in range(N_REP)]
        terms = [int(a["term"][r]) for r in range(N_REP)]
        lasts = [int(a["last"][r]) for r in range(N_REP)]
        commits = [int(a["committed"][r]) for r in range(N_REP)]
        leaders = [r for r in range(N_REP) if roles[r] == KP.LEADER]

        # election safety: per-state coexistence + per-path history
        for i, r in enumerate(leaders):
            for q in leaders[i + 1:]:
                if terms[r] == terms[q]:
                    self._violate(
                        "election_safety", node,
                        f"rows {r} and {q} both lead term {terms[r]}")
        for r in leaders:
            prior = node.leaders.get(terms[r])
            if prior is not None and prior != r + 1:
                self._violate(
                    "election_safety", node,
                    f"term {terms[r]} led by rid {prior} earlier on this "
                    f"path, now by rid {r + 1}")
            node.leaders[terms[r]] = r + 1

        # leader append-only (edge property over one kernel step)
        if prev is not None:
            pa = prev.arrs
            for r in range(N_REP):
                if (int(pa["role"][r]) == KP.LEADER
                        and roles[r] == KP.LEADER
                        and int(pa["term"][r]) == terms[r]):
                    old_last = int(pa["last"][r])
                    if lasts[r] < old_last:
                        self._violate(
                            "leader_append_only", node,
                            f"leader row {r} shrank last "
                            f"{old_last}->{lasts[r]} ({action})")
                    for i in range(1, old_last + 1):
                        if _log_term(pa, r, i, cap) != _log_term(a, r, i,
                                                                 cap):
                            self._violate(
                                "leader_append_only", node,
                                f"leader row {r} rewrote entry {i} "
                                f"({action})")
                            break

        # log matching: equal terms at an index => equal prefixes
        for r in range(N_REP):
            for q in range(r + 1, N_REP):
                hi = min(lasts[r], lasts[q])
                for i in range(hi, 0, -1):
                    if _log_term(a, r, i, cap) == _log_term(a, q, i, cap):
                        for j in range(1, i):
                            if _log_term(a, r, j, cap) != _log_term(
                                    a, q, j, cap):
                                self._violate(
                                    "log_matching", node,
                                    f"rows {r}/{q} agree at index {i} "
                                    f"(term {_log_term(a, r, i, cap)}) but "
                                    f"diverge at {j}")
                                break
                        break

        # leader completeness: every committed entry is on the leader
        for ldr in leaders:
            for r in range(N_REP):
                if commits[r] > lasts[ldr]:
                    self._violate(
                        "leader_completeness", node,
                        f"row {r} committed through {commits[r]} but "
                        f"leader row {ldr} only has {lasts[ldr]} entries")
                    continue
                for i in range(1, commits[r] + 1):
                    if _log_term(a, r, i, cap) != _log_term(a, ldr, i, cap):
                        self._violate(
                            "leader_completeness", node,
                            f"committed entry {i} of row {r} (term "
                            f"{_log_term(a, r, i, cap)}) missing from "
                            f"leader row {ldr}")
                        break

        # state-machine safety: agreement below both commit indices
        for r in range(N_REP):
            for q in range(r + 1, N_REP):
                for i in range(1, min(commits[r], commits[q]) + 1):
                    if _log_term(a, r, i, cap) != _log_term(a, q, i, cap):
                        self._violate(
                            "state_machine_safety", node,
                            f"rows {r}/{q} disagree on committed entry {i}")
                        break

        # declared INVARIANTS via the runtime probe's python oracle
        inv_fields = sorted({f for iv in inv_mod.PARSED.values()
                             for f in iv.fields})
        for r in range(N_REP):
            cur = {"kind": [int(v) for v in a["kind"][r]]}
            for f in inv_fields:
                col = a[f][r] if f in a else None
                if col is None:
                    continue
                cur[f] = ([int(v) for v in col]
                          if getattr(col, "ndim", 0) else int(col))
            prow = None
            if prev is not None:
                prow = {f: int(prev.arrs[f][r])
                        for f in inv_mod._PREV_FIELDS}
            for iv in inv_mod.PARSED.values():
                if eval_violated(iv, cur, prow):
                    self._violate(
                        "invariant:" + iv.name, node,
                        f"row {r} violates {iv.name} ({action})")

    # -- successor generation --------------------------------------------
    def successors(self, node: Node):
        """Deterministically ordered (action, Node) successors."""
        out: list[tuple[str, Node]] = []
        a = node.arrs

        def kernel_succ(action, deliveries, tick_rows=(), propose=None,
                        net_minus=None, keep_net=True):
            arrs, msgs = self._step(a, deliveries, tick_rows, propose)
            net = list(node.net)
            if net_minus is not None:
                net.remove(net_minus)
            nxt = Node(arrs=arrs, net=(), isolated=node.isolated,
                       part_used=node.part_used, depth=node.depth + 1,
                       leaders=dict(node.leaders),
                       trail=node.trail + (action,))
            nxt.net = self._route(
                Node(arrs=arrs, net=tuple(net), isolated=node.isolated,
                     part_used=node.part_used, depth=0), msgs)
            out.append((action, nxt))

        # tick: timers advance on every non-isolated row
        ticks = tuple(r for r in range(N_REP) if r != node.isolated)
        kernel_succ("tick", {}, tick_rows=ticks)

        # propose one entry at any live leader below the log bound
        for r in range(N_REP):
            if (int(a["role"][r]) == KP.LEADER and r != node.isolated
                    and int(a["last"][r]) < MAX_LOG):
                kernel_succ(f"propose@{r}", {}, propose=r)

        # one message delivered / duplicated / dropped
        for m in sorted(set(node.net)):
            to_row = m[2] - 1
            if to_row == node.isolated or m[1] - 1 == node.isolated:
                continue
            label = f"{MT(m[0]).name}:{m[1]}->{m[2]}"
            kernel_succ("deliver " + label, {to_row: [m]}, net_minus=m)
            kernel_succ("dup " + label, {to_row: [m]})
            net = list(node.net)
            net.remove(m)
            out.append(("drop " + label, Node(
                arrs=a, net=tuple(sorted(net)), isolated=node.isolated,
                part_used=node.part_used, depth=node.depth + 1,
                leaders=dict(node.leaders),
                trail=node.trail + ("drop " + label,))))

        # at most one partition event per path, plus its heal
        if not node.part_used:
            for r in range(N_REP):
                out.append((f"isolate@{r}", Node(
                    arrs=a, net=node.net, isolated=r, part_used=True,
                    depth=node.depth + 1, leaders=dict(node.leaders),
                    trail=node.trail + (f"isolate@{r}",))))
        elif node.isolated >= 0:
            out.append(("heal", Node(
                arrs=a, net=node.net, isolated=-1, part_used=True,
                depth=node.depth + 1, leaders=dict(node.leaders),
                trail=node.trail + ("heal",))))
        return out

    # -- seed construction ----------------------------------------------
    def seeds(self) -> list[Node]:
        """Deterministic happy-path prefix states (full delivery)."""
        arrs = _state_arrays(init_state(
            self.kp, N_REP, np.arange(1, N_REP + 1, dtype=np.int32),
            np.arange(1, N_REP + 1, dtype=np.int32),
            election_timeout=ELECTION_TIMEOUT,
            heartbeat_timeout=HEARTBEAT_TIMEOUT))
        node = Node(arrs=arrs, net=(), isolated=-1, part_used=False,
                    depth=0, trail=("seed:init",))
        seeds = [node]
        cur, net = arrs, []

        def advance(tick, propose=None, label=""):
            nonlocal cur, net
            deliveries: dict = {}
            for m in net:
                deliveries.setdefault(m[2] - 1, []).append(m)
            cur, msgs = self._step(
                cur, deliveries, tick_rows=range(N_REP) if tick else (),
                propose_row=propose)
            net = msgs
            return Node(arrs=cur, net=tuple(sorted(net)), isolated=-1,
                        part_used=False, depth=0, trail=(label,))

        leader = None
        for i in range(60):
            n = advance(tick=True, label=f"seed:tick{i}")
            roles = [int(cur["role"][r]) for r in range(N_REP)]
            if KP.CANDIDATE in roles and len(seeds) < 2:
                seeds.append(n)                       # mid-election
            if KP.LEADER in roles:
                leader = roles.index(KP.LEADER)
                seeds.append(n)                       # leader elected
                break
        if leader is None:
            raise RuntimeError("seed phase failed to elect a leader")
        for _ in range(4):                            # settle vote traffic
            advance(tick=False, label="seed:settle")
        seeds.append(advance(tick=False, propose=leader,
                             label="seed:proposed"))  # entry in flight
        for i in range(6):
            n = advance(tick=False, label=f"seed:drain{i}")
        if int(cur["committed"][leader]) < 1:
            raise RuntimeError("seed phase failed to commit an entry")
        seeds.append(n)                               # entry committed
        seeds.append(advance(tick=False, propose=leader,
                             label="seed:proposed2"))
        if self.scope.get("quiesce"):
            return self._quiesce_seeds(seeds)
        return seeds

    def _quiesce_seeds(self, seeds: list[Node]) -> list[Node]:
        """Quiesced variants of the init and entry-committed seeds: the
        mask is raised directly (the natural e_timeout*10 idle entry is
        outside the depth bound) and the election clock is banked past
        the largest randomized timeout, so any tick-path bug that
        ignores the mask campaigns on its very first step."""
        out: list[Node] = []
        for i, base in enumerate((seeds[0], seeds[-2])):
            arrs = {f: a.copy() for f, a in base.arrs.items()}
            arrs["quiesce_on"][:] = True
            arrs["quiesced"][:] = True
            arrs["idle_tick"][:] = ELECTION_TIMEOUT * 10
            arrs["e_tick"][:] = 2 * ELECTION_TIMEOUT
            out.append(Node(
                arrs=arrs, net=base.net, isolated=-1, part_used=False,
                depth=0, leaders=dict(base.leaders),
                trail=(f"seed:quiesced{i}",)))
        return out

    # -- BFS --------------------------------------------------------------
    def run(self) -> dict:
        frontier: deque[Node] = deque()
        for s in self.seeds():
            k = state_key(s)
            if k not in self._seen:
                self._seen.add(k)
                self.check_node(s, None, s.trail[-1])
                self.states_explored += 1
                frontier.append(s)
        budget = self.scope["max_states"]
        depth_cap = self.scope["depth"]
        while frontier:
            node = frontier.popleft()
            if node.depth >= depth_cap:
                continue
            if self.states_explored >= budget:
                break
            for action, nxt in self.successors(node):
                k = state_key(nxt)
                if k in self._seen:
                    continue
                self._seen.add(k)
                self.check_node(nxt, node, action)
                self.states_explored += 1
                frontier.append(nxt)
                if self.states_explored >= budget:
                    break
        self.frontier_exhausted = not frontier
        # the configured scope (depth radius x state budget) was fully
        # explored — either the frontier drained or the budget bound hit
        self.scope_complete = (self.frontier_exhausted
                               or self.states_explored >= budget)
        return self.result()

    def result(self) -> dict:
        return dict(
            scope=self.scope_name, mutation=self.mutation,
            states_explored=self.states_explored,
            transitions=self.transitions,
            net_overflow=self.net_overflow,
            frontier_exhausted=self.frontier_exhausted,
            scope_complete=self.scope_complete,
            violations=self.violations,
            properties=["election_safety", "leader_append_only",
                        "log_matching", "leader_completeness",
                        "state_machine_safety"]
            + ["invariant:" + n for n in inv_mod.INVARIANT_NAMES],
        )


def eval_violated(iv, cur, prev) -> bool:
    return inv_mod.eval_row(iv, cur, prev)


def run_scope(scope: str = "fast", mutation: str | None = None,
              root: str = _ROOT) -> dict:
    return ModelChecker(mutation=mutation, scope=scope, root=root).run()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--scope", choices=sorted(SCOPES), default="fast")
    ap.add_argument("--mutation", choices=sorted(MUTATIONS))
    ap.add_argument("--all-mutations", action="store_true")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)

    muts = sorted(MUTATIONS) if args.all_mutations else [args.mutation]
    ok = True
    reports = []
    for mut in muts:
        res = run_scope(args.scope, mut)
        reports.append(res)
        caught = bool(res["violations"])
        if mut is None:
            ok &= not caught
            verdict = ("CLEAN" if not caught
                       else f"{len(res['violations'])} VIOLATIONS")
        else:
            ok &= caught
            verdict = "caught" if caught else "ESCAPED"
        if not args.json:
            print(f"[model-check] scope={res['scope']} "
                  f"mutation={mut or '-'} states={res['states_explored']} "
                  f"transitions={res['transitions']} "
                  f"exhausted={res['frontier_exhausted']} -> {verdict}")
            for v in res["violations"][:5]:
                print(f"  {v['property']}: {v['detail']}")
    if args.json:
        print(json.dumps(reports if args.all_mutations else reports[0],
                         indent=2, sort_keys=True))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
