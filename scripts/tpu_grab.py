#!/usr/bin/env python
"""Opportunistic TPU measurement: probe the (flaky) device tunnel in a
subprocess; when it is alive, IMMEDIATELY measure kernel step time at an
ascending group ladder, appending one JSON line per config to
PERF_TPU.jsonl — so a revived tunnel is never wasted on a compile that
outlives it.  Small shapes first: every completed rung is a recorded
datapoint even if the tunnel dies mid-ladder.

Round-5 ladder hardening (VERDICT r4 item 1 — the 4096 rung died as an
undiagnosed "rung timeout"):
 - every rung emits staged PROG lines (built / elected / compiled), so a
   timeout records WHERE it died instead of nothing;
 - a timed-out rung is retried once with a doubled budget — the
   persistent jax compile cache means the retry skips the 10-minute
   compile the first attempt paid for, so a mid-rung wedge can no longer
   zero a long compile;
 - the per-rung budget scales with G (compile time grows super-linearly
   at big shapes).

The per-rung A/B now measures the question that matters: the
`onehot_reads` rewrite (gathers 155→36) against the dynamic-index form,
on the hardware the lever was built for.  TPU_GRAB_VARIANT overrides
(e.g. unroll_scans).

Usage: python scripts/tpu_grab.py [--ladder 256,1024,4096,8192]
"""

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

OUT = os.path.join(REPO, "PERF_TPU.jsonl")

RUNG = r"""
import os, sys, time, json
sys.path.insert(0, {repo!r})
import jax
from dragonboat_tpu.hostenv import jax_cache_dir
jax.config.update("jax_compilation_cache_dir", jax_cache_dir())
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
plat = jax.devices()[0].platform
import dataclasses
from dragonboat_tpu.bench_loop import bench_params, make_cluster, run_steps, elect_all

def prog(stage, **kw):
    print("PROG " + json.dumps(dict(stage=stage, t=round(time.time(), 1),
                                    **kw)), flush=True)

def measure(kp, tag):
    t0 = time.time()
    state, box = elect_all(kp, 3, make_cluster(kp, G, 3))
    jax.block_until_ready(state.term)
    setup_s = time.time() - t0
    prog("elected", tag=tag, setup_s=round(setup_s, 1))
    t0 = time.time()
    state, box = run_steps(kp, 3, 4, True, True, state, box)
    jax.block_until_ready(state.term)
    compile_s = time.time() - t0
    prog("compiled", tag=tag, compile_s=round(compile_s, 1))
    t0 = time.time()
    state, box = run_steps(kp, 3, N, True, True, state, box)
    jax.block_until_ready(state.term)
    dt = time.time() - t0
    return setup_s, compile_s, dt

G = {g}
N = {steps}
kp = bench_params(3)
prog("start", groups=G, onehot=bool(kp.onehot_reads), platform=plat)
setup_s, compile_s, dt = measure(kp, "plain")
wps = G * 28 / (dt / N)   # 28 committed writes per group-step (bench width)
rec = {{
    "ts": time.time(), "platform": plat, "groups": G,
    "onehot_reads": bool(kp.onehot_reads),
    "setup_s": round(setup_s, 1), "compile_s": round(compile_s, 1),
    "step_ms": round(dt / N * 1000, 3), "writes_per_s": int(wps),
}}
# bank the plain measurement NOW: the variant costs a second compile,
# and a wedge/timeout there must not lose the rung (the harvester takes
# the LAST RUNG line)
print("RUNG " + json.dumps(rec), flush=True)
# Second measurement per rung: A/B the onehot_reads rewrite (the round's
# open question — gathers 155->36) unless TPU_GRAB_VARIANT names another
# static flag to flip.
variant = os.environ.get("TPU_GRAB_VARIANT", "onehot_reads")
try:
    cur = getattr(kp, variant)
    kpm = dataclasses.replace(kp, **{{variant: not cur}})
    vtag = "%s=%s" % (variant, not cur)
    _, _, dtv = measure(kpm, vtag)
    rec[vtag + "_step_ms"] = round(dtv / N * 1000, 3)
except Exception as e:   # the plain rung must survive a variant failure
    rec[variant + "_error"] = str(e)[-200:]
print("RUNG " + json.dumps(rec))
"""


def probe(timeout: float = 60.0) -> bool:
    if os.environ.get("TPU_GRAB_FORCE_CPU") == "1":
        return True   # rung self-test: run the ladder on CPU
    try:
        r = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.default_backend())"],
            capture_output=True, text=True, timeout=timeout)
        return r.returncode == 0 and "tpu" in r.stdout
    except subprocess.TimeoutExpired:
        return False


def _last(out: str, prefix: str):
    """Last parseable line with the given prefix (a kill mid-write
    truncates the tail)."""
    rec = None
    for ln in out.splitlines():
        if ln.startswith(prefix):
            try:
                rec = json.loads(ln[len(prefix):])
            except ValueError:
                pass
    return rec


def _run_rung(code: str, env: dict, timeout: float):
    """One rung attempt.  Returns (rec_or_None, last_prog, timed_out)."""
    try:
        r = subprocess.run([sys.executable, "-c", code], env=env,
                           capture_output=True, text=True, timeout=timeout)
        out = r.stdout or ""
        err = r.stderr or ""
        timed_out = False
    except subprocess.TimeoutExpired as e:
        # salvage banked lines from the partial output
        out = (e.stdout or b"")
        out = out.decode(errors="replace") if isinstance(out, bytes) else out
        err = "rung timeout"
        timed_out = True
    rec = _last(out, "RUNG ")
    prog = _last(out, "PROG ")
    if rec is None and not timed_out:
        rec_err = {"error": (err or "no output")[-500:]}
        if prog:
            rec_err["last_stage"] = prog
        return rec_err, prog, False
    return rec, prog, timed_out


def main() -> None:
    ladder = [int(x) for x in (
        sys.argv[sys.argv.index("--ladder") + 1].split(",")
        if "--ladder" in sys.argv else ["256", "1024", "4096", "8192"])]
    if not probe():
        print(json.dumps({"ts": time.time(), "probe": "wedged"}))
        return
    print("tunnel alive; measuring", flush=True)
    for g in ladder:
        steps = max(20, min(100, 200_000 // g))
        code = RUNG.format(repo=REPO, g=g, steps=steps)
        env = dict(os.environ)
        if os.environ.get("TPU_GRAB_FORCE_CPU") == "1":
            env.update(PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
        # compile-aware budget: compile grows super-linearly with G over
        # the tunnel (the r4 4096 rung outlived a flat 900 s)
        budget = 900.0 if g <= 1024 else (1800.0 if g <= 4096 else 2700.0)
        rec, prog, timed_out = _run_rung(code, env, budget)
        if timed_out and rec is None:
            # the compile the first attempt paid for is in the
            # persistent cache — a retry skips straight to measurement
            note = {"ts": time.time(), "groups": g,
                    "note": "first attempt timed out; retrying on warm "
                            "cache", "last_stage": prog}
            print(json.dumps(note), flush=True)
            rec, prog, timed_out = _run_rung(code, env, budget * 2)
        if rec is None:
            rec = {"error": "rung timeout (after retry)"}
            if prog:
                rec["last_stage"] = prog
        rec.setdefault("ts", time.time())
        rec.setdefault("groups", g)
        if timed_out:
            rec["variant_timeout"] = True
        with open(OUT, "a") as f:
            f.write(json.dumps(rec) + "\n")
        print(json.dumps(rec), flush=True)
        if "error" in rec:
            break


if __name__ == "__main__":
    main()
