#!/usr/bin/env python
"""Opportunistic TPU measurement: probe the (flaky) device tunnel in a
subprocess; when it is alive, IMMEDIATELY measure kernel step time at an
ascending group ladder, appending one JSON line per config to
PERF_TPU.jsonl — so a revived tunnel is never wasted on a compile that
outlives it.  Small shapes first: every completed rung is a recorded
datapoint even if the tunnel dies mid-ladder.

Usage: python scripts/tpu_grab.py [--ladder 256,1024,4096,8192]
"""

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

OUT = os.path.join(REPO, "PERF_TPU.jsonl")

RUNG = r"""
import os, sys, time, json
sys.path.insert(0, {repo!r})
import jax
from dragonboat_tpu.hostenv import jax_cache_dir
jax.config.update("jax_compilation_cache_dir", jax_cache_dir())
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
plat = jax.devices()[0].platform
from dragonboat_tpu.bench_loop import bench_params, make_cluster, run_steps, elect_all
G = {g}
kp = bench_params(3)
t0 = time.time()
state, box = elect_all(kp, 3, make_cluster(kp, G, 3))
jax.block_until_ready(state.term)
setup_s = time.time() - t0
t0 = time.time()
state, box = run_steps(kp, 3, 4, True, True, state, box)
jax.block_until_ready(state.term)
compile_s = time.time() - t0
t0 = time.time()
N = {steps}
state, box = run_steps(kp, 3, N, True, True, state, box)
jax.block_until_ready(state.term)
dt = time.time() - t0
wps = {g} * 28 / (dt / N)   # 28 committed writes per group-step (bench width)
rec = {{
    "ts": time.time(), "platform": plat, "groups": G,
    "setup_s": round(setup_s, 1), "compile_s": round(compile_s, 1),
    "step_ms": round(dt / N * 1000, 3), "writes_per_s": int(wps),
}}
# Second measurement per rung: A/B one variant against the plain kernel.
# Default is unroll_scans (lax.scan unroll — bitwise-neutral scheduling,
# kills the per-iteration serial launches of the family scans);
# TPU_GRAB_MERGED=1 measures the old merge_inbox_families restructure
# instead (44% slower on TPU at r4, kept for re-checks).
variant = ("merge_inbox_families" if os.environ.get("TPU_GRAB_MERGED") == "1"
           else "unroll_scans")
# bank the plain measurement NOW: the variant costs a second compile,
# and a wedge/timeout there must not lose the rung (the harvester takes
# the LAST RUNG line)
print("RUNG " + json.dumps(rec), flush=True)
try:
    import dataclasses
    kpm = dataclasses.replace(kp, **{{variant: True}})
    state2, box2 = elect_all(kpm, 3, make_cluster(kpm, G, 3))
    state2, box2 = run_steps(kpm, 3, 4, True, True, state2, box2)
    jax.block_until_ready(state2.term)
    t0 = time.time()
    state2, box2 = run_steps(kpm, 3, N, True, True, state2, box2)
    jax.block_until_ready(state2.term)
    rec[variant + "_step_ms"] = round((time.time() - t0) / N * 1000, 3)
except Exception as e:   # the plain rung must survive a variant failure
    rec[variant + "_error"] = str(e)[-200:]
print("RUNG " + json.dumps(rec))
"""


def probe(timeout: float = 60.0) -> bool:
    if os.environ.get("TPU_GRAB_FORCE_CPU") == "1":
        return True   # rung self-test: run the ladder on CPU
    try:
        r = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.default_backend())"],
            capture_output=True, text=True, timeout=timeout)
        return r.returncode == 0 and "tpu" in r.stdout
    except subprocess.TimeoutExpired:
        return False


def main() -> None:
    ladder = [int(x) for x in (
        sys.argv[sys.argv.index("--ladder") + 1].split(",")
        if "--ladder" in sys.argv else ["256", "1024", "4096", "8192"])]
    if not probe():
        print(json.dumps({"ts": time.time(), "probe": "wedged"}))
        return
    print("tunnel alive; measuring", flush=True)
    for g in ladder:
        steps = max(20, min(100, 200_000 // g))
        code = RUNG.format(repo=REPO, g=g, steps=steps)
        env = dict(os.environ)
        if os.environ.get("TPU_GRAB_FORCE_CPU") == "1":
            env.update(PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
        # generous per-rung timeout: compile at new shapes is slow over
        # the tunnel, but a wedge must not eat the whole session
        try:
            r = subprocess.run([sys.executable, "-c", code], env=env,
                               capture_output=True, text=True, timeout=900)
            out = r.stdout or ""
            err = r.stderr or ""
        except subprocess.TimeoutExpired as e:
            # salvage a banked plain measurement from the partial output
            out = (e.stdout or b"")
            out = out.decode(errors="replace") if isinstance(out, bytes) else out
            err = "rung timeout"
            r = None
        rec_parsed = None
        for ln in out.splitlines():   # last PARSEABLE RUNG line wins (a
            if ln.startswith("RUNG "):  # kill mid-write truncates the tail)
                try:
                    rec_parsed = json.loads(ln[5:])
                except ValueError:
                    pass
        if rec_parsed is None:
            rec = {"ts": time.time(), "groups": g,
                   "error": (err or "no output")[-500:]}
        else:
            rec = rec_parsed
            if r is None:   # plain banked, variant lost to the timeout
                rec["variant_timeout"] = True
        with open(OUT, "a") as f:
            f.write(json.dumps(rec) + "\n")
        print(json.dumps(rec), flush=True)
        if "error" in rec:
            break


if __name__ == "__main__":
    main()
