#!/usr/bin/env python
"""Opportunistic TPU measurement: probe the (flaky) device tunnel in a
subprocess; when it is alive, IMMEDIATELY measure kernel step time at an
ascending group ladder, appending one JSON line per config to
PERF_TPU.jsonl — so a revived tunnel is never wasted on a compile that
outlives it.  Small shapes first: every completed rung is a recorded
datapoint even if the tunnel dies mid-ladder.

Usage: python scripts/tpu_grab.py [--ladder 256,1024,4096,8192]
"""

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

OUT = os.path.join(REPO, "PERF_TPU.jsonl")

RUNG = r"""
import os, sys, time, json
sys.path.insert(0, {repo!r})
import jax
from dragonboat_tpu.hostenv import jax_cache_dir
jax.config.update("jax_compilation_cache_dir", jax_cache_dir())
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
plat = jax.devices()[0].platform
from dragonboat_tpu.bench_loop import bench_params, make_cluster, run_steps, elect_all
G = {g}
kp = bench_params(3)
t0 = time.time()
state, box = elect_all(kp, 3, make_cluster(kp, G, 3))
jax.block_until_ready(state.term)
setup_s = time.time() - t0
t0 = time.time()
state, box = run_steps(kp, 3, 4, True, True, state, box)
jax.block_until_ready(state.term)
compile_s = time.time() - t0
t0 = time.time()
N = {steps}
state, box = run_steps(kp, 3, N, True, True, state, box)
jax.block_until_ready(state.term)
dt = time.time() - t0
wps = {g} * 28 / (dt / N)   # 28 committed writes per group-step (bench width)
rec = {{
    "ts": time.time(), "platform": plat, "groups": G,
    "setup_s": round(setup_s, 1), "compile_s": round(compile_s, 1),
    "step_ms": round(dt / N * 1000, 3), "writes_per_s": int(wps),
}}
# A/B the unrolled inbox families (KernelParams.merge_inbox_families):
# 28x slower on XLA:CPU, but built for exactly this device's serial
# launch overhead — the r4 ladder measured it 44% slower on TPU too
# (256 groups: 188 vs 130 ms), so the A/B is now opt-in
if os.environ.get("TPU_GRAB_MERGED") != "1":
    print("RUNG " + json.dumps(rec))
    raise SystemExit(0)
try:
    import dataclasses
    kpm = dataclasses.replace(kp, merge_inbox_families=True)
    state2, box2 = elect_all(kpm, 3, make_cluster(kpm, G, 3))
    state2, box2 = run_steps(kpm, 3, 4, True, True, state2, box2)
    jax.block_until_ready(state2.term)
    t0 = time.time()
    state2, box2 = run_steps(kpm, 3, N, True, True, state2, box2)
    jax.block_until_ready(state2.term)
    rec["merged_step_ms"] = round((time.time() - t0) / N * 1000, 3)
except Exception as e:   # the plain rung must survive a merged failure
    rec["merged_error"] = str(e)[-200:]
print("RUNG " + json.dumps(rec))
"""


def probe(timeout: float = 60.0) -> bool:
    if os.environ.get("TPU_GRAB_FORCE_CPU") == "1":
        return True   # rung self-test: run the ladder on CPU
    try:
        r = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.default_backend())"],
            capture_output=True, text=True, timeout=timeout)
        return r.returncode == 0 and "tpu" in r.stdout
    except subprocess.TimeoutExpired:
        return False


def main() -> None:
    ladder = [int(x) for x in (
        sys.argv[sys.argv.index("--ladder") + 1].split(",")
        if "--ladder" in sys.argv else ["256", "1024", "4096", "8192"])]
    if not probe():
        print(json.dumps({"ts": time.time(), "probe": "wedged"}))
        return
    print("tunnel alive; measuring", flush=True)
    for g in ladder:
        steps = max(20, min(100, 200_000 // g))
        code = RUNG.format(repo=REPO, g=g, steps=steps)
        env = dict(os.environ)
        if os.environ.get("TPU_GRAB_FORCE_CPU") == "1":
            env.update(PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
        # generous per-rung timeout: compile at new shapes is slow over
        # the tunnel, but a wedge must not eat the whole session
        try:
            r = subprocess.run([sys.executable, "-c", code], env=env,
                               capture_output=True, text=True, timeout=900)
        except subprocess.TimeoutExpired:
            rec = {"ts": time.time(), "groups": g, "error": "rung timeout"}
            with open(OUT, "a") as f:
                f.write(json.dumps(rec) + "\n")
            print(json.dumps(rec), flush=True)
            break
        line = next((ln for ln in r.stdout.splitlines()
                     if ln.startswith("RUNG ")), None)
        if line is None:
            rec = {"ts": time.time(), "groups": g,
                   "error": (r.stderr or "no output")[-500:]}
        else:
            rec = json.loads(line[5:])
        with open(OUT, "a") as f:
            f.write(json.dumps(rec) + "\n")
        print(json.dumps(rec), flush=True)
        if "error" in rec:
            break


if __name__ == "__main__":
    main()
