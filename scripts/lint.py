#!/usr/bin/env python
"""Static-analysis runner: the nine lint passes over the repo.

Passes (dragonboat_tpu/analysis/):

  tracer-safety   Python control flow / host coercions on traced values
                  in every function reachable from a jit/vmap call site
  hlo-budget      optimized-HLO gather/scatter/while counts of the step
                  kernel vs the checked-in analysis/hlo_budget.json
  concurrency     `# guarded-by:` annotation discipline on shared
                  mutable state in the threaded modules, plus the CC003
                  lock-order graph (static deadlock detection)
  determinism     wall clock / unseeded RNG / set-iteration order in
                  the core/ and rsm/ replay paths
  contracts       machine-checked shape/dtype/domain/ring-mask
                  contracts over the batched Raft step (abstract
                  interpretation of core/kernel.py against the
                  CONTRACTS declarations, plus an eval_shape diff of
                  declared vs actual structures)
  partition       SPMD partition safety for the G axis: cross-group
                  data flow outside declared collectives, shard_map
                  in/out_specs vs the part= contract tags, donation
                  sharding identity, host callbacks inside shard_map
                  bodies, implicit device→host syncs in the engine hot
                  paths, and a 2-device dynamic diff of declared vs
                  actual output shardings
  engine-unity    one step loop, one dispatch abstraction: subclass
                  step-loop overrides (EU001), per-path dispatch
                  feature drift (EU002), donation/waiver parity of the
                  declared dispatch entries (EU003), the pipelined
                  retire-before-dispatch protocol on every path
                  (EU004), CompileTracker coverage of every jit entry
                  the engine layer touches (EU005), and engine-layer
                  imports of kernel internals (EU006) — all against
                  the literal contract in engine/dispatch.py
  safety          Raft protocol safety: the kstate INVARIANTS
                  declarations lint (RS001/RS006), provenance-checked
                  store obligations on committed / vote / last in
                  core/kernel.py (RS002-RS004), and the cached
                  small-scope exhaustive model check of the real jitted
                  kernel step (scripts/model_check.py fast scope,
                  RS005)
  transfer        the device<->host boundary as a checked contract:
                  every crossing into/out of the jitted dispatch
                  entries declared in engine/dispatch.py
                  TRANSFER_LEDGER and sized in closed form from the
                  CONTRACTS grammar — undeclared crossings (TB001),
                  per-step byte budgets vs
                  analysis/transfer_budget.json (TB002), unmasked wide
                  downloads outside the _LazyOut path (TB003), uploads
                  bypassing the staging builders (TB004), syncs outside
                  the declared SYNC_POINTS (TB005, the engine-wide
                  sharpening of PS006), per-step crossing-count growth
                  (TB006), plus a dynamic leg that steps the real
                  dispatch seams under jax.transfer_guard("disallow")
                  at three geometries and diffs the live METER counts
                  against the static ledger

Passes run in parallel worker processes (one fork per pass; jax
initializes per-child so the AST-only passes never pay for it).  Use
`--jobs 1` to force the serial path, `--changed-only` to run only the
passes whose input files differ from git HEAD (the tight-edit-loop
mode; scripts/run_tests.sh lint-fast wraps it).

Exit status is non-zero iff any unwaived finding remains.  Waivers live
in dragonboat_tpu/analysis/waivers.toml; waived findings are still
printed (with their reasons) so suppressions stay visible.  On a full
run (no --pass filter, no --changed-only) the waivers themselves are
linted: an entry whose path pattern matches no file (SW001) or that
suppressed zero findings (SW002) is stale and fails the run.

`--format json` emits one finding per line (JSON object with path,
line, pass, rule, message, waived, reason) so CI can diff findings
between commits; `--format sarif` emits a single SARIF 2.1.0 document
(one run, one result per finding, waived findings at level=note) for
code-scanning UIs; the default human format is unchanged.

The hlo-budget pass compiles the bench kernel (~10 s on CPU) only when
a hashed kernel source changed since the cached measurement
(analysis/.hlo_budget_cache.json); skip it entirely during tight edit
loops with `--pass` selecting the AST passes, or refresh its budget
after a justified kernel change with `--reseed-hlo-budget` (then
record why in PERF.md).  The partition pass's dynamic mesh check
caches the same way (analysis/.partition_cache.json), as does the
safety pass's model-check gate (analysis/.safety_cache.json) and the
transfer pass's live seam diff (analysis/.transfer_cache.json); the
transfer budget reseeds with `--reseed-transfer-budget`.
"""

from __future__ import annotations

import argparse
import fnmatch
import json
import os
import subprocess
import sys

# lowering must never grab a TPU just to count ops
os.environ.setdefault("JAX_PLATFORMS", "cpu")
# the partition pass's dynamic check needs a 2-device mesh; the flag
# must be set before any child (or this process) initializes jax
_xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _xla_flags:
    os.environ["XLA_FLAGS"] = (
        _xla_flags + " --xla_force_host_platform_device_count=2").strip()

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

from dragonboat_tpu.analysis import (  # noqa: E402
    common,
    concurrency,
    contracts,
    determinism,
    engine_unity,
    hlo_budget,
    partition,
    safety,
    tracer_safety,
    transfer,
)

PASSES = {
    "tracer-safety": tracer_safety.run,
    "concurrency": concurrency.run,
    "determinism": determinism.run,
    "hlo-budget": hlo_budget.run,
    "contracts": contracts.run,
    "partition": partition.run,
    "engine-unity": engine_unity.run,
    "safety": safety.run,
    "transfer": transfer.run,
}

# repo-relative inputs of each pass, for --changed-only (entries may be
# fnmatch globs — determinism scopes whole directories)
PASS_SCOPES = {
    "tracer-safety": tracer_safety.DEFAULT_MODULES,
    "concurrency": concurrency.DEFAULT_MODULES,
    "determinism": determinism.DEFAULT_GLOBS,
    "hlo-budget": hlo_budget.CACHE_SOURCES,
    "contracts": (contracts.CONTRACT_FILES + (contracts.PARAMS_FILE,)
                  + contracts.DONATION_MODULES),
    "partition": partition.SCOPE,
    "engine-unity": engine_unity.SCOPE,
    "safety": safety.SCOPE,
    "transfer": transfer.SCOPE,
}

WAIVERS_FILE = "dragonboat_tpu/analysis/waivers.toml"


def _repo_rel_files(root: str) -> list[str]:
    """Repo-relative paths of all source files (skips ignored dirs)."""
    skip = {"__pycache__", ".git", ".pytest_cache", ".hypothesis"}
    out: list[str] = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d not in skip]
        for fn in filenames:
            out.append(common.rel(root, os.path.join(dirpath, fn)))
    return out


def stale_waiver_findings(waivers: list[common.Waiver],
                          root: str) -> list[common.Finding]:
    """SW001/SW002: waivers that outlived the code they excused.

    Only meaningful after a FULL run — a --pass / --changed-only subset
    legitimately leaves other passes' waivers unexercised — so the
    caller gates on that.
    """
    relpath = common.rel(root, os.path.join(root, WAIVERS_FILE))
    files = _repo_rel_files(root)
    findings = []
    for w in waivers:
        if not any(fnmatch.fnmatch(p, w.path) for p in files):
            findings.append(common.Finding(
                "stale-waiver", relpath, w.line, "SW001",
                f"waiver path pattern {w.path!r} (pass {w.pass_name}) "
                "matches no file in the repo — delete the entry"))
        elif w.hits == 0:
            findings.append(common.Finding(
                "stale-waiver", relpath, w.line, "SW002",
                f"waiver for pass {w.pass_name}, path {w.path!r} "
                "suppressed zero findings this run — the code it "
                "excused is gone; delete the entry"))
    return findings


def changed_files(root: str) -> list[str] | None:
    """Repo-relative changed paths vs HEAD (staged + unstaged +
    untracked), or None when git is unavailable (callers run
    everything)."""
    try:
        diff = subprocess.run(
            ["git", "diff", "--name-only", "HEAD"], cwd=root,
            capture_output=True, text=True, timeout=30)
        untracked = subprocess.run(
            ["git", "ls-files", "--others", "--exclude-standard"],
            cwd=root, capture_output=True, text=True, timeout=30)
    except (OSError, subprocess.SubprocessError):
        return None
    if diff.returncode != 0:
        return None
    out = [ln.strip() for ln in diff.stdout.splitlines() if ln.strip()]
    if untracked.returncode == 0:
        out += [ln.strip() for ln in untracked.stdout.splitlines()
                if ln.strip()]
    return sorted(set(out))


def select_changed(changed: list[str]) -> list[str]:
    """Which passes a change set touches.  Any edit to the analyzers or
    this runner invalidates everything — and so does a waivers.toml
    edit (spelled out even though the analysis/ prefix covers it: a
    changed waiver can un-suppress a finding in ANY pass, so no pass's
    prior verdict survives it)."""
    if any(c == WAIVERS_FILE
           or c.startswith("dragonboat_tpu/analysis/")
           or c.startswith("scripts/lint") for c in changed):
        return sorted(PASSES)
    out = []
    for name in sorted(PASSES):
        scope = PASS_SCOPES[name]
        if any(fnmatch.fnmatch(c, pat) or c == pat
               for c in changed for pat in scope):
            out.append(name)
    return out


def _run_pass(name: str) -> list[common.Finding]:
    """Worker entry: one pass, raw (unwaived) findings.  Waivers are
    applied in the parent so hit-counting (stale-waiver lint) sees every
    pass's results."""
    return PASSES[name](ROOT)


def run_passes(selected: list[str],
               jobs: int) -> dict[str, list[common.Finding]]:
    """Run passes, in parallel when possible; results keyed by pass."""
    if jobs != 1 and len(selected) > 1:
        try:
            import multiprocessing
            from concurrent.futures import ProcessPoolExecutor

            nworkers = min(len(selected),
                           jobs if jobs > 0 else (os.cpu_count() or 2))
            # fork so workers inherit the imported analyzers; jax is
            # only ever initialized inside a child
            with ProcessPoolExecutor(
                    max_workers=nworkers,
                    mp_context=multiprocessing.get_context("fork"),
            ) as pool:
                futs = {name: pool.submit(_run_pass, name)
                        for name in selected}
                return {name: fut.result() for name, fut in futs.items()}
        except Exception as e:  # no fork/semaphores: degrade, don't fail
            print(f"note: parallel pass execution unavailable "
                  f"({type(e).__name__}: {e}); running serially",
                  file=sys.stderr)
    return {name: _run_pass(name) for name in selected}


def to_sarif(unwaived: list[common.Finding],
             waived: list[tuple[common.Finding, common.Waiver]]) -> dict:
    """One SARIF 2.1.0 run: rules derived from the findings, waived
    findings downgraded to level=note with the waiver reason attached."""
    rules: dict[str, dict] = {}
    results = []
    for f, reason in ([(f, None) for f in unwaived]
                      + [(f, wv.reason) for f, wv in waived]):
        rules.setdefault(f.rule, {
            "id": f.rule,
            "properties": {"pass": f.pass_name},
            "shortDescription": {"text": f"{f.pass_name} {f.rule}"},
        })
        res = {
            "ruleId": f.rule,
            "level": "note" if reason is not None else "error",
            "message": {"text": f.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": f.path},
                    "region": {"startLine": max(f.line, 1)},
                },
            }],
            "properties": {"pass": f.pass_name,
                           "waived": reason is not None},
        }
        if reason is not None:
            res["properties"]["waiverReason"] = reason
        results.append(res)
    return {
        "$schema": ("https://raw.githubusercontent.com/oasis-tcs/"
                    "sarif-spec/master/Schemata/sarif-schema-2.1.0.json"),
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "dragonboat-tpu-lint",
                "rules": [rules[k] for k in sorted(rules)],
            }},
            "results": results,
        }],
    }


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--pass", dest="passes", action="append",
                    choices=sorted(PASSES),
                    help="run only this pass (repeatable; default: all)")
    ap.add_argument("--changed-only", action="store_true",
                    help="run only passes whose input files changed vs "
                         "git HEAD (skips the stale-waiver lint)")
    ap.add_argument("--jobs", type=int, default=0,
                    help="worker processes (0 = one per pass up to CPU "
                         "count; 1 = serial)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable findings blob on stdout "
                         "(legacy; prefer --format json)")
    ap.add_argument("--format", choices=("human", "json", "sarif"),
                    default="human",
                    help="json = one finding per line "
                         "(path, line, pass, rule, message, waived, "
                         "reason); sarif = one SARIF 2.1.0 document; "
                         "default: human")
    ap.add_argument("--reseed-hlo-budget", action="store_true",
                    help="re-measure the kernel and overwrite "
                         "analysis/hlo_budget.json (justify in PERF.md)")
    ap.add_argument("--reseed-transfer-budget", action="store_true",
                    help="re-size the declared transfer ledger and "
                         "overwrite analysis/transfer_budget.json "
                         "(justify in PERF.md)")
    args = ap.parse_args(argv)

    if args.reseed_hlo_budget:
        spec = hlo_budget.reseed(ROOT)
        print(f"reseeded {hlo_budget.BUDGET_FILE}:")
        print(json.dumps(spec["budget"], indent=2, sort_keys=True))
        return 0

    if args.reseed_transfer_budget:
        spec = transfer.reseed(ROOT)
        print(f"reseeded {transfer.BUDGET_FILE}:")
        print(json.dumps(spec["budget"], indent=2, sort_keys=True))
        return 0

    try:
        waivers = common.load_waivers(os.path.join(ROOT, WAIVERS_FILE))
    except common.WaiverError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    selected = args.passes or sorted(PASSES)
    skipped: list[str] = []
    if args.changed_only:
        changed = changed_files(ROOT)
        if changed is not None:
            wanted = select_changed(changed)
            skipped = [n for n in selected if n not in wanted]
            selected = [n for n in selected if n in wanted]
    human = args.format == "human" and not args.json
    if human and skipped:
        print(f"-- changed-only: skipping {', '.join(skipped)} "
              "(inputs unchanged)")

    results = run_passes(selected, args.jobs)
    unwaived: list[common.Finding] = []
    waived: list[tuple[common.Finding, common.Waiver]] = []
    for name in selected:
        u, w = common.apply_waivers(results[name], waivers)
        unwaived += u
        waived += w
        if human:
            print(f"== {name}: {len(u)} finding(s), {len(w)} waived ==")
            for f in u:
                print(f"  {f.format()}")
            for f, wv in w:
                print(f"  [waived: {wv.reason}] {f.format()}")

    if args.passes is None and not args.changed_only:
        # full run: a waiver that excuses nothing is itself a finding
        # (not waivable — a waiver cannot excuse its own staleness)
        stale = stale_waiver_findings(waivers, ROOT)
        unwaived += stale
        if human and (stale or waivers):
            print(f"== stale-waiver: {len(stale)} finding(s) ==")
            for f in stale:
                print(f"  {f.format()}")

    def row(f: common.Finding, reason: str | None) -> dict:
        return {"path": f.path, "line": f.line, "pass": f.pass_name,
                "rule": f.rule, "message": f.message,
                "waived": reason is not None, "reason": reason}

    if args.format == "json":
        for f in unwaived:
            print(json.dumps(row(f, None), sort_keys=True))
        for f, wv in waived:
            print(json.dumps(row(f, wv.reason), sort_keys=True))
    elif args.format == "sarif":
        print(json.dumps(to_sarif(unwaived, waived), indent=2,
                         sort_keys=True))
    elif args.json:
        print(json.dumps({
            "findings": [f.__dict__ for f in unwaived],
            "waived": [{"finding": f.__dict__, "reason": wv.reason}
                       for f, wv in waived],
        }, indent=2))
    elif unwaived:
        print(f"\nFAIL: {len(unwaived)} unwaived finding(s)")
    else:
        print("\nOK: no unwaived findings")
    return 1 if unwaived else 0


if __name__ == "__main__":
    sys.exit(main())
