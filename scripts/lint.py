#!/usr/bin/env python
"""Static-analysis runner: the four lint passes over the repo.

Passes (dragonboat_tpu/analysis/):

  tracer-safety   Python control flow / host coercions on traced values
                  in every function reachable from a jit/vmap call site
  hlo-budget      optimized-HLO gather/scatter/while counts of the step
                  kernel vs the checked-in analysis/hlo_budget.json
  concurrency     `# guarded-by:` annotation discipline on shared
                  mutable state in the threaded modules
  determinism     wall clock / unseeded RNG / set-iteration order in
                  the core/ and rsm/ replay paths

Exit status is non-zero iff any unwaived finding remains.  Waivers live
in dragonboat_tpu/analysis/waivers.toml; waived findings are still
printed (with their reasons) so suppressions stay visible.

The hlo-budget pass compiles the bench kernel (~10 s on CPU); skip it
during tight edit loops with `--pass` selecting the AST passes, or
refresh its budget after a justified kernel change with
`--reseed-hlo-budget` (then record why in PERF.md).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

# lowering must never grab a TPU just to count ops
os.environ.setdefault("JAX_PLATFORMS", "cpu")

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

from dragonboat_tpu.analysis import (  # noqa: E402
    common,
    concurrency,
    determinism,
    hlo_budget,
    tracer_safety,
)

PASSES = {
    "tracer-safety": tracer_safety.run,
    "concurrency": concurrency.run,
    "determinism": determinism.run,
    "hlo-budget": hlo_budget.run,
}

WAIVERS_FILE = "dragonboat_tpu/analysis/waivers.toml"


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--pass", dest="passes", action="append",
                    choices=sorted(PASSES),
                    help="run only this pass (repeatable; default: all)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable findings on stdout")
    ap.add_argument("--reseed-hlo-budget", action="store_true",
                    help="re-measure the kernel and overwrite "
                         "analysis/hlo_budget.json (justify in PERF.md)")
    args = ap.parse_args(argv)

    if args.reseed_hlo_budget:
        spec = hlo_budget.reseed(ROOT)
        print(f"reseeded {hlo_budget.BUDGET_FILE}:")
        print(json.dumps(spec["budget"], indent=2, sort_keys=True))
        return 0

    try:
        waivers = common.load_waivers(os.path.join(ROOT, WAIVERS_FILE))
    except common.WaiverError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    selected = args.passes or sorted(PASSES)
    unwaived: list[common.Finding] = []
    waived: list[tuple[common.Finding, common.Waiver]] = []
    for name in selected:
        findings = PASSES[name](ROOT)
        u, w = common.apply_waivers(findings, waivers)
        unwaived += u
        waived += w
        if not args.json:
            print(f"== {name}: {len(u)} finding(s), {len(w)} waived ==")
            for f in u:
                print(f"  {f.format()}")
            for f, wv in w:
                print(f"  [waived: {wv.reason}] {f.format()}")

    if args.json:
        print(json.dumps({
            "findings": [f.__dict__ for f in unwaived],
            "waived": [{"finding": f.__dict__, "reason": wv.reason}
                       for f, wv in waived],
        }, indent=2))
    elif unwaived:
        print(f"\nFAIL: {len(unwaived)} unwaived finding(s)")
    else:
        print("\nOK: no unwaived findings")
    return 1 if unwaived else 0


if __name__ == "__main__":
    sys.exit(main())
