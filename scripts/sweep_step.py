"""Step-time sensitivity sweep on the live backend.

Measures steady-state step_ms for the self-driving bench loop across
kernel-geometry variations to locate the hot dimension (K inbox slots,
E entry lanes, CAP ring, B proposal width, G lanes).  Usage:

    python scripts/sweep_step.py [quick]
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import numpy as np

jax.config.update("jax_compilation_cache_dir", "/tmp/dragonboat_tpu_jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

from dragonboat_tpu.bench_loop import elect_all, make_cluster, run_steps
from dragonboat_tpu.core import params as KP


def measure(groups, cap=256, k=None, e=16, b=16, steps=20, replicas=3):
    import dataclasses

    from dragonboat_tpu.bench_loop import bench_params

    k = k if k is not None else 5 * (replicas - 1)
    # geometry overrides on top of bench_params so the sweep inherits
    # every platform-picked lowering flag (onehot_reads today, whatever
    # comes next) instead of hand-copying the pick
    kp = dataclasses.replace(
        bench_params(replicas),
        log_cap=cap, inbox_cap=k, msg_entries=e, proposal_cap=b,
        readindex_cap=4, apply_batch=2 * b, compaction_overhead=2 * b,
    )
    state = make_cluster(kp, groups, replicas)
    t0 = time.time()
    state, box = elect_all(kp, replicas, state)
    elect_s = time.time() - t0
    # warmup/compile the timed variant
    state, box = run_steps(kp, replicas, steps, True, True, state, box)
    state.term.block_until_ready()
    t0 = time.time()
    state, box = run_steps(kp, replicas, steps, True, True, state, box)
    state.committed.block_until_ready()
    dt = time.time() - t0
    lead = np.asarray(state.role) == KP.LEADER
    step_ms = dt / steps * 1e3
    wps = groups * b / (dt / steps)
    print(f"G={groups:<6} CAP={cap:<5} K={k:<3} E={e:<3} B={b:<3} "
          f"step_ms={step_ms:8.2f}  writes/s={wps:>12,.0f}  "
          f"(elect {elect_s:.1f}s, leaders {int(lead.sum())})", flush=True)
    return step_ms


if __name__ == "__main__":
    quick = len(sys.argv) > 1 and sys.argv[1] == "quick"
    print(f"backend: {jax.devices()[0].platform}", flush=True)
    base = dict(groups=1024, cap=256, k=10, e=16, b=16)
    measure(**base)
    if not quick:
        measure(**{**base, "groups": 256})
        measure(**{**base, "groups": 4096})
        measure(**{**base, "k": 4})
        measure(**{**base, "k": 2})
        measure(**{**base, "e": 4})
        measure(**{**base, "e": 1, "b": 1})
        measure(**{**base, "cap": 64})
        measure(**{**base, "cap": 1024})
        measure(**{**base, "b": 4})
