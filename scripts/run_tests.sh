#!/bin/sh
# Full test suite in three file-chunked processes.
#
# Why not one `pytest tests/`: on this 1-core box, a single process that
# has executed ~300 tests crashes inside XLA:CPU's compile/deserialize
# path (SIGABRT in compilation-cache load or SIGSEGV in
# backend_compile, always in an engine thread) when it next touches a
# jitted engine executable.  Four full-run reproductions on 2026-07-31
# all died this way at a late collection position, while every file
# subset — including the exact crash-position test — passes in a fresh
# process, with identical code and a warm cache.  Deep engine-thread
# stacks and cross-engine first-compile serialization (both now in the
# product) narrowed but did not remove it; chunking bounds process age
# instead.  Exit status is non-zero if any chunk fails.
set -e
cd "$(dirname "$0")/.."
PY="${PYTHON:-python}"
rc=0
run() {
    echo "== chunk: $* =="
    PYTHONPATH= "$PY" -m pytest "$@" -q || rc=$?
}
run tests/test_zz_kernel_scale.py tests/test_zz_mesh_scale.py
run tests/test_a*.py tests/test_b*.py tests/test_d*.py tests/test_e*.py \
    tests/test_f*.py tests/test_g*.py tests/test_h*.py tests/test_k*.py
run tests/test_m*.py tests/test_n*.py tests/test_r*.py tests/test_s*.py \
    tests/test_t*.py tests/test_v*.py
# catch-all: any test file whose first letter the chunks above do not
# enumerate (a future test_c*/test_i*/... must not silently never run)
leftover=$(ls tests/test_*.py | grep -v \
    -e 'tests/test_zz_kernel_scale\.py' -e 'tests/test_zz_mesh_scale\.py' \
    -e 'tests/test_[abdefghkmnrstv]' || true)
if [ -n "$leftover" ]; then
    # shellcheck disable=SC2086
    run $leftover
fi
exit $rc
