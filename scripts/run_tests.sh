#!/bin/sh
# Full test suite in three file-chunked processes.
#
# History: a single `pytest tests/` used to die after ~300 tests inside
# XLA:CPU compile/deserialize (SIGABRT/SIGSEGV).  ROOT-CAUSED r5
# (PERF.md): vm.max_map_count exhaustion — jitted executables pin
# mmap'd segments and the suite compiles hundreds of geometries; the
# map count crossed 65,530 at exactly the crash position.
# tests/conftest.py now fences it (jax.clear_caches() above 45k maps),
# and one-process runs survive: GREEN x3 on 2026-07-31 (361+1-flake /
# 365 clean / 365 clean; the fence fired 37x on the first run).
# Chunking is kept as belt+braces for CI determinism on slow boxes.
# Exit status is non-zero if any chunk fails.
set -e
cd "$(dirname "$0")/.."
PY="${PYTHON:-python}"
rc=0
# fresh flaky-retry tally for this run; the chunked pytest processes
# below each merge their counts into tests/.retry_report.json
# (tests/conftest.py), and any module retrying >3x fails its chunk
rm -f tests/.retry_report.json
DBT_RETRY_REPORT_MERGE=1
export DBT_RETRY_REPORT_MERGE
run() {
    echo "== chunk: $* =="
    PYTHONPATH= "$PY" -m pytest "$@" -q || rc=$?
}
# Lint findings are written as a JSON-lines build artifact (CI uploads
# it; diffable between commits) and rendered as a per-check summary
# table by scripts/lint_summary.py, which carries the pass/fail.  A
# SARIF 2.1.0 sibling is emitted alongside for code-scanning UIs.
ARTIFACT="${LINT_ARTIFACT:-build/lint_findings.jsonl}"
SARIF_ARTIFACT="${LINT_SARIF_ARTIFACT:-build/lint_findings.sarif}"
mkdir -p "$(dirname "$ARTIFACT")" "$(dirname "$SARIF_ARTIFACT")"
lint() {
    # $@ = extra scripts/lint.py args; rc 2+ (waiver/parse errors) must
    # not be masked by an empty artifact looking clean
    lint_rc=0
    PYTHONPATH= "$PY" scripts/lint.py --format json "$@" \
        > "$ARTIFACT" || lint_rc=$?
    if [ "$lint_rc" -ge 2 ]; then
        echo "lint runner error (rc=$lint_rc)"
        return "$lint_rc"
    fi
    # second emission is cheap: every dynamic check is cache-warm from
    # the json run one line up
    PYTHONPATH= "$PY" scripts/lint.py --format sarif "$@" \
        > "$SARIF_ARTIFACT" || true
    # the sized device<->host crossing inventory (ROADMAP item 2's
    # work-list); CI uploads it next to the findings
    PYTHONPATH= "$PY" -m dragonboat_tpu.analysis.transfer . \
        > /dev/null 2>&1 || true
    PYTHONPATH= "$PY" scripts/lint_summary.py "$ARTIFACT"
}
# `run_tests.sh lint-fast`: the tight-edit-loop entry — only the lint
# passes whose input files changed vs git HEAD, then exit
if [ "${1:-}" = "lint-fast" ]; then
    echo "== lint (changed-only) =="
    lint --changed-only
    exit $?
fi
# fast pre-test stage: the nine static-analysis passes (scripts/lint.py;
# ~2 s when kernel sources are unchanged — the hlo-budget compile result
# is cached in analysis/.hlo_budget_cache.json keyed by a source hash,
# and the partition pass's 2-device mesh check likewise in
# analysis/.partition_cache.json, the safety pass's model-check gate
# in analysis/.safety_cache.json, the transfer pass's live seam diff in
# analysis/.transfer_cache.json — and ~20 s after a kernel edit).
# After a justified kernel change that shifts the
# gather/scatter/while counts: `python scripts/lint.py
# --reseed-hlo-budget`, review the analysis/hlo_budget.json diff, and
# record why in PERF.md.
echo "== lint =="
lint || rc=$?
run tests/test_zz_kernel_scale.py tests/test_zz_mesh_scale.py
run tests/test_a*.py tests/test_b*.py tests/test_d*.py tests/test_e*.py \
    tests/test_f*.py tests/test_g*.py tests/test_h*.py tests/test_k*.py
run tests/test_m*.py tests/test_n*.py tests/test_r*.py tests/test_s*.py \
    tests/test_t*.py tests/test_v*.py
# chaos tier-1: the fault-injection unit/acceptance tests plus the
# fixed-seed fast schedules; the long schedule sweep stays out of the
# default run (`pytest tests/test_chaos_schedules.py -m slow` on demand)
run tests/test_chaos_faults.py
run tests/test_chaos_schedules.py -m chaos_fast
# catch-all: any test file whose first letter the chunks above do not
# enumerate (a future test_c*/test_i*/... must not silently never run)
leftover=$(ls tests/test_*.py | grep -v \
    -e 'tests/test_zz_kernel_scale\.py' -e 'tests/test_zz_mesh_scale\.py' \
    -e 'tests/test_chaos_' \
    -e 'tests/test_[abdefghkmnrstv]' || true)
if [ -n "$leftover" ]; then
    # shellcheck disable=SC2086
    run $leftover
fi
exit $rc
