"""Where does the device step go?  Compile the bench step for the live
backend and report (a) XLA's own cost analysis, (b) optimized-HLO op
histogram with the serializing suspects called out (while loops,
scatters, gathers, dynamic slices), (c) measured step time at a small
shape for cross-checking.  Pure diagnosis — no state is mutated.

Usage: python scripts/tpu_profile.py [groups] [--hlo-dump FILE]
"""

import collections
import os
import re
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from dragonboat_tpu.hostenv import jax_cache_dir

jax.config.update("jax_compilation_cache_dir", jax_cache_dir())
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

from dragonboat_tpu.bench_loop import bench_params, make_cluster, run_steps
from dragonboat_tpu.core.kstate import empty_inbox


def op_histogram(hlo_text: str) -> dict:
    """Count optimized-HLO instructions by opcode (fusion bodies included:
    the text form inlines called computations, which is what we want —
    a serializing scatter inside a fusion still serializes)."""
    counts = collections.Counter()
    for m in re.finditer(r"^\s*(?:ROOT\s+)?%?[\w.\-]+\s*=\s*[\w\[\]{},/ ]+?\s"
                         r"([a-z][\w\-]*)\(", hlo_text, re.M):
        counts[m.group(1)] += 1
    return dict(counts)


def main() -> None:
    g = int(sys.argv[1]) if len(sys.argv) > 1 and sys.argv[1].isdigit() else 1024
    plat = jax.devices()[0].platform
    print(f"backend: {plat}  groups: {g}", flush=True)

    kp = bench_params(3)
    # no election: the compiled graph is state-independent, and elect_all
    # is its own multi-minute compile over the tunnel
    state = make_cluster(kp, g, 3)
    box = empty_inbox(kp, g * 3)
    jax.block_until_ready(state.term)

    # the exact bench inner loop (same jit key as the bench: run_steps
    # itself is jitted with static (kp, replicas, iters))
    t0 = time.time()
    lowered = run_steps.lower(kp, 3, 20, True, True, state, box)
    compiled = lowered.compile()
    print(f"compile: {time.time() - t0:.1f}s", flush=True)

    ca = compiled.cost_analysis()
    if isinstance(ca, list):
        ca = ca[0] if ca else {}
    if ca:
        keys = ["flops", "bytes accessed", "transcendentals",
                "optimal_seconds"]
        print("cost_analysis: " + "  ".join(
            f"{k}={ca[k]:.3g}" for k in keys if k in ca), flush=True)

    ma = compiled.memory_analysis()
    if ma is not None:
        for attr in ("temp_size_in_bytes", "argument_size_in_bytes",
                     "output_size_in_bytes", "generated_code_size_in_bytes"):
            v = getattr(ma, attr, None)
            if v is not None:
                print(f"memory.{attr}: {v:,}")

    hlo = compiled.as_text()
    print(f"optimized HLO: {len(hlo.splitlines()):,} lines")
    hist = op_histogram(hlo)
    suspects = ("while", "scatter", "gather", "dynamic-slice",
                "dynamic-update-slice", "sort", "all-reduce", "conditional",
                "rng-bit-generator", "custom-call")
    for name in suspects:
        if hist.get(name):
            print(f"  SUSPECT {name}: {hist[name]}")
    top = sorted(hist.items(), key=lambda kv: -kv[1])[:25]
    print("  top ops: " + ", ".join(f"{k}={v}" for k, v in top))

    if "--hlo-dump" in sys.argv:
        i = sys.argv.index("--hlo-dump") + 1
        if i >= len(sys.argv):
            print("--hlo-dump needs a filename; skipping dump")
        else:
            path = sys.argv[i]
            with open(path, "w") as f:
                f.write(hlo)
            print(f"dumped HLO to {path}")

    # measured time via the jitted entry (same executable via cache)
    out = run_steps(kp, 3, 20, True, True, state, box)
    jax.block_until_ready(out[0].term)
    t0 = time.time()
    out = run_steps(kp, 3, 20, True, True, *out)
    jax.block_until_ready(out[0].term)
    dt = time.time() - t0
    print(f"measured: {dt / 20 * 1000:.2f} ms/step at G={g}", flush=True)


if __name__ == "__main__":
    main()
