#!/usr/bin/env python
"""100k-group scale proof (VERDICT r4 item 4 / BASELINE config #3 shape).

Measures, with real allocations rather than projections:

  A. the batched kernel SoA state at 100k groups x 3 replicas
     (300k lanes): build time, device/host bytes, per-step time;
  B. the host side at 100k device-resident shards on ONE NodeHost:
     admission rate (batched lane injection), host-book bytes per lane
     (tracemalloc over a 10k slice), RSS, injection-flush time, idle
     staging scan time, and staging time under a proposal wave.

Each phase prints one JSON line (PHASE_A / PHASE_B); partial runs still
yield data.  Both rungs carry the capacity triple —
``predicted_bytes`` (contracts-derived model, capacity.py),
``measured_bytes`` (live tree bytes), ``max_g_at_budget`` (largest G
fitting the device HBM limit / SCALE_BUDGET_BYTES) — so a sweep shows
the model tracking reality rung by rung.  Run on an idle box:
`python scripts/scale_100k.py [--groups N]`.
"""

import json
import os
import resource
import sys
import time
import tracemalloc

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

GROUPS = 100_000
if "--groups" in sys.argv:
    GROUPS = int(sys.argv[sys.argv.index("--groups") + 1])
STEPS = int(os.environ.get("SCALE_STEPS", "5"))


def _enable_compile_cache() -> None:
    """Persistent compile cache keyed at capacity shapes: the 100k-lane
    step executable compiled once per box (the r4 measurement paid a
    479 s first-step compile on every run).  Counts artifacts BEFORE
    enabling so the log line says whether this run starts cold or rides
    a warm cache."""
    from dragonboat_tpu import hostenv

    try:
        artifacts = len(os.listdir(hostenv.jax_cache_dir()))
    except OSError:
        artifacts = 0
    cache_dir = hostenv.enable_compile_cache()
    if cache_dir is None:
        print("SCALE compile_cache: vetoed "
              "(DRAGONBOAT_TPU_COMPILE_CACHE=0)", flush=True)
    else:
        print(f"SCALE compile_cache: {'warm' if artifacts else 'cold'} "
              f"({artifacts} artifact(s)) dir={cache_dir}", flush=True)


def _budget_bytes(capacity_mod) -> int:
    """Device HBM limit when the backend reports one, else the
    SCALE_BUDGET_BYTES env (default 16 GiB — one v5e core)."""
    for row in capacity_mod.device_memory_stats():
        if row.get("bytes_limit"):
            return int(row["bytes_limit"])
    return int(os.environ.get("SCALE_BUDGET_BYTES", str(16 << 30)))


def rss_gb() -> float:
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1e6


def phase_a() -> None:
    import jax
    import jax.numpy as jnp

    _enable_compile_cache()

    from dragonboat_tpu import capacity
    from dragonboat_tpu.bench_loop import bench_params, make_cluster, run_steps
    from dragonboat_tpu.core.kstate import empty_inbox

    kp = bench_params(3)
    t0 = time.time()
    state = make_cluster(kp, GROUPS, 3)
    box = empty_inbox(kp, state.term.shape[0])
    jax.block_until_ready(state.term)
    build_s = time.time() - t0
    # contracts-derived model vs what the trees actually hold: the two
    # must agree (test_capacity pins <1%); the rung records both
    lanes = int(state.term.shape[0])
    classes = ("ShardState", "Inbox")
    predicted = capacity.predict_bytes(kp, lanes, classes)
    state_bytes = capacity.measure_tree_bytes(state)
    box_bytes = capacity.measure_tree_bytes(box)
    budget = _budget_bytes(capacity)
    max_g = capacity.max_g_for_budget(kp, budget, classes)
    # iters is a static jit arg: warm the EXACT executable we measure —
    # through CompileTracker, so the rung itself proves the steady-state
    # contract (one compile at this geometry, zero retraces after)
    tracked = capacity.TRACKER.wrap("scale_run_steps", run_steps)
    t0 = time.time()
    state, box = tracked(kp, 3, STEPS, True, True, state, box)
    jax.block_until_ready(state.term)
    compile_s = time.time() - t0
    t0 = time.time()
    state, box = tracked(kp, 3, STEPS, True, True, state, box)
    jax.block_until_ready(state.term)
    dt = time.time() - t0
    tstats = tracked.stats()
    assert tstats["compiles"] == 1 and tstats["retraces"] == 0, (
        f"scale rung retraced: {tstats}")
    print("PHASE_A " + json.dumps({
        "groups": GROUPS, "lanes": GROUPS * 3,
        "platform": jax.devices()[0].platform,
        "build_s": round(build_s, 1),
        "state_mb": round(state_bytes / 1e6, 1),
        "inbox_mb": round(box_bytes / 1e6, 1),
        "predicted_bytes": predicted,
        "measured_bytes": state_bytes + box_bytes,
        "max_g_at_budget": max_g,
        "compile_s": round(compile_s, 1),
        "step_ms": round(dt / STEPS * 1e3, 1),
        "dispatch_compiles": tstats["compiles"],
        "dispatch_retraces": tstats["retraces"],
        "rss_gb": round(rss_gb(), 2),
    }), flush=True)
    del state, box


def phase_b() -> None:
    import numpy as np

    from dragonboat_tpu.config import Config, ExpertConfig, NodeHostConfig
    from dragonboat_tpu.nodehost import NodeHost
    from dragonboat_tpu.statemachine import IStateMachine, Result

    class NullSM(IStateMachine):
        """Minimal SM: the measurement targets the books, not the RSM."""

        def __init__(self, shard_id, replica_id):
            self.n = 0

        def update(self, entry):
            self.n += 1
            return Result(value=self.n)

        def lookup(self, q):
            return self.n

        def save_snapshot(self, w, files, done):
            w.write(b"\x00" * 4)

        def recover_from_snapshot(self, r, files, done):
            r.read(4)

    _enable_compile_cache()
    expert = ExpertConfig()
    expert.kernel_capacity = GROUPS
    # no node_host_dir -> MemLogDB: the measurement targets the host
    # books and the staging scan, not storage
    nh = NodeHost(NodeHostConfig(raft_address="scale-1", rtt_millisecond=5,
                                 expert=expert), auto_run=False)
    base_cfg = dict(election_rtt=10, heartbeat_rtt=1)

    def admit(lo: int, hi: int) -> float:
        t0 = time.time()
        for sid in range(lo, hi):
            nh.start_replica({1: "scale-1"}, False, NullSM, Config(
                shard_id=sid, replica_id=1, device_resident=True,
                **base_cfg))
        return time.time() - t0

    # warm slice to settle dict shapes, then a traced slice for the
    # bytes/lane number, then the untraced remainder (tracemalloc ~2x)
    head = max(2, min(5_000, GROUPS // 4))
    traced = max(2, min(10_000, GROUPS // 2))
    admit_head_s = admit(1, head + 1)
    tracemalloc.start()
    s0, _ = tracemalloc.get_traced_memory()
    t_traced = admit(head + 1, head + traced + 1)
    s1, _ = tracemalloc.get_traced_memory()
    tracemalloc.stop()
    bytes_per_lane = (s1 - s0) / traced
    t_rest = admit(head + traced + 1, GROUPS + 1)
    n_shards = len(nh.nodes)
    # the traced slice runs ~2x slow under tracemalloc: exclude it from
    # BOTH sides of the rate instead of inflating the numerator
    admit_rate = (n_shards - traced) / (admit_head_s + t_rest + 1e-9)

    eng = nh.kernel_engine

    def tick_all():
        # the PRODUCTION tick round: one shared-clock advance + one
        # engine-wide pending tick (consumed as a vectorized broadcast
        # at the next step) — not a per-lane Python walk
        nh._do_tick_round()

    # first kernel call: flushes EVERY queued injection at once AND
    # compiles the step executable at this capacity
    tick_all()
    t0 = time.time()
    eng.step_all()
    flush_compile_s = time.time() - t0
    # election pump: single-member shards campaign once their election
    # timer fires; the engine sees ticks only when the host ticks nodes
    from dragonboat_tpu.core import params as KP

    leaders = 0
    pump_rounds = 0
    t_pump = time.time()
    for _ in range(40):
        pump_rounds += 1
        tick_all()
        eng.step_all()
        leaders = int((np.asarray(eng.state.role) == KP.LEADER).sum())
        if leaders >= n_shards:
            break
    pump_s = time.time() - t_pump
    idle = []
    for _ in range(5):
        t0 = time.time()
        eng.step_all()
        idle.append(time.time() - t0)

    # proposal wave on 1k shards through the real client path
    waves = 0
    for sid in range(1, 1001):
        sess = nh.get_noop_session(sid)
        try:
            nh.propose(sess, b"k=1", timeout_s=30)
            waves += 1
        except Exception:
            pass
    stage_t0 = time.time()
    eng.step_all()
    eng.step_all()
    wave_steps_s = time.time() - stage_t0
    committed = int(np.asarray(eng.state.committed)[:n_shards].sum())
    # the rung ran entirely through the unified dispatch seam
    # (engine/dispatch.py): its active tracked entry must show exactly
    # one compile at this capacity and zero steady-state retraces
    active = "step_donated" if eng.pipeline_depth > 0 else "step"
    dstats = eng._cap_entries[active].stats()
    assert dstats["compiles"] == 1 and dstats["retraces"] == 0, (
        f"dispatch entry {active!r} retraced at scale: {dstats}")
    # same model the engine's /debug/capacity serves: classes + trees
    # come from the engine so the rung and the endpoint can't diverge
    from dragonboat_tpu import capacity

    classes = eng._capacity_model_classes()
    predicted = capacity.predict_bytes(
        eng.kp, int(eng.state.term.shape[0]), classes)
    measured = capacity.measure_tree_bytes(*eng._capacity_trees())
    max_g = capacity.max_g_for_budget(
        eng.kp, _budget_bytes(capacity), classes)
    print("PHASE_B " + json.dumps({
        "shards": n_shards,
        "predicted_bytes": predicted,
        "measured_bytes": measured,
        "max_g_at_budget": max_g,
        "admit_per_s": round(admit_rate),
        "bytes_per_lane_host_books": round(bytes_per_lane),
        "rss_gb": round(rss_gb(), 2),
        "injection_flush_plus_compile_s": round(flush_compile_s, 2),
        "election_pump_rounds": pump_rounds,
        "election_pump_s": round(pump_s, 1),
        "leaders": leaders,
        "idle_scan_step_ms": round(1e3 * sum(idle) / max(len(idle), 1), 1),
        "proposals_queued": waves,
        "wave_2steps_s": round(wave_steps_s, 3),
        "committed_total": committed,
        "dispatch_entry": active,
        "dispatch_compiles": dstats["compiles"],
        "dispatch_retraces": dstats["retraces"],
    }), flush=True)
    nh.close()


if __name__ == "__main__":
    which = os.environ.get("SCALE_PHASE", "ab")
    if "a" in which:
        phase_a()
    if "b" in which:
        phase_b()
