#!/usr/bin/env python
"""On-device A/B of the rsm-apply kernels (VERDICT r4 item 8: the pallas
kernel has been bit-exact in interpret mode for two rounds; its reason
to exist is a compiled device number).

Measures, at the bench shape (sm_params, direct-mapped table):

  1. the bare apply kernels on a synthetic [G, AB] committed window —
     sequential probing scan vs one-pass range apply vs the pallas
     block kernel (VMEM-resident table across the window);
  2. the full device-SM step loop (run_steps_sm) with the XLA range
     apply vs the pallas apply.

Round 17 adds ``kind=fabric_ab`` rungs for the device-resident fabric:
the serving loop with hub delivery vs the in-step collective exchange
(parallel/ici.py per-link cut mask open vs all-cut + host route), and
the two hot gather shapes on that path — inbox lane staging and the
quorum match select — as pallas VMEM block kernels vs their XLA
lowerings (parallel/fabric_pallas.py).

Appends JSON lines (kind=pallas_ab / pipeline_ab / fabric_ab) to
PERF_TPU.jsonl.  Self-test on CPU with PALLAS_AB_FORCE_CPU=1 (pallas
runs in interpret mode there — the relative number is meaningless
off-TPU, the plumbing check is not).

Usage: python scripts/tpu_pallas_ab.py [groups]
"""

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# the fabric serve rung needs one host device per replica slot; must be
# set before jax loads (harmless on real TPU: flag only affects CPU)
if os.environ.get("PALLAS_AB_FORCE_CPU") == "1":
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=8").strip()

import jax
import jax.numpy as jnp

from dragonboat_tpu import hostenv

# shared persistent-cache helper (hostenv): same fingerprinted dir as
# scale_100k.py, vetoable via DRAGONBOAT_TPU_COMPILE_CACHE=0
try:
    _CACHE_ARTIFACTS = len(os.listdir(hostenv.jax_cache_dir()))
except OSError:
    _CACHE_ARTIFACTS = 0
_CACHE_DIR = hostenv.enable_compile_cache()
print("PALLAS_AB compile_cache: "
      + ("vetoed (DRAGONBOAT_TPU_COMPILE_CACHE=0)" if _CACHE_DIR is None
         else f"{'warm' if _CACHE_ARTIFACTS else 'cold'} "
              f"({_CACHE_ARTIFACTS} artifact(s)) dir={_CACHE_DIR}"),
      flush=True)

OUT = os.path.join(REPO, "PERF_TPU.jsonl")


def bare_apply_ab(G: int, AB: int, iters: int = 50) -> dict:
    """Apply kernels alone on synthetic windows (no raft step around
    them): per-call ms for scan/range/pallas at [G, AB]."""
    import numpy as np

    from dragonboat_tpu.rsm.device_kv import DeviceKV
    from dragonboat_tpu.rsm.device_kv_pallas import apply_kernel_pallas

    kv = DeviceKV(table_cap=1024, hash_keys=False)
    T = kv.table_cap
    rng = np.random.default_rng(3)
    first = jnp.asarray(rng.integers(0, T, G), jnp.int32)
    vals = jnp.asarray(rng.integers(1, 1 << 20, (G, AB)), jnp.int32)
    valid = jnp.asarray(rng.random((G, AB)) < 0.9)
    idx = first[:, None] + jnp.arange(AB, dtype=jnp.int32)[None, :]
    keys = idx & (T - 1)
    cmds = jnp.stack([keys, vals], axis=-1)

    out = {}

    def timed(tag, fn):
        st = kv.init_state(G)
        st, _ = fn(st)                      # compile
        jax.block_until_ready(st["vals"])
        t0 = time.time()
        for _ in range(iters):
            st, _ = fn(st)
        jax.block_until_ready(st["vals"])
        out[tag + "_ms"] = round((time.time() - t0) / iters * 1e3, 3)

    timed("apply_scan", lambda st: kv.apply_kernel(st, cmds, valid))
    timed("apply_range",
          lambda st: kv.apply_kernel_range(st, first & (T - 1), vals, valid))
    try:
        timed("apply_pallas",
              lambda st: apply_kernel_pallas(kv, st, cmds, valid))
    except Exception as e:
        out["apply_pallas_error"] = str(e)[-200:]
    return out


def step_loop_ab(G: int, steps: int) -> dict:
    """run_steps_sm with the range apply vs the pallas apply — the
    number that decides which one full_step_sm ships."""
    from dragonboat_tpu.bench_loop import (
        elect_all,
        make_cluster,
        make_device_sm,
        run_steps_sm,
        sm_params,
    )

    kp = sm_params(3)
    out = {}
    for tag, use_pallas in (("sm_range", False), ("sm_pallas", True)):
        try:
            state, box = elect_all(kp, 3, make_cluster(kp, G, 3))
            kv, kv_state = make_device_sm(G, 3, use_pallas=use_pallas)
            state, box, kv_state, _ = run_steps_sm(
                kp, 3, kv, 4, True, True, state, box, kv_state)  # compile
            jax.block_until_ready(state.term)
            t0 = time.time()
            state, box, kv_state, _ = run_steps_sm(
                kp, 3, kv, steps, True, True, state, box, kv_state)
            jax.block_until_ready(state.term)
            out[tag + "_step_ms"] = round(
                (time.time() - t0) / steps * 1e3, 3)
        except Exception as e:
            out[tag + "_error"] = str(e)[-200:]
    return out


def pipeline_loop_ab(G: int, pipe_iters: int) -> dict:
    """Serial run_steps vs the fused depth-1 run_steps_pipelined at
    matched micro-step counts (serial iters = 2 * pipe_iters) — the
    device-side cost of the pipelined loop body (PR 6 tentpole)."""
    from dragonboat_tpu.bench_loop import (
        bench_params,
        elect_all,
        make_cluster,
        run_steps,
        run_steps_pipelined,
    )

    kp = bench_params(3)
    out = {}
    for tag, loop, iters in (("serial", run_steps, 2 * pipe_iters),
                             ("pipelined", run_steps_pipelined, pipe_iters)):
        try:
            state, box = elect_all(kp, 3, make_cluster(kp, G, 3))
            # warm the EXACT executable (iters is a static arg)
            state, box = loop(kp, 3, iters, True, True, state, box)
            jax.block_until_ready(state.term)
            t0 = time.time()
            state, box = loop(kp, 3, iters, True, True, state, box)
            jax.block_until_ready(state.term)
            micro = iters * (2 if tag == "pipelined" else 1)
            out[tag + "_step_ms"] = round(
                (time.time() - t0) / micro * 1e3, 3)
        except Exception as e:
            out[tag + "_error"] = str(e)[-200:]
    return out


def gather_donated_ab(G: int, iters: int = 30) -> dict:
    """Single-dispatch step vs step_donated at the bench shape: the hot
    gather paths (log window fetch, inbox route) re-lowered with buffer
    donation, which lets XLA write outputs over the dead input SoA
    arrays instead of allocating per step.  Both arms pay the same
    host-side empty-inbox/input staging, as the engine does."""
    from dragonboat_tpu.bench_loop import bench_params, elect_all, make_cluster
    from dragonboat_tpu.core.kernel import step, step_donated
    from dragonboat_tpu.core.kstate import empty_inbox, empty_input

    kp = bench_params(3)
    out = {}
    for tag, fn in (("step", step), ("step_donated", step_donated)):
        try:
            state, _ = elect_all(kp, 3, make_cluster(kp, G, 3))
            n = state.term.shape[0]
            state, _ = fn(kp, state, empty_inbox(kp, n),
                          empty_input(kp, n))           # compile
            jax.block_until_ready(state.term)
            t0 = time.time()
            for _ in range(iters):
                state, _ = fn(kp, state, empty_inbox(kp, n),
                              empty_input(kp, n))
            jax.block_until_ready(state.term)
            out[tag + "_ms"] = round((time.time() - t0) / iters * 1e3, 3)
        except Exception as e:
            out[tag + "_error"] = str(e)[-200:]
    return out


def fabric_serve_ab(groups: int, micro: int = 40,
                    replicas: int = 2) -> dict:
    """Hub delivery vs device-resident exchange on the SERVING loop
    (round 17 tentpole): both arms run jit_serve_step; the resident arm
    serves with an all-open per-link cut mask (messages ride the
    in-step collective), the hub arm with EVERY link cut — its
    out-lanes are pulled to the host, staged back through
    core/router.route (the hub fallback's addressing) and re-uploaded
    as the next inbox.  Per-micro-step ms for each arm; the delta is
    the host hub's tax on co-located links."""
    import numpy as np

    from jax.sharding import Mesh

    from dragonboat_tpu.bench_loop import bench_params
    from dragonboat_tpu.core import params as KP
    from dragonboat_tpu.core.router import route
    from dragonboat_tpu.parallel.ici import (
        jit_serve_step,
        make_ici_cluster,
        self_driving_input,
    )

    devs = jax.devices()
    if len(devs) < replicas:
        return {"serve_error":
                f"needs {replicas} devices, have {len(devs)}"}
    kp = bench_params(replicas)
    mesh = Mesh(np.array(devs[:replicas]).reshape(1, replicas),
                ("g", "r"))
    cluster, state, box = make_ici_cluster(kp, mesh, groups)
    n_local = groups  # g_size=1: mesh row ir*n_local + n <-> router n*R+ir
    perm = np.empty(groups * replicas, np.int64)
    for n in range(groups):
        for ir in range(replicas):
            perm[n * replicas + ir] = ir * n_local + n
    iperm = np.argsort(perm)
    total = cluster.total_rows
    cut_open = cluster.shard(np.zeros((total, kp.num_peers), bool))
    cut_all = cluster.shard(np.ones((total, kp.num_peers), bool))

    # election pump (resident path) until every group has a leader
    for _ in range(40):
        if int((np.asarray(state.role) == KP.LEADER).sum()) >= groups:
            break
        inp = self_driving_input(kp, state, propose=False)
        state, box, _ = jit_serve_step(
            kp, cluster, state, box, inp, cut_open)

    route_jit = jax.jit(route, static_argnums=(0, 1))
    pull = lambda t: jax.tree.map(lambda x: np.array(x), t)  # noqa: E731
    repermute = lambda t, p: jax.tree.map(  # noqa: E731
        lambda x: x[p], t)

    arms = {"resident": (state, box), "hub": (state, box)}
    out = {}
    for tag in arms:
        st, bx = arms[tag]
        for warm in (True, False):
            t0 = time.time()
            for _ in range(micro):
                inp = self_driving_input(kp, st, propose=True)
                if tag == "resident":
                    st, bx, _ = jit_serve_step(
                        kp, cluster, st, bx, inp, cut_open)
                else:
                    st, _, outgoing = jit_serve_step(
                        kp, cluster, st, bx, inp, cut_all)
                    hub_box = route_jit(
                        kp, replicas, repermute(pull(outgoing), perm))
                    bx = cluster.shard(repermute(pull(hub_box), iperm))
            jax.block_until_ready(st.term)
            if warm:  # first window compiles; only the second is timed
                continue
            out[tag + "_step_ms"] = round(
                (time.time() - t0) / micro * 1e3, 3)
    if "resident_step_ms" in out and "hub_step_ms" in out:
        out["hub_over_resident_x"] = round(
            out["hub_step_ms"] / max(out["resident_step_ms"], 1e-9), 3)
    return out


def fabric_gather_ab(G: int, iters: int = 50) -> dict:
    """The serving path's two hot gather shapes as pallas VMEM block
    kernels vs their XLA lowerings (parallel/fabric_pallas.py): inbox
    lane staging (batched gather) and the quorum match order statistic
    (sort + gather).  Asserts bitwise agreement on the way."""
    import numpy as np

    from dragonboat_tpu.parallel.fabric_pallas import (
        gather_lanes_pallas,
        gather_lanes_xla,
        quorum_match_pallas,
        quorum_match_xla,
    )

    K, R = 32, 8
    rng = np.random.default_rng(11)
    vals = jnp.asarray(rng.integers(0, 1 << 20, (G, K)), jnp.int32)
    idx = jnp.asarray(rng.integers(0, K, (G, K)), jnp.int32)
    match = jnp.asarray(rng.integers(0, 1 << 16, (G, R)), jnp.int32)
    voting = jnp.asarray(rng.random((G, R)) < 0.9)
    q = jnp.asarray(rng.integers(1, R // 2 + 2, G), jnp.int32)
    interpret = jax.devices()[0].platform not in ("tpu", "axon")
    out = {"gather_interpret": interpret}

    def timed(tag, fn, *a):
        r = fn(*a)                                  # compile
        jax.block_until_ready(r)
        t0 = time.time()
        for _ in range(iters):
            r = fn(*a)
        jax.block_until_ready(r)
        out[tag + "_ms"] = round((time.time() - t0) / iters * 1e3, 3)
        return r

    ref = timed("inbox_gather_xla", jax.jit(gather_lanes_xla), vals, idx)
    try:
        got = timed("inbox_gather_pallas",
                    gather_lanes_pallas, vals, idx, interpret)
        out["inbox_gather_bitwise"] = bool(jnp.array_equal(ref, got))
    except Exception as e:
        out["inbox_gather_pallas_error"] = str(e)[-200:]
    ref = timed("quorum_match_xla",
                jax.jit(quorum_match_xla), match, voting, q)
    try:
        got = timed("quorum_match_pallas",
                    quorum_match_pallas, match, voting, q, interpret)
        out["quorum_match_bitwise"] = bool(jnp.array_equal(ref, got))
    except Exception as e:
        out["quorum_match_pallas_error"] = str(e)[-200:]
    return out


def main() -> None:
    g = int(sys.argv[1]) if len(sys.argv) > 1 and sys.argv[1].isdigit() \
        else 1024
    plat = jax.devices()[0].platform
    if plat == "cpu" and os.environ.get("PALLAS_AB_FORCE_CPU") != "1":
        print(json.dumps({"skipped": "cpu backend (interpret-mode pallas "
                                     "measures nothing); set "
                                     "PALLAS_AB_FORCE_CPU=1 to self-test"}))
        return
    rec = {"ts": time.time(), "kind": "pallas_ab", "platform": plat,
           "groups": g}
    from dragonboat_tpu.bench_loop import sm_params

    AB = sm_params(3).apply_batch
    print(f"backend: {plat}  groups: {g}  AB: {AB}", flush=True)
    rec.update(bare_apply_ab(g * 3, AB))
    print("bare: " + json.dumps(rec), flush=True)
    rec.update(step_loop_ab(g, steps=max(10, min(50, 100_000 // g))))
    # pipelined-loop + donated-dispatch rungs (PR 6) as their own
    # kind-tagged line so downstream greps select by rung family
    pipe = {"ts": time.time(), "kind": "pipeline_ab", "platform": plat,
            "groups": g}
    pipe.update(pipeline_loop_ab(g, pipe_iters=max(5, min(25, 50_000 // g))))
    pipe.update(gather_donated_ab(g))
    # device-resident fabric rungs (round 17) as their own kind line
    fab = {"ts": time.time(), "kind": "fabric_ab", "platform": plat,
           "groups": g}
    fab.update(fabric_serve_ab(min(g, 1024),
                               micro=max(5, min(40, 20_000 // g))))
    fab.update(fabric_gather_ab(g))
    with open(OUT, "a") as f:
        f.write(json.dumps(rec) + "\n")
        f.write(json.dumps(pipe) + "\n")
        f.write(json.dumps(fab) + "\n")
    print(json.dumps(rec), flush=True)
    print(json.dumps(pipe), flush=True)
    print(json.dumps(fab), flush=True)


if __name__ == "__main__":
    main()
