#!/usr/bin/env python
"""Triage CLI over a NodeHost's fleet-health drill-down endpoints.

Scrapes ``/debug/groups`` (NodeHost.info(): merged health snapshot +
NodeHostInfo-parity shard list) from a running NodeHost's metrics
listener, validates it strictly (core/health.py validate_info — the
same schema check the tests pin), and prints a human triage report:
anomaly class counts, the top-K worst-offender table, and the per-shard
residency/leader summary.

    python scripts/fleet_doctor.py 127.0.0.1:9090
    python scripts/fleet_doctor.py 127.0.0.1:9090 --json
    python scripts/fleet_doctor.py 127.0.0.1:9090 --shard 7
    python scripts/fleet_doctor.py 127.0.0.1:9090 --shard 7 --json
    python scripts/fleet_doctor.py 127.0.0.1:9090 --plan
    python scripts/fleet_doctor.py 127.0.0.1:9090 --plan --json

``--shard N`` drills into ``/debug/group/N`` (NodeHost.shard_info():
the one group's O(1) device row merged with host registers — pending
books, logdb range, breaker states, gossip ShardView).  ``--json``
prints the validated payload verbatim, so the output round-trips
against the endpoint byte-for-byte.

``--plan`` runs the elastic control plane's pure planner
(dragonboat_tpu/control.py) READ-ONLY over the scraped payload — the
same decision core the NodeHost acts on, fed the same observation, but
nothing is issued.  It prints transfer / refuse / quiesce counts with
each decision's evidence row, validates its own output against the
strict plan schema (control.validate_plan), and exits 1 when any
action is pending so the flag scripts as a fleet-drift check.  The
dry-run is per-host and stateless: hysteresis is 1 (a one-observation
controller has no streak history) and the admission check is advisory
(the doctor cannot know the host's enforcement mode).

When the payload carries a ``capacity`` section (capacity.py merged
snapshot), the report adds a capacity block — live/peak bytes, headroom
against the device budget, the contracts-model prediction, and the
per-entry compile/retrace counters — and memory pressure or a retrace
storm counts as degraded alongside the anomaly classes.

``--fabric`` scrapes ``/debug/fabric`` (fabric.py per-link transport
telemetry + hop census), validates it strictly
(fabric.validate_fabric), and prints the hottest links — top-K by bytes
sent and by p99 delivery latency — the hop-census summary, each
attached hub's queue depth and breaker states, and the carrier class
of every mesh-co-located link (``resident`` = served by the in-step
collective, ``hub`` = cut/partitioned and host-delivered — round 17's
device-resident fabric).  Any non-closed breaker counts as degraded
(exit 1).  ``--top`` sizes K.

Exit status: 0 healthy, 1 degraded (any anomaly class nonzero, memory
pressure, a retrace storm, or — under ``--fabric`` — a tripped
breaker), 2 unreachable or schema-invalid.  Stdlib-only on the wire
(urllib).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import urllib.error
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from dragonboat_tpu.core import health  # noqa: E402


def fetch_json(address: str, path: str, timeout: float):
    url = f"http://{address}{path}"
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return json.loads(resp.read().decode("utf-8"))


def _fmt_counts(counts: dict) -> str:
    return " ".join(f"{c}={counts[c]}" for c in health.CLASS_NAMES)


def _capacity_degraded(cap: dict) -> list[str]:
    return [k for k in ("memory_pressure", "retrace_storm") if cap.get(k)]


def render_capacity(cap: dict) -> list[str]:
    """Capacity block lines for a validated capacity snapshot."""
    flags = _capacity_degraded(cap)
    mb = 1024.0 * 1024.0
    lines = [
        f"capacity: {'DEGRADED (' + ' '.join(flags) + ')' if flags else 'OK'}"
        f"  ticks={cap['ticks']} groups={cap['capacity']}",
        f"  bytes: live={cap['bytes_in_use'] / mb:.2f}MiB"
        f" peak={cap['bytes_peak'] / mb:.2f}MiB"
        f" budget={cap['budget_bytes'] / mb:.2f}MiB"
        f" headroom={cap['headroom_pct']:.1f}%",
        f"  model: per_group={cap['model_bytes_per_group']}B"
        f" predicted={cap['model_predicted_bytes'] / mb:.2f}MiB"
        f" max_g_at_budget={cap['model_max_g_at_budget']}",
    ]
    if cap["entries"]:
        lines.append("  compile entries:")
        hdr = ("entry", "calls", "compiles", "retraces", "compile_ms")
        rows = [hdr]
        for name in sorted(cap["entries"]):
            e = cap["entries"][name]
            rows.append((name, str(e["calls"]), str(e["compiles"]),
                         str(e["retraces"]),
                         f"{e['compile_us_total'] / 1000.0:.1f}"))
        widths = [max(len(r[i]) for r in rows) for i in range(len(hdr))]
        for r in rows:
            lines.append("    " + "  ".join(
                v.ljust(widths[i]) for i, v in enumerate(r)).rstrip())
    return lines


def render_groups(info: dict) -> str:
    """Human triage report for a validated NodeHost.info() payload."""
    h = info["health"]
    degraded = any(h["class_count"].values())
    lines = [
        f"fleet doctor — {info['node_host_id']} @ {info['raft_address']}",
        f"health: {'DEGRADED' if degraded else 'OK'}"
        f"  anomalous={h['anomalous']} leaderless_now={h['leaderless_now']}",
        f"  classes: {_fmt_counts(h['class_count'])}",
    ]
    if h["worst"]:
        lines.append("  worst offenders:")
        hdr = ("lane", "engine", "score", "classes", "term", "leader",
               "lag", "stall", "churn")
        rows = [hdr]
        for w in h["worst"]:
            rows.append((str(w["lane"]), w.get("engine", "-"),
                         str(w["score"]), ",".join(w["classes"]) or "-",
                         str(w["term"]), str(w["leader"]), str(w["lag"]),
                         str(w["stall_ticks"]), str(w["churn_score"])))
        widths = [max(len(r[i]) for r in rows) for i in range(len(hdr))]
        for r in rows:
            lines.append("    " + "  ".join(
                v.ljust(widths[i]) for i, v in enumerate(r)).rstrip())
    if "capacity" in info:
        lines.extend(render_capacity(info["capacity"]))
    lines.append(f"shards ({len(info['shards'])}):")
    for s in sorted(info["shards"], key=lambda s: s["shard_id"]):
        lead = ("leader" if s["is_leader"]
                else f"leader={s['leader_id'] or '?'}")
        lines.append(
            f"  shard {s['shard_id']} replica {s['replica_id']}"
            f"  [{s['resident']}]  {lead} term={s['term']}"
            f" applied={s['last_applied']}")
    return "\n".join(lines)


def build_plan(info: dict) -> dict:
    """Dry-run the control planner over a validated info() payload."""
    from dragonboat_tpu import control

    # hysteresis 1: a throwaway controller sees exactly one observation,
    # so requiring a streak would plan nothing by construction
    ctl = control.FleetController(control.ControlPolicy(
        enabled=True, hysteresis=1, warmup_obs=0))
    shards = [s for s in info["shards"] if s.get("resident") != "host"]
    decisions = ctl.observe(info["health"]["worst"], shards)
    cap = info.get("capacity") or {}
    limit = int(cap.get("model_max_g_at_budget", 0))
    adm = control.check_admission(0, len(shards), limit,
                                  mode=control.ADMISSION_WARN)
    if adm is not None:
        decisions.append(adm)
    quiesced = int((info.get("fleet") or {}).get("quiesced", 0))
    return control.plan_to_dict(decisions, quiesced)


def render_plan(plan: dict) -> str:
    """Human report for a validated plan_to_dict payload."""
    c = plan["counts"]
    lines = [f"plan: transfers={c['transfer']} refusals={c['refuse']}"
             f" quiesced={c['quiesced']}"]
    for t in plan["transfers"]:
        ev = t["evidence"]
        lines.append(
            f"  transfer shard {t['shard_id']} -> replica {t['target']}"
            f"  [lane={ev['lane']} score={ev['score']} lag={ev['lag']}"
            f" term={ev['term']} host_hot={ev['host_hot']}"
            f" classes={','.join(ev['classes']) or '-'}]")
    for r in plan["refusals"]:
        ev = r["evidence"]
        lines.append(
            f"  refuse next-device-replica  [occupied={ev['occupied']}"
            f" limit={ev['limit']} mode={ev['mode']}]")
    if not (plan["transfers"] or plan["refusals"]):
        lines.append("  nothing pending")
    return "\n".join(lines)


def _fabric_degraded(fab: dict) -> list[str]:
    """Non-closed breakers across the attached hubs (degradation)."""
    out = []
    for addr in sorted(fab["hubs"]):
        for peer, state in sorted(fab["hubs"][addr]["breakers"].items()):
            if state != "closed":
                out.append(f"{addr}->{peer}={state}")
    return out


def render_fabric(fab: dict, top_k: int = 5) -> str:
    """Human report for a validated /debug/fabric payload: hottest
    links by bytes and by p99 delivery latency, the hop-census summary,
    and per-hub queue/breaker state."""
    tripped = _fabric_degraded(fab)
    cen = fab["census"]
    lines = [
        f"fabric: {'DEGRADED (' + ' '.join(tripped) + ')' if tripped else 'OK'}"
        f"  enabled={fab['enabled']} links={len(fab['links'])}",
        f"  census: p50_commit_host_hops={cen['p50_commit_host_hops']}"
        f" finished={cen['finished']} active={cen['active']}"
        f" dropped={cen['dropped']}"
        f" hops={{{' '.join(f'{h}:{n}' for h, n in sorted(cen['hop_counts'].items(), key=lambda kv: int(kv[0])))}}}",
    ]
    # carrier classes (round 17): resident links ride the mesh
    # collective and never show hub traffic; hub links are cut /
    # partitioned co-located links the host delivers (fallback matrix
    # in README).  Unclassified links (off-mesh) are hub-by-nature and
    # appear only in the traffic tables above.
    classes = fab.get("link_classes", {})
    if classes:
        by_cls: dict = {}
        for link, cls in sorted(classes.items()):
            by_cls.setdefault(cls, []).append(link)
        counts = " ".join(f"{cls}={len(by_cls[cls])}"
                          for cls in sorted(by_cls))
        lines.append(f"  link classes: {counts}")
        for cls in sorted(by_cls):
            shown = by_cls[cls][:top_k]
            more = len(by_cls[cls]) - len(shown)
            lines.append(f"    {cls}: " + " ".join(shown)
                         + (f" (+{more} more)" if more > 0 else ""))

    def link_table(title, ranked):
        if not ranked:
            return
        lines.append(f"  {title}:")
        hdr = ("link", "sent", "recv", "bytes_out", "p50_us", "p99_us")
        rows = [hdr]
        for li in ranked[:top_k]:
            rows.append((f"{li['src']}->{li['dst']}",
                         str(sum(li["sent"].values())),
                         str(sum(li["recv"].values())),
                         str(li["bytes_sent"]),
                         f"{li['delivery_p50_us']:.0f}",
                         f"{li['delivery_p99_us']:.0f}"))
        widths = [max(len(r[i]) for r in rows) for i in range(len(hdr))]
        for r in rows:
            lines.append("    " + "  ".join(
                v.ljust(widths[i]) for i, v in enumerate(r)).rstrip())

    link_table("hottest links by bytes sent",
               sorted(fab["links"], key=lambda li: -li["bytes_sent"]))
    link_table("hottest links by p99 delivery latency",
               sorted(fab["links"],
                      key=lambda li: -li["delivery_p99_us"]))
    for addr in sorted(fab["hubs"]):
        hv = fab["hubs"][addr]
        br = " ".join(f"{p}={s}" for p, s in sorted(hv["breakers"].items()))
        lines.append(f"  hub {addr}: queued={hv['queue_msgs']}msg"
                     f"/{hv['queue_bytes']}B  breakers: {br or '-'}")
    return "\n".join(lines)


def render_shard(si: dict) -> str:
    """Human drill-down for a validated NodeHost.shard_info() payload."""
    lines = [
        f"shard {si['shard_id']} replica {si['replica_id']}"
        f"  [{si['resident']}]",
        f"  leader={si['leader_id']} term={si['term']}"
        f" is_leader={si['is_leader']} applied={si['last_applied']}",
        f"  pending: proposals={si['pending']['proposals']}"
        f" read_indexes={si['pending']['read_indexes']}",
    ]
    ldb = si["logdb"]
    snap = ldb["snapshot"]
    snap_s = (f" snapshot@{snap['index']}(t{snap['term']})"
              if snap else " no-snapshot")
    lines.append(f"  logdb: [{ldb['first_index']}, {ldb['last_index']}]"
                 f" count={ldb['entry_count']}{snap_s}")
    if si["breakers"]:
        lines.append("  breakers: " + " ".join(
            f"{a}={s}" for a, s in sorted(si["breakers"].items())))
    dev = si["device"]
    if dev is None:
        lines.append("  device: (host-resident — no device row)")
    else:
        lines.append(
            f"  device: role={dev['role']} commit={dev['committed']}"
            f" applied={dev['applied']} last={dev['last']}"
            f" inbox={dev['inbox_occ']}"
            f" classes={','.join(dev['classes']) or '-'}")
        lines.append(
            f"    counters: leaderless={dev['leaderless_ticks']}"
            f" stall={dev['stall_ticks']} lag={dev['lag_ticks']}"
            f" churn={dev['churn_score']} runaway={dev['runaway_ticks']}")
    mb = si["membership"]
    lines.append("  members: " + " ".join(
        f"{r}@{a}" for r, a in sorted(mb["addresses"].items(),
                                      key=lambda kv: int(kv[0]))))
    return "\n".join(lines)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("address", help="host:port of the metrics endpoint")
    ap.add_argument("--shard", type=int, default=None,
                    help="drill into /debug/group/<id> for one group")
    ap.add_argument("--json", action="store_true",
                    help="print the validated payload as JSON instead of "
                         "the human report")
    ap.add_argument("--plan", action="store_true",
                    help="dry-run the control planner over the scraped "
                         "payload; exit 1 when any action is pending")
    ap.add_argument("--fabric", action="store_true",
                    help="report /debug/fabric (per-link transport "
                         "telemetry + hop census); any non-closed "
                         "breaker exits 1")
    ap.add_argument("--top", type=int, default=5,
                    help="K for the --fabric hottest-link tables "
                         "(default 5)")
    ap.add_argument("--timeout", type=float, default=5.0)
    args = ap.parse_args()
    if args.plan and args.shard is not None:
        ap.error("--plan reads the whole-host payload; drop --shard")
    if args.fabric and (args.plan or args.shard is not None):
        ap.error("--fabric reads /debug/fabric; drop --plan/--shard")

    path = ("/debug/fabric" if args.fabric
            else f"/debug/group/{args.shard}" if args.shard is not None
            else "/debug/groups")
    try:
        obj = fetch_json(args.address, path, args.timeout)
    except (urllib.error.URLError, OSError, ValueError) as e:
        print(f"error: cannot scrape http://{args.address}{path}: {e}",
              file=sys.stderr)
        return 2

    if args.fabric:
        from dragonboat_tpu.fabric import validate_fabric

        try:
            validate_fabric(obj)
        except ValueError as e:
            print(f"error: schema validation failed: {e}", file=sys.stderr)
            return 2
        if args.json:
            print(json.dumps(obj, indent=2, sort_keys=True))
        else:
            print(render_fabric(obj, args.top))
        return 1 if _fabric_degraded(obj) else 0

    try:
        if args.shard is not None:
            health.validate_shard_info(obj)
        else:
            health.validate_info(obj)
            if "capacity" in obj:
                # lazy: capacity.py pulls jax; the pure-health path
                # must stay scrapeable even under a wedged backend
                from dragonboat_tpu.capacity import validate_capacity

                validate_capacity(obj["capacity"], where="info.capacity")
    except ValueError as e:
        print(f"error: schema validation failed: {e}", file=sys.stderr)
        return 2

    if args.plan:
        from dragonboat_tpu import control

        plan = build_plan(obj)
        control.validate_plan(plan)
        if args.json:
            print(json.dumps({"plan": plan}, indent=2, sort_keys=True))
        else:
            print(render_plan(plan))
        return 1 if plan["transfers"] or plan["refusals"] else 0

    if args.json:
        print(json.dumps(obj, indent=2, sort_keys=True))
    else:
        print(render_shard(obj) if args.shard is not None
              else render_groups(obj))

    if args.shard is not None:
        degraded = bool(obj["device"] and obj["device"]["classes"])
    else:
        degraded = (any(obj["health"]["class_count"].values())
                    or bool(_capacity_degraded(obj.get("capacity", {}))))
    return 1 if degraded else 0


if __name__ == "__main__":
    sys.exit(main())
